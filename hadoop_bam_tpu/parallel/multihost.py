"""Multi-host orchestration: one process per host, SPMD over the global mesh.

The reference's scale-out runtime is the Hadoop cluster — one mapper per
split on whatever host owns it, record bytes moving through the MapReduce
shuffle's spill/fetch data plane (pom.xml:296-300 hadoop-client;
BAMInputFormat.java:216-260 assigns splits, SURVEY §2.7 the shuffle).  The
TPU-native equivalent here:

- **control plane**: ``jax.distributed.initialize`` (one process per host)
  — the global device mesh spans every process; split planning is
  deterministic, so every process plans identically and takes ownership of
  ``split_idx % num_processes == process_id`` (no coordinator needed).
- **key plane**: the existing range-partitioned ``all_to_all`` shuffle sort
  (parallel/shuffle.py) runs unchanged over the *global* mesh — XLA routes
  the collective over ICI within a host and DCN across hosts.  The shuffle
  additionally returns each input row's destination device (the sender-side
  routing table).
- **byte plane**: ragged record payloads move host-to-host either through
  spill files on a shared filesystem (the GCS-backed-shuffle stance) or —
  with ``byte_plane="http"`` — over authenticated HTTP range fetches from
  each process's LOCAL disk (Hadoop's map-output servlet + parallel
  copier, no shared filesystem in the data path): each process writes one
  run of records per destination process, sorted by global source row
  with a memmappable row/offset sidecar; after a global barrier every
  process fetches and gathers exactly the bytes its devices' key ranges
  own.  Both planes compose with ``memory_budget`` (key-sorted spill
  runs, contiguous per-destination slices, receiver-side (key, ordinal)
  range merge).

  By default the wire format is **compressed**: the sender re-blocks each
  destination's record run into ≤64 KiB BGZF members through the job's
  :class:`~..device_stream.DeviceStream` deflate seam (device deflate
  when the lanes tier is armed, host zlib otherwise — per-member
  tier-down as everywhere else) and ships the members plus a tiny member
  table ``(raw_off, raw_len, comp_off, comp_len)``; the ``.rows``/
  ``.offs`` sidecars keep addressing *raw* space, so receivers inflate
  the members batched through the same stream's decode seam and the
  gather contract is byte-identical to the raw plane.  This is Hadoop's
  ``mapreduce.map.output.compress`` stance rebuilt at ICI/NIC speed:
  keys ride the mesh ``all_to_all``, record bytes ride BGZF.
  ``hadoopbam.shuffle.compress=false`` selects the raw plane.  The byte
  matrix counters measure the **wire** (compressed) bytes per edge, with
  raw twins (``mh.shuffle.sent_raw.<dst>``) making the per-edge
  compression ratio a first-class measurement.

``sort_bam_multihost`` is the end-to-end driver: it produces a part file
per *global device* and process 0 performs the ordinary header+parts+
terminator merge, so the output is byte-identical to the single-process
sort of the same input.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax

from .. import faults, native
from ..utils import nio
from ..utils.tracing import METRICS, TRACER, span, trace_ctx
from .mesh import DATA_AXIS, make_mesh, process_of_device
from .shuffle import KEY_ROW_BYTES, DistributedSort


@dataclass
class MultihostContext:
    """Process identity + the global mesh."""

    process_id: int
    num_processes: int
    mesh: "jax.sharding.Mesh"

    @property
    def local_device_count(self) -> int:
        return jax.local_device_count()

    @property
    def global_device_count(self) -> int:
        return self.mesh.devices.size

    def owned(self, items: Sequence) -> List:
        """Round-robin ownership — deterministic, planner-free
        (every process computes the same global plan)."""
        return [
            it
            for k, it in enumerate(items)
            if k % self.num_processes == self.process_id
        ]

    def barrier(self, name: str) -> None:
        """Named global barrier, timed three ways: a cumulative span +
        ``mh.barrier.<name>`` log2 histogram (milliseconds) in METRICS,
        and — with the timeline tracer armed — a ``category="stage"``
        trace event whose *start* is this host's arrival.  Barriers are
        exactly where stragglers hide: on the merged mesh timeline the
        host that arrived last at a barrier is the one every other
        host's wait should be blamed on, which is precisely what
        ``tools/mesh_report.py`` computes from these events."""
        from jax.experimental import multihost_utils

        t0 = time.perf_counter()
        with span(f"mh.barrier.{name}", category="stage"):
            multihost_utils.sync_global_devices(name)
        METRICS.observe(
            f"mh.barrier.{name}", (time.perf_counter() - t0) * 1e3
        )

    def allgather_counts(self, n: int) -> np.ndarray:
        """[num_processes] int64 — one scalar contributed per process."""
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(np.int64(n))
        ).reshape(-1)

    def allgather_array(self, a: np.ndarray) -> np.ndarray:
        """[num_processes, *a.shape] — same-shape array from every process."""
        from jax.experimental import multihost_utils

        out = np.asarray(multihost_utils.process_allgather(a))
        return out.reshape((self.num_processes,) + a.shape)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> MultihostContext:
    """Join (or create) the multi-process JAX runtime and build the global
    1-D data mesh.

    With no arguments in a single-process setting this degrades to a local
    mesh over the visible devices — the same code path runs on one host or
    sixteen.  On CPU the cross-process collectives use the gloo transport;
    on TPU pods the PJRT plugin provides ICI/DCN natively.
    """
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return MultihostContext(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        mesh=make_mesh(),
    )


#: Thin debug view of the last sort_bam_multihost call (budget mode's
#: accounted peak of materialized record bytes; tests assert against it).
#: Retired into the mesh manifests: the authoritative per-host record is
#: :data:`LAST_MANIFEST` (this process) and — on process 0 — the folded
#: :data:`LAST_CLUSTER_MANIFEST`; ``peak_bytes`` also rides the
#: ``mh.peak_bytes`` gauge so the metrics plane stays single-sourced
#: through utils/tracing.
LAST_STATS: dict = {}

#: This process's host manifest from the last mesh-traced run ({} until
#: one completes): run_manifest + byte/key matrices row + barrier waits.
LAST_MANIFEST: dict = {}

#: Process 0 only: the folded ClusterManifest dict of the last
#: mesh-traced run ({} elsewhere / until one completes).  The CLI's
#: ``--metrics`` report and the MULTICHIP bench rounds attach it.
LAST_CLUSTER_MANIFEST: dict = {}


# ---------------------------------------------------------------------------
# The byte plane: shared-filesystem record shuffle.
# ---------------------------------------------------------------------------


def _bytes_name(src: int, dst: int) -> str:
    return f"shufbytes-s{src:03d}-d{dst:03d}"


def _bytes_file(d: str, src: int, dst: int) -> str:
    return os.path.join(d, _bytes_name(src, dst))


# ---------------------------------------------------------------------------
# The compressed wire format: BGZF members + a member table in raw space.
# ---------------------------------------------------------------------------

#: Member-table sidecar suffix: one flat int64 ``.npy`` holding
#: ``(raw_off, raw_len, comp_off, comp_len)`` per member (flattened so
#: the ranged-``.npy`` reader handles it unchanged on the HTTP plane).
_MTAB_SUFFIX = ".mtab.npy"


def _resolve_shuffle_compress(conf) -> bool:
    """``hadoopbam.shuffle.compress`` → HBAM_SHUFFLE_COMPRESS → True."""
    if conf is not None:
        from ..conf import SHUFFLE_COMPRESS

        if conf.get(SHUFFLE_COMPRESS) is not None:
            return conf.get_boolean(SHUFFLE_COMPRESS, True)
    env = os.environ.get("HBAM_SHUFFLE_COMPRESS", "").strip().lower()
    if env:
        return env not in ("0", "false", "off", "no")
    return True


def _resolve_member_bytes(conf) -> int:
    """Shuffle member payload: conf → env → the device codec cap
    (``DEV_MAX_PAYLOAD`` — a ≤64 KiB member on the wire, the same
    deterministic blocking the part writer uses)."""
    from ..ops.flate import DEV_MAX_PAYLOAD

    v = 0
    if conf is not None:
        from ..conf import SHUFFLE_MEMBER_BYTES

        v = conf.get_int(SHUFFLE_MEMBER_BYTES, 0)
    if v <= 0:
        env = os.environ.get("HBAM_SHUFFLE_MEMBER_BYTES", "")
        try:
            v = int(env) if env else 0
        except ValueError:
            v = 0
    if v <= 0:
        v = DEV_MAX_PAYLOAD
    return max(512, min(v, DEV_MAX_PAYLOAD))


def _resolve_fetch_threads(conf) -> int:
    """Peer-fetch pool width: ``hadoopbam.shuffle.fetch-threads`` →
    HBAM_SHUFFLE_FETCH_THREADS → 8 (callers cap at the peer count)."""
    v = 0
    if conf is not None:
        from ..conf import SHUFFLE_FETCH_THREADS

        v = conf.get_int(SHUFFLE_FETCH_THREADS, 0)
    if v <= 0:
        env = os.environ.get("HBAM_SHUFFLE_FETCH_THREADS", "")
        try:
            v = int(env) if env else 0
        except ValueError:
            v = 0
    return v if v > 0 else 8


def _resolve_skew_bound(conf) -> float:
    """Adaptive-repartition trigger: ``hadoopbam.mesh.skew-bound`` →
    HBAM_MESH_SKEW_BOUND → 1.5.  A routed round whose per-device
    record-count max/mean exceeds this refreshes the range partitioner
    once from a key reservoir; ``<= 0`` disables the refresh."""
    if conf is not None:
        from ..conf import MESH_SKEW_BOUND

        got = conf.get(MESH_SKEW_BOUND)
        if got is not None:
            try:
                return float(got)
            except ValueError:
                pass
    env = os.environ.get("HBAM_MESH_SKEW_BOUND", "")
    try:
        return float(env) if env else 1.5
    except ValueError:
        return 1.5


def _resolve_speculate_factor(conf) -> float:
    """Speculative re-execution trigger: ``hadoopbam.mesh.speculate-factor``
    → HBAM_MESH_SPECULATE_FACTOR → 0 (disabled).  A straggling host's
    parts stage is re-executed by a finished peer once the stage has run
    longer than factor × the median finished-peer duration."""
    if conf is not None:
        from ..conf import MESH_SPECULATE_FACTOR

        got = conf.get(MESH_SPECULATE_FACTOR)
        if got is not None:
            try:
                return float(got)
            except ValueError:
                pass
    env = os.environ.get("HBAM_MESH_SPECULATE_FACTOR", "")
    try:
        return float(env) if env else 0.0
    except ValueError:
        return 0.0


def _resolve_repartition_samples(conf) -> int:
    """Per-host key reservoir size for the repartition refresh:
    ``hadoopbam.mesh.repartition-samples`` →
    HBAM_MESH_REPARTITION_SAMPLES → 4096."""
    v = 0
    if conf is not None:
        from ..conf import MESH_REPARTITION_SAMPLES

        v = conf.get_int(MESH_REPARTITION_SAMPLES, 0)
    if v <= 0:
        env = os.environ.get("HBAM_MESH_REPARTITION_SAMPLES", "")
        try:
            v = int(env) if env else 0
        except ValueError:
            v = 0
    return v if v > 0 else 4096


def _deflate_member_stream(
    raw, dstream, level: int, member_bytes: int
) -> Tuple[bytes, np.ndarray]:
    """Re-block a raw record stream into BGZF members for the wire.

    Returns ``(member stream bytes, flat int64 member table)`` where the
    table is ``(raw_off, raw_len, comp_off, comp_len)`` per member.  The
    deflate rides the job's DeviceStream seam (device lanes when armed,
    host zlib otherwise; per-member tier-down inside).  A stream the
    codec *grew* (incompressible payload) falls back to stored members
    (level 0 — ~31 B overhead per member instead of deflate expansion),
    counted as ``mh.shuffle.store_fallback``."""
    n = int(len(raw))
    if n == 0:
        return b"", np.zeros(0, dtype=np.int64)
    lvl = level if level > 0 else 1
    if dstream is not None:
        comp = dstream.deflate_stream(
            raw, level=lvl, block_payload=member_bytes
        )
    else:
        comp = native.deflate_blocks(
            raw, level=lvl, block_payload=member_bytes
        )
    if len(comp) >= n:
        METRICS.count("mh.shuffle.store_fallback", 1)
        comp = native.deflate_blocks(
            raw, level=0, block_payload=member_bytes
        )
    return comp, _member_table(comp, n)


def _member_table(comp: bytes, raw_total: int) -> np.ndarray:
    """Scan a member stream into the flat ``(raw_off, raw_len,
    comp_off, comp_len)`` table; the raw sizes must tile exactly the
    raw stream the ``.offs`` sidecar addresses (anything else is an
    accounting desync, caught here rather than as a garbled gather)."""
    co, cs, us = native.scan_blocks(np.frombuffer(comp, dtype=np.uint8))
    us64 = us.astype(np.int64)
    if int(us64.sum()) != raw_total:
        raise RuntimeError(
            f"shuffle member table desync: members carry "
            f"{int(us64.sum())} raw bytes, sidecars address {raw_total}"
        )
    mtab = np.empty((len(us), 4), dtype=np.int64)
    mtab[:, 0] = np.concatenate(([0], np.cumsum(us64[:-1])))
    mtab[:, 1] = us64
    mtab[:, 2] = co
    mtab[:, 3] = cs
    return mtab.reshape(-1)


def _member_cover(mtab: np.ndarray, b0: int, b1: int) -> Tuple[int, int]:
    """Member index range [m0, m1) covering raw byte span [b0, b1)."""
    m = mtab.reshape(-1, 4)
    if b1 <= b0 or len(m) == 0:
        return 0, 0
    raw_off = m[:, 0]
    m0 = max(0, int(np.searchsorted(raw_off, b0, side="right")) - 1)
    m1 = int(np.searchsorted(raw_off, b1, side="left"))
    return m0, m1


def _cover_comp_bytes(mtab: np.ndarray, b0: int, b1: int) -> int:
    """Wire bytes of the members covering raw span [b0, b1) — the unit
    both sides of the budget plane's byte matrix count in."""
    m0, m1 = _member_cover(mtab, b0, b1)
    if m1 <= m0:
        return 0
    m = mtab.reshape(-1, 4)
    return int(m[m1 - 1, 2] + m[m1 - 1, 3] - m[m0, 2])


def _inflate_member_stream(
    comp: np.ndarray, mtab: np.ndarray, dstream, errors: Optional[str]
) -> Tuple[np.ndarray, List[Tuple[int, int]]]:
    """Inflate a fetched member stream back to raw record bytes.

    Returns ``(raw uint8, quarantined raw intervals)``.  The armed
    ``mh.corrupt`` fault seam flips a byte of a member's compressed
    payload here — after the wire, before inflate — so the BGZF CRC gate
    is what catches it.  Strict mode propagates the codec error (the
    whole sort fails loudly); ``errors="salvage"`` retries member by
    member, quarantining exactly the corrupt ones (``salvage.*``
    counters) and zero-filling their raw spans so the caller's gather
    can drop the records they carried while survivors stay byte-exact.
    """
    m = mtab.reshape(-1, 4)
    nm = len(m)
    if nm == 0:
        return np.empty(0, dtype=np.uint8), []
    co = np.ascontiguousarray(m[:, 2], dtype=np.int64)
    cs = np.ascontiguousarray(m[:, 3], dtype=np.int32)
    us = np.ascontiguousarray(m[:, 1], dtype=np.int32)
    plan = faults.ACTIVE
    if plan is not None:
        for i in range(nm):
            if plan.mh_corrupt(i):
                comp = np.array(comp, copy=True)
                # Mid-payload of member i: past the 18-byte gzip header,
                # before the 8-byte CRC/ISIZE trailer.
                pos = int(co[i]) + 18 + max(0, (int(cs[i]) - 26) // 2)
                comp[pos] ^= 0xFF

    def _decode(data, coffs, csz, usz):
        if dstream is not None:
            return dstream.decode_members(
                data, coffs, csz, usz, on_error="host"
            )
        return native.inflate_blocks(data, coffs, csz, usz)

    if errors != "salvage":
        out, _ = _decode(comp, co, cs, us)
        return out, []
    try:
        out, _ = _decode(comp, co, cs, us)
        return out, []
    except Exception:
        pass  # re-walk member by member below, quarantining failures
    offs = np.zeros(nm + 1, dtype=np.int64)
    np.cumsum(us.astype(np.int64), out=offs[1:])
    out = np.zeros(int(offs[-1]), dtype=np.uint8)
    bad: List[Tuple[int, int]] = []
    for i in range(nm):
        try:
            p, _ = native.inflate_blocks(
                comp, co[i : i + 1], cs[i : i + 1], us[i : i + 1]
            )
            out[int(offs[i]) : int(offs[i + 1])] = p
        except Exception:
            bad.append((int(offs[i]), int(offs[i + 1])))
            METRICS.count("salvage.members_quarantined", 1)
            METRICS.count("salvage.bytes_quarantined", int(us[i]))
    return out, bad


def _write_run_compressed(
    directory: str,
    idx: int,
    batch,
    perm: np.ndarray,
    dstream,
    level: int,
    member_bytes: int,
) -> None:
    """Spill one sorted run in the compressed wire format: the data file
    is a BGZF member stream (what ``io.runs.write_run`` writes, deflated
    through the shuffle's member re-block) plus the ``.mtab.npy`` member
    table; the key/offset sidecars are unchanged and keep addressing RAW
    space, so the budget plane's cut tables, slice math and (key,
    ordinal) merge are plane-independent."""
    from ..io import runs as runs_mod
    from ..io.bam import gather_record_array

    data_p, keys_p, offs_p, _ = runs_mod.run_paths(directory, idx)
    stream = gather_record_array(batch, perm)
    keys_sorted = np.ascontiguousarray(batch.keys[perm], dtype=np.int64)
    lens = batch.soa["rec_len"].astype(np.int64)[perm] + 4
    offs = np.empty(len(lens) + 1, dtype=np.int64)
    offs[0] = 0
    np.cumsum(lens, out=offs[1:])
    with span("mh.byte_shuffle.deflate", category="stage"):
        comp, mtab = _deflate_member_stream(
            stream, dstream, level, member_bytes
        )
    mtab_p = os.path.join(directory, f"run-{idx:05d}{_MTAB_SUFFIX}")
    targets = (
        (data_p, lambda f: f.write(comp)),
        (keys_p, lambda f: np.save(f, keys_sorted)),
        (offs_p, lambda f: np.save(f, offs)),
        (mtab_p, lambda f: np.save(f, mtab)),
    )
    for path, writer in targets:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            writer(f)
        os.replace(tmp, path)


def _serve_dir(directory: str, token: str):
    """Serve ``directory`` read-only over HTTP with Range support.

    The network byte plane's data server — the role of Hadoop's
    map-output HTTP servlet in the shuffle fetch phase (SURVEY §2.7):
    each process serves its outgoing spill files from local disk and
    receivers pull exactly their share, so the byte plane needs no
    shared filesystem.  ``token`` is this job's fetch credential (the
    moral equivalent of Hadoop's shuffle job token): every request must
    carry it in ``X-Hbam-Token`` or gets 403 — the per-process tokens
    travel only over the job's own allgather channel.  Returns
    ``(server, base_url)``; the caller owns shutdown."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    root = os.path.abspath(directory)

    import hmac

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _path(self):
            got = self.headers.get("X-Hbam-Token") or ""
            if not hmac.compare_digest(got, token):
                self.send_error(403)
                return None
            # One flat directory; reject anything path-like.
            name = self.path.lstrip("/")
            if "/" in name or ".." in name or not name:
                self.send_error(404)
                return None
            p = os.path.join(root, name)
            if not os.path.isfile(p):
                self.send_error(404)
                return None
            return p

        def do_HEAD(self):
            METRICS.count("mh.http.requests", 1)
            p = self._path()
            if p is None:
                return
            self.send_response(200)
            self.send_header("Content-Length", str(os.path.getsize(p)))
            self.send_header("Accept-Ranges", "bytes")
            self.end_headers()

        def do_GET(self):
            # Server-side fetch accounting (Hadoop's shuffle servlet has
            # the same counters): requests, range-vs-whole, bytes out.
            METRICS.count("mh.http.requests", 1)
            p = self._path()
            if p is None:
                return
            size = os.path.getsize(p)
            rng = self.headers.get("Range")
            if rng:
                METRICS.count("mh.http.range_requests", 1)
            lo, hi = 0, size - 1
            status = 200
            if rng:
                try:
                    a, b = rng.split("=")[1].split("-")
                    if a == "":  # RFC suffix form: last N bytes
                        n_suffix = int(b)
                        lo = max(0, size - n_suffix)
                    else:
                        lo = int(a)
                        hi = min(int(b) if b else size - 1, size - 1)
                except ValueError:
                    self.send_error(400)
                    return
                if lo >= size or hi < lo:
                    self.send_error(416)
                    return
                status = 206
            n = hi - lo + 1
            self.send_response(status)
            if status == 206:
                self.send_header(
                    "Content-Range", f"bytes {lo}-{hi}/{size}"
                )
            self.send_header("Content-Length", str(n))
            self.end_headers()
            with open(p, "rb") as f:
                f.seek(lo)
                remaining = n
                while remaining > 0:
                    chunk = f.read(min(1 << 20, remaining))
                    if not chunk:
                        break
                    self.wfile.write(chunk)
                    remaining -= len(chunk)
            METRICS.count("mh.http.bytes_served", n - remaining)

    # Peers must reach this address: the hostname by default (resolvable
    # on real clusters), HBAM_SHUFFLE_HOST to override (tests pin
    # 127.0.0.1; multi-NIC hosts pin the data-plane address).  When an
    # address is pinned, LISTEN on it too — spill bytes must not be
    # reachable on interfaces the operator pinned away from.
    import socket

    pinned = os.environ.get("HBAM_SHUFFLE_HOST")
    srv = ThreadingHTTPServer((pinned or "0.0.0.0", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    host = pinned or socket.gethostname()
    return srv, f"http://{host}:{srv.server_address[1]}"


_ENDPOINT_REC = 512  # fits http:// + 253-char FQDN + port + 32-hex token


def _publish_endpoints(
    ctx: MultihostContext, url: str, token: str
) -> List[Tuple[str, str]]:
    """Allgather each process's (URL, fetch token), fixed-width UTF-8.

    The allgather also doubles as the 'server is up' barrier — no
    receiver can hold a peer's endpoint before that peer published it."""
    rec = f"{url} {token}".encode()
    buf = np.zeros(_ENDPOINT_REC, dtype=np.uint8)
    if len(rec) > _ENDPOINT_REC:
        raise ValueError(f"shuffle endpoint too long: {rec!r}")
    buf[: len(rec)] = np.frombuffer(rec, np.uint8)
    allb = ctx.allgather_array(buf)  # [P, _ENDPOINT_REC]
    out = []
    for p in range(len(allb)):
        u, t = bytes(allb[p]).rstrip(b"\x00").decode().split(" ", 1)
        out.append((u, t))
    return out


def _start_http_plane(ctx: MultihostContext, serve_dir: str, stack):
    """Start the data server over ``serve_dir``, publish the endpoint,
    and return the per-source locator list (own files stay local).

    Server teardown (shutdown + socket close) is registered on ``stack``
    (a ``contextlib.ExitStack`` owned by the driver), so every failure
    path from this moment on closes the data port; the serve directory
    itself belongs to its creator."""
    import secrets

    token = secrets.token_hex(16)
    srv, url = _serve_dir(serve_dir, token)
    stack.callback(srv.server_close)
    stack.callback(srv.shutdown)
    sources: List = list(_publish_endpoints(ctx, url, token))
    sources[ctx.process_id] = serve_dir  # no socket hop for own files
    return sources


def _write_byte_runs(
    shuffle_dir: str,
    ctx: MultihostContext,
    batch,
    dest_dev: np.ndarray,
    row_of_record: np.ndarray,
    rows_per_device: int,
    compress: bool = False,
    dstream=None,
    member_bytes: int = 0,
    level: int = 1,
) -> None:
    """Ship this process's records to their destination processes.

    One run per destination process, records ascending by *global source
    row*, plus ``.rows``/``.offs`` sidecars so receivers can
    binary-search any (src_dev, src_row) reference the key shuffle hands
    them.  With ``compress`` (the default plane) the run is re-blocked
    into ≤64 KiB BGZF members (``.bgzf`` + the ``.mtab.npy`` member
    table) through the job's DeviceStream deflate seam; the sidecars
    keep addressing *raw* space, so the receiver's row binary search is
    plane-independent.  Raw plane: the pre-PR-15 ``.bin`` stream.

    Sender side of the shuffle byte matrix: the **wire** bytes addressed
    to each destination process count ``mh.shuffle.sent.<dst>``
    (compressed bytes on the compressed plane; the diagonal is this
    process's own share — it moves by local read, not the network), with
    the raw twin ``mh.shuffle.sent_raw.<dst>`` making the per-edge
    compression ratio first-class.  With the tracer armed the wire
    bytes also land as cumulative ``mh.shuffle.sent`` counter-track
    samples.  The receiver measures the same edges independently
    (``mh.shuffle.recv.<src>`` / ``recv_raw``); mesh_report and the
    ClusterManifest assert the two sides agree per edge.
    """
    L = ctx.local_device_count
    first_global_dev = ctx.process_id * L
    # Global row id of each local record (row_of_record is the local slot).
    g_row = (
        (first_global_dev + row_of_record // rows_per_device).astype(np.int64)
        * rows_per_device
        + (row_of_record % rows_per_device).astype(np.int64)
    )
    dest_proc = process_of_device(dest_dev, L)
    lens = batch.soa["rec_len"].astype(np.int64) + 4
    sent_track: dict = {}
    for q in range(ctx.num_processes):
        sel = np.nonzero(dest_proc == q)[0]
        order = sel[np.argsort(g_row[sel], kind="stable")]
        stream = native.gather_records(
            batch.data,
            batch.soa["rec_off"],
            batch.soa["rec_len"],
            order,
        )
        offs = np.empty(len(order) + 1, dtype=np.int64)
        offs[0] = 0
        np.cumsum(lens[order], out=offs[1:])
        raw_total = int(offs[-1])
        METRICS.count(f"mh.shuffle.sent_raw.{q}", raw_total)
        base = _bytes_file(shuffle_dir, ctx.process_id, q)
        if compress:
            with span("mh.byte_shuffle.deflate", category="stage"):
                comp, mtab = _deflate_member_stream(
                    stream, dstream, level, member_bytes
                )
            wire = len(comp)
            targets = (
                (base + ".bgzf", memoryview(comp), True),
                (base + _MTAB_SUFFIX, mtab, False),
                (base + ".rows", g_row[order], False),
                (base + ".offs", offs, False),
            )
        else:
            wire = raw_total
            targets = (
                (base + ".bin", memoryview(stream), True),
                (base + ".rows", g_row[order], False),
                (base + ".offs", offs, False),
            )
        METRICS.count(f"mh.shuffle.sent.{q}", wire)
        sent_track[str(q)] = float(wire)
        TRACER.counter("mh.shuffle.sent", sent_track)
        for path, payload, rawbytes in targets:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                if rawbytes:
                    f.write(payload)  # no tobytes() copy
                else:
                    np.save(f, payload)
            os.replace(tmp, path)


class _ByteFetcher:
    """Receiver side: resolve (src_dev, src_row) → record bytes across the
    per-source spill files addressed to this process.

    ``sources`` locates each process's outgoing files: a filesystem
    directory (shared-FS plane, and the local fast path for a process's
    own files) or an ``(http_base, token)`` endpoint (network plane —
    the Hadoop shuffle's HTTP fetch, authenticated by the job's fetch
    token).

    On the compressed plane each fetch pulls the ``.bgzf`` member stream
    (fewer bytes on the same wire) and inflates it *inside the fetch
    thread* through the stream's decode seam (the inflate lanes when
    armed, native zlib otherwise) — so source A's inflate overlaps
    source B's fetch instead of serializing after the whole fetch phase
    (visible as ``mh.byte_shuffle.inflate`` stage events nested in the
    fetch stage).  ``errors="salvage"`` quarantines corrupt members
    (CRC-failing after the wire) instead of failing the sort; the
    records they carried are dropped at :meth:`gather` time with
    ``salvage.*`` counters, survivors byte-exact."""

    def __init__(self, sources: List, ctx: MultihostContext,
                 rows_per_device: int, compress: bool = False,
                 dstream=None, fetch_threads: int = 8,
                 errors: Optional[str] = None,
                 dest_pid: Optional[int] = None):
        import io as _io
        from concurrent.futures import ThreadPoolExecutor

        from ..io.fs import HttpFilesystem

        self.rows = rows_per_device
        self.ctx = ctx
        P_ = ctx.num_processes
        # Speculative re-execution fetches ANOTHER host's share
        # (``dest_pid``): those bytes are redundant copies, accounted
        # under ``mh.speculate.fetch_bytes`` — never the recv matrix,
        # which must keep balancing against what senders measured.
        dest = ctx.process_id if dest_pid is None else dest_pid
        speculative = dest != ctx.process_id
        #: Per source: quarantined raw intervals (salvage mode only).
        self.bad: List[List[Tuple[int, int]]] = [[] for _ in range(P_)]

        def fetch_one(s: int):
            name = _bytes_name(s, dest)
            ext = ".bgzf" if compress else ".bin"
            if isinstance(sources[s], tuple):
                url, token = sources[s]
                f = HttpFilesystem(
                    headers={"X-Hbam-Token": token},
                    retry_metric="mh.http.fetch_retries",
                )
                base = url.rstrip("/")

                def rd(suffix: str) -> bytes:
                    return f.read_all(f"{base}/{name}{suffix}")

                wire_buf = np.frombuffer(rd(ext), dtype=np.uint8)
                rows = np.load(_io.BytesIO(rd(".rows")))
                offs = np.load(_io.BytesIO(rd(".offs")))
                mtab = (
                    np.load(_io.BytesIO(rd(_MTAB_SUFFIX)))
                    if compress
                    else None
                )
            else:
                p = os.path.join(sources[s], name)
                with open(p + ext, "rb") as fh:
                    wire_buf = np.frombuffer(fh.read(), dtype=np.uint8)
                rows = np.load(p + ".rows")
                offs = np.load(p + ".offs")
                mtab = np.load(p + _MTAB_SUFFIX) if compress else None
            # Receiver side of the shuffle byte matrix, measured from the
            # bytes that actually arrived (not inferred from the sender).
            if speculative:
                METRICS.count(
                    "mh.speculate.fetch_bytes", int(len(wire_buf))
                )
            else:
                METRICS.count(f"mh.shuffle.recv.{s}", int(len(wire_buf)))
                TRACER.counter(
                    "mh.shuffle.recv", {str(s): float(len(wire_buf))}
                )
            if compress:
                with span("mh.byte_shuffle.inflate", category="stage"):
                    raw, bad = _inflate_member_stream(
                        wire_buf, mtab, dstream, errors
                    )
                self.bad[s] = bad
            else:
                raw = wire_buf
            if not speculative:
                METRICS.count(f"mh.shuffle.recv_raw.{s}", int(len(raw)))
            if len(offs) and int(offs[-1]) != len(raw):
                raise RuntimeError(
                    f"byte shuffle sidecar desync from process {s}: "
                    f"offs address {int(offs[-1])} raw bytes, stream "
                    f"carries {len(raw)}"
                )
            return raw, rows, offs

        # Pull peers concurrently (Hadoop's parallel copier): the fetch
        # phase is network-bound, not peer-count-bound.  Pool width is
        # ``hadoopbam.shuffle.fetch-threads`` (surfaced in the host
        # manifest), capped at the peer count.
        with ThreadPoolExecutor(
            max_workers=max(1, min(fetch_threads, P_))
        ) as pool:
            got = list(pool.map(fetch_one, range(P_)))
        bufs = [g[0] for g in got]
        self.rows_tab = [g[1] for g in got]
        self.offs_tab = [g[2] for g in got]
        # One concatenated buffer built once (gather() runs per local
        # device; re-concatenating there would copy the whole received
        # shard L times).
        self.base = np.zeros(ctx.num_processes + 1, dtype=np.int64)
        np.cumsum([len(b) for b in bufs], out=self.base[1:])
        self.big = (
            np.concatenate(bufs) if bufs else np.empty(0, np.uint8)
        )
        del bufs

    def gather(self, src_dev: np.ndarray, src_row: np.ndarray):
        """Concatenated raw records for the given (src_dev, src_row) refs,
        in the given order.  Returns (data uint8, rec_off, rec_len).

        Buffers are concatenated once and the ragged copy is a single
        ``native.gather_records`` call — no per-record Python loop.

        Salvage mode only: records whose raw span touches a quarantined
        member's interval are DROPPED from the output (counted as
        ``salvage.records_dropped``; a record straddling into a bad
        member is unrecoverable too) — the returned arrays then hold the
        byte-exact survivors in unchanged order.  Strict runs (and clean
        salvage runs) return exactly the pre-compression contract.
        """
        L = self.ctx.local_device_count
        g = src_dev.astype(np.int64) * self.rows + src_row.astype(np.int64)
        src_proc = src_dev // L
        n = len(g)
        out_len = np.zeros(n, dtype=np.int64)
        src_off = np.zeros(n, dtype=np.int64)
        keep: Optional[np.ndarray] = None
        for s in range(self.ctx.num_processes):
            m = src_proc == s
            if not m.any():
                continue
            idx = np.searchsorted(self.rows_tab[s], g[m])
            if np.any(idx >= len(self.rows_tab[s])) or np.any(
                self.rows_tab[s][idx] != g[m]
            ):
                raise RuntimeError(
                    f"byte shuffle missing rows from process {s}"
                )
            src_off[m] = self.offs_tab[s][idx] + self.base[s]
            out_len[m] = self.offs_tab[s][idx + 1] - self.offs_tab[s][idx]
            if self.bad[s]:
                # Quarantined intervals are sorted and disjoint (member
                # spans): a record overlaps one iff the first interval
                # ending after the record's start begins before its end.
                lo = np.asarray(self.offs_tab[s][idx], dtype=np.int64)
                hi = lo + out_len[m]
                starts = np.array([a for a, _ in self.bad[s]], np.int64)
                ends = np.array([b for _, b in self.bad[s]], np.int64)
                j = np.searchsorted(ends, lo, side="right")
                ov = (j < len(starts)) & (
                    starts[np.minimum(j, len(starts) - 1)] < hi
                )
                if ov.any():
                    if keep is None:
                        keep = np.ones(n, dtype=bool)
                    keep[np.nonzero(m)[0][ov]] = False
        if keep is not None:
            ndrop = int((~keep).sum())
            METRICS.count("salvage.records_dropped", ndrop)
            src_off = src_off[keep]
            out_len = out_len[keep]
            n = len(src_off)
        data = native.gather_records(
            self.big, src_off + 4, out_len - 4, order=None
        )
        out_off = np.empty(n + 1, dtype=np.int64)
        out_off[0] = 0
        np.cumsum(out_len, out=out_off[1:])
        return data, out_off[:-1] + 4, out_len - 4


class _RemoteNpy:
    """Range-read slices of a remote int64 ``.npy`` sideband.

    The local plane memmaps sidecars (O(log n) pages touched); the
    network plane must match that footprint or it silently defeats the
    memory budget, so only the header (to locate the data) and the
    requested element ranges ever cross the wire."""

    def __init__(self, fs, url: str):
        self._fs = fs
        self._url = url
        head = fs.read_range(url, 0, 128)
        if head[:6] != b"\x93NUMPY":
            raise IOError(f"not an npy file: {url}")
        major = head[6]
        if major == 1:
            hlen = int.from_bytes(head[8:10], "little")
            self._data0 = 10 + hlen
            hdr = head[10 : 10 + hlen]
        else:
            hlen = int.from_bytes(head[8:12], "little")
            self._data0 = 12 + hlen
            hdr = head[12 : 12 + hlen]
        if len(hdr) < hlen:
            hdr = fs.read_range(url, self._data0 - hlen, hlen)
        text = hdr.decode("latin-1")
        if "'<i8'" not in text or "'fortran_order': False" not in text:
            raise IOError(f"unexpected npy layout for ranged reads: {url}")

    def slice(self, i0: int, i1: int) -> np.ndarray:
        n = i1 - i0
        if n <= 0:
            return np.empty(0, np.int64)
        raw = self._fs.read_range(self._url, self._data0 + 8 * i0, 8 * n)
        if len(raw) != 8 * n:
            raise IOError(f"short sideband read from {self._url}")
        return np.frombuffer(raw, dtype="<i8")


class _RunAccess:
    """Uniform access to one process's spill runs for the budget plane:
    a local directory (shared-FS plane / own files, memmapped sidecars)
    or an ``(http_base, token)`` endpoint (network plane, ranged reads).
    Per-run handles are cached; bulk data never is.

    On the compressed plane the run's data file is a BGZF member stream
    (the spill IS the wire format — the budget now bounds *compressed*
    residency) and byte addressing stays in raw space via the
    ``.mtab.npy`` member table: :meth:`read_into` fetches exactly the
    compressed members covering the requested raw span and inflates them
    per window at gather time.  A one-member cache per run keeps a
    boundary member shared by two adjacent device windows from being
    fetched (or counted) twice, so the receiver-side wire accounting
    equals the sender's analytic member-cover count per edge."""

    def __init__(self, source, compressed: bool = False, dstream=None):
        self._source = source
        self._cache: dict = {}
        self.compressed = compressed
        self._dstream = dstream
        #: Per run: (member index, inflated payload) of the last member
        #: of the previous window — the boundary-member reuse cache.
        self._last: dict = {}

    def _handles(self, j: int):
        got = self._cache.get(j)
        if got is not None:
            return got
        from ..io import runs as runs_mod

        if isinstance(self._source, tuple):
            from ..io.fs import HttpFilesystem

            url, token = self._source
            f = HttpFilesystem(headers={"X-Hbam-Token": token})
            stem = f"{url.rstrip('/')}/run-{j:05d}"
            mtab = None
            if self.compressed:
                import io as _io

                mtab = np.load(
                    _io.BytesIO(f.read_all(stem + _MTAB_SUFFIX))
                )
            got = (
                _RemoteNpy(f, stem + runs_mod.RUN_KEYS_EXT),
                _RemoteNpy(f, stem + runs_mod.RUN_OFFS_EXT),
                _RemoteNpy(f, stem + ".org.npy"),
                (f, stem + runs_mod.RUN_DATA_EXT),
                mtab,
            )
        else:
            run = runs_mod.Run.open(self._source, j)
            org = np.load(
                os.path.join(self._source, f"run-{j:05d}.org.npy"),
                mmap_mode="r",
            )
            mtab = None
            if self.compressed:
                mtab = np.load(
                    os.path.join(
                        self._source, f"run-{j:05d}{_MTAB_SUFFIX}"
                    )
                )
            got = (run.keys, run.offs, org, run.data_path, mtab)
        self._cache[j] = got
        return got

    @staticmethod
    def _sl(arr, i0: int, i1: int) -> np.ndarray:
        if isinstance(arr, _RemoteNpy):
            return arr.slice(i0, i1)
        return np.asarray(arr[i0:i1], dtype=np.int64)

    def slices(self, j: int, i0: int, i1: int):
        """(keys[i0:i1], org[i0:i1], lens, byte_start, byte_len)."""
        keys, offs, org, _, _ = self._handles(j)
        o = self._sl(offs, i0, i1 + 1)
        return (
            self._sl(keys, i0, i1),
            self._sl(org, i0, i1),
            np.diff(o),
            int(o[0]),
            int(o[-1] - o[0]),
        )

    def _read_span(self, loc, start: int, size: int) -> np.ndarray:
        if isinstance(loc, tuple):
            f, url = loc
            data = f.read_range(url, start, size)
            if len(data) != size:
                raise IOError(f"short HTTP read from {url}")
            return np.frombuffer(data, np.uint8)
        out = np.empty(size, dtype=np.uint8)
        with open(loc, "rb") as fh:
            fh.seek(start)
            got = fh.readinto(memoryview(out))
        if got != size:
            raise IOError(f"short read from spill run {loc}")
        return out

    def read_into(self, j: int, view, byte_start: int, size: int) -> int:
        """Fill ``view`` with raw record bytes [byte_start, byte_start+
        size) of run ``j``; returns the WIRE bytes newly pulled for it
        (== size on the raw plane; the compressed members fetched —
        boundary member deduplicated — on the compressed plane)."""
        _, _, _, loc, mtab = self._handles(j)
        if not self.compressed:
            view[:] = self._read_span(loc, byte_start, size)
            return size
        m = mtab.reshape(-1, 4)
        m0, m1 = _member_cover(mtab, byte_start, byte_start + size)
        if m1 <= m0:
            return 0
        parts: List[np.ndarray] = []
        wire = 0
        fetch0 = m0
        cached = self._last.get(j)
        if cached is not None and cached[0] == m0:
            parts.append(cached[1])
            fetch0 = m0 + 1
        if fetch0 < m1:
            c0 = int(m[fetch0, 2])
            c1 = int(m[m1 - 1, 2] + m[m1 - 1, 3])
            comp = self._read_span(loc, c0, c1 - c0)
            wire = c1 - c0
            co = np.ascontiguousarray(m[fetch0:m1, 2] - c0, np.int64)
            cs = np.ascontiguousarray(m[fetch0:m1, 3], np.int32)
            us = np.ascontiguousarray(m[fetch0:m1, 1], np.int32)
            with span("mh.byte_shuffle.inflate", category="stage"):
                if self._dstream is not None:
                    raw, roffs = self._dstream.decode_members(
                        comp, co, cs, us, on_error="host"
                    )
                else:
                    raw, roffs = native.inflate_blocks(comp, co, cs, us)
            parts.append(raw)
            # Cache the final member alone for the next window's seam.
            self._last[j] = (
                m1 - 1,
                np.array(raw[int(roffs[-2]) : int(roffs[-1])], copy=True),
            )
        raw_all = parts[0] if len(parts) == 1 else np.concatenate(parts)
        s0 = byte_start - int(m[m0, 0])
        view[:] = raw_all[s0 : s0 + size]
        return wire


def _budget_byte_plane(
    ctx: MultihostContext,
    td: str,
    sources: List,
    splits,
    own_counts: List[int],
    dest_of_record: np.ndarray,
    level: int,
    D: int,
    peak_bytes: int,
    RecordBatch,
    write_part_fast,
    compress: bool = False,
    dstream=None,
) -> Tuple[int, List[int]]:
    """Out-of-core byte plane: the key-sorted spill runs ARE the shuffle.

    The shuffle's destination is a monotone function of the key, so each
    run's share of destination device ``g`` is one contiguous slice; a
    [runs, D+1] cut table per process (allgathered — a few KB) tells every
    receiver exactly which slice of which run it owns.  Receivers merge
    their slices by (key, ordinal) one destination device at a time —
    straight off the shared filesystem, or over authenticated HTTP range
    reads when the runs live on peers' local disks (``sources`` carries a
    directory or endpoint per process) — so peak materialized bytes is
    one device's output, not the received shard.

    Returns ``(peak_bytes, records per local output device)``.  The
    shuffle byte matrix is measured on both sides here too: the sender's
    ``mh.shuffle.sent.<dst>`` comes from its own runs' byte offsets at
    the cut indices (the runs ARE the byte plane, so the slice byte
    spans are the shipped bytes), the receiver's ``mh.shuffle.recv.<src>``
    from the slice bytes it actually read.  With ``compress`` the runs
    were spilled as BGZF member streams: both sides count the WIRE bytes
    of the members covering each slice (the sender analytically from the
    member table, the receiver from the member spans it actually pulled,
    boundary members deduplicated) with raw twins beside them, and
    receivers inflate per window — the memory budget bounds compressed
    fetch residency."""
    P_ = ctx.num_processes
    L = ctx.local_device_count
    n_runs_of = [
        sum(1 for k in range(len(splits)) if k % P_ == s)
        for s in range(P_)
    ]
    max_runs = max(1, max(n_runs_of))
    cuts = np.zeros((max_runs, D + 1), dtype=np.int64)
    rbase = 0
    for j, c in enumerate(own_counts):
        dr = dest_of_record[rbase : rbase + c]
        cuts[j] = np.searchsorted(dr, np.arange(D + 1), side="left")
        rbase += c
    cuts_all = ctx.allgather_array(cuts)  # [P, max_runs, D+1]
    # Sender side of the byte matrix: this process's runs live on local
    # (or shared) disk — the bytes destination process q will pull are
    # the runs' byte spans between q's device cuts, read off the
    # memmapped offset sidecars (no record bytes touched).
    from ..io import runs as runs_mod

    own_dir = sources[ctx.process_id]
    sent_bytes = np.zeros(P_, dtype=np.int64)
    sent_raw = np.zeros(P_, dtype=np.int64)
    for j in range(len(own_counts)):
        run = runs_mod.Run.open(own_dir, j)
        mtab_j = (
            np.load(os.path.join(own_dir, f"run-{j:05d}{_MTAB_SUFFIX}"))
            if compress
            else None
        )
        for q in range(P_):
            i0 = int(cuts[j][q * L])
            i1 = int(cuts[j][(q + 1) * L])
            raw_b = run.bytes_between(i0, i1)
            sent_raw[q] += raw_b
            if compress:
                b0 = int(run.offs[i0])
                sent_bytes[q] += _cover_comp_bytes(
                    mtab_j, b0, b0 + raw_b
                )
            else:
                sent_bytes[q] += raw_b
    for q in range(P_):
        METRICS.count(f"mh.shuffle.sent.{q}", int(sent_bytes[q]))
        METRICS.count(f"mh.shuffle.sent_raw.{q}", int(sent_raw[q]))
    TRACER.counter(
        "mh.shuffle.sent",
        {str(q): float(sent_bytes[q]) for q in range(P_)},
    )
    ctx.barrier("spill_published")

    access = [
        _RunAccess(src, compressed=compress, dstream=dstream)
        for src in sources
    ]
    recv_bytes = np.zeros(P_, dtype=np.int64)
    recv_raw = np.zeros(P_, dtype=np.int64)
    out_counts: List[int] = []
    with span("mh.range_merge", category="stage"):
        for g in range(ctx.process_id * L, (ctx.process_id + 1) * L):
            # Two passes over this device's slices: size everything, then
            # read each slice DIRECTLY into its place in one final buffer
            # (no per-slice temporaries coexisting with the concatenation).
            slices = []  # (source idx, run idx, byte_start, byte_len)
            key_parts: List[np.ndarray] = []
            org_parts: List[np.ndarray] = []
            len_parts: List[np.ndarray] = []
            for s in range(P_):
                for j in range(n_runs_of[s]):
                    i0 = int(cuts_all[s][j][g])
                    i1 = int(cuts_all[s][j][g + 1])
                    if i1 <= i0:
                        continue
                    keys_s, org_s, lens_s, b0, sz = access[s].slices(
                        j, i0, i1
                    )
                    slices.append((s, j, b0, sz))
                    recv_raw[s] += sz
                    key_parts.append(keys_s)
                    org_parts.append(org_s)
                    len_parts.append(lens_s)
            if slices:
                total = sum(sz for _, _, _, sz in slices)
                data = np.empty(total, dtype=np.uint8)
                pos = 0
                for s, j, b0, sz in slices:
                    recv_bytes[s] += access[s].read_into(
                        j, data[pos : pos + sz], b0, sz
                    )
                    pos += sz
                lens = np.concatenate(len_parts)
                keys_all = np.concatenate(key_parts)
                org_all = np.concatenate(org_parts)
                off = np.empty(len(lens) + 1, dtype=np.int64)
                off[0] = 0
                np.cumsum(lens, out=off[1:])
                perm = np.lexsort((org_all, keys_all))
                # write_part_fast gathers a permuted copy while ``data`` is
                # still alive: the honest materialized peak is ~2x the
                # device's payload.
                peak_bytes = max(peak_bytes, 2 * int(len(data)))
                batch = RecordBatch(
                    soa={
                        "rec_off": off[:-1] + 4,
                        "rec_len": lens - 4,
                    },
                    data=data,
                    keys=keys_all,
                )
            else:
                perm = None
                batch = RecordBatch(
                    soa={
                        "rec_off": np.empty(0, np.int64),
                        "rec_len": np.empty(0, np.int64),
                    },
                    data=np.empty(0, np.uint8),
                    keys=np.empty(0, np.int64),
                )
            out_counts.append(int(batch.n_records))
            tmp = os.path.join(td, f"_temporary.part-r-{g:05d}")
            with open(tmp, "wb") as f:
                write_part_fast(f, batch, order=perm, level=level)
            os.replace(tmp, os.path.join(td, f"part-r-{g:05d}"))
            del batch
    for s in range(P_):
        METRICS.count(f"mh.shuffle.recv.{s}", int(recv_bytes[s]))
        METRICS.count(f"mh.shuffle.recv_raw.{s}", int(recv_raw[s]))
    TRACER.counter(
        "mh.shuffle.recv",
        {str(s): float(recv_bytes[s]) for s in range(P_)},
    )
    ctx.barrier("parts_written")
    return peak_bytes, out_counts


# ---------------------------------------------------------------------------
# Mesh observability: per-host trace shards + manifests + the cluster fold.
# ---------------------------------------------------------------------------


def _distributed_name_ranks(
    ctx: MultihostContext, parts: List[dict]
) -> Tuple[np.ndarray, np.ndarray]:
    """The distributed half of the collation engine's rank pass.

    Each host collates its own splits by name hash, verifies every
    bucket against actual name bytes (:func:`collate.verify_and_repair`
    — no decision rests on hash equality), then allgathers only the
    per-group *representative names* — one short name per group, never
    per record.  Every host ranks the union with the samtools natural
    comparator over the same allgathered lists, so the dense global rank
    table agrees mesh-wide without a coordinator, and cross-host hash
    collisions cost nothing: two hosts whose different names share a
    64-bit hash simply contribute two distinct names to the union.

    Returns per-local-record (read order) ``(grank, tiebreak)``:
    ``grank`` the record name's global natural-order rank (the shuffle's
    primary word — routing on it colocates whole name groups) and
    ``tiebreak`` the engine's content tie-break word
    ``(flag << 32) | (pos + 1)`` (the secondary word; global read
    ordinal breaks remaining ties, matching the single-host lexsort).
    """
    from ..collate import (
        collate_by_name, concat_collation, verify_and_repair,
    )

    cols = concat_collation(parts)
    n = len(cols["qh1"])
    col = collate_by_name(cols, candidates=np.zeros(n, np.int32))
    col, _ = verify_and_repair(col, cols)
    grank, _n_names = _global_name_rank_pass(ctx, cols, col)
    tiebreak = (
        (cols["flag"].astype(np.int64) << 32)
        | (cols["pos"].astype(np.int64) + 1)
    )
    return grank, tiebreak


def _global_name_rank_pass(
    ctx: MultihostContext, cols: dict, col
) -> Tuple[np.ndarray, int]:
    """Allgather per-group representative names, rank the union, and
    return (per-record global rank in read order, global distinct-name
    count).  Collective: every host must call it, including hosts with
    zero local records."""
    from ..collate import global_name_ranks, group_representatives

    n = len(cols["qh1"])
    reps = group_representatives(cols, col) if n else []
    blob = (
        np.frombuffer(b"".join(reps), np.uint8)
        if reps else np.empty(0, np.uint8)
    )
    lens = np.array([len(r) for r in reps], np.int64)
    # Two allgathers of padded buffers (sizes first so every host pads
    # to the same global maximum — allgather shapes must agree).
    sizes = ctx.allgather_array(
        np.array([len(reps), len(blob)], np.int64)
    )
    max_g = int(sizes[:, 0].max())
    max_b = int(sizes[:, 1].max())
    lens_pad = np.zeros(max(1, max_g), np.int64)
    lens_pad[: len(lens)] = lens
    blob_pad = np.zeros(max(1, max_b), np.uint8)
    blob_pad[: len(blob)] = blob
    all_lens = ctx.allgather_array(lens_pad)
    all_blobs = ctx.allgather_array(blob_pad)
    rep_lists = []
    for p in range(ctx.num_processes):
        g = int(sizes[p, 0])
        offs = np.concatenate(
            [[0], np.cumsum(all_lens[p][:g])]
        ).astype(np.int64)
        buf = all_blobs[p].tobytes()
        rep_lists.append(
            [buf[int(offs[i]) : int(offs[i + 1])] for i in range(g)]
        )
    rank = global_name_ranks(rep_lists)
    METRICS.count("mh.rank.names", len(rank))
    grank = np.zeros(n, np.int64)
    if n:
        rank_of_group = np.array([rank[r] for r in reps], np.int64)
        grank[col.order] = rank_of_group[col.group]
    return grank, len(rank)


def _reservoir_splitters(
    ctx: MultihostContext,
    keys: np.ndarray,
    n_reservoir: int,
    n_devices: int,
    rng: np.random.Generator,
) -> Tuple[Optional[Tuple[np.ndarray, np.ndarray]], int]:
    """Re-elect range splitters from a per-host key reservoir.

    The in-shuffle election samples a handful of keys per device; a
    pathological (zipfian, clustered) key distribution can make those
    cuts land badly.  This is the rescue path: every host contributes up
    to ``n_reservoir`` uniformly-sampled keys, the allgathered pool is
    sorted, and new splitters are cut at the balanced quantiles — the
    best cut any sample of this size supports.  Returns the splitters as
    ``(hi, lo)`` int32/uint32 word arrays (the form
    :class:`~.shuffle.DistributedSort` pins as jit constants) plus the
    pool size, or ``(None, 0)`` for an empty mesh."""
    from ..ops.keys import split_keys_np

    n = len(keys)
    take = int(min(n, n_reservoir))
    samp = (
        rng.choice(keys, size=take, replace=False)
        if 0 < take < n else keys[:take].copy()
    )
    buf = np.full(max(1, n_reservoir), np.iinfo(np.int64).max, np.int64)
    buf[:take] = samp
    counts = ctx.allgather_counts(take)
    allb = ctx.allgather_array(buf)
    pool = np.concatenate(
        [allb[p, : int(counts[p])] for p in range(len(counts))]
    )
    if pool.size == 0:
        return None, 0
    pool.sort()
    cut = np.clip(
        np.arange(1, n_devices, dtype=np.int64) * len(pool) // n_devices,
        0, len(pool) - 1,
    )
    sp_hi, sp_lo = split_keys_np(pool[cut])
    return (sp_hi, sp_lo), int(pool.size)


# --- Speculative stage re-execution: the shared-directory control plane.
# Route sidecars publish each owned part's post-route locator (which
# (src_dev, src_row) feed it); done markers publish per-host stage
# durations.  Both live in the parts directory — already the one
# directory every host and the merge can reach.


def _route_sidecar(td: str, g_dev: int) -> str:
    return os.path.join(td, f"_route-d{g_dev:05d}.npy")


def _write_route_sidecar(
    td: str, g_dev: int, sd: np.ndarray, sr: np.ndarray
) -> None:
    tmp = _route_sidecar(td, g_dev) + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, np.stack(
            [sd.astype(np.int64), sr.astype(np.int64)]
        ))
    os.replace(tmp, _route_sidecar(td, g_dev))


def _done_marker(td: str, pid: int) -> str:
    return os.path.join(td, f"_done-h{pid:03d}.json")


def _write_done_marker(td: str, pid: int, dur_s: float) -> None:
    tmp = _done_marker(td, pid) + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"pid": pid, "dur_s": dur_s}, f)
    os.replace(tmp, _done_marker(td, pid))


def _try_read_json(path: str) -> Optional[dict]:
    """Tolerant read for poll loops: a marker that is absent, torn, or
    mid-rename reads as None, never an exception."""
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode())
    except (OSError, ValueError):
        return None


def _promote_part(
    td: str, writer_pid: int, g_dev: int, write_fn, first_wins: bool
) -> Tuple[bool, int]:
    """Write one part through a generation-tagged tmp and promote it.

    Disarmed (``first_wins=False``): the existing atomic
    tmp-then-replace.  Armed: the tmp name carries the writer's process
    id (the generation tag) and promotion is ``os.link`` — the
    filesystem's compare-and-swap, first writer wins, every later copy
    of the same part gets ``FileExistsError`` and is discarded.  Returns
    ``(won, part_bytes)``; a discarded copy's size is the speculation
    waste the manifests must confess."""
    final = os.path.join(td, f"part-r-{g_dev:05d}")
    if not first_wins:
        tmp = os.path.join(td, f"_temporary.part-r-{g_dev:05d}")
        with open(tmp, "wb") as f:
            write_fn(f)
        os.replace(tmp, final)
        return True, 0
    tmp = os.path.join(td, f"_tmp-h{writer_pid:03d}.part-r-{g_dev:05d}")
    with open(tmp, "wb") as f:
        write_fn(f)
    size = int(os.path.getsize(tmp))
    try:
        os.link(tmp, final)
        won = True
    except FileExistsError:
        won = False
    os.unlink(tmp)
    return won, size


def _speculate_stage(
    ctx: MultihostContext, td: str, sources: List, rows: int,
    compress: bool, dstream, fetch_threads: int,
    errors: Optional[str], target: int, level: int,
    RecordBatch, write_part_fast, plan,
) -> dict:
    """Re-execute the straggling ``target`` host's gather+write stage.

    The byte plane already holds everything needed: every sender wrote
    runs addressed to ``target`` before the ``byte_shuffle_written``
    barrier, and the straggler published its route sidecars before its
    own (slow) writes.  The copy fetches with ``dest_pid=target``
    (accounted as ``mh.speculate.fetch_bytes``, never the recv matrix),
    writes generation-tagged parts, and races the original through
    :func:`_promote_part` — whoever links first wins, byte-identical
    either way because the part bytes are a pure function of the route."""
    L = ctx.local_device_count
    with span("mh.speculate", category="stage"):
        METRICS.count("mh.speculate.launched", 1)
        fetcher = _ByteFetcher(
            sources, ctx, rows, compress=compress, dstream=dstream,
            fetch_threads=fetch_threads, errors=errors, dest_pid=target,
        )
        won_parts = 0
        wasted = 0
        for g_dev in range(target * L, (target + 1) * L):
            try:
                with open(_route_sidecar(td, g_dev), "rb") as f:
                    route = np.load(f)
            except (OSError, ValueError):
                continue  # locator never published; nothing to re-run
            data, rec_off, rec_len = fetcher.gather(
                route[0].astype(np.int32), route[1].astype(np.int32)
            )
            batch = RecordBatch(
                soa={"rec_off": rec_off, "rec_len": rec_len},
                data=data,
                keys=np.zeros(len(rec_off), dtype=np.int64),
            )
            if plan is not None:
                plan.mh_speculate_lose()
            won, size = _promote_part(
                td, ctx.process_id, g_dev,
                lambda f, b=batch: write_part_fast(
                    f, b, order=None, level=level
                ),
                first_wins=True,
            )
            if won:
                won_parts += 1
                METRICS.count("mh.speculate.won", 1)
            else:
                wasted += size
                METRICS.count("mh.speculate.wasted_bytes", size)
    return {
        "launched": 1,
        "target": target,
        "won_parts": won_parts,
        "wasted_bytes": wasted,
    }


def _maybe_speculate(
    ctx: MultihostContext, td: str, sources: List, rows: int,
    compress: bool, dstream, fetch_threads: int,
    errors: Optional[str], factor: float, my_dur: float, level: int,
    RecordBatch, write_part_fast, plan,
) -> dict:
    """The post-stage poll loop every armed host runs after writing its
    own parts: read peers' done markers; once the critical-path host has
    exceeded ``factor`` × the median finished-stage duration, the
    lowest-pid *finished* host (one designated speculator — no thundering
    herd) re-executes the straggler's stage.  The loop drains when every
    marker is present — exactly the wait the ``parts_written`` barrier
    would impose anyway, so speculation costs idle time, not new
    synchronization."""
    P_ = ctx.num_processes
    t0 = time.perf_counter()
    info: dict = {}
    speculated: set = set()
    while True:
        done: dict = {}
        for p in range(P_):
            blob = _try_read_json(_done_marker(td, p))
            if blob is not None:
                done[p] = float(blob.get("dur_s", 0.0))
        missing = [p for p in range(P_) if p not in done]
        if not missing:
            return info
        cand = [p for p in missing if p not in speculated]
        if cand and min(done) == ctx.process_id:
            durs = sorted(done.values())
            med = max(durs[len(durs) // 2], 1e-3)
            elapsed = my_dur + (time.perf_counter() - t0)
            if elapsed > factor * med:
                t = cand[0]
                speculated.add(t)
                got = _speculate_stage(
                    ctx, td, sources, rows, compress, dstream,
                    fetch_threads, errors, t, level,
                    RecordBatch, write_part_fast, plan,
                )
                info = {
                    k: info.get(k, 0) + v if k != "target" else v
                    for k, v in got.items()
                }
        time.sleep(0.05)


def _shard_name(pid: int) -> str:
    return f"trace-h{pid:03d}.json"


def _manifest_name(pid: int) -> str:
    return f"manifest-h{pid:03d}.json"


def _read_from_source(source, name: str) -> bytes:
    """One named flat file from a byte-plane source: a local/shared
    directory, or an ``(url, token)`` endpoint — the same retrieval the
    ``shufbytes-*`` runs ride."""
    if isinstance(source, tuple):
        from ..io.fs import HttpFilesystem

        url, token = source
        f = HttpFilesystem(
            headers={"X-Hbam-Token": token},
            retry_metric="mh.http.fetch_retries",
        )
        return f.read_all(f"{url.rstrip('/')}/{name}")
    with open(os.path.join(source, name), "rb") as fh:
        return fh.read()


class _MeshObservability:
    """The distributed observability plane of one ``sort_bam_multihost``
    call (ISSUE 14 tentpole).

    Armed (``mesh_trace``): every process arms the process-global
    :data:`TRACER` (unless the caller already did), anchors its trace
    clock at a dedicated ``trace_sync`` barrier — the per-host anchors
    are exchanged via ``allgather_array`` and stamped into each shard's
    ``otherData`` so ``tools/mesh_report.py`` can shift all shards onto
    one merged timeline — and, after the parts are written, exports
    ``trace-h<pid>.json`` + ``manifest-h<pid>.json`` into its byte-plane
    directory.  Process 0 then pulls every shard through the same
    locator list the ``shufbytes-*`` files use (local read or
    authenticated HTTP), drops them into ``trace_dir``, and folds the
    host manifests into a :class:`~..utils.tracing.ClusterManifest`
    (written as ``cluster_manifest.json`` and kept in
    :data:`LAST_CLUSTER_MANIFEST`).

    Disarmed (the default): every method returns immediately — no extra
    barriers, no exports, zero ``mh.shuffle.*`` / ``mh.barrier.*`` trace
    events (the METRICS counters/gauges are the always-on metrics plane,
    like the transfers ledger) and byte-identical output.
    """

    def __init__(self, ctx: MultihostContext, enabled: bool,
                 trace_dir: str, byte_plane: str, conf, budget: bool,
                 compressed: bool = False, fetch_threads: int = 8):
        self.ctx = ctx
        self.enabled = enabled
        self.trace_dir = trace_dir
        self.byte_plane = byte_plane
        self.conf = conf
        self.budget = budget
        self.compressed = compressed
        self.fetch_threads = fetch_threads
        self._started = False
        self.anchor_us = 0.0
        self.anchors: Optional[np.ndarray] = None
        self._peer_manifests: dict = {}
        self._mesh_meta: dict = {}
        self._before = None
        #: Skew-healing provenance, set by the driver before publish():
        #: the repartition block (triggered/sample_keys/ratio_before/
        #: ratio_after) and the speculation block (launched/won/
        #: wasted_bytes/target) land verbatim in the host manifest and
        #: fold into the ClusterManifest.
        self.repartition: dict = {}
        self.speculation: dict = {}

    # -- arming ------------------------------------------------------------

    def arm(self) -> None:
        """Arm the tracer and anchor every host's clock at one barrier."""
        if not self.enabled:
            return
        from ..utils.tracing import snapshot

        self._before = snapshot()
        if not TRACER.armed:
            from ..utils.tracing import DEFAULT_TRACE_EVENTS

            cap = DEFAULT_TRACE_EVENTS
            if self.conf is not None:
                from ..conf import TRACE_EVENTS

                cap = self.conf.get_int(TRACE_EVENTS, DEFAULT_TRACE_EVENTS)
            TRACER.start(capacity=cap)
            self._started = True
        # The shards' shared clock: every host leaves this barrier at
        # ~the same wall instant and stamps its own ring clock; shifting
        # each shard so the anchors coincide puts all hosts on one
        # timeline (collective-exit skew is the alignment error bound).
        self.ctx.barrier("trace_sync")
        self.anchor_us = float(TRACER.now_us())
        self.anchors = self.ctx.allgather_array(
            np.asarray([self.anchor_us], dtype=np.float64)
        ).reshape(-1)

    def stage_barrier(self, name: str) -> None:
        """An alignment barrier the observability plane inserts so
        per-stage skew is measured at a named point (the read stage's
        stragglers would otherwise smear into whichever collective runs
        next and be blamed on the wrong host).  No-op when disarmed."""
        if self.enabled:
            self.ctx.barrier(name)

    # -- manifests ---------------------------------------------------------

    def host_manifest(self, peak_bytes: int, n_local: int,
                      out_counts: List[int], skew_ratio: float) -> dict:
        from ..utils.tracing import delta, run_manifest

        d = delta(self._before) if self._before is not None else {
            "counters": METRICS.report()["counters"], "span_seconds": {},
        }
        counters = d.get("counters", {})
        spans = d.get("span_seconds", {})

        def _edges(prefix: str) -> dict:
            return {
                k[len(prefix):]: int(v)
                for k, v in counters.items()
                if k.startswith(prefix)
            }

        return {
            "host": self.ctx.process_id,
            "num_processes": self.ctx.num_processes,
            "byte_plane": self.byte_plane,
            "memory_budget": self.budget,
            "peak_bytes": int(peak_bytes),
            "records_local": int(n_local),
            "records_out": [int(c) for c in out_counts],
            "skew_ratio": float(skew_ratio),
            "shuffle_compressed": self.compressed,
            "fetch_threads": int(self.fetch_threads),
            "shuffle_sent_bytes": _edges("mh.shuffle.sent."),
            "shuffle_recv_bytes": _edges("mh.shuffle.recv."),
            "shuffle_sent_raw_bytes": _edges("mh.shuffle.sent_raw."),
            "shuffle_recv_raw_bytes": _edges("mh.shuffle.recv_raw."),
            "keys_sent_bytes": _edges("mh.keys.sent."),
            "keys_recv_bytes": _edges("mh.keys.recv."),
            "barrier_wait_ms": {
                k[len("mh.barrier."):]: round(v * 1e3, 3)
                for k, v in spans.items()
                if k.startswith("mh.barrier.")
            },
            "http": {
                k[len("mh.http."):]: int(v)
                for k, v in counters.items()
                if k.startswith("mh.http.")
            },
            "anchor_us": self.anchor_us,
            "repartition": dict(self.repartition),
            "speculation": dict(self.speculation),
            "run_manifest": run_manifest(
                backend="multihost", conf=self.conf, counters=counters
            ).as_dict(),
        }

    # -- publication + collection ------------------------------------------

    def publish(self, serve_dir: str, sources: List, peak_bytes: int,
                n_local: int, out_counts: List[int],
                skew_ratio: float) -> None:
        """Export this host's shard + manifest into its byte-plane
        directory, then (process 0) collect every peer's into
        ``trace_dir``.  Called after ``parts_written`` and *before* the
        byte-plane directories are deleted."""
        if not self.enabled:
            return
        pid = self.ctx.process_id
        mesh_meta = {
            "mesh": {
                "host": pid,
                "num_hosts": self.ctx.num_processes,
                "anchor_us": self.anchor_us,
                "anchors_us": [float(a) for a in (
                    self.anchors if self.anchors is not None else []
                )],
                "byte_plane": self.byte_plane,
            }
        }
        self._mesh_meta = mesh_meta
        manifest = self.host_manifest(
            peak_bytes, n_local, out_counts, skew_ratio
        )
        global LAST_MANIFEST
        LAST_MANIFEST = manifest
        TRACER.export_chrome(
            os.path.join(serve_dir, _shard_name(pid)), other=mesh_meta
        )
        with open(os.path.join(serve_dir, _manifest_name(pid)), "w") as f:
            json.dump(manifest, f)
        self.ctx.barrier("trace_published")
        if pid == 0:
            os.makedirs(self.trace_dir, exist_ok=True)
            for s in range(1, self.ctx.num_processes):
                blob = _read_from_source(sources[s], _shard_name(s))
                with open(
                    os.path.join(self.trace_dir, _shard_name(s)), "wb"
                ) as f:
                    f.write(blob)
                mblob = _read_from_source(sources[s], _manifest_name(s))
                self._peer_manifests[s] = json.loads(mblob.decode())
                with open(
                    os.path.join(self.trace_dir, _manifest_name(s)), "wb"
                ) as f:
                    f.write(mblob)
        # Peers must not tear their serve dirs down under host 0's
        # collection — everyone holds until the shards are safely out.
        self.ctx.barrier("trace_collected")

    def finalize(self, peak_bytes: int, n_local: int,
                 out_counts: List[int], skew_ratio: float) -> None:
        """After the merge: process 0 re-exports its own shard (now
        covering ``mh.merge``) straight into ``trace_dir``, folds the
        host manifests into the ClusterManifest, and writes
        ``cluster_manifest.json``; every process disarms the tracer it
        started."""
        if not self.enabled:
            return
        try:
            if self.ctx.process_id == 0:
                from ..utils.tracing import cluster_manifest

                os.makedirs(self.trace_dir, exist_ok=True)
                own = self.host_manifest(
                    peak_bytes, n_local, out_counts, skew_ratio
                )
                global LAST_MANIFEST, LAST_CLUSTER_MANIFEST
                LAST_MANIFEST = own
                TRACER.export_chrome(
                    os.path.join(self.trace_dir, _shard_name(0)),
                    other=self._mesh_meta,
                )
                with open(
                    os.path.join(self.trace_dir, _manifest_name(0)), "w"
                ) as f:
                    json.dump(own, f)
                manifests = [own] + [
                    self._peer_manifests[s]
                    for s in sorted(self._peer_manifests)
                ]
                cm = cluster_manifest(
                    manifests, byte_plane=self.byte_plane
                ).as_dict()
                LAST_CLUSTER_MANIFEST = cm
                with open(
                    os.path.join(self.trace_dir, "cluster_manifest.json"),
                    "w",
                ) as f:
                    json.dump(cm, f, indent=2, sort_keys=True)
        finally:
            if self._started:
                TRACER.stop()


def _resolve_mesh_trace(conf, mesh_trace: Optional[bool]) -> bool:
    """Explicit argument → ``hadoopbam.mesh.trace`` → HBAM_MESH_TRACE."""
    if mesh_trace is not None:
        return bool(mesh_trace)
    if conf is not None:
        from ..conf import MESH_TRACE

        if conf.get(MESH_TRACE) is not None:
            return conf.get_boolean(MESH_TRACE, False)
    env = os.environ.get("HBAM_MESH_TRACE", "").strip().lower()
    return env not in ("", "0", "false", "off", "no")


def _resolve_mesh_trace_dir(
    conf, mesh_trace_dir: Optional[str], out_path: str
) -> str:
    if mesh_trace_dir:
        return mesh_trace_dir
    if conf is not None:
        from ..conf import MESH_TRACE_DIR

        got = conf.get(MESH_TRACE_DIR)
        if got:
            return got
    env = os.environ.get("HBAM_MESH_TRACE_DIR")
    if env:
        return env
    return os.path.abspath(out_path) + ".mesh-trace"


# ---------------------------------------------------------------------------
# End-to-end multi-host coordinate sort.
# ---------------------------------------------------------------------------


def sort_bam_multihost(
    in_paths: Sequence[str] | str,
    out_path: str,
    ctx: Optional[MultihostContext] = None,
    conf=None,
    split_size: int = 32 << 20,
    level: int = 6,
    samples_per_device: int = 64,
    memory_budget: Optional[int] = None,
    byte_plane: str = "fs",
    mesh_trace: Optional[bool] = None,
    mesh_trace_dir: Optional[str] = None,
    errors: Optional[str] = None,
    sort_order: str = "coordinate",
) -> int:
    """Sort BAM(s) across every process of the JAX runtime
    (full docs on the implementation below; resources — shuffle data
    servers, local spill directories — are owned by an ExitStack so every
    failure path tears them down).

    ``sort_order`` is ``"coordinate"`` (default) or ``"queryname"``.
    Queryname runs the collation engine's rank pass *distributed*: each
    host collates its own splits by name hash, verifies buckets against
    actual name bytes, and allgathers only the per-group representative
    names; every host then ranks the union with the samtools natural
    comparator, so the global rank table agrees mesh-wide without a
    coordinator and cross-host hash collisions cost nothing (ranking is
    on name bytes, never on hashes).  Records route by (rank, flag, pos)
    through the same key/byte planes as coordinate — the output is
    byte-identical to single-host ``sort_bam(...,
    sort_order="queryname")``.  Queryname is in-core only
    (``memory_budget`` must be None: spill-run cut tables need read-time
    keys, and queryname ranks exist only after the rank pass).

    ``mesh_trace`` (default: ``hadoopbam.mesh.trace`` conf key /
    HBAM_MESH_TRACE env, off) arms the mesh observability plane: every
    process records a per-host timeline shard and a host manifest,
    process 0 collects them into ``mesh_trace_dir`` (default
    ``<out_path>.mesh-trace``) and folds a ClusterManifest — reduce with
    ``tools/mesh_report.py``.

    ``errors`` (default: ``hadoopbam.errors`` conf key, strict) selects
    the compressed byte plane's corruption policy: strict fails the sort
    on a member that arrives corrupt; ``"salvage"`` quarantines exactly
    that member (``salvage.*`` counters) and finishes with the surviving
    records byte-exact.  Salvage applies to the in-core fetch plane;
    the budget plane's windowed reads stay strict (its spill runs are
    local/validated, not in-flight fetches)."""
    import contextlib

    with contextlib.ExitStack() as stack:
        return _sort_bam_multihost_impl(
            in_paths, out_path, ctx, conf, split_size, level,
            samples_per_device, memory_budget, byte_plane, stack,
            mesh_trace, mesh_trace_dir, errors, sort_order,
        )


def _sort_bam_multihost_impl(
    in_paths,
    out_path: str,
    ctx: Optional[MultihostContext],
    conf,
    split_size: int,
    level: int,
    samples_per_device: int,
    memory_budget: Optional[int],
    byte_plane: str,
    _stack,
    mesh_trace: Optional[bool] = None,
    mesh_trace_dir: Optional[str] = None,
    errors: Optional[str] = None,
    sort_order: str = "coordinate",
) -> int:
    """Sort BAM(s) across every process of the JAX runtime.

    All paths (input, output, and the shuffle directory derived from the
    output path) must be on a filesystem visible to every process — the
    same contract HDFS gives the reference.  Returns the global record
    count (identical on every process); the merged output is written by
    process 0.

    ``byte_plane`` selects how record bytes move between processes:
    ``"fs"`` (spill files on a filesystem every process can read — the
    HDFS-backed stance) or ``"http"`` (each process writes its outgoing
    runs to *local* disk and serves them over HTTP; receivers pull their
    share through the io.fs seam — Hadoop's map-output fetch, no shared
    filesystem needed for the data plane).  The output/part directory
    still needs to be reachable by process 0 for the merge.

    ``memory_budget`` (bytes of uncompressed record stream, per process)
    composes the out-of-core sort with the multi-host shuffle (VERDICT r3
    #6 — Hadoop's sort-spill-merge shuffle, SURVEY §2.7): each process
    spills its splits as key-sorted runs at read time and only the
    key/ordinal columns stay resident; the runs then ARE the byte plane —
    the shuffle's destination is monotone in the key, so each
    destination device's share of every run is one contiguous slice,
    published in a tiny allgathered cut table and merged receiver-side by
    (key, ordinal) straight off the shared filesystem.  Peak materialized
    record bytes per process ≈ max(one split, one device's output part);
    the key plane (~13 bytes/record) is accounted separately as in the
    single-host external sort.
    """
    from ..io.bam import BamInputFormat, read_header, write_part_fast
    from ..io.merger import merge_bam_parts
    from ..io import runs as runs_mod
    from ..ops.keys import split_keys_np
    from ..pipeline import RecordBatch, _concat_batches
    from jax.sharding import NamedSharding, PartitionSpec as P

    if isinstance(in_paths, str):
        in_paths = [in_paths]
    if ctx is None:
        ctx = initialize()
    if byte_plane not in ("fs", "http"):
        raise ValueError(f"byte_plane must be 'fs' or 'http': {byte_plane!r}")
    if sort_order not in ("coordinate", "queryname"):
        raise ValueError(
            f"sort_order must be 'coordinate' or 'queryname': {sort_order!r}"
        )
    queryname = sort_order == "queryname"
    if queryname and memory_budget is not None:
        raise ValueError(
            "sort_order='queryname' is in-core on the mesh: the spill "
            "plane's monotone-key cut tables need keys at read time, and "
            "queryname ranks exist only after the distributed rank pass"
        )
    if errors is None and conf is not None:
        from ..conf import ERRORS_MODE

        errors = conf.get(ERRORS_MODE)
    # The compressed wire format + its per-job codec seams: tier policy,
    # residency and donation resolve ONCE here (the DeviceStream), and
    # every deflate/inflate the shuffle does rides that stream.
    compress_shuffle = _resolve_shuffle_compress(conf)
    member_bytes = _resolve_member_bytes(conf)
    fetch_threads = _resolve_fetch_threads(conf)
    # Skew healing (this PR): the post-route balance bound that triggers
    # the one-shot range repartition, the straggler factor that arms
    # speculative stage re-execution, and the repartition reservoir size.
    skew_bound = _resolve_skew_bound(conf)
    spec_factor = _resolve_speculate_factor(conf)
    n_reservoir = _resolve_repartition_samples(conf)
    from ..device_stream import DeviceStream

    dstream = DeviceStream(conf=conf, name="mh.shuffle")
    obs = _MeshObservability(
        ctx,
        enabled=_resolve_mesh_trace(conf, mesh_trace),
        trace_dir=_resolve_mesh_trace_dir(conf, mesh_trace_dir, out_path),
        byte_plane=byte_plane,
        conf=conf,
        budget=memory_budget is not None,
        compressed=compress_shuffle,
        fetch_threads=fetch_threads,
    )
    obs.arm()
    if memory_budget is not None:
        # A split inflates as one batch: keep it well under the budget
        # (same clamp rule as the single-host external sort).
        split_size = max(64 << 10, min(split_size, memory_budget // 16))
    fmt = BamInputFormat(conf)
    header = read_header(in_paths[0]).with_sort_order(sort_order)
    with span("mh.plan", category="stage"):
        splits = fmt.get_splits(in_paths, split_size=split_size)
    mine = ctx.owned(splits)

    out_dir_pre = os.path.dirname(os.path.abspath(out_path)) or "."
    td = os.path.join(
        out_dir_pre, f"_mh_{os.path.basename(out_path)}.parts"
    )
    shuffle_dir = os.path.join(td, "shuffle")
    spill_dir = os.path.join(shuffle_dir, f"spill-{ctx.process_id:03d}")
    if memory_budget is not None:
        if byte_plane == "http":
            # Network plane: spill runs live on LOCAL disk and are served
            # over HTTP; the shared directory is never written.  The
            # ExitStack owns the directory: any failure from here on
            # removes the spilled shard.
            import tempfile as _tf

            spill_dir = _tf.mkdtemp(prefix="hbam_spill_")
            _stack.callback(nio.delete_recursive, spill_dir)
        else:
            os.makedirs(spill_dir, exist_ok=True)

    # The mesh straggler drill's injection point: the PR 7 ``exec.delay``
    # (/crash/die/torn) directive fires here per split with item = this
    # process id and attempt = the local split ordinal, so a plan like
    # ``exec.delay:items=1,ms=250,n=*`` slows exactly host 1's read stage
    # — the injected-delay drill mesh_report must attribute correctly.
    _plan = faults.ACTIVE
    _torn = os.path.join(
        out_dir_pre, f"_mh_torn_{ctx.process_id:03d}.tmp"
    )

    peak_bytes = 0
    if memory_budget is None:
        qn_fields = None
        if queryname:
            from ..collate import collation_columns
            from ..io.bam import SORT_FIELDS

            qn_fields = tuple(
                dict.fromkeys(SORT_FIELDS + ("l_read_name",))
            )
        collate_cols: List[dict] = []
        with span("mh.read", category="stage"):
            batches = []
            for j, s in enumerate(mine):
                if _plan is not None:
                    _plan.exec_attempt(ctx.process_id, j, _torn)
                with trace_ctx(split=ctx.process_id + j * ctx.num_processes):
                    if queryname:
                        # Decode the name-collation columns now (hashes,
                        # flag/pos, the name blob) — the rank pass below
                        # works on these, never on whole records.
                        b = fmt.read_split(
                            s, fields=qn_fields, with_keys=False
                        )
                        collate_cols.append(collation_columns(b.data, b.soa))
                        b.soa = {
                            "rec_off": b.soa["rec_off"],
                            "rec_len": b.soa["rec_len"],
                        }
                        batches.append(b)
                    else:
                        batches.append(fmt.read_split(s))
            own_counts = [b.n_records for b in batches]
            if queryname:
                # The trimmed batches carry only record extents (keys and
                # the full SOA were never decoded) — concat those.
                base = np.cumsum(
                    [0] + [len(b.data) for b in batches[:-1]]
                ).astype(np.int64)
                local = RecordBatch(
                    soa={
                        "rec_off": (
                            np.concatenate([
                                b.soa["rec_off"] + base[i]
                                for i, b in enumerate(batches)
                            ])
                            if batches else np.empty(0, np.int64)
                        ),
                        "rec_len": (
                            np.concatenate(
                                [b.soa["rec_len"] for b in batches]
                            )
                            if batches else np.empty(0, np.int64)
                        ),
                    },
                    data=(
                        np.concatenate([b.data for b in batches])
                        if batches else np.empty(0, np.uint8)
                    ),
                    keys=np.empty(int(sum(own_counts)), np.int64),
                )
            else:
                local = _concat_batches(batches)
            del batches
        n_local = local.n_records
    else:
        # Budget mode: spill each split as a key-sorted run immediately;
        # only the sorted key/ordinal columns stay resident.
        local = None
        own_counts = []
        key_cols: List[np.ndarray] = []
        perm_cols: List[np.ndarray] = []  # per run: the sort permutation
        with span("mh.read_spill", category="stage"):
            for ri, s in enumerate(mine):
                if _plan is not None:
                    _plan.exec_attempt(ctx.process_id, ri, _torn)
                with trace_ctx(
                    split=ctx.process_id + ri * ctx.num_processes
                ):
                    b = fmt.read_split(s)
                peak_bytes = max(peak_bytes, int(len(b.data)))
                perm = np.argsort(b.keys, kind="stable")
                if compress_shuffle:
                    _write_run_compressed(
                        spill_dir, ri, b, perm, dstream, level,
                        member_bytes,
                    )
                else:
                    runs_mod.write_run(spill_dir, ri, b, perm)
                key_cols.append(np.ascontiguousarray(b.keys[perm]))
                perm_cols.append(perm.astype(np.int64))
                own_counts.append(b.n_records)
                del b
        n_local = int(sum(own_counts))
    # Armed runs align here so read-stage skew is measured at a named
    # barrier instead of smearing into the counts allgather below (and
    # being blamed on the wrong host); disarmed runs are unchanged.
    obs.stage_barrier("read_done")

    # Global record ordinals: allgather per-split record counts (padded to
    # the round-robin width) so every process derives the same exclusive
    # scan over splits in plan order.  Ordinals are the shuffle's
    # tie-breaker — output tie order matches the single-process stable
    # sort's exactly.
    P_ = ctx.num_processes
    max_owned = max(1, -(-len(splits) // P_))
    cm = np.zeros(max_owned, dtype=np.int64)
    cm[: len(own_counts)] = own_counts
    M = ctx.allgather_array(cm)  # [P, max_owned]
    counts_by_split = np.zeros(max(1, len(splits)), dtype=np.int64)
    for k in range(len(splits)):
        counts_by_split[k] = M[k % P_][k // P_]
    split_base = np.concatenate(
        [[0], np.cumsum(counts_by_split)]
    ).astype(np.int64)
    n_total = int(split_base[len(splits)])
    if n_total >= (1 << 31):
        raise ValueError(
            "record ordinals exceed int32; shard the input further"
        )
    if memory_budget is None:
        orig_local = (
            np.concatenate(
                [
                    split_base[ctx.process_id + j * P_] + np.arange(c)
                    for j, c in enumerate(own_counts)
                ]
            ).astype(np.int32)
            if own_counts
            else np.empty(0, np.int32)
        )
        if queryname:
            # The distributed rank pass: keys do not exist at read time
            # for queryname — they ARE the global name ranks.
            with span("mh.rank", category="stage"):
                keys_local, qn_tiebreak = _distributed_name_ranks(
                    ctx, collate_cols
                )
            del collate_cols
        else:
            keys_local = local.keys
    else:
        # Run r is split-ordinal-base + its sort permutation (the run is
        # the split's records in key order, so ordinal = base + perm).
        org_cols = [
            (split_base[ctx.process_id + j * P_] + perm_cols[j]).astype(
                np.int64
            )
            for j in range(len(own_counts))
        ]
        orig_local = (
            np.concatenate(org_cols).astype(np.int32)
            if org_cols
            else np.empty(0, np.int32)
        )
        keys_local = (
            np.concatenate(key_cols)
            if key_cols
            else np.empty(0, np.int64)
        )
        # Publish per-run ordinal sidecars for the receiver-side merge.
        for j, oc in enumerate(org_cols):
            tmp = os.path.join(spill_dir, f"run-{j:05d}.org.npy.tmp")
            with open(tmp, "wb") as f:
                np.save(f, oc)
            os.replace(tmp, tmp[: -len(".tmp")])
        del perm_cols, key_cols, org_cols

    counts = M.sum(axis=1)
    L = ctx.local_device_count
    D = ctx.global_device_count
    rows = max(1, -(-int(counts.max()) // L))

    # Place local records into local device slots.  A deterministic
    # per-process permutation spreads any key-ordered input across slots so
    # no (src,dst) capacity bucket is hit by a monotone run.
    rng = np.random.default_rng(0x5EED + ctx.process_id)
    slots = rng.permutation(L * rows)[:n_local]
    hi_l = np.full(L * rows, 0x7FFFFFFF, np.int32)
    lo_l = np.full(L * rows, 0xFFFFFFFF, np.uint32)
    val_l = np.zeros(L * rows, dtype=bool)
    org_l = np.full(L * rows, 0x7FFFFFFF, np.int32)
    k_hi, k_lo = split_keys_np(keys_local)
    hi_l[slots] = k_hi
    lo_l[slots] = k_lo
    val_l[slots] = True
    org_l[slots] = orig_local
    if queryname:
        # Secondary key word: the engine's (flag, pos+1) tie-break rides
        # the shuffle as a second (hi, lo) column pair; the global read
        # ordinal (org) breaks remaining ties, so the device-side sort
        # reproduces the single-host lexsort exactly.
        hi2_l = np.full(L * rows, 0x7FFFFFFF, np.int32)
        lo2_l = np.full(L * rows, 0xFFFFFFFF, np.uint32)
        k2_hi, k2_lo = split_keys_np(qn_tiebreak)
        hi2_l[slots] = k2_hi
        lo2_l[slots] = k2_lo
    # record index -> its local slot (for the byte plane)
    row_of_record = slots.astype(np.int64)

    sharding = NamedSharding(ctx.mesh, P(DATA_AXIS))

    def gshard(arr):
        return jax.make_array_from_process_local_data(
            sharding, arr, (D * rows,) + arr.shape[1:]
        )

    # Sender-side routing table: destination device of each local record.
    # Addressable-shard order is not guaranteed — order by global offset.
    def _local_view(arr, per_shard: int) -> List[np.ndarray]:
        got = sorted(
            arr.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        views = [np.asarray(s.data) for s in got]
        assert all(len(v) == per_shard for v in views), "shard shape drift"
        return views

    kw = 2 if queryname else 1
    dev_of_slot = np.arange(L * rows, dtype=np.int64) // rows
    overflow = -1
    cap = None
    splitters = None
    repartitioned = False
    cap_resolved = False
    repart_info: dict = {}
    with span("mh.key_shuffle", category="stage"):
        while True:
            ds = DistributedSort(
                ctx.mesh,
                rows_per_device=rows,
                capacity_per_pair=cap,
                samples_per_device=samples_per_device,
                key_words=kw,
                splitters=splitters,
            )
            res = (
                ds(
                    gshard(hi_l), gshard(lo_l), gshard(val_l),
                    gshard(org_l), hi2=gshard(hi2_l), lo2=gshard(lo2_l),
                )
                if queryname
                else ds(
                    gshard(hi_l), gshard(lo_l), gshard(val_l),
                    gshard(org_l),
                )
            )
            overflow = int(res.overflow)
            # Post-route census, allgathered so every host sees the same
            # numbers and branches identically: records per destination
            # device (what the skew bound judges) and the largest single
            # (src_dev, dst_dev) bucket (the capacity a retry actually
            # needs — measured, not guessed).
            dest_l = np.concatenate(_local_view(res.dest, rows))
            pair = np.zeros((L, D), dtype=np.int64)
            np.add.at(pair, (dev_of_slot[val_l], dest_l[val_l]), 1)
            stats = np.concatenate([pair.sum(axis=0), [pair.max()]])
            all_stats = ctx.allgather_array(stats)  # [P, D+1]
            per_dev = all_stats[:, :D].sum(axis=0)
            need = int(all_stats[:, D].max())
            mean = float(per_dev.mean())
            ratio = float(per_dev.max()) / mean if mean > 0 else 0.0
            if repartitioned and "ratio_after" not in repart_info:
                repart_info["ratio_after"] = ratio
                METRICS.set_gauge("mh.repartition.ratio_after", ratio)
            skewed = skew_bound > 0 and ratio > skew_bound
            if overflow == 0 and (not skewed or repartitioned):
                # Balanced — or already refreshed once: one repartition
                # per round, the bound is advisory after that.
                break
            if not repartitioned and skew_bound > 0:
                # Rescue #1 — adaptive range repartition: refresh the
                # partitioner from a real key reservoir and re-route.
                # Preferred over a capacity bump because it removes the
                # imbalance instead of buying the skewed cut more room.
                repartitioned = True
                splitters, n_pool = _reservoir_splitters(
                    ctx, keys_local, n_reservoir, D, rng
                )
                repart_info.update(
                    triggered=1, sample_keys=n_pool, ratio_before=ratio
                )
                METRICS.count("mh.repartition.triggered", 1)
                METRICS.count("mh.repartition.sample_keys", n_pool)
                METRICS.set_gauge("mh.repartition.ratio_before", ratio)
                continue
            if overflow > 0 and not cap_resolved:
                # Rescue #2 — one capacity retry, sized exactly from the
                # measured worst bucket so rescues cannot compound.
                cap_resolved = True
                cap = max(16, min(rows, need))
                METRICS.count("mh.shuffle.capacity_retry", 1)
                continue
            if overflow > 0:
                raise RuntimeError(
                    "shuffle overflow persists after repartition and "
                    "the measured-capacity retry"
                )
            break  # skewed but repartition disabled (skew-bound <= 0)
    obs.repartition = repart_info
    METRICS.count("mh.records", n_total)

    # The byte plane labels global rows as pid*L*rows + slot, which is
    # only correct if this process's devices occupy the contiguous mesh
    # range [pid*L, (pid+1)*L).  True for the default jax.devices()
    # ordering; verify rather than assume (a reordered mesh would
    # otherwise silently swap record bytes between processes).
    starts = sorted(
        (s.index[0].start or 0) for s in res.dest.addressable_shards
    )
    expect = [(ctx.process_id * L + k) * rows for k in range(L)]
    if starts != expect:
        raise RuntimeError(
            "process devices are not mesh-contiguous: shard starts "
            f"{starts} != {expect}; build the mesh from jax.devices() "
            "order (parallel.mesh.make_mesh)"
        )

    dest_l = np.concatenate(_local_view(res.dest, rows))
    dest_of_record = dest_l[row_of_record]

    # Key-plane byte accounting: routed rows per destination process ×
    # the sort's per-row key width (``ds.key_row_bytes`` — the six
    # all_to_all columns, eight when the queryname tie-break word rides
    # along).  The sender counts from its own routing table; the
    # receiver-side column comes from the allgathered row-count matrix
    # (both sides route identically by construction — the byte plane
    # below is the independently-measured matrix the balance assert
    # actually bites on).
    key_rows = np.bincount(
        process_of_device(dest_of_record, L), minlength=P_
    ).astype(np.int64)
    key_matrix = ctx.allgather_array(key_rows)  # [P, P] rows sent s->q
    for q in range(P_):
        METRICS.count(
            f"mh.keys.sent.{q}", int(key_rows[q]) * ds.key_row_bytes
        )
    for s in range(P_):
        METRICS.count(
            f"mh.keys.recv.{s}",
            int(key_matrix[s][ctx.process_id]) * ds.key_row_bytes,
        )
    TRACER.counter(
        "mh.keys.sent",
        {
            str(q): float(key_rows[q] * ds.key_row_bytes)
            for q in range(P_)
        },
    )

    # td / shuffle_dir were derived from out_path at function entry (the
    # budget spill path needs them before the shuffle).
    if ctx.process_id == 0:
        os.makedirs(shuffle_dir, exist_ok=True)
    ctx.barrier("mkdirs")
    os.makedirs(shuffle_dir, exist_ok=True)

    if memory_budget is None:
        write_dir = shuffle_dir
        if byte_plane == "http":
            # Network plane: outgoing runs live on LOCAL disk and are
            # served over HTTP; no process ever reads another's disk.
            import tempfile as _tf

            write_dir = _tf.mkdtemp(prefix="hbam_shuf_")
            _stack.callback(nio.delete_recursive, write_dir)
        with span("mh.byte_shuffle.write", category="stage"):
            _write_byte_runs(
                write_dir, ctx, local, dest_of_record, row_of_record,
                rows, compress=compress_shuffle, dstream=dstream,
                member_bytes=member_bytes, level=level,
            )
        if byte_plane == "http":
            sources: List = _start_http_plane(ctx, write_dir, _stack)
        else:
            sources = [shuffle_dir] * ctx.num_processes
        serve_dir = write_dir
        # The input shard is on disk in destination-keyed runs now; release
        # it so fetch-side peak is ~received-shard, not input+received.
        del local, dest_of_record, row_of_record, dest_l
        ctx.barrier("byte_shuffle_written")

        # Receiver: each local device's sorted rows → one part file each
        # (the ExitStack owns server/spill teardown on every outcome).
        # With the speculate factor armed this stage is re-executable: the
        # route sidecars published below are the locators a finished peer
        # needs to re-run a straggler's gather from the byte plane alone.
        speculate = spec_factor > 0.0
        out_counts: List[int] = []
        spec_info: dict = {}
        t_parts0 = time.perf_counter()
        with span("mh.byte_shuffle.fetch", category="stage"):
            cap_rows = res.hi.shape[0] // D
            v_sh = _local_view(res.valid, cap_rows)
            sd_sh = _local_view(res.src_dev, cap_rows)
            sr_sh = _local_view(res.src_row, cap_rows)
            # Which global devices are this process's shards?
            g_devs = sorted(
                (s.index[0].start or 0) // cap_rows
                for s in res.valid.addressable_shards
            )
            if speculate:
                for k, g_dev in enumerate(g_devs):
                    v = v_sh[k]
                    _write_route_sidecar(
                        td, g_dev, sd_sh[k][v], sr_sh[k][v]
                    )
            fetcher = _ByteFetcher(
                sources, ctx, rows, compress=compress_shuffle,
                dstream=dstream, fetch_threads=fetch_threads,
                errors=errors,
            )
            for k, g_dev in enumerate(g_devs):
                # The parts-stage injection point, offset +1000 so one
                # directive grammar drives read-stage and parts-stage
                # drills separately (exec.delay:items=1,attempts=1000-…
                # slows exactly host 1's writes — the speculation drill).
                if _plan is not None:
                    _plan.exec_attempt(ctx.process_id, 1000 + k, _torn)
                v = v_sh[k]
                sd = sd_sh[k][v]
                sr = sr_sh[k][v]
                data, rec_off, rec_len = fetcher.gather(sd, sr)
                # len(rec_off) == len(sd) except in salvage mode, where
                # quarantined members' records were dropped.
                keys = np.zeros(len(rec_off), dtype=np.int64)  # writer-unused
                batch = RecordBatch(
                    soa={"rec_off": rec_off, "rec_len": rec_len},
                    data=data,
                    keys=keys,
                )
                out_counts.append(int(len(rec_off)))
                won, size = _promote_part(
                    td, ctx.process_id, g_dev,
                    lambda f, b=batch: write_part_fast(
                        f, b, order=None, level=level
                    ),
                    first_wins=speculate,
                )
                if not won:
                    # A speculative copy beat this write to the link: the
                    # part on disk is byte-identical (same route, same
                    # writer), this copy is the loser the manifest counts.
                    spec_info["lost_parts"] = (
                        spec_info.get("lost_parts", 0) + 1
                    )
                    spec_info["wasted_bytes"] = (
                        spec_info.get("wasted_bytes", 0) + size
                    )
                    METRICS.count("mh.speculate.wasted_bytes", size)
        if speculate:
            my_dur = time.perf_counter() - t_parts0
            _write_done_marker(td, ctx.process_id, my_dur)
            got = _maybe_speculate(
                ctx, td, sources, rows, compress_shuffle, dstream,
                fetch_threads, errors, spec_factor, my_dur, level,
                RecordBatch, write_part_fast, _plan,
            )
            for k, v in got.items():
                if k == "target":
                    spec_info[k] = v
                else:
                    spec_info[k] = spec_info.get(k, 0) + v
        obs.speculation = spec_info
        ctx.barrier("parts_written")
        cleanup_dir = write_dir if byte_plane == "http" else None
    else:
        if byte_plane == "http":
            sources: List = _start_http_plane(ctx, spill_dir, _stack)
        else:
            sources = [
                os.path.join(shuffle_dir, f"spill-{s:03d}")
                for s in range(ctx.num_processes)
            ]
        serve_dir = spill_dir
        peak_bytes, out_counts = _budget_byte_plane(
            ctx, td, sources, splits, own_counts, dest_of_record,
            level, D, peak_bytes, RecordBatch, write_part_fast,
            compress=compress_shuffle, dstream=dstream,
        )
        cleanup_dir = spill_dir if byte_plane == "http" else None

    # Partition skew: output records per shard (one shard per global
    # device), allgathered so every host derives the same ratio — the
    # number the compressed-payload shuffle rework must not regress.
    oc = np.zeros(L, dtype=np.int64)
    oc[: len(out_counts)] = out_counts
    all_oc = ctx.allgather_array(oc).reshape(-1)  # [D]
    mean_oc = float(all_oc.mean()) if all_oc.size else 0.0
    skew_ratio = float(all_oc.max()) / mean_oc if mean_oc > 0 else 0.0
    METRICS.set_gauge("mh.skew_ratio", skew_ratio)
    # peak_bytes single-sourced through the tracing gauge layer (the
    # standing constraint); LAST_STATS stays as the thin legacy view.
    METRICS.set_gauge("mh.peak_bytes", float(peak_bytes))
    LAST_STATS["peak_bytes"] = peak_bytes

    # Mesh observability: shard + manifest out through the byte plane,
    # host 0 collects — must run before the plane directories go away.
    obs.publish(
        serve_dir, sources, peak_bytes, n_local, out_counts, skew_ratio
    )
    if cleanup_dir is not None:
        # Every process fetched its share (and host 0 its shards): drop
        # the outgoing/local-spill dir now so it does not coexist with
        # the merge on disk (the ExitStack callback stays as the
        # failure-path backstop; delete_recursive is idempotent).
        nio.delete_recursive(cleanup_dir)

    if ctx.process_id == 0:
        with span("mh.merge", category="stage"):
            nio.write_success(td)
            merge_bam_parts(td, out_path, header)
            nio.delete_recursive(td)
    obs.finalize(peak_bytes, n_local, out_counts, skew_ratio)
    ctx.barrier("merged")
    return n_total


def fixmate_bam_multihost(
    in_paths: Sequence[str] | str,
    out_path: str,
    ctx: Optional[MultihostContext] = None,
    conf=None,
    split_size: int = 32 << 20,
    level: int = 6,
    errors: Optional[str] = None,
):
    """Fixmate across every process of the JAX runtime — the collation
    engine's pairing run mesh-wide, output byte-identical to single-host
    :func:`pipeline.fixmate_bam` on the same input.

    Unlike the sort drivers, fixmate preserves record order, so no
    key/byte shuffle runs at all.  What *is* distributed is the pairing
    decision:

    1. every host reads its round-robin splits and collates them by name
       hash (verified against actual name bytes, as always);
    2. the distributed rank pass (:func:`_global_name_rank_pass`)
       allgathers only per-group representative names and gives every
       record a dense global name rank;
    3. per-rank candidate counts are allgathered — a rank with one local
       candidate and two global candidates is a **half-open pair**: the
       mate lives on another host.  Exactly those candidates' mate-facing
       columns (flag/refid/pos/span + the CIGAR blob for MC tags, ~tens
       of bytes each) are exchanged, never whole records;
    4. each host extends its local columns with the remote mates as
       *virtual rows*, wires the mate index across the boundary (pairs
       with >2 global candidates are broken, matching the single-host
       engine on the union), and runs the unchanged vectorized edit pass
       (:func:`collate.compute_fixmate_edits`) — virtual rows get edits
       too, but only local rows are ever applied;
    5. parts are written per owned split in plan order and process 0
       merges under the *input* header (fixmate changes neither order
       nor grouping).

    Returns a :class:`pipeline.FixmateStats` with mesh-global counts
    (identical on every process); straddling pairs are counted once, by
    the host owning the lower-ordinal record."""
    from ..collate import (
        Collation,
        FIXMATE_FIELDS,
        apply_fixmate,
        collate_by_name,
        collation_columns,
        compute_fixmate_edits,
        concat_collation,
        verify_and_repair,
    )
    from ..io.bam import BamInputFormat, read_header, write_part_fast
    from ..io.merger import merge_bam_parts
    from ..pipeline import FixmateStats
    from ..spec.bam import FLAG_PAIRED

    if isinstance(in_paths, str):
        in_paths = [in_paths]
    if ctx is None:
        ctx = initialize()
    if errors is None and conf is not None:
        from ..conf import ERRORS_MODE

        errors = conf.get(ERRORS_MODE)
    fmt = BamInputFormat(conf)
    header = read_header(in_paths[0])  # fixmate: header claims nothing new
    with span("mh.plan", category="stage"):
        splits = fmt.get_splits(in_paths, split_size=split_size)
    mine = ctx.owned(splits)
    P_ = ctx.num_processes
    _plan = faults.ACTIVE
    out_dir_pre = os.path.dirname(os.path.abspath(out_path)) or "."
    _torn = os.path.join(
        out_dir_pre, f"_mh_torn_{ctx.process_id:03d}.tmp"
    )
    read_fields = tuple(dict.fromkeys(FIXMATE_FIELDS))

    batches: List = []
    cols_parts: List[dict] = []
    with span("mh.read", category="stage"):
        for j, s in enumerate(mine):
            if _plan is not None:
                _plan.exec_attempt(ctx.process_id, j, _torn)
            with trace_ctx(split=ctx.process_id + j * P_):
                b = fmt.read_split(
                    s, fields=read_fields, with_keys=False, errors=errors
                )
            cols_parts.append(
                collation_columns(b.data, b.soa, with_cigars=True)
            )
            batches.append(b)
    own_counts = [b.n_records for b in batches]
    row_bases = np.concatenate(
        [[0], np.cumsum(own_counts)]
    ).astype(np.int64)
    n = int(row_bases[-1])

    # Global ordinals (same padded allgather as the sort driver): the
    # deterministic tie-breaker for straddling-pair ownership.
    max_owned = max(1, -(-len(splits) // P_))
    cm = np.zeros(max_owned, dtype=np.int64)
    cm[: len(own_counts)] = own_counts
    M = ctx.allgather_array(cm)
    counts_by_split = np.zeros(max(1, len(splits)), dtype=np.int64)
    for k in range(len(splits)):
        counts_by_split[k] = M[k % P_][k // P_]
    split_base = np.concatenate(
        [[0], np.cumsum(counts_by_split)]
    ).astype(np.int64)
    n_total = int(split_base[len(splits)])
    org_local = (
        np.concatenate(
            [
                split_base[ctx.process_id + j * P_] + np.arange(c)
                for j, c in enumerate(own_counts)
            ]
        ).astype(np.int64)
        if own_counts
        else np.empty(0, np.int64)
    )
    METRICS.count("mh.fixmate.records", n_total)

    with span("mh.rank", category="stage"):
        cols = concat_collation(cols_parts)
        cols_parts = []
        col = collate_by_name(cols)
        col, _ = verify_and_repair(col, cols)
        rk, n_names = _global_name_rank_pass(ctx, cols, col)

    with span("mh.fixmate.pair", category="stage"):
        # Per-rank candidate census: local counts, then the allgathered
        # global view every pairing decision below agrees on.
        cand_mask = cols["cand"] != 0
        local_cand = np.bincount(
            rk[cand_mask], minlength=max(1, n_names)
        ).astype(np.int64)
        global_cand = ctx.allgather_array(local_cand).sum(axis=0)

        # Local pairs survive only if the pair is globally exact (two
        # candidates anywhere) — a third candidate on another host makes
        # the name anomalous, exactly as a third local candidate would.
        mate_loc = col.mate.astype(np.int64).copy()
        lp = np.flatnonzero(mate_loc >= 0)
        if len(lp):
            broken = global_cand[rk[lp]] != 2
            mate_loc[lp[broken]] = -1

        # Half-open pairs: one candidate here, two globally — exchange
        # the mate-facing columns (never whole records).
        half_rows = np.flatnonzero(
            cand_mask & (local_cand[rk] == 1) & (global_cand[rk] == 2)
        )
        n_half = len(half_rows)
        METRICS.count("mh.fixmate.half_open", n_half)
        tab = np.zeros((n_half, 7), np.int64)
        if n_half:
            tab[:, 0] = rk[half_rows]
            tab[:, 1] = org_local[half_rows]
            tab[:, 2] = cols["flag"][half_rows]
            tab[:, 3] = cols["refid"][half_rows]
            tab[:, 4] = cols["pos"][half_rows]
            tab[:, 5] = cols["span"][half_rows]
            tab[:, 6] = cols["n_cig"][half_rows]
        chunks = [
            cols["cigs"][
                int(cols["cig_off"][r]) :
                int(cols["cig_off"][r]) + 4 * int(cols["n_cig"][r])
            ]
            for r in half_rows
        ]
        blob = (
            np.concatenate(chunks) if chunks else np.empty(0, np.uint8)
        )
        sizes = ctx.allgather_array(
            np.array([n_half, len(blob)], np.int64)
        )
        mg = int(sizes[:, 0].max())
        mb = int(sizes[:, 1].max())
        tab_pad = np.zeros((max(1, mg), 7), np.int64)
        tab_pad[:n_half] = tab
        blob_pad = np.zeros(max(1, mb), np.uint8)
        blob_pad[: len(blob)] = blob
        all_tab = ctx.allgather_array(tab_pad)
        all_blob = ctx.allgather_array(blob_pad)

        # Virtual rows: every remote half-open candidate whose rank
        # matches one of ours (global count 2 ⇒ exactly one match).
        rank_to_row = {int(rk[r]): int(r) for r in half_rows}
        v_local: List[int] = []
        v_tab: List[np.ndarray] = []
        v_cig: List[np.ndarray] = []
        straddle_owned = 0
        for p in range(P_):
            if p == ctx.process_id:
                continue
            g = int(sizes[p, 0])
            tp = all_tab[p][:g]
            offs = np.concatenate(
                [[0], np.cumsum(4 * tp[:, 6])]
            ).astype(np.int64)
            for i in range(g):
                r = rank_to_row.get(int(tp[i, 0]))
                if r is None:
                    continue
                v_local.append(r)
                v_tab.append(tp[i])
                v_cig.append(
                    all_blob[p][int(offs[i]) : int(offs[i + 1])]
                )
                if int(org_local[r]) < int(tp[i, 1]):
                    straddle_owned += 1
        n_virt = len(v_local)
        METRICS.count("mh.fixmate.virtual_mates", n_virt)

        vt = (
            np.stack(v_tab)
            if n_virt else np.zeros((0, 7), np.int64)
        )
        v_cig_blob = (
            np.concatenate(v_cig) if v_cig else np.empty(0, np.uint8)
        )
        v_cig_off = (
            np.concatenate([[0], np.cumsum(4 * vt[:, 6])[:-1]])
            if n_virt else np.empty(0, np.int64)
        ).astype(np.int64) + len(cols["cigs"])
        cols_ext = {
            "flag": np.concatenate([cols["flag"], vt[:, 2]]).astype(
                cols["flag"].dtype
            ),
            "refid": np.concatenate([cols["refid"], vt[:, 3]]).astype(
                np.int32
            ),
            "pos": np.concatenate([cols["pos"], vt[:, 4]]).astype(
                np.int32
            ),
            "span": np.concatenate([cols["span"], vt[:, 5]]).astype(
                np.int32
            ),
            "cand": np.concatenate(
                [cols["cand"], np.ones(n_virt, cols["cand"].dtype)]
            ),
            "n_cig": np.concatenate([cols["n_cig"], vt[:, 6]]).astype(
                np.int32
            ),
            "cig_off": np.concatenate(
                [cols["cig_off"], v_cig_off]
            ).astype(np.int64),
            "cigs": np.concatenate([cols["cigs"], v_cig_blob]),
        }
        mate_ext = np.concatenate(
            [mate_loc, np.full(n_virt, -1, np.int64)]
        ).astype(np.int32)
        for k, r in enumerate(v_local):
            mate_ext[r] = n + k
            mate_ext[n + k] = r
        n_ext = n + n_virt
        col_ext = Collation(
            order=np.arange(n_ext, dtype=np.int64),
            group=np.zeros(n_ext, np.int32),
            n_groups=0,
            mate=mate_ext,
            n_pairs=int((mate_ext >= 0).sum()) // 2,
        )

    with span("mh.fixmate.edits", category="stage"):
        edits = compute_fixmate_edits(cols_ext, col_ext)

    # Mesh-global stats (identical everywhere): straddling pairs counted
    # by the lower-ordinal owner; singletons/orphans are host-local facts.
    own_pairs = int((mate_loc >= 0).sum()) // 2 + straddle_owned
    singles = int(((cols["flag"] & FLAG_PAIRED) == 0).sum())
    orphans = int((cand_mask & (mate_ext[:n] < 0)).sum())
    totals = ctx.allgather_array(
        np.array([own_pairs, singles, orphans], np.int64)
    ).sum(axis=0)

    td = os.path.join(
        out_dir_pre, f"_mh_{os.path.basename(out_path)}.parts"
    )
    if ctx.process_id == 0:
        os.makedirs(td, exist_ok=True)
    ctx.barrier("fixmate_mkdirs")
    os.makedirs(td, exist_ok=True)
    with span("mh.fixmate.write", category="stage"):
        for j, b in enumerate(batches):
            gsi = ctx.process_id + j * P_
            patched = apply_fixmate(b, edits, int(row_bases[j]))
            tmp = os.path.join(td, f"_temporary.part-r-{gsi:05d}")
            with open(tmp, "wb") as f:
                write_part_fast(f, patched, order=None, level=level)
            os.replace(tmp, os.path.join(td, f"part-r-{gsi:05d}"))
    ctx.barrier("fixmate_parts_written")
    if ctx.process_id == 0:
        with span("mh.merge", category="stage"):
            nio.write_success(td)
            merge_bam_parts(td, out_path, header)
            nio.delete_recursive(td)
    ctx.barrier("fixmate_merged")
    return FixmateStats(
        n_records=n_total,
        n_splits=len(splits),
        n_pairs=int(totals[0]),
        n_singletons=int(totals[1]),
        n_orphans=int(totals[2]),
        backend="collate-fixmate[mesh]",
    )
