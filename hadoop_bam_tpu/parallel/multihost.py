"""Multi-host orchestration: one process per host, SPMD over the global mesh.

The reference's scale-out runtime is the Hadoop cluster — one mapper per
split on whatever host owns it, record bytes moving through the MapReduce
shuffle's spill/fetch data plane (pom.xml:296-300 hadoop-client;
BAMInputFormat.java:216-260 assigns splits, SURVEY §2.7 the shuffle).  The
TPU-native equivalent here:

- **control plane**: ``jax.distributed.initialize`` (one process per host)
  — the global device mesh spans every process; split planning is
  deterministic, so every process plans identically and takes ownership of
  ``split_idx % num_processes == process_id`` (no coordinator needed).
- **key plane**: the existing range-partitioned ``all_to_all`` shuffle sort
  (parallel/shuffle.py) runs unchanged over the *global* mesh — XLA routes
  the collective over ICI within a host and DCN across hosts.  The shuffle
  additionally returns each input row's destination device (the sender-side
  routing table).
- **byte plane**: ragged record payloads move host-to-host either through
  spill files on a shared filesystem (the GCS-backed-shuffle stance) or —
  with ``byte_plane="http"`` — over authenticated HTTP range fetches from
  each process's LOCAL disk (Hadoop's map-output servlet + parallel
  copier, no shared filesystem in the data path): each process writes one
  run of raw records per destination process, sorted by global source row
  with a memmappable row/offset sidecar; after a global barrier every
  process fetches and gathers exactly the bytes its devices' key ranges
  own.  Both planes compose with ``memory_budget`` (key-sorted spill
  runs, contiguous per-destination slices, receiver-side (key, ordinal)
  range merge).

``sort_bam_multihost`` is the end-to-end driver: it produces a part file
per *global device* and process 0 performs the ordinary header+parts+
terminator merge, so the output is byte-identical to the single-process
sort of the same input.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax

from .. import native
from ..utils import nio
from ..utils.tracing import METRICS, span
from .mesh import DATA_AXIS, make_mesh
from .shuffle import DistributedSort


@dataclass
class MultihostContext:
    """Process identity + the global mesh."""

    process_id: int
    num_processes: int
    mesh: "jax.sharding.Mesh"

    @property
    def local_device_count(self) -> int:
        return jax.local_device_count()

    @property
    def global_device_count(self) -> int:
        return self.mesh.devices.size

    def owned(self, items: Sequence) -> List:
        """Round-robin ownership — deterministic, planner-free
        (every process computes the same global plan)."""
        return [
            it
            for k, it in enumerate(items)
            if k % self.num_processes == self.process_id
        ]

    def barrier(self, name: str) -> None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)

    def allgather_counts(self, n: int) -> np.ndarray:
        """[num_processes] int64 — one scalar contributed per process."""
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(np.int64(n))
        ).reshape(-1)

    def allgather_array(self, a: np.ndarray) -> np.ndarray:
        """[num_processes, *a.shape] — same-shape array from every process."""
        from jax.experimental import multihost_utils

        out = np.asarray(multihost_utils.process_allgather(a))
        return out.reshape((self.num_processes,) + a.shape)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> MultihostContext:
    """Join (or create) the multi-process JAX runtime and build the global
    1-D data mesh.

    With no arguments in a single-process setting this degrades to a local
    mesh over the visible devices — the same code path runs on one host or
    sixteen.  On CPU the cross-process collectives use the gloo transport;
    on TPU pods the PJRT plugin provides ICI/DCN natively.
    """
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return MultihostContext(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        mesh=make_mesh(),
    )


#: Debug/observability: per-process stats of the last sort_bam_multihost
#: call (budget mode records its accounted peak of materialized record
#: bytes here; tests assert against it).
LAST_STATS: dict = {}


# ---------------------------------------------------------------------------
# The byte plane: shared-filesystem record shuffle.
# ---------------------------------------------------------------------------


def _bytes_name(src: int, dst: int) -> str:
    return f"shufbytes-s{src:03d}-d{dst:03d}"


def _bytes_file(d: str, src: int, dst: int) -> str:
    return os.path.join(d, _bytes_name(src, dst))


def _serve_dir(directory: str, token: str):
    """Serve ``directory`` read-only over HTTP with Range support.

    The network byte plane's data server — the role of Hadoop's
    map-output HTTP servlet in the shuffle fetch phase (SURVEY §2.7):
    each process serves its outgoing spill files from local disk and
    receivers pull exactly their share, so the byte plane needs no
    shared filesystem.  ``token`` is this job's fetch credential (the
    moral equivalent of Hadoop's shuffle job token): every request must
    carry it in ``X-Hbam-Token`` or gets 403 — the per-process tokens
    travel only over the job's own allgather channel.  Returns
    ``(server, base_url)``; the caller owns shutdown."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    root = os.path.abspath(directory)

    import hmac

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _path(self):
            got = self.headers.get("X-Hbam-Token") or ""
            if not hmac.compare_digest(got, token):
                self.send_error(403)
                return None
            # One flat directory; reject anything path-like.
            name = self.path.lstrip("/")
            if "/" in name or ".." in name or not name:
                self.send_error(404)
                return None
            p = os.path.join(root, name)
            if not os.path.isfile(p):
                self.send_error(404)
                return None
            return p

        def do_HEAD(self):
            p = self._path()
            if p is None:
                return
            self.send_response(200)
            self.send_header("Content-Length", str(os.path.getsize(p)))
            self.send_header("Accept-Ranges", "bytes")
            self.end_headers()

        def do_GET(self):
            p = self._path()
            if p is None:
                return
            size = os.path.getsize(p)
            rng = self.headers.get("Range")
            lo, hi = 0, size - 1
            status = 200
            if rng:
                try:
                    a, b = rng.split("=")[1].split("-")
                    if a == "":  # RFC suffix form: last N bytes
                        n_suffix = int(b)
                        lo = max(0, size - n_suffix)
                    else:
                        lo = int(a)
                        hi = min(int(b) if b else size - 1, size - 1)
                except ValueError:
                    self.send_error(400)
                    return
                if lo >= size or hi < lo:
                    self.send_error(416)
                    return
                status = 206
            n = hi - lo + 1
            self.send_response(status)
            if status == 206:
                self.send_header(
                    "Content-Range", f"bytes {lo}-{hi}/{size}"
                )
            self.send_header("Content-Length", str(n))
            self.end_headers()
            with open(p, "rb") as f:
                f.seek(lo)
                remaining = n
                while remaining > 0:
                    chunk = f.read(min(1 << 20, remaining))
                    if not chunk:
                        break
                    self.wfile.write(chunk)
                    remaining -= len(chunk)

    # Peers must reach this address: the hostname by default (resolvable
    # on real clusters), HBAM_SHUFFLE_HOST to override (tests pin
    # 127.0.0.1; multi-NIC hosts pin the data-plane address).  When an
    # address is pinned, LISTEN on it too — spill bytes must not be
    # reachable on interfaces the operator pinned away from.
    import socket

    pinned = os.environ.get("HBAM_SHUFFLE_HOST")
    srv = ThreadingHTTPServer((pinned or "0.0.0.0", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    host = pinned or socket.gethostname()
    return srv, f"http://{host}:{srv.server_address[1]}"


_ENDPOINT_REC = 512  # fits http:// + 253-char FQDN + port + 32-hex token


def _publish_endpoints(
    ctx: MultihostContext, url: str, token: str
) -> List[Tuple[str, str]]:
    """Allgather each process's (URL, fetch token), fixed-width UTF-8.

    The allgather also doubles as the 'server is up' barrier — no
    receiver can hold a peer's endpoint before that peer published it."""
    rec = f"{url} {token}".encode()
    buf = np.zeros(_ENDPOINT_REC, dtype=np.uint8)
    if len(rec) > _ENDPOINT_REC:
        raise ValueError(f"shuffle endpoint too long: {rec!r}")
    buf[: len(rec)] = np.frombuffer(rec, np.uint8)
    allb = ctx.allgather_array(buf)  # [P, _ENDPOINT_REC]
    out = []
    for p in range(len(allb)):
        u, t = bytes(allb[p]).rstrip(b"\x00").decode().split(" ", 1)
        out.append((u, t))
    return out


def _start_http_plane(ctx: MultihostContext, serve_dir: str, stack):
    """Start the data server over ``serve_dir``, publish the endpoint,
    and return the per-source locator list (own files stay local).

    Server teardown (shutdown + socket close) is registered on ``stack``
    (a ``contextlib.ExitStack`` owned by the driver), so every failure
    path from this moment on closes the data port; the serve directory
    itself belongs to its creator."""
    import secrets

    token = secrets.token_hex(16)
    srv, url = _serve_dir(serve_dir, token)
    stack.callback(srv.server_close)
    stack.callback(srv.shutdown)
    sources: List = list(_publish_endpoints(ctx, url, token))
    sources[ctx.process_id] = serve_dir  # no socket hop for own files
    return sources


def _write_byte_runs(
    shuffle_dir: str,
    ctx: MultihostContext,
    batch,
    dest_dev: np.ndarray,
    row_of_record: np.ndarray,
    rows_per_device: int,
) -> None:
    """Ship this process's records to their destination processes.

    One file per destination process, containing raw records (size word +
    body) ascending by *global source row*, plus ``.rows``/``.offs``
    sidecars so receivers can binary-search any (src_dev, src_row)
    reference the key shuffle hands them.
    """
    L = ctx.local_device_count
    first_global_dev = ctx.process_id * L
    # Global row id of each local record (row_of_record is the local slot).
    g_row = (
        (first_global_dev + row_of_record // rows_per_device).astype(np.int64)
        * rows_per_device
        + (row_of_record % rows_per_device).astype(np.int64)
    )
    dest_proc = dest_dev // L
    lens = batch.soa["rec_len"].astype(np.int64) + 4
    for q in range(ctx.num_processes):
        sel = np.nonzero(dest_proc == q)[0]
        order = sel[np.argsort(g_row[sel], kind="stable")]
        stream = native.gather_records(
            batch.data,
            batch.soa["rec_off"],
            batch.soa["rec_len"],
            order,
        )
        offs = np.empty(len(order) + 1, dtype=np.int64)
        offs[0] = 0
        np.cumsum(lens[order], out=offs[1:])
        base = _bytes_file(shuffle_dir, ctx.process_id, q)
        for path, payload, rawbytes in (
            (base + ".bin", stream, True),
            (base + ".rows", g_row[order], False),
            (base + ".offs", offs, False),
        ):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                if rawbytes:
                    f.write(memoryview(payload))  # no tobytes() copy
                else:
                    np.save(f, payload)
            os.replace(tmp, path)


class _ByteFetcher:
    """Receiver side: resolve (src_dev, src_row) → record bytes across the
    per-source spill files addressed to this process.

    ``sources`` locates each process's outgoing files: a filesystem
    directory (shared-FS plane, and the local fast path for a process's
    own files) or an ``(http_base, token)`` endpoint (network plane —
    the Hadoop shuffle's HTTP fetch, authenticated by the job's fetch
    token)."""

    def __init__(self, sources: List, ctx: MultihostContext,
                 rows_per_device: int):
        import io as _io
        from concurrent.futures import ThreadPoolExecutor

        from ..io.fs import HttpFilesystem

        self.rows = rows_per_device
        self.ctx = ctx

        def fetch_one(s: int):
            name = _bytes_name(s, ctx.process_id)
            if isinstance(sources[s], tuple):
                url, token = sources[s]
                f = HttpFilesystem(headers={"X-Hbam-Token": token})
                base = url.rstrip("/")
                return (
                    np.frombuffer(
                        f.read_all(f"{base}/{name}.bin"), dtype=np.uint8
                    ),
                    np.load(_io.BytesIO(f.read_all(f"{base}/{name}.rows"))),
                    np.load(_io.BytesIO(f.read_all(f"{base}/{name}.offs"))),
                )
            p = os.path.join(sources[s], name)
            with open(p + ".bin", "rb") as fh:
                buf = np.frombuffer(fh.read(), dtype=np.uint8)
            return buf, np.load(p + ".rows"), np.load(p + ".offs")

        # Pull peers concurrently (Hadoop's parallel copier): the fetch
        # phase is network-bound, not peer-count-bound.
        P_ = ctx.num_processes
        with ThreadPoolExecutor(max_workers=min(8, P_)) as pool:
            got = list(pool.map(fetch_one, range(P_)))
        bufs = [g[0] for g in got]
        self.rows_tab = [g[1] for g in got]
        self.offs_tab = [g[2] for g in got]
        # One concatenated buffer built once (gather() runs per local
        # device; re-concatenating there would copy the whole received
        # shard L times).
        self.base = np.zeros(ctx.num_processes + 1, dtype=np.int64)
        np.cumsum([len(b) for b in bufs], out=self.base[1:])
        self.big = (
            np.concatenate(bufs) if bufs else np.empty(0, np.uint8)
        )
        del bufs

    def gather(self, src_dev: np.ndarray, src_row: np.ndarray):
        """Concatenated raw records for the given (src_dev, src_row) refs,
        in the given order.  Returns (data uint8, rec_off, rec_len).

        Buffers are concatenated once and the ragged copy is a single
        ``native.gather_records`` call — no per-record Python loop.
        """
        L = self.ctx.local_device_count
        g = src_dev.astype(np.int64) * self.rows + src_row.astype(np.int64)
        src_proc = src_dev // L
        n = len(g)
        out_len = np.zeros(n, dtype=np.int64)
        src_off = np.zeros(n, dtype=np.int64)
        for s in range(self.ctx.num_processes):
            m = src_proc == s
            if not m.any():
                continue
            idx = np.searchsorted(self.rows_tab[s], g[m])
            if np.any(idx >= len(self.rows_tab[s])) or np.any(
                self.rows_tab[s][idx] != g[m]
            ):
                raise RuntimeError(
                    f"byte shuffle missing rows from process {s}"
                )
            src_off[m] = self.offs_tab[s][idx] + self.base[s]
            out_len[m] = self.offs_tab[s][idx + 1] - self.offs_tab[s][idx]
        data = native.gather_records(
            self.big, src_off + 4, out_len - 4, order=None
        )
        out_off = np.empty(n + 1, dtype=np.int64)
        out_off[0] = 0
        np.cumsum(out_len, out=out_off[1:])
        return data, out_off[:-1] + 4, out_len - 4


class _RemoteNpy:
    """Range-read slices of a remote int64 ``.npy`` sideband.

    The local plane memmaps sidecars (O(log n) pages touched); the
    network plane must match that footprint or it silently defeats the
    memory budget, so only the header (to locate the data) and the
    requested element ranges ever cross the wire."""

    def __init__(self, fs, url: str):
        self._fs = fs
        self._url = url
        head = fs.read_range(url, 0, 128)
        if head[:6] != b"\x93NUMPY":
            raise IOError(f"not an npy file: {url}")
        major = head[6]
        if major == 1:
            hlen = int.from_bytes(head[8:10], "little")
            self._data0 = 10 + hlen
            hdr = head[10 : 10 + hlen]
        else:
            hlen = int.from_bytes(head[8:12], "little")
            self._data0 = 12 + hlen
            hdr = head[12 : 12 + hlen]
        if len(hdr) < hlen:
            hdr = fs.read_range(url, self._data0 - hlen, hlen)
        text = hdr.decode("latin-1")
        if "'<i8'" not in text or "'fortran_order': False" not in text:
            raise IOError(f"unexpected npy layout for ranged reads: {url}")

    def slice(self, i0: int, i1: int) -> np.ndarray:
        n = i1 - i0
        if n <= 0:
            return np.empty(0, np.int64)
        raw = self._fs.read_range(self._url, self._data0 + 8 * i0, 8 * n)
        if len(raw) != 8 * n:
            raise IOError(f"short sideband read from {self._url}")
        return np.frombuffer(raw, dtype="<i8")


class _RunAccess:
    """Uniform access to one process's spill runs for the budget plane:
    a local directory (shared-FS plane / own files, memmapped sidecars)
    or an ``(http_base, token)`` endpoint (network plane, ranged reads).
    Per-run handles are cached; bulk data never is."""

    def __init__(self, source):
        self._source = source
        self._cache: dict = {}

    def _handles(self, j: int):
        got = self._cache.get(j)
        if got is not None:
            return got
        from ..io import runs as runs_mod

        if isinstance(self._source, tuple):
            from ..io.fs import HttpFilesystem

            url, token = self._source
            f = HttpFilesystem(headers={"X-Hbam-Token": token})
            stem = f"{url.rstrip('/')}/run-{j:05d}"
            got = (
                _RemoteNpy(f, stem + runs_mod.RUN_KEYS_EXT),
                _RemoteNpy(f, stem + runs_mod.RUN_OFFS_EXT),
                _RemoteNpy(f, stem + ".org.npy"),
                (f, stem + runs_mod.RUN_DATA_EXT),
            )
        else:
            run = runs_mod.Run.open(self._source, j)
            org = np.load(
                os.path.join(self._source, f"run-{j:05d}.org.npy"),
                mmap_mode="r",
            )
            got = (run.keys, run.offs, org, run.data_path)
        self._cache[j] = got
        return got

    @staticmethod
    def _sl(arr, i0: int, i1: int) -> np.ndarray:
        if isinstance(arr, _RemoteNpy):
            return arr.slice(i0, i1)
        return np.asarray(arr[i0:i1], dtype=np.int64)

    def slices(self, j: int, i0: int, i1: int):
        """(keys[i0:i1], org[i0:i1], lens, byte_start, byte_len)."""
        keys, offs, org, _ = self._handles(j)
        o = self._sl(offs, i0, i1 + 1)
        return (
            self._sl(keys, i0, i1),
            self._sl(org, i0, i1),
            np.diff(o),
            int(o[0]),
            int(o[-1] - o[0]),
        )

    def read_into(self, j: int, view, byte_start: int, size: int) -> None:
        _, _, _, loc = self._handles(j)
        if isinstance(loc, tuple):
            f, url = loc
            data = f.read_range(url, byte_start, size)
            if len(data) != size:
                raise IOError(f"short HTTP read from {url}")
            view[:] = np.frombuffer(data, np.uint8)
        else:
            with open(loc, "rb") as fh:
                fh.seek(byte_start)
                got = fh.readinto(memoryview(view))
            if got != size:
                raise IOError(f"short read from spill run {loc}")


def _budget_byte_plane(
    ctx: MultihostContext,
    td: str,
    sources: List,
    splits,
    own_counts: List[int],
    dest_of_record: np.ndarray,
    level: int,
    D: int,
    peak_bytes: int,
    RecordBatch,
    write_part_fast,
) -> int:
    """Out-of-core byte plane: the key-sorted spill runs ARE the shuffle.

    The shuffle's destination is a monotone function of the key, so each
    run's share of destination device ``g`` is one contiguous slice; a
    [runs, D+1] cut table per process (allgathered — a few KB) tells every
    receiver exactly which slice of which run it owns.  Receivers merge
    their slices by (key, ordinal) one destination device at a time —
    straight off the shared filesystem, or over authenticated HTTP range
    reads when the runs live on peers' local disks (``sources`` carries a
    directory or endpoint per process) — so peak materialized bytes is
    one device's output, not the received shard."""
    P_ = ctx.num_processes
    L = ctx.local_device_count
    n_runs_of = [
        sum(1 for k in range(len(splits)) if k % P_ == s)
        for s in range(P_)
    ]
    max_runs = max(1, max(n_runs_of))
    cuts = np.zeros((max_runs, D + 1), dtype=np.int64)
    rbase = 0
    for j, c in enumerate(own_counts):
        dr = dest_of_record[rbase : rbase + c]
        cuts[j] = np.searchsorted(dr, np.arange(D + 1), side="left")
        rbase += c
    cuts_all = ctx.allgather_array(cuts)  # [P, max_runs, D+1]
    ctx.barrier("spill_published")

    access = [_RunAccess(src) for src in sources]
    with span("mh.range_merge"):
        for g in range(ctx.process_id * L, (ctx.process_id + 1) * L):
            # Two passes over this device's slices: size everything, then
            # read each slice DIRECTLY into its place in one final buffer
            # (no per-slice temporaries coexisting with the concatenation).
            slices = []  # (source idx, run idx, byte_start, byte_len)
            key_parts: List[np.ndarray] = []
            org_parts: List[np.ndarray] = []
            len_parts: List[np.ndarray] = []
            for s in range(P_):
                for j in range(n_runs_of[s]):
                    i0 = int(cuts_all[s][j][g])
                    i1 = int(cuts_all[s][j][g + 1])
                    if i1 <= i0:
                        continue
                    keys_s, org_s, lens_s, b0, sz = access[s].slices(
                        j, i0, i1
                    )
                    slices.append((s, j, b0, sz))
                    key_parts.append(keys_s)
                    org_parts.append(org_s)
                    len_parts.append(lens_s)
            if slices:
                total = sum(sz for _, _, _, sz in slices)
                data = np.empty(total, dtype=np.uint8)
                pos = 0
                for s, j, b0, sz in slices:
                    access[s].read_into(j, data[pos : pos + sz], b0, sz)
                    pos += sz
                lens = np.concatenate(len_parts)
                keys_all = np.concatenate(key_parts)
                org_all = np.concatenate(org_parts)
                off = np.empty(len(lens) + 1, dtype=np.int64)
                off[0] = 0
                np.cumsum(lens, out=off[1:])
                perm = np.lexsort((org_all, keys_all))
                # write_part_fast gathers a permuted copy while ``data`` is
                # still alive: the honest materialized peak is ~2x the
                # device's payload.
                peak_bytes = max(peak_bytes, 2 * int(len(data)))
                batch = RecordBatch(
                    soa={
                        "rec_off": off[:-1] + 4,
                        "rec_len": lens - 4,
                    },
                    data=data,
                    keys=keys_all,
                )
            else:
                perm = None
                batch = RecordBatch(
                    soa={
                        "rec_off": np.empty(0, np.int64),
                        "rec_len": np.empty(0, np.int64),
                    },
                    data=np.empty(0, np.uint8),
                    keys=np.empty(0, np.int64),
                )
            tmp = os.path.join(td, f"_temporary.part-r-{g:05d}")
            with open(tmp, "wb") as f:
                write_part_fast(f, batch, order=perm, level=level)
            os.replace(tmp, os.path.join(td, f"part-r-{g:05d}"))
            del batch
    ctx.barrier("parts_written")
    return peak_bytes


# ---------------------------------------------------------------------------
# End-to-end multi-host coordinate sort.
# ---------------------------------------------------------------------------


def sort_bam_multihost(
    in_paths: Sequence[str] | str,
    out_path: str,
    ctx: Optional[MultihostContext] = None,
    conf=None,
    split_size: int = 32 << 20,
    level: int = 6,
    samples_per_device: int = 64,
    memory_budget: Optional[int] = None,
    byte_plane: str = "fs",
) -> int:
    """Coordinate-sort BAM(s) across every process of the JAX runtime
    (full docs on the implementation below; resources — shuffle data
    servers, local spill directories — are owned by an ExitStack so every
    failure path tears them down)."""
    import contextlib

    with contextlib.ExitStack() as stack:
        return _sort_bam_multihost_impl(
            in_paths, out_path, ctx, conf, split_size, level,
            samples_per_device, memory_budget, byte_plane, stack,
        )


def _sort_bam_multihost_impl(
    in_paths,
    out_path: str,
    ctx: Optional[MultihostContext],
    conf,
    split_size: int,
    level: int,
    samples_per_device: int,
    memory_budget: Optional[int],
    byte_plane: str,
    _stack,
) -> int:
    """Coordinate-sort BAM(s) across every process of the JAX runtime.

    All paths (input, output, and the shuffle directory derived from the
    output path) must be on a filesystem visible to every process — the
    same contract HDFS gives the reference.  Returns the global record
    count (identical on every process); the merged output is written by
    process 0.

    ``byte_plane`` selects how record bytes move between processes:
    ``"fs"`` (spill files on a filesystem every process can read — the
    HDFS-backed stance) or ``"http"`` (each process writes its outgoing
    runs to *local* disk and serves them over HTTP; receivers pull their
    share through the io.fs seam — Hadoop's map-output fetch, no shared
    filesystem needed for the data plane).  The output/part directory
    still needs to be reachable by process 0 for the merge.

    ``memory_budget`` (bytes of uncompressed record stream, per process)
    composes the out-of-core sort with the multi-host shuffle (VERDICT r3
    #6 — Hadoop's sort-spill-merge shuffle, SURVEY §2.7): each process
    spills its splits as key-sorted runs at read time and only the
    key/ordinal columns stay resident; the runs then ARE the byte plane —
    the shuffle's destination is monotone in the key, so each
    destination device's share of every run is one contiguous slice,
    published in a tiny allgathered cut table and merged receiver-side by
    (key, ordinal) straight off the shared filesystem.  Peak materialized
    record bytes per process ≈ max(one split, one device's output part);
    the key plane (~13 bytes/record) is accounted separately as in the
    single-host external sort.
    """
    from ..io.bam import BamInputFormat, read_header, write_part_fast
    from ..io.merger import merge_bam_parts
    from ..io import runs as runs_mod
    from ..ops.keys import split_keys_np
    from ..pipeline import RecordBatch, _concat_batches
    from jax.sharding import NamedSharding, PartitionSpec as P

    if isinstance(in_paths, str):
        in_paths = [in_paths]
    if ctx is None:
        ctx = initialize()
    if byte_plane not in ("fs", "http"):
        raise ValueError(f"byte_plane must be 'fs' or 'http': {byte_plane!r}")
    if memory_budget is not None:
        # A split inflates as one batch: keep it well under the budget
        # (same clamp rule as the single-host external sort).
        split_size = max(64 << 10, min(split_size, memory_budget // 16))
    fmt = BamInputFormat(conf)
    header = read_header(in_paths[0]).with_sort_order("coordinate")
    with span("mh.plan"):
        splits = fmt.get_splits(in_paths, split_size=split_size)
    mine = ctx.owned(splits)

    out_dir_pre = os.path.dirname(os.path.abspath(out_path)) or "."
    td = os.path.join(
        out_dir_pre, f"_mh_{os.path.basename(out_path)}.parts"
    )
    shuffle_dir = os.path.join(td, "shuffle")
    spill_dir = os.path.join(shuffle_dir, f"spill-{ctx.process_id:03d}")
    if memory_budget is not None:
        if byte_plane == "http":
            # Network plane: spill runs live on LOCAL disk and are served
            # over HTTP; the shared directory is never written.  The
            # ExitStack owns the directory: any failure from here on
            # removes the spilled shard.
            import tempfile as _tf

            spill_dir = _tf.mkdtemp(prefix="hbam_spill_")
            _stack.callback(nio.delete_recursive, spill_dir)
        else:
            os.makedirs(spill_dir, exist_ok=True)

    peak_bytes = 0
    if memory_budget is None:
        with span("mh.read"):
            batches = [fmt.read_split(s) for s in mine]
            own_counts = [b.n_records for b in batches]
            local = _concat_batches(batches)
            del batches
        n_local = local.n_records
    else:
        # Budget mode: spill each split as a key-sorted run immediately;
        # only the sorted key/ordinal columns stay resident.
        local = None
        own_counts = []
        key_cols: List[np.ndarray] = []
        perm_cols: List[np.ndarray] = []  # per run: the sort permutation
        with span("mh.read_spill"):
            for ri, s in enumerate(mine):
                b = fmt.read_split(s)
                peak_bytes = max(peak_bytes, int(len(b.data)))
                perm = np.argsort(b.keys, kind="stable")
                runs_mod.write_run(spill_dir, ri, b, perm)
                key_cols.append(np.ascontiguousarray(b.keys[perm]))
                perm_cols.append(perm.astype(np.int64))
                own_counts.append(b.n_records)
                del b
        n_local = int(sum(own_counts))

    # Global record ordinals: allgather per-split record counts (padded to
    # the round-robin width) so every process derives the same exclusive
    # scan over splits in plan order.  Ordinals are the shuffle's
    # tie-breaker — output tie order matches the single-process stable
    # sort's exactly.
    P_ = ctx.num_processes
    max_owned = max(1, -(-len(splits) // P_))
    cm = np.zeros(max_owned, dtype=np.int64)
    cm[: len(own_counts)] = own_counts
    M = ctx.allgather_array(cm)  # [P, max_owned]
    counts_by_split = np.zeros(max(1, len(splits)), dtype=np.int64)
    for k in range(len(splits)):
        counts_by_split[k] = M[k % P_][k // P_]
    split_base = np.concatenate(
        [[0], np.cumsum(counts_by_split)]
    ).astype(np.int64)
    n_total = int(split_base[len(splits)])
    if n_total >= (1 << 31):
        raise ValueError(
            "record ordinals exceed int32; shard the input further"
        )
    if memory_budget is None:
        orig_local = (
            np.concatenate(
                [
                    split_base[ctx.process_id + j * P_] + np.arange(c)
                    for j, c in enumerate(own_counts)
                ]
            ).astype(np.int32)
            if own_counts
            else np.empty(0, np.int32)
        )
        keys_local = local.keys
    else:
        # Run r is split-ordinal-base + its sort permutation (the run is
        # the split's records in key order, so ordinal = base + perm).
        org_cols = [
            (split_base[ctx.process_id + j * P_] + perm_cols[j]).astype(
                np.int64
            )
            for j in range(len(own_counts))
        ]
        orig_local = (
            np.concatenate(org_cols).astype(np.int32)
            if org_cols
            else np.empty(0, np.int32)
        )
        keys_local = (
            np.concatenate(key_cols)
            if key_cols
            else np.empty(0, np.int64)
        )
        # Publish per-run ordinal sidecars for the receiver-side merge.
        for j, oc in enumerate(org_cols):
            tmp = os.path.join(spill_dir, f"run-{j:05d}.org.npy.tmp")
            with open(tmp, "wb") as f:
                np.save(f, oc)
            os.replace(tmp, tmp[: -len(".tmp")])
        del perm_cols, key_cols, org_cols

    counts = M.sum(axis=1)
    L = ctx.local_device_count
    D = ctx.global_device_count
    rows = max(1, -(-int(counts.max()) // L))

    # Place local records into local device slots.  A deterministic
    # per-process permutation spreads any key-ordered input across slots so
    # no (src,dst) capacity bucket is hit by a monotone run.
    rng = np.random.default_rng(0x5EED + ctx.process_id)
    slots = rng.permutation(L * rows)[:n_local]
    hi_l = np.full(L * rows, 0x7FFFFFFF, np.int32)
    lo_l = np.full(L * rows, 0xFFFFFFFF, np.uint32)
    val_l = np.zeros(L * rows, dtype=bool)
    org_l = np.full(L * rows, 0x7FFFFFFF, np.int32)
    k_hi, k_lo = split_keys_np(keys_local)
    hi_l[slots] = k_hi
    lo_l[slots] = k_lo
    val_l[slots] = True
    org_l[slots] = orig_local
    # record index -> its local slot (for the byte plane)
    row_of_record = slots.astype(np.int64)

    sharding = NamedSharding(ctx.mesh, P(DATA_AXIS))

    def gshard(arr):
        return jax.make_array_from_process_local_data(
            sharding, arr, (D * rows,) + arr.shape[1:]
        )

    overflow = -1
    cap = None
    with span("mh.key_shuffle"):
        while True:
            ds = DistributedSort(
                ctx.mesh,
                rows_per_device=rows,
                capacity_per_pair=cap,
                samples_per_device=samples_per_device,
            )
            res = ds(
                gshard(hi_l), gshard(lo_l), gshard(val_l), gshard(org_l)
            )
            overflow = int(res.overflow)
            if overflow == 0:
                break
            if cap == rows:
                raise RuntimeError(
                    "shuffle overflow even at full capacity"
                )
            cap = min(rows, ds.capacity * 2)
    METRICS.count("mh.records", n_total)

    # Sender-side routing table: destination device of each local record.
    # Addressable-shard order is not guaranteed — order by global offset.
    def _local_view(arr, per_shard: int) -> List[np.ndarray]:
        got = sorted(
            arr.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        views = [np.asarray(s.data) for s in got]
        assert all(len(v) == per_shard for v in views), "shard shape drift"
        return views

    # The byte plane labels global rows as pid*L*rows + slot, which is
    # only correct if this process's devices occupy the contiguous mesh
    # range [pid*L, (pid+1)*L).  True for the default jax.devices()
    # ordering; verify rather than assume (a reordered mesh would
    # otherwise silently swap record bytes between processes).
    starts = sorted(
        (s.index[0].start or 0) for s in res.dest.addressable_shards
    )
    expect = [(ctx.process_id * L + k) * rows for k in range(L)]
    if starts != expect:
        raise RuntimeError(
            "process devices are not mesh-contiguous: shard starts "
            f"{starts} != {expect}; build the mesh from jax.devices() "
            "order (parallel.mesh.make_mesh)"
        )

    dest_l = np.concatenate(_local_view(res.dest, rows))
    dest_of_record = dest_l[row_of_record]

    # td / shuffle_dir were derived from out_path at function entry (the
    # budget spill path needs them before the shuffle).
    if ctx.process_id == 0:
        os.makedirs(shuffle_dir, exist_ok=True)
    ctx.barrier("mkdirs")
    os.makedirs(shuffle_dir, exist_ok=True)

    if memory_budget is None:
        write_dir = shuffle_dir
        if byte_plane == "http":
            # Network plane: outgoing runs live on LOCAL disk and are
            # served over HTTP; no process ever reads another's disk.
            import tempfile as _tf

            write_dir = _tf.mkdtemp(prefix="hbam_shuf_")
            _stack.callback(nio.delete_recursive, write_dir)
        with span("mh.byte_shuffle.write"):
            _write_byte_runs(
                write_dir, ctx, local, dest_of_record, row_of_record, rows
            )
        if byte_plane == "http":
            sources: List = _start_http_plane(ctx, write_dir, _stack)
        else:
            sources = [shuffle_dir] * ctx.num_processes
        # The input shard is on disk in destination-keyed runs now; release
        # it so fetch-side peak is ~received-shard, not input+received.
        del local, dest_of_record, row_of_record, dest_l
        ctx.barrier("byte_shuffle_written")

        # Receiver: each local device's sorted rows → one part file each
        # (the ExitStack owns server/spill teardown on every outcome).
        with span("mh.byte_shuffle.fetch"):
            fetcher = _ByteFetcher(sources, ctx, rows)
            cap_rows = res.hi.shape[0] // D
            v_sh = _local_view(res.valid, cap_rows)
            sd_sh = _local_view(res.src_dev, cap_rows)
            sr_sh = _local_view(res.src_row, cap_rows)
            # Which global devices are this process's shards?
            g_devs = sorted(
                (s.index[0].start or 0) // cap_rows
                for s in res.valid.addressable_shards
            )
            for k, g_dev in enumerate(g_devs):
                v = v_sh[k]
                sd = sd_sh[k][v]
                sr = sr_sh[k][v]
                data, rec_off, rec_len = fetcher.gather(sd, sr)
                keys = np.zeros(len(sd), dtype=np.int64)  # writer-unused
                batch = RecordBatch(
                    soa={"rec_off": rec_off, "rec_len": rec_len},
                    data=data,
                    keys=keys,
                )
                tmp = os.path.join(td, f"_temporary.part-r-{g_dev:05d}")
                with open(tmp, "wb") as f:
                    write_part_fast(f, batch, order=None, level=level)
                os.replace(
                    tmp, os.path.join(td, f"part-r-{g_dev:05d}")
                )
        ctx.barrier("parts_written")
        if byte_plane == "http":
            # Every process fetched its share: drop the outgoing shard
            # now so it does not coexist with the merge on disk (the
            # ExitStack callback stays as the failure-path backstop;
            # delete_recursive is idempotent).
            nio.delete_recursive(write_dir)
    else:
        if byte_plane == "http":
            sources: List = _start_http_plane(ctx, spill_dir, _stack)
        else:
            sources = [
                os.path.join(shuffle_dir, f"spill-{s:03d}")
                for s in range(ctx.num_processes)
            ]
        peak_bytes = _budget_byte_plane(
            ctx, td, sources, splits, own_counts, dest_of_record,
            level, D, peak_bytes, RecordBatch, write_part_fast,
        )
        if byte_plane == "http":
            # parts_written barrier has passed inside the plane: the
            # spill runs are no longer needed by any peer.
            nio.delete_recursive(spill_dir)
    LAST_STATS["peak_bytes"] = peak_bytes

    if ctx.process_id == 0:
        with span("mh.merge"):
            nio.write_success(td)
            merge_bam_parts(td, out_path, header)
            nio.delete_recursive(td)
    ctx.barrier("merged")
    return n_total
