"""Device-resident sorted record gather + markdup flag patch.

The write half of the on-chip residency story.  The read side already
leaves each split's inflated payload in HBM (``RecordBatch.device_data``,
PR 4); until now ``write_part_fast`` still assembled every part on the
host — NumPy fancy-indexing gather, host ``patch_flags``, host CRC32 —
and shipped the *uncompressed* stream h2d into the deflate lanes.  This
module assembles the part straight from the resident payloads: output
byte p of the permuted record stream reads
``stream[src0[r] + (p - dst0[r])]`` for its covering record r, and
duplicate records get ``FLAG_DUPLICATE`` ORed into their two flag bytes
(body offset 14 → bytes 18/19 past the size word) in the same pass — a
pure gather + compare program, no scatter, no host bounce of the payload.

Formulation notes (why this kernel-family member is an XLA program, like
``deflate_lanes._compact_tokens`` / ``flate._device_flatten``): the
per-position record cover is one batched ``searchsorted`` over the sorted
destination offsets and the body is three gathers — there is no serial
loop for a Pallas lockstep wave to win, and TPU dynamic gathers from HBM
are exactly what XLA emits well.  Launches are chunked under the
``_MAX_LAUNCH_ELEMS`` gather-precision cap with pow2-bucketed record
columns so distinct jit signatures stay few.

Only O(records) int32 columns ride h2d (≈12 bytes/record against the
~170-byte records they describe); the gathered stream itself is born in
HBM and feeds ``deflate_lanes`` device-to-device.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

#: SAM FLAG_DUPLICATE — the only patch the dedup write stage applies.
_FLAG_DUPLICATE = 0x400

#: Output positions per launch (gather elements stay far under the
#: XLA:TPU 2^24 index-precision cap; see ops/flate.py `_MAX_LAUNCH_ELEMS`).
_CHUNK = 1 << 22


def _pow2_at_least(n: int, lo: int) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


@functools.partial(jax.jit, static_argnums=(5, 6))
def _gather_chunk(
    stream: jax.Array,
    dst_end: jax.Array,
    dst_start: jax.Array,
    src0: jax.Array,
    dup: jax.Array,
    chunk: int,
    bits: int,
    b0=0,
    total=0,
) -> jax.Array:
    """One output tile [b0, b0+chunk) of the gathered stream.

    ``dst_end`` is the cumulative record-length column (sorted), so the
    record covering output byte p is the first row whose end exceeds p —
    a batched binary search, the `_coverage` idiom."""
    R = dst_end.shape[0]
    S = stream.shape[0]
    p = b0 + jnp.arange(chunk, dtype=jnp.int32)
    rec = jnp.clip(
        jnp.searchsorted(dst_end, p, side="right").astype(jnp.int32),
        0,
        R - 1,
    )
    rel = p - dst_start[rec]
    src = src0[rec] + rel
    out = stream[jnp.clip(src, 0, S - 1)]
    valid = p < total
    d = valid & (dup[rec] != 0)
    lo = bits & 0xFF
    hi = (bits >> 8) & 0xFF
    if lo:
        out = out | jnp.where(d & (rel == 18), jnp.uint8(lo), jnp.uint8(0))
    if hi:
        out = out | jnp.where(d & (rel == 19), jnp.uint8(hi), jnp.uint8(0))
    return jnp.where(valid, out, jnp.uint8(0))


def gather_stream_device(
    stream,
    src_starts: np.ndarray,
    lens: np.ndarray,
    dup_mask: Optional[np.ndarray] = None,
    bits: int = _FLAG_DUPLICATE,
    chunk: int = _CHUNK,
) -> Tuple[jax.Array, int]:
    """Assemble a permuted record stream in HBM from a resident payload.

    ``stream``: device uint8 (the flat resident payload bytes);
    ``src_starts``: int64 [R] position of each output record's size word
    in ``stream``, already in output (sorted) order; ``lens``: int64 [R]
    total bytes per record (size word + body); ``dup_mask``: optional
    bool [R] — rows to patch with ``bits`` (default ``FLAG_DUPLICATE``)
    at flag-byte offsets 18/19, the device ``io.bam.patch_flags``.

    Returns ``(device uint8 [total], total)``.  Raises ``ValueError``
    when the geometry leaves the int32 gather domain (callers tier down
    to the host gather).
    """
    from ...utils.tracing import count_h2d

    src_starts = np.asarray(src_starts, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    R = len(src_starts)
    if R == 0:
        return jnp.zeros((0,), jnp.uint8), 0
    dst_end = np.cumsum(lens)
    total = int(dst_end[-1])
    if total >= 2**31 or int((src_starts + lens).max()) >= 2**31:
        raise ValueError("gather geometry outside the int32 domain")
    dst_start = dst_end - lens
    Rp = _pow2_at_least(R, 256)
    ends_p = np.full(Rp, total, dtype=np.int32)
    starts_p = np.zeros(Rp, dtype=np.int32)
    src_p = np.zeros(Rp, dtype=np.int32)
    dup_p = np.zeros(Rp, dtype=np.int8)
    ends_p[:R] = dst_end
    starts_p[:R] = dst_start
    src_p[:R] = src_starts
    if dup_mask is not None:
        dup_p[:R] = np.asarray(dup_mask, dtype=np.int8)
    cols = (
        jnp.asarray(ends_p),
        jnp.asarray(starts_p),
        jnp.asarray(src_p),
        jnp.asarray(dup_p),
    )
    count_h2d(ends_p.nbytes + starts_p.nbytes + src_p.nbytes + dup_p.nbytes,
              "write_cols")
    dev = jnp.asarray(stream)
    parts = []
    for b0 in range(0, total, chunk):
        parts.append(
            _gather_chunk(
                dev, *cols, chunk, bits,
                b0=jnp.int32(b0), total=jnp.int32(total),
            )
        )
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return flat[:total], total


# --------------------------------------------------------------------------
# Bench probe (bench.py reports device_write_MBps per round on TPU).
# --------------------------------------------------------------------------


def bench_write_marginal(
    n_small: int = 1 << 20, n_big: int = 4 << 20
) -> dict:
    """Marginal throughput of the device write front-end (sorted gather +
    flag patch + CRC32) via a two-point fit — the same RTT-free protocol
    as ``inflate_probe.bench_marginal``: one resident stream, two output
    sizes; the slope is the per-byte cost, the intercept absorbs launch
    and tunnel round trips.  The deflate stage is excluded (it has its own
    ``device_deflate_MBps`` probe)."""
    import time

    from .crc32 import crc32_device

    rng = np.random.default_rng(3)
    rec_len = 168
    n_rec = n_big // rec_len + 1
    stream = jnp.asarray(
        rng.integers(0, 256, n_rec * rec_len, dtype=np.uint8)
    )
    perm = rng.permutation(n_rec)
    src = (perm * rec_len).astype(np.int64)
    lens = np.full(n_rec, rec_len, dtype=np.int64)
    dup = rng.random(n_rec) < 0.1

    def timed(nbytes: int) -> float:
        k = nbytes // rec_len
        offs = np.arange(0, k * rec_len, 57088, dtype=np.int64)
        mlens = np.minimum(57088, k * rec_len - offs)

        def once():
            out, total = gather_stream_device(
                stream, src[:k], lens[:k], dup_mask=dup[:k]
            )
            jax.block_until_ready(crc32_device(out, offs, mlens))

        once()  # warm the jit caches
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            once()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    dt_s = timed(n_small)
    dt_b = timed(n_big)
    per_byte = (dt_b - dt_s) / (n_big - n_small)
    fixed = dt_s - per_byte * n_small
    bytes_per_s = 1.0 / per_byte if per_byte > 0 else float("inf")
    return {
        "fixed_ms": fixed * 1e3,
        "bytes_per_s": bytes_per_s,
        "projected_mb_s": bytes_per_s / 1e6,
        "t_small_ms": dt_s * 1e3,
        "t_big_ms": dt_b * 1e3,
    }
