"""Hand-written Pallas TPU kernels for ops XLA doesn't fuse well.

Kernels fall back to interpreter mode off-TPU (tests run them on the CPU
mesh), and to the plain-XLA ops/ implementations when Pallas is unavailable.
"""

from .histogram import quality_histogram  # noqa: F401
