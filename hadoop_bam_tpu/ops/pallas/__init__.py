"""Hand-written Pallas TPU kernels for ops XLA doesn't fuse well.

Kernels fall back to interpreter mode off-TPU (tests run them on the CPU
mesh), and to the plain-XLA ops/ implementations when Pallas is unavailable.
"""

from .histogram import quality_histogram, quality_histogram_auto  # noqa: F401
from .overlap import overlap_mask, overlap_mask_auto  # noqa: F401
from .record_scan import (  # noqa: F401
    RecordScanStats,
    WindowOverrun,
    record_scan,
    scan_window_host,
    scan_window_py,
)
from .unpack import unpack_nibbles, unpack_nibbles_auto  # noqa: F401
