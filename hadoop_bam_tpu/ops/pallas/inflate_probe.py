"""Lockstep-lane inflate probe: measures the Pallas walk engine for the
next-generation device DEFLATE decoder (SURVEY §7 hard part #1).

Why this exists
---------------
The shipping device inflate (ops/flate.py) is an XLA array program built on
speculative decode + pointer doubling; it is correct and general but
bottlenecks on XLA:TPU gather throughput (~70M gathered elements/s → 0.5-1
MB/s end to end).  Beating the native host tier (~170 MB/s zlib) needs a
formulation whose inner loop never leaves registers/VMEM — the recipe
proven by the record-chain kernel (ops/pallas/chain.py).

The design this probe measures: **lockstep lanes** — 128 BGZF members in
the 128 vector lanes, each walking its own Huffman stream serially, all in
one Pallas kernel:

- streams live TRANSPOSED in VMEM ([words, 128]: member j's words go down
  lane j), so "read 32 bits at my cursor" is a per-lane row select — an
  iota-compare + masked column reduction over a [R,128] (or windowed
  [W,128]) tile, which Mosaic turns into dense VPU work with no gathers;
- canonical Huffman decode is 15 unrolled range compares against
  per-member table columns ([16,128] tiles) — pure elementwise;
- per-lane cursors advance by the decoded code lengths, so lanes diverge
  like real streams (members batched by compressed size keep the drift,
  and therefore the sliding window, small);
- one-hot emit scatters literal bytes into per-lane output columns; LZ77
  copies read back from the same columns through a recent window, with
  rare far-distance copies deferred to a host-assisted pass.

Measured result (TPU v5e via the dev tunnel, 2026-07-30)
--------------------------------------------------------
Wall-clocking one launch is meaningless on this topology: the tunnel costs
~66-70 ms per round trip and caches identically-shaped calls, so
``bench_marginal`` fits a line through two launch sizes and reports the
*marginal* per-wave cost, which is RTT-free:

    K1 (full-R extraction, R=4096, 128 lanes):
        90.2 ms @ T=32768 waves, 163.7 ms @ T=131072 waves
        → fixed ≈ 65.7 ms (the RTT), marginal ≈ **748 ns/wave**
        → 5.9 ns/token · 128 lanes ≈ **170M tokens/s**
    DEFLATE on BAM-class data emits ~2 output bytes/token, so the walk
    engine alone paces **~340 MB/s** — two orders of magnitude above the
    XLA formulation and ~2x the native host tier.  A windowed variant
    (W=512 sliding extraction) does 8x less extraction work per wave and
    bounds the engine even higher; output emit, copy resolution, and
    per-member table builds are the remaining (comparable-cost) stages,
    so a complete decoder plausibly lands at host-tier-or-better
    throughput.

Status: the walk engine clears the bar; the full decoder (tables, emit,
copies, splice validation) is the remaining build.  The production
pipeline keeps the tiered design (native host inflate on the hot path)
until that lands; ops/flate.py documents the same numbers from the
consumer side.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128


def _walk_kernel_factory(R: int, T: int):
    """T lockstep token waves over [R,128] per-lane streams."""

    def kernel(streams_ref, cursors_ref, out_ref, acc_ref):
        rows = lax.broadcasted_iota(jnp.int32, (R, LANES), 0)

        def extract_word(widx):
            onehot = rows == widx  # [R,128]
            return jnp.sum(
                jnp.where(onehot, streams_ref[:, :], 0),
                axis=0,
                keepdims=True,
            )  # [1,128]

        def body(t, state):
            cur, acc = state  # [1,128] bit cursors / checksum
            widx = cur >> 5
            w0 = extract_word(widx).astype(jnp.uint32)
            w1 = extract_word(widx + 1).astype(jnp.uint32)
            sh = (cur & 31).astype(jnp.uint32)
            win = jnp.where(
                sh == 0, w0, (w0 >> sh) | (w1 << (32 - sh))
            ).astype(jnp.int32)
            # Canonical-decode stand-in: 15 length classes of range
            # compares, data-dependent so lanes diverge like real streams.
            rev = win & 0x7FFF
            Lsel = jnp.full((1, LANES), 15, jnp.int32)
            for L in range(15, 0, -1):
                cand = rev >> (15 - L)
                match = cand < ((rev >> 7) & 0x7F) + L
                Lsel = jnp.where(match, L, Lsel)
            adv = Lsel + (win & 7)
            return cur + adv, acc + win

        cur0 = cursors_ref[:, :]
        acc0 = jnp.zeros((1, LANES), jnp.int32)
        cur, acc = lax.fori_loop(0, T, body, (cur0, acc0))
        out_ref[:, :] = cur
        acc_ref[:, :] = acc

    return kernel


def make_walk(R: int, T: int, interpret: bool = False):
    kernel = _walk_kernel_factory(R, T)

    def walk(streams, cursors):
        return pl.pallas_call(
            kernel,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((1, LANES), jnp.int32),
                jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            ),
            interpret=interpret,
        )(streams, cursors)

    return jax.jit(walk)


def reference_walk(streams: np.ndarray, cursors: np.ndarray, T: int):
    """NumPy oracle of the probe walk (tests pin kernel semantics)."""
    R = streams.shape[0]
    c = cursors.astype(np.int64).copy()
    a = np.zeros_like(c)
    lane = np.arange(LANES)
    for _ in range(T):
        widx = c >> 5
        in0 = (widx >= 0) & (widx < R)
        in1 = (widx + 1 >= 0) & (widx + 1 < R)
        w0 = np.where(
            in0, streams[np.clip(widx, 0, R - 1), lane], 0
        ).astype(np.uint32)
        w1 = np.where(
            in1, streams[np.clip(widx + 1, 0, R - 1), lane], 0
        ).astype(np.uint32)
        sh = (c & 31).astype(np.uint32)
        win = np.where(
            sh == 0, w0, (w0 >> sh) | (w1 << (np.uint32(32) - sh))
        ).astype(np.uint32).astype(np.int32)
        rev = win & 0x7FFF
        Lsel = np.full_like(c, 15)
        for L in range(15, 0, -1):
            cand = rev >> (15 - L)
            match = cand < ((rev >> 7) & 0x7F) + L
            Lsel = np.where(match, L, Lsel)
        c = c + Lsel + (win & 7)
        a = (a + win) & 0xFFFFFFFF
    return c, a


def bench_marginal(R: int = 4096, t_small: int = 32768,
                   t_big: int = 131072) -> dict:
    """Marginal per-wave cost via a two-point linear fit (RTT-free).

    Returns {'fixed_ms', 'ns_per_wave', 'tokens_per_s', 'projected_mb_s'}.
    Run with the chip otherwise idle — concurrent launches queue behind
    each other and corrupt both measurements."""
    rng = np.random.default_rng(0)
    streams = jnp.asarray(
        rng.integers(0, 1 << 31, (R, LANES), dtype=np.int32)
    )

    def timed(T: int) -> float:
        walk = make_walk(R, T)
        jax.block_until_ready(
            walk(streams, jnp.full((1, LANES), 3, jnp.int32))
        )
        ts = []
        for i in range(3):
            c = jnp.full((1, LANES), i, jnp.int32)
            t0 = time.perf_counter()
            jax.block_until_ready(walk(streams, c))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    dt_s = timed(t_small)
    dt_b = timed(t_big)
    per_wave = (dt_b - dt_s) / (t_big - t_small)
    fixed = dt_s - per_wave * t_small
    tokens_per_s = LANES / per_wave if per_wave > 0 else float("inf")
    return {
        "fixed_ms": fixed * 1e3,
        "ns_per_wave": per_wave * 1e9,
        "tokens_per_s": tokens_per_s,
        "projected_mb_s": 2 * tokens_per_s / 1e6,  # ~2 out bytes/token
        "t_small_ms": dt_s * 1e3,
        "t_big_ms": dt_b * 1e3,
    }


if __name__ == "__main__":
    print(f"device: {jax.devices()[0]}")
    r = bench_marginal()
    print(
        f"fixed {r['fixed_ms']:.1f} ms (launch/RTT), "
        f"marginal {r['ns_per_wave']:.0f} ns/wave "
        f"-> {r['tokens_per_s']/1e6:.0f}M tokens/s, "
        f"~{r['projected_mb_s']:.0f} MB/s walk-engine ceiling"
    )
