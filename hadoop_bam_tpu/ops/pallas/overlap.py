"""Pallas TPU kernel: batched interval-overlap mask.

The device replacement for htsjdk's per-record ``OverlapDetector`` loop
(VCFRecordReader.java:196-198,211-217) and the record-level tail of BAM
bounded traversal (after the coarse BAI chunk-span split filter,
BAMInputFormat.java:532-634): given per-record (refid, start, end) columns
and K query intervals, produce a keep-mask in one pass.

Records ride the [TILE, 128] vector tiles; the K intervals sit in SMEM as
scalars and the kernel unrolls over them (K is small — a handful of query
regions — while N is millions of records).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE = 8
_LANES = 128


def _kernel(iv_ref, refid_ref, start_ref, end_ref, out_ref, *, k: int):
    refid = refid_ref[:]
    start = start_ref[:]
    end = end_ref[:]
    acc = jnp.zeros(refid.shape, jnp.int32)
    for j in range(k):  # static unroll over the query intervals
        rid = iv_ref[j, 0]
        beg = iv_ref[j, 1]
        stop = iv_ref[j, 2]
        hit = (refid == rid) & (start < stop) & (end > beg)
        acc = acc | hit.astype(jnp.int32)
    out_ref[:] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def _overlap_call(intervals, refid, start, end, interpret: bool):
    k = intervals.shape[0]
    rows, lanes = refid.shape
    return pl.pallas_call(
        functools.partial(_kernel, k=k),
        grid=(rows // _TILE,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # intervals [K, 3]
            pl.BlockSpec((_TILE, lanes), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, lanes), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, lanes), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE, lanes), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), jnp.int32),
        interpret=interpret,
    )(intervals, refid, start, end)


def overlap_mask(
    intervals,  # int32[K, 3]: (refid, beg, end) half-open 0-based
    refid,  # int32[N]
    start,  # int32[N] 0-based inclusive start
    end,  # int32[N] 0-based exclusive end
    interpret: bool = False,
) -> jax.Array:
    """bool[N]: record i overlaps any query interval."""
    intervals = jnp.asarray(intervals, jnp.int32)
    if intervals.ndim != 2 or intervals.shape[1] != 3:
        raise ValueError("intervals must be [K, 3] (refid, beg, end)")
    if intervals.shape[0] == 0:
        return jnp.zeros(len(refid), bool)
    n = len(refid)
    block = _TILE * _LANES
    padded = -(-max(n, 1) // block) * block
    cols = []
    for a in (refid, start, end):
        a = jnp.asarray(a, jnp.int32)
        a = jnp.pad(a, (0, padded - n), constant_values=-2)
        cols.append(a.reshape(padded // _LANES, _LANES))
    out = _overlap_call(intervals, *cols, interpret=interpret)
    return out.reshape(-1)[:n] != 0


def overlap_mask_auto(intervals, refid, start, end) -> jax.Array:
    on_tpu = jax.devices()[0].platform == "tpu"
    return overlap_mask(intervals, refid, start, end, interpret=not on_tpu)


# -- ragged interval join (PR 20) -------------------------------------------
#
# The K-fixed-interval kernel above unrolls over SMEM scalars, which stops
# scaling the moment the query side is ragged (many windows, many records,
# both sorted): the generalization is the searchsorted-cover pattern from
# gather_stream.py — two sorted axes joined by binary search, no unroll.
#
#   mask form   (records × windows → per-record any-overlap):
#     with windows sorted by begin and P[j] = max(q_end[0..j]) (prefix max),
#     record [s, e) overlaps some window  ⟺  j_hi > 0 and P[j_hi-1] > s,
#     where j_hi = searchsorted(q_beg, e, 'left').
#     (j < j_hi ⟺ q_beg[j] < e; the prefix max witnesses ∃j: q_end[j] > s.)
#   counts form (windows → per-window record count):
#     with record starts and ends each sorted ascending,
#     count_j = #(start < q_end_j) − #(end ≤ q_beg_j)
#             = searchsorted(starts, q_end_j, 'left')
#               − searchsorted(ends, q_beg_j, 'right').
#
# Both forms are pure searchsorted+gather, so the device build is plain
# jitted XLA (the gather_stream idiom) — no Pallas needed — and the NumPy
# twins below are bit-identical by construction (same primitives, same
# side rules).  Coordinates ride int32 on device (JAX x64 is off); the
# multi-contig entry loops per contig, which also keeps every searchsorted
# on one coordinate axis.

_PAD_BEG = (1 << 31) - 1  # window sentinel: begins after any coordinate
_PAD_END = -(1 << 31)  # window sentinel: ends before any coordinate


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


@jax.jit
def _join_mask_call(starts, ends, qb_sorted, qe_cummax):
    j_hi = jnp.searchsorted(qb_sorted, ends, side="left").astype(jnp.int32)
    cover = qe_cummax[jnp.maximum(j_hi - 1, 0)]
    return (j_hi > 0) & (cover > starts)


@jax.jit
def _join_counts_call(starts_sorted, ends_sorted, q_beg, q_end):
    hi = jnp.searchsorted(starts_sorted, q_end, side="left")
    lo = jnp.searchsorted(ends_sorted, q_beg, side="right")
    return (hi - lo).astype(jnp.int32)


def join_mask_np(starts, ends, q_beg, q_end) -> np.ndarray:
    """NumPy twin of the device mask form (the tier-down oracle).
    Windows need not arrive sorted; records are arbitrary order."""
    starts = np.asarray(starts)
    ends = np.asarray(ends)
    q_beg = np.asarray(q_beg)
    q_end = np.asarray(q_end)
    if len(q_beg) == 0:
        return np.zeros(len(starts), dtype=bool)
    order = np.argsort(q_beg, kind="stable")
    qb = q_beg[order]
    qe_cummax = np.maximum.accumulate(q_end[order])
    j_hi = np.searchsorted(qb, ends, side="left")
    cover = qe_cummax[np.maximum(j_hi - 1, 0)]
    return (j_hi > 0) & (cover > starts)


def join_counts_np(starts, ends, q_beg, q_end) -> np.ndarray:
    """NumPy twin of the device counts form: per-window overlap counts
    over one record set (starts/ends sorted internally)."""
    starts = np.sort(np.asarray(starts), kind="stable")
    ends = np.sort(np.asarray(ends), kind="stable")
    hi = np.searchsorted(starts, np.asarray(q_end), side="left")
    lo = np.searchsorted(ends, np.asarray(q_beg), side="right")
    return (hi - lo).astype(np.int32)


def join_mask_device(starts, ends, q_beg, q_end) -> np.ndarray:
    """Device mask form: one coordinate axis, int32 coordinates.

    Sorts/pads on the host (pow2 shapes so only a few variants compile),
    runs the two searchsorted gathers as jitted XLA, returns a host bool
    mask.  Sentinel windows begin past every coordinate, so they never
    win a search; sentinel records end at INT32_MIN, so their j_hi is 0."""
    starts = np.asarray(starts, np.int32)
    ends = np.asarray(ends, np.int32)
    q_beg = np.asarray(q_beg, np.int32)
    q_end = np.asarray(q_end, np.int32)
    n, m = len(starts), len(q_beg)
    if n == 0 or m == 0:
        return np.zeros(n, dtype=bool)
    order = np.argsort(q_beg, kind="stable")
    qb = q_beg[order]
    qe_cummax = np.maximum.accumulate(q_end[order])
    mp = _pow2(m)
    qb = np.pad(qb, (0, mp - m), constant_values=_PAD_BEG)
    qe_cummax = np.pad(qe_cummax, (0, mp - m), constant_values=_PAD_END)
    np_ = _pow2(n)
    s = np.pad(starts, (0, np_ - n), constant_values=_PAD_BEG)
    e = np.pad(ends, (0, np_ - n), constant_values=_PAD_END)
    out = _join_mask_call(s, e, qb, qe_cummax)
    return np.asarray(out)[:n]


def join_counts_device(starts, ends, q_beg, q_end) -> np.ndarray:
    """Device counts form: per-window record counts, int32 axis."""
    starts = np.sort(np.asarray(starts, np.int32), kind="stable")
    ends = np.sort(np.asarray(ends, np.int32), kind="stable")
    q_beg = np.asarray(q_beg, np.int32)
    q_end = np.asarray(q_end, np.int32)
    n, m = len(starts), len(q_beg)
    if m == 0:
        return np.zeros(0, np.int32)
    if n == 0:
        return np.zeros(m, np.int32)
    np_ = _pow2(n)
    # Record sentinels start past every window end (never counted by hi)
    # and end past every window begin (never subtracted by lo).
    s = np.pad(starts, (0, np_ - n), constant_values=_PAD_BEG)
    e = np.pad(ends, (0, np_ - n), constant_values=_PAD_BEG)
    mp = _pow2(m)
    qb = np.pad(q_beg, (0, mp - m))
    qe = np.pad(q_end, (0, mp - m))
    out = _join_counts_call(s, e, qb, qe)
    return np.asarray(out)[:m]


def ragged_overlap_mask(
    refid,  # int[N] per-record contig index
    starts,  # int[N] 0-based inclusive start
    ends,  # int[N] 0-based exclusive end
    q_refid,  # int[M] per-window contig index
    q_beg,  # int[M] 0-based inclusive begin
    q_end,  # int[M] 0-based exclusive end
    use_device: bool = False,
) -> np.ndarray:
    """bool[N]: record i overlaps any query window — the shared entry for
    ``variants region``, multi-region scans and the depth windows.  Loops
    per query contig (few per request) so each join stays on one int32
    coordinate axis; ``use_device=False`` is the bit-identical host twin."""
    refid = np.asarray(refid)
    starts = np.asarray(starts)
    ends = np.asarray(ends)
    q_refid = np.asarray(q_refid)
    q_beg = np.asarray(q_beg)
    q_end = np.asarray(q_end)
    mask = np.zeros(len(refid), dtype=bool)
    for rid in np.unique(q_refid):
        qsel = q_refid == rid
        rows = np.nonzero(refid == rid)[0]
        if len(rows) == 0:
            continue
        join = join_mask_device if use_device else join_mask_np
        mask[rows] = join(
            starts[rows], ends[rows], q_beg[qsel], q_end[qsel]
        )
    return mask


def intervals_to_array(header_ref_index, intervals) -> np.ndarray:
    """[K, 3] device layout from parsed Interval objects; unknown contigs
    are dropped (VCFRecordReader's murmur-for-unknown only affects keys,
    not overlap — OverlapDetector skips unknown contigs)."""
    rows = []
    for iv in intervals:
        try:
            rid = header_ref_index(iv.contig)
        except KeyError:
            continue
        rows.append((rid, iv.start - 1, iv.end))
    return np.asarray(rows or np.empty((0, 3)), dtype=np.int32).reshape(-1, 3)
