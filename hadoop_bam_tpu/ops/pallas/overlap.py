"""Pallas TPU kernel: batched interval-overlap mask.

The device replacement for htsjdk's per-record ``OverlapDetector`` loop
(VCFRecordReader.java:196-198,211-217) and the record-level tail of BAM
bounded traversal (after the coarse BAI chunk-span split filter,
BAMInputFormat.java:532-634): given per-record (refid, start, end) columns
and K query intervals, produce a keep-mask in one pass.

Records ride the [TILE, 128] vector tiles; the K intervals sit in SMEM as
scalars and the kernel unrolls over them (K is small — a handful of query
regions — while N is millions of records).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE = 8
_LANES = 128


def _kernel(iv_ref, refid_ref, start_ref, end_ref, out_ref, *, k: int):
    refid = refid_ref[:]
    start = start_ref[:]
    end = end_ref[:]
    acc = jnp.zeros(refid.shape, jnp.int32)
    for j in range(k):  # static unroll over the query intervals
        rid = iv_ref[j, 0]
        beg = iv_ref[j, 1]
        stop = iv_ref[j, 2]
        hit = (refid == rid) & (start < stop) & (end > beg)
        acc = acc | hit.astype(jnp.int32)
    out_ref[:] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def _overlap_call(intervals, refid, start, end, interpret: bool):
    k = intervals.shape[0]
    rows, lanes = refid.shape
    return pl.pallas_call(
        functools.partial(_kernel, k=k),
        grid=(rows // _TILE,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # intervals [K, 3]
            pl.BlockSpec((_TILE, lanes), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, lanes), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, lanes), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE, lanes), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), jnp.int32),
        interpret=interpret,
    )(intervals, refid, start, end)


def overlap_mask(
    intervals,  # int32[K, 3]: (refid, beg, end) half-open 0-based
    refid,  # int32[N]
    start,  # int32[N] 0-based inclusive start
    end,  # int32[N] 0-based exclusive end
    interpret: bool = False,
) -> jax.Array:
    """bool[N]: record i overlaps any query interval."""
    intervals = jnp.asarray(intervals, jnp.int32)
    if intervals.ndim != 2 or intervals.shape[1] != 3:
        raise ValueError("intervals must be [K, 3] (refid, beg, end)")
    if intervals.shape[0] == 0:
        return jnp.zeros(len(refid), bool)
    n = len(refid)
    block = _TILE * _LANES
    padded = -(-max(n, 1) // block) * block
    cols = []
    for a in (refid, start, end):
        a = jnp.asarray(a, jnp.int32)
        a = jnp.pad(a, (0, padded - n), constant_values=-2)
        cols.append(a.reshape(padded // _LANES, _LANES))
    out = _overlap_call(intervals, *cols, interpret=interpret)
    return out.reshape(-1)[:n] != 0


def overlap_mask_auto(intervals, refid, start, end) -> jax.Array:
    on_tpu = jax.devices()[0].platform == "tpu"
    return overlap_mask(intervals, refid, start, end, interpret=not on_tpu)


def intervals_to_array(header_ref_index, intervals) -> np.ndarray:
    """[K, 3] device layout from parsed Interval objects; unknown contigs
    are dropped (VCFRecordReader's murmur-for-unknown only affects keys,
    not overlap — OverlapDetector skips unknown contigs)."""
    rows = []
    for iv in intervals:
        try:
            rid = header_ref_index(iv.contig)
        except KeyError:
            continue
        rows.append((rid, iv.start - 1, iv.end))
    return np.asarray(rows or np.empty((0, 3)), dtype=np.int32).reshape(-1, 3)
