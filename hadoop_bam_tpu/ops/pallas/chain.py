"""Pallas TPU kernel: BAM record-boundary chain over an uncompressed stream.

SURVEY §7 stage 4: records are ``[u32 block_size][body]`` back to back, so
boundary discovery is the sequential walk ``pos += 4 + u32(pos)`` — the one
step the vectorized SoA decode could not do on device (the host C++
``hbam_record_chain`` filled in).  This kernel runs the walk on-chip:

- the stream is processed in fixed chunks; each chunk is one
  ``pallas_call`` whose scalar carry (``cursor``) enters/leaves through
  SMEM, so a record spanning chunks resumes exactly where the previous
  chunk stopped (the "cross-tile carry" of the survey's prefix-scan
  formulation — the carry IS the scan state, and chunks pipeline back to
  back on the sequential TPU grid);
- inside a chunk the walk is a ``lax.while_loop`` of scalar VMEM loads:
  the u32 size word at an arbitrary byte offset is two aligned word loads
  recombined with shifts (TPU VMEM has no byte-granular addressing);
- offsets of records *starting* in the chunk append to a VMEM output
  block through a dynamic scalar store.

The walk is latency-bound scalar work (~one dependent VMEM load per
record), not VPU work — but one record is ~100+ bytes, so at ns-class VMEM
latency the kernel paces GB/s-of-stream class and the boundary pass never
leaves the chip.  Oracle: ``spec.bam.record_offsets``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: Bytes of stream walked per pallas_call.  VMEM footprint per call is
#: CHUNK (words) + CHUNK//9 (offsets) — well under the ~16MiB budget.
CHUNK = 4 << 20
#: A record is ≥ 36 bytes (u32 size + 32-byte fixed fields), so a chunk
#: can start at most CHUNK//36 records — pad to a lane-aligned bound.
MAX_REC_PER_CHUNK = -(-(CHUNK // 36 + 8) // 128) * 128
_MIN_BODY = 32  # BAM fixed fields; a smaller size word is corruption


def _chain_kernel(
    cursor_in_ref,  # SMEM (1,) int32: absolute resume cursor
    base_ref,  # SMEM (1,) int32: absolute byte offset of this chunk
    limit_ref,  # SMEM (1,) int32: absolute end of record starts (chunk end
    #             or stream end, whichever is smaller)
    words_ref,  # VMEM [rows, 128] int32: chunk bytes (+margin) as words
    offs_ref,  # VMEM [MAX_REC_PER_CHUNK//128, 128] int32 out: starts (abs)
    count_ref,  # SMEM (1,) int32 out
    cursor_out_ref,  # SMEM (1,) int32 out: resume cursor (abs)
    err_ref,  # SMEM (1,) int32 out: 1 on implausible size word
):
    """TPU VMEM has no scalar random access, so the walk uses the
    vector-native moves: the u32 size word at an arbitrary byte offset is
    a dynamic *row-pair* load from the [rows, 128]-word layout followed by
    masked lane extraction, and offsets accumulate in a register-carried
    [1, 128] buffer whose current row is flushed with an aligned full-row
    store each step (no read-modify-write)."""
    base = base_ref[0]
    limit = limit_ref[0]
    lane2 = lax.broadcasted_iota(jnp.int32, (2, 128), 1)
    row2 = lax.broadcasted_iota(jnp.int32, (2, 128), 0)
    lane1 = lax.broadcasted_iota(jnp.int32, (1, 128), 1)

    def u32_at(abs_off):
        off = abs_off - base
        wi = off >> 2
        r = wi >> 7
        rows = words_ref[pl.ds(r, 2), :]  # [2, 128]

        def word(widx):
            rr = (widx >> 7) - r
            ll = widx & 127
            return jnp.sum(
                jnp.where((row2 == rr) & (lane2 == ll), rows, 0)
            )

        w0 = word(wi).astype(jnp.uint32)
        w1 = word(wi + 1).astype(jnp.uint32)
        sh = ((off & 3) << 3).astype(jnp.uint32)
        lo = w0 >> sh
        hi = jnp.where(sh == 0, jnp.uint32(0), w1 << (32 - sh))
        return (lo | hi).astype(jnp.int32)

    def cond(state):
        cur, n, err, _ = state
        return (cur < limit) & (err == 0) & (n < MAX_REC_PER_CHUNK)

    def body(state):
        cur, n, err, buf = state
        bs = u32_at(cur)
        bad = (bs < _MIN_BODY) | (bs > (1 << 28))
        buf = jnp.where(lane1 == (n & 127), cur, buf)
        offs_ref[pl.ds(n >> 7, 1), :] = buf
        nxt = jnp.where(bad, limit, cur + 4 + bs)
        return nxt, n + jnp.where(bad, 0, 1), err | bad.astype(jnp.int32), buf

    cur0 = cursor_in_ref[0]
    buf0 = jnp.zeros((1, 128), jnp.int32)
    cur, n, err, _ = lax.while_loop(
        cond, body, (cur0, jnp.int32(0), jnp.int32(0), buf0)
    )
    count_ref[0] = n
    cursor_out_ref[0] = cur
    err_ref[0] = err | jnp.int32(n >= MAX_REC_PER_CHUNK)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _chain_chunk(cursor, base, limit, words, interpret: bool = False):
    return pl.pallas_call(
        _chain_kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((MAX_REC_PER_CHUNK // 128, 128), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ),
        interpret=interpret,
    )(cursor, base, limit, words)


@functools.partial(jax.jit, static_argnames=("n_chunks", "interpret"))
def _chain_all(stream_words, n_bytes, n_chunks: int, interpret: bool):
    """Run the chunk kernel over the whole stream, carrying the cursor."""
    WPC = CHUNK // 4
    cursor = jnp.zeros((1,), jnp.int32)
    offs_parts = []
    counts = []
    err_any = jnp.int32(0)
    for k in range(n_chunks):
        base = jnp.full((1,), k * CHUNK, jnp.int32)
        limit = jnp.minimum(jnp.int32((k + 1) * CHUNK), n_bytes)
        words = lax.dynamic_slice(
            stream_words, (k * WPC,), (WPC + 256,)
        ).reshape(-1, 128)
        offs, count, cursor, err = _chain_chunk(
            cursor, base, limit[None], words, interpret=interpret
        )
        offs_parts.append(offs.reshape(-1))
        counts.append(count[0])
        err_any = err_any | err[0]
    counts = jnp.stack(counts)
    # Flatten the per-chunk offset blocks into one packed array: output
    # slot t belongs to chunk k = searchsorted(cum, t), local index
    # t - cum[k-1] (gather-form compaction, no scatter).
    cum = jnp.cumsum(counts)
    total = cum[-1]
    stacked = jnp.stack(offs_parts)  # [K, MAXR]
    t = jnp.arange(n_chunks * MAX_REC_PER_CHUNK, dtype=jnp.int32)
    k_of_t = jnp.searchsorted(cum, t, side="right").astype(jnp.int32)
    k_c = jnp.clip(k_of_t, 0, n_chunks - 1)
    local = t - jnp.where(k_c > 0, cum[k_c - 1], 0)
    flat = stacked[
        k_c, jnp.clip(local, 0, MAX_REC_PER_CHUNK - 1)
    ]
    flat = jnp.where(t < total, flat, 0)
    ok = (err_any == 0) & (cursor[0] == n_bytes)
    return flat, total, ok


def record_chain_device(stream, n_bytes=None, interpret=None):
    """Record-start offsets of a BAM record stream, computed on device.

    ``stream``: uint8 array (device or host) holding ``n_bytes`` of
    back-to-back records.  Returns ``(offsets int32[cap], count, ok)`` —
    ``offsets[:count]`` equals ``spec.bam.record_offsets``; ``ok`` is False
    on a truncated/misaligned chain (caller falls back / raises).
    """
    a = jnp.asarray(stream, dtype=jnp.uint8)
    n = int(a.shape[0]) if n_bytes is None else int(n_bytes)
    if n > 2**31 - CHUNK:
        # Offsets, cursors and n_bytes ride int32 lanes inside the kernel;
        # past 2 GiB they wrap silently and the cursor==n_bytes check would
        # compare wrapped values.  The margin keeps the last chunk's
        # (k+1)*CHUNK limit inside int32 too.  Callers chunk well below.
        raise ValueError(
            f"record_chain_device: stream of {n} bytes exceeds the int32 "
            "offset domain (2 GiB); chunk the stream before calling"
        )
    n_chunks = max(1, -(-n // CHUNK))
    nbytes_pad = n_chunks * CHUNK + 256 * 4
    pad = nbytes_pad - a.shape[0]
    if pad > 0:
        a = jnp.pad(a, (0, pad))
    words = lax.bitcast_convert_type(
        a[:nbytes_pad].reshape(-1, 4), jnp.int32
    ).reshape(-1)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _chain_all(
        words, jnp.int32(n), n_chunks, bool(interpret)
    )
