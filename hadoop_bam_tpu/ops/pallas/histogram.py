"""Pallas TPU kernel: masked u8-value histogram over a record batch.

The hot op of baseline config #3 (FASTQ → quality-score histogram).  A
scatter-add histogram serializes on TPU; this kernel instead puts the *bin*
axis on the 128-wide lane dimension: for each position column ``j`` of the
[TILE, L] value tile, the [TILE, 1] column broadcasts against the [1, 128]
bin iota into a [TILE, 128] compare+mask, which reduces over sublanes into
the accumulator.  The output block's index map is constant, so it stays
resident in VMEM across the whole grid (first step zero-initializes).

Layout notes: everything stays 2D with a 128-lane minor dimension — Mosaic
rejects [TILE, L] → [TILE*L, 1] style shape casts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

_TILE = 64  # rows per grid step (keeps the unrolled column loop within VMEM)
_LANES = 128  # TPU lane width == bins per chunk


def _kernel(vals_ref, valid_ref, out_ref, *, nbins: int, length: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    nchunks = nbins // _LANES
    vals = vals_ref[:]  # [TILE, L] in registers
    mask = valid_ref[:] != 0
    acc = jnp.zeros((1, nbins), jnp.int32)
    for j in range(length):  # static unroll over read positions
        col = vals[:, j : j + 1]  # [TILE, 1]
        m = mask[:, j : j + 1]
        parts = []
        for c in range(nchunks):  # lanes carry the bins
            bins = c * _LANES + jax.lax.broadcasted_iota(
                jnp.int32, (1, _LANES), 1
            )
            hits = jnp.where(m & (col == bins), jnp.int32(1), jnp.int32(0))
            parts.append(jnp.sum(hits, axis=0, keepdims=True))  # [1, LANES]
        row = parts[0] if nchunks == 1 else jnp.concatenate(parts, axis=1)
        acc = acc + row
    out_ref[:] += acc


@functools.partial(jax.jit, static_argnames=("nbins", "interpret"))
def quality_histogram(
    values: jax.Array,  # int32[B, L]
    valid: jax.Array,  # int32[B, L] (0/1)
    nbins: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """int32[nbins] counts of values in [0, nbins) at valid positions."""
    B, L = values.shape
    if B % _TILE != 0:
        pad = _TILE - B % _TILE
        values = jnp.pad(values, ((0, pad), (0, 0)))
        valid = jnp.pad(valid, ((0, pad), (0, 0)))
        B += pad
    if nbins % _LANES != 0:
        raise ValueError(f"nbins must be a multiple of {_LANES}")
    grid = (B // _TILE,)
    out = pl.pallas_call(
        functools.partial(_kernel, nbins=nbins, length=L),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE, L), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, L), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, nbins), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, nbins), jnp.int32),
        interpret=interpret,
    )(values, valid)
    return out[0]


def quality_histogram_auto(values, valid, nbins: int = 128) -> jax.Array:
    """Dispatch: Pallas on TPU, interpreter elsewhere (tests/CPU mesh)."""
    on_tpu = jax.devices()[0].platform == "tpu"
    return quality_histogram(values, valid, nbins=nbins, interpret=not on_tpu)
