"""Pallas TPU kernel: BAM 4-bit sequence unpack (two bases per byte).

BAM packs bases as nibbles, high nibble first (SAM spec §4.2.3; the
reference defers to htsjdk's per-record decode).  Batched on device: the
kernel shifts/masks a [TILE, W] packed byte tile into high- and low-nibble
planes on the VPU; the final lane interleave ([T, W, 2] → [T, 2W]) happens
*outside* the kernel in XLA, which fuses it — Mosaic rejects lane-doubling
reshapes in-kernel (tpu.reshape vector<..x64x2> → <..x128> is unsupported),
so emitting two planes is the TPU-native formulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_TILE = 256  # rows per grid step


def _kernel(packed_ref, hi_ref, lo_ref):
    packed = packed_ref[:].astype(jnp.int32)  # [TILE, W]
    hi_ref[:] = (packed >> 4) & 0xF
    lo_ref[:] = packed & 0xF


@functools.partial(jax.jit, static_argnames=("interpret",))
def unpack_nibbles(packed: jax.Array, interpret: bool = False) -> jax.Array:
    """uint8/int32[B, W] packed → int32[B, 2W] base codes (0-15)."""
    B, W = packed.shape
    if W == 0:
        return jnp.zeros((B, 0), jnp.int32)
    pad = (-B) % _TILE
    if pad:
        packed = jnp.pad(packed, ((0, pad), (0, 0)))
    hi, lo = pl.pallas_call(
        _kernel,
        grid=((B + pad) // _TILE,),
        in_specs=[pl.BlockSpec((_TILE, W), lambda i: (i, 0))],
        out_specs=(
            pl.BlockSpec((_TILE, W), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, W), lambda i: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B + pad, W), jnp.int32),
            jax.ShapeDtypeStruct((B + pad, W), jnp.int32),
        ),
        interpret=interpret,
    )(packed)
    out = jnp.stack([hi, lo], axis=-1).reshape(B + pad, 2 * W)
    return out[:B]


def unpack_nibbles_auto(packed) -> jax.Array:
    """Pallas on TPU, interpreter elsewhere (CPU tests)."""
    on_tpu = jax.devices()[0].platform == "tpu"
    return unpack_nibbles(jnp.asarray(packed), interpret=not on_tpu)


SEQ_CODE_TO_BASE = "=ACMGRSVTWYHKDBN"  # SAM spec nibble alphabet
