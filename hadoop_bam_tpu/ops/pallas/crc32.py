"""Device CRC32 (the gzip/BGZF polynomial) over HBM-resident byte streams.

BGZF member framing needs ``CRC32(payload)`` and ISIZE per member; as long
as that CRC ran on the host, the uncompressed part stream had to exist
host-side even when the gather and the DEFLATE emit were already
device-resident — the whole write path stayed pinned to an h2d upload of
the raw bytes.  This kernel closes the loop: per-member CRCs compute on
chip straight from the HBM-resident gathered stream, so the part writer
d2h's a 4-byte CRC column instead of keeping the payload on the host.

Formulation: slicing-by-4.  The CRC recurrence is serial per *word*, not
per byte — each step folds 4 input bytes through four 256-entry tables:

    c ^= word(LE);  c = T3[c&ff] ^ T2[(c>>8)&ff] ^ T1[(c>>16)&ff] ^ T0[c>>24]

All members of a batch advance in lockstep (one ``fori_loop`` over the
word count of the longest member, retired members carry their value), so
the step is a dense [B]-wide gather program — the shape XLA:TPU runs
well.  A Pallas lockstep variant was considered and rejected: the table
gathers would become O(table)×O(members) one-hot selects per wave (the
probe-style row-select trick), turning a 4-gather step into a 1024-wide
reduction — the XLA gather path is strictly better here, which is why
this member of the kernel family has no ``pallas_call`` (same stance as
``deflate_lanes._compact_tokens``).

Oracle: ``zlib.crc32`` (tests/test_device_write.py fuzzes empty, 1-byte,
word-boundary and multi-member batches against it bit-for-bit).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def _build_tables() -> np.ndarray:
    t = np.zeros((4, 256), dtype=np.uint32)
    for i in range(256):
        c = np.uint32(i)
        for _ in range(8):
            c = (c >> np.uint32(1)) ^ (
                np.uint32(0xEDB88320) if c & np.uint32(1) else np.uint32(0)
            )
        t[0, i] = c
    for k in range(1, 4):
        for i in range(256):
            t[k, i] = (t[k - 1, i] >> np.uint32(8)) ^ t[
                0, int(t[k - 1, i] & np.uint32(0xFF))
            ]
    return t


#: Slicing-by-4 tables for the reflected 0xEDB88320 polynomial; row 0 is
#: the classic bytewise table (used for the ≤3-byte tail).
CRC_TABLES = _build_tables()


def _pow2_at_least(n: int, lo: int) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


def _crc32_core(
    stream: jax.Array, offs: jax.Array, lens: jax.Array, max_words: int
) -> jax.Array:
    """CRC32 of ``stream[offs[i] : offs[i]+lens[i]]`` for every member i,
    in lockstep.  ``max_words`` is the static word-loop bound (≥
    ``max(lens)//4``); members past their own length carry their value.
    Zero-length members return 0 (``zlib.crc32(b"") == 0``)."""
    S = stream.shape[0]
    t0 = jnp.asarray(CRC_TABLES[0])
    t1 = jnp.asarray(CRC_TABLES[1])
    t2 = jnp.asarray(CRC_TABLES[2])
    t3 = jnp.asarray(CRC_TABLES[3])
    offs = offs.astype(jnp.int32)
    lens = lens.astype(jnp.int32)
    nwords = lens >> 2

    def byte_at(idx):
        return stream[jnp.clip(idx, 0, S - 1)].astype(jnp.uint32)

    def word_step(i, crc):
        base = offs + 4 * i
        w = (
            byte_at(base)
            | (byte_at(base + 1) << 8)
            | (byte_at(base + 2) << 16)
            | (byte_at(base + 3) << 24)
        )
        c = crc ^ w
        c2 = (
            t3[(c & 0xFF).astype(jnp.int32)]
            ^ t2[((c >> 8) & 0xFF).astype(jnp.int32)]
            ^ t1[((c >> 16) & 0xFF).astype(jnp.int32)]
            ^ t0[(c >> 24).astype(jnp.int32)]
        )
        return jnp.where(i < nwords, c2, crc)

    crc = jnp.full(offs.shape, 0xFFFFFFFF, dtype=jnp.uint32)
    crc = lax.fori_loop(0, max_words, word_step, crc)
    # Bytewise tail: members whose length is not a word multiple have ≤3
    # trailing bytes (static unroll).
    for k in range(3):
        pos = nwords * 4 + k
        b = byte_at(offs + pos)
        c2 = (crc >> 8) ^ t0[((crc ^ b) & 0xFF).astype(jnp.int32)]
        crc = jnp.where(pos < lens, c2, crc)
    return crc ^ jnp.uint32(0xFFFFFFFF)


_crc32_kernel = functools.partial(jax.jit, static_argnums=(3,))(_crc32_core)
#: The donating twin: the stream argument's buffer is donated to the
#: launch, so the CRC column's allocation may reuse the gathered part
#: stream's HBM — the CRC is the stream's *last* reader on the
#: device-resident write path (``ops.flate.bgzf_compress_device`` orders
#: deflate → tier-downs → CRC), which makes this the gather→deflate
#: seam's buffer-donation point: after the CRC dispatch the part's
#: uncompressed bytes hold no HBM the consumer can't reuse.
_crc32_kernel_donating = functools.partial(
    jax.jit, static_argnums=(3,), donate_argnums=(0,)
)(_crc32_core)


def crc32_device(stream, offs, lens, donate: bool = False) -> jax.Array:
    """Per-member CRC32 over a device-resident byte stream.

    ``stream``: uint8 device array (or anything ``jnp.asarray`` accepts);
    ``offs``/``lens``: int member windows (host numpy — they are O(members)
    and ride up with the launch).  Returns a device uint32 [n_members]
    column; the caller downloads 4 bytes per member, never the payload.

    Launch shapes are pow2-bucketed on both the member count and the word
    loop so distinct jit signatures stay few (the shared-geometry stance
    of the codec kernels).

    ``donate=True`` donates the stream buffer to the launch (the caller
    must be the stream's final reader): requested only when the backend
    supports donation (``utils.backend.donation_supported``), silently a
    plain launch otherwise."""
    offs = np.asarray(offs, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    n = len(offs)
    if n == 0:
        return jnp.zeros((0,), jnp.uint32)
    if int(stream.shape[0]) == 0 or int(lens.max()) == 0:
        # Nothing to fold: every member is empty (zlib.crc32(b"") == 0);
        # also sidesteps gathering from a zero-length stream.
        return jnp.zeros((n,), jnp.uint32)
    if int(offs.max()) + int(lens.max()) > 2**31 - 8:
        raise ValueError("crc32_device: stream outside the int32 domain")
    B = _pow2_at_least(n, 8)
    offs_p = np.zeros(B, dtype=np.int32)
    lens_p = np.zeros(B, dtype=np.int32)
    offs_p[:n] = offs
    lens_p[:n] = lens
    max_words = _pow2_at_least(max(int(lens.max()) >> 2, 1), 64)
    if donate:
        from ...utils.backend import donation_supported

        donate = donation_supported()
    kernel = _crc32_kernel_donating if donate else _crc32_kernel
    out = kernel(
        jnp.asarray(stream), jnp.asarray(offs_p), jnp.asarray(lens_p),
        max_words,
    )
    return out[:n]
