"""Lockstep-lane Pallas inflate for literal-only fixed-Huffman members.

The first production slice of the lockstep-lane decoder design measured
by ops/pallas/inflate_probe.py: up to 128 BGZF members ride the 128
vector lanes of one kernel, each walking its own DEFLATE bit stream
serially — per-lane bit cursors, window extraction as dense iota-compare
column reductions over the transposed [words, 128] stream tile, fixed-
table decode as pure elementwise arithmetic.

Scope: single-block btype=01 members whose symbols are literals + EOB —
exactly what the device deflate (ops/flate.py deflate_fixed) emits, so
device-compressed BGZF round-trips entirely through Pallas.  The
restriction buys the key structural win: every token emits exactly ONE
byte, so the output row equals the wave index and all 128 lanes store
through one aligned full-row write every 4 waves — no scatter anywhere.
A member using length/distance codes (symbols 257+) or a non-01 block
header flags itself invalid and tiers down to the general XLA decoder
(ops/flate.py), same stance as every other fallback in the codec.

Oracle: zlib via spec/bgzf.py; tests run the kernel in interpret mode on
CPU and compare byte-for-byte.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128


def _kernel_factory(R: int, T: int):
    """R stream words per lane; T output bytes capacity (waves)."""

    def kernel(streams_ref, nbits_ref, out_ref, count_ref, ok_ref):
        rows = lax.broadcasted_iota(jnp.int32, (R, LANES), 0)

        def word_at(widx):
            onehot = rows == widx  # [R,128]
            return jnp.sum(
                jnp.where(onehot, streams_ref[:, :], 0),
                axis=0,
                keepdims=True,
            ).astype(jnp.uint32)

        def window(cur):
            widx = cur >> 5
            w0 = word_at(widx)
            w1 = word_at(widx + 1)
            sh = (cur & 31).astype(jnp.uint32)
            return jnp.where(
                sh == 0, w0, (w0 >> sh) | (w1 << (32 - sh))
            )

        nbits = nbits_ref[:, :]
        # Block header: bfinal=1, btype=01 → low 3 bits 0b011.
        hdr = window(jnp.zeros((1, LANES), jnp.int32))
        ok = (hdr & 7) == 3
        cur = jnp.full((1, LANES), 3, jnp.int32)
        done = ~ok  # invalid members stop immediately

        def body(t, state):
            cur, done, ok, word_acc, count = state
            w = window(cur)
            # Fixed-Huffman decode: reverse the next 9 stream bits
            # (codes are MSB-first), then classify by canonical ranges.
            rev = jnp.zeros((1, LANES), jnp.uint32)
            for k in range(9):
                rev = rev | (((w >> k) & 1) << (8 - k))
            c7 = (rev >> 2).astype(jnp.int32)
            c8 = (rev >> 1).astype(jnp.int32)
            c9 = rev.astype(jnp.int32)
            is7 = c7 <= 0x17          # symbols 256-279 (len 7)
            is_eob = c7 == 0
            is8 = (~is7) & (c8 >= 0x30) & (c8 <= 0xBF)  # literals 0-143
            # 280-287 are EXACTLY 0xC0-0xC7: the 9-bit literals
            # (0x190-0x1FF) share the 0xC8+ 8-bit prefixes.
            is8_len = (~is7) & (c8 >= 0xC0) & (c8 <= 0xC7)
            is9 = (~is7) & (~is8) & (~is8_len)          # literals 144-255
            lit = jnp.where(
                is8, c8 - 0x30, jnp.where(is9, c9 - 0x190 + 144, 0)
            )
            # Literal-only contract: a non-EOB 7-bit symbol (257-279) or
            # an 8-bit length symbol means LZ77 — tier down.
            bad = (is7 & ~is_eob) | is8_len
            adv = jnp.where(is7, 7, jnp.where(is8, 8, 9))
            live = ~done
            ok = ok & (~live | ~bad)
            emits = live & ~bad & ~is_eob
            # All emitting lanes write output byte t: pack into a word
            # register, flush the full row every 4th wave (aligned).
            byte = jnp.where(emits, lit, 0).astype(jnp.uint32)
            word_acc = word_acc | (byte << (8 * (t & 3)))
            @pl.when((t & 3) == 3)
            def _():
                out_ref[pl.ds(t >> 2, 1), :] = word_acc.astype(jnp.int32)
            word_acc = jnp.where((t & 3) == 3, 0, word_acc)
            count = count + emits.astype(jnp.int32)
            done_now = live & (bad | is_eob)
            # The EOB must end inside the member's real bit length.
            ok = ok & (
                ~done_now | (cur + adv <= nbits)
            )
            done = done | done_now
            cur = jnp.where(live & ~bad & ~is_eob, cur + adv, cur)
            # Consume the EOB itself so the final cursor check holds.
            cur = jnp.where(live & is_eob, cur + 7, cur)
            return cur, done, ok, word_acc, count

        word_acc0 = jnp.zeros((1, LANES), jnp.uint32)
        count0 = jnp.zeros((1, LANES), jnp.int32)
        cur, done, ok, word_acc, count = lax.fori_loop(
            0, T, body, (cur, done, ok, word_acc0, count0)
        )
        # Flush the trailing partial word.  Row T>>2: the partial row when
        # T%4 != 0, else the spare row past the last full flush (writing
        # (T-1)>>2 would zero a row the loop already flushed).
        out_ref[pl.ds(T >> 2, 1), :] = word_acc.astype(jnp.int32)
        ok = ok & done  # never reached EOB within T waves → invalid
        count_ref[:, :] = count
        ok_ref[:, :] = ok.astype(jnp.int32)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("r_words", "t_waves", "interpret")
)
def _launch(streams, nbits, r_words: int, t_waves: int, interpret: bool):
    kernel = _kernel_factory(r_words, t_waves)
    out_rows = -(-t_waves // 4) + 1
    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((out_rows, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
        ),
        interpret=interpret,
    )(streams, nbits)


#: VMEM budget for one launch (streams + output tiles + headroom).  The
#: whole member rides VMEM in this slice, so members past the budget
#: come back ok=False and tier down to the XLA decoder; a windowed
#: HBM-streaming variant is the follow-up that lifts the cap.
_VMEM_BUDGET_BYTES = 10 << 20


def inflate_fixed_literal(
    comp: np.ndarray,
    clens: np.ndarray,
    isizes: np.ndarray,
    interpret=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched lockstep inflate of literal-only fixed-Huffman members.

    ``comp`` uint8 [B, C] (rows zero-padded), ``clens``/``isizes`` int32
    [B].  Returns ``(out uint8 [B, max_isize], ok bool [B])`` — a member
    that violates the literal-only/single-block contract, exceeds the
    VMEM budget, or whose output disagrees in length comes back
    ``ok=False`` and the caller tiers down to the general decoder.
    """
    from ..flate import _pow2_at_least

    B, C = comp.shape
    if B == 0:
        return np.empty((0, 0), np.uint8), np.empty(0, bool)
    max_out = int(isizes.max()) if len(isizes) else 0
    t_waves = _pow2_at_least(max_out + 4, 64)
    r_words = _pow2_at_least(-(-C // 4) + 2, 64)
    vmem = (r_words + t_waves // 4 + 1) * LANES * 4
    if vmem > _VMEM_BUDGET_BYTES:
        return (
            np.zeros((B, max_out), np.uint8),
            np.zeros(B, dtype=bool),
        )
    out = np.empty((B, max_out), dtype=np.uint8)
    ok_all = np.empty(B, dtype=bool)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    for g0 in range(0, B, LANES):
        g1 = min(B, g0 + LANES)
        n = g1 - g0
        # Transpose the group: member j's words go down lane j.
        grp = np.zeros((r_words * 4, LANES), dtype=np.uint8)
        grp[:C, :n] = comp[g0:g1].T
        words = (
            grp.reshape(r_words, 4, LANES)
            .astype(np.uint32)
            * (np.uint32(1) << (8 * np.arange(4, dtype=np.uint32)))[
                None, :, None
            ]
        ).sum(axis=1).astype(np.uint32).view(np.int32)
        nbits = np.zeros((1, LANES), dtype=np.int32)
        nbits[0, :n] = clens[g0:g1] * 8
        o, cnt, okk = _launch(
            jnp.asarray(words), jnp.asarray(nbits), r_words, t_waves,
            bool(interpret),
        )
        o = np.asarray(o)
        cnt = np.asarray(cnt)[0]
        okk = np.asarray(okk)[0].astype(bool)
        # Un-transpose: lane j's packed words → member j's bytes.
        by = o.view(np.uint32).astype(np.uint32)
        bytes_mat = np.empty((t_waves, LANES), dtype=np.uint8)
        rows = by[: -(-t_waves // 4) + 1]
        for k in range(4):
            sel = np.arange(k, t_waves, 4)
            bytes_mat[sel] = ((rows[: len(sel)] >> (8 * k)) & 0xFF).astype(
                np.uint8
            )
        for j in range(n):
            i = g0 + j
            okj = okk[j] and int(cnt[j]) == int(isizes[i])
            ok_all[i] = okj
            if okj:
                out[i, : isizes[i]] = bytes_mat[: isizes[i], j]
            else:
                out[i, :] = 0
    return out, ok_all
