"""Lockstep-lane Pallas DEFLATE *encoder*: LZ77 match-finding on chip,
HBM-streaming token emit.

The symmetric counterpart to ops/pallas/inflate_lanes.py, and the removal
of the last codec stage still host-bound (BENCH_NOTES standing ranking:
part-write deflate ≈ 38% of host wall at the zlib level-1 ceiling).  Up to
128 BGZF member payloads ride the 128 vector lanes of one kernel; each
lane runs a greedy hash-table LZ77 match-finder over its own member, and
the resulting token streams are bit-packed into fixed-Huffman DEFLATE by
the same gather-only emit trick :func:`ops.flate.deflate_fixed` uses
(token bit-lengths → cumsum offsets → per-output-bit searchsorted) —
lifted from bytes to tokens.

Architecture (probe/inflate register/VMEM-resident style — per-lane row
selects are dense iota-compare column reductions, never gathers):

- member payloads live TRANSPOSED in VMEM ([words, 128]: member j's words
  go down lane j); "read 4 bytes at my cursor" is two one-hot row selects;
- per-lane hash tables (4-byte hash heads, two generations for bounded
  chain probes) live as [H, 128] scratch columns that persist across grid
  steps, so the match window spans everything already scanned (clamped to
  DEFLATE's 32 KiB distance domain at probe time);
- match-finding is a state machine in lockstep waves: every wave each
  live lane either (a) hashes the 4 bytes at its cursor, probes the two
  head generations, and on a 32-bit match enters extend mode, else emits
  one literal token; or (b) extends its current match word-at-a-time
  (XOR + leading-equal-byte count) until mismatch / member end /
  MAX_MATCH, then emits one copy token (min match 4);
- **streaming geometry**: the kernel grids over fixed-size INPUT chunks
  (``chunk_bytes`` of payload per lane per grid step).  Tokens emitted
  during a step land in that step's token tile (one per int32 row:
  literals as the byte value, copies as ``(1<<30)|(len<<16)|dist``) which
  streams out to the HBM-backed token array as the grid advances; a
  per-step count row records how many rows of each tile are live.  The
  per-lane cursor/match state persists in scratch, so a match may start
  in one chunk and emit in the next — only the token *tiles* are bounded,
  never the member;
- the ragged per-chunk token segments are re-compacted device-side (a
  cumsum + searchsorted + one take_along_axis gather — no host bounce)
  and the fixed-Huffman bit pack runs as a plain XLA program on the
  compacted token rows, exactly the :func:`ops.flate.deflate_fixed`
  shape.

A full-size BGZF member payload (up to ``_MAX_MEMBER`` = 64 KiB, which
covers the ~57 KiB ``DEV_MAX_PAYLOAD`` blocking the part writer uses) now
encodes on the lanes tier; the old whole-member token-column geometry
capped members at 32 KiB and in practice tiered everything past 4 KiB
down to host zlib.  Per-member ``[c_len, ok]`` meta still comes back with
the payload so a member past the cap or the VMEM budget (or an explicit
``max_clen`` output budget) tiers down to the literal-only / host-zlib
paths without dooming its launch.  Output is bit-exact decodable by
native zlib and by ``inflate_lanes`` (fixed-Huffman blocks, in-window
distances).

Oracle: zlib via tests/test_deflate_lanes.py and the streaming corpus in
tests/test_stream_codecs.py; tests run the kernel in interpret mode on
CPU and cross-check through ``zlib.decompressobj`` and the lanes decoder
byte-for-byte.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..flate import DIST_BASE, DIST_EXTRA, LEN_BASE, LEN_EXTRA

LANES = 128

MIN_MATCH = 4
MAX_MATCH = 258

#: DEFLATE's distance domain: matches may reach at most 32 KiB back.
_MAX_DIST = 1 << 15

#: Hard cap on member payload bytes: the copy-token dist field is 16 bits
#: (distances themselves are clamped to ``_MAX_DIST`` at probe time), so
#: the member size is bounded only by the token field widths and the
#: streaming geometry — 64 KiB covers the BGZF payload maximum.
_MAX_MEMBER = 1 << 16

#: Hash-table rows per generation (two generations = bounded chain probes).
_HASH_ROWS = 2048

#: VMEM budget for one launch (streams + heads + one token tile).
#: ~16 MiB/core physical on the target parts; leave compiler headroom.
#: Members whose geometry exceeds it come back ok=False and tier down.
_VMEM_BUDGET_BYTES = 14 << 20

#: Default input chunk per lane per grid step.
_DEFAULT_CHUNK = 4096

# Packed per-lane register rows in the ``st`` scratch bank.
_R_CUR = 0    # input byte cursor
_R_MODE = 1   # 1 = extending a match
_R_MPOS = 2   # match source position
_R_MLEN = 3   # match length so far
_R_NTOK = 4   # tokens emitted (member total)
_ST_ROWS = 8


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _geometry(P: int, chunk: int) -> dict:
    """Static launch geometry for a member capacity ``P`` (a multiple of
    ``chunk``): resident stream words, hash rows, token tile rows, grid
    depth and the per-step wave budget."""
    W = P // 4 + 8
    H = min(_HASH_ROWS, max(256, P))
    n_chunks = max(1, P // chunk)
    tok_tile = chunk + 8
    t_step = 2 * chunk + 96
    return {
        "w": W,
        "h": H,
        "n_chunks": n_chunks,
        "tok_tile": tok_tile,
        "t_step": t_step,
        "chunk": chunk,
    }


def _vmem_bytes(P: int, chunk: int = _DEFAULT_CHUNK) -> int:
    g = _geometry(P, chunk)
    return (
        g["w"] + 2 * g["h"] + g["tok_tile"] + _ST_ROWS + 512
    ) * LANES * 4


def accepts(max_plen: int, chunk_bytes: int = _DEFAULT_CHUNK) -> Tuple[bool, str]:
    """Would the streaming lanes encoder take a member of this payload
    size?  Pure host logic; ``(True, "")`` or ``(False, reason)`` with
    reason in ``{"size", "vmem"}``.  Full-size BGZF payloads (up to the
    part writer's ``DEV_MAX_PAYLOAD`` blocking) are accepted."""
    if max_plen > _MAX_MEMBER:
        return False, "size"
    P = _round_up(max(max_plen, 1), chunk_bytes)
    if _vmem_bytes(P, chunk_bytes) > _VMEM_BUDGET_BYTES:
        return False, "vmem"
    return True, ""


def _kernel_factory(
    W: int, H: int, TOK_TILE: int, IC_BYTES: int, T_STEP: int
):
    """One lockstep LZ77 match-finding wave per loop step; every live lane
    emits at most one token per wave.  Per grid step a lane advances its
    cursor to the step's input chunk boundary (matches may overrun it);
    the wave budget is bounded by the chunk size (literals advance 1
    byte/wave; a copy of length L costs ≤ L/4 + 2 waves end to end)."""
    HB = H.bit_length() - 1

    def kernel(
        streams_ref, plen_ref, tok_ref, cnt_ref, ntok_ref, ok_ref,
        h1_ref, h2_ref, st_ref,
    ):
        k = pl.program_id(0)
        rows_W = lax.broadcasted_iota(jnp.int32, (W, LANES), 0)
        rows_H = lax.broadcasted_iota(jnp.int32, (H, LANES), 0)
        rows_T = lax.broadcasted_iota(jnp.int32, (TOK_TILE, LANES), 0)
        rows_st = lax.broadcasted_iota(jnp.int32, (_ST_ROWS, LANES), 0)
        plen = plen_ref[:, :]

        @pl.when(k == 0)
        def _init():
            h1_ref[:, :] = jnp.zeros((H, LANES), jnp.int32)
            h2_ref[:, :] = jnp.zeros((H, LANES), jnp.int32)
            st_ref[:, :] = jnp.zeros((_ST_ROWS, LANES), jnp.int32)

        tok_ref[:, :] = jnp.zeros((TOK_TILE, LANES), jnp.int32)
        chunk_end = (k + 1) * IC_BYTES

        def word_at(widx):
            onehot = rows_W == widx
            return jnp.sum(
                jnp.where(onehot, streams_ref[:, :], 0),
                axis=0,
                keepdims=True,
            ).astype(jnp.uint32)

        def bytes4_at(bpos):
            """32 input bits at per-lane BYTE offset ``bpos`` [1,128]
            (LE; out-of-range rows read as zero)."""
            widx = bpos >> 2
            sh = ((bpos & 3) * 8).astype(jnp.uint32)
            w0 = word_at(widx)
            w1 = word_at(widx + 1)
            return jnp.where(sh == 0, w0, (w0 >> sh) | (w1 << (32 - sh)))

        st = st_ref[:, :]
        cur0 = st[_R_CUR : _R_CUR + 1, :]
        mode0 = st[_R_MODE : _R_MODE + 1, :] == 1
        mpos0 = st[_R_MPOS : _R_MPOS + 1, :]
        mlen0 = st[_R_MLEN : _R_MLEN + 1, :]
        ntok0 = st[_R_NTOK : _R_NTOK + 1, :]
        tok_base = ntok0

        def body(s):
            (it, cur, mode, mpos, mlen, ntok) = s
            finished = cur >= plen
            capacity = (ntok - tok_base) < TOK_TILE
            extending = ~finished & capacity & mode
            scanning = ~finished & capacity & ~mode & (cur < chunk_end)

            # Shared window read: scan lanes look at their cursor, extend
            # lanes at the next 4 bytes past the match so far.
            wa = bytes4_at(jnp.where(extending, cur + mlen, cur))

            # ---- scan: 4-byte hash, two-generation probe, insert -------
            canh = scanning & (cur + MIN_MATCH <= plen)
            hsh = (
                (wa * jnp.uint32(0x9E3779B1)) >> jnp.uint32(32 - HB)
            ).astype(jnp.int32)
            h1v = h1_ref[:, :]
            h2v = h2_ref[:, :]
            sel1 = jnp.sum(
                jnp.where(rows_H == hsh, h1v, 0), axis=0, keepdims=True
            )
            sel2 = jnp.sum(
                jnp.where(rows_H == hsh, h2v, 0), axis=0, keepdims=True
            )
            upd = (rows_H == hsh) & canh
            h2_ref[:, :] = jnp.where(upd, sel1, h2v)  # age the prev head
            h1_ref[:, :] = jnp.where(upd, cur + 1, h1v)  # pos+1; 0 = empty
            c1 = sel1 - 1
            c2 = sel2 - 1
            wc1 = bytes4_at(c1)
            wc2 = bytes4_at(c2)
            # Candidates must sit inside DEFLATE's 32 KiB distance window.
            m1 = canh & (c1 >= 0) & (cur - c1 <= _MAX_DIST) & (wc1 == wa)
            m2 = canh & (c2 >= 0) & (cur - c2 <= _MAX_DIST) & (wc2 == wa)
            mstart = m1 | m2
            mp_new = jnp.where(m1, c1, c2)  # prefer the nearer candidate

            # ---- extend: word-at-a-time leading-equal-byte count -------
            wb = bytes4_at(jnp.where(extending, mpos + mlen, 0))
            x = wa ^ wb
            nm = jnp.where(
                (x & 0xFF) != 0,
                0,
                jnp.where(
                    (x & 0xFF00) != 0,
                    1,
                    jnp.where(
                        (x & 0xFF0000) != 0,
                        2,
                        jnp.where((x >> 24) != 0, 3, 4),
                    ),
                ),
            )
            remaining = jnp.minimum(plen - (cur + mlen), MAX_MATCH - mlen)
            add = jnp.maximum(jnp.minimum(nm, remaining), 0)
            mlen2 = mlen + add
            ext_done = extending & (add < 4)

            # ---- token emit (at most one per lane per wave) ------------
            emit_lit = scanning & ~mstart
            lit = (wa & 0xFF).astype(jnp.int32)
            cpy = (jnp.int32(1) << 30) | (mlen2 << 16) | (cur - mpos)
            tv = jnp.where(ext_done, cpy, lit)
            emit = emit_lit | ext_done
            trow = ntok - tok_base
            tok_ref[:, :] = jnp.where(
                (rows_T == trow) & emit, tv, tok_ref[:, :]
            )
            ntok = ntok + emit.astype(jnp.int32)
            cur = (
                cur
                + jnp.where(emit_lit, 1, 0)
                + jnp.where(ext_done, mlen2, 0)
            )
            mode = jnp.where(mstart, True, jnp.where(ext_done, False, mode))
            mpos = jnp.where(mstart, mp_new, mpos)
            mlen = jnp.where(
                mstart, MIN_MATCH, jnp.where(extending, mlen2, mlen)
            )
            return (it + 1, cur, mode, mpos, mlen, ntok)

        def cond(s):
            (it, cur, mode, mpos, mlen, ntok) = s
            act = (cur < plen) & ((ntok - tok_base) < TOK_TILE) & (
                mode | (cur < chunk_end)
            )
            return (it < T_STEP) & jnp.any(act)

        (_, cur, mode, mpos, mlen, ntok) = lax.while_loop(
            cond, body, (jnp.int32(0), cur0, mode0, mpos0, mlen0, ntok0)
        )

        stw = jnp.zeros((_ST_ROWS, LANES), jnp.int32)

        def setreg(stw, r, v):
            return jnp.where(
                rows_st == r, jnp.broadcast_to(v, stw.shape), stw
            )

        stw = setreg(stw, _R_CUR, cur)
        stw = setreg(stw, _R_MODE, mode.astype(jnp.int32))
        stw = setreg(stw, _R_MPOS, mpos)
        stw = setreg(stw, _R_MLEN, mlen)
        stw = setreg(stw, _R_NTOK, ntok)
        st_ref[:, :] = stw
        cnt_ref[:, :] = ntok - tok_base
        ntok_ref[:, :] = ntok
        ok_ref[:, :] = (cur == plen).astype(jnp.int32)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("w", "h", "n_chunks", "tok_tile", "chunk", "t_step",
                     "interpret"),
)
def _launch(streams, plens, w: int, h: int, n_chunks: int, tok_tile: int,
            chunk: int, t_step: int, interpret: bool):
    kernel = _kernel_factory(w, h, tok_tile, chunk, t_step)
    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(
                (tok_tile, LANES), lambda k: (k, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, LANES), lambda k: (k, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, LANES), lambda k: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, LANES), lambda k: (0, 0), memory_space=pltpu.VMEM
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_chunks * tok_tile, LANES), jnp.int32),
            jax.ShapeDtypeStruct((n_chunks, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((h, LANES), jnp.int32),
            pltpu.VMEM((h, LANES), jnp.int32),
            pltpu.VMEM((_ST_ROWS, LANES), jnp.int32),
        ],
        interpret=interpret,
    )(streams, plens)


# --------------------------------------------------------------------------
# Ragged token compaction + fixed-Huffman bit pack: plain XLA on the
# kernel's chunked token tiles, device-to-device — tokens never bounce
# through the host.
# --------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(2, 3))
def _compact_tokens(tok_flat: jax.Array, cnts: jax.Array, tok_tile: int,
                    T: int) -> jax.Array:
    """Gather the per-chunk ragged token segments into dense per-lane
    rows.

    ``tok_flat``: int32 [n_chunks*tok_tile, 128] (chunk k's tokens for
    lane j at rows [k*tok_tile, k*tok_tile+cnts[k,j])), ``cnts``: int32
    [n_chunks, 128].  Returns int32 [128, T] (rows = lanes; garbage past
    each lane's total count — the emit masks by ntok)."""
    cum = jnp.cumsum(cnts, axis=0)  # [n_chunks, 128]
    t = jnp.arange(T, dtype=jnp.int32)
    # Chunk holding token t of each lane, then its offset inside it.
    ch = jax.vmap(functools.partial(jnp.searchsorted, side="right"))(
        cum.T, jnp.broadcast_to(t, (LANES, T))
    ).astype(jnp.int32)  # [128, T]
    n_chunks = cnts.shape[0]
    ch_c = jnp.clip(ch, 0, n_chunks - 1)
    prev = jnp.where(
        ch_c > 0,
        jnp.take_along_axis(
            cum.T, jnp.maximum(ch_c - 1, 0), axis=1
        ),
        0,
    )
    row = ch_c * tok_tile + (t[None, :] - prev)
    row = jnp.clip(row, 0, tok_flat.shape[0] - 1)
    return jnp.take_along_axis(tok_flat, row.T, axis=0).T


def _rev_var(code, n, width: int):
    """Bit-reverse the low ``width`` bits of ``code``, then keep the top
    ``n`` of them: MSB-first Huffman codes → LSB-first stream patterns."""
    r = jnp.zeros_like(code)
    for k in range(width):
        r = r | (((code >> k) & 1) << (width - 1 - k))
    return r >> (width - n)


@functools.partial(jax.jit, static_argnums=(2,))
def _emit_tokens_fixed(tokens: jax.Array, ntok: jax.Array, out_bytes: int):
    """Pack token streams into final fixed-Huffman DEFLATE members.

    ``tokens``: int32 [b, T] packed (lit: byte value; copy:
    ``(1<<30)|(len<<16)|dist``), ``ntok``: int32 [b] live token counts
    (the EOB is appended at index ntok, so T must be ≥ max(ntok)+1).
    Returns (comp uint8 [b, out_bytes], clens int32 [b]).
    """
    b, T = tokens.shape
    len_base = jnp.asarray(LEN_BASE)
    len_extra = jnp.asarray(LEN_EXTRA)
    dist_base = jnp.asarray(DIST_BASE)
    dist_extra = jnp.asarray(DIST_EXTRA)

    is_cpy = (tokens >> 30) & 1 == 1
    v = tokens & 0xFF
    L = (tokens >> 16) & 0x1FF
    D = tokens & 0xFFFF
    # Literal codeword (RFC 1951 §3.2.6).
    lit_hi = v >= 144
    lit_code = jnp.where(lit_hi, 0x190 + (v - 144), 0x30 + v)
    lit_n = jnp.where(lit_hi, 9, 8)
    pat_lit = _rev_var(lit_code, lit_n, 9)
    # Copy: length code + extra, 5-bit distance code + extra.
    li = jnp.clip(
        jnp.searchsorted(len_base, L, side="right").astype(jnp.int32) - 1,
        0,
        28,
    )
    sym_l = 257 + li
    len_code = jnp.where(sym_l <= 279, sym_l - 256, 0xC0 + (sym_l - 280))
    len_n = jnp.where(sym_l <= 279, 7, 8)
    e1 = len_extra[li]
    ev1 = jnp.clip(L - len_base[li], 0, None)
    di = jnp.clip(
        jnp.searchsorted(dist_base, D, side="right").astype(jnp.int32) - 1,
        0,
        29,
    )
    e2 = dist_extra[di]
    ev2 = jnp.clip(D - dist_base[di], 0, None)
    pat_cpy = (
        _rev_var(len_code, len_n, 8)
        | (ev1 << len_n)
        | (_rev_var(di, jnp.full_like(di, 5), 5) << (len_n + e1))
        | (ev2 << (len_n + e1 + 5))
    )
    nbits_tok = jnp.where(is_cpy, len_n + e1 + 5 + e2, lit_n)
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    live = t < ntok[:, None]
    eob = t == ntok[:, None]
    nbits = jnp.where(live, nbits_tok, jnp.where(eob, 7, 0))
    pattern = jnp.where(live, jnp.where(is_cpy, pat_cpy, pat_lit), 0)

    cum = jnp.cumsum(nbits, axis=1)
    ends = cum + 3  # 3 header bits (bfinal=1, btype=01)
    off = ends - nbits
    nbits_total = 3 + cum[:, -1]
    NB = out_bytes * 8
    j = jnp.arange(NB, dtype=jnp.int32)[None, :]
    src = jax.vmap(functools.partial(jnp.searchsorted, side="right"))(
        ends, jnp.broadcast_to(j, (b, NB))
    ).astype(jnp.int32)
    src_c = jnp.clip(src, 0, T - 1)
    pat_j = jnp.take_along_axis(pattern, src_c, axis=1)
    nb_j = jnp.take_along_axis(nbits, src_c, axis=1)
    off_j = jnp.take_along_axis(off, src_c, axis=1)
    k = j - off_j
    in_code = (src < T) & (k >= 0) & (k < nb_j)
    bit = jnp.where(in_code, (pat_j >> jnp.clip(k, 0, 31)) & 1, 0)
    bit = jnp.where(j < 2, 1, bit).astype(jnp.uint8)
    weights = (1 << jnp.arange(8, dtype=jnp.uint8)).astype(jnp.uint8)
    comp = (
        (bit.reshape(b, out_bytes, 8) * weights[None, None, :])
        .sum(axis=2)
        .astype(jnp.uint8)
    )
    clens = (nbits_total + 7) // 8
    return comp, clens


def _out_bytes(P: int) -> int:
    """Static output width: literals cost ≤9 bits/byte and copies strictly
    less per covered byte, so the deflate_fixed bound holds for tokens."""
    return (3 + 9 * P + 7 + 7) // 8 + 1


def _pow2_at_least(n: int, lo: int) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


@functools.partial(jax.jit, static_argnums=(3,))
def _words_from_stream(
    stream: jax.Array, offs: jax.Array, lens: jax.Array, W: int
) -> jax.Array:
    """Build one group's transposed word layout ([W, 128]: member j's
    words down lane j) straight from an HBM-resident byte stream — the
    device-input mirror of the host-side transpose in
    :func:`deflate_lanes`, so the payload never visits the host."""
    S = stream.shape[0]
    i = jnp.arange(W * 4, dtype=jnp.int32)[:, None]
    idx = jnp.clip(offs[None, :] + i, 0, S - 1)
    b = jnp.where(i < lens[None, :], stream[idx], 0).astype(jnp.uint32)
    shifts = (jnp.uint32(1) << (8 * jnp.arange(4, dtype=jnp.uint32)))
    w = (b.reshape(W, 4, LANES) * shifts[None, :, None]).sum(
        axis=1, dtype=jnp.uint32
    )
    return jax.lax.bitcast_convert_type(w, jnp.int32)


def _encode_group(
    words_dev, plens_np: np.ndarray, n: int, g: dict, out_bytes: int,
    interpret: bool, emit_step: int,
):
    """Match-kernel launch + device token compaction + fixed-Huffman pack
    for one ≤128-lane group whose words are already in the transposed
    layout (host- or device-built).  Returns (comp [n, out_bytes] uint8,
    clens int32 [n], ok bool [n]) as host arrays — only the compressed
    rows come back d2h."""
    from ...utils.tracing import count_d2h

    plens = np.zeros((1, LANES), dtype=np.int32)
    plens[0, :n] = plens_np
    toks, cnts, ntok, okk = _launch(
        words_dev, jnp.asarray(plens), g["w"], g["h"], g["n_chunks"],
        g["tok_tile"], g["chunk"], g["t_step"], bool(interpret),
    )
    ntok_np = np.asarray(ntok)[0]
    T = _pow2_at_least(int(ntok_np.max()) + 1, 256)
    tok_bt = _compact_tokens(toks, cnts, g["tok_tile"], T)
    ntok_vec = ntok[0]
    comp = np.zeros((n, out_bytes), dtype=np.uint8)
    clens = np.zeros(n, dtype=np.int32)
    for r0 in range(0, n, emit_step):
        r1 = min(n, r0 + emit_step)
        c, cl = _emit_tokens_fixed(
            tok_bt[r0:r1], ntok_vec[r0:r1], out_bytes
        )
        comp[r0:r1] = np.asarray(c)
        clens[r0:r1] = np.asarray(cl)
    count_d2h(comp.nbytes, "deflate_comp")
    ok = np.asarray(okk)[0, :n].astype(bool)
    return comp, clens, ok


def deflate_lanes(
    payload: np.ndarray,
    lens: np.ndarray,
    max_clen: Optional[int] = None,
    chunk_bytes: int = _DEFAULT_CHUNK,
    interpret=None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Batched lockstep LZ77 + fixed-Huffman DEFLATE of member payloads,
    128 members per kernel launch, token stream chunked out to HBM.

    ``payload`` uint8 [B, P] (rows zero-padded), ``lens`` int32 [B].
    Returns ``(comp uint8 [B, out_bytes], clens int32 [B], ok bool [B])``
    — every compressed row is a complete final DEFLATE member (header +
    tokens + EOB) decodable by ``zlib.decompressobj(-15)`` and by
    ``inflate_lanes``.  Full-size BGZF payloads (≤ ``_MAX_MEMBER``) ride
    the streaming geometry; a member past the cap or the VMEM budget, or
    whose compressed size exceeds ``max_clen``, comes back ``ok=False``
    and the caller tiers down to the literal-only / host-zlib encoders.
    ``chunk_bytes`` sets the per-lane input chunk per grid step."""
    from ..flate import _MAX_LAUNCH_ELEMS

    B = payload.shape[0]
    if B == 0:
        return (
            np.zeros((0, 0), np.uint8),
            np.zeros(0, np.int32),
            np.zeros(0, bool),
        )
    lens = np.asarray(lens, dtype=np.int32)
    max_len = int(lens.max()) if len(lens) else 0
    P = _round_up(max(max_len, 1), chunk_bytes)
    out_bytes = _out_bytes(P)
    comp = np.zeros((B, out_bytes), dtype=np.uint8)
    clens = np.zeros(B, dtype=np.int32)
    ok_all = np.zeros(B, dtype=bool)
    if max_len > _MAX_MEMBER or _vmem_bytes(P, chunk_bytes) > _VMEM_BUDGET_BYTES:
        return comp, clens, ok_all
    g = _geometry(P, chunk_bytes)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    NB = out_bytes * 8
    emit_step = max(1, _MAX_LAUNCH_ELEMS // NB)
    from ...utils.tracing import count_h2d

    for g0 in range(0, B, LANES):
        g1 = min(B, g0 + LANES)
        n = g1 - g0
        # Transpose the group: member j's words go down lane j.
        grp = np.zeros((g["w"] * 4, LANES), dtype=np.uint8)
        grp[: payload.shape[1], :n] = payload[g0:g1].T
        words = (
            grp.reshape(g["w"], 4, LANES).astype(np.uint32)
            * (np.uint32(1) << (8 * np.arange(4, dtype=np.uint32)))[
                None, :, None
            ]
        ).sum(axis=1).astype(np.uint32).view(np.int32)
        count_h2d(words.nbytes, "deflate_payload")
        c, cl, okg = _encode_group(
            jnp.asarray(words), lens[g0:g1], n, g, out_bytes,
            bool(interpret), emit_step,
        )
        comp[g0:g1] = c
        clens[g0:g1] = cl
        ok_all[g0:g1] = okg
    if max_clen is not None:
        ok_all &= clens <= max_clen
    return comp, clens, ok_all


def deflate_lanes_stream(
    stream,
    lens: np.ndarray,
    offs: Optional[np.ndarray] = None,
    max_clen: Optional[int] = None,
    chunk_bytes: int = _DEFAULT_CHUNK,
    interpret=None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """:func:`deflate_lanes` fed from an HBM-resident byte stream.

    ``stream``: device uint8 (e.g. the gathered part stream the write
    path leaves in HBM); member i's payload is
    ``stream[offs[i] : offs[i]+lens[i]]`` (``offs`` defaults to the
    back-to-back cumsum — the part writer's deterministic blocking).  The
    transposed per-group word layout is built device-side, so the only
    h2d traffic is the small offset/length columns and the only d2h
    traffic is the compressed rows — the whole point of the
    device-resident write path.  Same return contract as
    :func:`deflate_lanes`."""
    from ..flate import _MAX_LAUNCH_ELEMS

    lens = np.asarray(lens, dtype=np.int32)
    B = len(lens)
    if B == 0:
        return (
            np.zeros((0, 0), np.uint8),
            np.zeros(0, np.int32),
            np.zeros(0, bool),
        )
    if offs is None:
        ends = np.cumsum(lens.astype(np.int64))
        offs = ends - lens
    offs = np.asarray(offs, dtype=np.int64)
    if int(jnp.asarray(stream).shape[0]) == 0:
        # Every member is empty; encode through the host-input path (a
        # zero-length device gather is ill-formed) — same bits out.
        return deflate_lanes(
            np.zeros((B, 1), np.uint8), lens,
            max_clen=max_clen, chunk_bytes=chunk_bytes,
            interpret=interpret,
        )
    max_len = int(lens.max())
    P = _round_up(max(max_len, 1), chunk_bytes)
    out_bytes = _out_bytes(P)
    comp = np.zeros((B, out_bytes), dtype=np.uint8)
    clens = np.zeros(B, dtype=np.int32)
    ok_all = np.zeros(B, dtype=bool)
    if max_len > _MAX_MEMBER or _vmem_bytes(P, chunk_bytes) > _VMEM_BUDGET_BYTES:
        return comp, clens, ok_all
    if int((offs + lens).max()) >= 2**31:
        return comp, clens, ok_all  # past the int32 gather domain
    g = _geometry(P, chunk_bytes)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    NB = out_bytes * 8
    emit_step = max(1, _MAX_LAUNCH_ELEMS // NB)
    dev = jnp.asarray(stream)
    for g0 in range(0, B, LANES):
        g1 = min(B, g0 + LANES)
        n = g1 - g0
        offs_p = np.zeros(LANES, dtype=np.int32)
        lens_p = np.zeros(LANES, dtype=np.int32)
        offs_p[:n] = offs[g0:g1]
        lens_p[:n] = lens[g0:g1]
        words = _words_from_stream(
            dev, jnp.asarray(offs_p), jnp.asarray(lens_p), g["w"]
        )
        c, cl, okg = _encode_group(
            words, lens[g0:g1], n, g, out_bytes, bool(interpret), emit_step
        )
        comp[g0:g1] = c
        clens[g0:g1] = cl
        ok_all[g0:g1] = okg
    if max_clen is not None:
        ok_all &= clens <= max_clen
    return comp, clens, ok_all


# --------------------------------------------------------------------------
# Bench probes (bench.py reports these per round on TPU platforms).
# --------------------------------------------------------------------------


def bench_deflate_marginal(
    p_small: int = 1024, p_big: int = 4096
) -> dict:
    """Marginal per-wave cost of the match kernel via a two-point fit.

    Same RTT-free protocol as ``inflate_probe.bench_marginal``: one
    geometry (sized for ``p_big``), two live member lengths — the wave
    count tracks the member length on literal-dominated (random) data, so
    the slope is the per-wave cost and the intercept absorbs launch/RTT.
    Reports the literal-path floor (1 byte/lane/wave); matches only go
    faster.  The XLA bit-pack stage is excluded (it is bandwidth-bound
    and embarrassingly parallel, not the serial engine being probed).
    """
    import time

    P = _round_up(p_big, _DEFAULT_CHUNK)
    g = _geometry(P, _DEFAULT_CHUNK)
    rng = np.random.default_rng(0)
    words = jnp.asarray(
        rng.integers(0, 1 << 31, (g["w"], LANES), dtype=np.int32)
    )

    def timed(n_bytes: int) -> float:
        plens = jnp.full((1, LANES), n_bytes, jnp.int32)
        args = (words, plens, g["w"], g["h"], g["n_chunks"],
                g["tok_tile"], g["chunk"], g["t_step"], False)
        jax.block_until_ready(_launch(*args))
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(_launch(*args))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    dt_s = timed(p_small)
    dt_b = timed(p_big)
    per_wave = (dt_b - dt_s) / (p_big - p_small)
    fixed = dt_s - per_wave * p_small
    bytes_per_s = LANES / per_wave if per_wave > 0 else float("inf")
    return {
        "fixed_ms": fixed * 1e3,
        "ns_per_wave": per_wave * 1e9,
        "bytes_per_s": bytes_per_s,
        "projected_mb_s": bytes_per_s / 1e6,
        "t_small_ms": dt_s * 1e3,
        "t_big_ms": dt_b * 1e3,
    }


def _bam_like_corpus(n_members: int, member: int) -> np.ndarray:
    """Synthetic BAM-class member payloads: a fixed record template tiled
    with per-record position/name bytes varying — the part-write encoder's
    real workload shape (high local redundancy, short diverging fields)."""
    rng = np.random.default_rng(11)
    rec = bytearray(168)
    rec[0:4] = (164).to_bytes(4, "little")
    rec[12:36] = b"\x08\x00\x60\x12\x08\x00\x00\x00" * 3
    rec[36:45] = b"read0000\x00"
    rec[45:100] = bytes([7] * 55)
    rec[100:168] = (b"ACGT" * 17)[:68]
    n_rec = (n_members * member) // len(rec) + 1
    stream = np.tile(np.frombuffer(bytes(rec), np.uint8), n_rec)
    base = np.arange(n_rec, dtype=np.int64) * len(rec)
    pos = rng.integers(0, 1 << 26, n_rec, dtype=np.int64)
    for k in range(4):
        stream[base + 4 + k] = ((pos >> (8 * k)) & 0xFF).astype(np.uint8)
    idx = np.arange(n_rec, dtype=np.int64)
    for k in range(4):
        d = (idx >> (4 * k)) & 0xF
        stream[base + 40 + k] = (48 + d).astype(np.uint8)
    return stream[: n_members * member].reshape(n_members, member)


def bench_deflate_ratio(
    n_members: int = 32, member: int = 4096, interpret=None
) -> dict:
    """Compression ratio of the lanes encoder vs zlib level-1, same
    BAM-like corpus, same member split — bench.py tracks the relative
    ratio per round so coding-efficiency regressions are visible."""
    import zlib

    mat = _bam_like_corpus(n_members, member)
    lens = np.full(n_members, member, dtype=np.int32)
    comp, clens, ok = deflate_lanes(mat, lens, interpret=interpret)
    n_ok = int(ok.sum())
    dev_bytes = int(clens[ok].sum())
    z_bytes = 0
    orig = 0
    for i in range(n_members):
        if not ok[i]:
            continue
        co = zlib.compressobj(1, zlib.DEFLATED, -15)
        z_bytes += len(co.compress(mat[i].tobytes()) + co.flush())
        orig += member
    device_ratio = dev_bytes / orig if orig else float("inf")
    zlib1_ratio = z_bytes / orig if orig else float("inf")
    return {
        "device_ratio": device_ratio,
        "zlib1_ratio": zlib1_ratio,
        "rel_zlib1": device_ratio / zlib1_ratio if z_bytes else float("inf"),
        "n_ok": n_ok,
        "n_members": n_members,
    }
