"""Lockstep-lane Pallas decoder for rANS 4x8 (CRAM 3.0), HBM-streaming.

The third codec family on the inflate-lanes engine pattern: up to 128
compressed CRAM block payloads ride the 128 vector lanes of one kernel,
each advancing its own 4-state rANS machine in lockstep waves.  rANS is
the lockstep-friendly entropy coder — a fixed 4-way interleaved state
machine with byte-granular renormalization and no bit-serial Huffman —
so unlike DEFLATE there is no per-lane table build on chip: the order-0
/ order-1 frequency tables are tiny and parse host-side
(``spec.cram_codecs.parse_rans_plan``) into dense per-lane context banks.

Wave model (shared with the NumPy host tier in ``spec/cram_codecs.py``
— see the plan/wave notes there): global wave ``t`` decodes one byte per
lane with state ``j = t&3`` through the four quarters and ``j = 3`` in
the order-1 remainder tail; output lands in wave order and the host
de-interleaves order-1 quarters after download
(``cram_codecs.rans_deinterleave``).  Per the engine house style, every
per-lane lookup is a dense iota-compare column reduction, never a
gather:

- "my state / my last symbol" are one-hot row selects over the packed
  ``st`` register file;
- "which symbol owns slot ``m``" is a count of ``C <= m`` rows inside
  the lane's active context slab of the cumulative-frequency bank (the
  searchsorted-as-reduction idiom);
- "one renorm byte at my cursor" is a one-hot word select over the
  transposed stream bank, at most two per wave (the encoder invariants
  bound it; a stream needing more is corrupt and flips ``ok``).

**Streaming geometry**: the kernel grids over fixed-size output chunks
(``chunk_bytes`` per lane per grid step, 4 wave-bytes packed per int32
word); finished tiles stream to the HBM-backed output while the state
file persists in VMEM scratch.  Per-slice ``[n_out, ok]`` meta tiers a
slice that trips a size/VMEM/context/format gate — or that violates the
stream invariants mid-decode — down to the host tiers *per slice, never
per launch*.

Oracle: ``spec.cram_codecs.rans_decode_py`` (the original per-byte
Python decoder) via tests/test_rans_lanes.py; tests run the kernel in
interpret mode on CPU and compare byte-for-byte.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...spec import cram_codecs as _cc

LANES = 128

_RANS_L = 1 << 23
_TF_SHIFT = 12
_TOTFREQ = 1 << _TF_SHIFT

#: VMEM budget for one launch (streams + context banks + tile + state).
_VMEM_BUDGET_BYTES = 14 << 20

#: Per-slice output-size cap; past it the wrapper declines without
#: launching (tier-down reason "size").
_MAX_OSIZE = 1 << 20

#: Dense context-slab cap per slice (order-1 tables); a slice whose
#: outer table is wider tiers down with reason "ctx".  32 slabs keep the
#: two [NC*256, 128] int32 banks at 8 MiB.
_NC_CAP = 32

#: Default output chunk per lane per grid step (bytes, power of two).
_DEFAULT_CHUNK = 1024

# Packed per-lane register rows in the ``st`` scratch bank.
_S_R0 = 0        # rANS states R0..R3 in rows 0..3
_S_L0 = 4        # last-symbol (order-1 context) per state in rows 4..7
_S_P = 8         # renorm byte cursor
_S_OK = 9
_ST_ROWS = 16

# Per-lane launch meta rows.
_M_NOUT = 0
_M_4Q4 = 1       # 4*q4v: the wave index where state select locks to 3
_M_CLEN = 2
_M_R = 3         # initial states in rows 3..6
_META_ROWS = 8


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def stream_geometry(
    max_clen: int,
    max_osize: int,
    n_ctx: int,
    chunk_bytes: int = _DEFAULT_CHUNK,
) -> dict:
    """Static launch geometry (pure host math — also the tier-selection
    surface: ``vmem_bytes`` against the budget decides size-based
    tier-downs without touching a device)."""
    chunk_bytes = max(256, chunk_bytes)
    if chunk_bytes & (chunk_bytes - 1):
        raise ValueError("chunk_bytes must be a power of two")
    oc_words = chunk_bytes // 4
    r_words = _round_up(max(-(-max_clen // 4) + 2, 32), 512)
    ncb = 1
    while ncb < max(n_ctx, 1):
        ncb *= 2
    n_chunks = max(1, -(-max(max_osize, 1) // chunk_bytes))
    vmem = (
        r_words
        + 2 * ncb * 256
        + 256
        + oc_words
        + _ST_ROWS
        + _META_ROWS
        + 768
    ) * LANES * 4
    return {
        "r_words": r_words,
        "ncb": ncb,
        "oc_words": oc_words,
        "n_chunks": n_chunks,
        "vmem_bytes": vmem,
    }


def accepts(
    clen: int,
    osize: int,
    n_ctx: int,
    chunk_bytes: int = _DEFAULT_CHUNK,
) -> Tuple[bool, str]:
    """Would the lanes tier take a slice of this shape?  Returns
    ``(True, "")`` or ``(False, reason)`` with reason in
    ``{"size", "vmem", "ctx"}`` — the tier-down taxonomy
    ``cram_codecs.decompress_batch`` counts."""
    if osize > _MAX_OSIZE:
        return False, "size"
    if n_ctx > _NC_CAP:
        return False, "ctx"
    geo = stream_geometry(clen, osize, n_ctx, chunk_bytes)
    if geo["vmem_bytes"] > _VMEM_BUDGET_BYTES:
        return False, "vmem"
    return True, ""


def _kernel_factory(R_WORDS: int, NCB: int, OC_WORDS: int):
    """R_WORDS renorm-stream words/lane resident; NCB dense context
    slabs/lane; OC_WORDS output words/lane streamed per grid step."""

    def kernel(
        streams_ref, meta_ref, fbank_ref, cbank_ref, cmap_ref,
        out_ref, ok_ref, st_ref,
    ):
        k = pl.program_id(0)
        n_out = meta_ref[_M_NOUT:_M_NOUT + 1, :]
        fourq4 = meta_ref[_M_4Q4:_M_4Q4 + 1, :]
        clen = meta_ref[_M_CLEN:_M_CLEN + 1, :]
        rows_st = lax.broadcasted_iota(jnp.int32, (_ST_ROWS, LANES), 0)

        @pl.when(k == 0)
        def _init():
            st0 = jnp.zeros((_ST_ROWS, LANES), jnp.int32)
            for j in range(4):
                st0 = jnp.where(
                    rows_st == _S_R0 + j, meta_ref[_M_R + j:_M_R + j + 1, :],
                    st0,
                )
            st0 = jnp.where(rows_st == _S_OK, 1, st0)
            st_ref[:, :] = st0

        streams = streams_ref[:, :]
        fbank = fbank_ref[:, :]
        cbank = cbank_ref[:, :]
        cmap = cmap_ref[:, :]
        rows_bank = lax.broadcasted_iota(jnp.int32, (NCB * 256, LANES), 0)
        bank_ctx = lax.shift_right_logical(rows_bank, 8)
        bank_sym = rows_bank & 255
        rows_cmap = lax.broadcasted_iota(jnp.int32, (256, LANES), 0)
        rows_out = lax.broadcasted_iota(jnp.int32, (OC_WORDS, LANES), 0)
        rows_str = lax.broadcasted_iota(jnp.int32, (R_WORDS, LANES), 0)

        def strow(st, r):
            return jnp.sum(
                jnp.where(rows_st == r, st, 0), axis=0, keepdims=True
            )

        def body(w, carry):
            tile, st = carry
            word = jnp.zeros((1, LANES), jnp.int32)
            p = strow(st, _S_P)
            okv = strow(st, _S_OK)
            t0 = (k * OC_WORDS + w) * 4
            for jj in range(4):
                t = t0 + jj
                # State select: j = t&3 (== jj) in the quarters, 3 in
                # the order-1 remainder tail.
                j = jnp.where(t < fourq4, jj, 3)
                live = (t < n_out) & (okv == 1)
                Rj = jnp.sum(
                    jnp.where(rows_st == j, st, 0), axis=0, keepdims=True
                )
                lastj = jnp.sum(
                    jnp.where(rows_st == _S_L0 + j, st, 0),
                    axis=0, keepdims=True,
                )
                ci = jnp.sum(
                    jnp.where(rows_cmap == lastj, cmap, 0),
                    axis=0, keepdims=True,
                )
                # Context absent from the slice's table: invariant
                # breach — flag and let the host tiers resolve it.
                okv = jnp.where(live & (ci < 0), 0, okv)
                ci = jnp.maximum(ci, 0)
                m = Rj & (_TOTFREQ - 1)
                in_slab = bank_ctx == ci
                # searchsorted-as-reduction: the owning symbol is
                # |{s : C[s] <= m}| - 1 within the active slab.
                s = jnp.sum(
                    jnp.where(in_slab & (cbank <= m), 1, 0),
                    axis=0, keepdims=True,
                ) - 1
                s = jnp.maximum(s, 0)
                pick = in_slab & (bank_sym == s)
                Fv = jnp.sum(jnp.where(pick, fbank, 0), axis=0, keepdims=True)
                Cv = jnp.sum(jnp.where(pick, cbank, 0), axis=0, keepdims=True)
                Rn = Fv * lax.shift_right_logical(Rj, _TF_SHIFT) + m - Cv
                # Renormalize: at most two byte reads bring any valid
                # state back above L (encoder keeps post-renorm states
                # >= 2^11); still below after two means corrupt.
                for _ in range(2):
                    need = live & (Rn < _RANS_L)
                    wv = jnp.sum(
                        jnp.where(
                            rows_str == lax.shift_right_logical(p, 2),
                            streams, 0,
                        ),
                        axis=0, keepdims=True,
                    )
                    byte = lax.shift_right_logical(wv, 8 * (p & 3)) & 255
                    okv = jnp.where(need & (p >= clen), 0, okv)
                    Rn = jnp.where(need, (Rn << 8) | byte, Rn)
                    p = p + need.astype(jnp.int32)
                okv = jnp.where(live & (Rn < _RANS_L), 0, okv)
                st = jnp.where((rows_st == j) & live, Rn, st)
                st = jnp.where((rows_st == _S_L0 + j) & live, s, st)
                word = word | jnp.where(live, s << (8 * jj), 0)
            st = jnp.where(rows_st == _S_P, p, st)
            st = jnp.where(rows_st == _S_OK, okv, st)
            tile = jnp.where(rows_out == w, word, tile)
            return tile, st

        tile, st = lax.fori_loop(
            0, OC_WORDS, body,
            (jnp.zeros((OC_WORDS, LANES), jnp.int32), st_ref[:, :]),
        )
        st_ref[:, :] = st
        out_ref[:, :] = tile
        ok_ref[:, :] = jnp.sum(
            jnp.where(rows_st == _S_OK, st, 0), axis=0, keepdims=True
        )

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("r_words", "ncb", "oc_words", "n_chunks", "interpret"),
)
def _launch(
    streams, meta, fbank, cbank, cmap,
    r_words: int, ncb: int, oc_words: int, n_chunks: int, interpret: bool,
):
    kernel = _kernel_factory(r_words, ncb, oc_words)
    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
        out_specs=(
            pl.BlockSpec(
                (oc_words, LANES), lambda k: (k, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, LANES), lambda k: (0, 0), memory_space=pltpu.VMEM
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_chunks * oc_words, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
        ),
        scratch_shapes=[pltpu.VMEM((_ST_ROWS, LANES), jnp.int32)],
        interpret=interpret,
    )(streams, meta, fbank, cbank, cmap)


def _group_geometry(group, chunk_bytes):
    max_clen = max(len(p.payload) for _, p in group)
    max_osize = max(p.n_out for _, p in group)
    n_ctx = max(len(p.tables) for _, p in group)
    return stream_geometry(max_clen, max_osize, n_ctx, chunk_bytes)


def rans_lanes(
    blocks: Sequence[bytes],
    chunk_bytes: int = _DEFAULT_CHUNK,
    interpret=None,
) -> Tuple[List[Optional[bytes]], "_cc.RansTierStats"]:
    """Batched lockstep decode of rANS 4x8 streams, up to 128 per kernel
    launch, output streamed chunk-by-chunk to HBM.

    Returns ``(outs, stats)``: per-slice decoded bytes with ``None`` for
    every slice that tiered down (bad format, size/VMEM/context caps, or
    an in-kernel ``ok=0``) — the caller rescues those through the NumPy
    host tier and the Python oracle — plus the
    :class:`~hadoop_bam_tpu.spec.cram_codecs.RansTierStats` taxonomy of
    what went where.  Tier-down is per slice, never per launch."""
    stats = _cc.RansTierStats()
    B = len(blocks)
    outs: List[Optional[bytes]] = [None] * B
    accepted = []
    for i, data in enumerate(blocks):
        try:
            plan = _cc.parse_rans_plan(data)
        except Exception:
            stats.tierdown_format += 1
            continue
        if plan.n_out == 0:
            outs[i] = b""
            stats.lanes += 1
            continue
        ok, reason = accepts(
            len(plan.payload), plan.n_out, len(plan.tables), chunk_bytes
        )
        if not ok:
            setattr(
                stats, f"tierdown_{reason}",
                getattr(stats, f"tierdown_{reason}") + 1,
            )
            continue
        accepted.append((i, plan))
    if not accepted:
        return outs, stats
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    # Pack launch groups greedily: lane-capped at 128 and VMEM-capped on
    # the running group maxima (slices pass the per-slice gate alone, but
    # a wide-context slice and a long slice can only share a launch if
    # their combined banks still fit).
    groups = []
    cur: list = []
    for item in accepted:
        cand = cur + [item]
        if len(cand) > LANES or (
            cur
            and _group_geometry(cand, chunk_bytes)["vmem_bytes"]
            > _VMEM_BUDGET_BYTES
        ):
            groups.append(cur)
            cur = [item]
        else:
            cur = cand
    if cur:
        groups.append(cur)
    for group in groups:
        _launch_group(group, outs, stats, chunk_bytes, bool(interpret))
    return outs, stats


def _launch_group(group, outs, stats, chunk_bytes, interpret):
    geo = _group_geometry(group, chunk_bytes)
    r_words = geo["r_words"]
    ncb = geo["ncb"]
    oc_words = geo["oc_words"]
    n_chunks = geo["n_chunks"]
    n = len(group)
    grp = np.zeros((r_words * 4, LANES), dtype=np.uint8)
    meta = np.zeros((_META_ROWS, LANES), dtype=np.int32)
    fbank = np.zeros((ncb * 256, LANES), dtype=np.int32)
    cbank = np.zeros((ncb * 256, LANES), dtype=np.int32)
    cmap = np.full((256, LANES), -1, dtype=np.int32)
    for j, (_, plan) in enumerate(group):
        pay = np.frombuffer(plan.payload, dtype=np.uint8)
        grp[: len(pay), j] = pay
        meta[_M_NOUT, j] = plan.n_out
        meta[_M_4Q4, j] = 4 * plan.q4v
        meta[_M_CLEN, j] = len(pay)
        meta[_M_R:_M_R + 4, j] = (
            np.array(plan.states, dtype=np.uint32).view(np.int32)
        )
        if plan.order == 0:
            cmap[:, j] = 0
        for ci, (ctx, (F, C, _lk)) in enumerate(sorted(plan.tables.items())):
            if plan.order == 1:
                cmap[ctx, j] = ci
            fbank[ci * 256:(ci + 1) * 256, j] = F
            cbank[ci * 256:(ci + 1) * 256, j] = C[:256]
    words = (
        grp.reshape(r_words, 4, LANES).astype(np.uint32)
        * (np.uint32(1) << (8 * np.arange(4, dtype=np.uint32)))[
            None, :, None
        ]
    ).sum(axis=1).astype(np.uint32).view(np.int32)
    owords, okk = _launch(
        jnp.asarray(words), jnp.asarray(meta), jnp.asarray(fbank),
        jnp.asarray(cbank), jnp.asarray(cmap),
        r_words, ncb, oc_words, n_chunks, interpret,
    )
    by = np.asarray(owords).view(np.uint32)
    out_cap = n_chunks * oc_words * 4
    bytes_mat = np.zeros((out_cap, LANES), dtype=np.uint8)
    for k in range(4):
        bytes_mat[k::4] = ((by >> np.uint32(8 * k)) & 0xFF).astype(np.uint8)
    okk = np.asarray(okk)[0].astype(bool)
    for j, (i, plan) in enumerate(group):
        if okk[j]:
            outs[i] = _cc.rans_deinterleave(
                bytes_mat[: plan.n_out, j], plan.order, plan.n_out
            )
            stats.lanes += 1
        else:
            stats.tierdown_ok0 += 1
