"""Lockstep-lane Pallas inflate for *general* DEFLATE members.

The production promotion of the walk engine measured by
ops/pallas/inflate_probe.py (~748 ns per 128-token wave on a v5e — ~340
MB/s of walk-engine throughput): up to 128 BGZF members ride the 128
vector lanes of one kernel, each walking its own DEFLATE bit stream
serially through any per-member mix of stored/fixed/dynamic blocks.

Architecture (all stages share the probe's register/VMEM-resident style —
per-lane row selects are dense iota-compare column reductions, never
gathers):

- streams live TRANSPOSED in VMEM ([words, 128]: member j's words go down
  lane j); "read 32 bits at my cursor" is two one-hot row selects;
- per-member canonical Huffman tables are built ON CHIP per block — the
  length histogram, first-code and symbol-offset columns are static
  15-step loops over [1,128] rows, and the canonical symbol ranking is a
  288-step lockstep scan with one-hot scatters (semantics pinned to
  ops/flate.py's ``_canonical_decoder``/``_kraft_valid``, the spec);
- decode is the 15-compare canonical range test of the probe, against the
  per-lane table columns — pure elementwise VPU work;
- emit is a byte-per-wave state machine: every wave each live lane either
  emits one literal, copies one LZ77 byte back from its own output
  column, streams one stored-block byte, decodes a length/distance pair,
  or retires its block on EOB — so lanes with different block types and
  token mixes stay in lockstep;
- LZ77 copies resolve in-kernel through a window of the lane's own output
  column (the whole member rides VMEM in this slice, so the window spans
  the member); copies farther than ``far_dist`` — and any later copy
  whose source could overlap a deferred destination — are recorded in a
  small per-lane side list and replayed by a host-assisted pass after
  download (rare by construction; list overflow tiers the member down);
- per-member ``[n_out, ok]`` meta comes back with the payload, so a
  single bad member tiers down to the XLA/host decoders without dooming
  its launch.

The whole-member-in-VMEM layout caps member size by the VMEM budget
(``_VMEM_BUDGET_BYTES``); members past it come back ``ok=False`` and tier
down.  The HBM-streaming windowed variant (small ``far_dist``, sliding
output window) is the follow-up that lifts the cap — the host-assisted
far-copy pass below is exactly the machinery it needs.

Oracle: zlib via the fuzz corpus in tests/test_inflate_lanes.py; tests
run the kernel in interpret mode on CPU and compare byte-for-byte.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..flate import CLC_ORDER, DIST_BASE, DIST_EXTRA, LEN_BASE, LEN_EXTRA

LANES = 128

#: Code-length section is ≤ 286+30 = 316 codes; RLE tokens never exceed it.
_MAX_CODES = 320
_MAX_HDR_TOKENS = 318

#: VMEM budget for one launch (streams + output + table scratch).  Members
#: whose geometry exceeds it come back ok=False and tier down to the XLA
#: decoder; the HBM-streaming windowed variant is the follow-up.
_VMEM_BUDGET_BYTES = 10 << 20


def _sel_const(idx: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
    """Per-lane select from a small static table: out[lane]=table[idx[lane]]
    as a static compare loop (no gather)."""
    out = jnp.zeros_like(idx)
    for k in range(len(table)):
        out = jnp.where(idx == k, int(table[k]), out)
    return out


def _rev_bits(w: jnp.ndarray, n: int) -> jnp.ndarray:
    """Reverse the low ``n`` bits of uint32 ``w`` (stream bit 0 → MSB)."""
    r = jnp.zeros_like(w)
    for k in range(n):
        r = r | (((w >> k) & 1) << (n - 1 - k))
    return r.astype(jnp.int32)


def _build_canon(lens: jnp.ndarray, S: int, maxl: int):
    """Per-lane canonical tables from code lengths (``_canonical_decoder``
    semantics, lockstep form).

    ``lens``: int32 [S, 128].  Returns ``(first, count, symoff)`` as python
    lists of [1,128] columns indexed by code length, plus ``sym_sorted``
    [S,128]: a code of length L and MSB-first value c decodes to
    ``sym_sorted[symoff[L] + c - first[L]]``.
    """
    count = [jnp.zeros((1, LANES), jnp.int32)]
    for L in range(1, maxl + 1):
        count.append(
            jnp.sum((lens == L).astype(jnp.int32), axis=0, keepdims=True)
        )
    first = [jnp.zeros((1, LANES), jnp.int32)]
    code = jnp.zeros((1, LANES), jnp.int32)
    for L in range(1, maxl + 1):
        code = (code + count[L - 1]) << 1
        first.append(code)
    symoff = []
    acc = jnp.zeros((1, LANES), jnp.int32)
    for L in range(0, maxl + 1):
        symoff.append(acc)
        acc = acc + count[L]
    # Canonical symbol ranking: lockstep scan over the symbol axis; each
    # step places one symbol per lane via a one-hot row scatter.
    rows_S = lax.broadcasted_iota(jnp.int32, (S, LANES), 0)
    rows_L = lax.broadcasted_iota(jnp.int32, (maxl + 1, LANES), 0)

    def sbody(s, st):
        sym_sorted, taken = st
        len_s = jnp.sum(
            jnp.where(rows_S == s, lens, 0), axis=0, keepdims=True
        )
        rank = jnp.zeros((1, LANES), jnp.int32)
        for L in range(1, maxl + 1):
            rank = jnp.where(
                len_s == L, symoff[L] + taken[L : L + 1, :], rank
            )
        use = len_s > 0
        sym_sorted = jnp.where((rows_S == rank) & use, s, sym_sorted)
        taken = jnp.where((rows_L == len_s) & use, taken + 1, taken)
        return sym_sorted, taken

    sym_sorted, _ = lax.fori_loop(
        0,
        S,
        sbody,
        (
            jnp.zeros((S, LANES), jnp.int32),
            jnp.zeros((maxl + 1, LANES), jnp.int32),
        ),
    )
    return first, count, symoff, sym_sorted


def _kraft_ok(count, maxl: int, allow_single: bool) -> jnp.ndarray:
    """Per-lane Kraft validity of a length histogram (``_kraft_valid``
    semantics: reject over-subscribed and incomplete sets, except zlib's
    lone length-1 code grace when ``allow_single``)."""
    kraft = jnp.zeros((1, LANES), jnp.int32)
    ncodes = jnp.zeros((1, LANES), jnp.int32)
    for L in range(1, maxl + 1):
        kraft = kraft + (count[L] << (maxl - L))
        ncodes = ncodes + count[L]
    ok = (ncodes == 0) | (kraft == (1 << maxl))
    if allow_single:
        ok = ok | ((ncodes == 1) & (count[1] == 1))
    return ok


def _canon_decode(rev, first, count, symoff, sym_sorted, maxl, rows_S):
    """15-compare canonical decode of MSB-first-reversed windows against
    per-lane tables.  Returns (sym, L, matched); speculative garbage
    positions may be unmatched."""
    S = sym_sorted.shape[0]
    Lsel = jnp.full((1, LANES), 99, jnp.int32)
    f_s = jnp.zeros((1, LANES), jnp.int32)
    o_s = jnp.zeros((1, LANES), jnp.int32)
    for L in range(maxl, 0, -1):  # downward: smallest L wins last
        cand = rev >> (maxl - L)
        match = (cand >= first[L]) & (cand < first[L] + count[L])
        Lsel = jnp.where(match, L, Lsel)
        f_s = jnp.where(match, first[L], f_s)
        o_s = jnp.where(match, symoff[L], o_s)
    matched = Lsel < 99
    Ls = jnp.where(matched, Lsel, 1)
    cand = rev >> (maxl - Ls)
    idx = jnp.clip(o_s + cand - f_s, 0, S - 1)
    sym = jnp.sum(
        jnp.where(rows_S == idx, sym_sorted, 0), axis=0, keepdims=True
    )
    return sym, Ls, matched


def _kernel_factory(
    R: int,
    OUT_ROWS: int,
    T_ROUND: int,
    MAX_BLOCKS: int,
    MAX_FAR: int,
    FAR_DIST: int,
):
    """R stream words/lane; OUT_ROWS packed output words/lane; T_ROUND
    emit-wave budget per block round."""

    def kernel(
        streams_ref,
        nbits_ref,
        isize_ref,
        out_ref,
        nout_ref,
        ok_ref,
        farc_ref,
        fara_ref,
        farb_ref,
    ):
        rows_R = lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
        rows_O = lax.broadcasted_iota(jnp.int32, (OUT_ROWS, LANES), 0)
        rows_ll = lax.broadcasted_iota(jnp.int32, (288, LANES), 0)
        rows_dl = lax.broadcasted_iota(jnp.int32, (32, LANES), 0)
        rows_cl = lax.broadcasted_iota(jnp.int32, (19, LANES), 0)
        rows_hc = lax.broadcasted_iota(jnp.int32, (_MAX_CODES, LANES), 0)
        rows_F = lax.broadcasted_iota(jnp.int32, (MAX_FAR, LANES), 0)
        nbits = nbits_ref[:, :]
        isize = isize_ref[:, :]

        def word_at(widx):
            onehot = rows_R == widx
            return jnp.sum(
                jnp.where(onehot, streams_ref[:, :], 0),
                axis=0,
                keepdims=True,
            ).astype(jnp.uint32)

        def window(cur):
            """32 stream bits at per-lane bit cursor ``cur`` [1,128]."""
            widx = cur >> 5
            w0 = word_at(widx)
            w1 = word_at(widx + 1)
            sh = (cur & 31).astype(jnp.uint32)
            return jnp.where(sh == 0, w0, (w0 >> sh) | (w1 << (32 - sh)))

        def out_byte_at(out, pos):
            word = jnp.sum(
                jnp.where(rows_O == (pos >> 2), out, 0),
                axis=0,
                keepdims=True,
            ).astype(jnp.uint32)
            return (word >> (8 * (pos & 3)).astype(jnp.uint32)) & 0xFF

        def out_write(out, pos, byte, mask):
            onehot = (rows_O == (pos >> 2)) & mask
            shifted = (
                byte.astype(jnp.uint32)
                << (8 * (pos & 3)).astype(jnp.uint32)
            ).astype(jnp.int32)
            return jnp.where(onehot, out | shifted, out)

        # Fixed-Huffman length vectors (RFC 1951 §3.2.6), built from iota
        # in-kernel (Pallas kernels cannot capture array constants).
        fixed_ll = jnp.where(
            rows_ll < 144,
            8,
            jnp.where(rows_ll < 256, 9, jnp.where(rows_ll < 280, 7, 8)),
        ).astype(jnp.int32)
        fixed_dl = jnp.full((32, LANES), 5, jnp.int32)

        # ---- member-wide carried state ---------------------------------
        cur0 = jnp.zeros((1, LANES), jnp.int32)
        n_out0 = jnp.zeros((1, LANES), jnp.int32)
        ok0 = jnp.ones((1, LANES), bool)
        done0 = nbits == 0  # padding lanes finish immediately
        out0 = jnp.zeros((OUT_ROWS, LANES), jnp.int32)
        fara0 = jnp.zeros((MAX_FAR, LANES), jnp.int32)
        farb0 = jnp.zeros((MAX_FAR, LANES), jnp.int32)
        farc0 = jnp.zeros((1, LANES), jnp.int32)
        hole0 = jnp.full((1, LANES), jnp.int32(0x7FFFFFFF))

        def round_body(carry):
            (blk, cur, n_out, ok, done, out,
             fara, farb, farc, hole_lo) = carry
            live = ok & ~done
            hdr = window(cur)
            bfinal = (hdr & 1) == 1
            btype = ((hdr >> 1) & 3).astype(jnp.int32)
            ok = ok & (~live | (btype != 3))
            is_stored = live & (btype == 0)
            is_dyn = live & (btype == 2)

            # ---- stored block setup (byte-aligned LEN/NLEN) ------------
            st_bit = (cur + 3 + 7) & ~7
            ln_w = window(st_bit)
            s_len = (ln_w & 0xFFFF).astype(jnp.int32)
            s_nlen = ((ln_w >> 16) & 0xFFFF).astype(jnp.int32)
            ok = ok & (
                ~is_stored
                | (
                    (s_len == (s_nlen ^ 0xFFFF))
                    & (st_bit + 32 + 8 * s_len <= nbits)
                )
            )

            # ---- dynamic header parse (btype=10) -----------------------
            at = cur + 3
            hlit = (window(at) & 31).astype(jnp.int32) + 257
            hdist = (window(at + 5) & 31).astype(jnp.int32) + 1
            hclen = (window(at + 10) & 15).astype(jnp.int32) + 4
            ok = ok & (~is_dyn | ((hlit <= 286) & (hdist <= 30)))
            cl_lens = jnp.zeros((19, LANES), jnp.int32)
            for i in range(19):
                bits = (window(at + 14 + 3 * i) & 7).astype(jnp.int32)
                bits = jnp.where(i < hclen, bits, 0)
                cl_lens = jnp.where(
                    rows_cl == int(CLC_ORDER[i]), bits, cl_lens
                )
            clc = _build_canon(cl_lens, 19, 7)
            ok = ok & (~is_dyn | _kraft_ok(clc[1], 7, allow_single=False))
            total_codes = hlit + hdist

            # Code-length RLE: one CLC token per wave, lockstep across
            # lanes; repeats land as masked row-range writes.
            def hcond(st):
                pos, cnt, prev, okh, lens_all, it = st
                act = is_dyn & okh & (cnt < total_codes)
                return (it < _MAX_HDR_TOKENS) & jnp.any(act)

            def hbody(st):
                pos, cnt, prev, okh, lens_all, it = st
                w = window(pos)
                r7 = _rev_bits(w, 7)
                csym, cL, cm = _canon_decode(
                    r7, clc[0], clc[1], clc[2], clc[3], 7, rows_cl
                )
                ext = (w >> cL.astype(jnp.uint32)).astype(jnp.int32)
                rep = jnp.where(
                    csym < 16,
                    1,
                    jnp.where(
                        csym == 16,
                        3 + (ext & 3),
                        jnp.where(
                            csym == 17, 3 + (ext & 7), 11 + (ext & 127)
                        ),
                    ),
                )
                val = jnp.where(
                    csym < 16, csym, jnp.where(csym == 16, prev, 0)
                )
                nb = cL + jnp.where(
                    csym < 16,
                    0,
                    jnp.where(
                        csym == 16, 2, jnp.where(csym == 17, 3, 7)
                    ),
                )
                act = is_dyn & okh & (cnt < total_codes)
                okh = okh & (~act | cm)
                wr = act & okh
                lens_all = jnp.where(
                    (rows_hc >= cnt) & (rows_hc < cnt + rep) & wr,
                    val,
                    lens_all,
                )
                pos = pos + jnp.where(wr, nb, 0)
                cnt = cnt + jnp.where(wr, rep, 0)
                prev = jnp.where(wr, val, prev)
                return pos, cnt, prev, okh, lens_all, it + 1

            hpos, hcnt, _, hok, lens_all, _ = lax.while_loop(
                hcond,
                hbody,
                (
                    at + 14 + 3 * hclen,
                    jnp.zeros((1, LANES), jnp.int32),
                    jnp.zeros((1, LANES), jnp.int32),
                    jnp.ones((1, LANES), bool),
                    jnp.zeros((_MAX_CODES, LANES), jnp.int32),
                    jnp.int32(0),
                ),
            )
            ok = ok & (
                ~is_dyn | (hok & (hcnt == total_codes) & (hpos <= nbits))
            )

            dyn_ll = jnp.where(rows_ll < hlit, lens_all[:288, :], 0)
            dl_cols = []
            for d in range(32):
                col = jnp.sum(
                    jnp.where(rows_hc == hlit + d, lens_all, 0),
                    axis=0,
                    keepdims=True,
                )
                dl_cols.append(jnp.where(d < hdist, col, 0))
            dyn_dl = jnp.concatenate(dl_cols, axis=0)

            use_dyn = btype == 2
            ll_lens = jnp.where(use_dyn, dyn_ll, fixed_ll)
            dl_lens = jnp.where(use_dyn, dyn_dl, fixed_dl)
            ll = _build_canon(ll_lens, 288, 15)
            dl = _build_canon(dl_lens, 32, 15)
            ok = ok & (
                ~is_dyn
                | (
                    _kraft_ok(ll[1], 15, allow_single=True)
                    & _kraft_ok(dl[1], 15, allow_single=True)
                )
            )

            data_start = jnp.where(
                use_dyn, hpos, jnp.where(btype == 0, st_bit + 32, cur + 3)
            )

            # ---- emit loop: one output byte per lane per wave ----------
            def econd(st):
                (it, cur, n_out, ok, blk_done, copy_rem, copy_dist,
                 rem, out, fara, farb, farc, hole_lo) = st
                return (it < T_ROUND) & jnp.any(live & ok & ~blk_done)

            def ebody(st):
                (it, cur, n_out, ok, blk_done, copy_rem, copy_dist,
                 rem, out, fara, farb, farc, hole_lo) = st
                active = live & ok & ~blk_done
                in_copy = active & (copy_rem > 0)
                in_stored = active & is_stored & (rem > 0)
                decode = active & ~is_stored & ~in_copy

                # 1. LZ77 copy byte (reads before this wave's writes).
                cb = out_byte_at(out, n_out - copy_dist)
                # 2. stored byte (cursor is byte-aligned in stored blocks).
                sb = window(cur) & 0xFF
                # 3. token decode at the cursor.
                w = window(cur)
                sym, L, m = _canon_decode(
                    _rev_bits(w, 15), ll[0], ll[1], ll[2], ll[3], 15,
                    rows_ll,
                )
                islit = decode & m & (sym < 256)
                iseob = decode & m & (sym == 256)
                islen = decode & m & (sym > 256) & (sym < 286)
                bad = decode & (~m | (sym >= 286))
                li = jnp.clip(sym - 257, 0, 28)
                le = _sel_const(li, LEN_EXTRA)
                lenval = _sel_const(li, LEN_BASE) + (
                    (w >> L.astype(jnp.uint32)).astype(jnp.int32)
                    & ((1 << le) - 1)
                )
                wd = window(cur + L + le)
                dsym, Ld, md = _canon_decode(
                    _rev_bits(wd, 15), dl[0], dl[1], dl[2], dl[3], 15,
                    rows_dl,
                )
                bad = bad | (islen & (~md | (dsym >= 30)))
                dsym = jnp.clip(dsym, 0, 29)
                de = _sel_const(dsym, DIST_EXTRA)
                dist = _sel_const(dsym, DIST_BASE) + (
                    (wd >> Ld.astype(jnp.uint32)).astype(jnp.int32)
                    & ((1 << de) - 1)
                )
                adv = jnp.where(islit | iseob, L, L + le + Ld + de)
                bad = bad | (decode & (cur + adv > nbits))
                bad = bad | (islen & (dist > n_out))
                islit = islit & ~bad
                iseob = iseob & ~bad
                islen = islen & ~bad
                ok = ok & ~bad

                # Far copies (past the resolve window, or sourcing at/after
                # a deferred destination) are recorded for the host pass;
                # their output bytes stay zero and n_out skips ahead.
                far = islen & (
                    (dist > FAR_DIST)
                    | (n_out - dist + lenval > hole_lo)
                )
                can_rec = farc < MAX_FAR
                ok = ok & (~far | can_rec)
                rec = far & can_rec
                fara = jnp.where(
                    (rows_F == farc) & rec, (n_out << 9) | lenval, fara
                )
                farb = jnp.where((rows_F == farc) & rec, dist, farb)
                hole_lo = jnp.where(
                    rec, jnp.minimum(hole_lo, n_out), hole_lo
                )
                farc = farc + rec.astype(jnp.int32)
                near = islen & ~far

                # Emits: exactly one byte per emitting lane this wave.
                byte = jnp.where(
                    in_copy, cb, jnp.where(in_stored, sb, sym & 0xFF)
                ).astype(jnp.uint32)
                emit = in_copy | in_stored | islit
                out = out_write(out, n_out, byte, emit)
                n_out = (
                    n_out
                    + emit.astype(jnp.int32)
                    + jnp.where(rec, lenval, 0)
                )
                copy_rem = jnp.where(
                    near, lenval, copy_rem - in_copy.astype(jnp.int32)
                )
                copy_dist = jnp.where(near, dist, copy_dist)
                rem = rem - in_stored.astype(jnp.int32)
                cur = (
                    cur
                    + jnp.where(decode & ~bad, adv, 0)
                    + 8 * in_stored.astype(jnp.int32)
                )
                blk_done = blk_done | iseob | (
                    active & is_stored & (rem == 0)
                )
                return (it + 1, cur, n_out, ok, blk_done, copy_rem,
                        copy_dist, rem, out, fara, farb, farc, hole_lo)

            (_, cur, n_out, ok, blk_done, _, _, _, out,
             fara, farb, farc, hole_lo) = lax.while_loop(
                econd,
                ebody,
                (
                    jnp.int32(0),
                    data_start,
                    n_out,
                    ok,
                    ~live,
                    jnp.zeros((1, LANES), jnp.int32),
                    jnp.ones((1, LANES), jnp.int32),
                    jnp.where(is_stored, s_len, 0),
                    out,
                    fara,
                    farb,
                    farc,
                    hole_lo,
                ),
            )
            # A block that did not retire within the wave budget is invalid.
            ok = ok & (~live | blk_done)
            done = done | (live & bfinal)
            return (blk + 1, cur, n_out, ok, done, out,
                    fara, farb, farc, hole_lo)

        def round_cond(carry):
            blk, _, _, ok, done = carry[0], carry[1], carry[2], carry[3], carry[4]
            return (blk < MAX_BLOCKS) & jnp.any(ok & ~done)

        (_, _, n_out, ok, done, out, fara, farb, farc, _) = lax.while_loop(
            round_cond,
            round_body,
            (jnp.int32(0), cur0, n_out0, ok0, done0, out0,
             fara0, farb0, farc0, hole0),
        )
        ok = ok & done & (n_out == isize)
        out_ref[:, :] = out
        nout_ref[:, :] = n_out
        ok_ref[:, :] = ok.astype(jnp.int32)
        farc_ref[:, :] = farc
        fara_ref[:, :] = fara
        farb_ref[:, :] = farb

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "r_words", "out_rows", "t_round", "max_blocks", "max_far",
        "far_dist", "interpret",
    ),
)
def _launch(
    streams, nbits, isizes, r_words: int, out_rows: int, t_round: int,
    max_blocks: int, max_far: int, far_dist: int, interpret: bool,
):
    kernel = _kernel_factory(
        r_words, out_rows, t_round, max_blocks, max_far, far_dist
    )
    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(6)
        ),
        out_shape=(
            jax.ShapeDtypeStruct((out_rows, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            jax.ShapeDtypeStruct((max_far, LANES), jnp.int32),
            jax.ShapeDtypeStruct((max_far, LANES), jnp.int32),
        ),
        interpret=interpret,
    )(streams, nbits, isizes)


def _apply_far_copies(
    lane_bytes: np.ndarray, fara: np.ndarray, farb: np.ndarray, n: int
) -> None:
    """Replay a lane's deferred far-distance copies in stream order.

    Events are recorded so that every source byte is either kernel-correct
    or patched by an earlier event, so an in-order byte loop (which also
    handles overlapping copies) reconstructs the exact LZ77 semantics."""
    for e in range(n):
        a = int(fara[e])
        dst, ln, dist = a >> 9, a & 511, int(farb[e])
        for k in range(ln):
            lane_bytes[dst + k] = lane_bytes[dst + k - dist]


def inflate_lanes(
    comp: np.ndarray,
    clens: np.ndarray,
    isizes: np.ndarray,
    max_blocks: int = 12,
    max_far: int = 64,
    far_dist: int = 1 << 15,
    interpret=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched lockstep inflate of general DEFLATE members (any mix of
    stored/fixed/dynamic blocks), 128 members per kernel launch.

    ``comp`` uint8 [B, C] (rows zero-padded), ``clens``/``isizes`` int32
    [B].  Returns ``(out uint8 [B, max_isize], ok bool [B])`` — a member
    that is corrupt, exceeds ``max_blocks`` DEFLATE blocks, overflows the
    ``max_far`` far-copy budget, or whose geometry exceeds the VMEM budget
    comes back ``ok=False`` and the caller tiers down to the XLA/host
    decoders.  ``far_dist`` bounds the in-kernel LZ77 resolve window;
    copies past it defer to the host-assisted replay pass (the default
    covers every legal DEFLATE distance, so the pass is exercised only by
    the windowed configuration)."""
    from ..flate import _pow2_at_least

    B, C = comp.shape
    if B == 0:
        return np.empty((0, 0), np.uint8), np.empty(0, bool)
    max_out = int(isizes.max()) if len(isizes) else 0
    out_rows = _pow2_at_least(max(-(-max_out // 4), 1), 32)
    out_cap = out_rows * 4
    t_round = out_cap + out_cap // 3 + 64
    r_words = _pow2_at_least(-(-C // 4) + 2, 32)
    vmem = (
        (r_words + 2 * out_rows + _MAX_CODES + 288 + 64 + 2 * max_far + 256)
        * LANES * 4
    )
    out = np.zeros((B, max_out), dtype=np.uint8)
    ok_all = np.zeros(B, dtype=bool)
    if vmem > _VMEM_BUDGET_BYTES:
        return out, ok_all
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    for g0 in range(0, B, LANES):
        g1 = min(B, g0 + LANES)
        n = g1 - g0
        # Transpose the group: member j's words go down lane j.
        grp = np.zeros((r_words * 4, LANES), dtype=np.uint8)
        grp[:C, :n] = comp[g0:g1].T
        words = (
            grp.reshape(r_words, 4, LANES).astype(np.uint32)
            * (np.uint32(1) << (8 * np.arange(4, dtype=np.uint32)))[
                None, :, None
            ]
        ).sum(axis=1).astype(np.uint32).view(np.int32)
        nbits = np.zeros((1, LANES), dtype=np.int32)
        nbits[0, :n] = clens[g0:g1] * 8
        isz = np.zeros((1, LANES), dtype=np.int32)
        isz[0, :n] = isizes[g0:g1]
        o, nout, okk, farc, fara, farb = _launch(
            jnp.asarray(words), jnp.asarray(nbits), jnp.asarray(isz),
            r_words, out_rows, t_round, max_blocks, max_far, far_dist,
            bool(interpret),
        )
        by = np.asarray(o).view(np.uint32)
        bytes_mat = np.zeros((out_cap, LANES), dtype=np.uint8)
        for k in range(4):
            bytes_mat[k::4] = ((by >> np.uint32(8 * k)) & 0xFF).astype(
                np.uint8
            )
        nout = np.asarray(nout)[0]
        okk = np.asarray(okk)[0].astype(bool)
        farc = np.asarray(farc)[0]
        fara = np.asarray(fara)
        farb = np.asarray(farb)
        for j in range(n):
            i = g0 + j
            okj = okk[j] and int(nout[j]) == int(isizes[i])
            ok_all[i] = okj
            if okj:
                lane = bytes_mat[: isizes[i], j].copy()
                if farc[j]:
                    _apply_far_copies(
                        lane, fara[:, j], farb[:, j], int(farc[j])
                    )
                out[i, : isizes[i]] = lane
    return out, ok_all
