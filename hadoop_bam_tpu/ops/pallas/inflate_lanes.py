"""Lockstep-lane Pallas inflate for *general* DEFLATE members, HBM-streaming.

The production promotion of the walk engine measured by
ops/pallas/inflate_probe.py (~748 ns per 128-token wave on a v5e — ~340
MB/s of walk-engine throughput): up to 128 BGZF members ride the 128
vector lanes of one kernel, each walking its own DEFLATE bit stream
serially through any per-member mix of stored/fixed/dynamic blocks.

Architecture (all stages share the probe's register/VMEM-resident style —
per-lane row selects are dense iota-compare column reductions, never
gathers):

- streams live TRANSPOSED in VMEM ([words, 128]: member j's words go down
  lane j); "read 32 bits at my cursor" is two one-hot row selects;
- per-member canonical Huffman tables are built ON CHIP per block — the
  length histogram, first-code and symbol-offset columns are static
  15-step loops over [1,128] rows, and the canonical symbol ranking is a
  288-step lockstep scan with one-hot scatters (semantics pinned to
  ops/flate.py's ``_canonical_decoder``/``_kraft_valid``, the spec);
- decode is the 15-compare canonical range test of the probe, against the
  per-lane table columns — pure elementwise VPU work;
- emit is a byte-per-wave state machine: every wave each live lane either
  emits one literal, copies one LZ77 byte back from its own output
  window, streams one stored-block byte, decodes a length/distance pair,
  or retires its block on EOB — so lanes with different block types and
  token mixes stay in lockstep.

**Streaming geometry** (the lift of the old whole-member-VMEM cap): the
kernel grids over fixed-size OUTPUT chunks (``chunk_bytes`` per lane per
grid step).  Only one chunk tile is live in VMEM at a time; finished
tiles stream out to the HBM-backed output as the grid advances.  Per-lane
state carries across grid steps in VMEM scratch:

- the bit cursor, output cursor, ok/done flags, copy/stored progress and
  the far-copy ledger live in a packed register file (``st``);
- the current block's canonical litlen/dist tables persist in a packed
  table bank (``tabs``) so a block can span any number of chunks;
- LZ77 copies resolve from a **ring window** of the lane's last
  ``ring_bytes`` output bytes (sized to cover DEFLATE's full 32 KiB
  distance domain by default, so no legal copy ever leaves the window);
  copies farther than ``far_dist`` — and any later copy whose source
  could overlap a deferred destination — are recorded in a small per-lane
  side list and replayed by a host-assisted pass after download (never
  taken with the default window; exercised by the windowed test configs);
- block headers are parsed (and tables rebuilt) *between* emit phases,
  inside per-step rounds, gated by ``lax.cond`` so steps that resume
  mid-block pay no table-build cost;
- one epilogue grid step runs past the last output chunk so a member
  whose final EOB lands exactly on a chunk boundary still retires.

A full 64 KiB BGZF member (the cap real writers emit at) now decodes on
the lanes tier: VMEM holds the compressed words, the 32 KiB ring and one
chunk tile — about 13.5 MiB at the worst-case geometry — instead of the
old input + whole output residency that tiered everything past ~10 KiB
down to the XLA/host decoders.  Members whose *compressed* stream alone
exceeds the VMEM budget (impossible for BGZF, relevant only to future
CRAM containers) still come back ``ok=False`` and tier down, as do
corrupt members, via the per-member ``[n_out, ok]`` meta.

Oracle: zlib via tests/test_inflate_lanes.py and the streaming corpus in
tests/test_stream_codecs.py; tests run the kernel in interpret mode on
CPU and compare byte-for-byte.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..flate import CLC_ORDER, DIST_BASE, DIST_EXTRA, LEN_BASE, LEN_EXTRA

LANES = 128

#: Code-length section is ≤ 286+30 = 316 codes; RLE tokens never exceed it.
_MAX_CODES = 320
_MAX_HDR_TOKENS = 318

#: VMEM budget for one launch (streams + ring + tile + table scratch).
#: ~16 MiB/core physical on the target parts; leave compiler headroom.
#: Members whose geometry exceeds it come back ok=False and tier down.
_VMEM_BUDGET_BYTES = 14 << 20

#: Output-size sanity cap (BGZF members are ≤ 64 KiB; the margin is for
#: future CRAM containers).  Past it the wrapper declines without
#: launching.
_MAX_ISIZE = 1 << 20

#: Default output chunk per lane per grid step (must be a power of two).
_DEFAULT_CHUNK = 4096

# Packed per-lane register rows in the ``st`` scratch bank.
_R_CUR = 0        # bit cursor
_R_NOUT = 1       # output byte cursor
_R_OK = 2
_R_DONE = 3
_R_INBLK = 4      # mid-block (tables/stored state valid)
_R_STORED = 5     # current block is stored
_R_BFINAL = 6     # current block carries BFINAL
_R_CREM = 7       # LZ77 copy bytes remaining
_R_CDIST = 8      # LZ77 copy distance
_R_SREM = 9       # stored-block bytes remaining
_R_FARC = 10      # far-copy events recorded
_R_HOLE = 11      # lowest deferred-destination start
_R_BLK = 12       # blocks started
_ST_ROWS = 16

# Packed table bank rows: litlen syms, dist syms, then the 16-row
# first/count/symoff columns for each alphabet.
_T_LLSYM = 0          # [0, 288)
_T_DLSYM = 288        # [288, 320)
_T_LLFIRST = 320      # [320, 336)
_T_LLCOUNT = 336
_T_LLSYMOFF = 352
_T_DLFIRST = 368
_T_DLCOUNT = 384
_T_DLSYMOFF = 400
_TAB_ROWS = 416


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _sel_const(idx: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
    """Per-lane select from a small static table: out[lane]=table[idx[lane]]
    as a static compare loop (no gather)."""
    out = jnp.zeros_like(idx)
    for k in range(len(table)):
        out = jnp.where(idx == k, int(table[k]), out)
    return out


def _rev_bits(w: jnp.ndarray, n: int) -> jnp.ndarray:
    """Reverse the low ``n`` bits of uint32 ``w`` (stream bit 0 → MSB)."""
    r = jnp.zeros_like(w)
    for k in range(n):
        r = r | (((w >> k) & 1) << (n - 1 - k))
    return r.astype(jnp.int32)


def _build_canon(lens: jnp.ndarray, S: int, maxl: int):
    """Per-lane canonical tables from code lengths (``_canonical_decoder``
    semantics, lockstep form).

    ``lens``: int32 [S, 128].  Returns ``(first, count, symoff)`` as python
    lists of [1,128] columns indexed by code length, plus ``sym_sorted``
    [S,128]: a code of length L and MSB-first value c decodes to
    ``sym_sorted[symoff[L] + c - first[L]]``.
    """
    count = [jnp.zeros((1, LANES), jnp.int32)]
    for L in range(1, maxl + 1):
        count.append(
            jnp.sum((lens == L).astype(jnp.int32), axis=0, keepdims=True)
        )
    first = [jnp.zeros((1, LANES), jnp.int32)]
    code = jnp.zeros((1, LANES), jnp.int32)
    for L in range(1, maxl + 1):
        code = (code + count[L - 1]) << 1
        first.append(code)
    symoff = []
    acc = jnp.zeros((1, LANES), jnp.int32)
    for L in range(0, maxl + 1):
        symoff.append(acc)
        acc = acc + count[L]
    # Canonical symbol ranking: lockstep scan over the symbol axis; each
    # step places one symbol per lane via a one-hot row scatter.
    rows_S = lax.broadcasted_iota(jnp.int32, (S, LANES), 0)
    rows_L = lax.broadcasted_iota(jnp.int32, (maxl + 1, LANES), 0)

    def sbody(s, st):
        sym_sorted, taken = st
        len_s = jnp.sum(
            jnp.where(rows_S == s, lens, 0), axis=0, keepdims=True
        )
        rank = jnp.zeros((1, LANES), jnp.int32)
        for L in range(1, maxl + 1):
            rank = jnp.where(
                len_s == L, symoff[L] + taken[L : L + 1, :], rank
            )
        use = len_s > 0
        sym_sorted = jnp.where((rows_S == rank) & use, s, sym_sorted)
        taken = jnp.where((rows_L == len_s) & use, taken + 1, taken)
        return sym_sorted, taken

    sym_sorted, _ = lax.fori_loop(
        0,
        S,
        sbody,
        (
            jnp.zeros((S, LANES), jnp.int32),
            jnp.zeros((maxl + 1, LANES), jnp.int32),
        ),
    )
    return first, count, symoff, sym_sorted


def _kraft_ok(count, maxl: int, allow_single: bool) -> jnp.ndarray:
    """Per-lane Kraft validity of a length histogram (``_kraft_valid``
    semantics: reject over-subscribed and incomplete sets, except zlib's
    lone length-1 code grace when ``allow_single``)."""
    kraft = jnp.zeros((1, LANES), jnp.int32)
    ncodes = jnp.zeros((1, LANES), jnp.int32)
    for L in range(1, maxl + 1):
        kraft = kraft + (count[L] << (maxl - L))
        ncodes = ncodes + count[L]
    ok = (ncodes == 0) | (kraft == (1 << maxl))
    if allow_single:
        ok = ok | ((ncodes == 1) & (count[1] == 1))
    return ok


def _canon_decode(rev, first, count, symoff, sym_sorted, maxl, rows_S):
    """15-compare canonical decode of MSB-first-reversed windows against
    per-lane tables (``first``/``count``/``symoff`` index by code length:
    either python lists of [1,128] columns or stacked [16,128] banks).
    Returns (sym, L, matched); speculative garbage positions may be
    unmatched."""

    def row(t, L):
        return t[L] if isinstance(t, list) else t[L : L + 1, :]

    S = sym_sorted.shape[0]
    Lsel = jnp.full((1, LANES), 99, jnp.int32)
    f_s = jnp.zeros((1, LANES), jnp.int32)
    o_s = jnp.zeros((1, LANES), jnp.int32)
    for L in range(maxl, 0, -1):  # downward: smallest L wins last
        cand = rev >> (maxl - L)
        match = (cand >= row(first, L)) & (
            cand < row(first, L) + row(count, L)
        )
        Lsel = jnp.where(match, L, Lsel)
        f_s = jnp.where(match, row(first, L), f_s)
        o_s = jnp.where(match, row(symoff, L), o_s)
    matched = Lsel < 99
    Ls = jnp.where(matched, Lsel, 1)
    cand = rev >> (maxl - Ls)
    idx = jnp.clip(o_s + cand - f_s, 0, S - 1)
    sym = jnp.sum(
        jnp.where(rows_S == idx, sym_sorted, 0), axis=0, keepdims=True
    )
    return sym, Ls, matched


def _stack16(cols) -> jnp.ndarray:
    """[1,128] column list (len ≤ 16, indexed by code length) → [16,128]."""
    pad = [jnp.zeros((1, LANES), jnp.int32)] * (16 - len(cols))
    return jnp.concatenate(list(cols) + pad, axis=0)


def stream_geometry(
    max_clen: int,
    max_isize: int,
    chunk_bytes: int = _DEFAULT_CHUNK,
    far_dist: int = 1 << 15,
    max_far: int = 64,
    max_blocks: int = 12,
) -> dict:
    """Static launch geometry for the streaming decoder (pure host math —
    also the tier-selection surface: ``vmem_bytes`` against the budget
    decides size-based tier-downs without touching a device)."""
    chunk_bytes = max(256, chunk_bytes)
    if chunk_bytes & (chunk_bytes - 1):
        raise ValueError("chunk_bytes must be a power of two")
    oc_rows = chunk_bytes // 4
    # The resolve ring only has to cover distances that can actually
    # occur: DEFLATE caps them at 32768 and a member can never reference
    # before its own start, so small members get a small (cheap) ring.
    win = 1
    while win < min(max(max_isize, 1), 1 << 15):
        win *= 2
    ring_bytes = chunk_bytes
    while ring_bytes < min(far_dist, 1 << 15, win):
        ring_bytes *= 2
    # The static in-kernel threshold tracks the ring, not the member: any
    # distance the ring can hold resolves on chip, and tying the launch
    # signature to (chunk, ring) alone keeps jit recompiles rare.
    eff_far = min(far_dist, ring_bytes)
    r_words = _round_up(max(-(-max_clen // 4) + 2, 32), 512)
    n_chunks = -(-max(max_isize, 1) // chunk_bytes) + 1  # +1 epilogue
    t_step = (max_blocks + 2) * (chunk_bytes + chunk_bytes // 2 + 64)
    vmem = (
        r_words
        + ring_bytes // 4
        + oc_rows
        + 2 * _TAB_ROWS
        + 2 * _MAX_CODES
        + 4 * max_far
        + _ST_ROWS
        + 768
    ) * LANES * 4
    return {
        "r_words": r_words,
        "oc_rows": oc_rows,
        "ring_rows": ring_bytes // 4,
        "n_chunks": n_chunks,
        "t_step": t_step,
        "far_dist": eff_far,
        "vmem_bytes": vmem,
    }


def accepts(
    max_clen: int, max_isize: int, chunk_bytes: int = _DEFAULT_CHUNK
) -> Tuple[bool, str]:
    """Would the streaming lanes tier take a member of this shape?

    Pure host logic (no jax import needed at decision time beyond module
    load): returns ``(True, "")`` or ``(False, reason)`` with reason in
    ``{"size", "vmem"}`` — the tier-down taxonomy the flate wrappers
    count.  A full 64 KiB BGZF member is accepted."""
    if max_isize > _MAX_ISIZE:
        return False, "size"
    geo = stream_geometry(max_clen, max_isize, chunk_bytes)
    if geo["vmem_bytes"] > _VMEM_BUDGET_BYTES:
        return False, "vmem"
    return True, ""


def _kernel_factory(
    R: int,
    OC_ROWS: int,
    RING_ROWS: int,
    T_STEP: int,
    MAX_BLOCKS: int,
    MAX_FAR: int,
    FAR_DIST: int,
):
    """R stream words/lane resident; OC_ROWS output words/lane streamed per
    grid step; RING_ROWS LZ77 resolve window; T_STEP wave budget/step."""
    OC_BYTES = OC_ROWS * 4
    MAX_ROUNDS = MAX_BLOCKS + 2

    def kernel(
        streams_ref,
        nbits_ref,
        isize_ref,
        out_ref,
        nout_ref,
        ok_ref,
        farc_ref,
        fara_ref,
        farb_ref,
        ring_ref,
        st_ref,
        tabs_ref,
        fa_ref,
        fb_ref,
    ):
        k = pl.program_id(0)
        rows_R = lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
        rows_RING = lax.broadcasted_iota(jnp.int32, (RING_ROWS, LANES), 0)
        rows_ll = lax.broadcasted_iota(jnp.int32, (288, LANES), 0)
        rows_dl = lax.broadcasted_iota(jnp.int32, (32, LANES), 0)
        rows_cl = lax.broadcasted_iota(jnp.int32, (19, LANES), 0)
        rows_hc = lax.broadcasted_iota(jnp.int32, (_MAX_CODES, LANES), 0)
        rows_F = lax.broadcasted_iota(jnp.int32, (MAX_FAR, LANES), 0)
        rows_st = lax.broadcasted_iota(jnp.int32, (_ST_ROWS, LANES), 0)
        nbits = nbits_ref[:, :]
        isize = isize_ref[:, :]

        @pl.when(k == 0)
        def _init():
            init = jnp.zeros((_ST_ROWS, LANES), jnp.int32)
            init = jnp.where(
                (rows_st == _R_OK) & jnp.broadcast_to(nbits > 0, init.shape),
                1,
                init,
            )
            init = jnp.where(
                (rows_st == _R_DONE)
                & jnp.broadcast_to(nbits == 0, init.shape),
                1,
                init,
            )
            init = jnp.where(
                rows_st == _R_HOLE, jnp.int32(0x7FFFFFFF), init
            )
            st_ref[:, :] = init
            tabs_ref[:, :] = jnp.zeros((_TAB_ROWS, LANES), jnp.int32)
            fa_ref[:, :] = jnp.zeros((MAX_FAR, LANES), jnp.int32)
            fb_ref[:, :] = jnp.zeros((MAX_FAR, LANES), jnp.int32)
            ring_ref[:, :] = jnp.zeros((RING_ROWS, LANES), jnp.int32)

        chunk_end = (k + 1) * OC_BYTES

        def word_at(widx):
            onehot = rows_R == widx
            return jnp.sum(
                jnp.where(onehot, streams_ref[:, :], 0),
                axis=0,
                keepdims=True,
            ).astype(jnp.uint32)

        def window(cur):
            """32 stream bits at per-lane bit cursor ``cur`` [1,128]."""
            widx = cur >> 5
            w0 = word_at(widx)
            w1 = word_at(widx + 1)
            sh = (cur & 31).astype(jnp.uint32)
            return jnp.where(sh == 0, w0, (w0 >> sh) | (w1 << (32 - sh)))

        def ring_byte_at(rv, pos):
            """Byte at global output position ``pos`` from the ring
            snapshot ``rv`` (valid within the last RING_ROWS*4 bytes)."""
            wrow = (pos >> 2) & (RING_ROWS - 1)
            word = jnp.sum(
                jnp.where(rows_RING == wrow, rv, 0),
                axis=0,
                keepdims=True,
            ).astype(jnp.uint32)
            return (word >> (8 * (pos & 3)).astype(jnp.uint32)) & 0xFF

        # Fixed-Huffman length vectors (RFC 1951 §3.2.6), built from iota
        # in-kernel (Pallas kernels cannot capture array constants).
        fixed_ll = jnp.where(
            rows_ll < 144,
            8,
            jnp.where(rows_ll < 256, 9, jnp.where(rows_ll < 280, 7, 8)),
        ).astype(jnp.int32)
        fixed_dl = jnp.full((32, LANES), 5, jnp.int32)

        # ---- restore the carried member state ---------------------------
        st = st_ref[:, :]

        def reg(r):
            return st[r : r + 1, :]

        cur0 = reg(_R_CUR)
        n_out0 = reg(_R_NOUT)
        ok0 = reg(_R_OK) == 1
        done0 = reg(_R_DONE) == 1
        inblk0 = reg(_R_INBLK) == 1
        stored0 = reg(_R_STORED) == 1
        bfin0 = reg(_R_BFINAL) == 1
        crem0 = reg(_R_CREM)
        cdist0 = reg(_R_CDIST)
        srem0 = reg(_R_SREM)
        farc0 = reg(_R_FARC)
        hole0 = reg(_R_HOLE)
        blk0 = reg(_R_BLK)
        tabs0 = tabs_ref[:, :]
        fara0 = fa_ref[:, :]
        farb0 = fb_ref[:, :]

        # ---- header parse + table build (one new block per round) -------
        def parse_fn(c):
            (cur, n_out, okv, done, inblk, stored, bfin, crem, cdist,
             srem, farc, hole, blk, tabs, fara, farb) = c
            need = okv & ~done & ~inblk & (n_out < chunk_end)
            hdr = window(cur)
            bfinal = (hdr & 1) == 1
            btype = ((hdr >> 1) & 3).astype(jnp.int32)
            okv = okv & (~need | (btype != 3))
            blk = blk + need.astype(jnp.int32)
            okv = okv & (~need | (blk <= MAX_BLOCKS))
            is_stored = need & (btype == 0)
            is_dyn = need & (btype == 2)

            # stored block setup (byte-aligned LEN/NLEN)
            st_bit = (cur + 3 + 7) & ~7
            ln_w = window(st_bit)
            s_len = (ln_w & 0xFFFF).astype(jnp.int32)
            s_nlen = ((ln_w >> 16) & 0xFFFF).astype(jnp.int32)
            okv = okv & (
                ~is_stored
                | (
                    (s_len == (s_nlen ^ 0xFFFF))
                    & (st_bit + 32 + 8 * s_len <= nbits)
                )
            )

            # dynamic header parse (btype=10)
            at = cur + 3
            hlit = (window(at) & 31).astype(jnp.int32) + 257
            hdist = (window(at + 5) & 31).astype(jnp.int32) + 1
            hclen = (window(at + 10) & 15).astype(jnp.int32) + 4
            okv = okv & (~is_dyn | ((hlit <= 286) & (hdist <= 30)))
            cl_lens = jnp.zeros((19, LANES), jnp.int32)
            for i in range(19):
                bits = (window(at + 14 + 3 * i) & 7).astype(jnp.int32)
                bits = jnp.where(i < hclen, bits, 0)
                cl_lens = jnp.where(
                    rows_cl == int(CLC_ORDER[i]), bits, cl_lens
                )
            clc = _build_canon(cl_lens, 19, 7)
            okv = okv & (
                ~is_dyn | _kraft_ok(clc[1], 7, allow_single=False)
            )
            total_codes = hlit + hdist

            # Code-length RLE: one CLC token per wave, lockstep across
            # lanes; repeats land as masked row-range writes.
            def hcond(s):
                pos, cnt, prev, okh, lens_all, it = s
                act = is_dyn & okh & (cnt < total_codes)
                return (it < _MAX_HDR_TOKENS) & jnp.any(act)

            def hbody(s):
                pos, cnt, prev, okh, lens_all, it = s
                w = window(pos)
                r7 = _rev_bits(w, 7)
                csym, cL, cm = _canon_decode(
                    r7, clc[0], clc[1], clc[2], clc[3], 7, rows_cl
                )
                ext = (w >> cL.astype(jnp.uint32)).astype(jnp.int32)
                rep = jnp.where(
                    csym < 16,
                    1,
                    jnp.where(
                        csym == 16,
                        3 + (ext & 3),
                        jnp.where(
                            csym == 17, 3 + (ext & 7), 11 + (ext & 127)
                        ),
                    ),
                )
                val = jnp.where(
                    csym < 16, csym, jnp.where(csym == 16, prev, 0)
                )
                nb = cL + jnp.where(
                    csym < 16,
                    0,
                    jnp.where(
                        csym == 16, 2, jnp.where(csym == 17, 3, 7)
                    ),
                )
                act = is_dyn & okh & (cnt < total_codes)
                okh = okh & (~act | cm)
                wr = act & okh
                lens_all = jnp.where(
                    (rows_hc >= cnt) & (rows_hc < cnt + rep) & wr,
                    val,
                    lens_all,
                )
                pos = pos + jnp.where(wr, nb, 0)
                cnt = cnt + jnp.where(wr, rep, 0)
                prev = jnp.where(wr, val, prev)
                return pos, cnt, prev, okh, lens_all, it + 1

            hpos, hcnt, _, hok, lens_all, _ = lax.while_loop(
                hcond,
                hbody,
                (
                    at + 14 + 3 * hclen,
                    jnp.zeros((1, LANES), jnp.int32),
                    jnp.zeros((1, LANES), jnp.int32),
                    jnp.ones((1, LANES), bool),
                    jnp.zeros((_MAX_CODES, LANES), jnp.int32),
                    jnp.int32(0),
                ),
            )
            okv = okv & (
                ~is_dyn | (hok & (hcnt == total_codes) & (hpos <= nbits))
            )

            dyn_ll = jnp.where(rows_ll < hlit, lens_all[:288, :], 0)
            dl_cols = []
            for d in range(32):
                col = jnp.sum(
                    jnp.where(rows_hc == hlit + d, lens_all, 0),
                    axis=0,
                    keepdims=True,
                )
                dl_cols.append(jnp.where(d < hdist, col, 0))
            dyn_dl = jnp.concatenate(dl_cols, axis=0)

            use_dyn = btype == 2
            ll_lens = jnp.where(use_dyn, dyn_ll, fixed_ll)
            dl_lens = jnp.where(use_dyn, dyn_dl, fixed_dl)
            ll = _build_canon(ll_lens, 288, 15)
            dl = _build_canon(dl_lens, 32, 15)
            okv = okv & (
                ~is_dyn
                | (
                    _kraft_ok(ll[1], 15, allow_single=True)
                    & _kraft_ok(dl[1], 15, allow_single=True)
                )
            )
            data_start = jnp.where(
                use_dyn, hpos, jnp.where(btype == 0, st_bit + 32, cur + 3)
            )

            # Merge new tables for lanes opening a Huffman block; stored
            # lanes keep their (unused) bank.
            merge = need & (btype != 0)
            tabs_new = jnp.concatenate(
                [
                    ll[3],
                    dl[3],
                    _stack16(ll[0]),
                    _stack16(ll[1]),
                    _stack16(ll[2]),
                    _stack16(dl[0]),
                    _stack16(dl[1]),
                    _stack16(dl[2]),
                ],
                axis=0,
            )
            tabs = jnp.where(merge, tabs_new, tabs)
            cur = jnp.where(need, data_start, cur)
            inblk = inblk | need
            stored = jnp.where(need, is_stored, stored)
            bfin = jnp.where(need, bfinal, bfin)
            srem = jnp.where(need, jnp.where(is_stored, s_len, 0), srem)
            return (cur, n_out, okv, done, inblk, stored, bfin, crem,
                    cdist, srem, farc, hole, blk, tabs, fara, farb)

        # ---- one emit phase: byte-per-wave until every lane stalls ------
        def emit_phase(c, wav):
            (cur, n_out, okv, done, inblk, stored, bfin, crem, cdist,
             srem, farc, hole, blk, tabs, fara, farb) = c
            ll_first = tabs[_T_LLFIRST:_T_LLCOUNT, :]
            ll_count = tabs[_T_LLCOUNT:_T_LLSYMOFF, :]
            ll_symoff = tabs[_T_LLSYMOFF:_T_DLFIRST, :]
            ll_syms = tabs[_T_LLSYM:_T_DLSYM, :]
            dl_first = tabs[_T_DLFIRST:_T_DLCOUNT, :]
            dl_count = tabs[_T_DLCOUNT:_T_DLSYMOFF, :]
            dl_symoff = tabs[_T_DLSYMOFF:_TAB_ROWS, :]
            dl_syms = tabs[_T_DLSYM:_T_LLFIRST, :]

            def econd(s):
                (it, cur, n_out, okv, done, inblk, stored, bfin, crem,
                 cdist, srem, farc, hole, fara, farb) = s
                act = okv & ~done & inblk & (n_out < chunk_end)
                return (it < T_STEP) & jnp.any(act)

            def ebody(s):
                (it, cur, n_out, okv, done, inblk, stored, bfin, crem,
                 cdist, srem, farc, hole, fara, farb) = s
                active = okv & ~done & inblk & (n_out < chunk_end)
                in_copy = active & (crem > 0)
                in_stored = active & stored & (srem > 0)
                decode = active & ~stored & ~in_copy

                rv = ring_ref[:, :]
                # 1. LZ77 copy byte (reads before this wave's writes).
                cb = ring_byte_at(rv, n_out - cdist)
                # 2. stored byte (cursor is byte-aligned in stored blocks).
                sb = window(cur) & 0xFF
                # 3. token decode at the cursor.
                w = window(cur)
                sym, L, m = _canon_decode(
                    _rev_bits(w, 15), ll_first, ll_count, ll_symoff,
                    ll_syms, 15, rows_ll,
                )
                islit = decode & m & (sym < 256)
                iseob = decode & m & (sym == 256)
                islen = decode & m & (sym > 256) & (sym < 286)
                bad = decode & (~m | (sym >= 286))
                li = jnp.clip(sym - 257, 0, 28)
                le = _sel_const(li, LEN_EXTRA)
                lenval = _sel_const(li, LEN_BASE) + (
                    (w >> L.astype(jnp.uint32)).astype(jnp.int32)
                    & ((1 << le) - 1)
                )
                wd = window(cur + L + le)
                dsym, Ld, md = _canon_decode(
                    _rev_bits(wd, 15), dl_first, dl_count, dl_symoff,
                    dl_syms, 15, rows_dl,
                )
                bad = bad | (islen & (~md | (dsym >= 30)))
                dsym = jnp.clip(dsym, 0, 29)
                de = _sel_const(dsym, DIST_EXTRA)
                dist = _sel_const(dsym, DIST_BASE) + (
                    (wd >> Ld.astype(jnp.uint32)).astype(jnp.int32)
                    & ((1 << de) - 1)
                )
                adv = jnp.where(islit | iseob, L, L + le + Ld + de)
                bad = bad | (decode & (cur + adv > nbits))
                bad = bad | (islen & (dist > n_out))
                islit = islit & ~bad
                iseob = iseob & ~bad
                islen = islen & ~bad
                okv = okv & ~bad

                # Far copies (past the resolve window, or sourcing at/after
                # a deferred destination) are recorded for the host pass;
                # their output bytes stay garbage and n_out skips ahead.
                far = islen & (
                    (dist > FAR_DIST)
                    | (n_out - dist + lenval > hole)
                )
                can_rec = farc < MAX_FAR
                okv = okv & (~far | can_rec)
                rec = far & can_rec
                fara = jnp.where(
                    (rows_F == farc) & rec, (n_out << 9) | lenval, fara
                )
                farb = jnp.where((rows_F == farc) & rec, dist, farb)
                hole = jnp.where(rec, jnp.minimum(hole, n_out), hole)
                farc = farc + rec.astype(jnp.int32)
                near = islen & ~far

                # Emits: exactly one byte per emitting lane this wave,
                # written into the ring at the lane's output cursor.
                byte = jnp.where(
                    in_copy, cb, jnp.where(in_stored, sb, sym & 0xFF)
                ).astype(jnp.uint32)
                emit = in_copy | in_stored | islit
                wrow = (n_out >> 2) & (RING_ROWS - 1)
                sh = (8 * (n_out & 3)).astype(jnp.uint32)
                onehot = (rows_RING == wrow) & emit
                cleared = rv & jnp.broadcast_to(
                    ~(jnp.uint32(0xFF) << sh).astype(jnp.int32), rv.shape
                )
                word_new = cleared | jnp.broadcast_to(
                    (byte << sh).astype(jnp.int32), rv.shape
                )
                ring_ref[:, :] = jnp.where(onehot, word_new, rv)

                n_out = (
                    n_out
                    + emit.astype(jnp.int32)
                    + jnp.where(rec, lenval, 0)
                )
                crem = jnp.where(
                    near, lenval, crem - in_copy.astype(jnp.int32)
                )
                cdist = jnp.where(near, dist, cdist)
                srem = srem - in_stored.astype(jnp.int32)
                cur = (
                    cur
                    + jnp.where(decode & ~bad, adv, 0)
                    + 8 * in_stored.astype(jnp.int32)
                )
                retire = iseob | (active & stored & (srem == 0))
                inblk = inblk & ~retire
                done = done | (retire & bfin)
                return (it + 1, cur, n_out, okv, done, inblk, stored,
                        bfin, crem, cdist, srem, farc, hole, fara, farb)

            (wav, cur, n_out, okv, done, inblk, stored, bfin, crem,
             cdist, srem, farc, hole, fara, farb) = lax.while_loop(
                econd,
                ebody,
                (wav, cur, n_out, okv, done, inblk, stored, bfin, crem,
                 cdist, srem, farc, hole, fara, farb),
            )
            return (cur, n_out, okv, done, inblk, stored, bfin, crem,
                    cdist, srem, farc, hole, blk, tabs, fara, farb), wav

        # ---- per-step rounds: parse-if-needed, then emit ----------------
        def rcond(state):
            rnd, wav, c = state
            cur, n_out, okv, done = c[0], c[1], c[2], c[3]
            progress = okv & ~done & (n_out < chunk_end)
            return (rnd < MAX_ROUNDS) & (wav < T_STEP) & jnp.any(progress)

        def rbody(state):
            rnd, wav, c = state
            okv, done, inblk, n_out = c[2], c[3], c[4], c[1]
            need = okv & ~done & ~inblk & (n_out < chunk_end)
            c = lax.cond(jnp.any(need), parse_fn, lambda x: x, c)
            c, wav = emit_phase(c, wav)
            return rnd + 1, wav, c

        carry0 = (cur0, n_out0, ok0, done0, inblk0, stored0, bfin0,
                  crem0, cdist0, srem0, farc0, hole0, blk0, tabs0,
                  fara0, farb0)
        _, _, c = lax.while_loop(
            rcond, rbody, (jnp.int32(0), jnp.int32(0), carry0)
        )
        (cur, n_out, okv, done, inblk, stored, bfin, crem, cdist, srem,
         farc, hole, blk, tabs, fara, farb) = c

        # A lane that still has chunk capacity after the round budget is
        # stuck (pathological stream): fail it rather than loop forever.
        stuck = okv & ~done & (n_out < chunk_end)
        okv = okv & ~stuck

        # ---- persist state, stream the finished tile out ----------------
        stw = jnp.zeros((_ST_ROWS, LANES), jnp.int32)

        def setreg(stw, r, v):
            return jnp.where(rows_st == r, jnp.broadcast_to(v, stw.shape),
                             stw)

        stw = setreg(stw, _R_CUR, cur)
        stw = setreg(stw, _R_NOUT, n_out)
        stw = setreg(stw, _R_OK, okv.astype(jnp.int32))
        stw = setreg(stw, _R_DONE, done.astype(jnp.int32))
        stw = setreg(stw, _R_INBLK, inblk.astype(jnp.int32))
        stw = setreg(stw, _R_STORED, stored.astype(jnp.int32))
        stw = setreg(stw, _R_BFINAL, bfin.astype(jnp.int32))
        stw = setreg(stw, _R_CREM, crem)
        stw = setreg(stw, _R_CDIST, cdist)
        stw = setreg(stw, _R_SREM, srem)
        stw = setreg(stw, _R_FARC, farc)
        stw = setreg(stw, _R_HOLE, hole)
        stw = setreg(stw, _R_BLK, blk)
        st_ref[:, :] = stw
        tabs_ref[:, :] = tabs
        fa_ref[:, :] = fara
        fb_ref[:, :] = farb

        start = (k * OC_ROWS) & (RING_ROWS - 1)
        out_ref[:, :] = ring_ref[pl.ds(start, OC_ROWS), :]
        nout_ref[:, :] = n_out
        ok_ref[:, :] = (okv & done & (n_out == isize)).astype(jnp.int32)
        farc_ref[:, :] = farc
        fara_ref[:, :] = fara
        farb_ref[:, :] = farb

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "r_words", "oc_rows", "ring_rows", "n_chunks", "t_step",
        "max_blocks", "max_far", "far_dist", "interpret",
    ),
)
def _launch(
    streams, nbits, isizes, r_words: int, oc_rows: int, ring_rows: int,
    n_chunks: int, t_step: int, max_blocks: int, max_far: int,
    far_dist: int, interpret: bool,
):
    kernel = _kernel_factory(
        r_words, oc_rows, ring_rows, t_step, max_blocks, max_far, far_dist
    )
    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(
                (oc_rows, LANES), lambda k: (k, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, LANES), lambda k: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, LANES), lambda k: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, LANES), lambda k: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (max_far, LANES), lambda k: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (max_far, LANES), lambda k: (0, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_chunks * oc_rows, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            jax.ShapeDtypeStruct((max_far, LANES), jnp.int32),
            jax.ShapeDtypeStruct((max_far, LANES), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((ring_rows, LANES), jnp.int32),
            pltpu.VMEM((_ST_ROWS, LANES), jnp.int32),
            pltpu.VMEM((_TAB_ROWS, LANES), jnp.int32),
            pltpu.VMEM((max_far, LANES), jnp.int32),
            pltpu.VMEM((max_far, LANES), jnp.int32),
        ],
        interpret=interpret,
    )(streams, nbits, isizes)


def _apply_far_copies(
    lane_bytes: np.ndarray, fara: np.ndarray, farb: np.ndarray, n: int
) -> None:
    """Replay a lane's deferred far-distance copies in stream order.

    Events are recorded so that every source byte is either kernel-correct
    or patched by an earlier event, so an in-order byte loop (which also
    handles overlapping copies) reconstructs the exact LZ77 semantics."""
    for e in range(n):
        a = int(fara[e])
        dst, ln, dist = a >> 9, a & 511, int(farb[e])
        for k in range(ln):
            lane_bytes[dst + k] = lane_bytes[dst + k - dist]


@jax.jit
def _unpack_device(o: jax.Array) -> jax.Array:
    """[R,128] int32 word columns → [128, R*4] uint8 lane-major bytes
    (device-to-device; the on-chip output-residency view)."""
    bs = jnp.stack(
        [(o >> (8 * k)) & 0xFF for k in range(4)], axis=1
    ).astype(jnp.uint8)  # [R, 4, 128]
    return jnp.transpose(bs, (2, 0, 1)).reshape(o.shape[1], -1)


def inflate_lanes_ex(
    comp: np.ndarray,
    clens: np.ndarray,
    isizes: np.ndarray,
    max_blocks: int = 12,
    max_far: int = 64,
    far_dist: int = 1 << 15,
    chunk_bytes: int = _DEFAULT_CHUNK,
    interpret=None,
    keep_device: bool = False,
):
    """:func:`inflate_lanes` plus the on-chip output residency handoff.

    Returns ``(out, ok, dev)`` — ``dev`` is a device-resident uint8
    [128, out_cap] lane-major byte view of the decoded payloads (member
    j's bytes at ``dev[j, :isizes[j]]``), or ``None`` whenever the view
    would not be byte-exact without host help: more than one 128-lane
    group, any member not decoded (``ok=0``), or any deferred far copy
    (host-replayed bytes are not in the device buffer)."""
    return _inflate_lanes_impl(
        comp, clens, isizes, max_blocks, max_far, far_dist, chunk_bytes,
        interpret, keep_device,
    )


def inflate_lanes(
    comp: np.ndarray,
    clens: np.ndarray,
    isizes: np.ndarray,
    max_blocks: int = 12,
    max_far: int = 64,
    far_dist: int = 1 << 15,
    chunk_bytes: int = _DEFAULT_CHUNK,
    interpret=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched lockstep inflate of general DEFLATE members (any mix of
    stored/fixed/dynamic blocks), 128 members per kernel launch, output
    streamed chunk-by-chunk to HBM.

    ``comp`` uint8 [B, C] (rows zero-padded), ``clens``/``isizes`` int32
    [B].  Returns ``(out uint8 [B, max_isize], ok bool [B])`` — a member
    that is corrupt, exceeds ``max_blocks`` DEFLATE blocks, overflows the
    ``max_far`` far-copy budget, or whose *compressed* geometry exceeds
    the VMEM budget comes back ``ok=False`` and the caller tiers down to
    the XLA/host decoders.  Full 64 KiB BGZF members are inside the
    streaming geometry.  ``far_dist`` bounds the in-kernel LZ77 resolve
    ring; copies past it defer to the host-assisted replay pass (the
    default ring covers every legal DEFLATE distance, so the pass is
    exercised only by the windowed configuration).  ``chunk_bytes`` sets
    the per-lane output tile per grid step (power of two)."""
    out, ok_all, _ = _inflate_lanes_impl(
        comp, clens, isizes, max_blocks, max_far, far_dist, chunk_bytes,
        interpret, False,
    )
    return out, ok_all


def _inflate_lanes_impl(
    comp, clens, isizes, max_blocks, max_far, far_dist, chunk_bytes,
    interpret, keep_device,
):
    B, C = comp.shape
    if B == 0:
        return np.empty((0, 0), np.uint8), np.empty(0, bool), None
    max_out = int(isizes.max()) if len(isizes) else 0
    max_clen = int(clens.max()) if len(clens) else 0
    out = np.zeros((B, max_out), dtype=np.uint8)
    ok_all = np.zeros(B, dtype=bool)
    dev = None
    geo = stream_geometry(
        max_clen, max_out, chunk_bytes, far_dist, max_far, max_blocks
    )
    if geo["vmem_bytes"] > _VMEM_BUDGET_BYTES or max_out > _MAX_ISIZE:
        return out, ok_all, None
    r_words = geo["r_words"]
    oc_rows = geo["oc_rows"]
    n_chunks = geo["n_chunks"]
    out_cap = n_chunks * oc_rows * 4
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    for g0 in range(0, B, LANES):
        g1 = min(B, g0 + LANES)
        n = g1 - g0
        # Transpose the group: member j's words go down lane j.
        grp = np.zeros((r_words * 4, LANES), dtype=np.uint8)
        grp[:C, :n] = comp[g0:g1].T
        words = (
            grp.reshape(r_words, 4, LANES).astype(np.uint32)
            * (np.uint32(1) << (8 * np.arange(4, dtype=np.uint32)))[
                None, :, None
            ]
        ).sum(axis=1).astype(np.uint32).view(np.int32)
        nbits = np.zeros((1, LANES), dtype=np.int32)
        nbits[0, :n] = clens[g0:g1] * 8
        isz = np.zeros((1, LANES), dtype=np.int32)
        isz[0, :n] = isizes[g0:g1]
        o, nout, okk, farc, fara, farb = _launch(
            jnp.asarray(words), jnp.asarray(nbits), jnp.asarray(isz),
            r_words, oc_rows, geo["ring_rows"], n_chunks, geo["t_step"],
            max_blocks, max_far, geo["far_dist"], bool(interpret),
        )
        by = np.asarray(o).view(np.uint32)
        bytes_mat = np.zeros((out_cap, LANES), dtype=np.uint8)
        for k in range(4):
            bytes_mat[k::4] = ((by >> np.uint32(8 * k)) & 0xFF).astype(
                np.uint8
            )
        nout = np.asarray(nout)[0]
        okk = np.asarray(okk)[0].astype(bool)
        farc = np.asarray(farc)[0]
        fara = np.asarray(fara)
        farb = np.asarray(farb)
        for j in range(n):
            i = g0 + j
            okj = okk[j] and int(nout[j]) == int(isizes[i])
            ok_all[i] = okj
            if okj:
                lane = bytes_mat[: isizes[i], j].copy()
                if farc[j]:
                    _apply_far_copies(
                        lane, fara[:, j], farb[:, j], int(farc[j])
                    )
                out[i, : isizes[i]] = lane
        if (
            keep_device
            and B <= LANES
            and bool(ok_all[:B].all())
            and int(farc[:n].sum()) == 0
        ):
            # On-chip output residency: the lane-major device byte view is
            # exact (no host-side far-copy patches), so the caller can
            # feed the device-parse chain without a d2h→h2d bounce.
            dev = _unpack_device(o)
    return out, ok_all, dev
