"""Lockstep-lane Pallas FASTQ record-boundary scanner.

The fourth client of the lockstep-lane engine: up to 128 decoded FASTQ
chunks ride the 128 vector lanes of one kernel, each advancing its own
byte-wave line/frame state machine.  This vectorizes the split-guesser
pattern from ``io/fastq.py`` — find ``@``-record starts with the
4-line / plus-line / quality-length consistency check so a ``@`` inside
a quality string never splits a record — at device speed over payloads
that just came off the inflate lanes.

Wave model: global wave ``t`` consumes one byte per lane (4 wave-bytes
packed per int32 word, per the engine house style).  Each lane keeps a
packed register file in VMEM scratch — current-line accumulators, an
8-deep completed-line history (first byte, CR-stripped length, start
offset), sync/frame state — and every per-lane update is a dense
iota-compare column select, never a gather.

Resync is the **two-consecutive-verified-records** rule (the BGZF
split-guesser stance, shared with
``FastqInputFormat.position_at_first_record``): an ``@`` line is
trusted as a record start only when the 8-line history forms two
back-to-back frames ``(@, seq, +, qual)`` with ``len(seq) == len(qual)``
in both.  Aligned lanes (a chunk that starts exactly at a record start)
skip resync and validate every frame as it completes.

Claim protocol: lane ``k`` owns records *starting* inside its claim
region ``[0, chunk_len)``; the window extends ``overlap`` bytes past the
claim so the tail record can complete.  A record starting at or past
``chunk_len`` belongs to the next lane and halts the scan (``done``).

Per-lane ``[n_records, ok]`` meta tiers a chunk that cannot sync, hits a
frame violation, overflows the record tile, or leaves a claimed record
unfinished down to the host tiers *per chunk, never per launch*:
``scan_window_host`` (vectorized NumPy, the semantic reference) and
``scan_window_py`` (the plain Python walker oracle, which also carries
the ``errors=salvage`` quarantine semantics).  Tests run the kernel in
interpret mode on CPU and compare record tables bit-for-bit.

Record rows: each record is 8 int32s
``[id_start, id_len, seq_start, seq_len, plus_start, plus_len,
qual_start, qual_len]`` — offsets window-relative, lengths CR-stripped
(CRLF input parses identically to LF across all tiers).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...spec.fragment import FormatException

LANES = 128

#: VMEM budget for one launch (window bank + record tile + state).
_VMEM_BUDGET_BYTES = 14 << 20

#: Window cap per lane (bytes); chunks re-chunked at or below the
#: device inflate payload stay far under this.
_MAX_WINDOW = 1 << 17

_AT = 0x40     # '@'
_PLUS = 0x2B   # '+'
_NL = 0x0A
_CR = 0x0D

# Packed per-lane register rows in the ``st`` scratch bank.
_S_LEN = 0      # raw byte count of the current line (newline excluded)
_S_FIRST = 1    # first byte of the current line, -1 while empty
_S_START = 2    # window offset of the current line start
_S_LAST = 3     # last byte seen on the current line, -1 while empty
_S_LC = 4       # completed-line count
_S_SYNC = 5     # 1 once the frame phase is locked
_S_BASE = 6     # line index of the first locked record start
_S_NREC = 7     # claimed records emitted
_S_OK = 8       # 1 until a tier-down condition fires
_S_DONE = 9     # 1 once the first beyond-claim record start is seen
_H_FC = 10      # rows 10..17: first byte of the last 8 lines
_H_LN = 18      # rows 18..25: CR-stripped length of the last 8 lines
_H_ST = 26      # rows 26..33: window offset of the last 8 lines
_ST_ROWS = 40

_REC_W = 8


class WindowOverrun(Exception):
    """A claimed record does not finish inside the scan window; the
    caller rescans the whole run serially (bigger effective window)."""


@dataclass
class RecordScanStats:
    """Where each chunk of a scan went, and why the fallen fell."""

    lanes: int = 0            # chunks fully scanned on the lanes
    host: int = 0             # chunks rescued by the host tiers
    launches: int = 0
    reasons: Dict[str, int] = field(default_factory=dict)

    def tier_down(self, reason: str) -> None:
        self.host += 1
        self.reasons[reason] = self.reasons.get(reason, 0) + 1


def scan_geometry(max_window: int, rec_cap: int) -> Tuple[int, int]:
    """Static launch geometry: input words per lane (4 bytes packed per
    int32, padded to a 256-word step) and the record-tile row count."""
    n_words = max(256, -(-max_window // 4))
    n_words = -(-n_words // 256) * 256
    return n_words, _REC_W * rec_cap


def accepts(max_window: int, rec_cap: int) -> Tuple[bool, str]:
    """Geometry gate for one launch group; reasons feed the tier-down
    taxonomy (``size`` / ``vmem``)."""
    if max_window > _MAX_WINDOW:
        return False, "size"
    n_words, rec_rows = scan_geometry(max_window, rec_cap)
    vmem = (n_words + rec_rows + _ST_ROWS + 8) * LANES * 4
    if vmem > _VMEM_BUDGET_BYTES:
        return False, "vmem"
    return True, ""


def default_rec_cap(max_window: int) -> int:
    """Record-tile capacity for a window: the 6-byte minimum record
    bounds the count, clamped so the tile stays inside the VMEM budget
    (an overflowing lane tiers down with reason ``records``)."""
    cap = max_window // 6 + 2
    n_words, _ = scan_geometry(max_window, 1)
    budget_rows = _VMEM_BUDGET_BYTES // (LANES * 4) - n_words - _ST_ROWS - 8
    cap = min(cap, max(8, budget_rows // _REC_W))
    return -(-cap // 64) * 64


def _kernel_factory(n_words: int, rec_cap: int):
    rec_rows = _REC_W * rec_cap

    def kernel(meta_ref, words_ref, recs_ref, mout_ref, st_ref):
        rows_st = lax.broadcasted_iota(jnp.int32, (_ST_ROWS, LANES), 0)
        rows_rec = lax.broadcasted_iota(jnp.int32, (rec_rows, LANES), 0)
        chunk_len = meta_ref[0, :]
        win_len = meta_ref[1, :]
        aligned = meta_ref[2, :]
        final = meta_ref[3, :]

        def row(st, r):
            return jnp.sum(jnp.where(rows_st == r, st, 0), axis=0)

        def put(st, r, val):
            return jnp.where(rows_st == r, val, st)

        # Register-file init: empty line accumulators, history of
        # impossible lines, sync pre-locked on aligned lanes.
        st0 = jnp.zeros((_ST_ROWS, LANES), jnp.int32)
        st0 = put(st0, _S_FIRST, jnp.full((LANES,), -1, jnp.int32))
        st0 = put(st0, _S_LAST, jnp.full((LANES,), -1, jnp.int32))
        st0 = put(st0, _S_OK, jnp.ones((LANES,), jnp.int32))
        st0 = put(st0, _S_SYNC, aligned)
        for i in range(8):
            st0 = put(st0, _H_FC + i, jnp.full((LANES,), -1, jnp.int32))

        hist_mask = (rows_st >= _H_FC) & (rows_st < _H_ST + 8)

        def complete_line(st, recs, live, t_next):
            """One newline (real or synthetic) on the lanes in ``live``:
            push the finished line into history, attempt sync, emit and
            validate claimed frames, reset the line accumulators."""
            cur_len = row(st, _S_LEN)
            cur_first = row(st, _S_FIRST)
            cur_start = row(st, _S_START)
            cur_last = row(st, _S_LAST)
            eff = cur_len - (cur_last == _CR).astype(jnp.int32)

            rolled = jnp.concatenate([st[1:], st[:1]], axis=0)
            st = jnp.where(hist_mask & live, rolled, st)
            st = put(st, _H_FC + 7, jnp.where(live, cur_first, row(st, _H_FC + 7)))
            st = put(st, _H_LN + 7, jnp.where(live, eff, row(st, _H_LN + 7)))
            st = put(st, _H_ST + 7, jnp.where(live, cur_start, row(st, _H_ST + 7)))

            lc = row(st, _S_LC) + live.astype(jnp.int32)
            st = put(st, _S_LC, lc)

            fc = [row(st, _H_FC + i) for i in range(8)]
            ln = [row(st, _H_LN + i) for i in range(8)]
            stt = [row(st, _H_ST + i) for i in range(8)]

            synced = row(st, _S_SYNC)
            base = row(st, _S_BASE)
            nrec = row(st, _S_NREC)
            ok = row(st, _S_OK)
            done = row(st, _S_DONE)

            frame_a = (fc[0] == _AT) & (fc[2] == _PLUS) & (ln[1] == ln[3])
            frame_b = (fc[4] == _AT) & (fc[6] == _PLUS) & (ln[5] == ln[7])
            can_sync = live & (synced == 0) & (lc >= 8) & frame_a & frame_b
            sync_claim = can_sync & (stt[0] < chunk_len)
            sync_beyond = can_sync & (stt[0] >= chunk_len)

            bnd = live & (synced == 1) & (((lc - base) & 3) == 0)
            claim_b = stt[4] < chunk_len
            emit2 = (bnd | sync_claim) & claim_b & frame_b
            bad = bnd & claim_b & (~frame_b)
            done_now = ((bnd | sync_claim) & (~claim_b)) | sync_beyond

            n_emits = sync_claim.astype(jnp.int32) + emit2.astype(jnp.int32)
            over = (nrec + n_emits) > rec_cap
            good = (ok == 1) & (~over)
            do1 = sync_claim & good
            do2 = emit2 & good

            vals1 = [stt[0], ln[0], stt[1], ln[1], stt[2], ln[2], stt[3], ln[3]]
            for s in range(_REC_W):
                tgt = _REC_W * nrec + s
                recs = jnp.where((rows_rec == tgt) & do1, vals1[s], recs)
            nrec1 = nrec + do1.astype(jnp.int32)
            vals2 = [stt[4], ln[4], stt[5], ln[5], stt[6], ln[6], stt[7], ln[7]]
            for s in range(_REC_W):
                tgt = _REC_W * nrec1 + s
                recs = jnp.where((rows_rec == tgt) & do2, vals2[s], recs)

            st = put(st, _S_NREC, nrec1 + do2.astype(jnp.int32))
            st = put(st, _S_OK, jnp.where(bad | (live & over), 0, ok))
            st = put(st, _S_DONE, jnp.where(done_now, 1, done))
            st = put(st, _S_SYNC, jnp.where(sync_claim, 1, synced))
            st = put(st, _S_BASE, jnp.where(sync_claim, lc - 8, base))

            st = put(st, _S_LEN, jnp.where(live, 0, row(st, _S_LEN)))
            st = put(st, _S_FIRST, jnp.where(live, -1, row(st, _S_FIRST)))
            st = put(st, _S_LAST, jnp.where(live, -1, row(st, _S_LAST)))
            st = put(st, _S_START, jnp.where(live, t_next, row(st, _S_START)))
            return st, recs

        words = words_ref[:, :]

        def body(w, carry):
            st, recs = carry
            word = lax.dynamic_index_in_dim(words, w, 0, keepdims=False)
            for jj in range(4):
                byte = (word >> (8 * jj)) & 0xFF
                t = w * 4 + jj
                live = (t < win_len) & (row(st, _S_OK) == 1) \
                    & (row(st, _S_DONE) == 0)
                is_nl = live & (byte == _NL)
                txt = live & (byte != _NL)
                cur_first = row(st, _S_FIRST)
                st = put(st, _S_FIRST,
                         jnp.where(txt & (cur_first < 0), byte, cur_first))
                st = put(st, _S_LAST,
                         jnp.where(txt, byte, row(st, _S_LAST)))
                st = put(st, _S_LEN, row(st, _S_LEN) + txt.astype(jnp.int32))
                st, recs = complete_line(st, recs, is_nl, t + 1)
            return st, recs

        st = st0
        recs0 = jnp.zeros((rec_rows, LANES), jnp.int32)
        st, recs = lax.fori_loop(0, n_words, body, (st, recs0))

        # Synthetic final newline: end-of-run text without a trailing
        # '\n' still completes its last line, as in the host walker.
        tail = (final == 1) & (row(st, _S_LEN) > 0) \
            & (row(st, _S_OK) == 1) & (row(st, _S_DONE) == 0)
        st, recs = complete_line(st, recs, tail, win_len)

        # Final verdicts.  A claimed record left unfinished (partial
        # frame, or dangling text on a non-final window) and a lane
        # that never synced over real content both tier down.
        synced = row(st, _S_SYNC)
        lc = row(st, _S_LC)
        base = row(st, _S_BASE)
        done = row(st, _S_DONE)
        ok = row(st, _S_OK)
        pend = (lc - base) & 3
        part_start = jnp.zeros((LANES,), jnp.int32)
        for i in range(8):
            part_start = jnp.where(8 - pend == i, row(st, _H_ST + i),
                                   part_start)
        bad_tail = (synced == 1) & (done == 0) & (pend != 0) \
            & (part_start < chunk_len)
        bad_text = (done == 0) & (row(st, _S_LEN) > 0) \
            & (row(st, _S_START) < chunk_len)
        bad_sync = (synced == 0) & (done == 0) \
            & ((lc > 0) | (row(st, _S_LEN) > 0))
        ok = jnp.where(bad_tail | bad_text | bad_sync, 0, ok)

        recs_ref[:, :] = recs
        mout_ref[:, :] = jnp.stack([row(st, _S_NREC), ok], axis=0)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("n_words", "rec_cap", "interpret")
)
def _launch(meta, words, n_words: int, rec_cap: int, interpret: bool):
    kernel = _kernel_factory(n_words, rec_cap)
    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((_REC_W * rec_cap, LANES), jnp.int32),
            jax.ShapeDtypeStruct((2, LANES), jnp.int32),
        ),
        scratch_shapes=[pltpu.VMEM((_ST_ROWS, LANES), jnp.int32)],
        interpret=interpret,
    )(meta, words)


def _pack_windows(group, n_words):
    """Windows into the transposed [n_words, LANES] int32 word bank."""
    bank = np.zeros((n_words * 4, LANES), np.uint8)
    meta = np.zeros((4, LANES), np.int32)
    for lane, (_, win, chunk_len, algn, fin) in enumerate(group):
        bank[: len(win), lane] = np.frombuffer(win, np.uint8)
        meta[0, lane] = chunk_len
        meta[1, lane] = len(win)
        meta[2, lane] = 1 if algn else 0
        meta[3, lane] = 1 if fin else 0
    words = (
        bank.reshape(n_words, 4, LANES).astype(np.int32)
        * (1 << (8 * np.arange(4, dtype=np.int32)))[None, :, None]
    ).sum(axis=1, dtype=np.int32)
    return meta, words


def record_scan(
    chunks: Sequence[Tuple[bytes, int, bool, bool]],
    rec_cap: Optional[int] = None,
    interpret=None,
) -> Tuple[List[Optional[np.ndarray]], RecordScanStats]:
    """Batched lockstep record-boundary scan, up to 128 chunks per
    launch.  ``chunks`` entries are ``(window, chunk_len, aligned,
    final)`` — the window is the claim region plus overlap.

    Returns ``(tables, stats)``: per-chunk ``[n, 8]`` int32 record
    tables with ``None`` for every chunk that tiered down (the caller
    rescues those through :func:`scan_window_host` and the walker) plus
    the tier taxonomy.  Tier-down is per chunk, never per launch."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    stats = RecordScanStats()
    B = len(chunks)
    outs: List[Optional[np.ndarray]] = [None] * B
    accepted = []
    for i, (win, chunk_len, algn, fin) in enumerate(chunks):
        if len(win) > _MAX_WINDOW:
            stats.tier_down("size")
            continue
        accepted.append((i, bytes(win), int(chunk_len), bool(algn),
                         bool(fin)))
    for g0 in range(0, len(accepted), LANES):
        group = accepted[g0: g0 + LANES]
        max_win = max(len(win) for _, win, _, _, _ in group)
        cap = rec_cap if rec_cap is not None else default_rec_cap(max_win)
        okg, reason = accepts(max_win, cap)
        if not okg:
            for _ in group:
                stats.tier_down(reason)
            continue
        n_words, _ = scan_geometry(max_win, cap)
        meta, words = _pack_windows(group, n_words)
        recs, mout = _launch(
            jnp.asarray(meta), jnp.asarray(words),
            n_words=n_words, rec_cap=cap, interpret=bool(interpret),
        )
        recs = np.asarray(recs)
        mout = np.asarray(mout)
        stats.launches += 1
        for lane, (i, win, chunk_len, _, _) in enumerate(group):
            n, lane_ok = int(mout[0, lane]), int(mout[1, lane])
            if not lane_ok:
                stats.tier_down("scan")
                continue
            stats.lanes += 1
            outs[i] = (
                recs[: _REC_W * n, lane]
                .reshape(n, _REC_W).astype(np.int32, copy=True)
            )
    return outs, stats


# ---------------------------------------------------------------------------
# Host tiers: the NumPy scan is the semantic reference the kernel must
# match bit-for-bit where it reports ok; the Python walker beneath it is
# the oracle and carries the salvage quarantine semantics.

def _line_table_np(win: np.ndarray, final: bool):
    """Completed lines of a window: (starts, first raw byte or -1,
    CR-stripped lengths, unterminated tail start or -1).  On a final
    window the unterminated tail counts as a last line, exactly as the
    kernel's synthetic final newline."""
    nl = np.flatnonzero(win == _NL)
    starts = np.concatenate(([0], nl + 1)).astype(np.int64)
    tail_start = int(starts[-1]) if starts[-1] < len(win) else -1
    if final and tail_start >= 0:
        ends = np.concatenate((nl, [len(win)])).astype(np.int64)
        tail_start = -1
    else:
        ends = nl.astype(np.int64)
    starts = starts[: len(ends)]
    raw = ends - starts
    eff = raw.copy()
    if len(ends):
        has_cr = (raw > 0) & (win[np.maximum(ends - 1, 0)] == _CR)
        eff = raw - has_cr.astype(np.int64)
    fc = np.full(len(starts), -1, np.int64)
    if len(starts):
        nonempty = raw > 0
        fc[nonempty] = win[starts[nonempty]]
    return starts, fc, eff, tail_start


def scan_window_host(
    win, chunk_len: int, aligned: bool, final: bool
) -> np.ndarray:
    """Vectorized NumPy record scan of one window; the semantic
    reference for the kernel tier (bit-exact where the kernel reports
    ``ok``).  Raises :class:`FormatException` on a frame violation or a
    truncated claimed record, and :class:`WindowOverrun` when a claimed
    record runs past a non-final window (the caller widens by rescanning
    the whole run serially)."""
    win = np.frombuffer(bytes(win), np.uint8)
    if len(win) == 0:
        return np.zeros((0, _REC_W), np.int32)
    starts, fc, eff, tail_start = _line_table_np(win, final)
    nlines = len(starts)

    # frame[i]: lines i..i+3 form one (@, seq, +, qual) frame.
    frame = np.zeros(nlines, bool)
    if nlines >= 4:
        frame[: nlines - 3] = (
            (fc[: nlines - 3] == _AT) & (fc[2: nlines - 1] == _PLUS)
            & (eff[1: nlines - 2] == eff[3: nlines])
        )

    if aligned:
        l0 = 0
    else:
        # Two-consecutive-verified-records rule, with the end-of-data
        # relaxation (a final window trusts a lone trailing frame —
        # the stance shared with position_at_first_record).
        ver = np.zeros(nlines, bool)
        if nlines >= 8:
            ver[: nlines - 7] = frame[: nlines - 7] & frame[4: nlines - 3]
        if final and nlines >= 4:
            lo = max(0, nlines - 7)
            ver[lo: nlines - 3] |= frame[lo: nlines - 3]
        cand = np.flatnonzero(ver)
        if len(cand) == 0 or starts[int(cand[0])] >= chunk_len:
            # No trusted record start inside the claim: either the
            # window is the tail of the previous lane's record, or it is
            # garbage — the caller's run-tiling reconciliation tells the
            # two apart and rescans serially on a gap.
            return np.zeros((0, _REC_W), np.int32)
        l0 = int(cand[0])

    recs = []
    li = l0
    while li < nlines and starts[li] < chunk_len:
        if li + 3 >= nlines:
            if final:
                raise FormatException(
                    "fastq: truncated record at end of input"
                )
            raise WindowOverrun("fastq: claimed record overruns window")
        if not frame[li]:
            raise FormatException(
                "fastq: frame violation at offset %d" % starts[li]
            )
        recs.append([
            starts[li], eff[li], starts[li + 1], eff[li + 1],
            starts[li + 2], eff[li + 2], starts[li + 3], eff[li + 3],
        ])
        li += 4
    if tail_start >= 0 and tail_start < chunk_len and li >= nlines:
        raise WindowOverrun("fastq: claimed record overruns window")
    return np.asarray(recs, np.int32).reshape(len(recs), _REC_W)


def scan_window_py(
    win, chunk_len: int, aligned: bool, final: bool, salvage: bool = False
) -> Tuple[np.ndarray, int]:
    """Plain-Python walker: the oracle beneath the NumPy tier, one line
    at a time.  With ``salvage=True`` a frame violation or truncated
    claimed tail quarantines whole 4-line frames (never tearing one) and
    resyncs with the two-record rule; returns ``(records,
    n_quarantine_events)``."""
    win = bytes(win)
    lines = []       # (start, first byte or -1, eff len)
    pos = 0
    while pos < len(win):
        nl = win.find(b"\n", pos)
        if nl < 0:
            if not final:
                break
            nl = len(win)
        raw = nl - pos
        eff = raw - (1 if raw and win[nl - 1: nl] == b"\r" else 0)
        lines.append((pos, win[pos] if raw else -1, eff))
        pos = nl + 1
    tail_start = pos if pos < len(win) else -1
    n_quar = 0

    def frame_at(i):
        """True/False for a complete 4-line frame at ``i``; None when
        fewer than 4 lines remain."""
        if i + 3 >= len(lines):
            return None
        return (lines[i][1] == _AT and lines[i + 2][1] == _PLUS
                and lines[i + 1][2] == lines[i + 3][2])

    def sync_from(i0):
        for i in range(i0, len(lines)):
            fa = frame_at(i)
            if fa is None:
                break
            if not fa:
                continue
            fb = frame_at(i + 4)
            if not (fb or (fb is None and final)):
                continue
            if lines[i][0] >= chunk_len:
                return None   # first trusted start belongs to the next lane
            return i
        return None   # no trusted start: previous lane's tail, or garbage
                      # (the caller's run-tiling reconciliation decides)

    recs = []
    li = 0 if aligned else sync_from(0)
    while li is not None and li < len(lines) and lines[li][0] < chunk_len:
        fr = frame_at(li)
        if fr:
            s = lines[li: li + 4]
            recs.append([s[0][0], s[0][2], s[1][0], s[1][2],
                         s[2][0], s[2][2], s[3][0], s[3][2]])
            li += 4
            continue
        if fr is None and not final:
            raise WindowOverrun("fastq: claimed record overruns window")
        if not salvage:
            raise FormatException(
                "fastq: %s at offset %d" % (
                    "truncated record" if fr is None else "frame violation",
                    lines[li][0],
                )
            )
        n_quar += 1
        if fr is None:
            li = None
            break
        try:
            li = sync_from(li + 1)
        except (FormatException, WindowOverrun):
            li = None
    if tail_start >= 0 and tail_start < chunk_len \
            and li is not None and li >= len(lines):
        raise WindowOverrun("fastq: claimed record overruns window")
    return (np.asarray(recs, np.int32).reshape(len(recs), _REC_W), n_quar)
