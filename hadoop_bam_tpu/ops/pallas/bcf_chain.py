"""Pallas TPU kernel: BCF record-chain walk over an inflated BGZF stream.

The BAM boundary walk (``ops/pallas/chain.py``) applied to BCF's framing
(spec/bcf.py): records are ``[u32 l_shared][u32 l_indiv][shared block]
[indiv block]`` back to back, so the chain step is
``pos += 8 + l_shared + l_indiv`` — and unlike BAM, the first 24 bytes of
the shared block are fixed-width columns (CHROM/POS/rlen/QUAL/n_allele/
n_fmt), so the same walk that finds boundaries also emits the query-plane
columns in one pass.  Genotype (indiv) blocks are never touched — the
reference's LazyBCFGenotypesContext stance, kept on device.

Structure mirrors ``chain.py`` exactly:

- fixed chunks, one ``pallas_call`` each, scalar cursor carried through
  SMEM so a record spanning chunks resumes where the previous stopped;
- inside a chunk the walk is a ``lax.while_loop`` of scalar VMEM loads
  (u32 at an arbitrary byte offset = two aligned word loads recombined);
- seven per-record output columns (start offset + the six fixed shared
  words) accumulate in register-carried ``[1, 128]`` buffers flushed with
  aligned full-row stores.

Tier-down contract (per window, never per launch): an implausible
``l_shared``/``l_indiv``, a record overrunning the payload, or a count
overflow sets ``ok=False`` for the *window* and the caller re-walks that
window on the host (:func:`walk_chain_host`, bit-exact by construction)
or falls through to the ``spec/bcf.py`` per-record oracle.  Validity here
is framing-only — CHROM range, dictionary and typed-value checks stay
with the host decoders that own them (io/bcf.py).
"""

from __future__ import annotations

import functools
import struct

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: Bytes of stream walked per pallas_call (same budget story as chain.py).
CHUNK = 4 << 20
#: A record is ≥ 32 bytes (8-byte lengths + 24-byte fixed shared fields),
#: so a chunk can start at most CHUNK//32 records — lane-aligned bound.
MAX_REC_PER_CHUNK = -(-(CHUNK // 32 + 8) // 128) * 128
#: Fixed shared prefix every record carries (spec/bcf.py decode_record).
_MIN_SHARED = 24
#: Guesser sanity bounds (BCFSplitGuesser.java:273-360, io/bcf.py).
_MAX_SHARED = 1 << 24
_MAX_INDIV = 1 << 28

#: Column order of the walk's output tuple (after the start offsets).
COLUMNS = ("chrom", "pos", "rlen", "qual_bits", "n_allele_info", "n_fmt_sample")


def _bcf_chain_kernel(
    cursor_in_ref,  # SMEM (1,) int32: absolute resume cursor
    base_ref,  # SMEM (1,) int32: absolute byte offset of this chunk
    limit_ref,  # SMEM (1,) int32: chunk-local end of record starts
    hard_ref,  # SMEM (1,) int32: stream-wide record-start limit
    nbytes_ref,  # SMEM (1,) int32: payload length (truncation gate)
    words_ref,  # VMEM [rows, 128] int32: chunk bytes (+margin) as words
    offs_ref,  # VMEM [MAX_REC_PER_CHUNK//128, 128] int32 out: starts (abs)
    chrom_ref,  # VMEM out: CHROM contig index column
    pos_ref,  # VMEM out: 0-based POS column
    rlen_ref,  # VMEM out: rlen column
    qual_ref,  # VMEM out: QUAL float32 bit pattern column
    nai_ref,  # VMEM out: (n_allele<<16 | n_info) column
    nfs_ref,  # VMEM out: (n_fmt<<24 | n_sample) column
    count_ref,  # SMEM (1,) int32 out
    cursor_out_ref,  # SMEM (1,) int32 out: resume cursor (abs)
    err_ref,  # SMEM (1,) int32 out: 1 on implausible/overrunning record
):
    """Same VMEM moves as chain.py's kernel — dynamic row-pair loads with
    masked lane extraction for the unaligned u32 reads, register-carried
    [1, 128] buffers flushed with aligned full-row stores — with six more
    reads per step for the fixed shared columns."""
    base = base_ref[0]
    limit = limit_ref[0]
    hard = hard_ref[0]
    n_payload = nbytes_ref[0]
    lane2 = lax.broadcasted_iota(jnp.int32, (2, 128), 1)
    row2 = lax.broadcasted_iota(jnp.int32, (2, 128), 0)
    lane1 = lax.broadcasted_iota(jnp.int32, (1, 128), 1)

    def u32_at(abs_off):
        off = abs_off - base
        wi = off >> 2
        r = wi >> 7
        rows = words_ref[pl.ds(r, 2), :]  # [2, 128]

        def word(widx):
            rr = (widx >> 7) - r
            ll = widx & 127
            return jnp.sum(
                jnp.where((row2 == rr) & (lane2 == ll), rows, 0)
            )

        w0 = word(wi).astype(jnp.uint32)
        w1 = word(wi + 1).astype(jnp.uint32)
        sh = ((off & 3) << 3).astype(jnp.uint32)
        lo = w0 >> sh
        hi = jnp.where(sh == 0, jnp.uint32(0), w1 << (32 - sh))
        return (lo | hi).astype(jnp.int32)

    def cond(state):
        cur, n, err = state[0], state[1], state[2]
        return (cur < limit) & (cur + 8 <= hard) & (err == 0) & (
            n < MAX_REC_PER_CHUNK
        )

    def body(state):
        cur, n, err, bufs = state
        l_shared = u32_at(cur)
        l_indiv = u32_at(cur + 4)
        bad = (
            (l_shared < _MIN_SHARED)
            | (l_shared >= _MAX_SHARED)
            | (l_indiv < 0)
            | (l_indiv >= _MAX_INDIV)
        )
        # Truncation gate: guarded by `bad` so the sum cannot wrap int32
        # (l_shared/l_indiv are bounded when it is evaluated for real).
        bad = bad | (
            jnp.where(bad, n_payload + 1, cur + 8 + l_shared + l_indiv)
            > n_payload
        )
        body_off = cur + 8
        vals = (
            cur,
            u32_at(body_off),  # CHROM
            u32_at(body_off + 4),  # POS (0-based)
            u32_at(body_off + 8),  # rlen
            u32_at(body_off + 12),  # QUAL bits
            u32_at(body_off + 16),  # n_allele<<16 | n_info
            u32_at(body_off + 20),  # n_fmt<<24 | n_sample
        )
        refs = (offs_ref, chrom_ref, pos_ref, rlen_ref, qual_ref, nai_ref, nfs_ref)
        is_lane = lane1 == (n & 127)
        new_bufs = []
        for ref, buf, v in zip(refs, bufs, vals):
            buf = jnp.where(is_lane, v, buf)
            ref[pl.ds(n >> 7, 1), :] = buf
            new_bufs.append(buf)
        nxt = jnp.where(bad, limit, cur + 8 + l_shared + l_indiv)
        return (
            nxt,
            n + jnp.where(bad, 0, 1),
            err | bad.astype(jnp.int32),
            tuple(new_bufs),
        )

    cur0 = cursor_in_ref[0]
    bufs0 = tuple(jnp.zeros((1, 128), jnp.int32) for _ in range(7))
    cur, n, err, _ = lax.while_loop(
        cond, body, (cur0, jnp.int32(0), jnp.int32(0), bufs0)
    )
    count_ref[0] = n
    cursor_out_ref[0] = cur
    err_ref[0] = err | jnp.int32(n >= MAX_REC_PER_CHUNK)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bcf_chain_chunk(
    cursor, base, limit, hard, n_payload, words, interpret: bool = False
):
    col = jax.ShapeDtypeStruct((MAX_REC_PER_CHUNK // 128, 128), jnp.int32)
    one = jax.ShapeDtypeStruct((1,), jnp.int32)
    return pl.pallas_call(
        _bcf_chain_kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            tuple(pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(7))
            + tuple(pl.BlockSpec(memory_space=pltpu.SMEM) for _ in range(3))
        ),
        out_shape=tuple([col] * 7 + [one] * 3),
        interpret=interpret,
    )(cursor, base, limit, hard, n_payload, words)


@functools.partial(jax.jit, static_argnames=("n_chunks", "interpret"))
def _bcf_chain_all(
    stream_words, start, hard, n_payload, n_chunks: int, interpret: bool
):
    """Run the chunk kernel over the stream, carrying the cursor, then
    compact the per-chunk column blocks with the same gather-form
    searchsorted flatten as chain.py — applied to all seven columns with
    one shared index computation."""
    WPC = CHUNK // 4
    cursor = jnp.reshape(start.astype(jnp.int32), (1,))
    parts = [[] for _ in range(7)]
    counts = []
    err_any = jnp.int32(0)
    for k in range(n_chunks):
        base = jnp.full((1,), k * CHUNK, jnp.int32)
        limit = jnp.minimum(jnp.int32((k + 1) * CHUNK), hard)
        words = lax.dynamic_slice(
            stream_words, (k * WPC,), (WPC + 256,)
        ).reshape(-1, 128)
        outs = _bcf_chain_chunk(
            cursor,
            base,
            limit[None],
            hard[None],
            n_payload[None],
            words,
            interpret=interpret,
        )
        for i in range(7):
            parts[i].append(outs[i].reshape(-1))
        count, cursor, err = outs[7], outs[8], outs[9]
        counts.append(count[0])
        err_any = err_any | err[0]
    counts = jnp.stack(counts)
    cum = jnp.cumsum(counts)
    total = cum[-1]
    t = jnp.arange(n_chunks * MAX_REC_PER_CHUNK, dtype=jnp.int32)
    k_of_t = jnp.searchsorted(cum, t, side="right").astype(jnp.int32)
    k_c = jnp.clip(k_of_t, 0, n_chunks - 1)
    local = t - jnp.where(k_c > 0, cum[k_c - 1], 0)
    li = jnp.clip(local, 0, MAX_REC_PER_CHUNK - 1)
    flats = []
    for i in range(7):
        stacked = jnp.stack(parts[i])  # [K, MAXR]
        flat = stacked[k_c, li]
        flats.append(jnp.where(t < total, flat, 0))
    # Clean completion: the walk stops when no further record can start
    # (cursor + 8 > hard) — same stance as the host `while p + 8 <= end`.
    ok = (err_any == 0) & (cursor[0] + 8 > hard)
    return tuple(flats) + (total, ok)


def walk_chain_device(payload, start: int, limit: int, interpret=None):
    """BCF record starts + fixed shared columns, computed on device.

    ``payload``: uint8 array (device or host) holding the inflated BCF
    stream; records start at ``start`` and keep starting while
    ``pos + 8 <= limit`` (the straddling tail record completes from bytes
    past ``limit``, exactly like the host loop in io/bcf.py).  Returns
    ``(offs, chrom, pos, rlen, qual_bits, n_allele_info, n_fmt_sample,
    count, ok)`` int32 device arrays — columns are valid in
    ``[:count]``; ``ok`` is False on a truncated/implausible chain and
    the caller re-walks this window on the host (never disables the
    tier for later windows)."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        payload = np.frombuffer(payload, dtype=np.uint8)
    a = jnp.asarray(payload, dtype=jnp.uint8)
    n = int(a.shape[0])
    if n > 2**31 - (1 << 29):
        # Offsets and the truncation sum `cur + 8 + l_shared + l_indiv`
        # ride int32 lanes; the margin keeps the sum (l_indiv < 2^28)
        # inside int32 for any in-bounds cursor.  Split windows are MB
        # class, so callers never get near this.
        raise ValueError(
            f"walk_chain_device: payload of {n} bytes exceeds the int32 "
            "offset domain; window the stream before calling"
        )
    n_chunks = max(1, -(-n // CHUNK))
    nbytes_pad = n_chunks * CHUNK + 256 * 4
    pad = nbytes_pad - a.shape[0]
    if pad > 0:
        a = jnp.pad(a, (0, pad))
    words = lax.bitcast_convert_type(
        a[:nbytes_pad].reshape(-1, 4), jnp.int32
    ).reshape(-1)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _bcf_chain_all(
        words,
        jnp.int32(start),
        jnp.int32(limit),
        jnp.int32(n),
        n_chunks,
        bool(interpret),
    )


def walk_chain_host(payload, start: int, limit: int):
    """Bit-exact NumPy twin of the device walk — the mid tier.

    Same framing-only validity rules, same straddling-tail semantics.
    Returns the same 9-tuple with host int32 arrays; ``ok=False`` leaves
    the caller to the ``spec/bcf.py`` per-record oracle, whose error
    semantics (STRICT raises and all) are the contract."""
    buf = bytes(payload) if isinstance(payload, (bytearray, memoryview)) else payload
    if isinstance(buf, np.ndarray):
        buf = buf.tobytes()
    n_payload = len(buf)
    rows = []
    p = int(start)
    lim = int(limit)
    ok = True
    while p + 8 <= lim:
        l_shared, l_indiv = struct.unpack_from("<II", buf, p)
        if (
            l_shared < _MIN_SHARED
            or l_shared >= _MAX_SHARED
            or l_indiv >= _MAX_INDIV
            or p + 8 + l_shared + l_indiv > n_payload
        ):
            ok = False
            break
        rows.append((p,) + struct.unpack_from("<iiiIII", buf, p + 8))
        p += 8 + l_shared + l_indiv
    cols = np.asarray(rows, dtype=np.int64).reshape(-1, 7)
    out = tuple(
        cols[:, i].astype(np.uint32).astype(np.int32) for i in range(7)
    )
    return out + (np.int32(len(rows)), bool(ok))


def walk_chain(payload, start: int, limit: int, interpret=None):
    """Tiered walk: device kernel, then the bit-exact host twin — the
    tier decision is per *window* (this call), never sticky.

    Returns ``(cols, count, ok, tier)`` where ``cols`` is the 7-tuple of
    host int32 numpy columns (offs + :data:`COLUMNS`) truncated to
    ``count`` and ``tier`` is ``"device"`` or ``"host"`` — whichever
    produced the answer.  ``ok=False`` (both tiers declined: corrupt or
    truncated framing) returns the host tier's verdict so the caller
    falls through to the exact ``spec/bcf.py`` decoder."""
    try:
        res = walk_chain_device(payload, start, limit, interpret=interpret)
        ok = bool(res[8])
        if ok:
            count = int(res[7])
            cols = tuple(
                np.asarray(res[i])[:count].astype(np.int32) for i in range(7)
            )
            return cols, count, True, "device"
    except Exception:
        pass
    res = walk_chain_host(payload, start, limit)
    count = int(res[7])
    return tuple(res[:7]), count, bool(res[8]), "host"
