"""Device ops: the dense phases of the pipeline as JAX/XLA/Pallas programs.

The reference's per-record hot loops (BGZF inflate → BAM decode → key → sort,
BAMRecordReader.java:223-232 and the shuffle) become batched device programs:

- ``decode``: fixed-field gather from a raw record-byte tensor into the SoA
  columns (the device half of SURVEY.md §7 stage 4),
- ``keys``: the 64-bit coordinate key as (hi, lo) int32/uint32 pairs with
  Java-exact signed semantics (BAMRecordReader.java:81-121),
- ``sort``: single-chip multi-key sort producing a permutation,
- ``quality``: FASTQ/QSEQ quality-encoding conversion + histograms
  (SequencedFragment.java:229-309 semantics) — elementwise + one-hot matmul
  so the MXU does the counting,
- ``pallas``: hand-written TPU kernels for the ops XLA doesn't fuse well.

Everything here is shape-static and jit-compatible; ragged record tails stay
in the uint8 sideband and are addressed by offset columns.
"""

from . import cigar, decode, keys, sort, quality  # noqa: F401
