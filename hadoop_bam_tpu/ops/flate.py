"""Device DEFLATE codec: batched BGZF inflate/deflate as TPU array programs.

The reference's compression layer is htsjdk's zlib behind
``BGZFCodec``/``BGZFCompressionOutputStream`` (util/BGZFCodec.java:33-63,
util/BGZFCompressionOutputStream.java) — native code on the host, one
stream at a time.  Here the hot loop is re-architected for a TPU: a batch
of BGZF members is decoded *in parallel as one array program* instead of
bit-serially.

Deflate (compress), device side
    Two tiers.  The top tier is the lockstep-lane Pallas **encoder**
    (ops/pallas/deflate_lanes.py): up to 128 members in the 128 vector
    lanes, each running a greedy hash-head LZ77 match-finder (4-byte
    hash, two-generation probe chain, min match 4) whose token stream is
    then bit-packed by the same gather-only emit trick as below — real
    compression, within ~1.05x of zlib level-1 on BAM-class data, wired
    into the part-write path (``deflate_blocks_device`` /
    ``io.bam.write_part_fast``) behind ``hadoopbam.deflate.lanes`` /
    ``HBAM_DEFLATE_LANES`` / the local-latency auto rule.

    The floor tier is literal-only fixed-Huffman DEFLATE (btype=01):
    every input byte maps to an 8- or 9-bit code independently, so the
    whole emit is a prefix sum over code lengths plus a per-output-bit
    searchsorted — embarrassingly parallel, MXU-free but VPU/HBM
    friendly.  "Fixed Huffman is enough for validity" (SURVEY.md §7
    stage 6); ratio is traded for zero serial device work.  ``level=0``
    bypasses both and emits stored blocks (uncompressed parts).

Inflate (decompress), device side
    DEFLATE decode looks inherently bit-serial (each Huffman codeword's
    start depends on the previous).  The TPU formulation is the two-pass
    speculative scheme (SURVEY.md §7 "hard parts" mitigation):

    1. *Speculative symbol resolve*: for EVERY bit position p, decode the
       token that WOULD start at p (one 512-entry table gather + a few
       arithmetic ops), yielding next[p] (where the following token would
       start), emit[p] (bytes it would produce) and its payload.
    2. *Chain marking by pointer doubling*: the true token sequence is
       the orbit of bit 3 (after the block header) under ``next``.
       log2(nbits) rounds of ``reach |= scatter(reach, jump);
       jump = jump[jump]`` mark it — O(n log n) work, all gathers/
       scatters, no data-dependent control flow.
    3. *Parallel LZ77 copy resolve*: output offsets are a prefix sum of
       on-chain emits; every output byte's source is either a literal
       token or a strictly-earlier output position (for overlapping
       copies, ``src = o - d + (j - o) mod d``), so log2(out) rounds of
       pointer-jumping materialize all back-references.

    Three kernels share the machinery: ``inflate_fixed`` (all-fixed
    members, one launch), ``inflate_stored`` (zlib level 0), and
    ``inflate_dynamic`` — the general decoder that builds canonical
    Huffman tables ON DEVICE per member per block (code-length RLE via a
    short ``lax.scan``, counts→first-codes→argsort ranks all dense) and
    walks any per-member mix of stored/fixed/dynamic blocks in a
    block-sequential outer loop, so real zlib output (level ≥1 emits
    dynamic blocks) decodes on device instead of tiering to the host.
    Members that fail any device check still tier down to native zlib in
    the ``bgzf_decompress_device`` wrapper — the same fallback stance as
    the split planner's index→guesser chain.

Host-side helpers assemble/validate the BGZF framing (headers, CRC32,
ISIZE — spec/bgzf.py owns the layout) around the device payloads.

Performance status (v5e-1, measured): these XLA kernels bottleneck on
XLA:TPU gather throughput (~70M gathered elements/s) — roughly 0.5-1 MB/s
end to end, far below the native host tier (~170 MB/s zlib).  They are
the correctness floor and the universal device fallback; the hot path is
the lockstep-lane Pallas tier below.

Device codec tiers, top to bottom (each tier falls through per member):

1. **LIVE — lockstep lanes, general** (ops/pallas/inflate_lanes.py):
   128 BGZF members ride the 128 vector lanes of one Pallas kernel —
   per-member canonical Huffman tables built on chip, transposed-stream
   bit windows, 15-compare canonical decode, byte-per-wave lockstep
   emit, windowed LZ77 resolve with a host-assisted pass for rare
   far-distance copies, and per-member ``[n_out, ok]`` meta so one bad
   member tiers down without dooming its launch.  Built on the walk
   engine ops/pallas/inflate_probe.py measured at **~748 ns per
   128-token wave** on the v5e (~170M tokens/s ≈ **~340 MB/s** at
   DEFLATE's ~2 output bytes/token — ~2x the native host tier).  Gated
   by the ``hadoopbam.inflate.lanes`` conf key / ``HBAM_INFLATE_LANES``
   env var, defaulting to the same local-latency auto rule as the
   device-resident parse (:func:`lanes_tier_enabled`).
2. **LIVE — lockstep lanes, literal-only fixed**
   (ops/pallas/inflate_fixed.py): the specialized slice for what
   :func:`deflate_fixed` emits; preferred for the "fixed" group on real
   chips when the general tier is off.
3. **XLA array programs** (this module): ``inflate_stored`` /
   ``inflate_fixed`` / ``inflate_dynamic`` — slow but fully general and
   platform-agnostic.
4. **Native host zlib** (spec/bgzf.py + native/): the unconditional
   correctness tier; nothing above it is load-bearing for correctness.

Both lanes kernels are HBM-STREAMING: they grid over fixed-size chunks
(output chunks for the decoder, input chunks for the encoder) with
per-lane state — bit cursors, canonical tables, a 32 KiB LZ77 resolve
ring, hash-head chains, token tiles — carried across grid steps in VMEM
scratch, so full 64 KiB BGZF members ride the lanes tiers instead of
tiering down at a whole-member-VMEM cap.  On-chip output residency is
wired too: ``inflate_blocks_device(return_device=True)`` leaves the
inflated split in HBM and the device-parse chain kernel consumes it
without the d2h/h2d bounce (``RecordBatch.device_data``).

Caveat for all launches: XLA:TPU gathers silently mis-index above 2^24
elements per launch (f32 index precision); wrappers chunk accordingly.
"""

from __future__ import annotations

import os
import struct
import zlib
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import faults
from ..spec import bgzf
from ..utils.tracing import stage as _trace_stage

# --------------------------------------------------------------------------
# Fixed-Huffman tables (RFC 1951 §3.2.5-3.2.6), precomputed as numpy consts.
# --------------------------------------------------------------------------

LEN_BASE = np.array(
    [3, 4, 5, 6, 7, 8, 9, 10, 11, 13, 15, 17, 19, 23, 27, 31, 35, 43, 51,
     59, 67, 83, 99, 115, 131, 163, 195, 227, 258], dtype=np.int32)
LEN_EXTRA = np.array(
    [0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3, 4, 4, 4,
     4, 5, 5, 5, 5, 0], dtype=np.int32)
DIST_BASE = np.array(
    [1, 2, 3, 4, 5, 7, 9, 13, 17, 25, 33, 49, 65, 97, 129, 193, 257, 385,
     513, 769, 1025, 1537, 2049, 3073, 4097, 6145, 8193, 12289, 16385,
     24577], dtype=np.int32)
DIST_EXTRA = np.array(
    [0, 0, 0, 0, 1, 1, 2, 2, 3, 3, 4, 4, 5, 5, 6, 6, 7, 7, 8, 8, 9, 9, 10,
     10, 11, 11, 12, 12, 13, 13], dtype=np.int32)


def _bit_reverse(v: int, n: int) -> int:
    r = 0
    for _ in range(n):
        r = (r << 1) | (v & 1)
        v >>= 1
    return r


def _fixed_code(sym: int) -> Tuple[int, int]:
    """(code, nbits) of a fixed-Huffman litlen symbol (MSB-first code)."""
    if sym <= 143:
        return 0x30 + sym, 8
    if sym <= 255:
        return 0x190 + (sym - 144), 9
    if sym <= 279:
        return sym - 256, 7
    return 0xC0 + (sym - 280), 8


def _build_litlen_table() -> np.ndarray:
    """512-entry stream-order lookup: next-9-bits → (sym<<4 | codelen)."""
    table = np.full(512, (287 << 4) | 8, dtype=np.int32)  # default: invalid
    for sym in range(288):
        code, n = _fixed_code(sym)
        rev = _bit_reverse(code, n)
        for free in range(1 << (9 - n)):
            table[rev | (free << n)] = (sym << 4) | n
    return table


def _build_dist_table() -> np.ndarray:
    """32-entry stream-order lookup: next-5-bits → distance symbol."""
    table = np.zeros(32, dtype=np.int32)
    for dsym in range(32):
        table[_bit_reverse(dsym, 5)] = dsym
    return table


LITLEN_TABLE = _build_litlen_table()
DIST_TABLE = _build_dist_table()

# Fixed-Huffman code lengths (RFC 1951 §3.2.6) — the btype=01 table is just
# a particular code-length vector, so the dynamic decoder subsumes it.
FIXED_LITLEN_LENS = np.array(
    [8] * 144 + [9] * 112 + [7] * 24 + [8] * 8, dtype=np.int32
)
FIXED_DIST_LENS = np.array([5] * 32, dtype=np.int32)
# Order in which code-length-code lengths appear in a dynamic header.
CLC_ORDER = np.array(
    [16, 17, 18, 0, 8, 7, 9, 6, 10, 5, 11, 4, 12, 3, 13, 2, 14, 1, 15],
    dtype=np.int32,
)
REV8 = np.array([_bit_reverse(i, 8) for i in range(256)], dtype=np.int32)

# Worst case the literal-only emit expands 9/8 + header; cap the per-member
# payload so a device-deflated block always fits the u16 BSIZE field.
DEV_MAX_PAYLOAD = 0xDF00  # 57088 → ≤ 64252-byte block, < 0x10000
# Default block payload for the device deflate: sized so every emitted
# member fits the lockstep Pallas decoder's whole-member-in-VMEM budget
# (ops/pallas/inflate_fixed.py) — device-compressed BGZF then decodes
# entirely on the Pallas tier.  Literal-only emit has no cross-block
# matches, so smaller blocks cost only the ~26-byte header per block
# (~0.1% at this size), not compression ratio.
DEV_DEFAULT_PAYLOAD = 24000

# Member payload for the lockstep-lane LZ77 encoder tier
# (ops/pallas/deflate_lanes.py).  The streaming geometry (token tiles
# chunked out to HBM, persistent hash heads) lifted the old 4 KiB
# whole-member-VMEM cap, so the lanes tier now emits full-size members:
# DEV_MAX_PAYLOAD is the largest payload whose worst-case (all-literal)
# fixed-Huffman emit still fits the u16 BSIZE field — the same blocking
# real BGZF writers target.
DEV_LZ_PAYLOAD = DEV_MAX_PAYLOAD

# XLA:TPU gathers mis-index when a single launch exceeds 2^24 elements
# (observed empirically: B*NB == 2^24 exact, 2^24+… corrupt — consistent
# with an f32-precision index path).  Keep every launch safely below.
_MAX_LAUNCH_ELEMS = 1 << 23


# --------------------------------------------------------------------------
# Host reference encoder (token-level) — the test oracle's writing half.
# --------------------------------------------------------------------------


class _BitWriter:
    def __init__(self) -> None:
        self.buf = bytearray()
        self.acc = 0
        self.n = 0

    def bits_lsb(self, value: int, n: int) -> None:
        """n bits of value, LSB first (extra-bits fields, headers)."""
        self.acc |= (value & ((1 << n) - 1)) << self.n
        self.n += n
        while self.n >= 8:
            self.buf.append(self.acc & 0xFF)
            self.acc >>= 8
            self.n -= 8

    def code_msb(self, code: int, n: int) -> None:
        """A Huffman codeword: MSB of the code enters the stream first."""
        for i in range(n - 1, -1, -1):
            self.bits_lsb((code >> i) & 1, 1)

    def done(self) -> bytes:
        if self.n:
            self.buf.append(self.acc & 0xFF)
            self.acc = 0
            self.n = 0
        return bytes(self.buf)


def encode_tokens_fixed(tokens: Sequence, final: bool = True) -> bytes:
    """Encode an explicit token list as fixed-Huffman DEFLATE (host oracle).

    Tokens: ``("lit", byte)``, ``("copy", length, dist)``, or ``("block",)``
    to close the current block (non-final) and open a new fixed block —
    precise control for exercising the device decoder's edge cases.
    """
    w = _BitWriter()

    def open_block(is_final: bool) -> None:
        w.bits_lsb(1 if is_final else 0, 1)
        w.bits_lsb(1, 2)  # btype=01 fixed

    blocks: List[List] = [[]]
    for t in tokens:
        if t[0] == "block":
            blocks.append([])
        else:
            blocks[-1].append(t)
    for bi, blk in enumerate(blocks):
        open_block(final and bi == len(blocks) - 1)
        for t in blk:
            if t[0] == "lit":
                code, n = _fixed_code(t[1])
                w.code_msb(code, n)
            else:
                _, length, dist = t
                li = int(np.searchsorted(LEN_BASE, length, side="right")) - 1
                if LEN_BASE[li] + (1 << LEN_EXTRA[li]) <= length:
                    li += 1
                code, n = _fixed_code(257 + li)
                w.code_msb(code, n)
                w.bits_lsb(length - int(LEN_BASE[li]), int(LEN_EXTRA[li]))
                di = int(np.searchsorted(DIST_BASE, dist, side="right")) - 1
                w.code_msb(di, 5)
                w.bits_lsb(dist - int(DIST_BASE[di]), int(DIST_EXTRA[di]))
        code, n = _fixed_code(256)
        w.code_msb(code, n)
    return w.done()


# --------------------------------------------------------------------------
# Device deflate: literal-only fixed-Huffman emit.
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(2,))
def deflate_fixed(
    payload: jax.Array, lens: jax.Array, out_bytes: int
) -> Tuple[jax.Array, jax.Array]:
    """Batched literal-only fixed-Huffman DEFLATE.

    ``payload``: uint8 [B, P] (rows padded), ``lens``: int32 [B] valid
    lengths, ``out_bytes``: static output width (≥ (3+9P+7+7)//8).
    Returns (comp uint8 [B, out_bytes], clens int32 [B]).
    """
    B, P = payload.shape
    b = payload.astype(jnp.int32)
    i = jnp.arange(P, dtype=jnp.int32)[None, :]
    valid = i < lens[:, None]
    hi = b >= 144
    code = jnp.where(hi, 0x190 + (b - 144), 0x30 + b)
    clen = jnp.where(valid, jnp.where(hi, 9, 8), 0)
    # Bit offset of each byte's codeword: 3 header bits + running emit.
    cum = jnp.cumsum(clen, axis=1)
    off = 3 + cum - clen
    nbits_total = 3 + cum[:, -1] + 7  # + EOB (7 zero bits)
    NB = out_bytes * 8
    # Gather-only emit (TPU scatters are pathologically slow): for every
    # output bit position, searchsorted finds the codeword covering it —
    # codewords are contiguous, so bit j belongs to the code whose offset
    # interval contains j.
    j = jnp.arange(NB, dtype=jnp.int32)[None, :]
    ends = cum + 3  # end bit (exclusive) of each codeword
    src = jax.vmap(partial(jnp.searchsorted, side="right"))(
        ends, jnp.broadcast_to(j, (B, NB))
    ).astype(jnp.int32)
    src_c = jnp.clip(src, 0, P - 1)
    code_j = jnp.take_along_axis(code, src_c, axis=1)
    clen_j = jnp.take_along_axis(clen, src_c, axis=1)
    off_j = jnp.take_along_axis(off, src_c, axis=1)
    k = j - off_j  # bit index within the codeword, MSB first
    in_code = (src < P) & (k >= 0) & (k < clen_j)
    bit = jnp.where(
        in_code, (code_j >> jnp.maximum(clen_j - 1 - k, 0)) & 1, 0
    )
    # Header bits 0b011 at positions 0-1 (bfinal=1, btype=01).
    bit = jnp.where(j < 2, 1, bit).astype(jnp.uint8)
    weights = (1 << jnp.arange(8, dtype=jnp.uint8)).astype(jnp.uint8)
    comp = (
        (bit.reshape(B, out_bytes, 8) * weights[None, None, :])
        .sum(axis=2)
        .astype(jnp.uint8)
    )
    clens = (nbits_total + 7) // 8
    return comp, clens


# --------------------------------------------------------------------------
# Device inflate: speculative decode + pointer doubling + parallel copies.
# --------------------------------------------------------------------------


def _token_tables():
    return (
        jnp.asarray(LITLEN_TABLE),
        jnp.asarray(DIST_TABLE),
        jnp.asarray(LEN_BASE),
        jnp.asarray(LEN_EXTRA),
        jnp.asarray(DIST_BASE),
        jnp.asarray(DIST_EXTRA),
    )


# --------------------------------------------------------------------------
# Machinery shared by the inflate kernels (fixed / dynamic): bit-window
# reads, the pointer-doubling chain walk, token→output coverage, and the
# member-wide LZ77 copy resolution.
# --------------------------------------------------------------------------


def _bit_window_fn(comp: jax.Array, pad: int = 8):
    """Returns ``window(bitpos) -> uint32`` reading 32 stream bits at any
    per-member bit offset (bitpos broadcastable to [B, ...])."""
    B = comp.shape[0]
    data = jnp.pad(comp, ((0, 0), (0, pad))).astype(jnp.uint32)

    def window(bitpos):
        bp = jnp.broadcast_to(bitpos, (B,) + bitpos.shape[1:])
        flat = bp.reshape(B, -1)
        bi = flat >> 3
        s = (flat & 7).astype(jnp.uint32)
        b0 = jnp.take_along_axis(data, bi, axis=1)
        b1 = jnp.take_along_axis(data, bi + 1, axis=1)
        b2 = jnp.take_along_axis(data, bi + 2, axis=1)
        b3 = jnp.take_along_axis(data, bi + 3, axis=1)
        w = b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)
        return (w >> s).reshape(bp.shape)

    return window


def _chain_walk(nxt: jax.Array, start: jax.Array, T: int) -> jax.Array:
    """Enumerate ``T`` chain positions from ``start`` through the jump map
    ``nxt`` (gather-only pointer doubling; terminal tokens self-loop so
    slots past the chain end stall there).  ``start``: int32 [B]."""
    B, NB = nxt.shape
    t = jnp.arange(T, dtype=jnp.int32)
    cur = jnp.broadcast_to(
        jnp.clip(start, 0, NB - 1)[:, None], (B, T)
    )
    jump = nxt
    for k in range(max(1, int(T - 1).bit_length())):
        stepped = jnp.take_along_axis(jump, cur, axis=1)
        cur = jnp.where(((t >> k) & 1)[None, :] == 1, stepped, cur)
        jump = jnp.take_along_axis(jump, jump, axis=1)
    return cur


def _coverage(cum_out: jax.Array, jj: jax.Array, T: int) -> jax.Array:
    """Index of the chain slot covering each output position: output byte
    ``jj`` belongs to the first token whose cumulative emit exceeds it
    (cum_out is sorted — a batched binary search)."""
    B = cum_out.shape[0]
    cov = jax.vmap(partial(jnp.searchsorted, side="right"))(
        cum_out, jnp.broadcast_to(jj, (B,) + jj.shape[1:])
    ).astype(jnp.int32)
    return jnp.clip(cov, 0, T - 1)


def _lz77_resolve(lit_j, val_j, d_j, o_j, covered, j):
    """Materialize all LZ77 copies with log-rounds pointer jumping.
    Returns (out uint8, neg_src bool[B]) — ``neg_src`` flags copies
    reaching before the stream start (invalid)."""
    OUT = j.shape[1]
    src = jnp.where(
        lit_j | ~covered, j, o_j - d_j + ((j - o_j) % d_j)
    )
    neg = jnp.any(covered & (src < 0), axis=1)
    src = jnp.clip(src, 0, OUT - 1)
    val0 = jnp.where(lit_j, val_j, 0).astype(jnp.uint8)
    ptr = src
    for _ in range(max(1, int(OUT - 1).bit_length())):
        ptr = jnp.take_along_axis(ptr, ptr, axis=1)
    out = jnp.take_along_axis(val0, ptr, axis=1)
    return jnp.where(covered, out, 0), neg


@partial(jax.jit, static_argnums=(3, 4))
def inflate_fixed(
    comp: jax.Array,
    clens: jax.Array,
    isizes: jax.Array,
    out_bytes: int,
    max_cbits: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Batched inflate of all-fixed-Huffman DEFLATE members.

    ``comp``: uint8 [B, C]; ``clens``/``isizes``: int32 [B];
    ``out_bytes``: static output width (≥ max isize); ``max_cbits``: static
    bound on real compressed bits per member (defaults to the padded C*8 —
    callers pass the batch max so the chain-walk slot budget tracks real
    stream size, not the pow2 bucket).
    Returns (out uint8 [B, out_bytes], ok bool [B]).
    """
    B, C = comp.shape
    litlen_t, dist_t, len_base, len_extra, dist_base, dist_extra = (
        _token_tables()
    )
    NB = C * 8
    window = _bit_window_fn(comp)
    p = jnp.arange(NB, dtype=jnp.int32)[None, :]

    w = window(p)
    t = litlen_t[(w & 511).astype(jnp.int32)]
    sym = t >> 4
    L = t & 15
    islit = sym < 256
    iseob = sym == 256
    islen = (sym > 256) & (sym < 286)
    bad = sym >= 286
    li = jnp.clip(sym - 257, 0, 28)
    lext = len_extra[li]
    lenval = len_base[li] + ((w >> L.astype(jnp.uint32)).astype(jnp.int32)
                             & ((1 << lext) - 1))
    # Distance field starts after the length code + extra bits.
    pd = p + L + lext
    wd = window(pd)
    dsym = dist_t[(wd & 31).astype(jnp.int32)]
    bad = bad | (islen & (dsym >= 30))
    dsym = jnp.clip(dsym, 0, 29)
    dext = dist_extra[dsym]
    dist = dist_base[dsym] + ((wd >> 5).astype(jnp.int32) & ((1 << dext) - 1))
    # EOB: terminal iff its code ends inside the final byte's bit padding
    # (bfinal lives in the block *header*, which a mid-stream position
    # can't see; the byte-boundary test is equivalent because a non-final
    # EOB is always followed by ≥10 more payload bits).  A non-final EOB
    # chains straight into the next 3-bit header, which must announce
    # another fixed block (btype=01).
    nbits_real = clens[:, None] * 8
    term = iseob & (p + 15 > nbits_real)
    hdr3 = ((w >> L.astype(jnp.uint32)) & 7).astype(jnp.int32)
    next_fixed = ((hdr3 >> 1) & 3) == 1
    bad = bad | (iseob & ~term & ~next_fixed)
    adv = jnp.where(
        islit,
        L,
        jnp.where(iseob, L + 3, L + lext + 5 + dext),
    )
    nxt = jnp.where(term, p, jnp.minimum(p + adv, NB - 1))
    emit = jnp.where(islit, 1, jnp.where(islen, lenval, 0))
    # A token must end inside the member's compressed bytes.
    overrun = (~term) & ((p + adv) > nbits_real)
    bad = bad | overrun
    emit = jnp.where(bad, 0, emit)

    # Chain enumeration, gather-only (TPU scatters are pathologically
    # slow): token t's bit position is advance(3, t); binary-decompose t
    # while doubling the jump map — jump composition along a chain is
    # additive, so bits can be applied in any order.  The terminal EOB is
    # a self-loop, so slots past the end of the chain stall there (emit 0).
    # Slot budget: every emitting token produces ≥1 byte (≤ out_bytes of
    # them) and every extra block costs ≥10 bits of stream (3-bit header +
    # 7-bit EOB), so the EOB count is bounded by real-bits//10 — no fixed
    # 64-block cap (ADVICE r1: many tiny blocks previously overflowed the
    # walk).
    real_bits = NB if max_cbits is None else min(NB, max_cbits)
    T = out_bytes + real_bits // 10 + 8
    cur = _chain_walk(nxt, jnp.full((B,), 3, jnp.int32), T)

    bad_t = jnp.take_along_axis(bad, cur, axis=1)
    term_t = jnp.take_along_axis(term, cur, axis=1)
    ok = ~jnp.any(bad_t, axis=1) & term_t[:, -1]  # must reach a final EOB
    emit_t = jnp.take_along_axis(emit, cur, axis=1)
    cum_out = jnp.cumsum(emit_t, axis=1)
    out_off_t = cum_out - emit_t
    total = cum_out[:, -1]
    ok = ok & (total == isizes) & (total <= out_bytes)

    # Output coverage + member-wide LZ77 resolution (shared machinery).
    OUT = out_bytes
    j = jnp.arange(OUT, dtype=jnp.int32)[None, :]
    cov = _coverage(cum_out, j, T)
    tp = jnp.take_along_axis(cur, cov, axis=1)  # bit pos of covering token
    covered = j < total[:, None]
    lit_j = jnp.take_along_axis(islit, tp, axis=1) & covered
    sym_j = jnp.take_along_axis(sym, tp, axis=1)
    d_j = jnp.maximum(jnp.take_along_axis(dist, tp, axis=1), 1)
    o_j = jnp.take_along_axis(out_off_t, cov, axis=1)
    out, neg = _lz77_resolve(lit_j, sym_j, d_j, o_j, covered, j)
    ok = ok & ~neg
    return out, ok


_MAX_STORED_BLOCKS = 8  # zlib level-0 emits ≤3 for a ≤64KB member


@partial(jax.jit, static_argnums=(3,))
def inflate_stored(
    comp: jax.Array, clens: jax.Array, isizes: jax.Array, out_bytes: int
) -> Tuple[jax.Array, jax.Array]:
    """Stored-block members (zlib level 0): a short chain of
    [3-bit header | pad-to-byte | LEN NLEN | raw] blocks per member,
    walked in lock-step across the batch."""
    B, C = comp.shape
    pad = jnp.pad(comp, ((0, 0), (0, 5))).astype(jnp.int32)
    j = jnp.arange(out_bytes, dtype=jnp.int32)[None, :]
    out0 = jnp.zeros((B, out_bytes), dtype=jnp.uint8)
    state = (
        jnp.zeros(B, jnp.int32),  # byte pos in comp
        jnp.zeros(B, jnp.int32),  # bytes emitted
        jnp.ones(B, bool),  # ok so far
        jnp.zeros(B, bool),  # saw bfinal
        out0,
    )

    def step(_, st):
        pos, outp, ok, done, out = st
        hdr = jnp.take_along_axis(pad, pos[:, None], axis=1)[:, 0] & 7
        b1 = jnp.take_along_axis(pad, pos[:, None] + 1, axis=1)[:, 0]
        b2 = jnp.take_along_axis(pad, pos[:, None] + 2, axis=1)[:, 0]
        b3 = jnp.take_along_axis(pad, pos[:, None] + 3, axis=1)[:, 0]
        b4 = jnp.take_along_axis(pad, pos[:, None] + 4, axis=1)[:, 0]
        ln = b1 | (b2 << 8)
        nln = b3 | (b4 << 8)
        live = ~done & ok
        good = ((hdr & 6) == 0) & (ln == (nln ^ 0xFFFF)) & (
            pos + 5 + ln <= clens
        )
        ok = jnp.where(live, good, ok)
        src = pos[:, None] + 5 + (j - outp[:, None])
        mask = live[:, None] & (j >= outp[:, None]) & (
            j < outp[:, None] + ln[:, None]
        )
        vals = jnp.take_along_axis(
            pad, jnp.clip(src, 0, C + 4), axis=1
        ).astype(jnp.uint8)
        out = jnp.where(mask, vals, out)
        done = done | (live & ((hdr & 1) == 1))
        pos = jnp.where(live, pos + 5 + ln, pos)
        outp = jnp.where(live, outp + ln, outp)
        return pos, outp, ok, done, out

    pos, outp, ok, done, out = jax.lax.fori_loop(
        0, _MAX_STORED_BLOCKS, step, state
    )
    ok = ok & done & (outp == isizes) & (isizes <= out_bytes)
    out = jnp.where(j < isizes[:, None], out, 0)
    return out, ok


# --------------------------------------------------------------------------
# Dynamic-Huffman device inflate (VERDICT r2: real zlib output must decode
# on device, not tier straight to the host).
#
# Architecture: a block-sequential outer loop (static unroll, lock-step
# across the batch) whose every iteration decodes ONE DEFLATE block per
# member — any mix of stored/fixed/dynamic across members and across
# blocks.  Per iteration:
#   1. parse the block header; for btype=10 run the code-length RLE section
#      through a short lax.scan (≤318 steps) and build the member's
#      canonical litlen/dist decoders ON DEVICE (counts → first codes →
#      argsort symbol ranks — all dense);
#   2. speculative token resolve at every bit position using canonical
#      decode (15 unrolled range compares + one ≤288-entry gather — no
#      2^15 LUT per member);
#   3. chain-walk from the block's first data bit (pointer doubling); the
#      EOB is a self-loop so the walk terminates exactly at block end;
#   4. merge the block's literal/copy coverage into member-wide val/src
#      planes, advance the bit cursor past the EOB into the next header.
# A single member-wide LZ77 pointer-jump pass then materializes all copies
# (back-references legally span blocks).
# --------------------------------------------------------------------------


def _canonical_decoder(lens: jax.Array, max_len: int):
    """Canonical-Huffman decode tables from per-symbol code lengths.

    ``lens``: int32 [B, S] (0 = symbol unused).  Returns
    ``(first, count, symoff, sym_sorted)`` with shapes [B, max_len+1]×3 and
    [B, S]: a code of length L and MSB-first value c maps to symbol
    ``sym_sorted[symoff[L] + c - first[L]]`` iff
    ``first[L] <= c < first[L]+count[L]`` (RFC 1951 §3.2.2).
    """
    B, S = lens.shape
    Lr = jnp.arange(max_len + 1, dtype=jnp.int32)
    count = jnp.sum(
        (lens[:, None, :] == Lr[None, :, None]) & (Lr[None, :, None] > 0),
        axis=2,
        dtype=jnp.int32,
    )
    firsts = [jnp.zeros((B,), jnp.int32)]
    code = jnp.zeros((B,), jnp.int32)
    for L in range(1, max_len + 1):
        code = (code + count[:, L - 1]) << 1
        firsts.append(code)
    first = jnp.stack(firsts, axis=1)
    symoff = jnp.cumsum(count, axis=1) - count
    key = jnp.where(
        lens > 0,
        lens * (2 * S) + jnp.arange(S, dtype=jnp.int32)[None, :],
        jnp.int32(1 << 24),
    )
    sym_sorted = jnp.argsort(key, axis=1).astype(jnp.int32)
    return first, count, symoff, sym_sorted


def _kraft_valid(
    count: jax.Array, max_len: int, allow_single: bool = True
) -> jax.Array:
    """Per-member validity of a canonical table's length histogram
    (ADVICE r2 low).  ``count``: int32 [B, max_len+1], as returned in
    ``_canonical_decoder``'s tables[1].

    Over-subscribed sets (Kraft sum > 1) can alias two symbols onto one
    window and ``_canon_decode``'s smallest-length-wins rule would silently
    pick one — so reject them.  Incomplete sets are rejected too, except —
    matching zlib's inftrees.c — a single code of length 1 when
    ``allow_single`` (some encoders emit a lone distance code; zlib never
    extends this grace to the code-length table).  Empty sets are valid
    here; whether an empty table may be *used* is enforced at decode
    time."""
    Lr = jnp.arange(max_len + 1, dtype=jnp.int32)
    kraft = jnp.sum(count << (max_len - Lr)[None, :], axis=1)
    ncodes = jnp.sum(count, axis=1)
    full = jnp.int32(1) << max_len
    ok = (ncodes == 0) | (kraft == full)
    if allow_single:
        ok = ok | ((ncodes == 1) & (count[:, 1] == 1))
    return ok


def _canon_decode(rev: jax.Array, tables, max_len: int):
    """Decode MSB-first-reversed bit windows against canonical tables.

    ``rev``: int32 [...], the next ``max_len`` stream bits with the first
    stream bit in the MSB.  Returns (sym, L, matched); garbage positions
    (speculative) may be unmatched."""
    first, count, symoff, sym_sorted = tables
    expand = (1,) * (rev.ndim - 1)
    Lsel = jnp.full(rev.shape, 99, dtype=jnp.int32)
    for L in range(max_len, 0, -1):  # downward: smallest L wins last
        cand = rev >> (max_len - L)
        f = first[:, L].reshape(-1, *expand)
        c = count[:, L].reshape(-1, *expand)
        match = (cand >= f) & (cand < f + c)
        Lsel = jnp.where(match, L, Lsel)
    matched = Lsel < 99
    Ls = jnp.where(matched, Lsel, 1)
    cand = rev >> (max_len - Ls)
    f_s = jnp.take_along_axis(
        first, Ls.reshape(first.shape[0], -1), axis=1
    ).reshape(Ls.shape)
    o_s = jnp.take_along_axis(
        symoff, Ls.reshape(symoff.shape[0], -1), axis=1
    ).reshape(Ls.shape)
    idx = jnp.clip(o_s + cand - f_s, 0, sym_sorted.shape[1] - 1)
    sym = jnp.take_along_axis(
        sym_sorted, idx.reshape(sym_sorted.shape[0], -1), axis=1
    ).reshape(Ls.shape)
    return sym, Ls, matched


_MAX_HDR_TOKENS = 318  # ≤286+30+2 RLE tokens fill the code-length section


@partial(jax.jit, static_argnums=(3, 4))
def inflate_dynamic(
    comp: jax.Array,
    clens: jax.Array,
    isizes: jax.Array,
    out_bytes: int,
    max_blocks: int = 8,
) -> Tuple[jax.Array, jax.Array]:
    """Batched inflate of general DEFLATE members (dynamic/fixed/stored
    blocks in any per-member mix), tables built on device.

    ``comp``: uint8 [B, C]; ``clens``/``isizes``: int32 [B]; ``out_bytes``
    static ≥ max isize; ``max_blocks`` static bound on DEFLATE blocks per
    member (zlib's 16K-symbol block buffer means a 64KiB BGZF payload has
    ≤5; members exceeding the bound fail cleanly → host tier).
    Returns (out uint8 [B, out_bytes], ok bool [B]).
    """
    B, C = comp.shape
    NB = C * 8
    OUT = out_bytes
    _, _, len_base, len_extra, dist_base, dist_extra = _token_tables()
    rev8 = jnp.asarray(REV8)
    clc_order = jnp.asarray(CLC_ORDER)
    fixed_ll = jnp.asarray(FIXED_LITLEN_LENS)
    fixed_dl = jnp.asarray(FIXED_DIST_LENS)

    nbits_real = clens * 8
    window = _bit_window_fn(comp)
    bytes_pad = jnp.pad(comp, ((0, 0), (0, 8)))  # stored-block raw copies

    def rev15(w):
        v = (w & 0x7FFF).astype(jnp.int32)
        r16 = (rev8[v & 0xFF] << 8) | rev8[v >> 8]
        return r16 >> 1

    p = jnp.arange(NB, dtype=jnp.int32)[None, :]
    j = jnp.arange(OUT, dtype=jnp.int32)[None, :]

    # Member-wide output planes, merged block by block.
    lit_plane = jnp.zeros((B, OUT), bool)
    val_plane = jnp.zeros((B, OUT), jnp.uint8)
    dst_plane = jnp.ones((B, OUT), jnp.int32)
    off_plane = jnp.zeros((B, OUT), jnp.int32)  # token output offset

    bitpos = jnp.zeros((B,), jnp.int32)
    out_base = jnp.zeros((B,), jnp.int32)
    ok = jnp.ones((B,), bool)
    done = jnp.zeros((B,), bool)

    T = OUT + 2  # per-block chain slots: every emitting token emits ≥1 byte

    def _block_step(carry):
        """Decode ONE DEFLATE block per still-live member."""
        (bitpos, out_base, ok, done,
         lit_plane, val_plane, dst_plane, off_plane) = carry
        live = ok & ~done
        hdr = window(bitpos[:, None])[:, 0]
        bfinal = (hdr & 1) == 1
        btype = ((hdr >> 1) & 3).astype(jnp.int32)
        ok = ok & (~live | (btype != 3))

        # ---- stored block (btype=00): byte-aligned raw copy ------------
        st_bit = (bitpos + 3 + 7) & ~7
        sb = st_bit >> 3
        ln_w = window((sb << 3)[:, None])[:, 0]
        s_len = (ln_w & 0xFFFF).astype(jnp.int32)
        s_nlen = ((ln_w >> 16) & 0xFFFF).astype(jnp.int32)
        stored = live & (btype == 0)
        ok = ok & (
            ~stored
            | ((s_len == (s_nlen ^ 0xFFFF)) & ((sb + 4) * 8 + s_len * 8 <= nbits_real))
        )
        src_byte = (sb + 4)[:, None] + (j - out_base[:, None])
        s_mask = stored[:, None] & (j >= out_base[:, None]) & (
            j < (out_base + s_len)[:, None]
        )
        s_vals = jnp.take_along_axis(
            bytes_pad, jnp.clip(src_byte, 0, C + 7), axis=1
        )
        lit_plane = jnp.where(s_mask, True, lit_plane)
        val_plane = jnp.where(s_mask, s_vals, val_plane)

        # ---- dynamic header parse (btype=10) ---------------------------
        at = bitpos + 3
        hlit = (window(at[:, None])[:, 0] & 31).astype(jnp.int32) + 257
        hdist = (window((at + 5)[:, None])[:, 0] & 31).astype(jnp.int32) + 1
        hclen = (window((at + 10)[:, None])[:, 0] & 15).astype(jnp.int32) + 4
        is_dyn = live & (btype == 2)
        ok = ok & (~is_dyn | ((hlit <= 286) & (hdist <= 30)))
        # 19 code-length-code lengths at fixed 3-bit slots, CLC order.
        ci = jnp.arange(19, dtype=jnp.int32)[None, :]
        cl_raw = (
            window(at[:, None] + 14 + 3 * ci) & 7
        ).astype(jnp.int32)
        cl_raw = jnp.where(ci < hclen[:, None], cl_raw, 0)
        cl_lens = jnp.zeros((B, 19), jnp.int32).at[
            jnp.arange(B)[:, None], clc_order[None, :]
        ].set(cl_raw)
        cl_tables = _canonical_decoder(cl_lens, 7)
        ok = ok & (~is_dyn | _kraft_valid(cl_tables[1], 7, allow_single=False))
        total_codes = hlit + hdist

        def hstep(carry, _):
            pos, cnt, prev, okh = carry
            w = window(pos[:, None])[:, 0]
            r7 = rev8[(w & 0x7F).astype(jnp.int32)] >> 1
            csym, cL, cmatch = _canon_decode(r7, cl_tables, 7)
            ext = (w >> cL.astype(jnp.uint32)).astype(jnp.int32)
            rep = jnp.where(
                csym < 16,
                1,
                jnp.where(
                    csym == 16,
                    3 + (ext & 3),
                    jnp.where(csym == 17, 3 + (ext & 7), 11 + (ext & 127)),
                ),
            )
            val = jnp.where(
                csym < 16, csym, jnp.where(csym == 16, prev, 0)
            )
            nb = cL + jnp.where(
                csym < 16,
                0,
                jnp.where(csym == 16, 2, jnp.where(csym == 17, 3, 7)),
            )
            act = cnt < total_codes
            okh = okh & (~act | cmatch)
            return (
                pos + jnp.where(act, nb, 0),
                cnt + jnp.where(act, rep, 0),
                jnp.where(act, val, prev),
                okh,
            ), (jnp.where(act, rep, 0), val)

        (hpos, hcnt, _, hok), (reps, vals) = jax.lax.scan(
            hstep,
            (at + 14 + 3 * hclen, jnp.zeros((B,), jnp.int32),
             jnp.zeros((B,), jnp.int32), jnp.ones((B,), bool)),
            None,
            length=_MAX_HDR_TOKENS,
        )
        ok = ok & (~is_dyn | (hok & (hcnt == total_codes)))
        reps_t = reps.T  # [B, 318]
        vals_t = vals.T
        cum_rep = jnp.cumsum(reps_t, axis=1)
        m = jnp.arange(_MAX_HDR_TOKENS, dtype=jnp.int32)[None, :]
        tok_of_m = jax.vmap(partial(jnp.searchsorted, side="right"))(
            cum_rep, jnp.broadcast_to(m, (B, _MAX_HDR_TOKENS))
        ).astype(jnp.int32)
        lens_all = jnp.take_along_axis(
            vals_t, jnp.clip(tok_of_m, 0, _MAX_HDR_TOKENS - 1), axis=1
        )
        li288 = jnp.arange(288, dtype=jnp.int32)[None, :]
        dyn_ll = jnp.where(
            li288 < hlit[:, None],
            jnp.take_along_axis(
                lens_all, jnp.minimum(li288, _MAX_HDR_TOKENS - 1), axis=1
            ),
            0,
        )
        di32 = jnp.arange(32, dtype=jnp.int32)[None, :]
        dyn_dl = jnp.where(
            di32 < hdist[:, None],
            jnp.take_along_axis(
                lens_all,
                jnp.clip(hlit[:, None] + di32, 0, _MAX_HDR_TOKENS - 1),
                axis=1,
            ),
            0,
        )

        use_dyn = (btype == 2)[:, None]
        ll_lens = jnp.where(use_dyn, dyn_ll, fixed_ll[None, :])
        dl_lens = jnp.where(use_dyn, dyn_dl, fixed_dl[None, :])
        ll_tables = _canonical_decoder(ll_lens, 15)
        dl_tables = _canonical_decoder(dl_lens, 15)
        # For dynamic members ll_lens == dyn_ll (and likewise dist), so the
        # decoder's own histograms serve; non-dynamic members are masked.
        ok = ok & (
            ~is_dyn
            | (
                _kraft_valid(ll_tables[1], 15)
                & _kraft_valid(dl_tables[1], 15)
            )
        )
        data_start = jnp.where(btype == 2, hpos, bitpos + 3)

        # ---- speculative token resolve at every bit position -----------
        w = window(p)
        sym, L, matched = _canon_decode(rev15(w), ll_tables, 15)
        islit = matched & (sym < 256)
        iseob = matched & (sym == 256)
        islen = matched & (sym > 256) & (sym < 286)
        bad = ~matched | (matched & (sym >= 286))
        li = jnp.clip(sym - 257, 0, 28)
        lext = len_extra[li]
        lenval = len_base[li] + (
            (w >> L.astype(jnp.uint32)).astype(jnp.int32) & ((1 << lext) - 1)
        )
        pd = p + L + lext
        wd = window(pd)
        dsym, Ld, dmatch = _canon_decode(rev15(wd), dl_tables, 15)
        bad = bad | (islen & (~dmatch | (dsym >= 30)))
        dsym = jnp.clip(dsym, 0, 29)
        dext = dist_extra[dsym]
        dist = dist_base[dsym] + (
            (wd >> Ld.astype(jnp.uint32)).astype(jnp.int32)
            & ((1 << dext) - 1)
        )
        adv = jnp.where(islit | iseob, L, L + lext + Ld + dext)
        nxt = jnp.where(iseob, p, jnp.minimum(p + adv, NB - 1))
        emit = jnp.where(islit, 1, jnp.where(islen, lenval, 0))
        overrun = (~iseob) & ((p + adv) > nbits_real[:, None])
        bad = bad | overrun
        emit = jnp.where(bad, 0, emit)

        # ---- chain walk from the block's first data bit ----------------
        cur = _chain_walk(nxt, data_start, T)

        huff = live & (btype == 1) | live & (btype == 2)
        bad_t = jnp.take_along_axis(bad, cur, axis=1)
        term_t = jnp.take_along_axis(iseob, cur, axis=1)
        reached = term_t[:, -1]
        ok = ok & (~huff | (~jnp.any(bad_t, axis=1) & reached))
        emit_t = jnp.take_along_axis(emit, cur, axis=1)
        emit_t = jnp.where(huff[:, None], emit_t, 0)
        cum_out = jnp.cumsum(emit_t, axis=1)
        tok_off = out_base[:, None] + cum_out - emit_t
        total = jnp.where(huff, cum_out[:, -1], 0)

        # ---- merge this block's coverage into the member planes --------
        jj = j - out_base[:, None]
        cov = _coverage(cum_out, jnp.clip(jj, 0, OUT), T)
        tp = jnp.take_along_axis(cur, cov, axis=1)
        in_blk = huff[:, None] & (jj >= 0) & (jj < total[:, None])
        lit_j = jnp.take_along_axis(islit, tp, axis=1)
        sym_j = jnp.take_along_axis(sym, tp, axis=1).astype(jnp.uint8)
        d_j = jnp.maximum(jnp.take_along_axis(dist, tp, axis=1), 1)
        o_j = jnp.take_along_axis(tok_off, cov, axis=1)
        lit_plane = jnp.where(in_blk, lit_j, lit_plane)
        val_plane = jnp.where(in_blk & lit_j, sym_j, val_plane)
        dst_plane = jnp.where(in_blk, d_j, dst_plane)
        off_plane = jnp.where(in_blk, o_j, off_plane)

        # ---- advance cursor / bookkeeping ------------------------------
        eob_pos = cur[:, -1]
        eob_L = jnp.take_along_axis(L, eob_pos[:, None], axis=1)[:, 0]
        nxt_bit = jnp.where(
            btype == 0,
            (sb + 4) * 8 + s_len * 8,
            eob_pos + eob_L,
        )
        out_base = out_base + jnp.where(
            live, jnp.where(stored, s_len, total), 0
        )
        done = done | (live & bfinal)
        bitpos = jnp.where(live, nxt_bit, bitpos)
        return (bitpos, out_base, ok, done,
                lit_plane, val_plane, dst_plane, off_plane)

    # Early-exit outer loop: stop as soon as every member is done (or
    # failed) instead of paying max_blocks full passes — typical zlib
    # members hold 1-4 blocks, so this is the common 2-4x saving (and the
    # graph holds ONE block body, not max_blocks unrolled copies).
    def _cond(state):
        blk, carry = state
        ok_c, done_c = carry[2], carry[3]
        return (blk < max_blocks) & jnp.any(ok_c & ~done_c)

    def _body(state):
        blk, carry = state
        return blk + 1, _block_step(carry)

    _, (bitpos, out_base, ok, done,
        lit_plane, val_plane, dst_plane, off_plane) = jax.lax.while_loop(
        _cond,
        _body,
        (
            jnp.int32(0),
            (bitpos, out_base, ok, done,
             lit_plane, val_plane, dst_plane, off_plane),
        ),
    )

    ok = ok & done & (out_base == isizes) & (isizes <= OUT)

    # ---- member-wide LZ77 copy resolution (spans blocks, shared) -------
    covered = j < out_base[:, None]
    out, neg = _lz77_resolve(
        lit_plane, val_plane, dst_plane, off_plane, covered, j
    )
    ok = ok & ~neg
    return out, ok


# --------------------------------------------------------------------------
# Host wrappers: full BGZF streams ↔ device codec, with framing + CRC here.
# --------------------------------------------------------------------------


class CodecTierStats:
    """Per-call tier accounting for the device codec wrappers.

    ``bgzf_decompress_device`` / ``bgzf_compress_device`` populate a fresh
    instance per call (module globals ``LAST_INFLATE_STATS`` /
    ``LAST_DEFLATE_STATS``) and mirror every field into METRICS counters
    (``flate.inflate.*`` / ``flate.deflate.*``), which the CLI's
    ``--metrics`` JSON report surfaces next to the sort/markdup spans.

    Fields: members taken per tier (``lanes`` / ``xla`` / ``host``) and
    tier-down causes out of the lanes tier (``tierdown_size`` — member
    shape past the streaming caps, ``tierdown_vmem`` — launch geometry
    past the VMEM budget, ``tierdown_ok0`` — the kernel itself declined,
    i.e. corrupt data or an in-kernel budget overflow).
    """

    __slots__ = (
        "lanes", "xla", "host",
        "tierdown_size", "tierdown_vmem", "tierdown_ok0",
    )

    def __init__(self) -> None:
        self.lanes = 0
        self.xla = 0
        self.host = 0
        self.tierdown_size = 0
        self.tierdown_vmem = 0
        self.tierdown_ok0 = 0

    @property
    def total(self) -> int:
        return self.lanes + self.xla + self.host

    def lanes_hit_rate(self) -> float:
        """Fraction of members the lanes tier actually took (1.0 = no
        tier-downs) — the bench artifact's ``device_*_tier_hit_rate``."""
        t = self.total
        return self.lanes / t if t else 0.0

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    def publish(self, prefix: str) -> None:
        from ..utils.tracing import METRICS, current_request

        for k in self.__slots__:
            v = getattr(self, k)
            if v:
                METRICS.count(f"{prefix}.{k}", v)
        rctx = current_request()
        if rctx is not None and self.total:
            # The per-call tier verdict as a request hop: which codec
            # tier actually served this request's members (the serve
            # waterfall's "which kernel ran" answer).  Named ``codec.*``
            # — NOT ``tier.*``, which the tail sampler treats as a
            # degradation trigger; a clean all-lanes call is not one.
            rctx.annotate(
                f"codec.{prefix.rsplit('.', 1)[-1]}",
                **{
                    k: getattr(self, k)
                    for k in self.__slots__
                    if getattr(self, k)
                },
            )


#: Tier accounting of the most recent wrapper call (read by bench.py).
LAST_INFLATE_STATS = CodecTierStats()
LAST_DEFLATE_STATS = CodecTierStats()


def inflate_lanes_accepts(max_clen: int, max_isize: int) -> Tuple[bool, str]:
    """Pure-host tier selection for the streaming lanes decoder: would a
    member of this compressed/inflated shape ride the lanes tier?  Returns
    ``(True, "")`` or ``(False, "size"|"vmem")``.  A full 64 KiB BGZF
    member is accepted — the point of the HBM-streaming geometry."""
    from .pallas.inflate_lanes import accepts

    return accepts(max_clen, max_isize)


def deflate_lanes_accepts(max_plen: int) -> Tuple[bool, str]:
    """Pure-host tier selection for the streaming lanes encoder (mirror of
    :func:`inflate_lanes_accepts`); payloads up to the part writer's
    ``DEV_MAX_PAYLOAD`` blocking are accepted."""
    from .pallas.deflate_lanes import accepts

    return accepts(max_plen)


def device_auto_rtt_ms(conf=None) -> float:
    """The local-latency auto rule's RTT gate, in milliseconds.

    The ``hadoopbam.device.auto-rtt-ms`` conf key overrides the historic
    5 ms default — one number for every device tier, so a topology whose
    RTT is hidden by pipelining (or simply accepted) flips the whole
    device pipeline with one key instead of four env forces.  A
    malformed value keeps the default."""
    from ..conf import DEVICE_AUTO_RTT_MS

    if conf is not None and DEVICE_AUTO_RTT_MS in conf:
        try:
            v = float(conf.get(DEVICE_AUTO_RTT_MS))
            if v > 0:
                return v
        except (TypeError, ValueError):
            pass
    return 5.0


def lanes_tier_enabled(conf=None, max_rtt_ms: Optional[float] = None) -> bool:
    """Should BGZF inflate route through the lockstep-lane Pallas tier?

    Resolution order: ``HBAM_INFLATE_LANES`` env var (0/1 force) →
    ``hadoopbam.inflate.lanes`` conf key → the local-latency auto rule
    (same stance as ``pipeline._default_device_parse``): on only for a
    real TPU whose host↔device round trip is local-class (under
    :func:`device_auto_rtt_ms`, historically 5 ms).  On a CPU backend
    the kernel runs in (slow) interpret mode, and on a tunneled remote
    chip the per-batch transfers pay latency the native host codec does
    not — both lose, so the auto rule declines.  ``max_rtt_ms``
    overrides the gate threshold — the DeviceStream's pipelined-mode
    relaxation passes ``depth × device_auto_rtt_ms`` here, because a
    ≥2-deep pipeline hides that much per-launch RTT behind the other
    splits' compute.
    """
    env = os.environ.get("HBAM_INFLATE_LANES")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    if conf is not None:
        from ..conf import INFLATE_LANES

        if INFLATE_LANES in conf:
            return conf.get_boolean(INFLATE_LANES)
    from ..utils.backend import local_tpu_ready

    return local_tpu_ready(
        max_rtt_ms if max_rtt_ms is not None else device_auto_rtt_ms(conf)
    )


def deflate_lanes_tier_enabled(
    conf=None, max_rtt_ms: Optional[float] = None
) -> bool:
    """Should BGZF deflate route through the lockstep-lane LZ77 encoder?

    The write-side mirror of :func:`lanes_tier_enabled`: resolution order
    is the ``HBAM_DEFLATE_LANES`` env var (0/1 force) → the
    ``hadoopbam.deflate.lanes`` conf key → the shared local-latency auto
    rule (``utils.backend.local_tpu_ready`` under
    :func:`device_auto_rtt_ms`, with the same pipelined-mode
    ``max_rtt_ms`` relaxation as :func:`lanes_tier_enabled`).  On a CPU
    backend the match kernel runs in (slow) interpret mode and on a
    tunneled remote chip the per-part transfers pay latency the threaded
    native zlib does not — both lose, so the auto rule declines.
    """
    env = os.environ.get("HBAM_DEFLATE_LANES")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    if conf is not None:
        from ..conf import DEFLATE_LANES

        if DEFLATE_LANES in conf:
            return conf.get_boolean(DEFLATE_LANES)
    from ..utils.backend import local_tpu_ready

    return local_tpu_ready(
        max_rtt_ms if max_rtt_ms is not None else device_auto_rtt_ms(conf)
    )


def rans_lanes_tier_enabled(
    conf=None, max_rtt_ms: Optional[float] = None
) -> bool:
    """Should CRAM rANS 4x8 decode route through the lockstep-lane
    Pallas tier (ops/pallas/rans_lanes.py)?

    The third codec family's gate, same shape as
    :func:`lanes_tier_enabled`: resolution order is the
    ``HBAM_RANS_LANES`` env var (0/1 force) → the
    ``hadoopbam.cram.rans-lanes`` conf key → the shared local-latency
    auto rule (``utils.backend.local_tpu_ready`` under
    :func:`device_auto_rtt_ms`, with the same pipelined-mode
    ``max_rtt_ms`` relaxation).  Slices the device tier declines or
    flags tier down per-slice — never per-launch — to the NumPy host
    decoder and the Python oracle in ``spec.cram_codecs``.
    """
    env = os.environ.get("HBAM_RANS_LANES")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    if conf is not None:
        from ..conf import CRAM_RANS_LANES

        if CRAM_RANS_LANES in conf:
            return conf.get_boolean(CRAM_RANS_LANES)
    from ..utils.backend import local_tpu_ready

    return local_tpu_ready(
        max_rtt_ms if max_rtt_ms is not None else device_auto_rtt_ms(conf)
    )


def bcf_chain_tier_enabled(
    conf=None, max_rtt_ms: Optional[float] = None
) -> bool:
    """Should BCF record-chain walks route through the device kernel
    (ops/pallas/bcf_chain.py)?

    The variant plane's gate, same shape as :func:`lanes_tier_enabled`:
    resolution order is the ``HBAM_BCF_CHAIN`` env var (0/1 force) → the
    ``hadoopbam.bcf.chain`` conf key → the shared local-latency auto rule
    (``utils.backend.local_tpu_ready`` under :func:`device_auto_rtt_ms`,
    with the same pipelined-mode ``max_rtt_ms`` relaxation).  Windows the
    device walk declines (framing errors, truncation, int32 domain) tier
    down per-window — never per-launch — to the bit-exact NumPy walk and
    then the ``spec/bcf.py`` per-record oracle.
    """
    env = os.environ.get("HBAM_BCF_CHAIN")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    if conf is not None:
        from ..conf import BCF_CHAIN

        if BCF_CHAIN in conf:
            return conf.get_boolean(BCF_CHAIN)
    from ..utils.backend import local_tpu_ready

    return local_tpu_ready(
        max_rtt_ms if max_rtt_ms is not None else device_auto_rtt_ms(conf)
    )


def device_write_enabled(
    conf=None, max_rtt_ms: Optional[float] = None
) -> bool:
    """Should part writes assemble on device — the sorted record gather,
    markdup flag patch and per-member CRC32 running over the HBM-resident
    split payloads, feeding the deflate lanes device-to-device so only
    compressed bytes come back d2h (``io.bam.write_part_fast``'s device
    variant)?

    Resolution order mirrors the codec tiers: ``HBAM_DEVICE_WRITE`` env
    var (0/1 force) → the ``hadoopbam.write.device`` conf key → the
    shared local-latency auto rule (``utils.backend.local_tpu_ready``).
    The gate answers "should we try"; per-part the path still tiers down
    to the host gather when the batch lacks residency or the geometry
    leaves the device domain (reasons in ``bam.device_write_tierdown.*``).
    """
    env = os.environ.get("HBAM_DEVICE_WRITE")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    if conf is not None:
        from ..conf import WRITE_DEVICE

        if WRITE_DEVICE in conf:
            return conf.get_boolean(WRITE_DEVICE)
    from ..utils.backend import local_tpu_ready

    return local_tpu_ready(
        max_rtt_ms if max_rtt_ms is not None else device_auto_rtt_ms(conf)
    )


def _lanes_decode_members(
    raw: np.ndarray, co, cs, xlen, idx: List[int], us,
    stats: Optional[CodecTierStats] = None,
    keep_device: bool = False,
) -> Tuple[dict, int, Optional[object]]:
    """Run the lockstep-lane decoder over the members in ``idx``.

    Returns ``({member_index: payload_bytes}, n_tierdown, dev)`` — members
    the lanes tier could not decode are simply absent and flow to the next
    tier.  Never raises: a launch failure counts every member as a
    tier-down (visible in METRICS, like the fixed-slice tier).  Members
    whose shape the streaming geometry rejects are filtered host-side
    (``inflate_lanes_accepts``) so one oversized member no longer tiers
    down its whole launch; ``stats`` (when given) records the tier-down
    taxonomy.  With ``keep_device`` the per-lane device byte view rides
    back for the on-chip output-residency handoff (None unless every
    member of a single 128-lane launch decoded clean)."""
    from ..utils.tracing import METRICS
    from .pallas.inflate_lanes import inflate_lanes_ex

    clens_all = np.asarray(
        [cs[i] - 20 - xlen[i] for i in idx], dtype=np.int32
    )
    isz_all = np.asarray([us[i] for i in idx], dtype=np.int32)
    take: List[int] = []
    for k in range(len(idx)):
        ok_k, reason = inflate_lanes_accepts(
            int(clens_all[k]), int(isz_all[k])
        )
        if ok_k:
            take.append(k)
        elif stats is not None:
            if reason == "size":
                stats.tierdown_size += 1
            else:
                stats.tierdown_vmem += 1
    if not take:
        if len(idx):
            METRICS.count("flate.lanes_tierdown", len(idx))
        return {}, len(idx), None
    clens = clens_all[take]
    isz = isz_all[take]
    comp = np.zeros((len(take), max(int(clens.max()), 1)), dtype=np.uint8)
    for k2, k in enumerate(take):
        i = idx[k]
        s = int(co[i]) + 12 + int(xlen[i])
        comp[k2, : clens[k2]] = raw[s : s + clens[k2]]
    from ..utils.tracing import count_d2h, count_h2d

    count_h2d(comp.nbytes, "inflate_comp")
    try:
        out_l, ok_l, dev = inflate_lanes_ex(
            comp, clens, isz, keep_device=keep_device
        )
    except Exception as e:
        METRICS.count("flate.lanes_launch_error", 1)
        from ..utils.backend import is_resource_exhausted

        if is_resource_exhausted(e):
            # Device memory exhausted is a *capacity* failure, not a
            # decode failure: counted separately so the serve layer's
            # OOM degradation (and the run manifest) can tell "HBM was
            # full" from "the kernel rejected the member".
            METRICS.count("flate.oom_tierdown", 1)
        from ..utils.tracing import current_request

        rctx = current_request()
        if rctx is not None:
            # A codec tier decision is a request hop: a served request
            # whose members tiered down names the seam in its waterfall
            # instead of just paying an unexplained slower decode.
            rctx.annotate(
                "tier.inflate_lanes_down",
                members=len(idx),
                oom=is_resource_exhausted(e),
            )
        if stats is not None:
            stats.tierdown_ok0 += len(idx)
        return {}, len(idx), None
    decoded = {
        idx[take[k2]]: out_l[k2, : isz[k2]].tobytes()
        for k2 in range(len(take))
        if ok_l[k2]
    }
    count_d2h(int(sum(len(v) for v in decoded.values())), "inflate_out")
    if stats is not None:
        stats.tierdown_ok0 += int((~ok_l).sum())
    n_down = len(idx) - len(decoded)
    if n_down:
        METRICS.count("flate.lanes_tierdown", n_down)
        dev = None  # the device view is only exact when everything decoded
    if dev is not None and len(take) != len(idx):
        dev = None
    return decoded, n_down, dev


@partial(jax.jit, static_argnums=(4,))
def _device_flatten(bytes2d, lane_of, start_of, local0, n_total: int):
    """Concatenate ragged per-lane payload slices into one device-resident
    byte stream: position p of the flat stream reads
    ``bytes2d[lane_of[m], p - start_of[m]]`` for its covering member m.
    ``lane_of``/``start_of`` expand from small per-member columns on
    device (``jnp.repeat``), so only O(members) data is uploaded."""
    lanes = jnp.repeat(lane_of, local0, total_repeat_length=n_total)
    starts = jnp.repeat(start_of, local0, total_repeat_length=n_total)
    p = jnp.arange(n_total, dtype=jnp.int32)
    return bytes2d[lanes, p - starts]


@_trace_stage("flate.stage.inflate_device")
def inflate_blocks_device(
    data,
    coffsets: np.ndarray,
    csizes: np.ndarray,
    usizes: np.ndarray,
    check_crc: bool = True,
    return_device: bool = False,
):
    """Device-tier drop-in for :func:`native.inflate_blocks`.

    Same contract — ``(out, out_offsets)`` with block i's payload at
    ``out[out_offsets[i]:out_offsets[i+1]]`` — but the member payloads
    ship to the accelerator *compressed* (≈4x fewer h2d bytes than the
    inflated stream) and inflate on the lockstep-lane tier; members the
    tier rejects fall back to native host zlib per member.  This is the
    split-read surface: ``io.bam.read_virtual_range(device_inflate=True)``
    routes its batched block inflate here when the lanes tier is enabled.

    ``return_device`` adds a third return value: a device-resident uint8
    array holding the same concatenated payload stream (the on-chip
    output-residency handoff — the device-parse chain kernel can consume
    it without the d2h→h2d bounce), or ``None`` whenever the device copy
    would not be byte-exact (any tier-down, CRC retry, host-replayed far
    copy, or more members than one 128-lane launch).
    """
    from .. import native

    raw = np.frombuffer(data, dtype=np.uint8) if not isinstance(
        data, np.ndarray
    ) else data
    n = len(coffsets)
    out_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.asarray(usizes, dtype=np.int64), out=out_offsets[1:])
    out = np.empty(int(out_offsets[-1]), dtype=np.uint8)
    co64 = np.asarray(coffsets, dtype=np.int64)
    xlen = raw[co64 + 10].astype(np.int32) | (
        raw[co64 + 11].astype(np.int32) << 8
    )
    live = [i for i in range(n) if usizes[i] > 0]
    decoded, _, dev2d = (
        _lanes_decode_members(
            raw, coffsets, csizes, xlen, live, usizes,
            keep_device=return_device,
        )
        if live
        else ({}, 0, None)
    )
    fallback: List[int] = []
    for i in live:
        payload = decoded.get(i)
        if payload is None:
            fallback.append(i)
            continue
        if check_crc:
            crc = struct.unpack_from(
                "<I", raw, int(coffsets[i]) + int(csizes[i]) - 8
            )[0]
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                fallback.append(i)  # host re-decode decides corrupt-vs-bug
                continue
        out[out_offsets[i] : out_offsets[i + 1]] = np.frombuffer(
            payload, dtype=np.uint8
        )
    if fallback:
        dev2d = None  # host bytes diverge from the device copy
        f_out, f_offs = native.inflate_blocks(
            raw,
            co64[fallback],
            np.asarray(csizes, dtype=np.int32)[fallback],
            np.asarray(usizes, dtype=np.int32)[fallback],
            check_crc=check_crc,
        )
        for k, i in enumerate(fallback):
            out[out_offsets[i] : out_offsets[i + 1]] = f_out[
                f_offs[k] : f_offs[k + 1]
            ]
    if not return_device:
        return out, out_offsets
    dev_flat = None
    if dev2d is not None and len(out):
        # Lanes of the (single) launch are the live members in order;
        # empty members contribute zero bytes and need no lane.
        lane_of = np.asarray(
            [live.index(i) for i in range(n) if usizes[i] > 0],
            dtype=np.int32,
        )
        isz = np.asarray(
            [usizes[i] for i in range(n) if usizes[i] > 0], np.int32
        )
        starts = np.asarray(
            [out_offsets[i] for i in range(n) if usizes[i] > 0], np.int32
        )
        from ..utils.hbm import LEDGER
        from ..utils.tracing import METRICS

        dev_flat = _device_flatten(
            dev2d, jnp.asarray(lane_of), jnp.asarray(starts),
            jnp.asarray(isz), int(out_offsets[-1]),
        )
        # Residency ledger: the inflate tier now owns a split window in
        # HBM; the read path transfers ownership when it attaches the
        # window to a RecordBatch, and whoever holds it last must
        # release it — an unreleased window is a named leak.
        dev_flat = LEDGER.register(
            dev_flat, kind="split_window", holder="flate.inflate_device",
            nbytes=int(out_offsets[-1]),
        )
        METRICS.count("flate.inflate_device_residency", 1)
    return out, out_offsets, dev_flat


def _pow2_at_least(n: int, lo: int) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


def _deflate_fixed_rows(
    mat: np.ndarray, lens: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Literal-only fixed-Huffman emit over padded member rows (the XLA
    :func:`deflate_fixed` kernel, launch-chunked).  Returns (comp rows,
    clens)."""
    nblk, P = mat.shape
    out_bytes = (3 + 9 * P + 7 + 7) // 8 + 1
    step = max(1, _MAX_LAUNCH_ELEMS // (out_bytes * 8))
    comp_rows: List[np.ndarray] = []
    clen_rows: List[np.ndarray] = []
    for g0 in range(0, nblk, step):
        c, cl = deflate_fixed(
            jnp.asarray(mat[g0 : g0 + step]),
            jnp.asarray(lens[g0 : g0 + step]),
            out_bytes,
        )
        comp_rows.append(np.asarray(c))
        clen_rows.append(np.asarray(cl))
    return np.concatenate(comp_rows), np.concatenate(clen_rows)


def _host_raw_deflate(payload: np.ndarray, level: int) -> bytes:
    """One member's payload through host zlib as a raw DEFLATE stream —
    the per-member tier-down target when the lanes encoder declines."""
    co = zlib.compressobj(max(1, min(level, 9)), zlib.DEFLATED, -15)
    return co.compress(payload.tobytes()) + co.flush()


def bgzf_compress_device(
    data,
    block_payload: Optional[int] = None,
    append_terminator: bool = True,
    level: int = 1,
    conf=None,
    use_lanes: Optional[bool] = None,
    device_input=None,
    donate_input: bool = False,
) -> bytes:
    """Compress a byte stream into BGZF using the device deflate tiers.

    Framing (gzip headers, CRC32, ISIZE) is host-side; the DEFLATE emit
    runs on device for all blocks at once.  Tiers, top to bottom:

    1. ``level == 0``: stored members (one final stored block per
       member) — no device work, bit-faithful to "uncompressed parts".
    2. **Lockstep-lane LZ77 encoder** (ops/pallas/deflate_lanes.py), when
       ``use_lanes`` is True or the :func:`deflate_lanes_tier_enabled`
       gate fires: real match-finding compression; members the kernel
       declines (geometry past the VMEM budget) tier down per member to
       host zlib at ``level``.
    3. **Literal-only fixed-Huffman** (:func:`deflate_fixed`): the
       original XLA emit — valid DEFLATE, ratio traded for zero host CPU
       and zero serial device work.

    ``block_payload`` defaults per tier (``DEV_LZ_PAYLOAD`` — full-size
    streaming members — for the lanes encoder, ``DEV_DEFAULT_PAYLOAD``
    otherwise); per-block CRC32 runs over slices of the original
    contiguous input, and the stream is assembled in one preallocated
    buffer.

    ``device_input`` (a device-resident uint8 array, exclusive with
    ``data``) is the device-resident write path's handoff: the lanes
    encoder reads its member windows straight from HBM
    (``deflate_lanes_stream``) and the per-member CRC32 runs on chip
    (``ops.pallas.crc32``), so the uncompressed stream never visits the
    host — only the compressed rows, the 4-byte CRC column and any
    tier-down members' payloads come back d2h (ledgered under
    ``transfers.d2h.*``).  Output is byte-identical to the host-input
    path on the same bytes.

    ``donate_input`` marks the caller done with ``device_input`` after
    this call: the on-chip CRC launch — the stream's *final* reader in
    this function's ordering (deflate rows → per-member tier-downs →
    CRC) — donates the buffer (the CRC kernel's ``donate=True``), so on
    donation-capable backends the gathered part stream's HBM is
    reusable the moment the CRC dispatches instead of surviving until
    the caller's release.  This is the DeviceStream's gather→deflate
    donation seam; backends without donation run identically minus the
    aliasing.

    Per-call tier accounting lands in :data:`LAST_DEFLATE_STATS` (and the
    ``flate.deflate.*`` METRICS counters): members per tier plus the
    size/vmem/ok0 tier-down taxonomy out of the lanes tier."""
    global LAST_DEFLATE_STATS
    from ..utils.tracing import METRICS, count_d2h

    stats = CodecTierStats()
    LAST_DEFLATE_STATS = stats
    a: Optional[np.ndarray]
    if device_input is not None:
        if data is not None:
            raise ValueError("pass data or device_input, not both")
        a = None
        n = int(device_input.shape[0])
    else:
        a = np.frombuffer(data, dtype=np.uint8) if not isinstance(
            data, np.ndarray
        ) else data
        n = len(a)
    if use_lanes is None:
        use_lanes = level != 0 and deflate_lanes_tier_enabled(conf)
    if device_input is not None and (level == 0 or not use_lanes):
        # Device-resident input only pays off on the lanes tier; the
        # stored/XLA tiers need the bytes host-side anyway — spill once,
        # visibly, and continue exactly as the host-input path.
        a = np.asarray(device_input)
        count_d2h(a.nbytes, "write_spill")
        METRICS.count("flate.deflate.device_input_spill", 1)
        device_input = None
    if block_payload is None:
        block_payload = DEV_LZ_PAYLOAD if use_lanes else DEV_DEFAULT_PAYLOAD
    if block_payload > DEV_MAX_PAYLOAD:
        raise bgzf.BgzfError(
            f"device codec payload cap is {DEV_MAX_PAYLOAD}, "
            f"got {block_payload}"
        )
    nblk = max(1, -(-n // block_payload))
    lens = np.full(nblk, block_payload, dtype=np.int32)
    if n:
        lens[-1] = n - (nblk - 1) * block_payload
    else:
        lens[0] = 0

    comp: Optional[np.ndarray] = None  # padded rows (device tiers)
    clens = np.zeros(nblk, dtype=np.int64)
    overrides: dict = {}  # member index -> bytes (stored / host tiers)

    def _member_payload(i: int) -> np.ndarray:
        """Member i's raw payload, host-side — the per-member tier-down
        target.  On the device-input path this is the only payload d2h,
        and only for members the lanes tier declined."""
        s = i * block_payload
        ln = int(lens[i])
        if a is not None:
            return a[s : s + ln]
        sl = np.asarray(device_input[s : s + ln])
        count_d2h(sl.nbytes, "write_tierdown")
        return sl

    if level == 0:
        # Uncompressed parts: one final stored block per member (LEN/NLEN
        # framing only; an empty member is the 5-byte empty stored block).
        for i in range(nblk):
            s = i * block_payload
            ln = int(lens[i])
            overrides[i] = (
                b"\x01"
                + struct.pack("<HH", ln, ln ^ 0xFFFF)
                + a[s : s + ln].tobytes()
            )
            clens[i] = 5 + ln
        stats.host += nblk
    else:
        mat: Optional[np.ndarray] = None
        if a is not None:
            P = max(int(lens.max()), 1)
            mat = np.zeros((nblk, P), dtype=np.uint8)
            for i in range(nblk):
                s = i * block_payload
                mat[i, : lens[i]] = a[s : s + lens[i]]
        done = False
        if use_lanes:
            from .pallas.deflate_lanes import (
                deflate_lanes,
                deflate_lanes_stream,
            )

            accepted, reason = deflate_lanes_accepts(int(lens.max()))
            if not accepted:
                if reason == "size":
                    stats.tierdown_size += nblk
                else:
                    stats.tierdown_vmem += nblk
                ok = np.zeros(nblk, dtype=bool)
            else:
                try:
                    if device_input is not None:
                        # HBM-resident payload: member windows are the
                        # deterministic blocking cuts, read on device.
                        comp, cl, ok = deflate_lanes_stream(
                            device_input, lens
                        )
                    else:
                        comp, cl, ok = deflate_lanes(mat, lens)
                except Exception:
                    METRICS.count("flate.deflate_lanes_launch_error", 1)
                    ok = np.zeros(nblk, dtype=bool)
                stats.tierdown_ok0 += int((~ok).sum())
            stats.lanes += int(ok.sum())
            if ok.any():
                clens[:] = cl
                done = True
            n_down = int((~ok).sum())
            if n_down:
                METRICS.count("flate.deflate_lanes_tierdown", n_down)
                stats.host += n_down
                for i in np.nonzero(~ok)[0]:
                    overrides[int(i)] = _host_raw_deflate(
                        _member_payload(int(i)), level
                    )
                    clens[int(i)] = len(overrides[int(i)])
                done = True
        if not done:
            if mat is None:
                # The lanes tier never engaged and the XLA emit needs the
                # payload rows host-side: spill the device input.
                a = np.asarray(device_input)
                count_d2h(a.nbytes, "write_spill")
                METRICS.count("flate.deflate.device_input_spill", 1)
                device_input = None
                P = max(int(lens.max()), 1)
                mat = np.zeros((nblk, P), dtype=np.uint8)
                for i in range(nblk):
                    s = i * block_payload
                    mat[i, : lens[i]] = a[s : s + lens[i]]
            comp, cl = _deflate_fixed_rows(mat, lens)
            clens[:] = cl
            stats.xla += nblk
    if faults.ACTIVE is not None and level != 0:
        # Forced tier-down seam: selected members drop to host zlib no
        # matter which device tier produced them — the cascade must stay
        # bit-exact through the framing below (tests/test_faults.py).
        for i in range(nblk):
            if faults.ACTIVE.flate_tierdown("deflate", i):
                overrides[i] = _host_raw_deflate(_member_payload(i), level)
                clens[i] = len(overrides[i])
    stats.publish("flate.deflate")

    # ---- framing: one preallocated pass, CRC over the input itself -----
    # Host input: zlib.crc32 over slices of the contiguous stream.
    # Device input: the on-chip slice-by-4 kernel over the HBM-resident
    # stream — the framing never touches the uncompressed bytes, only a
    # 4-byte CRC column comes back d2h.
    dev_crcs: Optional[np.ndarray] = None
    if a is None:
        from ..utils.hbm import LEDGER
        from .pallas.crc32 import crc32_device

        crc_dev = crc32_device(
            device_input,
            np.arange(nblk, dtype=np.int64) * block_payload,
            lens.astype(np.int64),
            donate=donate_input,
        )
        if donate_input:
            from ..utils.backend import donation_supported

            if donation_supported():
                METRICS.count("flate.deflate.input_donated", 1)
        # The on-chip CRC column is ledgered for its (short) residency:
        # registered, fetched, released — device bytes accounted even
        # when the lifetime is one statement.
        LEDGER.register(
            crc_dev, kind="crc_column", holder="flate.deflate_crc"
        )
        dev_crcs = np.asarray(crc_dev)
        LEDGER.release(crc_dev)
        count_d2h(dev_crcs.nbytes, "write_crc")
    total = int((18 + 8) * nblk + clens.sum())
    if append_terminator:
        total += len(bgzf.TERMINATOR)
    buf = bytearray(total)
    pos = 0
    off_in = 0
    for i in range(nblk):
        c = int(clens[i])
        ln = int(lens[i])
        bsize = c + 12 + 6 + 8
        buf[pos : pos + 4] = bgzf.MAGIC
        struct.pack_into(
            "<IBBHBBHH", buf, pos + 4, 0, 0, 0xFF, 6, 0x42, 0x43, 2,
            bsize - 1,
        )
        pos += 18
        od = overrides.get(i)
        if od is not None:
            buf[pos : pos + c] = od
        else:
            buf[pos : pos + c] = memoryview(comp[i, :c])
        pos += c
        crc = (
            int(dev_crcs[i])
            if dev_crcs is not None
            else zlib.crc32(a[off_in : off_in + ln]) & 0xFFFFFFFF
        )
        struct.pack_into("<II", buf, pos, crc, ln)
        pos += 8
        off_in += ln
    if append_terminator:
        buf[pos:] = bgzf.TERMINATOR
    return bytes(buf)


@_trace_stage("flate.stage.deflate_device")
def deflate_blocks_device(
    payload,
    level: int = 1,
    block_payload: Optional[int] = None,
    conf=None,
    use_lanes: Optional[bool] = None,
    device_input=None,
    donate_input: bool = False,
) -> bytes:
    """Device-tier drop-in for :func:`native.deflate_blocks` (no
    terminator appended): the part-write surface of the lockstep-lane
    encoder.  With host ``payload`` the caller gathers the sorted records
    and the LZ77 match-find + Huffman emit run on chip; with
    ``device_input`` (the device-resident write path) the gathered stream
    is already in HBM and the lanes encoder + CRC32 both read it there —
    the host does framing over compressed rows and a 4-byte CRC column
    only.  Blocking is deterministic (payload cut every ``block_payload``
    bytes), so ``write_part_fast``'s analytic splitting-bai voffset math
    holds with the same ``block_payload``."""
    return bgzf_compress_device(
        payload,
        block_payload=block_payload,
        append_terminator=False,
        level=level,
        conf=conf,
        use_lanes=use_lanes,
        device_input=device_input,
        donate_input=donate_input,
    )


def bgzf_decompress_device(
    data,
    check_crc: bool = True,
    _force_no_host: bool = False,
    conf=None,
) -> bytes:
    """Decompress a whole BGZF stream, batching members onto the device.

    When the lockstep-lane tier is enabled (``hadoopbam.inflate.lanes`` /
    ``HBAM_INFLATE_LANES`` / the local-latency auto rule — see
    :func:`lanes_tier_enabled`), every member first rides the general
    Pallas decoder (ops/pallas/inflate_lanes.py); only members it rejects
    continue below.  The remainder are grouped by first-block DEFLATE
    flavor and dispatched to the matching XLA kernel —
    ``inflate_stored`` / ``inflate_fixed`` / ``inflate_dynamic`` (the
    general decoder; real zlib output at level ≥1 is dynamic-Huffman and
    decodes on device).  A member whose specialized kernel rejects it
    (mixed block flavors) retries through the general decoder, and only a
    member the device cannot decode at all tiers down to native host
    zlib — same data, same result, tiered like the split planner
    (BAMInputFormat.java:244-258).  The chain is lanes → XLA → host and
    correctness never depends on a device tier.  ``_force_no_host`` turns
    the last tier into an error (device-only mode, used by tests).

    Per-call tier accounting lands in :data:`LAST_INFLATE_STATS` (and the
    ``flate.inflate.*`` METRICS counters): members per tier plus the
    size/vmem/ok0 tier-down taxonomy out of the lanes tier."""
    global LAST_INFLATE_STATS
    from .. import native

    stats = CodecTierStats()
    LAST_INFLATE_STATS = stats

    raw = np.frombuffer(data, dtype=np.uint8) if not isinstance(
        data, np.ndarray
    ) else data
    co, cs, us = native.scan_blocks(raw)
    nblk = len(co)
    outs: List[Optional[bytes]] = [None] * nblk
    # Per-member XLEN (u16 at header offset 10): BGZF requires the BC
    # subfield but permits additional extra subfields, so the DEFLATE
    # payload starts at co+12+xlen, not a hardcoded co+18 (ADVICE r1).
    co64 = np.asarray(co, dtype=np.int64)
    xlen = raw[co64 + 10].astype(np.int32) | (
        raw[co64 + 11].astype(np.int32) << 8
    )
    groups: dict = {"stored": [], "fixed": [], "dyn": []}
    for i in range(nblk):
        # Empty member (e.g. the 28-byte EOF terminator): an empty DEFLATE
        # payload is ≤2 bytes, so cs ≤ 22+xlen — short-circuit, no kernel.
        if us[i] == 0 and cs[i] <= 22 + xlen[i]:
            outs[i] = b""
            continue
        first = int(raw[int(co[i]) + 12 + int(xlen[i])])  # first payload byte
        hdr3 = first & 7
        if hdr3 in (0, 1):  # stored, possibly a non-final chain (zlib lvl 0)
            groups["stored"].append(i)
        elif hdr3 in (2, 3):
            groups["fixed"].append(i)
        else:
            # Dynamic-Huffman first block (zlib level ≥1, i.e. essentially
            # every real-world BAM): the device decoder builds the
            # canonical tables per member/block on chip.
            groups["dyn"].append(i)
    if faults.ACTIVE is not None:
        # Forced tier-down seam: fired members skip every device tier and
        # host-decode immediately (corrupt data still raises, exactly as
        # a real per-member tier-down would surface it).
        forced = [
            i
            for kind in groups
            for i in groups[kind]
            if faults.ACTIVE.flate_tierdown("inflate", i)
        ]
        for i in forced:
            member = raw[int(co[i]) : int(co[i]) + int(cs[i])]
            outs[i], _ = bgzf.inflate_block(member.tobytes(), 0, check_crc)
            stats.host += 1
        if forced:
            fset = set(forced)
            for kind in groups:
                groups[kind] = [i for i in groups[kind] if i not in fset]
    # ---- Tier 1: the general lockstep-lane Pallas decoder --------------
    # One pass over every member regardless of block flavor (the lanes
    # kernel walks any stored/fixed/dynamic mix); members it rejects stay
    # in their flavor group and continue through the XLA tiers below.
    lanes_idx = (
        groups["stored"] + groups["fixed"] + groups["dyn"]
        if lanes_tier_enabled(conf)
        else []
    )
    if lanes_idx:
        decoded, _, _ = _lanes_decode_members(
            raw, co, cs, xlen, lanes_idx, us, stats=stats
        )
        stats.lanes += len(decoded)
        for i, payload in decoded.items():
            outs[i] = payload
        for kind in groups:
            groups[kind] = [i for i in groups[kind] if i not in decoded]
    for kind in ("stored", "fixed", "dyn"):
        idx = groups[kind]
        if not idx:
            continue
        # Payload = member bytes between the (12+xlen)-byte header and
        # 8-byte footer; bucket the compressed width to bound recompiles.
        clens = np.asarray(
            [cs[i] - 20 - xlen[i] for i in idx], dtype=np.int32
        )
        isz = np.asarray([us[i] for i in idx], dtype=np.int32)
        C = _pow2_at_least(int(clens.max()), 512)
        OUT = _pow2_at_least(int(isz.max()) if len(isz) else 1, 1024)
        fn = {
            "stored": inflate_stored,
            "fixed": inflate_fixed,
            "dyn": inflate_dynamic,
        }[kind]
        # Cap the members per kernel launch: bounded HBM footprint AND the
        # TPU gather-index precision limit, on BOTH the bit-position
        # (C*8) and output-byte (OUT) gather extents.
        widest = max(C * 8 if kind != "stored" else C, OUT)
        step = max(1, _MAX_LAUNCH_ELEMS // widest)
        for g0 in range(0, len(idx), step):
            gi = idx[g0 : g0 + step]
            gc = clens[g0 : g0 + step]
            gz = isz[g0 : g0 + step]
            comp = np.zeros((len(gi), C), dtype=np.uint8)
            for k, i in enumerate(gi):
                s = int(co[i]) + 12 + int(xlen[i])
                comp[k, : gc[k]] = raw[s : s + gc[k]]
            from ..utils.tracing import count_d2h, count_h2d

            count_h2d(comp.nbytes, "inflate_comp")
            if kind == "fixed" and jax.devices()[0].platform == "tpu":
                # Preferred tier on real chips: the lockstep-lane Pallas
                # decoder for literal-only fixed members (everything the
                # device deflate emits).  Members outside its contract
                # come back ok=False and fall through to the XLA kernels
                # below.  Never taken on CPU: interpret-mode emulation of
                # the lockstep walk is far slower than the XLA path.
                from ..utils.tracing import METRICS
                from .pallas.inflate_fixed import inflate_fixed_literal

                try:
                    out_l, ok_l = inflate_fixed_literal(comp, gc, gz)
                except Exception:
                    # Compile/launch failure is a tier-down, but never a
                    # silent one — the counter makes a dead tier visible.
                    METRICS.count("flate.lockstep_launch_error", 1)
                    ok_l = np.zeros(len(gi), dtype=bool)
                    out_l = None
                all_ok = bool(ok_l.all()) if len(ok_l) else False
                for k, i in enumerate(gi):
                    if ok_l[k]:
                        outs[i] = out_l[k, : gz[k]].tobytes()
                        stats.lanes += 1
                if all_ok:
                    continue
                METRICS.count(
                    "flate.lockstep_tierdown", int((~ok_l).sum())
                )
            if kind == "fixed":
                # pow2-bucketed like C so distinct jit signatures stay few.
                cbits = _pow2_at_least(int(gc.max()) * 8, 4096)
                out_d, ok = fn(
                    jnp.asarray(comp),
                    jnp.asarray(gc),
                    jnp.asarray(gz),
                    OUT,
                    cbits,
                )
            else:
                out_d, ok = fn(
                    jnp.asarray(comp), jnp.asarray(gc), jnp.asarray(gz), OUT
                )
            out_d = np.asarray(out_d)
            ok = np.asarray(ok)
            count_d2h(out_d.nbytes, "inflate_out")
            for k, i in enumerate(gi):
                if outs[i] is not None:
                    # Already decoded by the lockstep Pallas tier in a
                    # mixed fixed group — keep that result.
                    continue
                if ok[k]:
                    outs[i] = out_d[k, : gz[k]].tobytes()
                    stats.xla += 1
                elif kind != "dyn":
                    # Routing by the first block's btype is best-effort:
                    # zlib may mix block flavors inside one member (e.g. a
                    # fixed or stored first block followed by dynamic
                    # ones).  The general decoder handles any mix — retry
                    # there, still on device.
                    groups["dyn"].append(i)
                elif _force_no_host:
                    raise bgzf.BgzfError(
                        f"device inflate failed for member at offset {co[i]}"
                    )
                else:
                    # Device tiers down to the host codec for just this
                    # member (raises if the data itself is corrupt).
                    member = raw[int(co[i]) : int(co[i]) + int(cs[i])]
                    payload, _ = bgzf.inflate_block(
                        member.tobytes(), 0, check_crc
                    )
                    outs[i] = payload
                    stats.host += 1
    stats.publish("flate.inflate")
    if check_crc:
        for i in range(nblk):
            if us[i] == 0:
                continue
            crc = struct.unpack_from(
                "<I", raw, int(co[i]) + int(cs[i]) - 8
            )[0]
            if (zlib.crc32(outs[i]) & 0xFFFFFFFF) != crc:
                if _force_no_host:
                    raise bgzf.BgzfError(
                        f"CRC mismatch in BGZF member at offset {co[i]}"
                    )
                # Device result failed the host CRC gate: re-decode this
                # member on the host tier (raises BgzfError if the data —
                # not the device — is what's corrupt).
                member = raw[int(co[i]) : int(co[i]) + int(cs[i])]
                outs[i], _ = bgzf.inflate_block(
                    member.tobytes(), 0, check_crc=True
                )
    return b"".join(outs)  # type: ignore[arg-type]
