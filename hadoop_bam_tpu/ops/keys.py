"""Sort-key construction on device, bit-exact with the reference.

The shuffle key is Java ``long getKey0(int refIdx, int pos0) = (long)refIdx
<< 32 | pos0`` (BAMRecordReader.java:119-121) — note the *sign extension* of
``pos0`` (and of the murmur hash for unmapped reads) floods the high word
when negative.  TPUs prefer 32-bit lanes, so the key is carried as a pair
``(hi: int32, lo: uint32)`` whose lexicographic order (hi signed, lo
unsigned) equals signed-int64 order of the packed key.  ``lax.sort`` with
``num_keys=2`` implements exactly that comparison.

Unmapped reads need ``murmur3`` over ragged record bytes; that column is
computed host-side (utils/murmur3, batched in native/) and passed in as
``hash32`` — the device op just selects per the reference's condition
(unmapped flag OR refid<0 OR alignmentStart<0, BAMRecordReader.java:85-86).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..spec.bam import FLAG_UNMAPPED, INT_MAX


def unmapped_mask(
    refid: jax.Array, pos: jax.Array, flag: jax.Array
) -> jax.Array:
    """Rows keyed by the murmur3 hash instead of (refid, pos): the
    reference's condition is unmapped flag OR refid<0 OR alignmentStart<0
    (BAMRecordReader.java:85-86).  The single definition shared by the key
    builders and the device-parse hash patching."""
    return ((flag & FLAG_UNMAPPED) != 0) | (refid < 0) | ((pos + 1) < 0)


def make_keys(
    refid: jax.Array,  # int32[N]
    pos: jax.Array,  # int32[N], 0-based, -1 if unplaced
    flag: jax.Array,  # int32[N]
    hash32: jax.Array,  # int32[N], murmur3 low word (only used when unmapped)
) -> tuple[jax.Array, jax.Array]:
    """(hi: int32[N], lo: uint32[N]) with Java-exact packing."""
    unmapped = unmapped_mask(refid, pos, flag)
    sel_hi = jnp.where(unmapped, jnp.int32(INT_MAX), refid)
    sel_lo = jnp.where(unmapped, hash32, pos)
    # Java `|` sign-extends the low int into the long: a negative low word
    # turns the whole high word into 0xffffffff.
    hi = jnp.where(sel_lo < 0, jnp.int32(-1), sel_hi)
    lo = sel_lo.astype(jnp.uint32)
    return hi, lo


def pack_keys_np(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Host-side: (hi, lo) → signed int64 key (for oracle comparison)."""
    return (hi.astype(np.int64) << np.int64(32)) | lo.astype(np.uint32).astype(
        np.int64
    )


def split_keys_np(keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: signed int64 key → (hi int32, lo uint32)."""
    return (
        (keys >> np.int64(32)).astype(np.int32),
        (keys & np.int64(0xFFFFFFFF)).astype(np.uint32),
    )


def pack_hash64_np(qh1: np.ndarray, qh2: np.ndarray) -> np.ndarray:
    """The collation engine's 64-bit name-hash key as one int64 column:
    ``qh1`` in the high word, ``qh2`` (zero-extended) in the low — the
    packed form of the (qh1, qh2) operand pair the device collation
    sorts by (collate/device.py), for host-side oracles and sideband
    storage.  Lexicographic (int32, uint32) order == signed-int64 order,
    the ops/sort.py key contract."""
    return (qh1.astype(np.int64) << np.int64(32)) | (
        qh2.astype(np.uint32).astype(np.int64)
    )


def split_hash64_np(h: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`pack_hash64_np`: int64 → (qh1 int32, qh2 int32)."""
    return (
        (h >> np.int64(32)).astype(np.int32),
        (h & np.int64(0xFFFFFFFF)).astype(np.int32),
    )
