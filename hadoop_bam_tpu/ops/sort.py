"""Single-chip sort: the per-device phase of the coordinate sort.

``lax.sort`` with two key operands ((hi signed, lo unsigned) — signed-int64
order, see ops/keys.py) plus a validity column for padding.  XLA lowers this
to an efficient on-chip sort; the returned permutation indexes the original
rows so the ragged byte sideband can be reordered host-side (or gathered
device-side when columns are packed fixed-width).

This replaces the MapReduce shuffle's within-reducer merge-sort; the
cross-chip phase lives in parallel/shuffle.py.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def sort_keys(
    hi: jax.Array, lo: jax.Array, valid: Optional[jax.Array] = None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sort by the 64-bit key; invalid (padding) rows sink to the end.

    Returns (hi_sorted, lo_sorted, permutation int32[N]).
    """
    n = hi.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    if valid is None:
        hi_s, lo_s, perm = lax.sort((hi, lo, idx), num_keys=2, is_stable=True)
        return hi_s, lo_s, perm
    invalid = (~valid).astype(jnp.uint8)
    _, hi_s, lo_s, perm = lax.sort(
        (invalid, hi, lo, idx), num_keys=3, is_stable=True
    )
    return hi_s, lo_s, perm


@jax.jit
def apply_permutation(columns: dict, perm: jax.Array) -> dict:
    """Gather every SoA column through the sort permutation (device-side)."""
    return {k: v[perm] for k, v in columns.items()}
