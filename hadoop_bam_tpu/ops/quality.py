"""Quality-encoding ops + histograms, batched for the VPU/MXU.

Semantics from the reference (SequencedFragment.java:229-309,
FormatConstants.java:30-48): Sanger = Phred+33 (range [0,93]), Illumina =
Phred+64 (range [0,62]); conversion shifts by 31 after range validation.
The per-byte Java loops become masked elementwise ops over a whole batch;
range violations are *reported* (index of first bad byte per row, -1 if ok)
rather than thrown, so a jit program can carry them as data (the
STRICT/LENIENT/SILENT policy is applied host-side).

The quality histogram — baseline config #3's kernel — is computed as a
one-hot × ones matmul so the reduction runs on the MXU in bfloat16-free
int32 space, instead of a scatter-add that would serialize on the VPU.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

SANGER_OFFSET = 33
SANGER_MAX = 93
ILLUMINA_OFFSET = 64
ILLUMINA_MAX = 62


@jax.jit
def verify_quality_sanger(qual: jax.Array, valid: jax.Array) -> jax.Array:
    """First offending index per row, -1 if in-range (verifyQuality
    semantics).  ``qual``: uint8[B, L]; ``valid``: bool[B, L] length masks."""
    bad = valid & (
        (qual < SANGER_OFFSET) | (qual > SANGER_OFFSET + SANGER_MAX)
    )
    return _first_true(bad)


@jax.jit
def verify_quality_illumina(qual: jax.Array, valid: jax.Array) -> jax.Array:
    bad = valid & (
        (qual < ILLUMINA_OFFSET) | (qual > ILLUMINA_OFFSET + ILLUMINA_MAX)
    )
    return _first_true(bad)


def _first_true(mask: jax.Array) -> jax.Array:
    L = mask.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    hit = jnp.where(mask, idx, L)
    first = jnp.min(hit, axis=-1)
    return jnp.where(first == L, jnp.int32(-1), first.astype(jnp.int32))


@jax.jit
def illumina_to_sanger(qual: jax.Array) -> jax.Array:
    """Phred+64 → Phred+33 (validation is the caller's verify_* pass)."""
    return (qual.astype(jnp.int32) - (ILLUMINA_OFFSET - SANGER_OFFSET)).astype(
        jnp.uint8
    )


@jax.jit
def sanger_to_illumina(qual: jax.Array) -> jax.Array:
    return (qual.astype(jnp.int32) + (ILLUMINA_OFFSET - SANGER_OFFSET)).astype(
        jnp.uint8
    )


@partial(jax.jit, static_argnames=("nbins",))
def histogram_u8(values: jax.Array, valid: jax.Array, nbins: int = 64) -> jax.Array:
    """Counts of each value in [0, nbins) over the valid positions.

    One-hot [B*L, nbins] contracted against ones on the MXU; int32 output.
    Out-of-range values fall outside every one-hot column and count nowhere.
    """
    v = values.reshape(-1).astype(jnp.int32)
    m = valid.reshape(-1)
    onehot = (
        (v[:, None] == jnp.arange(nbins, dtype=jnp.int32)[None, :])
        & m[:, None]
    ).astype(jnp.int32)
    # int32 accumulation: float32 would silently drop counts past 2^24.
    return jnp.sum(onehot, axis=0)


@jax.jit
def base_counts(seq_codes: jax.Array, valid: jax.Array) -> jax.Array:
    """Counts of the 16 4-bit BAM base codes (=ACMGRSVTWYHKDBN) — the
    base-count reduction of baseline config #3."""
    v = seq_codes.reshape(-1).astype(jnp.int32)
    m = valid.reshape(-1)
    onehot = (
        (v[:, None] == jnp.arange(16, dtype=jnp.int32)[None, :]) & m[:, None]
    ).astype(jnp.int32)
    return jnp.sum(onehot, axis=0)


@jax.jit
def unpack_seq_nibbles(packed: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """uint8[B, L/2] packed 4-bit bases → (hi, lo) uint8[B, L/2] nibbles."""
    return packed >> 4, packed & 0xF
