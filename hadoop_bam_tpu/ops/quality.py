"""Quality-encoding ops + histograms, batched for the VPU/MXU.

Semantics from the reference (SequencedFragment.java:229-309,
FormatConstants.java:30-48): Sanger = Phred+33 (range [0,93]), Illumina =
Phred+64 (range [0,62]); conversion shifts by 31 after range validation.
The per-byte Java loops become masked elementwise ops over a whole batch;
range violations are *reported* (index of first bad byte per row, -1 if ok)
rather than thrown, so a jit program can carry them as data (the
STRICT/LENIENT/SILENT policy is applied host-side).

The quality histogram — baseline config #3's kernel — is computed as a
one-hot × ones matmul so the reduction runs on the MXU in bfloat16-free
int32 space, instead of a scatter-add that would serialize on the VPU.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

SANGER_OFFSET = 33
SANGER_MAX = 93
ILLUMINA_OFFSET = 64
ILLUMINA_MAX = 62


@jax.jit
def verify_quality_sanger(qual: jax.Array, valid: jax.Array) -> jax.Array:
    """First offending index per row, -1 if in-range (verifyQuality
    semantics).  ``qual``: uint8[B, L]; ``valid``: bool[B, L] length masks."""
    bad = valid & (
        (qual < SANGER_OFFSET) | (qual > SANGER_OFFSET + SANGER_MAX)
    )
    return _first_true(bad)


@jax.jit
def verify_quality_illumina(qual: jax.Array, valid: jax.Array) -> jax.Array:
    bad = valid & (
        (qual < ILLUMINA_OFFSET) | (qual > ILLUMINA_OFFSET + ILLUMINA_MAX)
    )
    return _first_true(bad)


def _first_true(mask: jax.Array) -> jax.Array:
    L = mask.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    hit = jnp.where(mask, idx, L)
    first = jnp.min(hit, axis=-1)
    return jnp.where(first == L, jnp.int32(-1), first.astype(jnp.int32))


@jax.jit
def illumina_to_sanger(qual: jax.Array) -> jax.Array:
    """Phred+64 → Phred+33 (validation is the caller's verify_* pass)."""
    return (qual.astype(jnp.int32) - (ILLUMINA_OFFSET - SANGER_OFFSET)).astype(
        jnp.uint8
    )


@jax.jit
def sanger_to_illumina(qual: jax.Array) -> jax.Array:
    return (qual.astype(jnp.int32) + (ILLUMINA_OFFSET - SANGER_OFFSET)).astype(
        jnp.uint8
    )


@partial(jax.jit, static_argnames=("nbins",))
def histogram_u8(values: jax.Array, valid: jax.Array, nbins: int = 64) -> jax.Array:
    """Counts of each value in [0, nbins) over the valid positions.

    One-hot [B*L, nbins] contracted against ones on the MXU; int32 output.
    Out-of-range values fall outside every one-hot column and count nowhere.
    """
    v = values.reshape(-1).astype(jnp.int32)
    m = valid.reshape(-1)
    onehot = (
        (v[:, None] == jnp.arange(nbins, dtype=jnp.int32)[None, :])
        & m[:, None]
    ).astype(jnp.int32)
    # int32 accumulation: float32 would silently drop counts past 2^24.
    return jnp.sum(onehot, axis=0)


@jax.jit
def base_counts(seq_codes: jax.Array, valid: jax.Array) -> jax.Array:
    """Counts of the 16 4-bit BAM base codes (=ACMGRSVTWYHKDBN) — the
    base-count reduction of baseline config #3."""
    v = seq_codes.reshape(-1).astype(jnp.int32)
    m = valid.reshape(-1)
    onehot = (
        (v[:, None] == jnp.arange(16, dtype=jnp.int32)[None, :]) & m[:, None]
    ).astype(jnp.int32)
    return jnp.sum(onehot, axis=0)


@jax.jit
def unpack_seq_nibbles(packed: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """uint8[B, L/2] packed 4-bit bases → (hi, lo) uint8[B, L/2] nibbles."""
    return packed >> 4, packed & 0xF


# ---------------------------------------------------------------------------
# Duplicate-marking score: summed base quality (Picard/samtools convention)
# ---------------------------------------------------------------------------

#: Quality threshold for the markdup score (samtools markdup / Picard
#: MarkDuplicates both sum only bases with quality ≥ 15).
MARKDUP_MIN_QUALITY = 15
_QUAL_MISSING = 0xFF  # the spec's "qual absent" fill byte never scores


def sum_base_qualities_np(
    data: np.ndarray, soa: dict, min_quality: int = MARKDUP_MIN_QUALITY
) -> np.ndarray:
    """int64[N] markdup score per record: sum of qual bytes ≥ ``min_quality``
    (0xFF = missing qual never counts), vectorized over the ragged qual
    sideband — the host-gathered reduction feeding the dedup segmented
    arg-max, same stance as the unmapped-key ``hash32`` column."""
    n = len(soa["rec_off"])
    scores = np.zeros(n, dtype=np.int64)
    if n == 0:
        return scores
    l_seq = soa["l_seq"].astype(np.int64)
    qual_off = (
        soa["rec_off"].astype(np.int64)
        + 32
        + soa["l_read_name"]
        + 4 * soa["n_cigar_op"].astype(np.int64)
        + (l_seq + 1) // 2
    )
    total = int(l_seq.sum())
    if total == 0:
        return scores
    rec_of_base = np.repeat(np.arange(n), l_seq)
    within = np.arange(total) - np.repeat(np.cumsum(l_seq) - l_seq, l_seq)
    q = data[np.repeat(qual_off, l_seq) + within].astype(np.int64)
    counted = (q >= min_quality) & (q != _QUAL_MISSING)
    np.add.at(scores, rec_of_base, q * counted)
    return scores


@partial(jax.jit, static_argnames=("min_quality",))
def sum_base_qualities(
    qual: jax.Array,  # uint8[B, L]
    valid: jax.Array,  # bool[B, L]
    min_quality: int = MARKDUP_MIN_QUALITY,
) -> jax.Array:
    """Device twin of :func:`sum_base_qualities_np` over padded rows."""
    q = qual.astype(jnp.int32)
    counted = valid & (q >= min_quality) & (q != _QUAL_MISSING)
    return jnp.sum(jnp.where(counted, q, 0), axis=-1)
