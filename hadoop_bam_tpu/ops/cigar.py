"""CIGAR ops: reference span, unclipped ends, and interval-overlap masks.

``reference_length`` (span consumed on the reference: ops M/D/N/=/X, SAM
spec) feeds alignment ends for the BAI builder and for exact interval
overlap — the device-side replacement for htsjdk's ``OverlapDetector``
filtering in the readers (BAMRecordReader.java:171-175 via
createIndexIterator, VCFRecordReader.java:196-198).

``unclipped_start`` / ``unclipped_end`` back the duplicate-marking
signature (dedup/): the 5′ fragment coordinate before the aligner clipped
it, i.e. ``pos`` pushed left by the leading S/H run (start) and the
alignment end pushed right by the trailing S/H run (end).  Semantics,
shared bit-for-bit by every implementation and by ``dedup/oracle.py``:

- leading clips  = the maximal *prefix* of S(4)/H(5) ops,
- trailing clips = the maximal *suffix* of S(4)/H(5) ops (an all-clip
  CIGAR contributes its full length to both),
- ``unclipped_start = pos - leading``,
- ``unclipped_end   = pos + max(ref_span, 1) - 1 + trailing`` (the
  ``max(·,1)`` matches htsjdk/samtools ``bam_endpos`` treating a mapped
  record with an empty CIGAR as covering one base),
- flags are ignored: the functions are pure CIGAR/pos arithmetic (the
  dedup layer decides which records participate).

Two implementations of each:
- ``*_np``: host NumPy over the ragged sideband (flatten-all-cigars +
  scatter-add — no per-record Python loop),
- ``*_padded`` (with ``overlap_mask`` / ``reference_lengths_padded``): jit
  device versions over a padded [N, max_ops] cigar tensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# ops M(0) D(2) N(3) =(7) X(8) consume reference.
_REF_CONSUMING = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1, 0, 0, 0, 0, 0, 0, 0])
# ops S(4) H(5) are clips.
_IS_CLIP = np.array([0, 0, 0, 0, 1, 1, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0])


def reference_lengths_np(data: np.ndarray, soa: dict) -> np.ndarray:
    """Reference span per record from the ragged sideband (vectorized)."""
    n = len(soa["rec_off"])
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    cigar_off = soa["rec_off"].astype(np.int64) + 32 + soa["l_read_name"]
    n_ops = soa["n_cigar_op"].astype(np.int64)
    total_ops = int(n_ops.sum())
    if total_ops == 0:
        return np.zeros(n, dtype=np.int64)
    # Flatten every cigar u32 into one index array.
    rec_of_op = np.repeat(np.arange(n), n_ops)
    starts = np.repeat(cigar_off, n_ops)
    within = np.arange(total_ops) - np.repeat(
        np.cumsum(n_ops) - n_ops, n_ops
    )
    at = starts + 4 * within
    u32 = (
        data[at].astype(np.uint32)
        | (data[at + 1].astype(np.uint32) << 8)
        | (data[at + 2].astype(np.uint32) << 16)
        | (data[at + 3].astype(np.uint32) << 24)
    )
    oplen = (u32 >> 4).astype(np.int64)
    consume = _REF_CONSUMING[u32 & 0xF]
    spans = np.zeros(n, dtype=np.int64)
    np.add.at(spans, rec_of_op, oplen * consume)
    return spans


def clip_spans_np(
    data: np.ndarray, soa: dict
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(leading_clip, trailing_clip, ref_span) int64 triple per record,
    vectorized over the ragged sideband (one flatten + scatter-adds)."""
    n = len(soa["rec_off"])
    lead = np.zeros(n, dtype=np.int64)
    trail = np.zeros(n, dtype=np.int64)
    span = np.zeros(n, dtype=np.int64)
    if n == 0:
        return lead, trail, span
    cigar_off = soa["rec_off"].astype(np.int64) + 32 + soa["l_read_name"]
    n_ops = soa["n_cigar_op"].astype(np.int64)
    total_ops = int(n_ops.sum())
    if total_ops == 0:
        return lead, trail, span
    rec_of_op = np.repeat(np.arange(n), n_ops)
    starts = np.repeat(cigar_off, n_ops)
    within = np.arange(total_ops) - np.repeat(
        np.cumsum(n_ops) - n_ops, n_ops
    )
    at = starts + 4 * within
    u32 = (
        data[at].astype(np.uint32)
        | (data[at + 1].astype(np.uint32) << 8)
        | (data[at + 2].astype(np.uint32) << 16)
        | (data[at + 3].astype(np.uint32) << 24)
    )
    oplen = (u32 >> 4).astype(np.int64)
    code = u32 & 0xF
    is_clip = _IS_CLIP[code].astype(bool)
    np.add.at(span, rec_of_op, oplen * _REF_CONSUMING[code])
    # An op is a *leading* clip iff no non-clip op precedes it in its
    # record; *trailing* iff none follows.  Per-record prefix counts of
    # non-clip ops come from one global exclusive cumsum rebased at each
    # record's first op (same trick as the ragged murmur batch).
    nonclip = (~is_clip).astype(np.int64)
    before = np.cumsum(nonclip) - nonclip  # non-clip ops before, global
    # First-op index per record; 0-op records repeat zero times, so the
    # clip only guards the (unused) indices past the flattened op space.
    rec_first = np.clip(np.cumsum(n_ops) - n_ops, 0, total_ops - 1)
    before -= np.repeat(before[rec_first], n_ops)
    per_rec_nonclip = np.zeros(n, dtype=np.int64)
    np.add.at(per_rec_nonclip, rec_of_op, nonclip)
    after = per_rec_nonclip[rec_of_op] - before - nonclip
    np.add.at(lead, rec_of_op, oplen * (is_clip & (before == 0)))
    np.add.at(trail, rec_of_op, oplen * (is_clip & (after == 0)))
    return lead, trail, span


def unclipped_start_np(data: np.ndarray, soa: dict) -> np.ndarray:
    """0-based unclipped alignment start per record (``pos`` minus the
    leading S/H run) — the forward-strand 5′ fragment coordinate."""
    lead, _, _ = clip_spans_np(data, soa)
    return soa["pos"].astype(np.int64) - lead


def unclipped_end_np(data: np.ndarray, soa: dict) -> np.ndarray:
    """0-based unclipped alignment end per record (alignment end plus the
    trailing S/H run) — the reverse-strand 5′ fragment coordinate."""
    _, trail, span = clip_spans_np(data, soa)
    return (
        soa["pos"].astype(np.int64) + np.maximum(span, 1) - 1 + trail
    )


def pack_cigars_padded(
    data: np.ndarray, soa: dict, max_ops: int
) -> np.ndarray:
    """Gather cigars into a device-friendly [N, max_ops] uint32 tensor
    (0-padded; op code 0 with length 0 is a no-op)."""
    n = len(soa["rec_off"])
    n_ops_all = soa["n_cigar_op"].astype(np.int64)
    if n and int(n_ops_all.max()) > max_ops:
        raise ValueError(
            f"record has {int(n_ops_all.max())} CIGAR ops > max_ops={max_ops}; "
            "truncating would understate reference spans"
        )
    out = np.zeros((n, max_ops), dtype=np.uint32)
    cigar_off = soa["rec_off"].astype(np.int64) + 32 + soa["l_read_name"]
    n_ops = n_ops_all
    for k in range(max_ops):
        rows = n_ops > k
        if not rows.any():
            break
        at = cigar_off[rows] + 4 * k
        out[rows, k] = (
            data[at].astype(np.uint32)
            | (data[at + 1].astype(np.uint32) << 8)
            | (data[at + 2].astype(np.uint32) << 16)
            | (data[at + 3].astype(np.uint32) << 24)
        )
    return out


@jax.jit
def reference_lengths_padded(cigars: jax.Array) -> jax.Array:
    """[N, max_ops] uint32 cigar tensor → int32[N] reference spans."""
    oplen = (cigars >> 4).astype(jnp.int32)
    opcode = (cigars & 0xF).astype(jnp.int32)
    consume = jnp.asarray(_REF_CONSUMING, dtype=jnp.int32)[opcode]
    return jnp.sum(oplen * consume, axis=-1)


@jax.jit
def unclipped_start_padded(
    cigars: jax.Array,  # uint32[N, max_ops] (pack_cigars_padded)
    n_ops: jax.Array,  # int32[N]
    pos: jax.Array,  # int32[N] 0-based
) -> jax.Array:
    """Device twin of :func:`unclipped_start_np` over the padded tensor —
    the dedup signature op's orientation-aware clip adjustment."""
    lead, _, _ = _clip_spans_padded(cigars, n_ops)
    return pos - lead


@jax.jit
def unclipped_end_padded(
    cigars: jax.Array, n_ops: jax.Array, pos: jax.Array
) -> jax.Array:
    """Device twin of :func:`unclipped_end_np` over the padded tensor."""
    _, trail, span = _clip_spans_padded(cigars, n_ops)
    return pos + jnp.maximum(span, 1) - 1 + trail


def _clip_spans_padded(cigars: jax.Array, n_ops: jax.Array):
    oplen = (cigars >> 4).astype(jnp.int32)
    code = (cigars & 0xF).astype(jnp.int32)
    valid = (
        jnp.arange(cigars.shape[-1], dtype=jnp.int32)[None, :]
        < n_ops[:, None]
    )
    is_clip = jnp.asarray(_IS_CLIP, dtype=jnp.int32)[code].astype(bool) & valid
    consume = jnp.asarray(_REF_CONSUMING, dtype=jnp.int32)[code]
    span = jnp.sum(oplen * consume * valid, axis=-1)
    nonclip = (valid & ~is_clip).astype(jnp.int32)
    before = jnp.cumsum(nonclip, axis=-1) - nonclip
    after = jnp.sum(nonclip, axis=-1, keepdims=True) - before - nonclip
    lead = jnp.sum(oplen * (is_clip & (before == 0)), axis=-1)
    trail = jnp.sum(oplen * (is_clip & (after == 0)), axis=-1)
    return lead, trail, span


@jax.jit
def overlap_mask(
    refid: jax.Array,  # int32[N]
    pos: jax.Array,  # int32[N] 0-based
    ref_len: jax.Array,  # int32[N]
    iv_refid: jax.Array,  # int32[K]
    iv_beg: jax.Array,  # int32[K] 0-based inclusive
    iv_end: jax.Array,  # int32[K] 0-based exclusive
) -> jax.Array:
    """bool[N]: record overlaps any interval (exact OverlapDetector
    replacement; unplaced records never match)."""
    end = pos + jnp.maximum(ref_len, 1)  # 0-length records occupy 1 base
    rec_ref = refid[:, None]
    hit = (
        (rec_ref == iv_refid[None, :])
        & (pos[:, None] < iv_end[None, :])
        & (end[:, None] > iv_beg[None, :])
        & (pos[:, None] >= 0)
    )
    return jnp.any(hit, axis=-1)
