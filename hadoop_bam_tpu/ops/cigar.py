"""CIGAR ops: reference span and interval-overlap masks.

``reference_length`` (span consumed on the reference: ops M/D/N/=/X, SAM
spec) feeds alignment ends for the BAI builder and for exact interval
overlap — the device-side replacement for htsjdk's ``OverlapDetector``
filtering in the readers (BAMRecordReader.java:171-175 via
createIndexIterator, VCFRecordReader.java:196-198).

Two implementations:
- ``reference_lengths_np``: host NumPy over the ragged sideband
  (flatten-all-cigars + reduceat — no per-record Python loop),
- ``overlap_mask`` / ``reference_lengths_padded``: jit device version over a
  padded [N, max_ops] cigar tensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# ops M(0) D(2) N(3) =(7) X(8) consume reference.
_REF_CONSUMING = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1, 0, 0, 0, 0, 0, 0, 0])


def reference_lengths_np(data: np.ndarray, soa: dict) -> np.ndarray:
    """Reference span per record from the ragged sideband (vectorized)."""
    n = len(soa["rec_off"])
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    cigar_off = soa["rec_off"].astype(np.int64) + 32 + soa["l_read_name"]
    n_ops = soa["n_cigar_op"].astype(np.int64)
    total_ops = int(n_ops.sum())
    if total_ops == 0:
        return np.zeros(n, dtype=np.int64)
    # Flatten every cigar u32 into one index array.
    rec_of_op = np.repeat(np.arange(n), n_ops)
    starts = np.repeat(cigar_off, n_ops)
    within = np.arange(total_ops) - np.repeat(
        np.cumsum(n_ops) - n_ops, n_ops
    )
    at = starts + 4 * within
    u32 = (
        data[at].astype(np.uint32)
        | (data[at + 1].astype(np.uint32) << 8)
        | (data[at + 2].astype(np.uint32) << 16)
        | (data[at + 3].astype(np.uint32) << 24)
    )
    oplen = (u32 >> 4).astype(np.int64)
    consume = _REF_CONSUMING[u32 & 0xF]
    spans = np.zeros(n, dtype=np.int64)
    np.add.at(spans, rec_of_op, oplen * consume)
    return spans


def pack_cigars_padded(
    data: np.ndarray, soa: dict, max_ops: int
) -> np.ndarray:
    """Gather cigars into a device-friendly [N, max_ops] uint32 tensor
    (0-padded; op code 0 with length 0 is a no-op)."""
    n = len(soa["rec_off"])
    n_ops_all = soa["n_cigar_op"].astype(np.int64)
    if n and int(n_ops_all.max()) > max_ops:
        raise ValueError(
            f"record has {int(n_ops_all.max())} CIGAR ops > max_ops={max_ops}; "
            "truncating would understate reference spans"
        )
    out = np.zeros((n, max_ops), dtype=np.uint32)
    cigar_off = soa["rec_off"].astype(np.int64) + 32 + soa["l_read_name"]
    n_ops = n_ops_all
    for k in range(max_ops):
        rows = n_ops > k
        if not rows.any():
            break
        at = cigar_off[rows] + 4 * k
        out[rows, k] = (
            data[at].astype(np.uint32)
            | (data[at + 1].astype(np.uint32) << 8)
            | (data[at + 2].astype(np.uint32) << 16)
            | (data[at + 3].astype(np.uint32) << 24)
        )
    return out


@jax.jit
def reference_lengths_padded(cigars: jax.Array) -> jax.Array:
    """[N, max_ops] uint32 cigar tensor → int32[N] reference spans."""
    oplen = (cigars >> 4).astype(jnp.int32)
    opcode = (cigars & 0xF).astype(jnp.int32)
    consume = jnp.asarray(_REF_CONSUMING, dtype=jnp.int32)[opcode]
    return jnp.sum(oplen * consume, axis=-1)


@jax.jit
def overlap_mask(
    refid: jax.Array,  # int32[N]
    pos: jax.Array,  # int32[N] 0-based
    ref_len: jax.Array,  # int32[N]
    iv_refid: jax.Array,  # int32[K]
    iv_beg: jax.Array,  # int32[K] 0-based inclusive
    iv_end: jax.Array,  # int32[K] 0-based exclusive
) -> jax.Array:
    """bool[N]: record overlaps any interval (exact OverlapDetector
    replacement; unplaced records never match)."""
    end = pos + jnp.maximum(ref_len, 1)  # 0-length records occupy 1 base
    rec_ref = refid[:, None]
    hit = (
        (rec_ref == iv_refid[None, :])
        & (pos[:, None] < iv_end[None, :])
        & (end[:, None] > iv_beg[None, :])
        & (pos[:, None] >= 0)
    )
    return jnp.any(hit, axis=-1)
