"""Device-side BAM fixed-field decode: byte tensor + offsets → SoA columns.

The device half of SURVEY.md §7 stage 4: once the host has inflated blocks
and walked the record chain (native/), the raw record bytes ship to device
*once* as a uint8 tensor, and every fixed field of every record is gathered
and bit-assembled there in parallel — the batched replacement for htsjdk's
per-record ``BAMRecordCodec.decode`` loop (BAMRecordReader.java:223-232).

All shapes are static under jit: callers pad ``offsets`` to a fixed batch
size with a trailing sentinel (offset 0, masked by ``valid``).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp


def _le(data: jax.Array, at: jax.Array, nbytes: int) -> jax.Array:
    """Little-endian gather: uint32 from ``nbytes`` bytes at ``at``."""
    v = jnp.zeros(at.shape, dtype=jnp.uint32)
    for k in range(nbytes):
        v = v | (data[at + k].astype(jnp.uint32) << jnp.uint32(8 * k))
    return v


@partial(jax.jit, donate_argnums=())
def soa_decode_device(data: jax.Array, offsets: jax.Array) -> Dict[str, jax.Array]:
    """``data``: uint8[B]; ``offsets``: int32[N] record (block_size-word)
    offsets.  Returns the SoA dict matching spec.bam.soa_decode.
    """
    body = offsets + 4
    u32 = lambda off: _le(data, body + off, 4)
    i32 = lambda off: u32(off).astype(jnp.int32)
    u16 = lambda off: _le(data, body + off, 2).astype(jnp.int32)
    u8 = lambda off: data[body + off].astype(jnp.int32)

    return {
        "refid": i32(0),
        "pos": i32(4),
        "l_read_name": u8(8),
        "mapq": u8(9),
        "bin": u16(10),
        "n_cigar_op": u16(12),
        "flag": u16(14),
        "l_seq": i32(16),
        "next_refid": i32(20),
        "next_pos": i32(24),
        "tlen": i32(28),
        "rec_off": body,
        "rec_len": _le(data, offsets, 4).astype(jnp.int32),
    }


def parse_stream_device(data, n_bytes=None, interpret=None):
    """Full on-device BAM parse: record-boundary scan → fixed-field SoA →
    64-bit sort keys, with NO host pass over the uncompressed stream
    (SURVEY §7 stage 4; the host ``hbam_record_chain`` walk replaced by the
    Pallas chain kernel with cross-chunk carry).

    ``data``: uint8 record stream (device or host array).  Returns
    ``(soa, hi, lo, valid, ok)`` — SoA columns and key halves are padded to
    the chain kernel's capacity; ``valid`` masks live rows; ``ok`` is False
    on a misaligned/truncated chain.  Unmapped-read keys use the murmur3
    hash column convention of :func:`ops.keys.make_keys` (hash32 = 0 here;
    callers needing reference-exact unmapped ordering supply the hash
    column separately — the mapped-key fast path is what the sort needs).
    """
    from .keys import make_keys
    from .pallas.chain import record_chain_device

    a = jnp.asarray(data, dtype=jnp.uint8)
    offs, count, ok = record_chain_device(a, n_bytes, interpret=interpret)
    valid = jnp.arange(offs.shape[0], dtype=jnp.int32) < count
    # Clip padded rows to offset 0 (in bounds, masked by ``valid``).
    offs = jnp.where(valid, offs, 0)
    if a.shape[0] < 36:  # minimum one fixed-field record for the gathers
        a = jnp.pad(a, (0, 36 - a.shape[0]))
    soa = soa_decode_device(a, offs)
    hash32 = jnp.zeros(offs.shape, jnp.int32)
    hi, lo = make_keys(soa["refid"], soa["pos"], soa["flag"], hash32)
    return soa, hi, lo, valid, ok


@jax.jit
def _stream_keys(data: jax.Array, offs: jax.Array, count: jax.Array):
    """Slim key-only field gather: refid/pos/flag at the chain offsets →
    (hi, lo) key halves + a valid-masked unmapped-row mask.

    The production subset of :func:`soa_decode_device` — the sort needs only
    the three key inputs, so the other ten columns' gathers are skipped.
    Padded rows (``offs`` beyond ``count``) are clipped to offset 0 and
    masked out of ``unmapped``; their hi/lo values are garbage the caller
    never reads (it slices ``[:count]``).
    """
    from .keys import make_keys, unmapped_mask

    valid = jnp.arange(offs.shape[0], dtype=jnp.int32) < count
    offs = jnp.where(valid, offs, 0)
    body = offs + 4
    refid = _le(data, body, 4).astype(jnp.int32)
    pos = _le(data, body + 4, 4).astype(jnp.int32)
    flag = _le(data, body + 14, 2).astype(jnp.int32)
    hash32 = jnp.zeros(offs.shape, jnp.int32)
    hi, lo = make_keys(refid, pos, flag, hash32)
    unmapped = unmapped_mask(refid, pos, flag) & valid
    return hi, lo, unmapped


def keys_from_stream_device(stream, n_bytes=None, interpret=None):
    """Sort keys of a raw BAM record stream, computed entirely on device.

    The production device-resident read path (SURVEY §7 stage 4): the
    caller uploads the inflated record stream once; the Pallas chain kernel
    re-derives record boundaries from the raw bytes, and the key gathers +
    :func:`ops.keys.make_keys` assemble the (hi, lo) sort-key halves
    on-chip — the host never walks fields or builds keys (displacing the
    per-record decode loop of BAMRecordReader.java:223-232).

    Returns ``(hi, lo, unmapped, count, ok)`` — all device arrays, padded
    to the chain kernel's capacity; live rows are ``[:count]``.  ``unmapped``
    marks rows whose key needs the host murmur3 hash patched in via
    :func:`patch_unmapped_keys` (hash32 is 0 here; mapped rows are final).
    ``ok`` is False on a misaligned/truncated chain (caller falls back).
    """
    from .pallas.chain import record_chain_device

    a = jnp.asarray(stream, dtype=jnp.uint8)
    offs, count, ok = record_chain_device(a, n_bytes, interpret=interpret)
    if a.shape[0] < 36:
        a = jnp.pad(a, (0, 36 - a.shape[0]))
    hi, lo, unmapped = _stream_keys(a, offs, count)
    return hi, lo, unmapped, count, ok


@jax.jit
def patch_unmapped_keys(
    hi: jax.Array, lo: jax.Array, unmapped: jax.Array, hash32: jax.Array
):
    """Overwrite unmapped rows' keys with the host-computed murmur3 hash.

    Java packs the unmapped key as ``(long)INT_MAX << 32 | hash`` with sign
    extension (BAMRecordReader.java:85-86, 119-121): a negative hash floods
    the high word to -1.  Bit-equal to :func:`spec.bam.soa_keys`.
    """
    int_max = jnp.int32(2**31 - 1)
    hi = jnp.where(
        unmapped, jnp.where(hash32 < 0, jnp.int32(-1), int_max), hi
    )
    lo = jnp.where(unmapped, hash32.astype(jnp.uint32), lo)
    return hi, lo


def pad_offsets(offsets, batch: int):
    """Pad an offsets array to ``batch`` rows; returns (padded, valid mask).

    Pad rows point at offset 0 (always in-bounds) and are masked out.
    """
    import numpy as np

    n = len(offsets)
    if n > batch:
        raise ValueError(f"batch {batch} < record count {n}")
    padded = np.zeros(batch, dtype=np.int32)
    padded[:n] = offsets
    valid = np.zeros(batch, dtype=bool)
    valid[:n] = True
    return padded, valid
