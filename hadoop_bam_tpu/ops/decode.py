"""Device-side BAM fixed-field decode: byte tensor + offsets → SoA columns.

The device half of SURVEY.md §7 stage 4: once the host has inflated blocks
and walked the record chain (native/), the raw record bytes ship to device
*once* as a uint8 tensor, and every fixed field of every record is gathered
and bit-assembled there in parallel — the batched replacement for htsjdk's
per-record ``BAMRecordCodec.decode`` loop (BAMRecordReader.java:223-232).

All shapes are static under jit: callers pad ``offsets`` to a fixed batch
size with a trailing sentinel (offset 0, masked by ``valid``).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp


def _le(data: jax.Array, at: jax.Array, nbytes: int) -> jax.Array:
    """Little-endian gather: uint32 from ``nbytes`` bytes at ``at``."""
    v = jnp.zeros(at.shape, dtype=jnp.uint32)
    for k in range(nbytes):
        v = v | (data[at + k].astype(jnp.uint32) << jnp.uint32(8 * k))
    return v


@partial(jax.jit, donate_argnums=())
def soa_decode_device(data: jax.Array, offsets: jax.Array) -> Dict[str, jax.Array]:
    """``data``: uint8[B]; ``offsets``: int32[N] record (block_size-word)
    offsets.  Returns the SoA dict matching spec.bam.soa_decode.
    """
    body = offsets + 4
    u32 = lambda off: _le(data, body + off, 4)
    i32 = lambda off: u32(off).astype(jnp.int32)
    u16 = lambda off: _le(data, body + off, 2).astype(jnp.int32)
    u8 = lambda off: data[body + off].astype(jnp.int32)

    return {
        "refid": i32(0),
        "pos": i32(4),
        "l_read_name": u8(8),
        "mapq": u8(9),
        "bin": u16(10),
        "n_cigar_op": u16(12),
        "flag": u16(14),
        "l_seq": i32(16),
        "next_refid": i32(20),
        "next_pos": i32(24),
        "tlen": i32(28),
        "rec_off": body,
        "rec_len": _le(data, offsets, 4).astype(jnp.int32),
    }


def pad_offsets(offsets, batch: int):
    """Pad an offsets array to ``batch`` rows; returns (padded, valid mask).

    Pad rows point at offset 0 (always in-bounds) and are masked out.
    """
    import numpy as np

    n = len(offsets)
    if n > batch:
        raise ValueError(f"batch {batch} < record count {n}")
    padded = np.zeros(batch, dtype=np.int32)
    padded[:n] = offsets
    valid = np.zeros(batch, dtype=bool)
    valid[:n] = True
    return padded, valid
