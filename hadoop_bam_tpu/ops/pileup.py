"""Pileup/depth: segmented count over the sorter's coordinate columns.

Per-base depth over a region is a segmented count the coordinate keys the
sorter already builds (``ops/keys.py``) answer directly: with the
per-record reference spans as two *independently sorted* axes,

    depth[x] = #(start <= x) - #(end <= x)
             = searchsorted(starts, x, 'right') - searchsorted(ends, x, 'right')

— the same searchsorted-cover idiom as the ragged interval join
(``ops/pallas/overlap.py``), vectorized over the base axis.  The device
build is jitted XLA over fixed-size base chunks (one compiled shape); the
NumPy twin is bit-identical by construction (same primitives, same side
rules; the cast to int32 is exact — depth is bounded by the record
count).  Windowed summaries (binned mean/max, covered bases) reduce the
profile chunk by chunk, so a contig-scale region never materializes a
contig-scale array on the host.

Tier policy: ``use_device`` is per *call*; a device failure tiers that
call down to the host twin (counted ``pileup.tierdowns``) — never a
sticky disable.  Disarmed calls move zero ``pileup.*`` counters.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .keys import split_keys_np
from ..utils.tracing import METRICS

#: Bases of profile computed per device launch / host vector op.
CHUNK_BASES = 1 << 20
_PAD = (1 << 31) - 1  # span sentinel: past every base coordinate


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _profile_host(starts_sorted, ends_sorted, c0: int, c1: int) -> np.ndarray:
    xs = np.arange(c0, c1, dtype=np.int64)
    return (
        np.searchsorted(starts_sorted, xs, side="right")
        - np.searchsorted(ends_sorted, xs, side="right")
    ).astype(np.int32)


def _profile_device(starts_sorted, ends_sorted, c0: int, c1: int) -> np.ndarray:
    import jax
    import jax.numpy as jnp

    @jax.jit
    def call(s, e, base):
        xs = base + jnp.arange(CHUNK_BASES, dtype=jnp.int32)
        return (
            jnp.searchsorted(s, xs, side="right")
            - jnp.searchsorted(e, xs, side="right")
        ).astype(jnp.int32)

    n = len(starts_sorted)
    npad = _pow2(max(n, 1))
    s = np.pad(starts_sorted.astype(np.int32), (0, npad - n), constant_values=_PAD)
    e = np.pad(ends_sorted.astype(np.int32), (0, npad - n), constant_values=_PAD)
    out = call(s, e, np.int32(c0))
    return np.asarray(out)[: c1 - c0]


def depth_profile(
    starts, ends, beg: int, end: int, use_device: bool = False
) -> np.ndarray:
    """int32[end-beg] per-base depth over [beg, end), 0-based half-open.
    ``starts``/``ends`` are the per-record reference spans, any order."""
    starts = np.sort(np.asarray(starts, np.int64), kind="stable")
    ends = np.sort(np.asarray(ends, np.int64), kind="stable")
    parts = []
    for c0 in range(int(beg), int(end), CHUNK_BASES):
        c1 = min(int(end), c0 + CHUNK_BASES)
        if use_device:
            try:
                parts.append(_profile_device(starts, ends, c0, c1))
                METRICS.count("pileup.device_chunks", 1)
                continue
            except Exception:
                METRICS.count("pileup.tierdowns", 1)
        parts.append(_profile_host(starts, ends, c0, c1))
    if not parts:
        return np.zeros(0, np.int32)
    return np.concatenate(parts)


def depth_summary(
    starts,
    ends,
    beg: int,
    end: int,
    bin_size: int = 1 << 12,
    use_device: bool = False,
) -> Dict:
    """Windowed depth summary over [beg, end): per-bin mean depth, plus
    region max/mean/covered — reduced chunk by chunk so the full profile
    never lives at once.  JSON-ready (plain ints/floats/lists)."""
    beg, end = int(beg), int(end)
    bin_size = max(1, int(bin_size))
    span = max(0, end - beg)
    n_bins = -(-span // bin_size) if span else 0
    sums = np.zeros(n_bins, np.int64)
    maxs = np.zeros(n_bins, np.int64)
    covered = 0
    starts = np.sort(np.asarray(starts, np.int64), kind="stable")
    ends_s = np.sort(np.asarray(ends, np.int64), kind="stable")
    # Chunks aligned to bin boundaries so each bin reduces whole.
    chunk = bin_size * max(1, CHUNK_BASES // bin_size)
    for c0 in range(beg, end, chunk):
        c1 = min(end, c0 + chunk)
        if use_device:
            try:
                prof = _profile_device(starts, ends_s, c0, c1)
                METRICS.count("pileup.device_chunks", 1)
            except Exception:
                METRICS.count("pileup.tierdowns", 1)
                prof = _profile_host(starts, ends_s, c0, c1)
        else:
            prof = _profile_host(starts, ends_s, c0, c1)
        covered += int((prof > 0).sum())
        k = -(-len(prof) // bin_size)
        padded = np.zeros(k * bin_size, np.int64)
        padded[: len(prof)] = prof
        b0 = (c0 - beg) // bin_size
        sums[b0 : b0 + k] += padded.reshape(k, bin_size).sum(axis=1)
        maxs[b0 : b0 + k] = np.maximum(
            maxs[b0 : b0 + k], padded.reshape(k, bin_size).max(axis=1)
        )
    widths = np.minimum(
        bin_size, span - np.arange(n_bins, dtype=np.int64) * bin_size
    )
    bin_mean = (sums / np.maximum(widths, 1)).round(4)
    total = int(sums.sum())
    return {
        "bin_size": bin_size,
        "bins": [float(x) for x in bin_mean],
        "max_depth": int(maxs.max()) if n_bins else 0,
        "mean_depth": round(total / span, 4) if span else 0.0,
        "covered_bases": covered,
        "total_bases": span,
    }


def spans_from_keys(
    keys, lengths, rid: int, beg: Optional[int] = None, end: Optional[int] = None
):
    """(starts, ends) reference spans on contig ``rid`` from the sorter's
    packed coordinate keys (``ops.keys.pack_keys_np`` layout) and the
    per-record reference lengths — clipped to [beg, end) when given."""
    hi, lo = split_keys_np(np.asarray(keys, np.int64))
    sel = hi == rid
    starts = lo[sel].astype(np.int64)
    ends = starts + np.asarray(lengths, np.int64)[sel]
    if beg is not None or end is not None:
        b = 0 if beg is None else int(beg)
        e = (1 << 62) if end is None else int(end)
        keep = (starts < e) & (ends > b)
        starts, ends = starts[keep], ends[keep]
    return starts, ends
