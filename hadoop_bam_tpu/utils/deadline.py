"""End-to-end deadlines: one budget carried from client to kernel seam.

Hadoop-BAM inherits its liveness story from the Hadoop task runtime — a
task that exceeds ``mapreduce.task.timeout`` is killed and retried — but
that bound is per *attempt*, not per *request*: a caller has no way to
say "this answer is worthless after 500 ms".  This module is the missing
request-scoped bound, the Clipper-style inference-serving deadline: a
:class:`Deadline` is created once (client side, or at daemon dispatch
from the request's ``deadline_ms``) and carried through every seam that
can burn time — admission queueing, the lane-batcher queue, endpoint
window loops, the elastic-executor attempt loop — each of which calls
:meth:`Deadline.check` and raises :class:`DeadlineExceeded` instead of
doing work nobody will read.

Deliberately in ``utils`` (not ``serve``): the executor and batcher
seams live below the serve layer and must not import it.

Disarmed contract (the PR 7 stance): with no deadline set, every seam is
one ``is None`` branch and records no counters — asserted by the
zero-overhead test in tests/test_faults.py.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional

from .tracing import METRICS, current_request


class DeadlineExceeded(RuntimeError):
    """A request's end-to-end deadline expired at ``seam``.

    Distinct from shed (the work was never admitted) and from the
    retryable transport errors (retrying cannot help — the budget is
    gone); the serve protocol maps it to the ``DEADLINE_EXCEEDED`` error
    code and clients must not auto-retry it.
    """

    def __init__(self, seam: str, remaining_ms: float = 0.0):
        self.seam = seam
        super().__init__(
            f"deadline exceeded at the {seam} seam "
            f"({abs(remaining_ms):.1f} ms over)"
        )


class Deadline:
    """An absolute monotonic expiry, checked (never polled) at seams.

    Seam names are metric-name components (lowercase, no dots):
    ``dispatch`` / ``admission`` / ``batcher`` / ``endpoint`` /
    ``executor`` / ``pipeline`` / ``client``.
    """

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float):
        self.expires_at = float(expires_at)

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(time.monotonic() + float(ms) / 1e3)

    @classmethod
    def from_request(cls, req: dict) -> Optional["Deadline"]:
        """The request's remaining budget (``deadline_ms``), or None.
        A malformed value is treated as absent — a garbled deadline must
        not turn into an unbounded one *or* a hard reject."""
        ms = req.get("deadline_ms")
        if ms is None:
            return None
        try:
            return cls.after_ms(float(ms))
        except (TypeError, ValueError):
            return None

    def remaining_ms(self) -> float:
        return (self.expires_at - time.monotonic()) * 1e3

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at

    def check(self, seam: str) -> None:
        """Raise (and count) if expired; free otherwise.  With a request
        context ambient, the expiry is also annotated as a hop so the
        waterfall names the seam where the budget died."""
        rem = self.remaining_ms()
        if rem <= 0.0:
            METRICS.count("serve.deadline.exceeded", 1)
            METRICS.count(f"serve.deadline.exceeded.{seam}", 1)
            rctx = current_request()
            if rctx is not None:
                rctx.annotate(f"deadline.{seam}", over_ms=abs(rem))
            raise DeadlineExceeded(seam, rem)


# Ambient per-thread deadline: the serve handler thread sets it once and
# the seams it calls into synchronously (read_split → inflate_fn → the
# lane batcher) pick it up without every signature growing a parameter.
# Work handed to OTHER threads (the executor pool) gets the deadline
# explicitly — thread-locals do not follow a ThreadPoolExecutor submit.
_TLS = threading.local()


def current_deadline() -> Optional[Deadline]:
    return getattr(_TLS, "deadline", None)


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]) -> Iterator[None]:
    """Ambient deadline for the current thread (None = leave unset)."""
    if deadline is None:
        yield
        return
    old = getattr(_TLS, "deadline", None)
    _TLS.deadline = deadline
    try:
        yield
    finally:
        _TLS.deadline = old
