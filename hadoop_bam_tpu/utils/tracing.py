"""Tracing, counters, and progress: the observability the reference lacks.

The reference's only instrumentation is a deprecated nanosecond ``Timer``
(util/Timer.java:4-12) and a ``-`` progress tick every 500MB in its indexers
(SplittingBAMIndexer.java:144,277-282); task progress is Hadoop's
``getProgress()`` contract.  Per SURVEY.md §5 the TPU build wires real
tracing instead, in three layers:

1. **Cumulative metrics** (:class:`MetricsRegistry`): thread-safe named
   counters, per-name span-time sums, and fixed-bucket log2
   :class:`Histogram` distributions (p50/p95/p99 without unbounded
   memory) — the ``--metrics`` / serve ``stats`` substrate.
2. **Timeline tracer** (:class:`Tracer`): an opt-in bounded ring buffer
   of per-event ``(name, t0, t1, thread, category, args)`` records fed by
   the same :func:`span` call sites, exported as Chrome trace-event JSON
   (loadable in Perfetto/chrome://tracing, reduced by
   ``tools/trace_report.py``).  Disarmed, the ring buffer is never
   allocated and :func:`span` pays one attribute check — the same
   disarmed-contract stance as the fault seams.
3. **Run provenance** (:class:`RunManifest`): what actually ran — the
   backend, every device-tier decision with its reason counters, the
   fault/salvage mode — attached to every ``--metrics`` JSON and bench
   round so a silent CPU fallback can never masquerade as a device
   number (the r4/r5 lesson, BENCH_NOTES.md).

Everything degrades to no-ops: spans/counters are cheap dict updates, and
the profiler hooks import ``jax`` lazily so host-only tools never touch a
device backend.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import sys
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

#: Every span/counter/histogram/gauge name must match: dotted lowercase,
#: at least two components (``subsystem.metric``), so the metrics
#: namespace stays greppable.  tests/test_tracing.py lints the source
#: against this pattern.
METRIC_NAME_PATTERN = r"^[a-z0-9_]+(\.[a-z0-9_]+)+$"


class Histogram:
    """Fixed log2-bucket value distribution: percentiles without unbounded
    memory.

    Bucket ``i`` counts observations ``v`` with ``2**(i-1) < v <= 2**i``
    (bucket 0 takes ``v <= 1``; the last bucket takes everything larger),
    so the footprint is :data:`N_BUCKETS` integers forever regardless of
    observation count.  :meth:`percentile` returns the upper bound of the
    bucket containing the requested rank — i.e. the smallest power of two
    that is ≥ the true percentile, a ≤2x overestimate by construction —
    which is the right fidelity for latency SLO gauges (the serve
    daemon's per-op p50/p95/p99).
    """

    N_BUCKETS = 64

    __slots__ = ("counts", "n", "total")

    def __init__(self) -> None:
        self.counts = [0] * self.N_BUCKETS
        self.n = 0
        self.total = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        if v <= 1.0:
            i = 0
        else:
            # frexp: v = m * 2**e with 0.5 <= m < 1, so the smallest
            # power of two >= v is 2**e (2**(e-1) for exact powers).
            m, e = math.frexp(v)
            i = min(self.N_BUCKETS - 1, e - 1 if m == 0.5 else e)
        self.counts[i] += 1
        self.n += 1
        self.total += v

    @staticmethod
    def bucket_upper(i: int) -> float:
        return float(2**i)

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding the ``q``-quantile
        observation (0 when empty)."""
        if self.n == 0:
            return 0.0
        rank = max(1, math.ceil(q * self.n))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return self.bucket_upper(i)
        return self.bucket_upper(self.N_BUCKETS - 1)

    def as_dict(self) -> Dict[str, object]:
        return {
            "count": self.n,
            "sum": self.total,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            # Sparse: only occupied buckets, keyed by their upper bound.
            "buckets": {
                str(self.bucket_upper(i)): c
                for i, c in enumerate(self.counts)
                if c
            },
        }

    def copy(self) -> "Histogram":
        h = Histogram()
        h.counts = list(self.counts)
        h.n = self.n
        h.total = self.total
        return h


class MetricsRegistry:
    """Thread-safe named counters + cumulative span timings + histograms."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._spans: Dict[str, float] = {}
        self._span_counts: Dict[str, int] = {}
        self._hists: Dict[str, Histogram] = {}
        self._gauges: Dict[str, float] = {}

    def count(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def set_gauge(self, name: str, value: float) -> None:
        """Set a first-class point-in-time gauge (arena occupancy, HBM
        ledger live/peak bytes, queue depths).  Unlike counters these are
        levels, not totals: the latest write wins, snapshots carry the
        current value, and the serve ``metrics`` op exports them in
        Prometheus text without each subsystem keeping its own ad-hoc
        gauges block."""
        with self._lock:
            self._gauges[name] = float(value)

    def gauges(self) -> Dict[str, float]:
        """A copy of the current gauge levels."""
        with self._lock:
            return dict(self._gauges)

    def add_span(self, name: str, seconds: float) -> None:
        with self._lock:
            self._spans[name] = self._spans.get(name, 0.0) + seconds
            self._span_counts[name] = self._span_counts.get(name, 0) + 1

    def observe(self, name: str, value: float) -> None:
        """One observation into the named log2 :class:`Histogram`
        (created on first use — e.g. per-op latency in milliseconds)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value)

    def report(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "span_seconds": dict(self._spans),
                "span_counts": dict(self._span_counts),
                "histograms": {
                    k: h.as_dict() for k, h in self._hists.items()
                },
                "gauges": dict(self._gauges),
            }

    def histogram(self, name: str) -> Optional[Histogram]:
        """A copy of the named histogram (None if never observed)."""
        with self._lock:
            h = self._hists.get(name)
            return h.copy() if h is not None else None

    def reset(self) -> None:
        """Zero every counter/span/histogram.

        **Hazard (concurrent use):** in a long-lived process — the serve
        daemon above all — any in-flight request doing
        ``delta(snapshot_at_admission)`` accounting sees its *before*
        snapshot become larger than the post-reset registry, corrupting
        its reported deltas (negative values are the visible symptom).
        Never call this while other threads may be mid-request: take a
        :func:`snapshot` at the interesting epoch and report
        :func:`delta` against it instead (the serve ``stats`` op and the
        CLI ``--metrics`` report both do exactly this).  Tests that own
        the whole process are the intended caller.
        """
        with self._lock:
            self._counters.clear()
            self._spans.clear()
            self._span_counts.clear()
            self._hists.clear()
            self._gauges.clear()


METRICS = MetricsRegistry()


def count_h2d(nbytes: int, what: str = "") -> None:
    """Transfer ledger, host→device direction: every deliberate upload on
    the hot paths reports its bytes here (keys, device-parse streams,
    compressed blocks, write-path offset columns…), so the round
    artifacts show the PCIe traffic instead of inferring it.  ``what``
    adds an itemized ``transfers.h2d.<what>`` counter next to the
    ``transfers.h2d_bytes`` total.  With the timeline tracer armed, each
    crossing also lands as an instant event on the trace."""
    n = int(nbytes)
    METRICS.count("transfers.h2d_bytes", n)
    if what:
        METRICS.count(f"transfers.h2d.{what}", n)
    if TRACER.armed:
        TRACER.instant("transfers.h2d", "xfer", {"bytes": n, "what": what})


def count_d2h(nbytes: int, what: str = "") -> None:
    """Transfer ledger, device→host direction (permutation fetches,
    inflated payloads, compressed part blobs, CRC columns…)."""
    n = int(nbytes)
    METRICS.count("transfers.d2h_bytes", n)
    if what:
        METRICS.count(f"transfers.d2h.{what}", n)
    if TRACER.armed:
        TRACER.instant("transfers.d2h", "xfer", {"bytes": n, "what": what})


def snapshot(registry: Optional[MetricsRegistry] = None) -> Dict[str, Dict[str, float]]:
    """A point-in-time copy of the registry's counters + spans.

    Pair with :func:`delta` for per-request accounting in long-lived
    processes (the serve daemon): the process-global counters keep
    accumulating — resetting them mid-flight would corrupt every other
    in-flight request's numbers (see :meth:`MetricsRegistry.reset`) —
    and each request reports ``delta(snapshot_at_admission)`` instead."""
    return (registry or METRICS).report()


def delta(
    before: Dict[str, Dict[str, float]],
    after: Optional[Dict[str, Dict[str, float]]] = None,
    registry: Optional[MetricsRegistry] = None,
) -> Dict[str, Dict[str, float]]:
    """Per-section difference of two :func:`snapshot` reports.

    ``after`` defaults to a fresh snapshot.  Only keys whose value moved
    are kept, so a request's report shows exactly the counters/spans it
    touched.  Counters never decrease, but the diff is computed signed so
    a misuse (swapped arguments) is visible rather than silently clamped.
    Histograms diff on their scalar ``count``/``sum`` only (bucket-level
    diffs would re-create the unbounded-memory problem they solve);
    percentiles remain a cumulative-distribution property and ride in the
    full snapshot.
    """
    if after is None:
        after = snapshot(registry)
    out: Dict[str, Dict[str, float]] = {}
    for section in ("counters", "span_seconds", "span_counts"):
        b = before.get(section, {})
        a = after.get(section, {})
        d = {}
        for k in set(a) | set(b):
            v = a.get(k, 0) - b.get(k, 0)
            if v:
                d[k] = v
        out[section] = d
    hd: Dict[str, Dict[str, float]] = {}
    bh = before.get("histograms", {})
    for k, av in after.get("histograms", {}).items():
        bv = bh.get(k, {})
        dc = av.get("count", 0) - bv.get("count", 0)
        if dc:
            hd[k] = {
                "count": dc,
                "sum": av.get("sum", 0.0) - bv.get("sum", 0.0),
            }
    out["histograms"] = hd
    # Gauges are levels, not totals — a difference of two levels is
    # meaningless, so the delta carries the *current* (after) levels.
    out["gauges"] = dict(after.get("gauges", {}))
    return out


def transfers_report(counters: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    """The ``transfers`` block of the CLI ``--metrics`` JSON: every
    ledger counter with the ``transfers.`` prefix stripped."""
    if counters is None:
        counters = METRICS.report()["counters"]
    return {
        k[len("transfers."):]: v
        for k, v in counters.items()
        if k.startswith("transfers.")
    }


# ---------------------------------------------------------------------------
# Timeline tracer: per-event ring buffer → Chrome trace-event JSON.
# ---------------------------------------------------------------------------

DEFAULT_TRACE_EVENTS = 1 << 16  # ring capacity: ~64k events ≈ a few MB


class Tracer:
    """Opt-in bounded ring buffer of timeline events.

    Disarmed (the default), no buffer exists and the :func:`span` hot
    path pays exactly one attribute load (``TRACER.armed``) — the same
    zero-cost-when-off contract as the fault seams, asserted by
    tests/test_tracing.py's disarmed-contract test.  Armed
    (:meth:`start`), every :func:`span` exit appends one event tuple
    ``(name, category, t0, t1, tid, args)``; when the ring fills, the
    OLDEST events are dropped (``dropped_events`` counts them — the
    cumulative METRICS spans are unaffected, so totals stay honest even
    on a truncated timeline).

    Export (:meth:`export_chrome`) writes Chrome trace-event JSON —
    ``{"traceEvents": [{"name", "cat", "ph": "X", "ts", "dur", "pid",
    "tid", "args"}, …]}`` — loadable in Perfetto/chrome://tracing and
    reducible by ``tools/trace_report.py``.  Timestamps are microseconds
    from :meth:`start`.  This is host-side wall clock; the XPlane hook
    (:func:`device_trace`) remains the device-timeline companion and the
    two compose (span names annotate the XPlane timeline via
    TraceAnnotation).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ring: Optional[List] = None  # allocated only when armed
        self._cap = 0
        self._head = 0  # next write slot
        self._count = 0
        self._epoch = 0.0
        self.armed = False
        self.dropped_events = 0
        # Per-category drop ledger: a request tree reassembled from the
        # ring can only be trusted complete when none of its categories
        # lost events to overflow — request_report stamps trees
        # ``incomplete`` from exactly this dict.
        self.dropped_by_category: Dict[str, int] = {}

    def start(self, capacity: int = DEFAULT_TRACE_EVENTS) -> None:
        """Arm the tracer with a fresh ring of ``capacity`` event slots."""
        with self._lock:
            self._cap = max(16, int(capacity))
            self._ring = [None] * self._cap
            self._head = 0
            self._count = 0
            self.dropped_events = 0
            self.dropped_by_category = {}
            self._epoch = time.perf_counter()
            self.armed = True

    def stop(self) -> None:
        """Disarm and free the ring (events are gone — export first)."""
        with self._lock:
            self.armed = False
            self._ring = None
            self._cap = 0
            self._head = 0
            self._count = 0

    def emit(
        self,
        name: str,
        category: str,
        t0: float,
        t1: float,
        args: Optional[dict] = None,
        merge_ctx: bool = True,
    ) -> None:
        """Append one complete event (perf_counter endpoints).  Ambient
        :func:`trace_ctx` key/values merge under explicit ``args``
        (``merge_ctx=False`` keeps ``args`` pure — counter events, whose
        args are the series values).  With an ambient
        :class:`RequestContext` in scope, the request's trace id rides
        along as ``args["trace"]`` — the key the per-request causal tree
        is reassembled on."""
        ctx = getattr(_TLS, "ctx", None) if merge_ctx else None
        if ctx:
            args = {**ctx, **args} if args else dict(ctx)
        if merge_ctx:
            rctx = getattr(_TLS, "request", None)
            if rctx is not None:
                args = (
                    {**args, "trace": rctx.trace_id}
                    if args
                    else {"trace": rctx.trace_id}
                )
        ev = (
            name,
            category,
            t0 - self._epoch,
            t1 - self._epoch,
            threading.get_ident(),
            args,
        )
        with self._lock:
            if self._ring is None:
                return  # disarmed between the caller's check and now
            old = self._ring[self._head]
            self._ring[self._head] = ev
            self._head = (self._head + 1) % self._cap
            if self._count < self._cap:
                self._count += 1
            else:
                self.dropped_events += 1
                # The evicted slot's category: drops are accounted per
                # category so a reassembled request tree knows whether
                # *its* event classes are still all present.
                cat = old[1] if old else ""
                self.dropped_by_category[cat] = (
                    self.dropped_by_category.get(cat, 0) + 1
                )

    def instant(
        self, name: str, category: str, args: Optional[dict] = None
    ) -> None:
        """A zero-duration marker event (progress ticks, transfers)."""
        t = time.perf_counter()
        self.emit(name, category, t, t, args)

    #: Reserved category for counter-track events (``ph: "C"`` on export).
    COUNTER_CATEGORY = "counter"

    def counter(self, name: str, values: Dict[str, float]) -> None:
        """A Chrome counter-track sample (``ph: "C"``): Perfetto renders
        the named series as a stacked area chart alongside the stage
        timeline — the HBM residency ledger samples ``hbm.live_bytes``
        per allocation kind here, so memory-over-time is a *track*, not
        an inference.  Ambient ``trace_ctx`` args are deliberately not
        merged (they would become phantom series)."""
        if not self.armed:
            return
        t = time.perf_counter()
        self.emit(
            name, self.COUNTER_CATEGORY, t, t, dict(values),
            merge_ctx=False,
        )

    def events(self) -> List[tuple]:
        """The live events, oldest first."""
        with self._lock:
            if self._ring is None or self._count == 0:
                return []
            if self._count < self._cap:
                return list(self._ring[: self._count])
            return (
                self._ring[self._head :] + self._ring[: self._head]
            )

    def chrome_events(self) -> List[dict]:
        """Events as Chrome trace-event dicts (``ph: "X"`` complete
        events; instants are zero-duration)."""
        pid = os.getpid()
        out = []
        for name, cat, t0, t1, tid, args in self.events():
            if cat == self.COUNTER_CATEGORY:
                # Counter-track sample: Perfetto draws args' numeric
                # values as series of the named counter track.
                ev = {
                    "name": name,
                    "ph": "C",
                    "ts": round(t0 * 1e6, 3),
                    "pid": pid,
                    "tid": tid,
                    "args": args or {},
                }
                out.append(ev)
                continue
            ev = {
                "name": name,
                "cat": cat,
                "ph": "X",
                "ts": round(t0 * 1e6, 3),
                "dur": round((t1 - t0) * 1e6, 3),
                "pid": pid,
                "tid": tid,
            }
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def chrome_events_for_trace(self, trace_id: str) -> List[dict]:
        """The live events annotated with ``trace_id`` (``args["trace"]``,
        or membership in a shared event's ``args["traces"]`` — the lane
        batcher's coalesced launches carry every rider), as Chrome dicts
        — the tail sampler's copy-out when a request earns an exemplar
        (rare, so the O(ring) scan is off the hot path)."""
        out = []
        for e in self.chrome_events():
            a = e.get("args") or {}
            if a.get("trace") == trace_id or (
                trace_id in a.get("traces", ())
            ):
                out.append(e)
        return out

    def now_us(self) -> float:
        """The current timestamp on the armed ring's clock (microseconds
        since :meth:`start`; 0.0 when disarmed) — the mesh trace shards'
        clock anchor: every host stamps this right after the same global
        barrier, so ``tools/mesh_report.py`` can shift each shard onto
        one merged timeline."""
        if not self.armed:
            return 0.0
        return (time.perf_counter() - self._epoch) * 1e6

    def drops_snapshot(self) -> Tuple[int, Dict[str, int]]:
        """``(total dropped, per-category dropped)`` — taken together so
        exemplar completeness verdicts see one consistent view."""
        with self._lock:
            return self.dropped_events, dict(self.dropped_by_category)

    def export_chrome(self, path_or_stream, other: Optional[dict] = None) -> int:
        """Write the Chrome trace-event JSON; returns the event count.
        ``other`` merges extra keys into ``otherData`` (the mesh shards
        carry their host id and clock anchor there)."""
        evs = self.chrome_events()
        other_data = {
            "dropped_events": self.dropped_events,
            "dropped_by_category": dict(self.dropped_by_category),
        }
        if other:
            other_data.update(other)
        doc = {
            "traceEvents": evs,
            "displayTimeUnit": "ms",
            "otherData": other_data,
        }
        if hasattr(path_or_stream, "write"):
            json.dump(doc, path_or_stream)
        else:
            with open(path_or_stream, "w") as f:
                json.dump(doc, f)
        return len(evs)


#: The process-global timeline tracer (CLI ``--trace`` arms it).
TRACER = Tracer()

_TLS = threading.local()


@contextlib.contextmanager
def trace_ctx(**kw) -> Iterator[None]:
    """Ambient event arguments for the current thread: every event
    emitted inside the scope carries these key/values (``split=3``,
    ``part=0`` — the stall reducer's per-item attribution).  Free when
    the tracer is disarmed."""
    if not TRACER.armed:
        yield
        return
    old = getattr(_TLS, "ctx", None)
    _TLS.ctx = {**old, **kw} if old else dict(kw)
    try:
        yield
    finally:
        _TLS.ctx = old


# ---------------------------------------------------------------------------
# Request-scoped tracing: Dapper-style ids + hop annotations per request.
# ---------------------------------------------------------------------------

#: Hop-annotation cap per request: a runaway seam (thousands of parts)
#: must not turn the always-on summary path into unbounded memory.
MAX_REQUEST_HOPS = 256


def _rand_hex(n_bytes: int) -> str:
    """``n_bytes`` of entropy as lowercase hex, from a per-thread buffer
    refilled by one ``os.urandom(1024)`` syscall per ~20 requests — id
    generation is on the always-on per-request path, and a syscall per
    id is the kind of fixed cost the <2% tracing-overhead contract is
    measured against."""
    n = n_bytes * 2
    buf = getattr(_TLS, "idbuf", "")
    if len(buf) < n:
        buf = os.urandom(1024).hex()
    out = buf[:n]
    _TLS.idbuf = buf[n:]
    return out


class RequestContext:
    """One served request's identity and its always-on hop summary.

    A 128-bit ``trace_id`` names the request end to end (the client
    originates it; the daemon continues it — the Dapper propagation
    stance), a 64-bit ``span_id`` names this process's segment of it,
    and ``baggage`` carries opaque key/values across the wire.  Both ids
    are lowercase hex strings so they serialize into the serve protocol
    and the JSONL artifacts without encoding ceremony.

    Beyond identity, the context accumulates a bounded list of **hop
    annotations** — ``(hop name, start offset, duration, extras)``
    appended by every seam the request crosses (admission queue wait,
    lane-batcher wait/decode, endpoint window reads, executor attempts,
    OOM evict/tier-down, deadline expiry).  This is the always-on tail
    of the tracing plane: O(1) per seam, no ring buffer needed, and it
    is what ``tools/request_report.py`` renders as the waterfall.  The
    ring's full event set (annotated with ``args["trace"]``) is only
    copied out for exemplar-worthy requests.

    Thread-ambient via :func:`request_scope` / :func:`current_request`
    (the :func:`deadline_scope` pattern): the serve handler thread sets
    it once; work handed to *other* threads (the job pool, the executor
    pool) re-enters the scope explicitly — thread-locals do not follow a
    ThreadPoolExecutor submit.
    """

    __slots__ = (
        "trace_id", "span_id", "parent_id", "op", "baggage",
        "t0", "t0_wall", "hops", "hops_dropped",
    )

    def __init__(
        self,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str] = None,
        op: str = "",
        baggage: Optional[Dict[str, str]] = None,
    ) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.op = op
        self.baggage = baggage or {}
        self.t0 = time.perf_counter()
        self.t0_wall = time.time()
        self.hops: List[dict] = []
        self.hops_dropped = 0

    @classmethod
    def new(
        cls, op: str = "", baggage: Optional[Dict[str, str]] = None
    ) -> "RequestContext":
        """Originate a fresh trace (client side, or daemon side for a
        request that arrived without one)."""
        return cls(_rand_hex(16), _rand_hex(8), op=op, baggage=baggage)

    def child(self, op: str = "") -> "RequestContext":
        """A new span of the *same* trace (the sort job continuing its
        submission request on the job-pool thread)."""
        return RequestContext(
            self.trace_id,
            _rand_hex(8),
            parent_id=self.span_id,
            op=op or self.op,
            baggage=dict(self.baggage),
        )

    # -- wire format --------------------------------------------------------

    def to_wire(self) -> dict:
        """The serve protocol's ``trace`` field."""
        d: dict = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.baggage:
            d["baggage"] = dict(self.baggage)
        return d

    @classmethod
    def from_wire(cls, d, op: str = "") -> Optional["RequestContext"]:
        """Continue a trace from a request's ``trace`` field; a garbled
        field is treated as absent (a broken client must not break the
        daemon *or* silently drop its own attribution — the daemon
        originates a fresh id instead)."""
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not (
            isinstance(tid, str) and isinstance(sid, str)
            and 8 <= len(tid) <= 64 and 4 <= len(sid) <= 32
        ):
            return None
        try:
            int(tid, 16), int(sid, 16)
        except ValueError:
            return None
        bg = d.get("baggage")
        return cls(
            tid, _rand_hex(8), parent_id=sid, op=op,
            baggage=dict(bg) if isinstance(bg, dict) else None,
        )

    # -- hop annotations ----------------------------------------------------

    def annotate(
        self, hop: str, ms: Optional[float] = None, **extras
    ) -> None:
        """Record one hop on the always-on summary path (appends are
        GIL-atomic, so executor pool threads sharing a job's context
        need no lock).  ``ms`` is the hop's duration; omitted for
        point events (a deadline expiry, a tier decision)."""
        if len(self.hops) >= MAX_REQUEST_HOPS:
            self.hops_dropped += 1
            METRICS.count("serve.trace.hops_dropped", 1)
            return
        h = {
            "hop": hop,
            "t_ms": (time.perf_counter() - self.t0) * 1e3,
        }
        if ms is not None:
            h["ms"] = float(ms)
        if extras:
            h.update(extras)
        self.hops.append(h)

    def elapsed_ms(self) -> float:
        return (time.perf_counter() - self.t0) * 1e3


def current_request() -> Optional[RequestContext]:
    """The ambient request context of this thread (None in batch mode —
    the disarmed contract: a batch pipeline run records zero
    request-context events)."""
    return getattr(_TLS, "request", None)


@contextlib.contextmanager
def request_scope(ctx: Optional[RequestContext]) -> Iterator[None]:
    """Ambient request context for the current thread (None = leave
    unset).  Every tracer event emitted in scope carries the trace id;
    every seam's :meth:`RequestContext.annotate` lands on ``ctx``."""
    if ctx is None:
        yield
        return
    old = getattr(_TLS, "request", None)
    _TLS.request = ctx
    try:
        yield
    finally:
        _TLS.request = old


@contextlib.contextmanager
def span(
    name: str,
    registry: Optional[MetricsRegistry] = None,
    category: str = "span",
    args: Optional[dict] = None,
) -> Iterator[None]:
    """Timed scope, cumulative per name; also annotates the JAX profiler
    timeline when a trace is active (TraceAnnotation is ~free otherwise)
    and, with the timeline :data:`TRACER` armed, records a per-event
    ``(name, t0, t1, thread, category, args)`` ring-buffer entry.
    ``category="stage"`` marks pipeline-stage events — the unit
    ``tools/trace_report.py`` attributes stalls to."""
    reg = registry or METRICS
    ann = _annotation(name)
    t0 = time.perf_counter()
    try:
        if ann is not None:
            with ann:
                yield
        else:
            yield
    finally:
        t1 = time.perf_counter()
        reg.add_span(name, t1 - t0)
        if TRACER.armed:
            TRACER.emit(name, category, t0, t1, args)


def stage(name: str):
    """Decorator form of ``span(name, category="stage")`` — marks a whole
    function as one pipeline stage (the codec wrappers use it; the stall
    reducer groups events by these names)."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            with span(name, category="stage"):
                return fn(*a, **k)

        return wrapper

    return deco


def _annotation(name: str):
    """A jax.profiler.TraceAnnotation if jax is already imported, else None
    (never *triggers* a jax import — host-only tools stay device-free)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler API unavailable
        return None


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture an XPlane trace of the enclosed scope into ``log_dir``
    (viewable in TensorBoard/XProf).  The real replacement for the
    reference's Timer: device timelines, not host nanoseconds."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


# ---------------------------------------------------------------------------
# Run provenance: what actually executed, attached to every artifact.
# ---------------------------------------------------------------------------

#: Counter prefixes that record a device-tier decision or fallback — the
#: ``RunManifest`` collects every counter under these so "which tier ran,
#: and why not the higher one" is a recorded fact, not an inference.
TIER_DECISION_PREFIXES = (
    "flate.inflate.",
    "flate.deflate.",
    "bam.device_write_tierdown.",
    "bam.device_write_fallback",
    "bam.device_write_parts",
    "bam.device_inflate_fallback",
    "bam.device_deflate_fallback",
    "bam.write_residency_kept",
    "sort_bam.device_parse_error",
    "sort_bam.device_parse_fallback",
    "sort_bam.device_parse_residency",
    "flate.inflate_device_residency",
    "flate.oom_tierdown",
    "bam.oom_tierdown",
    "serve.oom.",
)

#: Counter prefixes that record a degraded/error mode the run survived.
FAULT_MODE_PREFIXES = (
    "salvage.", "bgzf.missing_eof", "faults.",
    "serve.admission.shed", "serve.deadline.", "serve.journal.",
    "hbm.leaked", "hbm.double_copy",
)


class RunManifest:
    """Provenance of one run: backend actually used, per-tier decision
    counters (with their reason taxonomy), fault/salvage/error mode, and
    the explicit conf deltas — the block that makes a silent fallback
    impossible to miss in a ``--metrics`` JSON or a bench round.

    ``degraded`` is True when any *fallback-class* counter fired (a tier
    that was supposed to run declined or errored) or when salvage-mode
    losses were recorded; ``reasons`` names each trigger.  A run that
    never attempted a device tier is not degraded — degradation means
    "asked for X, got Y", which callers assert by also passing
    ``requested``."""

    def __init__(
        self,
        backend: Optional[str] = None,
        platform: Optional[str] = None,
        tier_decisions: Optional[Dict[str, int]] = None,
        modes: Optional[Dict[str, object]] = None,
        conf_deltas: Optional[Dict[str, str]] = None,
        degraded: bool = False,
        reasons: Optional[List[str]] = None,
    ) -> None:
        self.backend = backend
        self.platform = platform
        self.tier_decisions = tier_decisions or {}
        self.modes = modes or {}
        self.conf_deltas = conf_deltas or {}
        self.degraded = degraded
        self.reasons = reasons or []

    def as_dict(self) -> dict:
        return {
            "backend": self.backend,
            "platform": self.platform,
            "tier_decisions": dict(self.tier_decisions),
            "modes": dict(self.modes),
            "conf_deltas": dict(self.conf_deltas),
            "degraded": self.degraded,
            "reasons": list(self.reasons),
        }


#: Fallback-class counters: their firing means a higher tier was
#: attempted and lost — the manifest flags the run degraded and says why.
_FALLBACK_REASONS = {
    "bam.device_write_fallback": "device part write errored; host gather took the part",
    "bam.device_inflate_fallback": "device inflate tier errored; native zlib took the window",
    "bam.device_deflate_fallback": "device deflate tier errored; native zlib took the part",
    "sort_bam.device_parse_error": "device parse errored on a split",
    "sort_bam.device_parse_fallback": "device parse disagreed with the host walk; host keys used",
    "serve.oom.tierdowns": "device memory exhausted; the host codec took the affected request(s)",
    "flate.oom_tierdown": "device memory exhausted during a codec launch; members tiered down",
    "bam.oom_tierdown": "device memory exhausted during a window inflate; native zlib took the window",
}


def run_manifest(
    backend: Optional[str] = None,
    conf=None,
    counters: Optional[Dict[str, int]] = None,
    requested: Optional[str] = None,
) -> RunManifest:
    """Assemble a :class:`RunManifest` from the live registry.

    ``backend`` is the pipeline's actual sort backend string
    (``SortStats.backend``); ``requested`` the one asked for — a mismatch
    is itself a degradation reason.  ``conf`` contributes its explicit
    key/values as ``conf_deltas`` (what the operator overrode);
    ``counters`` defaults to the current METRICS counters."""
    if counters is None:
        counters = METRICS.report()["counters"]
    tiers = {
        k: v
        for k, v in counters.items()
        if any(k.startswith(p) for p in TIER_DECISION_PREFIXES)
    }
    modes: Dict[str, object] = {}
    for k, v in counters.items():
        if any(k.startswith(p) for p in FAULT_MODE_PREFIXES):
            modes[k] = v
    if conf is not None:
        try:
            from ..conf import ERRORS_MODE, FAULTS_PLAN

            modes["errors"] = conf.get(ERRORS_MODE, "strict") or "strict"
            if conf.get(FAULTS_PLAN):
                modes["faults_plan"] = conf.get(FAULTS_PLAN)
        except Exception:  # pragma: no cover - conf duck types in tests
            pass
    try:
        from .. import faults

        modes["faults_armed"] = faults.ACTIVE is not None
    except Exception:  # pragma: no cover
        pass
    # The split-pipelining depth the read drive actually used (the
    # ``pipeline.read_depth`` gauge, set by DeviceStream.read_splits):
    # a round's overlap numbers carry their pipelining provenance.
    depth_g = METRICS.gauges().get("pipeline.read_depth")
    if depth_g:
        modes["read_depth"] = int(depth_g)
    # The auto-rtt gate's inputs alongside the depth that relaxed it:
    # ``effective_rtt_ms = read_depth × auto_rtt_ms`` when pipelined
    # (PR 13), so a round's tier decisions carry their gate provenance.
    for rtt_key in ("pipeline.auto_rtt_ms", "pipeline.effective_rtt_ms"):
        rtt_g = METRICS.gauges().get(rtt_key)
        if rtt_g is not None:
            modes[rtt_key.split(".", 1)[1]] = float(rtt_g)
    platform = None
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            platform = jax.default_backend()
        except Exception:  # pragma: no cover - backend init failure
            platform = None
    reasons: List[str] = []
    for k, why in _FALLBACK_REASONS.items():
        if counters.get(k):
            reasons.append(f"{why} ({k}={counters[k]})")
    leaked = counters.get("hbm.leaked_bytes", 0)
    if leaked:
        # The residency ledger's leak check fired: a device allocation
        # was never explicitly released by its holder (the PR 5 bug
        # class).  Named and degraded, never fatal.
        holders = {
            k[len("hbm.leaked."):]: v
            for k, v in counters.items()
            if k.startswith("hbm.leaked.")
        }
        top = max(holders, key=holders.get) if holders else "unknown"
        reasons.append(
            f"HBM residency leaked: {leaked} bytes never released "
            f"by their holder (top holder {top}; hbm.leaked_bytes)"
        )
    if counters.get("hbm.double_copy"):
        reasons.append(
            "HBM double-copy: the same logical payload was resident "
            f"under two holders (hbm.double_copy="
            f"{counters['hbm.double_copy']})"
        )
    if counters.get("salvage.members_quarantined") or counters.get(
        "salvage.records_dropped"
    ):
        reasons.append(
            "salvage mode quarantined data "
            f"(members={counters.get('salvage.members_quarantined', 0)}, "
            f"records={counters.get('salvage.records_dropped', 0)})"
        )
    if requested is not None and backend is not None and requested != backend:
        reasons.append(
            f"requested backend {requested!r} but ran {backend!r}"
        )
    conf_deltas = {}
    if conf is not None:
        try:
            conf_deltas = {k: conf.get(k) for k in conf}
        except Exception:  # pragma: no cover
            conf_deltas = {}
    return RunManifest(
        backend=backend,
        platform=platform,
        tier_decisions=tiers,
        modes=modes,
        conf_deltas=conf_deltas,
        degraded=bool(reasons),
        reasons=reasons,
    )


class ClusterManifest:
    """Provenance of one multi-host run: every host's :class:`RunManifest`
    plus its byte-plane accounting, folded into one cluster verdict.

    A mesh round is only as honest as its weakest host — ``degraded`` is
    True when ANY host's manifest is degraded, when the shuffle byte
    matrix fails to balance (some edge's sender-side bytes disagree with
    the receiver-side measurement — lost or duplicated shuffle data), or
    when a host that should have reported never did.  ``hosts`` keeps the
    per-host detail (tier decisions, peak_bytes, sent/recv rows) so "which
    host, and why" stays answerable from the artifact alone.  The old
    module-global ``multihost.LAST_STATS`` dict is retired into this
    (kept as a thin view for existing tests).
    """

    def __init__(
        self,
        hosts: List[dict],
        byte_plane: Optional[str] = None,
        degraded: bool = False,
        reasons: Optional[List[str]] = None,
        edges_balanced: bool = True,
        skew_ratio: Optional[float] = None,
        shuffle_bytes: int = 0,
        keys_bytes: int = 0,
        records: int = 0,
        shuffle_raw_bytes: int = 0,
        shuffle_ratio: Optional[float] = None,
        repartition: Optional[dict] = None,
        speculation: Optional[dict] = None,
    ) -> None:
        self.hosts = hosts
        self.byte_plane = byte_plane
        self.degraded = degraded
        self.reasons = reasons or []
        self.edges_balanced = edges_balanced
        self.skew_ratio = skew_ratio
        self.shuffle_bytes = shuffle_bytes
        self.keys_bytes = keys_bytes
        self.records = records
        self.shuffle_raw_bytes = shuffle_raw_bytes
        self.shuffle_ratio = shuffle_ratio
        self.repartition = repartition
        self.speculation = speculation

    def as_dict(self) -> dict:
        return {
            "num_hosts": len(self.hosts),
            "hosts": [dict(h) for h in self.hosts],
            "byte_plane": self.byte_plane,
            "edges_balanced": self.edges_balanced,
            "skew_ratio": self.skew_ratio,
            "shuffle_bytes": self.shuffle_bytes,
            "shuffle_raw_bytes": self.shuffle_raw_bytes,
            "shuffle_ratio": self.shuffle_ratio,
            "keys_bytes": self.keys_bytes,
            "records": self.records,
            "repartition": (
                dict(self.repartition) if self.repartition else None
            ),
            "speculation": (
                dict(self.speculation) if self.speculation else None
            ),
            "degraded": self.degraded,
            "reasons": list(self.reasons),
        }


def cluster_manifest(
    host_manifests: List[dict], byte_plane: Optional[str] = None
) -> ClusterManifest:
    """Fold per-host mesh manifests into a :class:`ClusterManifest`.

    Each input dict is one host's published manifest (built by
    ``parallel/multihost.py``): ``host``, ``num_processes``,
    ``run_manifest`` (a :meth:`RunManifest.as_dict`), ``peak_bytes``,
    ``records_local``, ``records_out`` (per local device),
    ``shuffle_sent_bytes`` / ``shuffle_recv_bytes`` (per peer process,
    measured independently on each side of every edge), the key-plane
    twins, ``skew_ratio`` and ``barrier_wait_ms``.  Pure function of its
    inputs so tests can drive it with synthetic host sets."""
    hosts = sorted((dict(h) for h in host_manifests), key=lambda h: h.get("host", 0))
    reasons: List[str] = []
    n_expect = max(
        [len(hosts)] + [int(h.get("num_processes", 0)) for h in hosts]
    )
    seen = {int(h.get("host", -1)) for h in hosts}
    for p in range(n_expect):
        if p not in seen:
            reasons.append(f"host {p} never published a manifest")
    for h in hosts:
        rm = h.get("run_manifest") or {}
        if rm.get("degraded"):
            why = "; ".join(rm.get("reasons", [])) or "unspecified"
            reasons.append(f"host {h.get('host')} degraded: {why}")
    # The byte matrix must balance: what host s measured writing for q
    # must equal what host q measured fetching from s, per edge.
    edges_balanced = True
    shuffle_bytes = 0
    for hs in hosts:
        s = hs.get("host")
        sent = hs.get("shuffle_sent_bytes") or {}
        for hq in hosts:
            q = hq.get("host")
            b_sent = int(sent.get(str(q), 0))
            b_recv = int((hq.get("shuffle_recv_bytes") or {}).get(str(s), 0))
            shuffle_bytes += b_sent
            if b_sent != b_recv:
                edges_balanced = False
                reasons.append(
                    f"shuffle byte matrix imbalanced on edge {s}->{q}: "
                    f"sent {b_sent} != received {b_recv}"
                )
    keys_bytes = sum(
        int(b)
        for h in hosts
        for b in (h.get("keys_sent_bytes") or {}).values()
    )
    # Compression accounting (PR 15): the sent matrix counts WIRE bytes;
    # its raw twin makes the cluster-wide shuffle ratio first-class.
    shuffle_raw_bytes = sum(
        int(b)
        for h in hosts
        for b in (h.get("shuffle_sent_raw_bytes") or {}).values()
    )
    shuffle_ratio = (
        round(shuffle_raw_bytes / shuffle_bytes, 4)
        if shuffle_bytes and shuffle_raw_bytes
        else None
    )
    records = sum(int(h.get("records_local", 0)) for h in hosts)
    skews = [h["skew_ratio"] for h in hosts if h.get("skew_ratio")]
    # Skew healing (PR 16): the repartition decision is collective (every
    # host allgathers the same census and branches identically), so any
    # non-empty block speaks for the round; speculation blocks differ per
    # host (the speculator reports launches/wins, the straggler its lost
    # parts) and fold into one event list + cluster totals.
    repartition = next(
        (dict(h["repartition"]) for h in hosts if h.get("repartition")),
        None,
    )
    spec_events: List[dict] = []
    spec_launched = spec_won = spec_wasted = 0
    for h in hosts:
        sp = h.get("speculation") or {}
        if not sp:
            continue
        spec_launched += int(sp.get("launched", 0))
        spec_won += int(sp.get("won_parts", 0))
        spec_wasted += int(sp.get("wasted_bytes", 0))
        if sp.get("launched"):
            spec_events.append({
                "by": h.get("host"),
                "target": sp.get("target"),
                "won_parts": int(sp.get("won_parts", 0)),
            })
    speculation = (
        {
            "launched": spec_launched,
            "won_parts": spec_won,
            "wasted_bytes": spec_wasted,
            "events": spec_events,
        }
        if (spec_launched or spec_wasted)
        else None
    )
    return ClusterManifest(
        hosts=hosts,
        byte_plane=byte_plane
        or (hosts[0].get("byte_plane") if hosts else None),
        degraded=bool(reasons),
        reasons=reasons,
        edges_balanced=edges_balanced,
        skew_ratio=max(skews) if skews else None,
        shuffle_bytes=shuffle_bytes,
        keys_bytes=keys_bytes,
        records=records,
        shuffle_raw_bytes=shuffle_raw_bytes,
        shuffle_ratio=shuffle_ratio,
        repartition=repartition,
        speculation=speculation,
    )


# ---------------------------------------------------------------------------
# Prometheus text exposition (the serve daemon's ``metrics`` op).
# ---------------------------------------------------------------------------


def _prom_name(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def prometheus_text(
    report: Optional[Dict[str, Dict[str, float]]] = None,
    gauges: Optional[Dict[str, float]] = None,
    prefix: str = "hbam",
) -> str:
    """Render a metrics report in Prometheus text exposition format.

    Counters become ``<prefix>_<name>_total``, span sums
    ``<prefix>_<name>_seconds_total`` (+ ``_count``), histograms the
    standard cumulative ``_bucket{le="…"}`` / ``_sum`` / ``_count``
    triplet, and ``gauges`` plain ``<prefix>_<name>`` samples.  Dots in
    metric names map to underscores.  The report's own first-class
    ``gauges`` section (``MetricsRegistry.set_gauge`` — arena occupancy,
    HBM ledger levels) is merged under the explicit ``gauges`` argument,
    so registered gauges export without each caller re-collecting them.
    """
    if report is None:
        report = METRICS.report()
    merged_gauges = dict(report.get("gauges", {}))
    if gauges:
        merged_gauges.update(gauges)
    gauges = merged_gauges
    lines: List[str] = []
    for k in sorted(report.get("counters", {})):
        n = f"{prefix}_{_prom_name(k)}_total"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {report['counters'][k]}")
    spans_s = report.get("span_seconds", {})
    spans_n = report.get("span_counts", {})
    for k in sorted(spans_s):
        n = f"{prefix}_{_prom_name(k)}_seconds_total"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {spans_s[k]:.6f}")
        n = f"{prefix}_{_prom_name(k)}_count"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {spans_n.get(k, 0)}")
    for k in sorted(report.get("histograms", {})):
        h = report["histograms"][k]
        n = f"{prefix}_{_prom_name(k)}"
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        for le, c in sorted(
            h.get("buckets", {}).items(), key=lambda kv: float(kv[0])
        ):
            cum += c
            lines.append(f'{n}_bucket{{le="{float(le):g}"}} {cum}')
        lines.append(f'{n}_bucket{{le="+Inf"}} {h.get("count", 0)}')
        lines.append(f"{n}_sum {h.get('sum', 0.0):.6f}")
        lines.append(f"{n}_count {h.get('count', 0)}")
    for k in sorted(gauges or {}):
        n = f"{prefix}_{_prom_name(k)}"
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {gauges[k]}")
    return "\n".join(lines) + "\n"


class Progress:
    """Byte-cadence progress ticks (SplittingBAMIndexer.java:277-282 prints
    one ``-`` per 500MB; here: a callback or stderr tick, plus totals).

    With the timeline :data:`TRACER` armed, the default sink routes ticks
    onto the event stream as ``progress.tick`` instants instead of
    writing bare ``-`` to stderr — a ``--trace``/``--metrics`` run keeps
    machine-readable output clean while still recording cadence."""

    DEFAULT_CADENCE = 500 << 20

    def __init__(
        self,
        total_bytes: Optional[int] = None,
        cadence: int = DEFAULT_CADENCE,
        sink=None,
    ) -> None:
        self.total = total_bytes
        self.cadence = cadence
        self.done = 0
        self._next = cadence
        self._sink = sink if sink is not None else self._default_sink
        self._lock = threading.Lock()

    @staticmethod
    def _default_sink(progress: "Progress") -> None:
        if TRACER.armed:
            TRACER.instant(
                "progress.tick",
                "progress",
                {"done": progress.done, "total": progress.total},
            )
            return
        sys.stderr.write("-")
        sys.stderr.flush()

    def advance(self, nbytes: int) -> None:
        with self._lock:
            self.done += nbytes
            fire = self.done >= self._next
            if fire:
                self._next += self.cadence * (
                    1 + (self.done - self._next) // self.cadence
                )
        if fire:
            self._sink(self)

    def fraction(self) -> float:
        """Hadoop ``getProgress()`` analog; 0.0 when the total is unknown
        (the reference's virtual-offset progress is likewise inexact)."""
        if not self.total:
            return 0.0
        return min(1.0, self.done / self.total)
