"""Tracing, counters, and progress: the observability the reference lacks.

The reference's only instrumentation is a deprecated nanosecond ``Timer``
(util/Timer.java:4-12) and a ``-`` progress tick every 500MB in its indexers
(SplittingBAMIndexer.java:144,277-282); task progress is Hadoop's
``getProgress()`` contract.  Per SURVEY.md §5 the TPU build wires real
tracing instead: wall-clock spans + named counters in a process-local
registry, an optional 500MB-cadence progress printer, and hooks into the JAX
profiler (XPlane) so device phases show up in TensorBoard traces.

Everything degrades to no-ops: spans/counters are cheap dict updates, and the
profiler hooks import ``jax`` lazily so host-only tools never touch a device
backend.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
from typing import Dict, Iterator, Optional


class MetricsRegistry:
    """Thread-safe named counters + cumulative span timings."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._spans: Dict[str, float] = {}
        self._span_counts: Dict[str, int] = {}

    def count(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def add_span(self, name: str, seconds: float) -> None:
        with self._lock:
            self._spans[name] = self._spans.get(name, 0.0) + seconds
            self._span_counts[name] = self._span_counts.get(name, 0) + 1

    def report(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "span_seconds": dict(self._spans),
                "span_counts": dict(self._span_counts),
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._spans.clear()
            self._span_counts.clear()


METRICS = MetricsRegistry()


def count_h2d(nbytes: int, what: str = "") -> None:
    """Transfer ledger, host→device direction: every deliberate upload on
    the hot paths reports its bytes here (keys, device-parse streams,
    compressed blocks, write-path offset columns…), so the round
    artifacts show the PCIe traffic instead of inferring it.  ``what``
    adds an itemized ``transfers.h2d.<what>`` counter next to the
    ``transfers.h2d_bytes`` total."""
    n = int(nbytes)
    METRICS.count("transfers.h2d_bytes", n)
    if what:
        METRICS.count(f"transfers.h2d.{what}", n)


def count_d2h(nbytes: int, what: str = "") -> None:
    """Transfer ledger, device→host direction (permutation fetches,
    inflated payloads, compressed part blobs, CRC columns…)."""
    n = int(nbytes)
    METRICS.count("transfers.d2h_bytes", n)
    if what:
        METRICS.count(f"transfers.d2h.{what}", n)


def snapshot(registry: Optional[MetricsRegistry] = None) -> Dict[str, Dict[str, float]]:
    """A point-in-time copy of the registry's counters + spans.

    Pair with :func:`delta` for per-request accounting in long-lived
    processes (the serve daemon): the process-global counters keep
    accumulating — resetting them mid-flight would corrupt every other
    in-flight request's numbers — and each request reports
    ``delta(snapshot_at_admission)`` instead."""
    return (registry or METRICS).report()


def delta(
    before: Dict[str, Dict[str, float]],
    after: Optional[Dict[str, Dict[str, float]]] = None,
    registry: Optional[MetricsRegistry] = None,
) -> Dict[str, Dict[str, float]]:
    """Per-section difference of two :func:`snapshot` reports.

    ``after`` defaults to a fresh snapshot.  Only keys whose value moved
    are kept, so a request's report shows exactly the counters/spans it
    touched.  Counters never decrease, but the diff is computed signed so
    a misuse (swapped arguments) is visible rather than silently clamped.
    """
    if after is None:
        after = snapshot(registry)
    out: Dict[str, Dict[str, float]] = {}
    for section in ("counters", "span_seconds", "span_counts"):
        b = before.get(section, {})
        a = after.get(section, {})
        d = {}
        for k in set(a) | set(b):
            v = a.get(k, 0) - b.get(k, 0)
            if v:
                d[k] = v
        out[section] = d
    return out


def transfers_report(counters: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    """The ``transfers`` block of the CLI ``--metrics`` JSON: every
    ledger counter with the ``transfers.`` prefix stripped."""
    if counters is None:
        counters = METRICS.report()["counters"]
    return {
        k[len("transfers."):]: v
        for k, v in counters.items()
        if k.startswith("transfers.")
    }


@contextlib.contextmanager
def span(name: str, registry: Optional[MetricsRegistry] = None) -> Iterator[None]:
    """Timed scope, cumulative per name; also annotates the JAX profiler
    timeline when a trace is active (TraceAnnotation is ~free otherwise)."""
    reg = registry or METRICS
    ann = _annotation(name)
    t0 = time.perf_counter()
    try:
        if ann is not None:
            with ann:
                yield
        else:
            yield
    finally:
        reg.add_span(name, time.perf_counter() - t0)


def _annotation(name: str):
    """A jax.profiler.TraceAnnotation if jax is already imported, else None
    (never *triggers* a jax import — host-only tools stay device-free)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler API unavailable
        return None


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture an XPlane trace of the enclosed scope into ``log_dir``
    (viewable in TensorBoard/XProf).  The real replacement for the
    reference's Timer: device timelines, not host nanoseconds."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


class Progress:
    """Byte-cadence progress ticks (SplittingBAMIndexer.java:277-282 prints
    one ``-`` per 500MB; here: a callback or stderr tick, plus totals)."""

    DEFAULT_CADENCE = 500 << 20

    def __init__(
        self,
        total_bytes: Optional[int] = None,
        cadence: int = DEFAULT_CADENCE,
        sink=None,
    ) -> None:
        self.total = total_bytes
        self.cadence = cadence
        self.done = 0
        self._next = cadence
        self._sink = sink if sink is not None else self._default_sink
        self._lock = threading.Lock()

    @staticmethod
    def _default_sink(progress: "Progress") -> None:
        sys.stderr.write("-")
        sys.stderr.flush()

    def advance(self, nbytes: int) -> None:
        with self._lock:
            self.done += nbytes
            fire = self.done >= self._next
            if fire:
                self._next += self.cadence * (
                    1 + (self.done - self._next) // self.cadence
                )
        if fire:
            self._sink(self)

    def fraction(self) -> float:
        """Hadoop ``getProgress()`` analog; 0.0 when the total is unknown
        (the reference's virtual-offset progress is likewise inexact)."""
        if not self.total:
            return 0.0
        return min(1.0, self.done / self.total)
