"""HBM residency ledger: every deliberate device allocation, accounted.

The transfer ledger (``tracing.count_h2d``/``count_d2h``) answers "how
many bytes crossed PCIe"; nothing so far answers "how many bytes are
*resident* in HBM right now, and who holds them".  That question is the
whole correctness/perf story of the coming ``DeviceStream`` refactor
(ROADMAP #1: double-buffering with buffer donation — "HBM never holds
two copies"), and the one residency bug we have actually shipped (PR 5:
the out-of-core spill path silently pinning every split's inflated
window in HBM) was found by eye.  This module is the instrument:

- :meth:`HbmLedger.register` — a subsystem takes ownership of a
  device-resident buffer ``(nbytes, kind, holder, logical payload id)``;
  live occupancy, per-kind breakdown and the high watermark update, a
  ``hbm.alloc`` instant + an ``hbm.live_bytes`` counter-track sample
  land on the timeline tracer (Perfetto renders an HBM track next to
  the stage timeline), and ambient ``trace_ctx`` split/part attribution
  rides along.
- :meth:`HbmLedger.release` — the holder explicitly gives the bytes
  back.  **This is the audited event**: a buffer whose weakref
  finalizer fires *without* an explicit release/transfer/donation is
  counted as ``hbm.leaked_bytes`` under ``hbm.leaked.<holder>`` — the
  bytes were freed only by the accident of refcounting, which is
  exactly how the PR 5 bug stayed invisible.
- :meth:`HbmLedger.transfer` / :meth:`HbmLedger.adopt` — ownership
  handoffs (split window → write stream, read path → serve arena,
  future buffer donation): the receiving holder takes over, donors are
  closed cleanly, and the handoff is an event, not silence.
- **Double-copy detector**: two live buffers carrying the same
  ``logical`` payload id under different holders is the PR 5 bug class
  and the regression guard for buffer donation — counted
  (``hbm.double_copy``), traced, and surfaced as a degradation reason
  in the run manifest.
- :meth:`HbmLedger.assert_drained` — the end-of-run leak check: still
  -held entries are force-closed as leaks (holder named, bytes
  counted) and the run manifest flags the run degraded instead of the
  check crashing anything.

The ledger never imports jax: it tracks *any* object with an ``nbytes``
(numpy arrays in tests, jax arrays in production), so host-only tools
and ``JAX_PLATFORMS=cpu`` CI exercise the same accounting the chip
path runs.  All metrics flow through :mod:`utils.tracing` (METRICS
counters + first-class gauges + the timeline tracer) so the round
artifacts stay single-source.
"""

from __future__ import annotations

import re
import threading
import weakref
from typing import Dict, Iterable, List, Optional

from .tracing import METRICS, TRACER

#: Metric-name-safe holder slug (the ``hbm.leaked.<holder>`` counters).
_SAFE = re.compile(r"[^a-z0-9_.]+")


def _safe(name: str) -> str:
    return _SAFE.sub("_", str(name).lower()).strip("._") or "unknown"


class HbmLedger:
    """Thread-safe registry of live device-resident allocations."""

    def __init__(self, name: str = "hbm") -> None:
        self.name = name
        # RLock: weakref finalizers run at arbitrary allocation points
        # (cyclic GC), potentially while this thread already holds the
        # ledger lock — re-entry must not deadlock.
        self._lock = threading.RLock()
        self._seq = 0
        self._entries: Dict[int, dict] = {}  # eid -> entry
        self._by_obj: Dict[int, int] = {}  # id(obj) -> eid
        self._kind_bytes: Dict[str, int] = {}
        self.live_bytes = 0
        self.peak_bytes = 0
        #: Logical payload ids currently (or ever) seen double-resident.
        self.double_copy_logicals: List[str] = []

    # -- internal -----------------------------------------------------------

    def _emit(self, event: str, entry: dict, **extra) -> None:
        """One ledger event onto the timeline: an ``hbm.<event>`` instant
        with full attribution plus a counter-track sample of the live
        occupancy (total + per kind) so Perfetto draws the HBM track."""
        with self._lock:
            live = self.live_bytes
            peak = self.peak_bytes
            kinds = dict(self._kind_bytes)
        METRICS.set_gauge("hbm.live_bytes", live)
        METRICS.set_gauge("hbm.peak_bytes", peak)
        if not TRACER.armed:
            return
        TRACER.instant(
            f"hbm.{event}",
            "hbm",
            {
                "id": entry["eid"],
                "bytes": entry["nbytes"],
                "kind": entry["kind"],
                "holder": entry["holder"],
                "logical": entry["logical"],
                **extra,
            },
        )
        TRACER.counter("hbm.live_bytes", {"total": live, **kinds})

    def _close(self, eid: int, entry: dict, obj_id: Optional[int]) -> None:
        """Drop a live entry from the occupancy accounting (lock held)."""
        self._entries.pop(eid, None)
        if obj_id is not None and self._by_obj.get(obj_id) == eid:
            del self._by_obj[obj_id]
        self.live_bytes -= entry["nbytes"]
        k = entry["kind"]
        self._kind_bytes[k] = self._kind_bytes.get(k, 0) - entry["nbytes"]
        if self._kind_bytes[k] <= 0:
            del self._kind_bytes[k]

    def _finalized(self, eid: int) -> None:
        """Weakref callback: the buffer died.  An explicit release got
        here first on the clean path; otherwise the holder never gave
        the bytes back and refcounting saved them — a leak, by name.
        (An abandoned buffer on an exception path counts too: errors
        don't get to hide residency either.)"""
        try:
            with self._lock:
                entry = self._entries.get(eid)
                if entry is None:
                    return
                self._close(eid, entry, entry.get("obj_id"))
            self._leak_account(entry)
        except Exception:  # pragma: no cover - interpreter shutdown
            pass

    def _leak_account(self, entry: dict) -> None:
        METRICS.count("hbm.leaked_bytes", entry["nbytes"])
        METRICS.count(f"hbm.leaked.{_safe(entry['holder'])}", entry["nbytes"])
        self._emit("leak", entry)

    # -- the ownership API --------------------------------------------------

    def register(
        self,
        obj,
        kind: str,
        holder: str,
        nbytes: Optional[int] = None,
        logical: Optional[str] = None,
    ):
        """Take ownership of a device-resident buffer.  Returns ``obj``
        (chainable at the attach site).  ``logical`` identifies the
        payload *content* — two live registrations of the same logical
        id under different holders is a double copy."""
        if obj is None:
            return None
        nb = int(nbytes if nbytes is not None else getattr(obj, "nbytes", 0))
        with self._lock:
            self._seq += 1
            eid = self._seq
            if logical is None:
                logical = f"payload_{eid}"
            dup_holders = sorted(
                {
                    e["holder"]
                    for e in list(self._entries.values())
                    if e["logical"] == logical and e["holder"] != holder
                }
            )
            entry = {
                "eid": eid,
                "nbytes": nb,
                "kind": kind,
                "holder": holder,
                "logical": logical,
                "obj_id": id(obj),
            }
            self._entries[eid] = entry
            self._by_obj[id(obj)] = eid
            self.live_bytes += nb
            self.peak_bytes = max(self.peak_bytes, self.live_bytes)
            self._kind_bytes[kind] = self._kind_bytes.get(kind, 0) + nb
            if dup_holders:
                self.double_copy_logicals.append(logical)
        try:
            entry["wr"] = weakref.ref(
                obj, lambda _wr, eid=eid: self._finalized(eid)
            )
        except TypeError:  # no weakref support: explicit lifecycle only
            entry["wr"] = None
        METRICS.count("hbm.allocs", 1)
        METRICS.count("hbm.alloc_bytes", nb)
        self._emit("alloc", entry)
        if dup_holders:
            METRICS.count("hbm.double_copy", 1)
            self._emit(
                "double_copy", entry, other_holders=",".join(dup_holders)
            )
        return obj

    def release(self, obj) -> bool:
        """The holder explicitly gives the bytes back (idempotent: an
        untracked or already-closed buffer is a silent no-op, so release
        sites may run after an ownership handoff)."""
        if obj is None:
            return False
        with self._lock:
            eid = self._by_obj.get(id(obj))
            entry = self._entries.get(eid) if eid is not None else None
            if entry is None:
                return False
            self._close(eid, entry, id(obj))
        METRICS.count("hbm.frees", 1)
        METRICS.count("hbm.free_bytes", entry["nbytes"])
        self._emit("free", entry)
        return True

    def transfer(self, obj, holder: str, kind: Optional[str] = None):
        """Ownership handoff: the buffer stays resident, the named
        ``holder`` (and optionally ``kind``) takes over — the split
        window becoming the write stream, the read path handing a decoded
        window to the serve arena, a donated buffer changing stages.
        An untracked buffer is adopted fresh (accounting completeness
        beats provenance pedantry).  Returns ``obj``."""
        if obj is None:
            return None
        with self._lock:
            eid = self._by_obj.get(id(obj))
            entry = self._entries.get(eid) if eid is not None else None
            if entry is not None:
                old = entry["holder"]
                entry["holder"] = holder
                if kind is not None and kind != entry["kind"]:
                    nb = entry["nbytes"]
                    ok = entry["kind"]
                    self._kind_bytes[ok] = self._kind_bytes.get(ok, 0) - nb
                    if self._kind_bytes[ok] <= 0:
                        del self._kind_bytes[ok]
                    self._kind_bytes[kind] = (
                        self._kind_bytes.get(kind, 0) + nb
                    )
                    entry["kind"] = kind
        if entry is None:
            return self.register(obj, kind or "split_window", holder)
        METRICS.count("hbm.transfers", 1)
        self._emit("transfer", entry, from_holder=old)
        return obj

    def adopt(
        self,
        obj,
        kind: str,
        holder: str,
        donors: Iterable = (),
        nbytes: Optional[int] = None,
        logical: Optional[str] = None,
    ):
        """Register ``obj`` as the successor of ``donors`` (the
        device-to-device concat of per-split windows into one write
        stream, a donation chain): donors close cleanly — their later
        finalize is not a leak — and the new buffer carries the
        accounting forward.  Returns ``obj``."""
        for d in donors:
            if d is None or d is obj:
                continue
            self.release(d)
        return self.register(
            obj, kind, holder, nbytes=nbytes, logical=logical
        )

    # -- introspection / checks ---------------------------------------------

    def logical_of(self, obj) -> Optional[str]:
        with self._lock:
            eid = self._by_obj.get(id(obj))
            entry = self._entries.get(eid) if eid is not None else None
            return entry["logical"] if entry is not None else None

    def live_by_holder(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for e in list(self._entries.values()):
                out[e["holder"]] = out.get(e["holder"], 0) + e["nbytes"]
            return out

    def live_by_kind(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._kind_bytes)

    def reset_peak(self) -> int:
        """Start a fresh high-watermark epoch (bench rounds measure the
        per-run peak as a delta from here).  Returns the new peak (=
        current live bytes)."""
        with self._lock:
            self.peak_bytes = self.live_bytes
            return self.peak_bytes

    def gauges(self) -> Dict[str, float]:
        """Live occupancy levels, per kind — the flight recorder's and
        the serve ``metrics`` op's HBM block."""
        with self._lock:
            g = {
                "hbm.live_bytes": float(self.live_bytes),
                "hbm.peak_bytes": float(self.peak_bytes),
                "hbm.live_entries": float(len(self._entries)),
            }
            for k, v in list(self._kind_bytes.items()):
                g[f"hbm.live.{_safe(k)}"] = float(v)
            return g

    def assert_drained(
        self, ignore_holders: Iterable[str] = ("serve.arena",)
    ) -> dict:
        """The end-of-run leak check.  Entries still held (outside
        ``ignore_holders`` — the serve arena keeps residency across
        requests *by design*) are force-closed as leaks: counted under
        ``hbm.leaked_bytes`` / ``hbm.leaked.<holder>``, emitted as
        ``hbm.leak`` trace instants, and picked up by the run manifest
        as a degradation reason.  Returns the verdict; never raises —
        a leak degrades the run, it does not crash it."""
        ignore = set(ignore_holders or ())
        with self._lock:
            leaked = [
                e
                for e in list(self._entries.values())
                if e["holder"] not in ignore
            ]
            for e in leaked:
                self._close(e["eid"], e, e.get("obj_id"))
        holders: Dict[str, int] = {}
        for e in leaked:
            holders[e["holder"]] = holders.get(e["holder"], 0) + e["nbytes"]
            self._leak_account(e)
        return {
            "leaked_bytes": sum(holders.values()),
            "leaked_entries": len(leaked),
            "holders": holders,
        }

    def _reset_for_tests(self) -> None:
        """Silently drop all state (no leak accounting): test isolation
        only — drills must not bleed live entries into later tests."""
        with self._lock:
            # Dangling weakref callbacks no-op on the now-missing eids.
            self._entries.clear()
            self._by_obj.clear()
            self._kind_bytes.clear()
            self.live_bytes = 0
            self.peak_bytes = 0
            self.double_copy_logicals = []
        METRICS.set_gauge("hbm.live_bytes", 0)
        METRICS.set_gauge("hbm.peak_bytes", 0)


#: The process-global residency ledger (single-source, like METRICS).
LEDGER = HbmLedger()
