"""Part-file conventions: globbing, _SUCCESS markers, concat helpers.

Mirrors the reference's util/NIOFileUtil.java: the ``part-[mr]-NNNNN`` output
glob (:24), sorted part listing (:70-92), and delete-recursive helpers, plus
the `_SUCCESS` completeness check used by the mergers
(util/SAMFileMerger.java:50-54).  The part file is also the restart unit for
elastic re-execution (SURVEY.md §5 checkpoint notes).
"""

from __future__ import annotations

import os
import re
import shutil
from pathlib import Path
from typing import List, Union

PathLike = Union[str, os.PathLike]

PARTS_GLOB = "part-[mr]-*"  # reference util/NIOFileUtil.java:24
_PART_RE = re.compile(r"^part-[mr]-\d{5}.*$")
SUCCESS_MARKER = "_SUCCESS"


def as_path(p: PathLike) -> Path:
    return Path(p)


def list_parts(
    directory: PathLike, excludes_ext: str = ".splitting-bai"
) -> List[Path]:
    """Sorted list of part files, excluding companion index files
    (reference NIOFileUtil.getFilesMatching's excludesExt,
    util/NIOFileUtil.java:88-93)."""
    d = as_path(directory)
    return sorted(
        x
        for x in d.iterdir()
        if _PART_RE.match(x.name)
        and not (excludes_ext and x.name.endswith(excludes_ext))
    )


def check_success(directory: PathLike) -> None:
    """Raise if the job did not complete (no _SUCCESS marker) —
    reference util/SAMFileMerger.java:50-54 semantics."""
    d = as_path(directory)
    if not (d / SUCCESS_MARKER).exists():
        raise FileNotFoundError(
            f"no {SUCCESS_MARKER} marker in {d}: job output incomplete"
        )


def write_success(directory: PathLike) -> None:
    (as_path(directory) / SUCCESS_MARKER).touch()


def delete_recursive(directory: PathLike) -> None:
    shutil.rmtree(as_path(directory), ignore_errors=True)


def concat_files(sources: List[PathLike], out_stream) -> int:
    """Append each source file's bytes to an open binary stream; returns total
    bytes copied (merge data plane, util/NIOFileUtil.java:94-106 equivalent)."""
    total = 0
    for src in sources:
        with open(src, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                out_stream.write(chunk)
                total += len(chunk)
    return total
