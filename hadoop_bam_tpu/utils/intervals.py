"""Genomic interval parsing: the `chr:start-stop[,...]` property format.

Reference semantics: util/IntervalUtil.java:27-53 — a comma-separated list of
``contig:start-stop`` (1-based, inclusive) intervals stored in a single
configuration property (e.g. ``hadoopbam.bam.intervals``,
BAMInputFormat.java:89-111).  The last ``:`` splits contig from the range so
contig names may themselves contain ``:``.

On top of the reference grammar, :func:`parse_interval` accepts the two
samtools-style shorthands the ``view`` endpoint needs: a bare ``contig``
(no colon at all) means the whole contig (``1-MAX_END``), and
``contig:pos`` (numeric, no dash) means the single position ``pos-pos``.
A contig name that itself contains ``:`` still requires the explicit
``contig:start-stop`` form — the shorthand never guesses where such a
name ends (the same ambiguity samtools resolves with ``{...}`` quoting).

Bounds accept samtools-style thousands separators (``1:1,000,000-2,000,000``)
— strictly grouped (1–3 leading digits then exactly-3-digit groups), so a
stray or misplaced comma is still a :class:`FormatError`, never a silent
partial parse.  Note the *property* grammar (:func:`parse_intervals`)
splits the list on ``,`` first, so separators there would tear the list —
the shorthand belongs to single-interval surfaces (CLI regions, serve
requests), matching where samtools itself accepts it.
"""

from __future__ import annotations

import re

from dataclasses import dataclass
from typing import List, Optional

#: Strict samtools grouping: ``1,234,567`` yes; ``12,34`` / ``,123`` /
#: ``1,,2`` no.  A plain ungrouped integer is handled by int() directly.
_GROUPED_INT = re.compile(r"\d{1,3}(?:,\d{3})+$")

#: Largest representable 1-based position: the BAI binning scheme (SAM spec
#: §5.3) addresses coordinates below 2^29, so a whole-contig shorthand ends
#: here — callers with a header in hand may clamp tighter.
MAX_END = (1 << 29) - 1


class FormatError(ValueError):
    """Reference FormatException.java equivalent."""


@dataclass(frozen=True, order=True)
class Interval:
    contig: str
    start: int  # 1-based inclusive
    end: int  # 1-based inclusive

    def __str__(self) -> str:
        return f"{self.contig}:{self.start}-{self.end}"

    def overlaps(self, contig: str, start: int, end: int) -> bool:
        return contig == self.contig and start <= self.end and end >= self.start


def _parse_bound(text: str) -> int:
    """One 1-based bound: a plain integer, or a strictly-grouped
    thousands-separated one.  Raises ValueError on anything else (the
    caller wraps it in FormatError with the full interval text)."""
    if "," in text:
        if not _GROUPED_INT.fullmatch(text):
            raise ValueError(f"bad thousands grouping {text!r}")
        return int(text.replace(",", ""))
    return int(text)


def parse_interval(text: str) -> Interval:
    colon = text.rfind(":")
    if colon < 0:
        # Bare-contig shorthand: the whole contig.
        if not text:
            raise FormatError("empty interval")
        return Interval(text, 1, MAX_END)
    if colon == 0 or colon == len(text) - 1:
        raise FormatError(f"no contig:start-stop in interval '{text}'")
    contig = text[:colon]
    rng = text[colon + 1 :]
    dash = rng.find("-")
    if dash < 0:
        # Single-position shorthand: contig:pos.  Only a clean integer
        # qualifies — anything else is malformed, not a contig name (a
        # name containing ':' must use the explicit range form).
        try:
            pos = _parse_bound(rng)
        except ValueError as e:
            raise FormatError(
                f"non-integer position in interval '{text}'"
            ) from e
        if pos < 1:
            raise FormatError(f"invalid position in interval '{text}'")
        return Interval(contig, pos, pos)
    if dash == 0 or dash == len(rng) - 1:
        raise FormatError(f"no start-stop in interval '{text}'")
    try:
        start = _parse_bound(rng[:dash])
        end = _parse_bound(rng[dash + 1 :])
    except ValueError as e:
        raise FormatError(f"non-integer bound in interval '{text}'") from e
    if start < 1 or end < start:
        raise FormatError(f"invalid range in interval '{text}'")
    return Interval(contig, start, end)


def parse_intervals(prop: Optional[str]) -> Optional[List[Interval]]:
    """Parse the comma-separated property value; None/empty → None."""
    if not prop:
        return None
    return [parse_interval(part) for part in prop.split(",")]
