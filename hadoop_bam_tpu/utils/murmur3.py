"""First-64-bits-of-MurmurHash3_x64_128, with the reference's exact semantics.

The reference (util/MurmurHash3.java:32-171) implements MurmurHash3_x64_128 and
returns ``h1`` only.  It also deviates from canonical murmur3 in one line of
the mixing loop (``h2 = h2 << 31 | h1 >>> 33`` — the right-shift reads *h1*
where canonical murmur reads *h2*).  Because these hashes become sort keys for
unmapped reads (BAMRecordReader.java:97-110) and unknown VCF contigs
(VCFRecordReader.java:200-204), we reproduce the reference's bit-for-bit
behavior, quirk included, so record orderings match across frameworks.

Two variants, as in the reference:
- ``murmurhash3_bytes``: hashes raw bytes (used for undecoded BAM records);
- ``murmurhash3_chars``: hashes UTF-16 code units of a string directly
  (NOT equivalent to hashing the UTF-8 bytes; MurmurHash3.java:105-108).

Both return a Java-``long``-style signed 64-bit int.

``murmurhash3_int32_batch`` is the vectorized form over ragged slices of
one byte buffer (numpy uint64 lanes, one mixing round per 16-byte block
index across every row at once) — bit-exact with the scalar functions,
used by the pipeline to hash all unmapped records of a split in one pass
instead of a per-record Python loop.
"""

from __future__ import annotations

import numpy as np

_M = (1 << 64) - 1
_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AD432745937F


def _signed64(x: int) -> int:
    x &= _M
    return x - (1 << 64) if x >= 1 << 63 else x


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M


def _fmix(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _M
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _M
    k ^= k >> 33
    return k


def _mix(h1: int, h2: int, k1: int, k2: int) -> tuple[int, int]:
    k1 = (k1 * _C1) & _M
    k1 = _rotl(k1, 31)
    k1 = (k1 * _C2) & _M
    h1 ^= k1
    h1 = _rotl(h1, 27)
    h1 = (h1 + h2) & _M
    h1 = (h1 * 5 + 0x52DCE729) & _M
    k2 = (k2 * _C2) & _M
    k2 = _rotl(k2, 33)
    k2 = (k2 * _C1) & _M
    h2 ^= k2
    # Reference quirk: the right-shift operand is h1, not h2
    # (MurmurHash3.java:60 / :146).  Kept for key parity.
    h2 = ((h2 << 31) | (h1 >> 33)) & _M
    h2 = (h2 + h1) & _M
    h2 = (h2 * 5 + 0x38495AB5) & _M
    return h1, h2


def _finish(h1: int, h2: int, length: int) -> int:
    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & _M
    h2 = (h2 + h1) & _M
    h1 = _fmix(h1)
    h2 = _fmix(h2)
    h1 = (h1 + h2) & _M
    return _signed64(h1)


def murmurhash3_bytes(key: bytes, seed: int = 0) -> int:
    """Hash raw bytes (reference MurmurHash3.java:32-103)."""
    seed &= _M
    h1 = h2 = seed
    length = len(key)
    nblocks = length // 16
    for i in range(nblocks):
        off = i * 16
        k1 = int.from_bytes(key[off : off + 8], "little")
        k2 = int.from_bytes(key[off + 8 : off + 16], "little")
        h1, h2 = _mix(h1, h2, k1, k2)

    tail = key[nblocks * 16 :]
    k1 = k2 = 0
    n = length & 15
    if n > 8:
        k2 = int.from_bytes(tail[8:n], "little")
        k2 = (k2 * _C2) & _M
        k2 = _rotl(k2, 33)
        k2 = (k2 * _C1) & _M
        h2 ^= k2
    if n > 0:
        k1 = int.from_bytes(tail[: min(n, 8)], "little")
        k1 = (k1 * _C1) & _M
        k1 = _rotl(k1, 31)
        k1 = (k1 * _C2) & _M
        h1 ^= k1
    return _finish(h1, h2, length)


def murmurhash3_int32(key: bytes, seed: int = 0) -> int:
    """Low 32 bits of the hash as a *signed* int32 — the unmapped-read key
    truncation of BAMRecordReader.java:85-86 (Java's implicit (int) cast).
    The single definition of the sign rule shared by every key builder."""
    v = murmurhash3_bytes(key, seed) & 0xFFFFFFFF
    return v - (1 << 32) if v >= 1 << 31 else v


_C1_U = np.uint64(_C1)
_C2_U = np.uint64(_C2)


def _rotl_vec(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _fmix_vec(k: np.ndarray) -> np.ndarray:
    k = k ^ (k >> np.uint64(33))
    k = k * np.uint64(0xFF51AFD7ED558CCD)
    k = k ^ (k >> np.uint64(33))
    k = k * np.uint64(0xC4CEB9FE1A85EC53)
    return k ^ (k >> np.uint64(33))


def _mix_vec(h1, h2, k1, k2):
    k1 = _rotl_vec(k1 * _C1_U, 31) * _C2_U
    h1 = h1 ^ k1
    h1 = _rotl_vec(h1, 27) + h2
    h1 = h1 * np.uint64(5) + np.uint64(0x52DCE729)
    k2 = _rotl_vec(k2 * _C2_U, 33) * _C1_U
    h2 = h2 ^ k2
    # Reference quirk preserved: the right-shift operand is h1, not h2.
    h2 = ((h2 << np.uint64(31)) | (h1 >> np.uint64(33))) + h1
    h2 = h2 * np.uint64(5) + np.uint64(0x38495AB5)
    return h1, h2


def murmurhash3_int32_batch(
    data: np.ndarray, offs: np.ndarray, lens: np.ndarray, seed: int = 0
) -> np.ndarray:
    """Vectorized :func:`murmurhash3_int32` over ragged buffer slices.

    Hashes ``data[offs[i] : offs[i] + lens[i]]`` for every row in one
    numpy pass (uint64 wrap-around arithmetic; one ``_mix`` round per
    16-byte block index, rows masked once past their own length).
    Bit-exact with the scalar path, including the reference's h1/h2 mixing
    quirk and Java's implicit ``(int)`` truncation of the result.
    """
    offs = np.asarray(offs, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    n = len(offs)
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    maxlen = int(lens.max()) if n else 0
    # Pad to whole 16-byte blocks plus one spare block so a row whose
    # length is an exact multiple still has an (all-zero) tail window.
    W = ((max(maxlen, 0) + 15) // 16) * 16 + 16
    col = np.arange(W, dtype=np.int64)[None, :]
    idx = offs[:, None] + col
    valid = col < lens[:, None]
    m = np.where(
        valid, np.asarray(data)[np.clip(idx, 0, len(data) - 1)], 0
    ).astype(np.uint8)
    # Little-endian 8-byte words per row (explicit assembly: endianness-
    # independent, unlike a raw .view).
    shifts = (np.uint64(8) * np.arange(8, dtype=np.uint64))[None, None, :]
    w64 = (m.reshape(n, W // 8, 8).astype(np.uint64) << shifts).sum(
        axis=2, dtype=np.uint64
    )
    nblocks = (lens // 16).astype(np.int64)
    h1 = np.full(n, np.uint64(seed & _M))
    h2 = np.full(n, np.uint64(seed & _M))
    for i in range(int(nblocks.max()) if n else 0):
        act = i < nblocks
        nh1, nh2 = _mix_vec(h1, h2, w64[:, 2 * i], w64[:, 2 * i + 1])
        h1 = np.where(act, nh1, h1)
        h2 = np.where(act, nh2, h2)
    # Tail (last <16 bytes): the padded matrix is zero past each row's
    # length, so the tail words need no per-byte masking.
    toff = (nblocks * 2).astype(np.int64)
    tk1 = np.take_along_axis(w64, toff[:, None], axis=1)[:, 0]
    tk2 = np.take_along_axis(w64, toff[:, None] + 1, axis=1)[:, 0]
    tn = lens & 15
    k2v = _rotl_vec(tk2 * _C2_U, 33) * _C1_U
    h2 = np.where(tn > 8, h2 ^ k2v, h2)
    # Rows with 0 < tn <= 8 must hash only tn bytes into k1; w64 already
    # zero-pads, so tk1 is exactly int.from_bytes(tail[:min(tn,8)], "le").
    k1v = _rotl_vec(tk1 * _C1_U, 31) * _C2_U
    h1 = np.where(tn > 0, h1 ^ k1v, h1)
    ulen = lens.astype(np.uint64)
    h1 = h1 ^ ulen
    h2 = h2 ^ ulen
    h1 = h1 + h2
    h2 = h2 + h1
    h1 = _fmix_vec(h1)
    h2 = _fmix_vec(h2)
    h1 = h1 + h2
    return (h1 & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)


def murmurhash3_chars(chars: str, seed: int = 0) -> int:
    """Hash UTF-16 code units directly (reference MurmurHash3.java:105-171).

    Astral characters become surrogate pairs, exactly as Java's char-indexed
    loop sees them."""
    enc = chars.encode("utf-16-le", "surrogatepass")
    units = [int.from_bytes(enc[i : i + 2], "little") for i in range(0, len(enc), 2)]
    seed &= _M
    h1 = h2 = seed
    length = len(units)
    nblocks = length // 8
    for i in range(nblocks):
        i0 = i * 8
        k1 = (
            units[i0]
            | units[i0 + 1] << 16
            | units[i0 + 2] << 32
            | units[i0 + 3] << 48
        )
        k2 = (
            units[i0 + 4]
            | units[i0 + 5] << 16
            | units[i0 + 6] << 32
            | units[i0 + 7] << 48
        )
        h1, h2 = _mix(h1, h2, k1, k2)

    tail = units[nblocks * 8 :]
    k1 = k2 = 0
    n = length & 7
    if n > 4:
        for j in range(4, n):
            k2 |= tail[j] << (16 * (j - 4))
        k2 = (k2 * _C2) & _M
        k2 = _rotl(k2, 33)
        k2 = (k2 * _C1) & _M
        h2 ^= k2
    if n > 0:
        for j in range(min(n, 4)):
            k1 |= tail[j] << (16 * j)
        k1 = (k1 * _C1) & _M
        k1 = _rotl(k1, 31)
        k1 = (k1 * _C2) & _M
        h1 ^= k1
    return _finish(h1, h2, length)
