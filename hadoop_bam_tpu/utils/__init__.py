from .murmur3 import murmurhash3_bytes, murmurhash3_chars  # noqa: F401
from .intervals import Interval, parse_intervals  # noqa: F401
