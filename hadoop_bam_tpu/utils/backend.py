"""JAX backend guards: never hang, never wedge on a broken TPU plugin.

The container environment registers a TPU PJRT plugin ("axon") at interpreter
start; when the chip or tunnel is wedged, even ``jax.devices()`` blocks
forever, and the ``JAX_PLATFORMS=cpu`` *environment variable* alone does not
stop the plugin from initializing.  The only reliable in-process switch is
``jax.config.update("jax_platforms", "cpu")`` executed before the first
backend touch.  These helpers centralize that dance for ``bench.py``,
``__graft_entry__.dryrun_multichip`` and the TPU e2e test:

- :func:`backend_initialized` — has this process already created backends?
- :func:`force_cpu` — point an *uninitialized* process at the virtual CPU
  platform with ``n`` host devices.
- :func:`probe_platform` — discover the default platform in a *subprocess*
  under a wall-clock watchdog, so a wedged plugin costs a timeout, not a hang.

Reference role: the Hadoop runtime owns executor liveness for Hadoop-BAM
(task retry; SURVEY §5 "failure detection"); here the framework must defend
its own entry points.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Tuple

_DEVICE_RTT_MS: Optional[float] = None


def device_roundtrip_ms() -> float:
    """Median small-transfer host↔device round trip (cached per process).

    Local PCIe/ICI chips answer in well under a millisecond; a tunneled
    remote chip (the dev topology here) costs tens of milliseconds per
    RPC, which changes which codec/parse tiers win — both the
    device-resident parse (pipeline._default_device_parse) and the
    lockstep-lane inflate tier (ops.flate.lanes_tier_enabled) gate on it.
    """
    global _DEVICE_RTT_MS
    if _DEVICE_RTT_MS is None:
        import time

        import jax
        import numpy as np

        x = np.zeros(256, np.int32)
        ts = []
        try:
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(jax.device_put(x))
                ts.append(time.perf_counter() - t0)
            _DEVICE_RTT_MS = sorted(ts)[1] * 1e3
        except Exception:
            _DEVICE_RTT_MS = float("inf")
    return _DEVICE_RTT_MS


def local_tpu_ready(max_rtt_ms: float = 5.0) -> bool:
    """Shared auto rule for the device codec tiers: a real TPU whose
    host↔device round trip is local-class.

    Both lockstep-lane tiers (``ops.flate.lanes_tier_enabled`` for inflate,
    ``ops.flate.deflate_lanes_tier_enabled`` for the part-write encoder)
    and the device-resident parse gate on this same measurement, so one
    probe decides the whole device pipeline.  Never *initializes* the
    backend (a wedged TPU plugin can hang on first touch): it fires only
    in processes where the device pipeline already brought JAX up.
    """
    try:
        if not backend_initialized():
            return False
        import jax

        if jax.devices()[0].platform != "tpu":
            return False
        return device_roundtrip_ms() < max_rtt_ms
    except Exception:
        return False


def donation_supported() -> bool:
    """Does the current backend honor ``jax.jit(..., donate_argnums=…)``?

    Buffer donation is the DeviceStream's single-copy guarantee at the
    stage seams (inflate→parse slice+pad, split-windows→write-stream
    concat, gathered-stream→CRC): the donor's HBM is reusable by the
    consumer's output, so the seam never holds two copies of a split.
    The CPU backend ignores donation (with a warning per compile), so
    the seams skip requesting it there — interpret-mode CI exercises the
    same code path minus the aliasing, and the ledger's adopt/transfer
    bookkeeping is identical either way.
    """
    try:
        if not backend_initialized():
            return False
        import jax

        return jax.default_backend() in ("tpu", "gpu")
    except Exception:
        return False


def backend_initialized() -> bool:
    """True if this process has already initialized any JAX backend.

    Uses internal API with a conservative fallback: if we cannot tell,
    assume initialized (callers then fall back to a fresh subprocess, which
    is always safe).
    """
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized"):
            return bool(xla_bridge.backends_are_initialized())
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return True


def is_resource_exhausted(e: BaseException) -> bool:
    """Is this exception a device out-of-memory (``RESOURCE_EXHAUSTED``)?

    Matches the real thing — ``XlaRuntimeError``/``jaxlib`` errors whose
    message carries the XLA status name — and the fault harness's
    :class:`~hadoop_bam_tpu.faults.InjectedResourceExhausted` stand-in
    (which embeds the same token), so the serve layer's evict-retry-
    tierdown recovery is driven identically by injection and reality.
    ``MemoryError`` counts too: on the CPU/interpret tiers, host
    allocation failure is the same condition.
    """
    if isinstance(e, MemoryError):
        return True
    return "RESOURCE_EXHAUSTED" in f"{type(e).__name__}: {e}"


def _merge_host_device_flag(flags: str, n_devices: int) -> str:
    """Return XLA_FLAGS with ``--xla_force_host_platform_device_count`` set
    to at least ``n_devices`` (replacing a smaller existing value)."""
    key = "--xla_force_host_platform_device_count"
    parts = [p for p in flags.split() if p]
    out = []
    current = 0
    for p in parts:
        if p.startswith(key + "="):
            try:
                current = int(p.split("=", 1)[1])
            except ValueError:
                current = 0
        else:
            out.append(p)
    out.append(f"{key}={max(current, n_devices)}")
    return " ".join(out)


def force_cpu(n_devices: Optional[int] = None) -> None:
    """Point this (not-yet-initialized) process at the CPU platform.

    Must run before the first backend touch; raises if the backend is
    already up on a different platform.
    """
    if n_devices is not None:
        os.environ["XLA_FLAGS"] = _merge_host_device_flag(
            os.environ.get("XLA_FLAGS", ""), n_devices
        )
    os.environ["JAX_PLATFORMS"] = "cpu"  # belt: helps fresh subprocesses
    import jax

    if backend_initialized():
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                "JAX backend already initialized on "
                f"{jax.default_backend()!r}; cannot force CPU in-process"
            )
        return
    jax.config.update("jax_platforms", "cpu")


def _stderr_tail(stderr, n: int = 5) -> str:
    """Last ``n`` non-empty stderr lines, joined — the diagnosable part of
    a failed/wedged probe subprocess."""
    if isinstance(stderr, bytes):
        stderr = stderr.decode("utf-8", "replace")
    lines = [ln for ln in (stderr or "").strip().splitlines() if ln.strip()]
    return " | ".join(lines[-n:])


def probe_platform_ex(
    timeout_s: float = 300.0, retries: int = 1
) -> Tuple[Optional[str], Optional[str]]:
    """Default-platform discovery with failure diagnostics.

    Like :func:`probe_platform`, but returns ``(platform, error)``:
    ``platform`` is ``jax.devices()[0].platform`` under the *ambient*
    configuration (or ``None``), and ``error`` carries the probe
    subprocess's stderr tail so a fallback is diagnosable instead of a
    bare timeout string (BENCH r4/r5 showed two consecutive opaque CPU
    fallbacks).  A failed or timed-out probe is retried up to ``retries``
    times, each in a *fresh* subprocess — a transiently wedged plugin or
    tunnel gets one more chance before the caller tiers down.
    """
    code = (
        "import jax\n"
        "d = jax.devices()\n"
        "print('PLATFORM=' + d[0].platform)\n"
    )
    env = dict(os.environ)
    # Probe the *default* stack: drop any CPU forcing we may have added.
    env.pop("JAX_PLATFORMS", None)
    last_err: Optional[str] = None
    for attempt in range(retries + 1):
        try:
            res = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout_s,
                env=env,
            )
        except subprocess.TimeoutExpired as e:
            tail = _stderr_tail(e.stderr)
            last_err = (
                f"probe attempt {attempt + 1} timed out after "
                f"{timeout_s:.0f}s" + (f"; stderr: {tail}" if tail else "")
            )
            continue
        if res.returncode != 0:
            tail = _stderr_tail(res.stderr)
            last_err = (
                f"probe attempt {attempt + 1} exited rc={res.returncode}"
                + (f"; stderr: {tail}" if tail else "")
            )
            continue
        for line in res.stdout.splitlines():
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1].strip(), None
        last_err = (
            f"probe attempt {attempt + 1} produced no PLATFORM line"
        )
    return None, last_err


def probe_platform(timeout_s: float = 300.0) -> Optional[str]:
    """Default-platform discovery in a watchdogged subprocess.

    Returns the platform string (e.g. ``"tpu"``/``"cpu"``) of
    ``jax.devices()[0]`` under the *ambient* configuration, or ``None`` if
    initialization failed or timed out (wedged plugin).  The subprocess is
    killed on timeout, so the caller never hangs.  See
    :func:`probe_platform_ex` for the retrying variant with diagnostics.
    """
    return probe_platform_ex(timeout_s, retries=0)[0]
