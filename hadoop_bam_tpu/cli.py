"""Operator CLI: the reference's per-class ``main()`` entry points, unified.

The reference exposes L8 utilities as ``java -cp … <Class> args``:
``SplittingBAMIndexer.main`` (SplittingBAMIndexer.java:72),
``SplittingBAMIndex.main`` (SplittingBAMIndex.java:116),
``BGZFBlockIndexer.main`` (util/BGZFBlockIndexer.java:42),
``BAMSplitGuesser.main`` (BAMSplitGuesser.java:341),
``BCFSplitGuesser.main`` (BCFSplitGuesser.java:368) and
``GetSortedBAMHeader.main`` (util/GetSortedBAMHeader.java:36).  Here they are
subcommands of ``python -m hadoop_bam_tpu``, plus ``sort`` (the end-to-end
TestBAM-style coordinate sort the reference only ships as an example job) and
``bai-index`` (the reference delegates `.bai` construction to htsjdk).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _cmd_splitting_index(args) -> int:
    from .spec import indices

    for path in args.bam:
        idx = indices.build_splitting_bai(path, granularity=args.granularity)
        out = path + indices.SPLITTING_BAI_EXT
        with open(out, "wb") as f:
            idx.save(f)
        print(f"{out}: {idx.size()} offsets (granularity {args.granularity})")
    return 0


def _cmd_splitting_index_dump(args) -> int:
    from .spec import indices

    idx = indices.SplittingBai.load(args.index)
    print(f"{args.index}: {idx.size()} offsets, bam size {idx.bam_size()}")
    for v in idx.voffsets:
        print(f"{v >> 16}:{v & 0xFFFF}")
    return 0


def _cmd_bgzf_index(args) -> int:
    from .spec.indices import BGZFI_EXT, BgzfBlockIndex

    for path in args.file:
        with open(path, "rb") as f:
            data = f.read()
        idx = BgzfBlockIndex.build(data, granularity=args.granularity)
        out = path + BGZFI_EXT
        with open(out, "wb") as f:
            idx.save(f)
        print(f"{out}: {idx.size()} offsets (granularity {args.granularity})")
    return 0


def _cmd_bai_index(args) -> int:
    from .spec import indices

    for path in args.bam:
        bai = indices.build_bai(path)
        out = path + ".bai"
        with open(out, "wb") as f:
            bai.save(f)
        print(f"{out}: {len(bai.refs)} references")
    return 0


def _cmd_bam_guess(args) -> int:
    from .io.bam import read_header
    from .io.guesser import BamSplitGuesser

    with open(args.bam, "rb") as f:
        data = f.read()
    hdr = read_header(data)
    end = args.end if args.end is not None else len(data)
    g = BamSplitGuesser(data, hdr.n_refs)
    v = g.guess_next_record_start(args.pos, end)
    if v == end:
        print(f"no BAM record found in [{args.pos},{end})")
        return 1
    print(f"{v >> 16}:{v & 0xFFFF}")
    return 0


def _cmd_bcf_guess(args) -> int:
    from .io.bcf import BcfSplitGuesser, read_bcf_header

    with open(args.bcf, "rb") as f:
        data = f.read()
    hdr, _ = read_bcf_header(data)
    end = args.end if args.end is not None else len(data)
    g = BcfSplitGuesser(data, hdr)
    v = g.guess_next_record_start(args.pos, end)
    if v is None:
        print(f"no BCF record found in [{args.pos},{end})")
        return 1
    if g.compressed:
        print(f"{v >> 16}:{v & 0xFFFF}")
    else:
        # _guess_plain returns the degenerate voffset form (off << 16);
        # report the plain file offset for uncompressed input.
        print(v >> 16)
    return 0


def _cmd_sorted_header(args) -> int:
    from .io.bam import read_header
    from .io.merger import prepare_bam_header_block

    hdr = read_header(args.bam).with_sort_order("coordinate")
    block = prepare_bam_header_block(hdr)
    if args.out == "-":
        sys.stdout.buffer.write(block)
    else:
        with open(args.out, "wb") as f:
            f.write(block)
        print(f"{args.out}: {len(block)} bytes (BGZF header block)")
    return 0


def _parse_size(text: str) -> int:
    """'512m'/'2g'-style byte counts for --memory-budget (plain ints pass
    through)."""
    s = text.strip().lower()
    mult = 1
    for suffix, m in (("k", 1 << 10), ("m", 1 << 20), ("g", 1 << 30)):
        if s.endswith(suffix):
            s, mult = s[: -len(suffix)], m
            break
    try:
        return int(s) * mult
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"invalid size {text!r} (expected bytes or k/m/g suffix)"
        )


def _apply_robustness_args(conf, args) -> None:
    """Wire the shared ``--errors`` / ``--faults`` flags into the conf
    (and arm the process-global fault plan for ``--faults``)."""
    from . import faults
    from .conf import ERRORS_MODE, FAULTS_PLAN

    if getattr(args, "errors", None):
        conf.set(ERRORS_MODE, args.errors)
    if getattr(args, "faults", None):
        conf.set(FAULTS_PLAN, args.faults)
        faults.arm(args.faults)


def _arm_trace(args, conf=None) -> bool:
    """Arm the process-global timeline tracer for a ``--trace`` run.

    The ring capacity comes from ``hadoopbam.trace.events`` when set
    (oldest events drop on overflow; cumulative metrics are unaffected).
    """
    if not getattr(args, "trace", None):
        return False
    from .conf import TRACE_EVENTS
    from .utils.tracing import DEFAULT_TRACE_EVENTS, TRACER

    cap = (
        conf.get_int(TRACE_EVENTS, DEFAULT_TRACE_EVENTS)
        if conf is not None
        else DEFAULT_TRACE_EVENTS
    )
    TRACER.start(capacity=cap)
    return True


def _check_drained() -> None:
    """End-of-run HBM leak check: any device allocation still held
    (outside the serve arena, which keeps residency by design) is
    force-closed as a leak — counted under ``hbm.leaked_bytes`` with its
    holder named, flagged as a degradation reason in the run manifest,
    and emitted onto the trace — instead of the run crashing or the pin
    staying invisible.  Runs before the trace/metrics exports so the
    verdict lands in both artifacts."""
    from .utils.hbm import LEDGER

    rep = LEDGER.assert_drained()
    if rep["leaked_bytes"]:
        holders = ", ".join(
            f"{h}={n}B" for h, n in sorted(rep["holders"].items())
        )
        print(
            f"warning: {rep['leaked_bytes']} HBM bytes leaked "
            f"({holders}); run flagged degraded",
            file=sys.stderr,
        )


def _export_trace(args) -> None:
    """Write the Chrome trace-event JSON and disarm (stderr status line —
    stdout may be carrying a BAM blob for ``view -o -``)."""
    from .utils.tracing import TRACER

    n = TRACER.export_chrome(args.trace)
    dropped = TRACER.dropped_events
    TRACER.stop()
    msg = f"{args.trace}: {n} trace events"
    if dropped:
        msg += f" ({dropped} oldest dropped; raise hadoopbam.trace.events)"
    print(msg, file=sys.stderr)


def _cmd_sort(args, mark_duplicates: bool = False) -> int:
    from .conf import (
        BAM_MARK_DUPLICATES,
        BAM_SORT_ORDER,
        BAM_WRITE_SPLITTING_BAI,
        DEFLATE_LANES,
        INFLATE_LANES,
        WRITE_DEVICE,
        Configuration,
    )
    from .pipeline import sort_bam

    conf = Configuration()
    _apply_robustness_args(conf, args)
    sort_order = (
        "queryname" if getattr(args, "queryname", False) else "coordinate"
    )
    conf.set(BAM_SORT_ORDER, sort_order)
    if args.write_splitting_bai:
        conf.set_boolean(BAM_WRITE_SPLITTING_BAI, True)
    # Device codec toggles: unset leaves the conf key absent, deferring to
    # the HBAM_* env vars / local-latency auto rule (ops.flate gates).
    if args.inflate_lanes is not None:
        conf.set_boolean(INFLATE_LANES, args.inflate_lanes == "on")
    if args.deflate_lanes is not None:
        conf.set_boolean(DEFLATE_LANES, args.deflate_lanes == "on")
    if getattr(args, "device_write", None) is not None:
        conf.set_boolean(WRITE_DEVICE, args.device_write == "on")
    mark_duplicates = mark_duplicates or getattr(
        args, "mark_duplicates", False
    )
    if mark_duplicates:
        conf.set_boolean(BAM_MARK_DUPLICATES, True)
    mesh = None
    if args.devices:
        from .parallel.mesh import make_mesh

        mesh = make_mesh(args.devices)
    import contextlib

    from .utils.tracing import delta, device_trace, snapshot

    ctx = (
        device_trace(args.trace_dir) if args.trace_dir
        else contextlib.nullcontext()
    )
    traced = _arm_trace(args, conf)
    # Snapshot/delta, not reset(): the ``--metrics`` report covers exactly
    # this run even when sort_bam is invoked from a process with prior
    # registry traffic (a resident daemon, a test harness) — resetting the
    # process-global registry here would corrupt any concurrent user's
    # delta accounting (see MetricsRegistry.reset's hazard note).
    before = snapshot() if args.metrics else None
    with ctx:
        stats = sort_bam(
            list(args.bam),
            args.output,
            conf=conf,
            split_size=args.split_size,
            mesh=mesh,
            level=args.level,
            write_splitting_bai=args.write_splitting_bai,
            memory_budget=args.memory_budget,
            part_dir=args.part_dir,
            sort_order=sort_order,
        )
    _check_drained()
    if traced:
        _export_trace(args)
    dup = (
        f", {stats.n_duplicates} duplicates flagged" if mark_duplicates
        else ""
    )
    print(
        f"{args.output}: {stats.n_records} records from {stats.n_splits} "
        f"splits via {stats.backend}{dup}"
    )
    if args.metrics:
        import json

        report = delta(before)
        # Device codec tier accounting, explicit even when every counter
        # is zero (publish() skips zeros): members per tier plus the
        # size/vmem/ok0 tier-down taxonomy of the most recent call to
        # each wrapper.  Cumulative totals ride in the flate.inflate.* /
        # flate.deflate.* counters above.
        from .ops import flate

        report["codec_tiers"] = {
            "inflate_last_call": flate.LAST_INFLATE_STATS.as_dict(),
            "deflate_last_call": flate.LAST_DEFLATE_STATS.as_dict(),
        }
        # Transfer ledger: the h2d/d2h byte totals (and per-kind splits)
        # the hot paths reported — the write-side "only compressed bytes
        # cross PCIe" claim is a number here, not an inference.
        from .utils.tracing import run_manifest, transfers_report

        report["transfers"] = transfers_report(report["counters"])
        # Run provenance: backend actually used, every tier decision with
        # its reason counters, fault/salvage mode, conf overrides — the
        # block that keeps a silent fallback from masquerading as a
        # device run (the bench rounds carry the same manifest).
        report["run_manifest"] = run_manifest(
            backend=stats.backend,
            conf=conf,
            counters=report["counters"],
        ).as_dict()
        # Mesh provenance: if this process folded a ClusterManifest (a
        # mesh-traced sort_bam_multihost ran here — the driver scripts
        # and bench workers do exactly that before asking for metrics),
        # it rides the report so the cluster verdict and the per-host
        # byte matrix land in the same artifact as the run manifest.
        mh_mod = sys.modules.get("hadoop_bam_tpu.parallel.multihost")
        if mh_mod is not None and getattr(
            mh_mod, "LAST_CLUSTER_MANIFEST", None
        ):
            report["cluster_manifest"] = mh_mod.LAST_CLUSTER_MANIFEST
        print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _cmd_markdup(args) -> int:
    return _cmd_sort(args, mark_duplicates=True)


def _cmd_fixmate(args) -> int:
    """Fill mate coordinates/flags/TLEN/MC from collated pairs,
    preserving record order (the samtools-fixmate role, on any input
    order — the collation engine pairs mates by name)."""
    from .conf import Configuration
    from .pipeline import fixmate_bam

    conf = Configuration()
    _apply_robustness_args(conf, args)
    traced = _arm_trace(args, conf)
    from .utils.tracing import delta, snapshot

    before = snapshot() if args.metrics else None
    stats = fixmate_bam(
        list(args.bam),
        args.output,
        conf=conf,
        split_size=args.split_size,
        level=args.level,
        memory_budget=args.memory_budget,
        part_dir=args.part_dir,
    )
    _check_drained()
    if traced:
        _export_trace(args)
    print(
        f"{args.output}: {stats.n_records} records from {stats.n_splits} "
        f"splits via {stats.backend}: {stats.n_pairs} pairs fixed, "
        f"{stats.n_singletons} singletons, {stats.n_orphans} orphans"
    )
    if args.metrics:
        import json

        from .utils.tracing import run_manifest

        report = delta(before)
        report["run_manifest"] = run_manifest(
            backend=stats.backend,
            conf=conf,
            counters=report["counters"],
        ).as_dict()
        print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _cmd_ingest(args) -> int:
    from .conf import (
        DEFLATE_LANES,
        FASTQ_BASE_QUALITY_ENCODING,
        FASTQ_FILTER_FAILED_QC,
        INFLATE_LANES,
        INGEST_DEVICE_SCAN,
        Configuration,
    )
    from .ingest import ingest_fastq

    conf = Configuration()
    _apply_robustness_args(conf, args)
    if args.inflate_lanes is not None:
        conf.set_boolean(INFLATE_LANES, args.inflate_lanes == "on")
    if args.deflate_lanes is not None:
        conf.set_boolean(DEFLATE_LANES, args.deflate_lanes == "on")
    if args.device_scan is not None:
        conf.set(INGEST_DEVICE_SCAN,
                 "true" if args.device_scan == "on" else "false")
    if args.quality_encoding:
        conf.set(FASTQ_BASE_QUALITY_ENCODING, args.quality_encoding)
    if args.filter_failed_qc:
        conf.set_boolean(FASTQ_FILTER_FAILED_QC, True)
    traced = _arm_trace(args, conf)
    from .utils.tracing import delta, snapshot

    before = snapshot() if args.metrics else None
    stats = ingest_fastq(
        args.fastq,
        args.output,
        r2=args.r2,
        conf=conf,
        level=args.level,
        memory_budget=args.memory_budget,
        part_dir=args.part_dir,
    )
    _check_drained()
    if traced:
        _export_trace(args)
    paired = f", {stats.n_pairs} pairs" if stats.n_pairs else ""
    lost = (
        f", {stats.n_quarantined_members} members quarantined"
        if stats.n_quarantined_members else ""
    )
    print(
        f"{args.output}: {stats.n_records} records from "
        f"{stats.n_members or 1} members{paired}{lost}"
    )
    if args.metrics:
        import json

        from .utils.tracing import run_manifest

        report = delta(before)
        report["run_manifest"] = run_manifest(
            backend="ingest", conf=conf, counters=report["counters"]
        ).as_dict()
        print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def _cmd_view(args) -> int:
    """One-shot ranged view: the daemon's ``view`` endpoint without a
    daemon — same code path (serve.endpoints.view_blob), so the output is
    byte-identical to a served response for the same file and region."""
    from .conf import Configuration
    from .serve.endpoints import ServeContext, view_blob

    conf = Configuration()
    _apply_robustness_args(conf, args)
    traced = _arm_trace(args, conf)
    ctx = ServeContext.from_conf(conf, with_batcher=False)
    try:
        blob = view_blob(ctx, args.bam, args.region, level=args.level)
    finally:
        ctx.close()
        _check_drained()
        if traced:
            _export_trace(args)
    if args.output == "-":
        sys.stdout.buffer.write(blob)
    else:
        with open(args.output, "wb") as f:
            f.write(blob)
        print(f"{args.output}: {len(blob)} bytes")
    return 0


def _cmd_flagstat(args) -> int:
    """One-shot flag census (the daemon's ``flagstat`` endpoint)."""
    import json

    from .conf import Configuration
    from .serve.endpoints import ServeContext, flagstat

    conf = Configuration()
    _apply_robustness_args(conf, args)
    traced = _arm_trace(args, conf)
    ctx = ServeContext.from_conf(conf, with_batcher=False)
    try:
        counts = flagstat(ctx, args.bam)
    finally:
        ctx.close()
        _check_drained()
        if traced:
            _export_trace(args)
    print(json.dumps(counts, indent=2, sort_keys=True))
    return 0


def _cmd_variants(args) -> int:
    """One-shot ranged variant query: the daemon's ``variants`` endpoint
    without a daemon — same code path (serve.endpoints.variants_blob), so
    the output BCF is byte-identical to a served response for the same
    file and region."""
    import json

    from .conf import Configuration
    from .serve.endpoints import ServeContext, variants_blob
    from .utils.tracing import delta, snapshot

    conf = Configuration()
    _apply_robustness_args(conf, args)
    traced = _arm_trace(args, conf)
    before = snapshot() if args.metrics else None
    ctx = ServeContext.from_conf(conf, with_batcher=False)
    try:
        blob = variants_blob(ctx, args.bcf, args.region)
    finally:
        ctx.close()
        _check_drained()
        if traced:
            _export_trace(args)
    if args.output == "-":
        sys.stdout.buffer.write(blob)
    else:
        with open(args.output, "wb") as f:
            f.write(blob)
        print(f"{args.output}: {len(blob)} bytes")
    if args.metrics:
        # The variant-plane tier story in one report: bcf.chain.* walk
        # tiers, bcf.guess.* resync work, variants.join_* cut tiers,
        # salvage.* quarantines — printed to stderr so `-o -` piping
        # stays a clean BCF stream.
        print(
            json.dumps(delta(before), indent=2, sort_keys=True),
            file=sys.stderr,
        )
    return 0


def _cmd_depth(args) -> int:
    """One-shot pileup depth summary (the daemon's ``depth`` endpoint)."""
    import json

    from .conf import Configuration
    from .serve.endpoints import ServeContext, depth_stat
    from .utils.tracing import delta, snapshot

    conf = Configuration()
    _apply_robustness_args(conf, args)
    traced = _arm_trace(args, conf)
    before = snapshot() if args.metrics else None
    ctx = ServeContext.from_conf(conf, with_batcher=False)
    try:
        stat = depth_stat(
            ctx,
            args.bam,
            args.region,
            bin_size=args.bin_size,
            per_base=args.per_base,
        )
    finally:
        ctx.close()
        _check_drained()
        if traced:
            _export_trace(args)
    print(json.dumps(stat, indent=2, sort_keys=True))
    if args.metrics:
        print(
            json.dumps(delta(before), indent=2, sort_keys=True),
            file=sys.stderr,
        )
    return 0


def _cmd_serve(args) -> int:
    """Run the resident daemon until a ``shutdown`` request (or SIGINT)."""
    from .conf import (
        Configuration,
        SERVE_ACCESS_LOG,
        SERVE_ACCESS_LOG_BYTES,
        SERVE_ADMISSION_TOKENS,
        SERVE_ARENA_BYTES,
        SERVE_BATCH_WINDOW_MS,
        SERVE_CACHE_BYTES,
        SERVE_EXEMPLAR_DIR,
        SERVE_EXEMPLAR_THRESHOLD_MS,
        SERVE_EXEMPLARS_MAX,
        SERVE_FLIGHTREC,
        SERVE_FLIGHTREC_BYTES,
        SERVE_FLIGHTREC_CADENCE_MS,
        SERVE_JOURNAL,
        SERVE_MAX_INFLIGHT,
        SERVE_MAX_QUEUE,
        SERVE_MAX_QUEUE_MS,
        SERVE_REQUEST_TRACING,
        SERVE_SLO,
        SERVE_SLO_WINDOWS,
    )
    from .serve.server import BamDaemon

    conf = Configuration()
    _apply_robustness_args(conf, args)
    if args.cache_bytes is not None:
        conf.set_int(SERVE_CACHE_BYTES, args.cache_bytes)
    if args.arena_bytes is not None:
        conf.set_int(SERVE_ARENA_BYTES, args.arena_bytes)
    if args.batch_window_ms is not None:
        conf.set_int(SERVE_BATCH_WINDOW_MS, args.batch_window_ms)
    if args.max_inflight is not None:
        conf.set_int(SERVE_MAX_INFLIGHT, args.max_inflight)
    if args.admission_tokens is not None:
        conf.set_int(SERVE_ADMISSION_TOKENS, args.admission_tokens)
    if args.max_queue is not None:
        conf.set_int(SERVE_MAX_QUEUE, args.max_queue)
    if args.max_queue_ms is not None:
        conf.set_int(SERVE_MAX_QUEUE_MS, args.max_queue_ms)
    if args.journal is not None:
        conf.set(SERVE_JOURNAL, args.journal)
    if args.flightrec is not None:
        conf.set(SERVE_FLIGHTREC, args.flightrec)
    if args.flightrec_cadence_ms is not None:
        conf.set_int(SERVE_FLIGHTREC_CADENCE_MS, args.flightrec_cadence_ms)
    if args.flightrec_bytes is not None:
        conf.set_int(SERVE_FLIGHTREC_BYTES, args.flightrec_bytes)
    if args.no_request_tracing:
        conf.set_boolean(SERVE_REQUEST_TRACING, False)
    if args.exemplar_threshold_ms is not None:
        conf.set_int(SERVE_EXEMPLAR_THRESHOLD_MS, args.exemplar_threshold_ms)
    if args.exemplars_max is not None:
        conf.set_int(SERVE_EXEMPLARS_MAX, args.exemplars_max)
    if args.exemplar_dir is not None:
        conf.set(SERVE_EXEMPLAR_DIR, args.exemplar_dir)
    if args.access_log is not None:
        conf.set(SERVE_ACCESS_LOG, args.access_log)
    if args.access_log_bytes is not None:
        conf.set_int(SERVE_ACCESS_LOG_BYTES, args.access_log_bytes)
    if args.slo is not None:
        conf.set(SERVE_SLO, args.slo)
    if args.slo_windows is not None:
        conf.set(SERVE_SLO_WINDOWS, args.slo_windows)
    from .conf import FLEET_DIR, FLEET_HEARTBEAT_MS, FLEET_NAME

    if args.fleet_dir is not None:
        conf.set(FLEET_DIR, args.fleet_dir)
    if args.fleet_name is not None:
        conf.set(FLEET_NAME, args.fleet_name)
    if args.heartbeat_ms is not None:
        conf.set_int(FLEET_HEARTBEAT_MS, args.heartbeat_ms)
    daemon = BamDaemon(
        conf=conf,
        socket_path=args.socket,
        port=args.port,
        warmup=not args.no_warmup,
    )
    # SIGTERM/SIGINT drain like the shutdown op: finish in-flight jobs,
    # journal their terminal states, then exit the accept loop.
    daemon.install_signal_handlers()
    daemon.start()
    if daemon.warmup_report is not None:
        w = daemon.warmup_report
        print(
            f"warm-up: {w['compiles']} compiles over "
            f"{sum(w['warmed'].values())} geometries"
            + (f", errors: {w['errors']}" if w["errors"] else "")
        )
    print(f"serving on {daemon.endpoint}")
    try:
        daemon.serve_forever()
    except KeyboardInterrupt:
        daemon.stop()
    return 0


def _cmd_fleet(args) -> int:
    """Run the fleet front router: one address for N serve daemons
    (consistent-hash routing on the cache file identity, federated
    admission, heartbeat membership, journal adoption on an unclean
    death)."""
    from .conf import (
        Configuration,
        FLEET_DIR,
        FLEET_FILE_TOKENS,
        FLEET_HEARTBEAT_TIMEOUT_MS,
        FLEET_MIGRATE_WARMTH,
        FLEET_TOKENS,
        FLEET_VNODES,
    )
    from .serve.router import FleetRouter

    conf = Configuration()
    conf.set(FLEET_DIR, args.fleet_dir)
    if args.heartbeat_timeout_ms is not None:
        conf.set_int(FLEET_HEARTBEAT_TIMEOUT_MS, args.heartbeat_timeout_ms)
    if args.vnodes is not None:
        conf.set_int(FLEET_VNODES, args.vnodes)
    if args.fleet_tokens is not None:
        conf.set_int(FLEET_TOKENS, args.fleet_tokens)
    if args.file_tokens is not None:
        conf.set_int(FLEET_FILE_TOKENS, args.file_tokens)
    if args.migrate_warmth:
        conf.set_boolean(FLEET_MIGRATE_WARMTH, True)
    router = FleetRouter(
        conf=conf, socket_path=args.socket, port=args.port
    )
    router.start()
    print(
        f"fleet router on {router.endpoint} "
        f"(dir {router.fleet_dir}, {len(router.ring)} member(s))"
    )
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        router.stop()
    return 0


def _cmd_stats(args) -> int:
    """One stats snapshot from a running daemon, with the SLO block
    pretty-printed (burn rates, window compliance, worst op) — the
    operator's "is the service meeting its objectives" one-liner."""
    import json

    from .serve.client import ServeClient
    from .serve.slo import format_slo_block

    client = ServeClient(socket_path=args.socket, port=args.port)
    st = client.stats()
    if args.json:
        print(json.dumps(st, indent=2, sort_keys=True, default=str))
        return 0
    print(format_slo_block(st.get("slo") or {}))
    hists = (st.get("metrics") or {}).get("histograms") or {}
    lat = {
        k: v for k, v in hists.items()
        if k.startswith("serve.op.") and k.endswith(".ms")
    }
    if lat:
        print("\nper-op latency (log2-bucket percentiles, ms):")
        for k in sorted(lat):
            h = lat[k]
            print(
                f"  {k:<28} n={h.get('count', 0):<8.0f} "
                f"p50≤{h.get('p50', 0):g} p95≤{h.get('p95', 0):g} "
                f"p99≤{h.get('p99', 0):g}"
            )
    jobs = st.get("jobs") or {}
    if jobs:
        by_status: dict = {}
        for j in jobs.values():
            by_status[j["status"]] = by_status.get(j["status"], 0) + 1
        print("\njobs: " + ", ".join(
            f"{k}={v}" for k, v in sorted(by_status.items())
        ))
    ex_count = (st.get("gauges") or {}).get("serve.trace.exemplar_count")
    if ex_count:
        print(
            f"\nexemplars held: {ex_count:.0f} "
            "(list with the `exemplars` op; render one with "
            "tools/request_report.py)"
        )
    return 0


def _add_trace_arg(s) -> None:
    """The shared ``--trace`` flag (sort/markdup/view/flagstat)."""
    s.add_argument(
        "--trace", default=None, metavar="OUT.json",
        help="record a per-event timeline (bounded ring buffer; "
             "hadoopbam.trace.events caps it) and export Chrome "
             "trace-event JSON here — load in Perfetto/chrome://tracing, "
             "reduce with tools/trace_report.py for per-stage "
             "busy/idle/overlap and the top stall")


def _add_robustness_args(s) -> None:
    """The shared failure-policy flags (sort/markdup/view/flagstat/serve)."""
    s.add_argument(
        "--errors", choices=("strict", "salvage"), default=None,
        help="corrupt-input policy (hadoopbam.errors): strict = abort on "
             "the first bad BGZF member or torn record (default); salvage "
             "= quarantine corrupt members/records, re-sync the record "
             "chain via the guesser, finish the job (losses reported as "
             "salvage.* counters in --metrics)")
    s.add_argument(
        "--faults", default=None, metavar="PLAN",
        help="arm a deterministic fault-injection plan "
             "(hadoopbam.faults.plan / HBAM_FAULTS; directive grammar in "
             "hadoop_bam_tpu/faults/plan.py, e.g. "
             "'seed=7;io.read.error:n=2;exec.crash:items=0,attempts=0') — "
             "for robustness drills; disarmed runs pay nothing")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hadoop_bam_tpu",
        description="TPU-native splittable bioinformatics format toolkit",
    )
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser(
        "splitting-index",
        help="build .splitting-bai record index(es) for BAM file(s)",
    )
    s.add_argument("bam", nargs="+")
    s.add_argument("-g", "--granularity", type=int, default=4096)
    s.set_defaults(func=_cmd_splitting_index)

    s = sub.add_parser(
        "splitting-index-dump", help="print a .splitting-bai's offsets"
    )
    s.add_argument("index")
    s.set_defaults(func=_cmd_splitting_index_dump)

    s = sub.add_parser(
        "bgzf-index", help="build .bgzfi block index(es) for BGZF file(s)"
    )
    s.add_argument("file", nargs="+")
    s.add_argument("-g", "--granularity", type=int, default=1024)
    s.set_defaults(func=_cmd_bgzf_index)

    s = sub.add_parser(
        "bai-index", help="build a standard .bai for a coordinate-sorted BAM"
    )
    s.add_argument("bam", nargs="+")
    s.set_defaults(func=_cmd_bai_index)

    s = sub.add_parser(
        "bam-guess", help="find the first BAM record start at/after a byte position"
    )
    s.add_argument("bam")
    s.add_argument("pos", type=int)
    s.add_argument("--end", type=int, default=None)
    s.set_defaults(func=_cmd_bam_guess)

    s = sub.add_parser(
        "bcf-guess", help="find the first BCF record start at/after a byte position"
    )
    s.add_argument("bcf")
    s.add_argument("pos", type=int)
    s.add_argument("--end", type=int, default=None)
    s.set_defaults(func=_cmd_bcf_guess)

    s = sub.add_parser(
        "sorted-header",
        help="extract a BAM header, set SO:coordinate, emit as a BGZF block",
    )
    s.add_argument("bam")
    s.add_argument("out", nargs="?", default="-")
    s.set_defaults(func=_cmd_sorted_header)

    def add_sort_args(s, markdup: bool) -> None:
        s.add_argument("bam", nargs="+")
        s.add_argument("-o", "--output", required=True)
        s.add_argument("--split-size", type=int, default=32 << 20)
        s.add_argument("--level", type=int, default=6)
        s.add_argument("--devices", type=int, default=0,
                       help="sort over an n-device mesh (0 = single device)")
        s.add_argument("--write-splitting-bai", action="store_true")
        s.add_argument(
            "--memory-budget", type=_parse_size, default=None,
            metavar="BYTES",
            help="bounded-memory out-of-core sort: cap materialized record "
                 "bytes (accepts k/m/g suffixes, e.g. 512m)")
        s.add_argument(
            "--part-dir", default=None, metavar="DIR",
            help="persistent part/spill directory: finished parts (and, "
                 "with --memory-budget, the manifest-validated spill runs) "
                 "become crash-restart checkpoints — rerun the same "
                 "command after a kill and only missing work is redone")
        s.add_argument(
            "--inflate-lanes", choices=("on", "off"), default=None,
            help="force the lockstep-lane device inflate tier "
                 "(hadoopbam.inflate.lanes; default: auto rule)")
        s.add_argument(
            "--deflate-lanes", choices=("on", "off"), default=None,
            help="force the lockstep-lane device deflate tier "
                 "(hadoopbam.deflate.lanes; default: auto rule)")
        s.add_argument(
            "--device-write", choices=("on", "off"), default=None,
            help="force the device-resident part writes (on-chip sorted "
                 "gather + flag patch + CRC32 feeding the deflate lanes "
                 "from HBM; hadoopbam.write.device, default: auto rule)")
        if not markdup:
            s.add_argument(
                "-n", "--queryname", action="store_true",
                help="sort by read name (samtools natural order) instead "
                     "of coordinates: the collation engine groups records "
                     "by name hash on device and ranks the verified "
                     "buckets with the exact strnum_cmp comparator; the "
                     "output header says SO:queryname")
            s.add_argument(
                "--mark-duplicates", action="store_true",
                help="fuse samtools-class duplicate marking into the sort "
                     "(OR 0x400 into duplicates' flags at write time)")
        s.add_argument("--metrics", action="store_true",
                       help="print the span/counter report after the run "
                            "(includes the device codec tier counters: "
                            "flate.inflate.* / flate.deflate.* members "
                            "per tier and size/vmem/ok0 tier-downs, plus "
                            "the transfers block: h2d/d2h bytes by kind)")
        s.add_argument("--trace-dir", default=None,
                       help="capture a JAX profiler (XPlane) trace here "
                            "(device timeline; composable with --trace's "
                            "host timeline)")
        _add_trace_arg(s)
        _add_robustness_args(s)

    s = sub.add_parser("sort", help="coordinate-sort BAM file(s) end to end")
    add_sort_args(s, markdup=False)
    s.set_defaults(func=_cmd_sort)

    s = sub.add_parser(
        "markdup",
        help="coordinate-sort + mark PCR/optical duplicates (0x400) in "
             "one fused pass (a no-op reorder for already-sorted input)",
    )
    add_sort_args(s, markdup=True)
    s.set_defaults(func=_cmd_markdup)

    s = sub.add_parser(
        "fixmate",
        help="fill mate coordinates, mate flags, TLEN and MC tags from "
             "collated pairs, preserving record order (samtools fixmate "
             "semantics; any input order — mates pair by name collation)",
    )
    s.add_argument("bam", nargs="+")
    s.add_argument("-o", "--output", required=True)
    s.add_argument("--split-size", type=int, default=32 << 20)
    s.add_argument("--level", type=int, default=6)
    s.add_argument(
        "--memory-budget", type=_parse_size, default=None, metavar="BYTES",
        help="bounded-memory fixmate: pass B re-reads splits instead of "
             "retaining them (accepts k/m/g suffixes)")
    s.add_argument(
        "--part-dir", default=None, metavar="DIR",
        help="persistent part directory: finished parts are crash-restart "
             "checkpoints, as for sort")
    s.add_argument("--metrics", action="store_true",
                   help="print the span/counter report after the run "
                        "(collate.pairs/singletons/orphans, fixmate.* "
                        "counters, run manifest)")
    _add_trace_arg(s)
    _add_robustness_args(s)
    s.set_defaults(func=_cmd_fixmate)

    s = sub.add_parser(
        "ingest",
        help="FASTQ (optionally .gz, optionally paired R1/R2) to "
             "queryname-collated unaligned BAM: gzip members decode on "
             "the inflate lanes, record boundaries come from the device "
             "record-scan kernel, pairs collate by name, the uBAM writes "
             "through the device deflate path — fixmate-ready output",
    )
    s.add_argument("fastq", help="R1 (or sole) FASTQ input, plain or gzip")
    s.add_argument("--r2", default=None, metavar="FASTQ",
                   help="R2 mate file for paired-end input")
    s.add_argument("-o", "--output", required=True)
    s.add_argument("--level", type=int, default=6)
    s.add_argument(
        "--memory-budget", type=_parse_size, default=None, metavar="BYTES",
        help="bounded-memory ingest: encoded records spill in rank-tagged "
             "runs and k-way merge (byte-identical output; accepts k/m/g "
             "suffixes)")
    s.add_argument(
        "--part-dir", default=None, metavar="DIR",
        help="spill directory for --memory-budget runs (default: a "
             "temporary directory)")
    s.add_argument(
        "--quality-encoding", choices=("sanger", "illumina"), default=None,
        help="input base quality encoding (hbam.fastq-input."
             "base-quality-encoding; illumina converts to sanger)")
    s.add_argument(
        "--filter-failed-qc", action="store_true",
        help="drop records whose CASAVA 1.8 filter field says Y "
             "(hbam.fastq-input.filter-failed-qc)")
    s.add_argument(
        "--inflate-lanes", choices=("on", "off"), default=None,
        help="force the lockstep-lane device inflate tier for the "
             "compressed members (default: auto rule)")
    s.add_argument(
        "--deflate-lanes", choices=("on", "off"), default=None,
        help="force the lockstep-lane device deflate tier for the uBAM "
             "output (default: auto rule)")
    s.add_argument(
        "--device-scan", choices=("on", "off"), default=None,
        help="force the device record-boundary scan kernel "
             "(hadoopbam.ingest.device-scan; default: follows the "
             "inflate-lanes auto rule)")
    s.add_argument("--metrics", action="store_true",
                   help="print the counter report after the run "
                        "(ingest.*, fastq.scan.*, salvage.ingest_* "
                        "counters plus the run manifest)")
    _add_trace_arg(s)
    _add_robustness_args(s)
    s.set_defaults(func=_cmd_ingest)

    s = sub.add_parser(
        "view",
        help="index-backed ranged read: records overlapping a region "
             "as a small BAM (samtools-style region shorthand accepted; "
             "same code path as the serve daemon's view endpoint)",
    )
    s.add_argument("bam")
    s.add_argument("region", help="contig | contig:pos | contig:start-end")
    s.add_argument("-o", "--output", default="-")
    s.add_argument("--level", type=int, default=6)
    _add_trace_arg(s)
    _add_robustness_args(s)
    s.set_defaults(func=_cmd_view)

    s = sub.add_parser(
        "flagstat",
        help="whole-file flag census (samtools flagstat-class counters, "
             "printed as JSON; same code path as the daemon endpoint)",
    )
    s.add_argument("bam")
    _add_trace_arg(s)
    _add_robustness_args(s)
    s.set_defaults(func=_cmd_flagstat)

    s = sub.add_parser(
        "variants",
        help="ranged BCF query: variant records overlapping a region as "
             "a small BCF (same code path as the serve daemon's variants "
             "endpoint; device record-chain walk under the "
             "hadoopbam.bcf.chain gate)",
    )
    s.add_argument("bcf")
    s.add_argument("region", help="contig | contig:pos | contig:start-end "
                                  "(samtools thousands separators OK)")
    s.add_argument("-o", "--output", default="-")
    s.add_argument("--metrics", action="store_true",
                   help="print the counter delta to stderr after the run "
                        "(bcf.chain.*, bcf.guess.*, variants.*, "
                        "salvage.* tier/fault accounting)")
    _add_trace_arg(s)
    _add_robustness_args(s)
    s.set_defaults(func=_cmd_variants)

    s = sub.add_parser(
        "depth",
        help="pileup depth summary over an alignment region (binned "
             "vector + max/mean/coverage as JSON; same code path as the "
             "daemon's depth endpoint)",
    )
    s.add_argument("bam")
    s.add_argument("region", help="contig | contig:pos | contig:start-end")
    s.add_argument("--bin-size", type=int, default=1 << 12)
    s.add_argument("--per-base", action="store_true",
                   help="include the exact per-base vector (span-capped "
                        "server-side)")
    s.add_argument("--metrics", action="store_true",
                   help="print the counter delta to stderr after the run "
                        "(pileup.* tier accounting)")
    _add_trace_arg(s)
    _add_robustness_args(s)
    s.set_defaults(func=_cmd_depth)

    s = sub.add_parser(
        "serve",
        help="resident service mode: a long-lived daemon owning the TPU "
             "(warm kernel/index caches, HBM arena, cross-request lane "
             "batching) behind a localhost/UDS JSON socket",
    )
    s.add_argument(
        "--socket", default=None,
        help="UDS socket path (default: a per-user path under the temp "
             "dir; hadoopbam.serve.socket)")
    s.add_argument(
        "--port", type=int, default=None,
        help="serve on 127.0.0.1:PORT instead of a UDS socket "
             "(hadoopbam.serve.port)")
    s.add_argument(
        "--cache-bytes", type=_parse_size, default=None, metavar="BYTES",
        help="header/index cache budget (hadoopbam.serve.cache-bytes; "
             "accepts k/m/g suffixes)")
    s.add_argument(
        "--arena-bytes", type=_parse_size, default=None, metavar="BYTES",
        help="HBM residency arena budget (hadoopbam.serve.arena-bytes)")
    s.add_argument(
        "--batch-window-ms", type=int, default=None,
        help="admission batch window for cross-request lane coalescing "
             "(hadoopbam.serve.batch-window-ms; 0 disables)")
    s.add_argument(
        "--max-inflight", type=int, default=None,
        help="max concurrently-running submitted jobs "
             "(hadoopbam.serve.max-inflight)")
    s.add_argument(
        "--no-warmup", action="store_true",
        help="skip the startup kernel-geometry pre-compilation "
             "(hadoopbam.serve.warmup)")
    s.add_argument(
        "--admission-tokens", type=int, default=None,
        help="admission concurrency budget in cost units (view=1, "
             "flagstat=2, sort=4; hadoopbam.serve.admission-tokens)")
    s.add_argument(
        "--max-queue", type=int, default=None,
        help="admission queue depth bound — beyond it requests shed "
             "with code SHED + a retry_after_ms hint "
             "(hadoopbam.serve.max-queue)")
    s.add_argument(
        "--max-queue-ms", type=int, default=None,
        help="queue-wait p95 bound in ms — beyond it requests shed with "
             "code RETRY_AFTER (hadoopbam.serve.max-queue-ms; 0 "
             "disables the wait rule)")
    s.add_argument(
        "--journal", default=None, metavar="FILE",
        help="crash-safe job journal (append-only fsync'd JSONL, "
             "hadoopbam.serve.journal): a restarted daemon reports "
             "accurate terminal job states, resumes interrupted sorts "
             "byte-identically via their part-dir checkpoints, and "
             "answers unknown ids with code JOB_LOST")
    s.add_argument(
        "--flightrec", default=None, metavar="BASE",
        help="flight recorder ring base path "
             "(hadoopbam.serve.flightrec): periodic gauge/counter/HBM "
             "snapshots to a bounded two-segment JSONL ring, finalized "
             "on drain — after a kill -9, replay the daemon's final "
             "seconds with tools/flightrec_report.py")
    s.add_argument(
        "--flightrec-cadence-ms", type=int, default=None,
        help="flight-recorder snapshot cadence in milliseconds "
             "(hadoopbam.serve.flightrec-cadence-ms; default 500)")
    s.add_argument(
        "--flightrec-bytes", type=_parse_size, default=None,
        metavar="BYTES",
        help="flight-recorder ring byte budget across both segments "
             "(hadoopbam.serve.flightrec-bytes; default 1m)")
    s.add_argument(
        "--no-request-tracing", action="store_true",
        help="turn the per-request tracing plane off "
             "(hadoopbam.serve.request-tracing; on by default — "
             "trace-id propagation, hop summaries, tail exemplars)")
    s.add_argument(
        "--exemplar-threshold-ms", type=int, default=None,
        help="latency threshold for the tail sampler: a request slower "
             "than this gets its full event set copied into the "
             "exemplar store (hadoopbam.serve.exemplar-threshold-ms, "
             "default 1000; 0 disables the latency trigger — "
             "shed/deadline/error/tier-down outcomes always sample)")
    s.add_argument(
        "--exemplars-max", type=int, default=None,
        help="exemplar store bound, oldest evicted "
             "(hadoopbam.serve.exemplars-max, default 64)")
    s.add_argument(
        "--exemplar-dir", default=None, metavar="DIR",
        help="also spill each exemplar as DIR/<trace_id>.json "
             "(hadoopbam.serve.exemplar-dir) — survives the daemon; "
             "render with tools/request_report.py")
    s.add_argument(
        "--access-log", default=None, metavar="BASE",
        help="JSONL access log base path (hadoopbam.serve.access-log): "
             "one structured line per completed request (trace id, op, "
             "outcome, duration, queue/batch waits, tier decisions), "
             "rotated with the flight recorder's two-segment scheme; "
             "joins with exemplars on trace id")
    s.add_argument(
        "--access-log-bytes", type=_parse_size, default=None,
        metavar="BYTES",
        help="access-log ring byte budget across both segments "
             "(hadoopbam.serve.access-log-bytes; default 4m)")
    s.add_argument(
        "--slo", default=None, metavar="SPEC",
        help="declared SLO objectives (hadoopbam.serve.slo), e.g. "
             "'view:latency=100@0.999;sort:availability=0.99' — "
             "evaluated over sliding windows from the per-op "
             "histograms; burn-rate alerts surface in stats, the "
             "flight recorder and Prometheus text")
    s.add_argument(
        "--slo-windows", default=None, metavar="FAST,SLOW",
        help="SLO sliding windows in seconds "
             "(hadoopbam.serve.slo-windows; default '60,600')")
    s.add_argument(
        "--fleet-dir", default=None, metavar="DIR",
        help="join a fleet: publish an atomic member record (name, "
             "endpoint, journal, flight recorder) in DIR and heartbeat "
             "it (hadoopbam.fleet.dir) — the fleet router routes to "
             "members it finds there")
    s.add_argument(
        "--fleet-name", default=None,
        help="this member's fleet name (hadoopbam.fleet.member-name; "
             "default daemon-<pid>)")
    s.add_argument(
        "--heartbeat-ms", type=int, default=None,
        help="fleet heartbeat cadence (hadoopbam.fleet.heartbeat-ms; "
             "default 500)")
    _add_robustness_args(s)
    s.set_defaults(func=_cmd_serve)

    s = sub.add_parser(
        "fleet",
        help="fleet front router: one UDS/TCP address for N serve "
             "daemons — consistent-hash placement on the cache file "
             "identity, federated admission, heartbeat membership, "
             "journal adoption on an unclean death",
    )
    s.add_argument(
        "--fleet-dir", required=True, metavar="DIR",
        help="the shared fleet directory daemons heartbeat into "
             "(hadoopbam.fleet.dir)")
    s.add_argument(
        "--socket", default=None,
        help="router UDS socket path (default: a per-user "
             "hbam-fleet-<uid>.sock under the temp dir; "
             "hadoopbam.fleet.socket)")
    s.add_argument(
        "--port", type=int, default=None,
        help="route on 127.0.0.1:PORT instead of a UDS socket "
             "(hadoopbam.fleet.port)")
    s.add_argument(
        "--heartbeat-timeout-ms", type=int, default=None,
        help="declare a member dead after this much heartbeat silence, "
             "then consult its flight recorder before adopting "
             "(hadoopbam.fleet.heartbeat-timeout-ms; default 3000)")
    s.add_argument(
        "--vnodes", type=int, default=None,
        help="virtual nodes per member on the consistent-hash ring "
             "(hadoopbam.fleet.vnodes; default 64)")
    s.add_argument(
        "--fleet-tokens", type=int, default=None,
        help="fleet-wide admission pool in cost units "
             "(hadoopbam.fleet.tokens; default 32)")
    s.add_argument(
        "--file-tokens", type=int, default=None,
        help="per-file in-flight cap in cost units — the hot-file "
             "starvation bound (hadoopbam.fleet.file-tokens; default 8)")
    s.add_argument(
        "--migrate-warmth", action="store_true",
        help="on a planned member leave, ship its warm arena windows "
             "to the new ring owners as compressed BGZF members "
             "(hadoopbam.fleet.migrate-warmth)")
    s.set_defaults(func=_cmd_fleet)

    s = sub.add_parser(
        "stats",
        help="one stats snapshot from a running daemon with the SLO "
             "block pretty-printed (burn rates, compliance, worst op)",
    )
    s.add_argument(
        "--socket", default=None,
        help="daemon UDS socket path (default: the per-user default)")
    s.add_argument(
        "--port", type=int, default=None,
        help="daemon 127.0.0.1 TCP port instead of a UDS socket")
    s.add_argument(
        "--json", action="store_true",
        help="emit the raw stats reply as JSON instead of the summary")
    s.set_defaults(func=_cmd_stats)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
