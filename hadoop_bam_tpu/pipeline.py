"""End-to-end jobs: the reference's example MapReduce programs, TPU-native.

``sort_bam`` is the TestBAM coordinate-sort job (SURVEY.md §3.5): read
record-aligned splits → batched decode → 64-bit keying → sort → headerless
parts → merge to one valid BAM.  The sort runs either on one chip
(``lax.sort``) or across a mesh (range-partitioned ``all_to_all`` shuffle),
selected by ``mesh``.

The host↔device contract: fixed-field SoA columns and keys live on device;
ragged record bytes stay host-side and are permuted once at write time (the
LazyBAMRecord stance — the sort never touches variable-length payloads).
"""

from __future__ import annotations

import contextlib
import io
import os
import tempfile
import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from .conf import (
    BAM_MARK_DUPLICATES,
    BAM_SORT_ORDER,
    BAM_WRITE_SPLITTING_BAI,
    ERRORS_MODE,
    EXECUTOR_ATTEMPT_TIMEOUT_MS,
    EXECUTOR_BACKOFF_MS,
    Configuration,
)
from .utils.hbm import LEDGER
from .utils.tracing import METRICS, current_request, span, trace_ctx


@contextlib.contextmanager
def _request_hop(name: str, **extras):
    """Annotate the enclosed phase as one hop on the ambient request
    context (a serve sort job's waterfall shows read/sort/write
    durations without the ring).  Batch mode — no ambient context — is
    one ``is None`` branch, the disarmed contract."""
    rctx = current_request()
    if rctx is None:
        yield
        return
    import time as _time

    t0 = _time.perf_counter()
    try:
        yield
    finally:
        rctx.annotate(
            name, ms=(_time.perf_counter() - t0) * 1e3, **extras
        )
from .io.bam import (
    SORT_FIELDS,
    BamInputFormat,
    BamOutputWriter,
    ChunkedRecords,
    RecordBatch,
    read_header,
)
from .io.merger import merge_bam_parts
from .ops.sort import sort_keys
from .parallel.executor import ElasticExecutor, bgzf_part_valid

# The FASTQ front door lives in its own module (it feeds this pipeline
# rather than riding it) but is part of the public pipeline surface.
from .ingest import IngestStats, ingest_fastq, ingest_oracle  # noqa: F401


def _input_format(conf, in_paths):
    """BamInputFormat for all-``.bam`` inputs (the hot default path,
    unchanged), the AnySam dispatcher when any input is CRAM/SAM — the
    front door that lets ``sort_bam`` and fixmate take ``.cram`` input
    through the same DeviceStream read drive (CRAM block decode rides
    the stream's rANS-lanes tier policy)."""
    from .io.anysam import AnySamInputFormat, infer_from_file_path

    if all(infer_from_file_path(p) == "bam" for p in in_paths):
        return BamInputFormat(conf)
    return AnySamInputFormat(conf)


def _read_any_header(fmt, path):
    """Header via the format's own reader when it has one (the AnySam
    dispatcher routes CRAM to the file-header container), the BAM/BGZF
    reader otherwise."""
    rh = getattr(fmt, "read_header", None)
    return rh(path) if rh is not None else read_header(path)
from .parallel.mesh import make_mesh
from .parallel.shuffle import DistributedSort
from .spec import bam
from .utils import nio


@dataclass
class SortStats:
    n_records: int
    n_splits: int
    backend: str
    n_runs: int = 0  # out-of-core path: sorted spill runs written
    n_ranges: int = 0  # out-of-core path: merge key ranges
    peak_bytes: int = 0  # out-of-core path: largest materialized chunk
    n_duplicates: int = 0  # markdup fusion stage: records flagged 0x400


def _release_split_residency(b: RecordBatch) -> None:
    """Give a split's HBM-resident window back through the residency
    ledger and drop the reference.  Every path that is done with a
    split's ``device_data`` — the unused-handoff case, the post-parse
    drop, the post-adopt cleanup, the out-of-core spill loop — comes
    through here (delegating to the DeviceStream's shared release seam),
    so a skipped release shows up as a *named* ``hbm.leaked.<holder>``
    counter instead of a silent HBM pin (the PR 5 bug class; the leak
    drill monkeypatches exactly this helper)."""
    from .device_stream import DeviceStream

    DeviceStream.release_batch(b)


def _concat_batches(batches: List[RecordBatch]) -> RecordBatch:
    """One global batch over all splits (offsets rebased into the
    concatenated sideband)."""
    if not batches:
        return RecordBatch(
            soa={k: np.empty(0, np.int64) for k in bam.SOA_FIELDS},
            data=np.empty(0, np.uint8),
            keys=np.empty(0, np.int64),
        )
    if len(batches) == 1:
        return batches[0]
    data = np.concatenate([b.data for b in batches])
    base = np.cumsum([0] + [len(b.data) for b in batches[:-1]])
    soa = {}
    for k in bam.SOA_FIELDS:
        cols = [b.soa[k] for b in batches]
        if k == "rec_off":
            cols = [c + base[i] for i, c in enumerate(cols)]
        soa[k] = np.concatenate(cols)
    keys = np.concatenate([b.keys for b in batches])
    return RecordBatch(soa=soa, data=data, keys=keys)


def sort_bam(
    in_paths: Sequence[str] | str,
    out_path: str,
    conf: Optional[Configuration] = None,
    split_size: int = 32 << 20,
    mesh=None,
    distributed: Optional[DistributedSort] = None,
    level: int = 6,
    write_splitting_bai: bool = False,
    max_attempts: int = 3,
    part_dir: Optional[str] = None,
    write_workers: Optional[int] = None,
    backend: str = "device",
    memory_budget: Optional[int] = None,
    device_parse: Optional[bool] = None,
    mark_duplicates: bool = False,
    resource_cache=None,
    errors: Optional[str] = None,
    sort_order: Optional[str] = None,
    deadline=None,
) -> SortStats:
    """Sort BAM file(s) into one merged BAM.

    ``sort_order`` selects the output ordering: ``"coordinate"`` (the
    default — the reference's TestBAM job) or ``"queryname"`` (the
    collation engine: records grouped on-device by their 64-bit name
    hash, buckets ranked host-side with the exact samtools
    ``strnum_cmp`` natural comparator, ties broken by flag → position →
    read index; the CLI's ``sort -n``).  ``None`` defers to the
    ``hadoopbam.bam.sort-order`` conf key.  The output header's
    ``@HD SO:`` field reports whichever order was actually written —
    never an unconditional claim.  Queryname keys come from the
    collation engine, so ``sort_order="queryname"`` is incompatible
    with ``mesh``/``distributed``, ``mark_duplicates`` (which needs the
    coordinate stream; markdup itself already accepts unsorted input by
    collating signatures) and an explicit ``device_parse=True`` (the
    device-parse path builds coordinate keys).

    ``backend``: "device" (single-chip sort with host↔device transfers
    overlapped against split reads and part writes), or "host" (NumPy
    argsort oracle — the samtools-class single-core baseline, also the
    CPU-only fallback).  A ``mesh``/``distributed`` argument overrides
    ``backend`` with the multi-chip all_to_all shuffle sort.

    ``hadoopbam.bam.write-splitting-bai`` in ``conf`` enables the per-part
    splitting index like the kwarg does (the reference's config-driven
    WRITE_SPLITTING_BAI, BAMOutputFormat.java).

    ``memory_budget`` (bytes of uncompressed record stream) switches to the
    bounded-memory out-of-core path: splits stream through sorted spill
    runs on disk and a key-range merge, so files far larger than host RAM
    sort with a flat peak (the Hadoop shuffle's spill+merge, SURVEY §7
    hard part #3).  Not combinable with ``mesh``/``distributed``.

    ``device_parse`` selects the device-resident read path: each split's
    inflated record stream uploads once (h2d is the cheap direction) and
    the Pallas chain kernel + on-chip field gathers + ``make_keys`` build
    the sort keys from raw bytes — the host does no field decode or key
    assembly, displacing the reference's per-record hot loop
    (BAMRecordReader.java:223-232) onto the chip.  ``None`` (auto) enables
    it when the default JAX backend is a TPU (the ``HBAM_DEVICE_PARSE``
    env var forces it 0/1); it is skipped under interval filtering (the
    kept-record subset is not a contiguous stream) and is incompatible
    with ``memory_budget`` (explicit True raises; spill runs sort
    host-side).  Device-derived record counts are validated against the
    host chain walk; any mismatch — or any device-side error — falls back
    to host-built keys for the whole job.

    When the lockstep-lane inflate tier is also enabled (the
    ``hadoopbam.inflate.lanes`` conf key on ``conf``, or the same
    local-latency auto rule), the split reads feeding this mode upload
    *compressed* BGZF blocks and inflate them on-device
    (``io.bam.read_split`` → ``ops.flate.inflate_blocks_device``) — ≈4x
    fewer h2d bytes than shipping the inflated stream.

    The part writes have the symmetric device tier: when the lockstep-lane
    *deflate* encoder is enabled (``hadoopbam.deflate.lanes`` conf key /
    ``HBAM_DEFLATE_LANES`` env / the same local-latency auto rule), each
    part's gathered record stream compresses on-chip
    (``ops.pallas.deflate_lanes`` LZ77 + fixed-Huffman emit) and the host
    does only gzip framing + CRC32 — displacing the ~38% of host wall the
    level-1 zlib part writes cost on the 1-core bench host.

    ``mark_duplicates`` (or the ``hadoopbam.bam.mark-duplicates`` conf
    key) fuses the dedup subsystem into the sort: each split's ragged
    sidebands reduce to fixed-width signature columns during the read
    (clip-adjusted unclipped-5′ ends, summed base qualities, name
    hashes), the samtools-class decision runs on device over the whole
    job (:mod:`hadoop_bam_tpu.dedup`), and the part writes OR
    ``FLAG_DUPLICATE`` into each duplicate's flag bytes just before
    deflate.  Works on every sort path, including ``memory_budget`` —

    ``deadline`` (a :class:`utils.deadline.Deadline`) is the request's
    end-to-end budget — the serve daemon threads it from the client's
    ``deadline_ms``.  It is checked at the phase boundaries and before
    every part-write attempt (the elastic executor composes it with
    ``attempt-timeout-ms``); expiry raises ``DeadlineExceeded`` instead
    of burning device time.  None (the batch default) costs one branch
    per seam.
    there the record *bytes* stay budget-bounded while the signature
    columns (~18 bytes/record, like samtools markdup's per-read state)
    stay in memory.

    ``resource_cache`` (a :class:`serve.cache.ResourceCache`) serves the
    input header from the resident daemon's identity-keyed cache instead
    of re-reading it per job — the serve subsystem passes its own; batch
    invocations leave it None and read cold as before.

    ``errors`` (default: the ``hadoopbam.errors`` conf key, else
    "strict") is the corrupt-input policy.  "strict" aborts on the first
    bad BGZF member or torn record (pre-PR-7 behavior, and the hot path
    is untouched).  "salvage" degrades instead of dying: corrupt members
    and unparseable records are quarantined with guesser re-sync
    (``salvage.*`` counters report exactly what was lost), a split whose
    read fails outright contributes an empty batch, and a part that
    exhausts its write attempts is quarantined rather than failing the
    job.  Clean input produces byte-identical output in both modes."""
    if backend not in ("device", "host"):
        raise ValueError(
            f"backend must be 'device' or 'host', got {backend!r}"
        )
    if isinstance(in_paths, str):
        in_paths = [in_paths]
    fmt = _input_format(conf, in_paths)
    if conf is not None:
        write_splitting_bai = write_splitting_bai or conf.get_boolean(
            BAM_WRITE_SPLITTING_BAI
        )
        mark_duplicates = mark_duplicates or conf.get_boolean(
            BAM_MARK_DUPLICATES
        )
    if errors is None:
        errors = (
            conf.get(ERRORS_MODE, "strict") if conf is not None else "strict"
        ) or "strict"
    if errors not in ("strict", "salvage"):
        raise ValueError(f"errors must be strict|salvage, got {errors!r}")
    if sort_order is None:
        sort_order = (
            conf.get(BAM_SORT_ORDER, "coordinate")
            if conf is not None
            else "coordinate"
        ) or "coordinate"
    if sort_order not in ("coordinate", "queryname"):
        raise ValueError(
            f"sort_order must be coordinate|queryname, got {sort_order!r}"
        )
    queryname = sort_order == "queryname"
    if queryname:
        if mesh is not None or distributed is not None:
            raise ValueError(
                "sort_order='queryname' with a mesh goes through "
                "parallel.multihost.sort_bam_multihost(sort_order="
                "'queryname') — its distributed rank pass replaces "
                "this driver's single-host collation"
            )
        if mark_duplicates:
            raise ValueError(
                "mark_duplicates needs the coordinate stream; markdup "
                "already accepts unsorted/queryname-grouped input by "
                "collating signatures — run it without sort_order"
            )
        if device_parse:
            raise ValueError(
                "device_parse builds coordinate keys; queryname keys "
                "come from the collation engine"
            )
    # Executor hardening knobs (attempt deadline + retry backoff), shared
    # by every write phase below.
    timeout_ms = conf.get_int(EXECUTOR_ATTEMPT_TIMEOUT_MS, 0) if conf else 0
    exec_timeout = timeout_ms / 1e3 if timeout_ms > 0 else None
    exec_backoff = (
        conf.get_int(EXECUTOR_BACKOFF_MS, 50) if conf else 50
    ) / 1e3
    if resource_cache is not None:
        header = resource_cache.header(in_paths[0])[0]
    else:
        header = _read_any_header(fmt, in_paths[0])
    # The header claims the order actually written (satellite fix: this
    # used to stamp "coordinate" unconditionally on every write path).
    header = header.with_sort_order(sort_order)
    # The job's DeviceStream: tier policy (with the pipelined auto-rtt
    # relaxation), residency seam, deadline checks and the double-
    # buffered split drive, resolved once here instead of per call site.
    from .device_stream import DeviceStream

    stream = DeviceStream(conf=conf, deadline=deadline)
    if memory_budget is not None:
        if mesh is not None or distributed is not None:
            raise ValueError(
                "memory_budget is single-host; use the multi-host runner "
                "for distributed out-of-core sorts"
            )
        if device_parse:
            raise ValueError(
                "device_parse is not supported with memory_budget: spill "
                "runs sort host-side (the device-resident parse applies to "
                "the in-memory path only)"
            )
        # A split is the memory floor (it inflates as one batch): keep its
        # compressed size well under the budget.  BGZF inflation is
        # typically 3-5x but can exceed 10x on low-entropy data, so clamp
        # to budget/16 (peak_bytes reports honestly if a pathological
        # split still overshoots).
        split_size = max(64 << 10, min(split_size, memory_budget // 16))
        splits = fmt.get_splits(in_paths, split_size=split_size)

        key_column = None
        if queryname:
            # The rank prepass: one extra streaming read builds the
            # collation columns (≈20 B/record + name bytes — the same
            # "columns stay in memory, payloads stay bounded" stance as
            # out-of-core markdup), and the resulting read-order rank
            # becomes the external sort's key column — unique int64s,
            # so spill runs and exact range planning work unchanged.
            key_column = _queryname_rank_column(
                fmt, splits, errors, stream=stream
            )
        return _sort_bam_external(
            fmt,
            splits,
            header,
            out_path,
            memory_budget=memory_budget,
            level=level,
            backend=backend,
            write_splitting_bai=write_splitting_bai,
            max_attempts=max_attempts,
            part_dir=part_dir,
            write_workers=write_workers,
            device_deflate=stream.policy.deflate_lanes,
            mark_duplicates=mark_duplicates,
            device_write=stream.policy.device_write,
            errors=errors,
            attempt_timeout=exec_timeout,
            retry_backoff=exec_backoff,
            sort_order=sort_order,
            key_column=key_column,
            deadline=deadline,
            stream=stream,
        )
    with span("sort_bam.plan"):
        splits = fmt.get_splits(in_paths, split_size=split_size)

    use_device = (
        backend == "device" and distributed is None and mesh is None
    )
    if queryname:
        # Queryname keys come from the collation engine (its lax.sort
        # grouping pass IS the device stage); the coordinate key
        # upload/sort machinery below stays cold.
        use_device = False
    if device_parse is None:
        env = os.environ.get("HBAM_DEVICE_PARSE")
        if env is not None:
            device_parse = env.strip().lower() not in (
                "0", "false", "no", "off", "",
            )
    use_device_parse = (
        use_device
        # CRAM/SAM ByteSplits have no BGZF chunk plan and no device
        # inflate residency, so the device-parse chain never applies.
        and all(
            getattr(s, "interval_chunks", None) is None
            and hasattr(s, "vstart")
            for s in splits
        )
        and (
            device_parse
            if device_parse is not None
            else stream.default_device_parse()
        )
    )
    # Device-resident part writes: the sorted gather + flag patch + CRC32
    # feed the deflate lanes straight from the HBM-resident split
    # payloads, so the write side d2h's only compressed bytes.  Resolved
    # once per job on the stream's policy (``hadoopbam.write.device`` /
    # HBAM_DEVICE_WRITE / the pipelined-relaxed local-latency auto rule)
    # independently of the sort backend — it is a codec-tier concern like
    # the deflate lanes; split residency is kept through the sort when on.
    use_device_write = stream.policy.device_write
    batches: List[RecordBatch] = []
    parsed: List[Optional[tuple]] = []  # per batch: (hi, lo, unm, meta)
    dev_hi: List = []
    dev_lo: List = []
    pending: List[np.ndarray] = []

    def _upload_pending() -> None:
        # Batched key upload: one device RPC per ~quarter of the file,
        # dispatched mid-read so the transfer rides under the next splits'
        # native inflate (which releases the GIL).  Per-split uploads pay
        # a tunnel round trip each; one big upload at sort time overlaps
        # with nothing.
        if pending:
            from .ops.keys import split_keys_np
            from .utils.tracing import count_h2d

            hi_i, lo_i = split_keys_np(
                pending[0] if len(pending) == 1 else np.concatenate(pending)
            )
            count_h2d(hi_i.nbytes + lo_i.nbytes, "keys")
            dev_hi.append(jnp.asarray(hi_i))
            dev_lo.append(jnp.asarray(lo_i))
            pending.clear()

    upload_every = max(1, -(-len(splits) // 4))  # ceil: ≤4 upload RPCs
    read_fields = (
        ("rec_off", "rec_len") if use_device_parse else SORT_FIELDS
    )
    collate_cols: List[dict] = []
    if queryname:
        # Name hashes need the qname geometry on top of the key inputs.
        read_fields = tuple(
            dict.fromkeys(SORT_FIELDS + ("l_read_name",))
        )
    sig_cols: List[dict] = []
    if mark_duplicates:
        # The dedup signature needs the clip/qual/name geometry columns on
        # top of the key inputs; they are reduced per split and dropped
        # with the rest of the SoA, so host peak stays at the extents.
        from .dedup import DEDUP_EXTRA_FIELDS, signature_columns

        read_fields = tuple(
            dict.fromkeys(
                read_fields + SORT_FIELDS + DEDUP_EXTRA_FIELDS
            )
        )
    with span("sort_bam.read"), _request_hop("pipeline.read"):
        for si, b in enumerate(
            stream.read_splits(
                fmt,
                splits,
                fields=read_fields,
                with_keys=not (use_device_parse or queryname),
                errors=errors,
            )
        ):
            if mark_duplicates:
                with span("sort_bam.markdup_signature"):
                    sig_cols.append(signature_columns(b.data, b.soa))
            if queryname:
                from .collate import collation_columns

                with span("collate.stage.signature", category="stage"):
                    collate_cols.append(collation_columns(b.data, b.soa))
            # Only the record extents stay live (the other fixed-field
            # columns would just inflate host peak).
            b.soa = {
                "rec_off": b.soa["rec_off"],
                "rec_len": b.soa["rec_len"],
            }
            if not use_device_parse and not use_device_write:
                # Neither the device-parse path nor the device write
                # consumes the residency handoff; don't pin HBM with
                # unused split windows.
                _release_split_residency(b)
            batches.append(b)
            if use_device_parse:
                # The split's record stream ships to the chip as raw bytes;
                # boundary walk + field gathers + key assembly all happen
                # there, overlapping the next split's host-side inflate.
                # One failed split dooms the whole device path (the sort
                # falls back to host keys for the job), so stop uploading
                # the moment any split fails rather than shipping the rest
                # of the file to the chip for results that will be thrown
                # away.
                if parsed and parsed[-1] is False:
                    parsed.append(False)
                    continue
                try:
                    with trace_ctx(split=si), span(
                        "pipeline.stage.device_parse", category="stage"
                    ):
                        parsed.append(
                            stream.parse_split(
                                b, keep_residency=use_device_write
                            )
                        )
                except Exception:
                    # Device OOM / compile failure / tunnel error: record
                    # the failure and let the sort fall back to host keys.
                    METRICS.count("sort_bam.device_parse_error", 1)
                    parsed.append(False)
                # The chain kernel has consumed (or declined) the
                # device-resident window; drop the reference so HBM frees
                # as the read proceeds instead of pinning every split —
                # unless the device write path will gather parts from it.
                if not use_device_write:
                    _release_split_residency(b)
            elif use_device:
                pending.append(b.keys)
                if (si + 1) % upload_every == 0:
                    _upload_pending()
    n = sum(b.n_records for b in batches)
    METRICS.count("sort_bam.records", n)
    METRICS.count("sort_bam.splits", len(splits))

    def _all_keys() -> np.ndarray:
        # Only the host/distributed sorts need the concatenated key column;
        # the device path keeps keys on-chip (ADVICE r1: building it
        # unconditionally cost an extra 8 bytes/record of host peak).
        return (
            np.concatenate([b.keys for b in batches])
            if batches
            else np.empty(0, np.int64)
        )

    if queryname and n:
        # The collation engine: one device grouping pass over the
        # job-global name-hash columns, host natural-order ranking of
        # the verified bucket representatives, one lexsort finish.
        from .collate import concat_collation, queryname_perm

        backend = "collate-queryname"
        with span("sort_bam.queryname_sort", category="stage"):
            perm, _qstats = queryname_perm(concat_collation(collate_cols))
        collate_cols = []
    elif distributed is not None or mesh is not None:
        ds = distributed
        if ds is None:
            mesh = mesh or make_mesh()
            rows = -(-max(n, 1) // mesh.devices.size)
            ds = DistributedSort(mesh, rows_per_device=rows)
        backend = f"mesh[{ds.n_devices}]"
        with span("sort_bam.shuffle_sort", category="stage"):
            all_keys = _all_keys()
            try:
                _, perm, _ = ds.sort_global(all_keys)
            except RuntimeError:
                # Degenerate key skew: retry with full capacity.
                ds = DistributedSort(
                    ds.mesh, ds.rows, capacity_per_pair=ds.rows
                )
                _, perm, _ = ds.sort_global(all_keys)
    elif use_device_parse and n:
        backend = "device-parse"
        with span("sort_bam.device_parse_sort", category="stage"):
            try:
                perm = _finish_device_parse(batches, parsed, n)
            except Exception:
                METRICS.count("sort_bam.device_parse_error", 1)
                perm = None
            if perm is None:
                # Device chain disagreed with the host walk (or errored):
                # rebuild keys host-side — correctness never depends on the
                # device path.
                METRICS.count("sort_bam.device_parse_fallback", 1)
                backend = "host-fallback"
                perm = np.argsort(
                    np.concatenate([_host_keys(b) for b in batches]),
                    kind="stable",
                )
    elif use_device and n:
        backend = "single-device"
        with span("sort_bam.device_sort", category="stage"):
            # Key columns were uploaded in batches during the read; the
            # permutation comes back in a few async group downloads that
            # are awaited lazily: group g's transfer rides under the
            # (CPU-bound, GIL-releasing) gather+deflate of the parts
            # covered by groups < g.  Remote chip links have high
            # per-transfer latency, so a handful of big groups beats both
            # one blocking fetch (no overlap left) and per-part slices (28
            # latencies).
            _upload_pending()
            hi = dev_hi[0] if len(dev_hi) == 1 else jnp.concatenate(dev_hi)
            lo = dev_lo[0] if len(dev_lo) == 1 else jnp.concatenate(dev_lo)
            dev_hi.clear()
            dev_lo.clear()
            _, _, perm_dev = sort_keys(hi, lo)
            perm = _LazyPermFetch(perm_dev, n)
    else:
        backend = "host"
        with span("sort_bam.host_sort", category="stage"):
            perm = np.argsort(_all_keys(), kind="stable")

    # The dedup fusion stage: one device decision over the job-global
    # signature columns (read order — the same index space the part
    # writers' ``order`` slices address, so patching is a plain gather).
    dup_mask = None
    n_dup = 0
    if mark_duplicates and n:
        from .dedup import concat_columns, mark_duplicates_device

        with span("sort_bam.markdup"):
            dup_mask = mark_duplicates_device(concat_columns(sig_cols))
            n_dup = int(dup_mask.sum())
        METRICS.count("sort_bam.duplicates", n_dup)
        sig_cols = []

    # A zero-copy chunked view over the per-split batches — the permuted
    # part writes gather straight from the split payloads (no global
    # concatenation; on a 1-core host that copy dominated the pipeline).
    from .io.bam import write_part_fast

    # Part-write deflate tier from the stream's policy, resolved once per
    # job: the lockstep-lane Pallas encoder (LZ77 on chip, host does
    # framing + CRC32) behind the ``hadoopbam.deflate.lanes`` conf key /
    # ``HBAM_DEFLATE_LANES`` env / the pipelined-relaxed auto rule.
    use_device_deflate = stream.policy.deflate_lanes
    merged = ChunkedRecords.from_batches(
        batches, with_keys=False, keep_device=use_device_write
    )
    if use_device_write:
        # The flat device copy (if any) now owns the resident bytes
        # (from_batches adopted the donors in the ledger); drop the
        # per-split references so the originals free before the writes
        # start instead of doubling HBM for the whole write phase.  When
        # the adoption didn't happen (a split lacked residency, or the
        # concat failed) the release here is the real one.
        for b in batches:
            _release_split_residency(b)
    with span("sort_bam.write_merge"), _request_hop("pipeline.write_merge"), contextlib.ExitStack() as stack:
        if part_dir is not None:
            # Persistent part dir: the parts are crash-restart units — a
            # rerun with the same part_dir redoes only missing parts (the
            # reference's part-file + _SUCCESS resume semantics, §5).
            td = part_dir
            os.makedirs(td, exist_ok=True)
        else:
            td = stack.enter_context(
                tempfile.TemporaryDirectory(
                    dir=os.path.dirname(os.path.abspath(out_path)) or "."
                )
            )
        executor = ElasticExecutor(
            td,
            max_attempts=max_attempts,
            max_workers=write_workers,
            validate_part=bgzf_part_valid,
            quarantine=errors == "salvage",
            attempt_timeout=exec_timeout,
            retry_backoff=exec_backoff,
            deadline=deadline,
        )
        # Split the native deflate thread budget across concurrent writers.
        deflate_threads = max(
            1, (os.cpu_count() or 4) // executor.max_workers
        )
        n_parts = max(1, len(batches))
        bounds = [n * i // n_parts for i in range(n_parts + 1)]

        def write_one(pi: int, tmp: str) -> None:
            order = perm[bounds[pi] : bounds[pi + 1]]
            sb_stream = None
            try:
                if write_splitting_bai:
                    sb_stream = open(tmp + ".sb", "wb")
                with trace_ctx(part=pi), span(
                    "pipeline.stage.write_part", category="item"
                ), open(tmp, "wb") as f:
                    write_part_fast(
                        f,
                        merged,
                        order=order,
                        level=level,
                        splitting_bai_stream=sb_stream,
                        threads=deflate_threads,
                        device_deflate=use_device_deflate,
                        dup_mask=dup_mask,
                        device_write=use_device_write,
                        device_stream=stream,
                    )
            finally:
                if sb_stream is not None:
                    sb_stream.close()
            if write_splitting_bai:
                os.replace(
                    tmp + ".sb",
                    os.path.join(td, f"part-r-{pi:05d}.splitting-bai"),
                )

        try:
            executor.run(list(range(n_parts)), write_one)
        finally:
            # Residency lifetime: the resident payload is dead once the
            # parts exist — free the HBM before the merge.
            merged.release_device()
        merge_bam_parts(
            td, out_path, header, write_splitting_bai=write_splitting_bai
        )
    return SortStats(
        n_records=n,
        n_splits=len(splits),
        backend=backend,
        n_duplicates=n_dup,
    )


def markdup_bam(
    in_paths: Sequence[str] | str,
    out_path: str,
    **kwargs,
) -> SortStats:
    """Standalone duplicate-marking job: ``sort_bam`` with the dedup
    fusion stage forced on.

    The sort is stable, so running it over an already coordinate-sorted
    BAM reproduces the input order — for sorted inputs this is a pure
    markdup pass (the biobambam ``bammarkduplicates`` role); for unsorted
    inputs it is sort+markdup in one pipeline (the ``samtools sort |
    samtools markdup`` pair, fused).  Accepts every ``sort_bam`` keyword
    (``memory_budget``, ``backend``, ``level``, …)."""
    kwargs["mark_duplicates"] = True
    return sort_bam(in_paths, out_path, **kwargs)


def _queryname_rank_column(
    fmt, splits, errors: str, stream=None
) -> np.ndarray:
    """The out-of-core queryname prepass: stream the splits once for
    their collation columns, run the engine, return each record's
    read-order *output rank* as an int64 column.  Ranks are unique, so
    they drop into the external sort's spill/range machinery as
    ordinary keys."""
    from .collate import collation_columns, concat_collation, queryname_perm

    fields = tuple(dict.fromkeys(SORT_FIELDS + ("l_read_name",)))
    cols: List[dict] = []
    with span("sort_bam.queryname_rank_prepass", category="stage"):
        for b in _read_splits_pipelined(
            fmt, splits, fields=fields, with_keys=False, errors=errors,
            stream=stream,
        ):
            with span("collate.stage.signature", category="stage"):
                cols.append(collation_columns(b.data, b.soa))
        perm, _ = queryname_perm(concat_collation(cols))
    rank = np.empty(len(perm), dtype=np.int64)
    rank[perm] = np.arange(len(perm), dtype=np.int64)
    return rank


@dataclass
class FixmateStats:
    n_records: int
    n_splits: int
    n_pairs: int
    n_singletons: int
    n_orphans: int
    backend: str


def fixmate_bam(
    in_paths: Sequence[str] | str,
    out_path: str,
    conf: Optional[Configuration] = None,
    split_size: int = 32 << 20,
    level: int = 6,
    memory_budget: Optional[int] = None,
    max_attempts: int = 3,
    part_dir: Optional[str] = None,
    write_workers: Optional[int] = None,
    write_splitting_bai: bool = False,
    errors: Optional[str] = None,
) -> FixmateStats:
    """Fill mate information from collated pairs, preserving record
    order (the ``samtools fixmate`` role, without requiring name-grouped
    input): mate coordinates, mate-unmapped/reverse flags, TLEN (the
    samtools 5′-to-5′ rule), MC mate-CIGAR tags, and placement of
    unmapped reads next to their mapped mates.  See
    :mod:`hadoop_bam_tpu.collate.fixmate` for the exact semantics and
    documented deviations.

    Two passes over the input: pass A streams the splits for the
    fixed-width collation columns (plus the small name/CIGAR blobs) and
    runs the engine's device grouping + host verification; pass B
    rewrites each split's records per the edit plan
    (:func:`io.bam.rebuild_record_stream` — source payloads never
    mutate) and writes one part per split through the elastic executor.
    In-core (default) pass A retains the decoded batches; with
    ``memory_budget`` set, pass B re-reads each split instead, so
    materialized record bytes stay bounded while the columns (~20
    B/record + name/CIGAR bytes) ride in memory — the out-of-core
    markdup stance.  The output header is the input's: fixmate changes
    neither order nor grouping, so it has nothing new to claim.

    ``errors="salvage"`` survives corrupt members like the sort paths;
    note both passes must then see the same surviving records, which
    holds for persistent corruption (the fault harness's bit-flips) but
    means transient-fault drills should prefer the strict reader."""
    if isinstance(in_paths, str):
        in_paths = [in_paths]
    from .collate import (
        FIXMATE_FIELDS,
        apply_fixmate,
        collate_by_name,
        collation_columns,
        compute_fixmate_edits,
        concat_collation,
        verify_and_repair,
    )

    fmt = _input_format(conf, in_paths)
    if conf is not None:
        write_splitting_bai = write_splitting_bai or conf.get_boolean(
            BAM_WRITE_SPLITTING_BAI
        )
    if errors is None:
        errors = (
            conf.get(ERRORS_MODE, "strict") if conf is not None else "strict"
        ) or "strict"
    if errors not in ("strict", "salvage"):
        raise ValueError(f"errors must be strict|salvage, got {errors!r}")
    timeout_ms = conf.get_int(EXECUTOR_ATTEMPT_TIMEOUT_MS, 0) if conf else 0
    exec_timeout = timeout_ms / 1e3 if timeout_ms > 0 else None
    exec_backoff = (
        conf.get_int(EXECUTOR_BACKOFF_MS, 50) if conf else 50
    ) / 1e3
    header = _read_any_header(fmt, in_paths[0])
    if memory_budget is not None:
        split_size = max(64 << 10, min(split_size, memory_budget // 16))
    with span("fixmate.plan"):
        splits = fmt.get_splits(in_paths, split_size=split_size)
    keep_batches = memory_budget is None
    read_fields = tuple(dict.fromkeys(FIXMATE_FIELDS))

    # Fixmate's DeviceStream: the read drive + deflate tier policy (the
    # rebuilt streams never carry residency, so device_write stays off
    # per part by construction).
    from .device_stream import DeviceStream

    stream = DeviceStream(conf=conf)
    batches: List[Optional[RecordBatch]] = []
    cols_parts: List[dict] = []
    row_bases: List[int] = [0]
    with span("fixmate.read", category="stage"):
        for b in stream.read_splits(
            fmt, splits, fields=read_fields, with_keys=False, errors=errors
        ):
            with span("collate.stage.signature", category="stage"):
                cols_parts.append(
                    collation_columns(b.data, b.soa, with_cigars=True)
                )
            _release_split_residency(b)  # fixmate rewrites host-side
            row_bases.append(row_bases[-1] + b.n_records)
            batches.append(b if keep_batches else None)
    n = row_bases[-1]
    METRICS.count("fixmate.records", n)

    with span("fixmate.collate", category="stage"):
        cols = concat_collation(cols_parts)
        cols_parts = []
        with span("collate.stage.device", category="stage"):
            col = collate_by_name(cols)
        with span("collate.stage.verify", category="stage"):
            col, _ = verify_and_repair(col, cols)
    with span("fixmate.stage.edits", category="stage"):
        edits = compute_fixmate_edits(cols, col)
    cols = None
    col = None

    with span("fixmate.write", category="stage"), \
            contextlib.ExitStack() as stack:
        if part_dir is not None:
            td = part_dir
            os.makedirs(td, exist_ok=True)
        else:
            td = stack.enter_context(
                tempfile.TemporaryDirectory(
                    dir=os.path.dirname(os.path.abspath(out_path)) or "."
                )
            )
        executor = ElasticExecutor(
            td,
            max_attempts=max_attempts,
            max_workers=write_workers,
            validate_part=bgzf_part_valid,
            quarantine=errors == "salvage",
            attempt_timeout=exec_timeout,
            retry_backoff=exec_backoff,
        )
        deflate_threads = max(
            1, (os.cpu_count() or 4) // executor.max_workers
        )
        from .io.bam import write_part_fast

        use_device_deflate = stream.policy.deflate_lanes

        def write_one(pi: int, tmp: str) -> None:
            b = batches[pi]
            if b is None:
                b = fmt.read_split(
                    splits[pi], fields=read_fields, with_keys=False,
                    errors=errors, stream=stream,
                )
            patched = apply_fixmate(b, edits, row_bases[pi])
            # The budget pass's re-read may carry the inflate tier's
            # residency handoff; the rebuilt stream never consumes it,
            # so give the window back before dropping the batch (an
            # unreleased drop is a named ledger leak).
            _release_split_residency(b)
            if not keep_batches:
                b = None
            sb_stream = None
            try:
                if write_splitting_bai:
                    sb_stream = open(tmp + ".sb", "wb")
                with trace_ctx(part=pi), span(
                    "pipeline.stage.write_part", category="item"
                ), open(tmp, "wb") as f:
                    write_part_fast(
                        f,
                        patched,
                        order=None,
                        level=level,
                        splitting_bai_stream=sb_stream,
                        threads=deflate_threads,
                        device_deflate=use_device_deflate,
                        device_write=False,  # rebuilt stream: no residency
                    )
            finally:
                if sb_stream is not None:
                    sb_stream.close()
            if write_splitting_bai:
                os.replace(
                    tmp + ".sb",
                    os.path.join(td, f"part-r-{pi:05d}.splitting-bai"),
                )

        executor.run(list(range(max(1, len(splits)))), write_one
                     if splits else _write_empty_part)
        merge_bam_parts(
            td, out_path, header, write_splitting_bai=write_splitting_bai
        )
    return FixmateStats(
        n_records=n,
        n_splits=len(splits),
        n_pairs=edits.counts["pairs"],
        n_singletons=edits.counts["singletons"],
        n_orphans=edits.counts["orphans"],
        backend="collate-fixmate"
        + ("[budget]" if memory_budget is not None else ""),
    )


def _default_device_parse() -> bool:
    """Auto rule for the device-resident parse: on for real, *local*
    accelerators.

    Under a CPU backend the chain kernel runs in (slow) interpret mode, so
    the host-key path wins there; tests force ``device_parse=True`` to
    exercise the interpret path on small inputs.  On a remote/tunneled
    chip the per-split stream uploads pay latency the host-key path does
    not — the gate is the DeviceStream's (RTT under the pipelined-relaxed
    ``hadoopbam.device.auto-rtt-ms``); ``HBAM_DEVICE_PARSE=1`` forces it
    on anyway.  ``sort_bam`` consults its own stream directly — this
    wrapper serves historical callers."""
    from .device_stream import DeviceStream

    return DeviceStream().default_device_parse()


def _device_parse_split(b: RecordBatch):
    """Upload (or donate) one split's record stream and launch the
    on-chip parse — the DeviceStream's inflate→parse seam
    (:meth:`~hadoop_bam_tpu.device_stream.DeviceStream.parse_split`);
    kept as a named pipeline helper for its historical callers."""
    from .device_stream import DeviceStream

    return DeviceStream().parse_split(b)


def _finish_device_parse(
    batches: List[RecordBatch], parsed: List[Optional[tuple]], n: int
):
    """Validate the device parse, patch unmapped keys, sort on-chip.

    One batched download fetches every split's ``[count, ok, n_unmapped]``
    triple (this is the sync point — all chain kernels have completed by
    now).  Returns a lazily-fetched permutation, or ``None`` if any split's
    device-derived record count disagrees with the host chain walk (caller
    rebuilds keys host-side).
    """
    from .ops.decode import patch_unmapped_keys

    if any(p is False for p in parsed):
        return None
    live = [(b, p) for b, p in zip(batches, parsed) if p is not None]
    if not live:
        return None
    meta = np.asarray(jnp.stack([p[3] for _, p in live]))
    counts, oks, unms = meta[:, 0], meta[:, 1], meta[:, 2]
    if not (
        np.all(oks == 1)
        and np.array_equal(counts, [b.n_records for b, _ in live])
    ):
        return None
    cat = lambda xs: xs[0] if len(xs) == 1 else jnp.concatenate(xs)
    hi_all = cat([p[0] for _, p in live])
    lo_all = cat([p[1] for _, p in live])
    if unms.sum():
        # Unmapped keys hash ragged record bytes (murmur3, host-side).
        # Patched once over the concatenated columns: one mask download,
        # one hash-column upload, one jit shape per job — not per split.
        unm_all = cat([p[2] for _, p in live])
        mask = np.asarray(unm_all)
        cols: List[np.ndarray] = []
        base = 0
        for b, _ in live:
            c = b.n_records
            cols.append(_unmapped_hash32(b, mask[base : base + c]))
            base += c
        hi_all, lo_all = patch_unmapped_keys(
            hi_all, lo_all, unm_all, jnp.asarray(np.concatenate(cols))
        )
    _, _, perm_dev = sort_keys(hi_all, lo_all)
    return _LazyPermFetch(perm_dev, n)


def _unmapped_hash32(b: RecordBatch, mask: np.ndarray) -> np.ndarray:
    """Host murmur3 hash column for a split's unmapped rows (others 0).

    Matches :func:`spec.bam.soa_keys`: the hash covers the record body past
    the 32 fixed bytes, seed 0, truncated to a signed int32.  All unmapped
    rows hash in one vectorized pass (``murmurhash3_int32_batch`` over the
    sliced offsets + a length column) — the per-record Python loop this
    replaces was O(records) interpreter work on the sort's hot path.
    """
    from .utils.murmur3 import murmurhash3_int32_batch

    h = np.zeros(len(mask), dtype=np.int32)
    rows = np.nonzero(mask)[0]
    if len(rows):
        off = np.asarray(b.soa["rec_off"], dtype=np.int64)[rows] + 32
        ln = np.maximum(
            np.asarray(b.soa["rec_len"], dtype=np.int64)[rows] - 32, 0
        )
        h[rows] = murmurhash3_int32_batch(b.data, off, ln, 0)
    return h


def _host_keys(b: RecordBatch) -> np.ndarray:
    """Rebuild a batch's sort keys from its retained raw bytes (oracle
    path; the device-parse fallback)."""
    soa = bam.soa_decode(
        b.data,
        np.asarray(b.soa["rec_off"], dtype=np.int64) - 4,
        fields=SORT_FIELDS,
    )
    return bam.soa_keys(soa, b.data)


def _read_splits_pipelined(
    fmt,
    splits,
    fields=None,
    depth: Optional[int] = None,
    with_keys: bool = True,
    errors: Optional[str] = None,
    stream=None,
):
    """Yield decoded split batches in order, double-buffered — the
    DeviceStream's split drive
    (:meth:`~hadoop_bam_tpu.device_stream.DeviceStream.read_splits`),
    kept as the pipeline's named entry point.  Depth resolves from the
    explicit argument → the ``hadoopbam.read.depth`` conf key → the
    ``HBAM_READ_DEPTH`` env var → 2 (measured neutral-to-positive even
    on the 1-core bench host, BENCH_NOTES.md), and is surfaced in the
    run manifest via the ``pipeline.read_depth`` gauge.

    Under ``errors="salvage"`` a split whose read fails outright (even
    the quarantining reader gave up — e.g. its header window is
    destroyed) degrades to an *empty batch* with a
    ``salvage.splits_failed`` counter instead of killing the job."""
    from .device_stream import DeviceStream

    if stream is None:
        stream = DeviceStream(conf=getattr(fmt, "conf", None), depth=depth)
    yield from stream.read_splits(
        fmt,
        splits,
        fields=fields,
        depth=depth,
        with_keys=with_keys,
        errors=errors,
    )


class _LazyPermFetch:
    """Device→host permutation download in lazily-awaited async groups.

    Slicing ``[lo:hi)`` materializes only the groups that cover the range
    (all groups' downloads are launched up front), so a part writer waiting
    on group g overlaps groups g+1.. with its own CPU work."""

    GROUPS = 4

    def __init__(self, perm_dev, n: int, groups: Optional[int] = None):
        k = max(1, min(groups or self.GROUPS, n))
        # Geometric group sizes (n/2^k, n/2^(k-1), …, n/2): the first wait
        # — which has had the least CPU work to hide behind — moves the
        # fewest bytes, and each later group downloads while the parts of
        # the groups before it deflate.
        self._bounds = [n >> (k - g) for g in range(k)] + [n]
        self._bounds[0] = 0
        self._parts: List = [
            perm_dev[self._bounds[g] : self._bounds[g + 1]]
            for g in range(k)
        ]
        for p in self._parts:
            p.copy_to_host_async()
        self._np: List[Optional[np.ndarray]] = [None] * k
        self._lock = threading.Lock()
        self.n = n

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, sl: slice) -> np.ndarray:
        lo, hi, step = sl.indices(self.n)
        assert step == 1
        g0 = max(0, int(np.searchsorted(self._bounds, lo, "right")) - 1)
        out: List[np.ndarray] = []
        for g in range(g0, len(self._parts)):
            b0, b1 = self._bounds[g], self._bounds[g + 1]
            if b0 >= hi:
                break
            if self._np[g] is None:
                with self._lock:
                    if self._np[g] is None:
                        from .utils.tracing import count_d2h

                        self._np[g] = np.asarray(self._parts[g])
                        count_d2h(self._np[g].nbytes, "perm")
                        self._parts[g] = None  # free the device buffer
            out.append(self._np[g][max(lo - b0, 0) : hi - b0])
        if not out:
            return np.empty(0, dtype=np.int64)
        return out[0] if len(out) == 1 else np.concatenate(out)


def _sort_perm(keys: np.ndarray, backend: str) -> np.ndarray:
    """Stable sort permutation of a key column — on-chip or NumPy oracle."""
    if backend == "device" and len(keys):
        from .ops.keys import split_keys_np

        hi, lo = split_keys_np(keys)
        _, _, perm = sort_keys(jnp.asarray(hi), jnp.asarray(lo))
        return np.asarray(perm).astype(np.int64)
    return np.argsort(keys, kind="stable")


def _sort_bam_external(
    fmt: BamInputFormat,
    splits,
    header,
    out_path: str,
    memory_budget: int,
    level: int,
    backend: str,
    write_splitting_bai: bool,
    max_attempts: int,
    part_dir: Optional[str],
    write_workers: Optional[int],
    device_deflate: bool = False,
    mark_duplicates: bool = False,
    device_write: bool = False,
    errors: str = "strict",
    attempt_timeout: Optional[float] = None,
    retry_backoff: float = 0.05,
    sort_order: str = "coordinate",
    key_column: Optional[np.ndarray] = None,
    deadline=None,
    stream=None,
) -> SortStats:
    """Bounded-memory sort: spill sorted runs, merge by exact key ranges.

    ``key_column`` (int64, global read order) overrides the per-record
    coordinate keys — the queryname path passes each record's
    precomputed output rank here, and the spill/range machinery runs
    unchanged over those unique keys.  ``sort_order`` rides into the
    spill manifest so a crash-resume never mixes checkpoints across
    orderings.

    Phase 1 streams splits in file order, accumulating decoded batches until
    the uncompressed budget fills, then sorts the chunk (device or host) and
    spills it as a :mod:`io.runs` run — raw sorted record stream plus
    memmappable key/offset sidebands.  Phase 2 partitions the global key
    space into ranges of ≤ budget bytes (exact, via the sorted sidebands —
    no sampling skew), loads each range's per-run slices, stable-sorts, and
    writes one part per range; parts concatenate in key order so the merge
    is the ordinary header + parts + terminator assembly.

    Peak materialized record bytes ≈ one budget's worth in each phase
    (reported in ``SortStats.peak_bytes``); everything else stays on disk
    behind memmaps.  Reference contract: the streaming record iterator
    (BAMRecordReader.java:223-232) + Hadoop's sort-spill-merge shuffle.

    With ``mark_duplicates``, runs carry a third sideband (each record's
    global read-order index) so the range-merge writes can address the
    job-global duplicate mask; the decision itself is identical to the
    in-core path's (same columns, same device program), so the two paths
    produce byte-identical marked output.

    **Crash-resume contract** (with a persistent ``part_dir``): a rerun
    after any mid-job death — including ``kill -9`` — trusts exactly two
    checkpoint classes.  Finished *final parts* (validated: non-empty +
    BGZF magic, so a torn ``os.replace`` race never survives a resume)
    are skipped by the executor as before.  Completed *spill phases* are
    certified by a manifest (:func:`io.runs.write_manifest`) written
    atomically only after every run is on disk: a valid manifest (input
    file identity, budget, markdup flag, per-run sideband sizes all
    matching) lets the rerun skip phase 1 entirely and re-derive the
    ranges from the runs — both deterministic, so the resumed output is
    byte-identical to an uninterrupted run.  Any mismatch silently redoes
    phase 1; checkpoints are an optimization, never trusted blindly.
    """
    from .io.bam import write_part_fast
    from .io.runs import (
        Run,
        input_identity,
        load_manifest,
        plan_ranges,
        write_manifest,
        write_run,
    )

    if mark_duplicates:
        from .dedup import DEDUP_EXTRA_FIELDS, signature_columns

        read_fields = tuple(
            dict.fromkeys(SORT_FIELDS + DEDUP_EXTRA_FIELDS)
        )
    else:
        read_fields = SORT_FIELDS

    with contextlib.ExitStack() as stack:
        out_dir = os.path.dirname(os.path.abspath(out_path)) or "."
        if part_dir is not None:
            td = part_dir
            os.makedirs(td, exist_ok=True)
        else:
            td = stack.enter_context(
                tempfile.TemporaryDirectory(dir=out_dir)
            )
        spill_dir = os.path.join(td, "spill")
        os.makedirs(spill_dir, exist_ok=True)

        # ---- Phase 0: crash-resume check ---------------------------------
        # With a persistent part_dir, a manifest left by a completed spill
        # phase (plus the dup-mask sideband when marking duplicates) lets
        # a rerun skip phase 1 and trust the runs as checkpoints.
        identity = None
        if part_dir is not None:
            try:
                identity = input_identity(
                    list(dict.fromkeys(s.path for s in splits))
                )
            except OSError:
                identity = None  # non-local inputs: no spill checkpointing
        dupmask_path = os.path.join(spill_dir, "dupmask.npy")
        manifest = (
            load_manifest(
                spill_dir, identity, memory_budget, mark_duplicates,
                sort_order=sort_order,
            )
            if identity is not None
            else None
        )
        if (
            manifest is not None
            and mark_duplicates
            and not os.path.exists(dupmask_path)
        ):
            manifest = None

        dup_mask = None
        n_dup = 0
        peak = 0
        if manifest is not None:
            n = int(manifest["n_records"])
            run_count = int(manifest["run_count"])
            METRICS.count("sort_bam.resume_spill_reused", 1)
            if mark_duplicates:
                dup_mask = np.load(dupmask_path)
                n_dup = int(dup_mask.sum())
        else:
            # ---- Phase 1: stream splits → sorted runs --------------------
            n = 0
            run_count = 0
            acc: List[RecordBatch] = []
            acc_bytes = 0
            sig_cols: List[dict] = []
            flushed_n = 0  # records already spilled (read-order index base)

            def flush() -> None:
                nonlocal run_count, acc, acc_bytes, peak, flushed_n
                if not acc:
                    return
                merged = ChunkedRecords.from_batches(acc)
                peak = max(peak, acc_bytes)
                perm = _sort_perm(merged.keys, backend)
                orig = None
                k = merged.n_records
                if mark_duplicates:
                    # Global read-order index of each spilled record: runs
                    # are flushed in read order, so this chunk covers
                    # exactly [flushed_n, flushed_n + k).
                    orig = np.arange(
                        flushed_n, flushed_n + k, dtype=np.int64
                    )
                write_run(spill_dir, run_count, merged, perm, orig_idx=orig)
                flushed_n += k
                run_count += 1
                acc = []
                acc_bytes = 0

            with span("sort_bam.spill"), _request_hop("pipeline.spill"):
                for b in _read_splits_pipelined(
                    fmt,
                    splits,
                    fields=read_fields,
                    with_keys=key_column is None,
                    errors=errors,
                    stream=stream,
                ):
                    if key_column is not None:
                        # Queryname ranks (or any precomputed key): the
                        # prepass indexed them by global read order.
                        b.keys = key_column[n : n + b.n_records]
                    if mark_duplicates:
                        with span("sort_bam.markdup_signature"):
                            sig_cols.append(
                                signature_columns(b.data, b.soa)
                            )
                    b.soa = {
                        "rec_off": b.soa["rec_off"],
                        "rec_len": b.soa["rec_len"],
                    }
                    # Spill runs live on disk, not in HBM: the out-of-core
                    # path cannot consume the inflate tier's residency
                    # handoff, so drop the device window per split — before
                    # this fix the refs silently pinned every split's
                    # inflated bytes in HBM until its run flushed.  The
                    # ledger audits this exact release (the PR 5 drill
                    # monkeypatches it away and asserts the named leak).
                    _release_split_residency(b)
                    n += b.n_records
                    if acc and acc_bytes + len(b.data) > memory_budget:
                        flush()
                    acc.append(b)
                    acc_bytes += len(b.data)
                    if acc_bytes >= memory_budget:
                        flush()
                flush()

            if mark_duplicates and n:
                from .dedup import concat_columns, mark_duplicates_device

                with span("sort_bam.markdup"):
                    dup_mask = mark_duplicates_device(
                        concat_columns(sig_cols)
                    )
                    n_dup = int(dup_mask.sum())
                sig_cols = []

            if identity is not None:
                # Checkpoint the completed spill phase.  Sidebands first,
                # manifest last (atomically): a manifest on disk certifies
                # everything it names.
                if dup_mask is not None:
                    tmp_dm = dupmask_path + ".tmp"
                    with open(tmp_dm, "wb") as f:
                        np.save(f, dup_mask)
                    os.replace(tmp_dm, dupmask_path)
                write_manifest(
                    spill_dir,
                    identity,
                    n_records=n,
                    run_count=run_count,
                    memory_budget=memory_budget,
                    mark_duplicates=mark_duplicates,
                    sort_order=sort_order,
                )
        METRICS.count("sort_bam.records", n)
        METRICS.count("sort_bam.splits", len(splits))
        METRICS.count("sort_bam.runs", run_count)
        if n_dup:
            METRICS.count("sort_bam.duplicates", n_dup)

        # ---- Phase 2: exact key-range merge ------------------------------
        if deadline is not None:
            # Phase boundary: the spill runs just written are durable
            # checkpoints, so expiring here loses nothing a resume can't
            # reuse — the cheapest possible place to stop.
            deadline.check("pipeline")
        runs = [Run.open(spill_dir, k) for k in range(run_count)]
        with span("sort_bam.plan_ranges"):
            ranges = plan_ranges(runs, memory_budget) if runs else []
        METRICS.count("sort_bam.ranges", len(ranges))

        # One range in flight at a time: each materializes up to a budget's
        # worth of record bytes, so any concurrency would multiply the peak
        # past the contract (write_workers is deliberately not honored
        # here; deflate threads provide the parallelism instead).
        executor = ElasticExecutor(
            td,
            max_attempts=max_attempts,
            max_workers=1,
            validate_part=bgzf_part_valid,
            quarantine=errors == "salvage",
            attempt_timeout=attempt_timeout,
            retry_backoff=retry_backoff,
            deadline=deadline,
        )
        deflate_threads = max(
            1, (os.cpu_count() or 4) // executor.max_workers
        )

        def write_one(pi: int, tmp: str) -> None:
            nonlocal peak
            cuts = ranges[pi]
            datas: List[np.ndarray] = []
            keys_l: List[np.ndarray] = []
            off_l: List[np.ndarray] = []
            len_l: List[np.ndarray] = []
            orig_l: List[np.ndarray] = []
            base = 0
            for r, (i0, i1) in enumerate(cuts):
                if i1 <= i0:
                    continue
                sl = runs[r].slice_stream(i0, i1)
                offs = np.asarray(
                    runs[r].offs[i0 : i1 + 1], dtype=np.int64
                )
                local = offs - offs[0]
                off_l.append(base + local[:-1] + 4)  # body starts
                len_l.append(np.diff(offs) - 4)
                keys_l.append(
                    np.asarray(runs[r].keys[i0:i1], dtype=np.int64)
                )
                if dup_mask is not None:
                    orig_l.append(
                        np.asarray(runs[r].orig_idx[i0:i1], dtype=np.int64)
                    )
                datas.append(sl)
                base += len(sl)
            if not datas:
                data = np.empty(0, np.uint8)
                keys = np.empty(0, np.int64)
                soa = {
                    "rec_off": np.empty(0, np.int64),
                    "rec_len": np.empty(0, np.int64),
                }
                dup_rows = None
            else:
                data = np.concatenate(datas)
                keys = np.concatenate(keys_l)
                soa = {
                    "rec_off": np.concatenate(off_l),
                    "rec_len": np.concatenate(len_l),
                }
                # Range rows → job-global duplicate mask, via the runs'
                # read-order index sideband.
                dup_rows = (
                    dup_mask[np.concatenate(orig_l)]
                    if dup_mask is not None
                    else None
                )
            peak = max(peak, len(data))
            batch = RecordBatch(soa=soa, data=data, keys=keys)
            # Slices are each sorted; the stable sort merges them, keeping
            # run order on ties — identical output to the one-shot sort.
            perm = _sort_perm(keys, backend)
            sb_stream = None
            try:
                if write_splitting_bai:
                    sb_stream = open(tmp + ".sb", "wb")
                with trace_ctx(part=pi), span(
                    "pipeline.stage.write_part", category="item"
                ), open(tmp, "wb") as f:
                    # device_write passes through even though range
                    # batches are rebuilt from disk and never carry
                    # residency: the per-part tier-down records its
                    # ``no_residency`` reason instead of the path
                    # silently taking the host gather.
                    write_part_fast(
                        f,
                        batch,
                        order=perm,
                        level=level,
                        splitting_bai_stream=sb_stream,
                        threads=deflate_threads,
                        device_deflate=device_deflate,
                        dup_mask=dup_rows,
                        device_write=device_write,
                        device_stream=stream,
                    )
            finally:
                if sb_stream is not None:
                    sb_stream.close()
            if write_splitting_bai:
                os.replace(
                    tmp + ".sb",
                    os.path.join(td, f"part-r-{pi:05d}.splitting-bai"),
                )

        with span("sort_bam.range_merge"), _request_hop("pipeline.range_merge"):
            executor.run(list(range(max(1, len(ranges)))), write_one
                         if ranges else _write_empty_part)
            merge_bam_parts(
                td, out_path, header,
                write_splitting_bai=write_splitting_bai,
            )
    return SortStats(
        n_records=n,
        n_splits=len(splits),
        backend=f"external[{backend}]",
        n_runs=run_count,
        n_ranges=len(ranges),
        peak_bytes=peak,
        n_duplicates=n_dup,
    )


def _write_empty_part(pi: int, tmp: str) -> None:
    with open(tmp, "wb"):
        pass
