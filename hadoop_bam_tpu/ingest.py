"""Lane-speed FASTQ ingest: ``fastq[.gz] → queryname-collated uBAM``.

The unaligned front door (ROADMAP #5).  One job rides the existing
device machinery end to end:

- **Inflate**: gzip/BGZF members from the FASTQ inputs decode through
  ``DeviceStream.decode_members`` — the fourth stream client, the
  ``BGZFEnhancedGzipCodec`` stance.  A BGZF-style .fastq.gz yields its
  exact member table from the header scan; plain multi-member gzip is
  probed host-side and every member whose deflate payload fits a BGZF
  frame is *repacked by pure header byte-rewrite* (gzip and BGZF share
  the deflate body and CRC32/ISIZE trailer) so it rides the lanes in
  ≤64 KiB units; oversized members tier down to host zlib per member.
  Counted under ``ingest.inflate.*``.
- **Scan**: decoded runs re-chunk into claim regions for the
  ``ops/pallas/record_scan`` kernel (tier-down per chunk to the NumPy
  host scan, the serial walker beneath both); the per-run record tables
  are reconciled by extent tiling — any gap falls back to the walker.
- **Collate**: queryname order comes from the PR 9 collate engine
  (murmur3 name-hash pair grouping, ``strnum_cmp`` verification against
  the actual name bytes) over columns built straight from the id lines.
- **Write**: records emit through the device write path
  (``DeviceStream.deflate_stream``) with member cuts at fixed absolute
  payload offsets, so the in-core, ``memory_budget`` (spill + k-way
  rank merge), and ``errors=salvage`` paths are all byte-identical to
  :func:`ingest_oracle`, the pure-host reference.

Salvage semantics: a corrupt member quarantines *whole records* — runs
break at the gap, the tail frame of the pre-gap run and the torn head
of the post-gap run are dropped by the two-record resync, and a 4-line
frame is never torn.  Unequal R1/R2 record counts raise in strict mode
and quarantine the tail in salvage.
"""

from __future__ import annotations

import contextlib
import heapq
import os
import struct
import tempfile
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .collate.device import collate_by_name
from .collate.host import collation_counts, natural_sort_key, queryname_perm
from .collate.signature import QNAME_SEED2
from .conf import (
    ERRORS_MODE,
    FASTQ_BASE_QUALITY_ENCODING,
    FASTQ_FILTER_FAILED_QC,
    INGEST_CHUNK_BYTES,
    INGEST_DEVICE_SCAN,
    INGEST_SCAN_OVERLAP,
    INPUT_BASE_QUALITY_ENCODING,
    INPUT_FILTER_FAILED_QC,
)
from .device_stream import DeviceStream
from .io.fastq import ILLUMINA_PATTERN
from .ops.pallas.record_scan import (
    WindowOverrun,
    record_scan,
    scan_window_host,
    scan_window_py,
)
from .spec import bgzf
from .spec.bam import BamHeader, build_record
from .spec.fragment import (
    ILLUMINA_MAX,
    ILLUMINA_OFFSET,
    SANGER_MAX,
    SANGER_OFFSET,
    FormatException,
)
from .utils.murmur3 import murmurhash3_int32_batch
from .utils.tracing import METRICS, current_request, span

#: uBAM flags: PAIRED|UNMAP|MUNMAP plus READ1/READ2, or plain UNMAP.
FLAG_R1 = 0x4D
FLAG_R2 = 0x8D
FLAG_SINGLE = 0x4

#: Default claim region per scan chunk — the device inflate payload, so
#: one decoded member is one scan chunk on the common path.
DEFAULT_CHUNK_BYTES = 0xDF00
DEFAULT_SCAN_OVERLAP = 2048

#: BGZF member payload cut for the uBAM write path (spec MAX_PAYLOAD).
_BLOCK_PAYLOAD = 0xFF00

_GZ_MAGIC = b"\x1f\x8b\x08"


@contextlib.contextmanager
def _hop(name: str, **extras):
    """One waterfall hop on the ambient request context (a serve ingest
    job's trace shows decode/scan/collate/write durations); batch mode —
    no ambient context — is the disarmed ``is None`` branch."""
    rctx = current_request()
    if rctx is None:
        yield
        return
    import time as _time

    t0 = _time.perf_counter()
    try:
        yield
    finally:
        rctx.annotate(name, ms=(_time.perf_counter() - t0) * 1e3, **extras)


@dataclass
class IngestStats:
    """What one ingest job did, and what salvage cost."""

    n_records: int = 0
    n_pairs: int = 0
    n_singletons: int = 0
    n_orphans: int = 0
    n_members: int = 0
    n_repacked: int = 0
    n_host_members: int = 0
    n_quarantined_members: int = 0
    n_quarantined_frames: int = 0
    n_tail_records: int = 0
    n_filtered: int = 0
    scan_chunks: int = 0
    scan_lanes: int = 0
    scan_host: int = 0
    scan_serial: int = 0
    out_bytes: int = 0

    def merge_input(self, other: "IngestStats") -> None:
        for f in (
            "n_members", "n_repacked", "n_host_members",
            "n_quarantined_members", "n_quarantined_frames",
            "n_filtered", "scan_chunks", "scan_lanes", "scan_host",
            "scan_serial",
        ):
            setattr(self, f, getattr(self, f) + getattr(other, f))


# ---------------------------------------------------------------------------
# Member tables and the inflate-lane decode


@dataclass
class _Member:
    """One compressed member: device extents into ``dev_buf`` when it
    can ride the lanes, else raw extents for the per-member host tier.
    ``usize`` is None for a corrupt/unparseable gap (salvage only)."""

    usize: Optional[int]
    dev: Optional[Tuple[int, int]] = None    # (coffset, csize) in dev_buf
    raw: Optional[Tuple[int, int]] = None    # (offset, csize) in the input


def _gzip_header_len(buf: bytes, off: int) -> int:
    if buf[off: off + 3] != _GZ_MAGIC:
        raise FormatException("not a gzip member at offset %d" % off)
    flg = buf[off + 3]
    p = off + 10
    if flg & 4:
        xlen = buf[p] | (buf[p + 1] << 8)
        p += 2 + xlen
    if flg & 8:
        p = buf.index(b"\x00", p) + 1
    if flg & 16:
        p = buf.index(b"\x00", p) + 1
    if flg & 2:
        p += 2
    return p - off


def _bgzf_repack(buf: bytes, off: int, csize: int) -> Optional[bytes]:
    """A plain gzip member rewritten as one valid BGZF member — header
    swap only, the deflate body and CRC32/ISIZE trailer are byte-shared
    between the formats.  None when the member doesn't fit a BGZF frame
    (BSIZE u16, payload < 64 KiB): that member decodes on the host."""
    hdr = _gzip_header_len(buf, off)
    body = csize - hdr - 8
    total = 18 + body + 8
    if body < 0 or total - 1 > 0xFFFF:
        return None
    isize = struct.unpack_from("<I", buf, off + csize - 4)[0]
    if isize > 0xFFFF:
        return None
    return (
        bgzf.MAGIC
        + b"\x00\x00\x00\x00\x00\xff\x06\x00BC\x02\x00"
        + struct.pack("<H", total - 1)
        + buf[off + hdr: off + csize]
    )


def _member_table(
    data: bytes, errors: str, stats: IngestStats
) -> Tuple[List[_Member], bytes]:
    """Per-member decode plan for one input, plus the device buffer the
    ``dev`` extents index (the input itself for BGZF, the repacked
    synthetic stream for plain gzip, empty for uncompressed text)."""
    if not data.startswith(b"\x1f\x8b"):
        return [], b""   # uncompressed: one plain run, no members
    members: List[_Member] = []
    if bgzf.is_bgzf(data):
        pos = 0
        while pos < len(data):
            hdr = bgzf.parse_block_header(data, pos)
            if hdr is None:
                if errors != "salvage":
                    raise FormatException(
                        "corrupt BGZF member chain at offset %d" % pos
                    )
                nxt = bgzf.find_next_block(data, pos + 1)
                members.append(_Member(usize=None))
                stats.n_quarantined_members += 1
                METRICS.count("salvage.ingest_members", 1)
                if nxt is None:
                    break
                pos = nxt[0]
                continue
            bsize, _ = hdr
            usize = struct.unpack_from("<I", data, pos + bsize - 4)[0]
            members.append(_Member(usize=usize, dev=(pos, bsize)))
            pos += bsize
        return members, data

    # Plain multi-member gzip: host probe for extents, then repack
    # eligible members into synthetic BGZF units for the lanes.
    repacked = bytearray()
    pos = 0
    while pos < len(data):
        d = zlib.decompressobj(31)
        try:
            out = d.decompress(data[pos:])
            if not d.eof:
                raise zlib.error("truncated gzip member")
        except zlib.error:
            if errors != "salvage":
                raise FormatException(
                    "corrupt gzip member at offset %d" % pos
                )
            members.append(_Member(usize=None))
            stats.n_quarantined_members += 1
            METRICS.count("salvage.ingest_members", 1)
            nxt = data.find(_GZ_MAGIC, pos + 3)
            if nxt < 0:
                break
            pos = nxt
            continue
        csize = (len(data) - pos) - len(d.unused_data)
        syn = _bgzf_repack(data, pos, csize)
        if syn is not None and len(out) <= 0xFFFF:
            members.append(
                _Member(usize=len(out), dev=(len(repacked), len(syn)))
            )
            repacked += syn
            stats.n_repacked += 1
            METRICS.count("ingest.inflate.repacked", 1)
        else:
            members.append(_Member(usize=len(out), raw=(pos, csize)))
            stats.n_host_members += 1
            METRICS.count("ingest.inflate.host_members", 1)
        pos += csize
    return members, bytes(repacked)


def _decode_input(
    data: bytes, stream: DeviceStream, errors: str, stats: IngestStats
) -> List[Optional[bytes]]:
    """Decode one input into per-member payloads in stream order, with
    ``None`` gaps for quarantined members (salvage only).  Uncompressed
    inputs come back as a single payload."""
    members, dev_buf = _member_table(data, errors, stats)
    if not members:
        return [data]
    stats.n_members += len(members)
    METRICS.count("ingest.inflate.members", len(members))
    dev_idx = [i for i, m in enumerate(members) if m.dev is not None]
    payloads: List[Optional[bytes]] = [None] * len(members)
    if dev_idx:
        co = np.asarray([members[i].dev[0] for i in dev_idx], np.int64)
        cs = np.asarray([members[i].dev[1] for i in dev_idx], np.int64)
        us = np.asarray([members[i].usize for i in dev_idx], np.int64)
        try:
            out, offs = stream.decode_members(
                np.frombuffer(dev_buf, np.uint8), co, cs, us
            )
            blob = np.asarray(out).tobytes()
            for k, i in enumerate(dev_idx):
                payloads[i] = blob[int(offs[k]): int(offs[k + 1])]
        except Exception:
            if errors != "salvage":
                raise
            for i in dev_idx:
                off, _ = members[i].dev
                try:
                    payloads[i], _ = bgzf.inflate_block(dev_buf, off)
                except Exception:
                    members[i].usize = None
                    stats.n_quarantined_members += 1
                    METRICS.count("salvage.ingest_members", 1)
    for i, m in enumerate(members):
        if m.raw is not None:
            off, csize = m.raw
            try:
                payloads[i] = zlib.decompress(
                    data[off: off + csize], 31
                )
            except zlib.error:
                if errors != "salvage":
                    raise FormatException(
                        "corrupt gzip member at offset %d" % off
                    )
                m.usize = None
                stats.n_quarantined_members += 1
                METRICS.count("salvage.ingest_members", 1)
    decoded = sum(len(p) for p in payloads if p is not None)
    METRICS.count("ingest.inflate.bytes", decoded)
    return payloads


def _runs_of(payloads: List[Optional[bytes]]) -> List[Tuple[bytes, bool]]:
    """Contiguous decoded runs between quarantine gaps, each tagged
    aligned (True only for the stream head: a post-gap run resyncs)."""
    runs: List[Tuple[bytes, bool]] = []
    cur: List[bytes] = []
    aligned = True
    for p in payloads:
        if p is None:
            if cur:
                runs.append((b"".join(cur), aligned))
                cur = []
            aligned = False
            continue
        cur.append(p)
    if cur:
        runs.append((b"".join(cur), aligned))
    return runs


# ---------------------------------------------------------------------------
# The record scan: device kernel → host scan → serial walker


def _scan_run(
    run: bytes,
    aligned: bool,
    chunk_bytes: int,
    overlap: int,
    device: bool,
    errors: str,
    stats: IngestStats,
) -> np.ndarray:
    """Record table ``[n, 8]`` (run-absolute offsets) for one decoded
    run, via the tier ladder, with run-tiling reconciliation."""
    if not run:
        return np.zeros((0, 8), np.int32)
    chunks = []
    offs = []
    for off in range(0, len(run), chunk_bytes):
        win = run[off: off + chunk_bytes + overlap]
        chunks.append((
            win,
            min(chunk_bytes, len(run) - off),
            aligned and off == 0,
            off + len(win) >= len(run),
        ))
        offs.append(off)
    stats.scan_chunks += len(chunks)
    METRICS.count("fastq.scan.chunks", len(chunks))

    tables: List[Optional[np.ndarray]] = [None] * len(chunks)
    if device:
        tables, kstats = record_scan(chunks)
        stats.scan_lanes += kstats.lanes
        METRICS.count("fastq.scan.lanes", kstats.lanes)

    def serial() -> np.ndarray:
        stats.scan_serial += 1
        METRICS.count("fastq.scan.serial_fallback", 1)
        tab, n_quar = scan_window_py(
            run, len(run), aligned, True, salvage=(errors == "salvage")
        )
        if n_quar:
            stats.n_quarantined_frames += n_quar
            METRICS.count("salvage.ingest_frames", n_quar)
        return tab

    try:
        for k, (win, cl, al, fin) in enumerate(chunks):
            if tables[k] is None:
                stats.scan_host += 1
                METRICS.count("fastq.scan.host", 1)
                tables[k] = scan_window_host(win, cl, al, fin)
    except WindowOverrun:
        return serial()
    except FormatException:
        if errors != "salvage":
            raise
        return serial()

    parts = [t + np.int32(o) * np.array([1, 0] * 4, np.int32)
             for t, o in zip(tables, offs) if len(t)]
    table = (np.concatenate(parts) if parts
             else np.zeros((0, 8), np.int32))

    # Tiling reconciliation: consecutive records must abut (one LF or
    # CRLF apart) and an aligned run must start at offset 0 — a gap
    # means a chunk silently lost a record, so the walker decides.
    ok = True
    if len(table):
        qual_end = table[:-1, 6] + table[:-1, 7]
        sep = table[1:, 0].astype(np.int64) - qual_end.astype(np.int64)
        ok = bool(((sep >= 1) & (sep <= 2)).all())
        last_end = int(table[-1, 6] + table[-1, 7])
        ok = ok and (len(run) - last_end) in (0, 1, 2)
        if aligned:
            ok = ok and int(table[0, 0]) == 0
    elif aligned and len(run):
        ok = False
    if not ok:
        METRICS.count("fastq.scan.reconciled", 1)
        return serial()
    return table


# ---------------------------------------------------------------------------
# Columns: ids, qualities, flags


@dataclass
class _InputColumns:
    """Per-input record columns in stream order; seq/qual stay as
    offsets into the decoded runs (payloads bounded, columns in
    memory)."""

    runs: List[bytes] = field(default_factory=list)
    run_idx: List[int] = field(default_factory=list)
    table: List[np.ndarray] = field(default_factory=list)  # per-run [n,8]
    qnames: List[str] = field(default_factory=list)
    reads: List[int] = field(default_factory=list)         # 0 = unnumbered

    def __len__(self) -> int:
        return len(self.qnames)

    def record_bytes(self, i: int) -> Tuple[bytes, bytes, bytes]:
        """(id line sans '@', seq, qual) raw bytes of record ``i``."""
        run = self.runs[self.run_idx[i]]
        row = self.table[i]
        return (
            run[row[0] + 1: row[0] + row[1]],
            run[row[2]: row[2] + row[3]],
            run[row[6]: row[6] + row[7]],
        )


def _parse_id(name: str, look_for_illumina: bool):
    """(qname, read, filter_passed, still_illumina): the reference's
    stateful Illumina-then-``/N`` id chain, shared with
    ``io.fastq._fastq_materializer``."""
    read = 0
    filter_passed = None
    if look_for_illumina:
        m = ILLUMINA_PATTERN.fullmatch(name)
        if m:
            return (name.split(None, 1)[0], int(m.group(8)),
                    m.group(9) == "N", True)
        look_for_illumina = False
    qname = name.split(None, 1)[0] if name else ""
    if len(qname) >= 2 and qname[-2] == "/" and qname[-1].isdigit():
        read = int(qname[-1])
        qname = qname[:-2]
    return qname, read, filter_passed, look_for_illumina


def _scan_input(
    data: bytes,
    stream: DeviceStream,
    conf,
    errors: str,
    chunk_bytes: int,
    overlap: int,
    device: bool,
    filter_failed: bool,
) -> Tuple[_InputColumns, IngestStats]:
    """Decode + scan + id-parse one input into stream-order columns."""
    stats = IngestStats()
    with span("ingest.stage.decode", category="stage"), \
            _hop("ingest.decode"):
        payloads = _decode_input(data, stream, errors, stats)
        runs = _runs_of(payloads)
    cols = _InputColumns()
    look = True
    with span("ingest.stage.scan", category="stage"), _hop("ingest.scan"):
        for run, aligned in runs:
            table = _scan_run(
                run, aligned, chunk_bytes, overlap, device, errors, stats
            )
            r = len(cols.runs)
            cols.runs.append(run)
            for row in table:
                name = run[row[0] + 1: row[0] + row[1]].decode(
                    "latin-1"
                )
                qname, read, fpass, look = _parse_id(name, look)
                if filter_failed and fpass is False:
                    stats.n_filtered += 1
                    continue
                cols.run_idx.append(r)
                cols.table.append(row)
                cols.qnames.append(qname)
                cols.reads.append(read)
    return cols, stats


def _sanger_quals(cols: _InputColumns, encoding: str) -> List[bytes]:
    """Per-record Sanger-encoded quality bytes, verified (sanger input)
    or range-checked ±31 shifted (illumina input) — the read_split
    stance, vectorized per run would be overkill here: qualities stream
    straight into the record encoder."""
    out = []
    if encoding == "illumina":
        lo, hi = ILLUMINA_OFFSET, ILLUMINA_OFFSET + ILLUMINA_MAX
    elif encoding == "sanger":
        lo, hi = SANGER_OFFSET, SANGER_OFFSET + SANGER_MAX
    else:
        raise ValueError(f"Unsupported base quality encoding {encoding}")
    for i in range(len(cols)):
        _, _, qual = cols.record_bytes(i)
        a = np.frombuffer(qual, np.uint8)
        if len(a) and (int(a.min()) < lo or int(a.max()) > hi):
            raise FormatException(
                "base quality score out of range for %s encoding in "
                "record %r" % (encoding, cols.qnames[i])
            )
        if encoding == "illumina":
            a = (a.astype(np.int16)
                 - (ILLUMINA_OFFSET - SANGER_OFFSET)).astype(np.uint8)
        out.append(a.tobytes())
    return out


# ---------------------------------------------------------------------------
# The blocked uBAM writer (byte-stable member cuts)


class _BlockedUbamWriter:
    """BGZF writer with member cuts at fixed absolute payload offsets:
    compression only ever sees exact multiples of ``block_payload``
    (remainder buffered), so output bytes are independent of how the
    caller batches writes — the in-core, spill-merge, and oracle paths
    produce identical files."""

    def __init__(self, fh, stream: Optional[DeviceStream], level: int,
                 block_payload: int = _BLOCK_PAYLOAD):
        self._fh = fh
        self._stream = stream
        self._level = level
        self._bp = block_payload
        self._buf = bytearray()
        self.out_bytes = 0

    def _deflate(self, payload: bytes) -> bytes:
        if self._stream is not None:
            return self._stream.deflate_stream(
                payload, level=self._level, block_payload=self._bp
            )
        from . import native

        return native.deflate_blocks(
            payload, level=self._level, block_payload=self._bp
        )

    def write(self, b: bytes) -> None:
        self._buf += b
        cut = (len(self._buf) // self._bp) * self._bp
        if cut:
            comp = self._deflate(bytes(self._buf[:cut]))
            del self._buf[:cut]
            self._fh.write(comp)
            self.out_bytes += len(comp)

    def close(self) -> None:
        if self._buf:
            comp = self._deflate(bytes(self._buf))
            self._buf.clear()
            self._fh.write(comp)
            self.out_bytes += len(comp)
        self._fh.write(bgzf.TERMINATOR)
        self.out_bytes += len(bgzf.TERMINATOR)


_UBAM_HEADER_TEXT = "@HD\tVN:1.6\tSO:queryname\n"


def _encode_record(qname: str, flag: int, seq: bytes, qual: bytes) -> bytes:
    rec = build_record(
        name=qname, refid=-1, pos=-1, mapq=0, flag=flag, cigar=[],
        seq=seq.decode("latin-1"), qual=qual.decode("latin-1"),
    )
    return rec.encode()


# ---------------------------------------------------------------------------
# The front door


def ingest_fastq(
    fastq: Union[str, Sequence[str]],
    output: str,
    r2: Optional[str] = None,
    conf=None,
    level: int = 6,
    memory_budget: Optional[int] = None,
    part_dir: Optional[str] = None,
    errors: Optional[str] = None,
    chunk_bytes: Optional[int] = None,
    overlap: Optional[int] = None,
    deadline=None,
    resource_cache=None,
) -> IngestStats:
    """Ingest FASTQ (optionally gzip/BGZF compressed, optionally paired
    R1/R2) into a queryname-collated unaligned BAM at ``output``.

    ``memory_budget`` bounds the record-assembly working set: encoded
    records spill in rank-tagged runs and k-way merge back — the output
    is byte-identical to the in-core path.  ``errors="salvage"``
    quarantines corrupt members and torn frames instead of aborting.
    """
    if isinstance(fastq, (list, tuple)):
        paths = list(fastq)
        r1_path = paths[0]
        if len(paths) > 1 and r2 is None:
            r2 = paths[1]
    else:
        r1_path = fastq
    errors = errors or (
        (conf.get(ERRORS_MODE, "strict") if conf is not None else "strict")
        or "strict"
    )
    if errors not in ("strict", "salvage"):
        raise ValueError(f"unknown errors mode: {errors}")
    cget = (lambda k, d=None: conf.get(k, d)) if conf is not None \
        else (lambda k, d=None: d)
    if chunk_bytes is None:
        chunk_bytes = int(cget(INGEST_CHUNK_BYTES, DEFAULT_CHUNK_BYTES)
                          or DEFAULT_CHUNK_BYTES)
    if overlap is None:
        overlap = int(cget(INGEST_SCAN_OVERLAP, DEFAULT_SCAN_OVERLAP)
                      or DEFAULT_SCAN_OVERLAP)
    stream = DeviceStream(conf=conf, deadline=deadline, name="ingest")
    dev_conf = str(cget(INGEST_DEVICE_SCAN, "") or "").lower()
    device = (dev_conf == "true") if dev_conf in ("true", "false") \
        else stream.policy.inflate_lanes
    encoding = str(
        cget(FASTQ_BASE_QUALITY_ENCODING,
             cget(INPUT_BASE_QUALITY_ENCODING, "sanger")) or "sanger"
    )
    filter_failed = str(
        cget(FASTQ_FILTER_FAILED_QC,
             cget(INPUT_FILTER_FAILED_QC, "false")) or "false"
    ).lower() == "true"

    stats = IngestStats()
    inputs: List[_InputColumns] = []
    for path in [r1_path] + ([r2] if r2 else []):
        with open(path, "rb") as fh:
            data = fh.read()
        cols, istats = _scan_input(
            data, stream, conf, errors, chunk_bytes, overlap, device,
            filter_failed,
        )
        stats.merge_input(istats)
        inputs.append(cols)

    paired_files = r2 is not None
    if paired_files and len(inputs[0]) != len(inputs[1]):
        n1, n2 = len(inputs[0]), len(inputs[1])
        if errors != "salvage":
            raise FormatException(
                "paired FASTQ inputs have unequal record counts "
                f"({n1} vs {n2})"
            )
        lo = min(n1, n2)
        stats.n_tail_records += (n1 - lo) + (n2 - lo)
        METRICS.count("salvage.ingest_tail_records", (n1 - lo) + (n2 - lo))
        for cols in inputs:
            del cols.qnames[lo:], cols.reads[lo:]
            del cols.run_idx[lo:], cols.table[lo:]

    # Global record list in read order: R1 stream then R2 stream (the
    # collation owns interleaving them back into queryname order).
    qnames: List[str] = []
    flags: List[int] = []
    src: List[Tuple[int, int]] = []
    for fi, cols in enumerate(inputs):
        default_read = fi + 1 if paired_files else 0
        for i in range(len(cols)):
            read = cols.reads[i] or default_read
            flags.append(
                FLAG_SINGLE if read == 0
                else (FLAG_R2 if read == 2 else FLAG_R1)
            )
            qnames.append(cols.qnames[i])
            src.append((fi, i))
    n = len(qnames)
    stats.n_records = n
    METRICS.count("ingest.records", n)

    with span("ingest.stage.collate", category="stage"), \
            _hop("ingest.collate"):
        name_bytes = [q.encode("latin-1") for q in qnames]
        blob = np.frombuffer(b"".join(name_bytes), np.uint8)
        name_len = np.asarray([len(b) for b in name_bytes], np.int32)
        name_off = np.zeros(n, np.int64)
        if n:
            np.cumsum(name_len[:-1], out=name_off[1:])
        flag_col = np.asarray(flags, np.int32)
        cols = {
            "qh1": murmurhash3_int32_batch(
                blob, name_off, name_len.astype(np.int64), 0
            ),
            "qh2": murmurhash3_int32_batch(
                blob, name_off, name_len.astype(np.int64), QNAME_SEED2
            ),
            "flag": flag_col,
            "pos": np.full(n, -1, np.int32),
            "cand": ((flag_col & 0x1) != 0).astype(np.int32),
            "name_len": name_len,
            "name_off": name_off,
            "names": blob,
        }
        perm, _ = queryname_perm(cols)
        census = collation_counts(cols, collate_by_name(cols))
        stats.n_pairs = int(census["pairs"])
        stats.n_singletons = int(census["singletons"])
        stats.n_orphans = int(census["orphans"])
        METRICS.count("ingest.pairs", stats.n_pairs)
        METRICS.count("ingest.orphans", stats.n_orphans)

    quals = [_sanger_quals(cols, encoding) for cols in inputs]

    def record_payload(i: int) -> bytes:
        fi, ri = src[i]
        _, seq, _ = inputs[fi].record_bytes(ri)
        return _encode_record(qnames[i], flags[i], seq, quals[fi][ri])

    header = BamHeader(_UBAM_HEADER_TEXT, []).with_sort_order("queryname")
    with span("ingest.stage.write", category="stage"), \
            _hop("ingest.write"), open(output, "wb") as fh:
        w = _BlockedUbamWriter(fh, stream, level)
        w.write(header.encode())
        if memory_budget is None:
            for i in perm:
                w.write(record_payload(int(i)))
        else:
            _spill_merge(
                w, record_payload, perm, n, memory_budget, part_dir
            )
        w.close()
        stats.out_bytes = w.out_bytes
    METRICS.count("ingest.out_bytes", stats.out_bytes)
    return stats


def _spill_merge(w, record_payload, perm, n, memory_budget, part_dir):
    """Budget-bounded emission: encode records in read order into
    rank-sorted spill runs of at most ``memory_budget`` bytes, then
    k-way merge the runs by rank — the same record order, hence the
    same bytes, as the in-core path."""
    rank = np.empty(n, np.int64)
    rank[perm] = np.arange(n, dtype=np.int64)
    with contextlib.ExitStack() as stack:
        if part_dir is None:
            spill_dir = stack.enter_context(
                tempfile.TemporaryDirectory(prefix="hbam-ingest-")
            )
        else:
            os.makedirs(part_dir, exist_ok=True)
            spill_dir = part_dir
        run_paths: List[str] = []
        batch: List[Tuple[int, bytes]] = []
        batch_bytes = 0

        def flush():
            nonlocal batch, batch_bytes
            if not batch:
                return
            batch.sort(key=lambda t: t[0])
            path = os.path.join(
                spill_dir, "ingest-run-%05d.bin" % len(run_paths)
            )
            with open(path, "wb") as rf:
                for rk, payload in batch:
                    rf.write(struct.pack("<qI", rk, len(payload)))
                    rf.write(payload)
            run_paths.append(path)
            batch = []
            batch_bytes = 0

        for i in range(n):
            payload = record_payload(i)
            batch.append((int(rank[i]), payload))
            batch_bytes += len(payload)
            if batch_bytes >= max(memory_budget, 1):
                flush()
        flush()

        def reader(path):
            with open(path, "rb") as rf:
                while True:
                    hdr = rf.read(12)
                    if not hdr:
                        return
                    rk, ln = struct.unpack("<qI", hdr)
                    yield rk, rf.read(ln)

        for _, payload in heapq.merge(
            *[reader(p) for p in run_paths], key=lambda t: t[0]
        ):
            w.write(payload)


# ---------------------------------------------------------------------------
# The pure-host oracle


def ingest_oracle(
    fastq: Union[str, Sequence[str]],
    output: str,
    r2: Optional[str] = None,
    conf=None,
    level: int = 6,
    errors: Optional[str] = None,
) -> int:
    """Reference ingest: python-gzip decode, serial two-record-resync
    parse, python natural sort — no kernels, no collate engine, no
    device stream.  Shares only the spec-level byte encoders
    (``build_record`` and the blocked member cuts) so byte-identity is a
    meaningful check of the device path.  Returns the record count."""
    if isinstance(fastq, (list, tuple)):
        paths = list(fastq)
        r1_path = paths[0]
        if len(paths) > 1 and r2 is None:
            r2 = paths[1]
    else:
        r1_path = fastq
    errors = errors or (
        (conf.get(ERRORS_MODE, "strict") if conf is not None else "strict")
        or "strict"
    )
    cget = (lambda k, d=None: conf.get(k, d)) if conf is not None \
        else (lambda k, d=None: d)
    encoding = str(
        cget(FASTQ_BASE_QUALITY_ENCODING,
             cget(INPUT_BASE_QUALITY_ENCODING, "sanger")) or "sanger"
    )
    filter_failed = str(
        cget(FASTQ_FILTER_FAILED_QC,
             cget(INPUT_FILTER_FAILED_QC, "false")) or "false"
    ).lower() == "true"

    def decode(path):
        with open(path, "rb") as fh:
            data = fh.read()
        if not data.startswith(b"\x1f\x8b"):
            return [data]
        chunks: List[Optional[bytes]] = []
        pos = 0
        while pos < len(data):
            d = zlib.decompressobj(31)
            try:
                out = d.decompress(data[pos:])
                if not d.eof:
                    raise zlib.error("truncated member")
            except zlib.error:
                if errors != "salvage":
                    raise FormatException(
                        "corrupt gzip member at offset %d" % pos
                    )
                chunks.append(None)
                nxt = data.find(_GZ_MAGIC, pos + 3)
                if nxt < 0:
                    break
                pos = nxt
                continue
            chunks.append(out)
            pos += (len(data) - pos) - len(d.unused_data)
        return chunks

    def lines_of(run):
        out = []
        pos = 0
        while pos < len(run):
            nl = run.find(b"\n", pos)
            if nl < 0:
                nl = len(run)
            line = run[pos:nl]
            if line.endswith(b"\r"):
                line = line[:-1]
            out.append(line)
            pos = nl + 1
        return out

    def parse_run(run, aligned):
        lines = lines_of(run)

        def frame(i):
            if i + 3 >= len(lines):
                return None
            return (lines[i][:1] == b"@" and lines[i + 2][:1] == b"+"
                    and len(lines[i + 1]) == len(lines[i + 3]))

        i = 0
        if not aligned:
            while i < len(lines):
                fa = frame(i)
                if fa is None:
                    i = len(lines)
                    break
                if fa and (frame(i + 4) or frame(i + 4) is None):
                    break
                i += 1
        recs = []
        while i < len(lines):
            fr = frame(i)
            if fr:
                recs.append((lines[i][1:], lines[i + 1], lines[i + 3]))
                i += 4
                continue
            if errors != "salvage":
                raise FormatException(
                    "fastq: %s in record %d" % (
                        "truncated record" if fr is None
                        else "frame violation", len(recs),
                    )
                )
            if fr is None:
                break
            i += 1
            while i < len(lines):
                fa = frame(i)
                if fa is None:
                    i = len(lines)
                    break
                if fa and (frame(i + 4) or frame(i + 4) is None):
                    break
                i += 1
        return recs

    def parse_input(path):
        recs = []
        aligned = True
        pending: List[bytes] = []
        for chunk in decode(path):
            if chunk is None:
                if pending:
                    recs.extend(parse_run(b"".join(pending), aligned))
                    pending = []
                aligned = False
                continue
            pending.append(chunk)
        if pending:
            recs.extend(parse_run(b"".join(pending), aligned))
        out = []
        look = True
        for name_b, seq, qual in recs:
            name = name_b.decode("latin-1")
            qname, read, fpass, look = _parse_id(name, look)
            if filter_failed and fpass is False:
                continue
            if encoding == "illumina":
                a = np.frombuffer(qual, np.uint8)
                if len(a) and (int(a.min()) < ILLUMINA_OFFSET
                               or int(a.max()) > ILLUMINA_OFFSET
                               + ILLUMINA_MAX):
                    raise FormatException(
                        "base quality score out of range"
                    )
                qual = (a.astype(np.int16) - (ILLUMINA_OFFSET
                        - SANGER_OFFSET)).astype(np.uint8).tobytes()
            else:
                a = np.frombuffer(qual, np.uint8)
                if len(a) and (int(a.min()) < SANGER_OFFSET
                               or int(a.max()) > SANGER_OFFSET
                               + SANGER_MAX):
                    raise FormatException(
                        "base quality score out of range"
                    )
            out.append((qname, read, seq, qual))
        return out

    paired = r2 is not None
    records = []
    for fi, path in enumerate([r1_path] + ([r2] if r2 else [])):
        recs = parse_input(path)
        records.append(recs)
    if paired and len(records[0]) != len(records[1]):
        if errors != "salvage":
            raise FormatException(
                "paired FASTQ inputs have unequal record counts "
                f"({len(records[0])} vs {len(records[1])})"
            )
        lo = min(len(records[0]), len(records[1]))
        records = [r[:lo] for r in records]

    flat = []
    for fi, recs in enumerate(records):
        for qname, read, seq, qual in recs:
            read = read or (fi + 1 if paired else 0)
            flag = (FLAG_SINGLE if read == 0
                    else (FLAG_R2 if read == 2 else FLAG_R1))
            flat.append((qname, flag, seq, qual))

    order = sorted(
        range(len(flat)),
        key=lambda i: (
            natural_sort_key(flat[i][0].encode("latin-1")),
            flat[i][1], i,
        ),
    )
    header = BamHeader(_UBAM_HEADER_TEXT, []).with_sort_order("queryname")
    with open(output, "wb") as fh:
        w = _BlockedUbamWriter(fh, None, level)
        w.write(header.encode())
        for i in order:
            qname, flag, seq, qual = flat[i]
            w.write(_encode_record(qname, flag, seq, qual))
        w.close()
    return len(flat)
