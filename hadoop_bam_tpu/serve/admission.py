"""Admission control: bounded queueing, cost classes, typed load shedding.

PR 6's daemon bounded in-flight *sort jobs* but admitted everything else
unboundedly: a view storm queued without limit, latency grew without
bound, and the only "overload signal" a client ever saw was a socket
timeout.  This module is the Clipper-style admission layer in front of
every data-plane op:

- **cost classes** — each op charges a token cost proportional to its
  resource weight (``view`` 1, ``flagstat`` 2, ``sort`` 4); control-plane
  ops (ping/job/stats/metrics/shutdown) are never gated, so the daemon
  stays observable and drainable at any load;
- **token budget** — ``tokens`` concurrency units shared across admitted
  work; a ``sort`` holds its tokens for the *job's* lifetime (the job
  pool runs it asynchronously), inline ops for the request's;
- **bounded queue + typed shedding** — a request that cannot start
  immediately waits only while the queue is shallow and fast: depth over
  ``hadoopbam.serve.max-queue`` sheds with code ``SHED``, recent
  queue-wait p95 over ``hadoopbam.serve.max-queue-ms`` sheds with code
  ``RETRY_AFTER``; both replies carry a server-computed
  ``retry_after_ms`` backoff hint (clients back off by it instead of
  guessing);
- **deadline-aware waits** — a queued request whose end-to-end
  :class:`~hadoop_bam_tpu.utils.deadline.Deadline` expires is failed
  with ``DEADLINE_EXCEEDED`` *in the queue*, never dispatched.

Queue waits land in the ``serve.admission.queue_wait.ms`` histogram (the
overload SLO gauge) and — when the timeline tracer is armed — as
``category="queue"`` events that ``tools/trace_report.py`` folds into
the per-stage stall report, so overload shows up in the same harness as
pipeline stalls.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, Optional

from ..utils.deadline import Deadline, DeadlineExceeded
from ..utils.tracing import METRICS, TRACER, current_request

# -- the serve protocol's typed error codes ---------------------------------
#: Admission refused the request outright: the queue is full.  Retryable
#: after the reply's ``retry_after_ms``.
SHED = "SHED"
#: Admission refused the request softly: queueing is too slow right now
#: (queue-wait p95 over budget).  Retryable after ``retry_after_ms``.
RETRY_AFTER = "RETRY_AFTER"
#: The request's end-to-end deadline expired at a seam.  NOT retryable —
#: the client's budget is spent.
DEADLINE_EXCEEDED = "DEADLINE_EXCEEDED"
#: The daemon does not know this job id (it restarted and the journal
#: could not account for it, or the id never existed).  NOT retryable.
JOB_LOST = "JOB_LOST"

#: Every code the server can put in a reply's ``code`` field.  The
#: client maps each to a typed exception; tests/test_serve.py asserts
#: the mapping round-trips.
ERROR_CODES = (SHED, RETRY_AFTER, DEADLINE_EXCEEDED, JOB_LOST)

#: Token cost per data-plane op.  Ops absent here are control plane and
#: bypass admission entirely (the daemon must answer ping/stats/drain
#: even — especially — while shedding everything else).
DEFAULT_COSTS: Dict[str, int] = {
    "view": 1, "flagstat": 2, "variants": 1, "depth": 2,
    "sort": 4, "ingest": 4,
}

DEFAULT_TOKENS = 8
DEFAULT_MAX_QUEUE = 64
#: 0 disables the queue-wait p95 shed rule (depth still bounds).
DEFAULT_MAX_QUEUE_MS = 0


class ShedError(RuntimeError):
    """The daemon refused to admit a request (overload).

    ``code`` is :data:`SHED` (queue depth) or :data:`RETRY_AFTER`
    (queue-wait p95); ``retry_after_ms`` is the server-computed backoff
    hint the reply carries.
    """

    def __init__(self, code: str, retry_after_ms: int, why: str):
        self.code = code
        self.retry_after_ms = int(retry_after_ms)
        super().__init__(
            f"request shed ({why}); retry after ~{retry_after_ms} ms"
        )


class Ticket:
    """Held admission tokens; release exactly once (idempotent)."""

    __slots__ = ("_ctrl", "cost", "_released")

    def __init__(self, ctrl: "AdmissionController", cost: int):
        self._ctrl = ctrl
        self.cost = cost
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._ctrl._release(self.cost)

    def __enter__(self) -> "Ticket":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _NullTicket:
    """Control-plane ops: nothing held, nothing to release."""

    cost = 0

    def release(self) -> None:
        pass

    def __enter__(self) -> "_NullTicket":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_TICKET = _NullTicket()

#: Recent queue waits kept for the p95 shed rule and the backoff hint —
#: a small sliding window, deliberately not the lifetime histogram (an
#: hour-old fast quantile must not mask a fresh stall).
_RECENT_WINDOW = 64


class AdmissionController:
    """Token-budget admission with a bounded, shed-on-overload queue."""

    def __init__(
        self,
        tokens: int = DEFAULT_TOKENS,
        max_queue: int = DEFAULT_MAX_QUEUE,
        max_queue_ms: float = DEFAULT_MAX_QUEUE_MS,
        costs: Optional[Dict[str, int]] = None,
        name: str = "serve.admission",
    ):
        if tokens < 1:
            raise ValueError("tokens must be >= 1")
        self.tokens = int(tokens)
        self.max_queue = max(0, int(max_queue))
        self.max_queue_ms = float(max_queue_ms)
        self.costs = dict(DEFAULT_COSTS if costs is None else costs)
        self.name = name
        self._cond = threading.Condition()
        self._in_use = 0
        self._queued = 0
        self._recent_wait_ms: Deque[float] = collections.deque(
            maxlen=_RECENT_WINDOW
        )

    # -- introspection ------------------------------------------------------

    def gauges(self) -> Dict[str, float]:
        with self._cond:
            return {
                f"{self.name}.tokens": self.tokens,
                f"{self.name}.tokens_in_use": self._in_use,
                f"{self.name}.queue_depth": self._queued,
            }

    def _recent_p95_ms(self) -> float:
        waits = sorted(self._recent_wait_ms)
        if not waits:
            return 0.0
        return waits[min(len(waits) - 1, int(0.95 * len(waits)))]

    def _hint_ms(self) -> int:
        """The ``retry_after_ms`` backoff hint: roughly how long until a
        queue slot should free — recent mean service-side wait scaled by
        the backlog, clamped to a sane band.  A hint, not a promise."""
        waits = self._recent_wait_ms
        base = (sum(waits) / len(waits)) if waits else 50.0
        backlog = self._queued + max(1, self._in_use // max(1, self.tokens))
        return int(min(5000, max(10, base * backlog + 10)))

    # -- acquire / release --------------------------------------------------

    def acquire(
        self, op: str, deadline: Optional[Deadline] = None
    ):
        """Admit ``op`` or raise (:class:`ShedError` /
        :class:`~hadoop_bam_tpu.utils.deadline.DeadlineExceeded`).

        Returns a :class:`Ticket` (release when the work — for ``sort``,
        the *job* — finishes) or :data:`NULL_TICKET` for control-plane
        ops.  Use as a context manager for inline ops.
        """
        cost = self.costs.get(op)
        if cost is None:
            return NULL_TICKET
        # A cost above the whole budget would never fit; clamp so a heavy
        # op can still run alone (the single-oversized-entry cache rule).
        cost = min(int(cost), self.tokens)
        t0 = time.perf_counter()
        with self._cond:
            if self._in_use + cost > self.tokens:
                # Cannot start now: shed or queue — decided at arrival,
                # so a shed reply is immediate (overload must not slow
                # down saying "no").
                if self._queued >= self.max_queue:
                    hint = self._hint_ms()
                    METRICS.count(f"{self.name}.shed", 1)
                    METRICS.count(f"{self.name}.shed.queue_full", 1)
                    rctx = current_request()
                    if rctx is not None:
                        rctx.annotate(
                            "queue.shed", reason="queue_full", op=op
                        )
                    raise ShedError(
                        SHED, hint,
                        f"admission queue full ({self._queued} >= "
                        f"max-queue {self.max_queue})",
                    )
                if (
                    self.max_queue_ms > 0
                    and self._recent_p95_ms() > self.max_queue_ms
                ):
                    hint = self._hint_ms()
                    METRICS.count(f"{self.name}.shed", 1)
                    METRICS.count(f"{self.name}.shed.slow_queue", 1)
                    rctx = current_request()
                    if rctx is not None:
                        rctx.annotate(
                            "queue.shed", reason="slow_queue", op=op
                        )
                    raise ShedError(
                        RETRY_AFTER, hint,
                        f"queue-wait p95 {self._recent_p95_ms():.0f} ms "
                        f"over max-queue-ms {self.max_queue_ms:.0f}",
                    )
                self._queued += 1
                try:
                    while self._in_use + cost > self.tokens:
                        timeout = None
                        if deadline is not None:
                            rem = deadline.remaining_ms() / 1e3
                            if rem <= 0:
                                deadline.check("admission")  # raises
                            timeout = rem
                        self._cond.wait(timeout)
                finally:
                    self._queued -= 1
            self._in_use += cost
        wait_ms = (time.perf_counter() - t0) * 1e3
        self._recent_wait_ms.append(wait_ms)
        METRICS.count(f"{self.name}.admitted", 1)
        METRICS.observe(f"{self.name}.queue_wait.ms", wait_ms)
        rctx = current_request()
        if rctx is not None:
            # The waterfall's "queue wait" hop — always on (the tracer
            # ring may be cold; the summary path never is).
            rctx.annotate("queue.wait", ms=wait_ms, op=op, cost=cost)
        if TRACER.armed:
            t1 = time.perf_counter()
            TRACER.emit(
                f"{self.name}.wait", "queue", t1 - wait_ms / 1e3, t1,
                {"op": op, "cost": cost},
            )
        return Ticket(self, cost)

    def _release(self, cost: int) -> None:
        with self._cond:
            self._in_use = max(0, self._in_use - cost)
            self._cond.notify_all()


# -- fleet ledger -----------------------------------------------------------


class FleetLedger:
    """Router-side federated admission: a fleet-wide token pool plus a
    per-file cap, accounted at the routing hop.

    Each member daemon still runs its own :class:`AdmissionController`
    (its bounded queue is the only queue — the router never queues, so
    overload answers stay immediate).  What the members *cannot* see is
    cross-daemon skew: a zipfian workload pins one hot file's warmth on
    its ring owner, and without a fleet view that one daemon's clients
    consume every retry slot while the rest of the fleet idles.  The
    ledger therefore sheds at the front door on two rules:

    - **fleet pool** — at most ``tokens`` cost-units in flight across
      all members (sized ~N × a member's budget; a safety net, not the
      primary gate);
    - **per-file cap** — at most ``file_tokens`` cost-units in flight
      for any single routing key, so one hot file saturates its owner
      at a bounded rate and everyone else's files stay servable
      (``fleet.admission.shed.file_hot``).

    Sheds raise :class:`ShedError` with code :data:`SHED` and a backoff
    hint proportional to the contention, which the client's typed-retry
    path already honors.
    """

    def __init__(
        self,
        tokens: int,
        file_tokens: int,
        costs: Optional[Dict[str, int]] = None,
        name: str = "fleet.admission",
    ):
        if tokens < 1:
            raise ValueError("tokens must be >= 1")
        if file_tokens < 1:
            raise ValueError("file_tokens must be >= 1")
        self.tokens = int(tokens)
        self.file_tokens = int(file_tokens)
        self.costs = dict(DEFAULT_COSTS if costs is None else costs)
        self.name = name
        self._lock = threading.Lock()
        self._in_use = 0
        self._by_key: Dict[str, int] = {}

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return {
                f"{self.name}.tokens": self.tokens,
                f"{self.name}.tokens_in_use": self._in_use,
                f"{self.name}.hot_files": sum(
                    1 for v in self._by_key.values()
                    if v >= self.file_tokens
                ),
            }

    def acquire(self, op: str, key: Optional[str]):
        """Admit ``op`` against routing key ``key`` or raise
        :class:`ShedError`; returns a release callable (idempotent).
        Control-plane ops (no cost entry) pass untouched."""
        cost = self.costs.get(op)
        if cost is None or key is None:
            return lambda: None
        cost = min(int(cost), self.tokens)
        with self._lock:
            held = self._by_key.get(key, 0)
            if held + cost > self.file_tokens:
                METRICS.count(f"{self.name}.shed", 1)
                METRICS.count(f"{self.name}.shed.file_hot", 1)
                raise ShedError(
                    SHED, 25 * (1 + held),
                    f"file over fleet per-file cap ({held} + {cost} > "
                    f"{self.file_tokens})",
                )
            if self._in_use + cost > self.tokens:
                METRICS.count(f"{self.name}.shed", 1)
                METRICS.count(f"{self.name}.shed.pool_full", 1)
                raise ShedError(
                    SHED, 25 * (1 + self._in_use // max(1, self.tokens)),
                    f"fleet token pool exhausted ({self._in_use} + {cost} "
                    f"> {self.tokens})",
                )
            self._by_key[key] = held + cost
            self._in_use += cost
        METRICS.count(f"{self.name}.admitted", 1)
        released = [False]

        def _release() -> None:
            if released[0]:
                return
            released[0] = True
            with self._lock:
                self._in_use = max(0, self._in_use - cost)
                left = self._by_key.get(key, 0) - cost
                if left > 0:
                    self._by_key[key] = left
                else:
                    self._by_key.pop(key, None)

        return _release
