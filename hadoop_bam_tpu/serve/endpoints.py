"""Request endpoints: index-backed ranged ``view`` and flagstat scans.

One implementation, two surfaces: the daemon (serve/server.py) and the
one-shot CLI subcommands (``python -m hadoop_bam_tpu view|flagstat``) both
call these functions, so daemon responses are byte-identical to the batch
path by construction — the tests assert it anyway.

``view ref:start-end`` is the reference's bounded-traversal path
(BAMInputFormat.filterByInterval → chunk spans → OverlapDetector) turned
into a request: interval shorthand via ``utils.intervals``, chunk spans
from the cached ``.bai``, decoded windows from the residency arena (or
read through the cross-request lane batcher on a miss), and the exact
overlap cut on the ``ops/cigar.py`` ``overlap_mask`` kernel — padded to
the pow2 row buckets the warm-up pre-compiled, with a NumPy fallback that
is bit-identical when no device program is viable.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..conf import (
    Configuration,
    SERVE_ARENA_BYTES,
    SERVE_BATCH_WINDOW_MS,
    SERVE_CACHE_BYTES,
)
from ..spec import bam, bgzf
from ..utils.backend import is_resource_exhausted
from ..utils.deadline import Deadline, current_deadline
from ..utils.intervals import MAX_END, FormatError, parse_interval
from ..utils.tracing import METRICS, TRACER, current_request, span
from .arena import HbmArena
from .batching import LaneBatcher
from .cache import ResourceCache

#: SoA columns the view path needs: overlap inputs (refid/pos + cigar
#: geometry for reference spans) and the record extents for the gather.
VIEW_FIELDS = (
    "refid", "pos", "flag", "rec_off", "rec_len", "l_read_name",
    "n_cigar_op",
)
FLAGSTAT_FIELDS = ("flag", "rec_off", "rec_len")

DEFAULT_CACHE_BYTES = 256 << 20
DEFAULT_ARENA_BYTES = 1 << 30
DEFAULT_BATCH_WINDOW_MS = 2.0


@dataclass
class ServeContext:
    """The daemon's warm state, bundled: conf + cache + arena + batcher
    + the daemon's DeviceStream.

    The one-shot CLI builds a throwaway instance per invocation (same code
    path, cold state, no batcher thread unless asked); the daemon keeps
    one for its lifetime.  The arena and the lane batcher are *clients*
    of the one DeviceStream — the codec tier policy resolves once for
    the daemon's lifetime and every residency handoff rides the same
    ledger seam the batch pipeline uses.
    """

    conf: Configuration
    cache: ResourceCache
    arena: HbmArena
    batcher: Optional[LaneBatcher] = None
    stream: Optional[object] = None  # DeviceStream

    @classmethod
    def from_conf(
        cls, conf: Optional[Configuration] = None, with_batcher: bool = True
    ) -> "ServeContext":
        conf = conf or Configuration()
        cache_bytes = conf.get_int(SERVE_CACHE_BYTES, DEFAULT_CACHE_BYTES)
        arena_bytes = conf.get_int(SERVE_ARENA_BYTES, DEFAULT_ARENA_BYTES)
        window_ms = conf.get_int(
            SERVE_BATCH_WINDOW_MS, int(DEFAULT_BATCH_WINDOW_MS)
        )
        from ..device_stream import DeviceStream

        stream = DeviceStream(conf=conf, name="serve.stream")
        batcher = (
            LaneBatcher(window_s=window_ms / 1e3, conf=conf, stream=stream)
            if with_batcher
            else None
        )
        return cls(
            conf=conf,
            cache=ResourceCache(cache_bytes),
            arena=HbmArena(arena_bytes, stream=stream),
            batcher=batcher,
            stream=stream,
        )

    def close(self) -> None:
        if self.batcher is not None:
            self.batcher.close()
            self.batcher = None
        self.arena.release_all()

    def _inflate_fn(self):
        """The read path's member-inflate hook: the cross-request lane
        batcher, wrapped in the OOM degradation ladder.

        A device ``RESOURCE_EXHAUSTED`` (real, or the ``arena.oom``
        fault directive) never kills the daemon: first the arena's LRU
        residency is evicted — freeing HBM with the dropped references —
        and the shared launch retried once; if the device is still
        exhausted, *this request* tiers down to the native host codec
        (``serve.oom.tierdowns``) while every other request keeps its
        device tier.  The ambient request deadline rides into the
        batcher so queued-but-expired work is cancelled, not launched.
        """
        if self.batcher is None:
            return None
        b = self.batcher
        arena = self.arena

        def inflate(raw, co, cs, us):
            d = current_deadline()
            rctx = current_request()
            try:
                return b.submit(raw, co, cs, us, deadline=d)
            except Exception as e:
                if not is_resource_exhausted(e):
                    raise
            # Each rung of the degradation ladder is a named hop: the
            # waterfall of an OOM-afflicted request shows evict → retry
            # → tier-down instead of an unexplained slow "decode".
            if rctx is not None:
                rctx.annotate("oom.evict")
            arena.evict_lru()
            try:
                return b.submit(raw, co, cs, us, deadline=d)
            except Exception as e:
                if not is_resource_exhausted(e):
                    raise
            METRICS.count("serve.oom.tierdowns", 1)
            if rctx is not None:
                rctx.annotate("oom.tierdown", tier="host")
            if TRACER.armed:
                TRACER.instant(
                    "serve.oom.tierdown", "tier", {"tier": "host"}
                )
            from .. import native

            t_host = time.perf_counter()
            out = native.inflate_blocks(
                raw if isinstance(raw, np.ndarray)
                else np.frombuffer(raw, dtype=np.uint8),
                np.asarray(co, dtype=np.int64),
                np.asarray(cs, dtype=np.int32),
                np.asarray(us, dtype=np.int32),
            )
            if rctx is not None:
                rctx.annotate(
                    "oom.host_decode",
                    ms=(time.perf_counter() - t_host) * 1e3,
                )
            return out

        return inflate


def _endpoint_format(ctx: "ServeContext", path: str):
    """(kind, reader) for an endpoint path: the plain BamInputFormat for
    ``.bam`` (the hot path, unchanged), the AnySam dispatcher otherwise —
    its CRAM reader routes block decode through the daemon's DeviceStream
    rANS-lanes policy."""
    from ..io.anysam import AnySamInputFormat, infer_from_file_path

    if infer_from_file_path(path) == "bam":
        from ..io.bam import BamInputFormat

        return "bam", BamInputFormat(ctx.conf)
    fmt = AnySamInputFormat(ctx.conf)
    return fmt.get_format(path), fmt


def _split_span(s) -> Tuple[int, int]:
    """The arena-key byte span of a split: BGZF virtual offsets for a
    BAM FileVirtualSplit, plain byte offsets for a CRAM/SAM ByteSplit."""
    if hasattr(s, "vstart"):
        return s.vstart, s.vend
    return s.start, s.start + s.length


def _view_records_scan(
    ctx: "ServeContext", fmt, path: str, rid: int, beg0: int, end0: int,
    deadline: Optional[Deadline],
) -> List[Tuple[object, np.ndarray]]:
    """The index-free view path for container formats (CRAM has no
    ``.bai``): every split scans through the arena — warm windows are
    read-free exactly like the indexed path — and the same overlap cut
    picks the rows, so the records (and their order) match the BAM twin
    byte-for-byte."""
    ident = ctx.cache.identity(path)
    # Revalidation seam (PR 18): `identity` stats the file fresh, so any
    # arena window decoded under a previous (size, mtime_ns) vintage is
    # purged here — a rewritten file re-warms instead of serving stale
    # decoded records (`serve.cache.stale_evict`).
    ctx.arena.evict_stale(path, ident)
    picks: List[Tuple[object, np.ndarray]] = []
    for s in fmt.get_splits([path]):
        if deadline is not None:
            deadline.check("endpoint")
        a, b = _split_span(s)
        key = ("view", ident, a, b)
        batch = ctx.arena.get(key)
        if batch is None:
            with span("serve.view.read"):
                batch = fmt.read_split(
                    s, with_keys=False, fields=VIEW_FIELDS,
                    stream=ctx.stream,
                )
            ctx.arena.hold(key, batch)
        rows = _overlap_rows(batch, rid, beg0, end0)
        if len(rows):
            picks.append((batch, rows))
    return picks


def _pow2_rows(n: int) -> int:
    from .warmup import OVERLAP_PAD_MIN, pow2_at_least

    return pow2_at_least(max(n, 1), OVERLAP_PAD_MIN)


def _overlap_rows(batch, rid: int, beg0: int, end0: int) -> np.ndarray:
    """Row indices of records overlapping [beg0, end0) on refid ``rid``.

    Device path: the ``overlap_mask`` kernel over pow2-padded columns
    (padding rows carry refid -1, which never matches), so repeated
    requests reuse the warmed jit geometry.  Any device failure falls
    back to the identical NumPy formula — counted, never fatal.
    """
    n = batch.n_records
    if n == 0:
        return np.empty(0, dtype=np.int64)
    from ..ops.cigar import reference_lengths_np

    refid = np.asarray(batch.soa["refid"], dtype=np.int32)
    pos = np.asarray(batch.soa["pos"], dtype=np.int32)
    ref_len = reference_lengths_np(batch.data, batch.soa).astype(np.int32)
    try:
        import jax.numpy as jnp

        from ..ops.cigar import overlap_mask

        n_pad = _pow2_rows(n)
        refid_p = np.full(n_pad, -1, dtype=np.int32)
        pos_p = np.zeros(n_pad, dtype=np.int32)
        len_p = np.zeros(n_pad, dtype=np.int32)
        refid_p[:n] = refid
        pos_p[:n] = pos
        len_p[:n] = ref_len
        mask = np.asarray(
            overlap_mask(
                jnp.asarray(refid_p),
                jnp.asarray(pos_p),
                jnp.asarray(len_p),
                jnp.asarray(np.asarray([rid], dtype=np.int32)),
                jnp.asarray(np.asarray([beg0], dtype=np.int32)),
                jnp.asarray(np.asarray([end0], dtype=np.int32)),
            )
        )[:n]
        METRICS.count("serve.view.overlap_device", 1)
    except Exception:
        end = pos.astype(np.int64) + np.maximum(ref_len, 1)
        mask = (
            (refid == rid) & (pos >= 0) & (pos < end0) & (end > beg0)
        )
        METRICS.count("serve.view.overlap_host", 1)
    return np.nonzero(mask)[0].astype(np.int64)


def view_records(
    ctx: ServeContext, path: str, region: str,
    deadline: Optional[Deadline] = None,
) -> Tuple[bam.BamHeader, List[Tuple[object, np.ndarray]]]:
    """Resolve a ranged query to (header, [(decoded window, row indices)]).

    Windows come from the residency arena when warm; a miss reads the
    chunk span through the lane batcher (shared launches with concurrent
    requests) and holds the decoded batch for the next hit.  ``deadline``
    is checked per chunk window (the endpoint seam) — a request that
    expires mid-query stops decoding instead of finishing an answer
    nobody will read.
    """
    iv = parse_interval(region)
    rctx = current_request()
    t_idx = time.perf_counter()
    hdr, _ = ctx.cache.header(path)
    try:
        rid = hdr.ref_index(iv.contig)
    except KeyError:
        raise FormatError(
            f"unknown contig {iv.contig!r} in {path!r}"
        ) from None
    beg0 = iv.start - 1  # 1-based inclusive → 0-based half-open
    end0 = min(iv.end, MAX_END)
    kind, any_fmt = _endpoint_format(ctx, path)
    if kind != "bam":
        picks = _view_records_scan(
            ctx, any_fmt, path, rid, beg0, end0, deadline
        )
        return hdr, picks
    bai = ctx.cache.bai(path)
    chunks = bai.query(rid, beg0, end0)
    if rctx is not None:
        # Header + .bai resolution: ~0 on a cache hit, the dominant
        # cold-request hop on a miss — attributed so a cold p99 never
        # reads as an unexplained gap.
        rctx.annotate(
            "view.index", ms=(time.perf_counter() - t_idx) * 1e3
        )
    ident = ctx.cache.identity(path)
    # Revalidate on every routed hit: windows of a stale vintage are
    # invalidated now and re-warmed by the misses below (PR 18 satellite
    # — an mtime change must never serve yesterday's decode).
    ctx.arena.evict_stale(path, ident)
    picks: List[Tuple[object, np.ndarray]] = []
    from ..io.bam import BamInputFormat
    from ..io.splits import FileVirtualSplit

    fmt = BamInputFormat(ctx.conf)
    t_overlap = 0.0
    for c in chunks:
        if deadline is not None:
            deadline.check("endpoint")
        key = ("view", ident, c.beg, c.end)
        batch = ctx.arena.get(key)
        if batch is None:
            t_read = time.perf_counter()
            with span("serve.view.read"):
                batch = fmt.read_split(
                    FileVirtualSplit(path, c.beg, c.end),
                    with_keys=False,
                    fields=VIEW_FIELDS,
                    inflate_fn=ctx._inflate_fn(),
                )
            ctx.arena.hold(key, batch)
            if rctx is not None:
                # An arena miss is a real hop (read + inflate + parse);
                # a hit costs nothing and leaves no hop — warm requests'
                # waterfalls stay as short as their latency.
                rctx.annotate(
                    "window.read",
                    ms=(time.perf_counter() - t_read) * 1e3,
                )
        t_ov = time.perf_counter()
        rows = _overlap_rows(batch, rid, beg0, end0)
        t_overlap += time.perf_counter() - t_ov
        if len(rows):
            picks.append((batch, rows))
    if rctx is not None and chunks:
        # The kernel hop: the overlap cut (device kernel or its NumPy
        # fallback), accumulated across chunk windows into one hop —
        # separately attributed so "slow because kernel" and "slow
        # because read" never blur, one annotation per request so the
        # always-on path stays O(1) in window count.
        rctx.annotate(
            "view.overlap", ms=t_overlap * 1e3, windows=len(chunks)
        )
    return hdr, picks


def view_blob(
    ctx: ServeContext, path: str, region: str, level: int = 6,
    deadline: Optional[Deadline] = None,
) -> bytes:
    """A complete small BAM (header + overlapping records + terminator)
    for the requested region — records in file order, like samtools view.
    """
    import time as _time

    from .. import native
    from ..io.bam import gather_record_array
    from ..io.merger import prepare_bam_header_block

    t0 = _time.perf_counter()
    with span("serve.view"):
        hdr, picks = view_records(ctx, path, region, deadline=deadline)
        t_enc = _time.perf_counter()
        payloads = [
            gather_record_array(batch, rows) for batch, rows in picks
        ]
        n_records = sum(len(rows) for _, rows in picks)
        payload = (
            np.concatenate(payloads)
            if payloads
            else np.empty(0, np.uint8)
        )
        body = (
            native.deflate_blocks(payload, level=level)
            if len(payload)
            else b""
        )
        blob = (
            prepare_bam_header_block(hdr, level=level)
            + body
            + bgzf.TERMINATOR
        )
        rctx = current_request()
        if rctx is not None:
            # The reply-assembly hop (record gather + BGZF deflate).
            rctx.annotate(
                "view.encode",
                ms=(_time.perf_counter() - t_enc) * 1e3,
                records=n_records,
            )
    METRICS.count("serve.view.requests", 1)
    METRICS.count("serve.view.records", n_records)
    # Endpoint-level latency histogram: the daemon times whole requests
    # around dispatch (``serve.op.view.ms``); this one covers the shared
    # endpoint body, so the one-shot CLI surface gets p50/p95/p99 too.
    METRICS.observe("serve.view.ms", (_time.perf_counter() - t0) * 1e3)
    return blob


#: samtools-flagstat-class counter names, in report order.
FLAGSTAT_KEYS = (
    "total", "secondary", "supplementary", "duplicates", "mapped",
    "paired", "read1", "read2", "properly_paired",
    "with_itself_and_mate_mapped", "singletons",
)


def flagstat(
    ctx: ServeContext, path: str, deadline: Optional[Deadline] = None
) -> dict:
    """Whole-file flag census (the flagstat-class scan endpoint).

    Splits stream through the same read path as the sort (flag column
    only), with each decoded split held in the arena so a warm re-scan is
    read-free; the counts are pure NumPy popcounts over the flag column.
    """
    import time as _time

    t0 = _time.perf_counter()
    with span("serve.flagstat"):
        hdr, _ = ctx.cache.header(path)
        ident = ctx.cache.identity(path)
        ctx.arena.evict_stale(path, ident)  # PR 18: revalidate on hit
        kind, fmt = _endpoint_format(ctx, path)
        counts = {k: 0 for k in FLAGSTAT_KEYS}
        rctx = current_request()
        for s in fmt.get_splits([path]):
            if deadline is not None:
                deadline.check("endpoint")
            a, b = _split_span(s)
            key = ("flagstat", ident, a, b)
            batch = ctx.arena.get(key)
            if batch is None:
                t_read = time.perf_counter()
                batch = fmt.read_split(
                    s,
                    with_keys=False,
                    fields=FLAGSTAT_FIELDS,
                    inflate_fn=(
                        ctx._inflate_fn() if kind == "bam" else None
                    ),
                    stream=ctx.stream,
                )
                ctx.arena.hold(key, batch)
                if rctx is not None:
                    rctx.annotate(
                        "window.read",
                        ms=(time.perf_counter() - t_read) * 1e3,
                    )
            flag = np.asarray(batch.soa["flag"], dtype=np.int64)
            mapped = (flag & bam.FLAG_UNMAPPED) == 0
            paired = (flag & bam.FLAG_PAIRED) != 0
            mate_mapped = (flag & bam.FLAG_MATE_UNMAPPED) == 0
            counts["total"] += len(flag)
            counts["secondary"] += int(
                ((flag & bam.FLAG_SECONDARY) != 0).sum()
            )
            counts["supplementary"] += int(
                ((flag & bam.FLAG_SUPPLEMENTARY) != 0).sum()
            )
            counts["duplicates"] += int(
                ((flag & bam.FLAG_DUPLICATE) != 0).sum()
            )
            counts["mapped"] += int(mapped.sum())
            counts["paired"] += int(paired.sum())
            counts["read1"] += int(
                (paired & ((flag & bam.FLAG_FIRST_OF_PAIR) != 0)).sum()
            )
            counts["read2"] += int(
                (paired & ((flag & bam.FLAG_SECOND_OF_PAIR) != 0)).sum()
            )
            counts["properly_paired"] += int(
                (
                    paired
                    & mapped
                    & ((flag & bam.FLAG_PROPER_PAIR) != 0)
                ).sum()
            )
            counts["with_itself_and_mate_mapped"] += int(
                (paired & mapped & mate_mapped).sum()
            )
            counts["singletons"] += int(
                (paired & mapped & ~mate_mapped).sum()
            )
    METRICS.count("serve.flagstat.requests", 1)
    METRICS.observe(
        "serve.flagstat.ms", (_time.perf_counter() - t0) * 1e3
    )
    return counts


# -- variant plane (PR 20) --------------------------------------------------


def _variant_batch_nbytes(batch) -> int:
    """Arena budget charge for a VariantBatch: the int64 SoA columns plus
    a flat per-record charge standing in for the materializer's closure
    over the inflated payload (a VariantBatch has no ``.data``/``.soa``
    for the generic ``_batch_nbytes`` to walk)."""
    n = batch.n_records
    return (
        getattr(batch.keys, "nbytes", 8 * n)
        + getattr(batch.pos, "nbytes", 8 * n)
        + getattr(batch.end, "nbytes", 8 * n)
        + 64 * n
        + 4096
    )


def _variant_rows(
    batch, rid: int, beg0: int, end0: int, use_device: bool
) -> np.ndarray:
    """Row indices of variant records overlapping [beg0, end0) on contig
    index ``rid`` — the ragged interval join over the batch's key/pos/end
    columns (record span is 0-based half-open [pos-1, end)).  The device
    form runs only inside the int32 coordinate domain; outside it (or on
    any device failure) the bit-identical NumPy twin answers."""
    n = batch.n_records
    if n == 0:
        return np.empty(0, dtype=np.int64)
    from ..ops.pallas.overlap import ragged_overlap_mask

    refid = np.asarray(batch.keys, dtype=np.int64) >> 32
    starts = np.asarray(batch.pos, dtype=np.int64) - 1
    ends = np.asarray(batch.end, dtype=np.int64)
    use_dev = use_device and bool(
        starts.size
        and int(starts.min()) >= -(2**31)
        and int(ends.max()) < 2**31 - 8
        and end0 < 2**31 - 8
    )
    try:
        mask = ragged_overlap_mask(
            refid,
            starts,
            ends,
            np.asarray([rid], dtype=np.int64),
            np.asarray([beg0], dtype=np.int64),
            np.asarray([end0], dtype=np.int64),
            use_device=use_dev,
        )
        METRICS.count(
            "variants.join_device" if use_dev else "variants.join_host", 1
        )
    except Exception:
        endc = np.maximum(ends, starts + 1)
        mask = (refid == rid) & (starts < end0) & (endc > beg0)
        METRICS.count("variants.join_host", 1)
    return np.nonzero(mask)[0].astype(np.int64)


def variants_records(
    ctx: ServeContext, path: str, region: str,
    deadline: Optional[Deadline] = None,
) -> Tuple[object, List[Tuple[object, np.ndarray]]]:
    """Resolve a ranged BCF query to (BcfHeader, [(batch, row indices)]).

    The split plan comes from the resource cache (``bcf_plan`` — BCF has
    no CSI companion here, so the plan is the index analogue and every
    split scans, like the CRAM view path); decoded windows live in the
    residency arena unfiltered, so one warm file answers any region; the
    per-request cut is the ragged interval join over the batch's columns.
    """
    iv = parse_interval(region)
    rctx = current_request()
    t_plan = time.perf_counter()
    hdr, splits = ctx.cache.bcf_plan(path)
    if iv.contig not in hdr.contigs:
        raise FormatError(
            f"unknown contig {iv.contig!r} in {path!r}"
        ) from None
    rid = hdr.vcf.contig_index(iv.contig)
    beg0 = iv.start - 1  # 1-based inclusive → 0-based half-open
    end0 = min(iv.end, MAX_END)
    if rctx is not None:
        # Header + split-plan resolution: ~0 warm, the dominant cold hop
        # (the guesser walks the file once) — attributed like view.index.
        rctx.annotate(
            "variants.plan", ms=(time.perf_counter() - t_plan) * 1e3
        )
    ident = ctx.cache.identity(path)
    ctx.arena.evict_stale(path, ident)  # PR 18: revalidate on hit
    from ..io.bcf import BcfInputFormat

    fmt = BcfInputFormat(ctx.conf)
    use_dev = bool(
        ctx.stream is not None and ctx.stream.policy.use_bcf_chain
    )
    picks: List[Tuple[object, np.ndarray]] = []
    t_join = 0.0
    for s in splits:
        if deadline is not None:
            deadline.check("endpoint")
        key = ("variants", ident, s.vstart, s.vend)
        batch = ctx.arena.get(key)
        if batch is None:
            t_read = time.perf_counter()
            with span("serve.variants.read"):
                batch = fmt.read_split(
                    s,
                    stream=ctx.stream,
                    inflate_fn=ctx._inflate_fn(),
                )
            ctx.arena.hold(
                key, batch, nbytes=_variant_batch_nbytes(batch)
            )
            if rctx is not None:
                rctx.annotate(
                    "window.read",
                    ms=(time.perf_counter() - t_read) * 1e3,
                )
        t_ov = time.perf_counter()
        rows = _variant_rows(batch, rid, beg0, end0, use_dev)
        t_join += time.perf_counter() - t_ov
        if len(rows):
            picks.append((batch, rows))
    if rctx is not None and splits:
        rctx.annotate(
            "variants.join", ms=t_join * 1e3, windows=len(splits)
        )
    return hdr, picks


def variants_blob(
    ctx: ServeContext, path: str, region: str,
    deadline: Optional[Deadline] = None,
) -> bytes:
    """A complete small BCF (header + overlapping records + terminator)
    for the requested region — records in file order, like bcftools view.
    """
    import io as _io
    import time as _time

    from ..io.bcf import BcfRecordWriter

    t0 = _time.perf_counter()
    with span("serve.variants"):
        hdr, picks = variants_records(ctx, path, region, deadline=deadline)
        t_enc = _time.perf_counter()
        n_records = sum(len(rows) for _, rows in picks)
        buf = _io.BytesIO()
        w = BcfRecordWriter(buf, hdr.vcf, append_terminator=True)
        for batch, rows in picks:
            # Materialization is per batch and cached on it (the arena
            # warmth carries the VariantContext rows too) — row picks
            # index into the shared list in file order.
            vs = batch.variants
            for i in rows:
                w.write(vs[int(i)])
        w.close()
        blob = buf.getvalue()
        rctx = current_request()
        if rctx is not None:
            # The reply-assembly hop (materialize + BCF encode + BGZF).
            rctx.annotate(
                "variants.encode",
                ms=(_time.perf_counter() - t_enc) * 1e3,
                records=n_records,
            )
    METRICS.count("serve.variants.requests", 1)
    METRICS.count("serve.variants.records", n_records)
    METRICS.observe("serve.variants.ms", (_time.perf_counter() - t0) * 1e3)
    return blob


#: Hard cap on a per-base depth reply: one int per base, so an unbounded
#: region would turn a stats endpoint into a bulk-transfer one.
DEPTH_PER_BASE_MAX = 1 << 20


def depth_stat(
    ctx: ServeContext, path: str, region: str, bin_size: int = 1 << 12,
    per_base: bool = False, deadline: Optional[Deadline] = None,
) -> dict:
    """Pileup depth summary over an alignment region (the depth endpoint).

    Reuses the view scan verbatim for window residency and the overlap
    cut, then turns the picked records' reference spans into a segmented
    depth profile (``ops/pileup``) — binned summaries always, the exact
    per-base vector only under the ``DEPTH_PER_BASE_MAX`` cap.
    """
    import time as _time

    from ..ops.cigar import reference_lengths_np
    from ..ops.pileup import depth_profile, depth_summary

    t0 = _time.perf_counter()
    with span("serve.depth"):
        iv = parse_interval(region)
        hdr, picks = view_records(ctx, path, region, deadline=deadline)
        rid = hdr.ref_index(iv.contig)  # validated inside view_records
        beg0 = iv.start - 1
        end0 = min(iv.end, MAX_END)
        ref_len = hdr.refs[rid][1]
        if ref_len > 0:
            # Clip to the declared contig length: depth past the contig
            # end is identically zero and only bloats the bin vector.
            end0 = min(end0, ref_len)
        if end0 <= beg0:
            raise FormatError(
                f"empty depth window {region!r} (contig length {ref_len})"
            )
        starts_l: List[np.ndarray] = []
        ends_l: List[np.ndarray] = []
        for batch, rows in picks:
            pos = np.asarray(batch.soa["pos"], dtype=np.int64)[rows]
            rl = reference_lengths_np(batch.data, batch.soa).astype(
                np.int64
            )[rows]
            starts_l.append(pos)
            ends_l.append(pos + np.maximum(rl, 1))
        starts = (
            np.concatenate(starts_l) if starts_l else np.empty(0, np.int64)
        )
        ends = (
            np.concatenate(ends_l) if ends_l else np.empty(0, np.int64)
        )
        use_dev = bool(
            ctx.stream is not None and ctx.stream.policy.use_bcf_chain
        )
        t_pile = _time.perf_counter()
        out = {
            "contig": iv.contig,
            "beg": beg0 + 1,
            "end": end0,
            "n_records": int(len(starts)),
        }
        out.update(
            depth_summary(
                starts, ends, beg0, end0,
                bin_size=bin_size, use_device=use_dev,
            )
        )
        if per_base:
            if end0 - beg0 > DEPTH_PER_BASE_MAX:
                raise FormatError(
                    f"per-base depth span {end0 - beg0} exceeds cap "
                    f"{DEPTH_PER_BASE_MAX}; use binned summaries"
                )
            prof = depth_profile(
                starts, ends, beg0, end0, use_device=use_dev
            )
            out["per_base"] = [int(x) for x in prof]
        rctx = current_request()
        if rctx is not None:
            # The kernel hop: the segmented pileup (device chunks or the
            # bit-identical NumPy twin), one annotation per request.
            rctx.annotate(
                "depth.pileup",
                ms=(_time.perf_counter() - t_pile) * 1e3,
                records=int(len(starts)),
            )
    METRICS.count("serve.depth.requests", 1)
    METRICS.observe("serve.depth.ms", (_time.perf_counter() - t0) * 1e3)
    return out
