"""Daemon flight recorder: the last seconds before death, on disk.

The PR 10 journal makes a killed daemon's *jobs* recoverable; nothing
makes its *state* explainable — after a ``kill -9`` the operator knows
what was queued, not whether the daemon was drowning in admission waits,
evicting the arena in a loop, or watching HBM climb.  This module is the
black box: a background thread snapshots the daemon's gauges (queue
depth, admission tokens, arena/cache/HBM occupancy) and its
degradation-class counters (sheds, OOM tierdowns, journal events, HBM
leaks) to an on-disk JSONL ring at a configurable cadence.

The ring is two alternating segment files ``<base>.0`` / ``<base>.1``:
the writer appends to the active segment (flushed per line — a SIGKILL
loses at most the torn final line, since flushed bytes are in the kernel)
and, when the active segment crosses half the byte budget, truncates the
other segment and switches to it.  Total disk is bounded by the budget;
the survivable history is at least half of it.  On a graceful drain the
recorder writes one ``"final": true`` snapshot, so a ring *without* a
final record is itself evidence of an unclean death.

Replay is ``tools/flightrec_report.py`` — stdlib-only, torn-tail
tolerant, ordered by ``seq`` across both segments.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.tracing import METRICS

DEFAULT_CADENCE_MS = 500
DEFAULT_RING_BYTES = 1 << 20
DEFAULT_ACCESS_LOG_BYTES = 4 << 20

#: Counter prefixes worth replaying after a crash: the degradation story.
SNAPSHOT_COUNTER_PREFIXES = (
    "serve.admission.shed",
    "serve.admission.admitted",
    "serve.oom.",
    "serve.deadline.",
    "serve.journal.",
    "serve.jobs_",
    "serve.request_errors",
    "serve.slo.",
    "serve.trace.",
    "hbm.leaked",
    "hbm.double_copy",
)


def default_source() -> Dict[str, dict]:
    """Fallback snapshot source: registry gauges + degradation counters
    (the daemon passes a richer closure over its live context)."""
    counters = METRICS.report()["counters"]
    return {
        "gauges": METRICS.gauges(),
        "counters": {
            k: v
            for k, v in counters.items()
            if k.startswith(SNAPSHOT_COUNTER_PREFIXES)
        },
    }


def segment_paths(base: str) -> Tuple[str, str]:
    return base + ".0", base + ".1"


class JsonlRing:
    """The two-segment JSONL ring writer, factored out so the flight
    recorder and the per-request access log share one rotation scheme:
    append to the active segment (flushed per line), and when it crosses
    half the byte budget, truncate the other segment and switch —
    bounded disk, at least half the budget of survivable history.

    Not itself thread-safe: callers serialize appends (both owners
    already hold their own locks)."""

    def __init__(
        self, base: str, max_bytes: int, rotate_metric: str
    ) -> None:
        self.base = base
        self.max_bytes = max(8 << 10, int(max_bytes))
        self._rotate_metric = rotate_metric
        self._f = None
        self._active = 0

    def prepare(self, active: int = 0) -> None:
        d = os.path.dirname(os.path.abspath(self.base))
        if d:
            os.makedirs(d, exist_ok=True)
        self._active = active

    def append(self, rec: dict) -> None:
        """One record as a flushed JSONL line (a SIGKILL after return
        loses at most a torn tail on a *later* line)."""
        if self._f is None:
            self._f = open(segment_paths(self.base)[self._active], "ab")
        self._f.write(json.dumps(rec, sort_keys=True).encode() + b"\n")
        self._f.flush()
        if self._f.tell() > self.max_bytes // 2:
            self._f.close()
            self._active ^= 1
            # Truncate the segment we are rotating onto: the ring
            # reclaims the oldest half.
            self._f = open(segment_paths(self.base)[self._active], "wb")
            METRICS.count(self._rotate_metric, 1)

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None


class FlightRecorder:
    """Bounded JSONL ring writer with a periodic snapshot thread."""

    def __init__(
        self,
        base_path: str,
        cadence_s: float = DEFAULT_CADENCE_MS / 1e3,
        max_bytes: int = DEFAULT_RING_BYTES,
        source: Optional[Callable[[], dict]] = None,
    ) -> None:
        self.base = base_path
        self.cadence = max(0.02, float(cadence_s))
        self._ring = JsonlRing(
            base_path, max_bytes, "serve.flightrec.rotations"
        )
        self._source = source or default_source
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._finalized = False

    # -- segment management -------------------------------------------------

    def _scan_existing(self) -> None:
        """Resume numbering after the highest surviving seq (a restarted
        daemon extends the ring; pre-death history stays replayable until
        rotation naturally reclaims it)."""
        best_seq, best_idx = -1, 0
        for idx, p in enumerate(segment_paths(self.base)):
            try:
                with open(p, "rb") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                            if int(rec.get("seq", -1)) > best_seq:
                                best_seq = int(rec["seq"])
                                best_idx = idx
                        except (ValueError, TypeError):
                            continue  # torn line
            except OSError:
                continue
        self._seq = best_seq + 1
        self._ring.prepare(active=best_idx)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._scan_existing()
        self.snapshot()  # an immediate baseline record
        self._thread = threading.Thread(
            target=self._run, name="hbam-flightrec", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.cadence):
            try:
                self.snapshot()
            except Exception:  # noqa: BLE001 - the recorder never kills
                METRICS.count("serve.flightrec.errors", 1)

    def snapshot(self, final: bool = False) -> dict:
        """Write one snapshot record (thread-safe; flushed so a SIGKILL
        after return cannot lose it)."""
        rec = {
            "seq": 0,  # patched under the lock
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "final": bool(final),
        }
        try:
            rec.update(self._source() or {})
        except Exception:  # noqa: BLE001 - snapshot beats perfection
            METRICS.count("serve.flightrec.source_errors", 1)
        with self._lock:
            if self._finalized:
                return rec
            rec["seq"] = self._seq
            self._seq += 1
            self._ring.append(rec)
            if final:
                self._finalized = True
        METRICS.count("serve.flightrec.snapshots", 1)
        return rec

    def stop(self, final: bool = True) -> None:
        """Finalize the ring (SIGTERM drain / shutdown op): one last
        snapshot flagged ``final`` so replay can tell a clean drain from
        a kill, then close.  Idempotent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final:
            try:
                self.snapshot(final=True)
            except Exception:  # noqa: BLE001
                METRICS.count("serve.flightrec.errors", 1)
        with self._lock:
            self._ring.close()


class AccessLog:
    """One structured JSONL line per completed request (trace id, op,
    outcome, duration, queue/batch waits, tier decisions, shed/OOM
    flags), rotated with the same two-segment scheme as the flight
    recorder, so the per-request history is bounded on disk and joins
    with the exemplar store on ``trace_id``."""

    def __init__(
        self, base_path: str, max_bytes: int = DEFAULT_ACCESS_LOG_BYTES
    ) -> None:
        self.base = base_path
        self._ring = JsonlRing(
            base_path, max_bytes, "serve.accesslog.rotations"
        )
        self._lock = threading.Lock()
        self._ring.prepare()

    def log(self, record: dict) -> None:
        try:
            with self._lock:
                self._ring.append(record)
            METRICS.count("serve.accesslog.lines", 1)
        except OSError:
            # Logging must never fail a request; the error is counted.
            METRICS.count("serve.accesslog.errors", 1)

    def close(self) -> None:
        with self._lock:
            self._ring.close()


def load_jsonl_segments(base: str) -> Tuple[List[dict], int]:
    """Read both segments of a two-segment ring back, in file order:
    ``(records, torn_line_count)``.  Accepts the base path or either
    segment path; tolerant of torn final lines and missing segments.
    Ordering across segments is the caller's (flight-recorder rings
    sort by ``seq``; access logs by ``t_wall``)."""
    if base.endswith((".0", ".1")) and not os.path.exists(base + ".0"):
        base = base[:-2]
    recs: List[dict] = []
    torn = 0
    for p in segment_paths(base):
        try:
            with open(p, "rb") as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        recs.append(json.loads(line))
                    except (ValueError, TypeError):
                        torn += 1
        except OSError:
            continue
    return recs, torn


def load_access_log(base: str) -> Tuple[List[dict], int]:
    """An access log's records ordered by wall time, plus torn count."""
    recs, torn = load_jsonl_segments(base)
    recs.sort(key=lambda r: r.get("t_wall", 0.0))
    return recs, torn


def load_ring(base: str) -> Tuple[List[dict], int]:
    """Read a ring back: ``(snapshots ordered by seq, torn_line_count)``.
    Accepts the base path or either segment path; tolerant of torn final
    lines (the kill -9 case) and missing segments."""
    recs, torn = load_jsonl_segments(base)
    snaps: Dict[int, dict] = {}
    for rec in recs:
        try:
            snaps[int(rec["seq"])] = rec
        except (KeyError, ValueError, TypeError):
            torn += 1
    return [snaps[k] for k in sorted(snaps)], torn
