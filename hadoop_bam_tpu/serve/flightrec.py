"""Daemon flight recorder: the last seconds before death, on disk.

The PR 10 journal makes a killed daemon's *jobs* recoverable; nothing
makes its *state* explainable — after a ``kill -9`` the operator knows
what was queued, not whether the daemon was drowning in admission waits,
evicting the arena in a loop, or watching HBM climb.  This module is the
black box: a background thread snapshots the daemon's gauges (queue
depth, admission tokens, arena/cache/HBM occupancy) and its
degradation-class counters (sheds, OOM tierdowns, journal events, HBM
leaks) to an on-disk JSONL ring at a configurable cadence.

The ring is two alternating segment files ``<base>.0`` / ``<base>.1``:
the writer appends to the active segment (flushed per line — a SIGKILL
loses at most the torn final line, since flushed bytes are in the kernel)
and, when the active segment crosses half the byte budget, truncates the
other segment and switches to it.  Total disk is bounded by the budget;
the survivable history is at least half of it.  On a graceful drain the
recorder writes one ``"final": true`` snapshot, so a ring *without* a
final record is itself evidence of an unclean death.

Replay is ``tools/flightrec_report.py`` — stdlib-only, torn-tail
tolerant, ordered by ``seq`` across both segments.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.tracing import METRICS

DEFAULT_CADENCE_MS = 500
DEFAULT_RING_BYTES = 1 << 20

#: Counter prefixes worth replaying after a crash: the degradation story.
SNAPSHOT_COUNTER_PREFIXES = (
    "serve.admission.shed",
    "serve.admission.admitted",
    "serve.oom.",
    "serve.deadline.",
    "serve.journal.",
    "serve.jobs_",
    "serve.request_errors",
    "hbm.leaked",
    "hbm.double_copy",
)


def default_source() -> Dict[str, dict]:
    """Fallback snapshot source: registry gauges + degradation counters
    (the daemon passes a richer closure over its live context)."""
    counters = METRICS.report()["counters"]
    return {
        "gauges": METRICS.gauges(),
        "counters": {
            k: v
            for k, v in counters.items()
            if k.startswith(SNAPSHOT_COUNTER_PREFIXES)
        },
    }


def segment_paths(base: str) -> Tuple[str, str]:
    return base + ".0", base + ".1"


class FlightRecorder:
    """Bounded JSONL ring writer with a periodic snapshot thread."""

    def __init__(
        self,
        base_path: str,
        cadence_s: float = DEFAULT_CADENCE_MS / 1e3,
        max_bytes: int = DEFAULT_RING_BYTES,
        source: Optional[Callable[[], dict]] = None,
    ) -> None:
        self.base = base_path
        self.cadence = max(0.02, float(cadence_s))
        self.max_bytes = max(8 << 10, int(max_bytes))
        self._source = source or default_source
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._f = None
        self._active = 0
        self._seq = 0
        self._finalized = False

    # -- segment management -------------------------------------------------

    def _scan_existing(self) -> None:
        """Resume numbering after the highest surviving seq (a restarted
        daemon extends the ring; pre-death history stays replayable until
        rotation naturally reclaims it)."""
        best_seq, best_idx = -1, 0
        for idx, p in enumerate(segment_paths(self.base)):
            try:
                with open(p, "rb") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                            if int(rec.get("seq", -1)) > best_seq:
                                best_seq = int(rec["seq"])
                                best_idx = idx
                        except (ValueError, TypeError):
                            continue  # torn line
            except OSError:
                continue
        self._seq = best_seq + 1
        self._active = best_idx

    def _ensure_open(self):
        if self._f is None:
            path = segment_paths(self.base)[self._active]
            self._f = open(path, "ab")
        return self._f

    def _rotate_if_needed(self) -> None:
        if self._f is not None and self._f.tell() > self.max_bytes // 2:
            self._f.close()
            self._active ^= 1
            # Truncate the segment we are rotating onto: the ring
            # reclaims the oldest half.
            self._f = open(segment_paths(self.base)[self._active], "wb")
            METRICS.count("serve.flightrec.rotations", 1)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        d = os.path.dirname(os.path.abspath(self.base))
        if d:
            os.makedirs(d, exist_ok=True)
        self._scan_existing()
        self.snapshot()  # an immediate baseline record
        self._thread = threading.Thread(
            target=self._run, name="hbam-flightrec", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.cadence):
            try:
                self.snapshot()
            except Exception:  # noqa: BLE001 - the recorder never kills
                METRICS.count("serve.flightrec.errors", 1)

    def snapshot(self, final: bool = False) -> dict:
        """Write one snapshot record (thread-safe; flushed so a SIGKILL
        after return cannot lose it)."""
        rec = {
            "seq": 0,  # patched under the lock
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "final": bool(final),
        }
        try:
            rec.update(self._source() or {})
        except Exception:  # noqa: BLE001 - snapshot beats perfection
            METRICS.count("serve.flightrec.source_errors", 1)
        with self._lock:
            if self._finalized:
                return rec
            rec["seq"] = self._seq
            self._seq += 1
            f = self._ensure_open()
            f.write(json.dumps(rec, sort_keys=True).encode() + b"\n")
            f.flush()
            self._rotate_if_needed()
            if final:
                self._finalized = True
        METRICS.count("serve.flightrec.snapshots", 1)
        return rec

    def stop(self, final: bool = True) -> None:
        """Finalize the ring (SIGTERM drain / shutdown op): one last
        snapshot flagged ``final`` so replay can tell a clean drain from
        a kill, then close.  Idempotent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final:
            try:
                self.snapshot(final=True)
            except Exception:  # noqa: BLE001
                METRICS.count("serve.flightrec.errors", 1)
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                finally:
                    self._f = None


def load_ring(base: str) -> Tuple[List[dict], int]:
    """Read a ring back: ``(snapshots ordered by seq, torn_line_count)``.
    Accepts the base path or either segment path; tolerant of torn final
    lines (the kill -9 case) and missing segments."""
    if base.endswith((".0", ".1")) and not os.path.exists(base + ".0"):
        base = base[:-2]
    snaps: Dict[int, dict] = {}
    torn = 0
    for p in segment_paths(base):
        try:
            with open(p, "rb") as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        rec = json.loads(line)
                        snaps[int(rec["seq"])] = rec
                    except (ValueError, TypeError, KeyError):
                        torn += 1
        except OSError:
            continue
    return [snaps[k] for k in sorted(snaps)], torn
