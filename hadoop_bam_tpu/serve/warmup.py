"""Startup warm-up: pre-compile the pow2 kernel geometries, count compiles.

Every jit entry point in the pipeline buckets its launch shapes to powers
of two precisely so the set of distinct compiled geometries stays small —
which makes them *enumerable*: a daemon can compile the whole working set
once at startup and answer its first request warm.  ``warm_kernels``
drives the real wrappers (``ops.flate`` codec tiers, the ``ops.cigar``
overlap kernel, the sort keys program) over representative bucket sizes;
whatever geometry a request would hit afterwards is already in the jit
cache.

The other half is *proving* warmth: :class:`CompileWatcher` hooks
``jax.monitoring`` and counts every XLA backend compile into METRICS
(``serve.jit_compiles``), so "a warm view request triggers zero kernel
compiles" is an asserted counter delta, not a hope.  The listener is
process-global and idempotent; when the monitoring API is unavailable the
counter simply never moves (and tests that depend on it skip).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from ..utils.tracing import METRICS, span

_WATCHER = None
_WATCHER_LOCK = threading.Lock()

#: Warmable kernel families (the ``kinds`` vocabulary of warm_kernels).
ALL_KINDS = ("overlap", "keys", "codec")

#: Pow2 payload buckets for the codec warm-up when a real accelerator is
#: present: small member, mid member, and the part writer's full-size
#: blocking (DEV_LZ_PAYLOAD rides the last bucket's geometry).
TPU_CODEC_BUCKETS = (4096, 16384, 57088)
#: Interpret-mode (CPU) bucket: one tiny member — geometry coverage
#: without minutes of interpret emulation (see the kernel-test budget
#: note in tests/test_stream_codecs.py).
CPU_CODEC_BUCKETS = (1024,)

#: Row-count buckets for the overlap/keys programs: the serve endpoints
#: pad record counts to pow2 ≥ OVERLAP_PAD_MIN, so these are exactly the
#: shapes requests produce.
OVERLAP_PAD_MIN = 64
DEFAULT_ROW_BUCKETS = (64, 256, 1024, 4096)


class CompileWatcher:
    """Counts XLA backend compiles via the jax.monitoring event stream."""

    def __init__(self) -> None:
        self.count = 0
        self.available = False
        try:
            import jax.monitoring as monitoring

            def _on_duration(key: str, *args, **kwargs) -> None:
                if "backend_compile" in key:
                    self.count += 1
                    METRICS.count("serve.jit_compiles", 1)

            monitoring.register_event_duration_secs_listener(_on_duration)
            self.available = True
        except Exception:  # pragma: no cover - monitoring API moved away
            pass


def ensure_compile_watcher() -> CompileWatcher:
    """The process-global watcher (registered once; jax.monitoring has no
    unregister-by-handle, so a singleton avoids double counting)."""
    global _WATCHER
    with _WATCHER_LOCK:
        if _WATCHER is None:
            _WATCHER = CompileWatcher()
        return _WATCHER


def compile_count() -> int:
    """Backend compiles observed so far (0 until the watcher exists)."""
    w = _WATCHER
    return w.count if w is not None else 0


def pow2_at_least(n: int, lo: int = OVERLAP_PAD_MIN) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


def _warm_overlap(row_buckets: Sequence[int]) -> int:
    """Compile the interval-overlap kernel at every request pad shape
    (K=1 interval — the view endpoint queries one region at a time)."""
    import jax.numpy as jnp

    from ..ops.cigar import overlap_mask

    done = 0
    for n in row_buckets:
        z = jnp.zeros(n, dtype=jnp.int32)
        overlap_mask(
            z - 1,  # refid -1: padding rows, never match
            z,
            z,
            jnp.zeros(1, dtype=jnp.int32),
            jnp.zeros(1, dtype=jnp.int32),
            jnp.ones(1, dtype=jnp.int32),
        ).block_until_ready()
        done += 1
    return done


def _warm_keys(row_buckets: Sequence[int]) -> int:
    """Compile the two-column key sort at the same pow2 row buckets."""
    import jax.numpy as jnp

    from ..ops.sort import sort_keys

    done = 0
    for n in row_buckets:
        # Same dtypes as ops.keys.split_keys_np produces on the hot path.
        hi = jnp.zeros(n, dtype=jnp.int32)
        lo = jnp.zeros(n, dtype=jnp.uint32)
        _, _, perm = sort_keys(hi, lo)
        perm.block_until_ready()
        done += 1
    return done


def _warm_codec(buckets: Sequence[int], conf) -> int:
    """Round one synthetic payload per bucket through both device codec
    wrappers, compiling whichever tiers the gates select (lanes kernels
    when enabled, the XLA fixed/dynamic programs otherwise)."""
    from ..ops import flate

    rng = np.random.default_rng(0)
    done = 0
    for b in buckets:
        # Compressible-but-nontrivial bytes: exercises real match/Huffman
        # paths rather than the all-zero fast cases.
        payload = rng.integers(0, 8, size=b, dtype=np.uint8)
        blob = flate.bgzf_compress_device(
            payload, level=1, conf=conf, block_payload=min(b, 57088)
        )
        flate.bgzf_decompress_device(blob, conf=conf)
        done += 1
    return done


def warm_kernels(
    conf=None,
    kinds: Optional[Iterable[str]] = None,
    codec_buckets: Optional[Sequence[int]] = None,
    row_buckets: Sequence[int] = DEFAULT_ROW_BUCKETS,
) -> Dict[str, object]:
    """Pre-compile the daemon's kernel working set; returns a report.

    ``kinds`` defaults to everything warmable, with the codec family
    auto-sized to the backend: full-size pow2 buckets on a real
    accelerator, one tiny interpret-mode bucket on CPU (compiling is the
    point; emulating 64 KiB members is not).  Each family is independent
    and failure-isolated — a broken tier records an error string instead
    of killing startup (the request path has its own tier-downs).
    """
    ensure_compile_watcher()
    kinds = tuple(kinds) if kinds is not None else ALL_KINDS
    unknown = set(kinds) - set(ALL_KINDS)
    if unknown:
        raise ValueError(f"unknown warm-up kinds: {sorted(unknown)}")
    if codec_buckets is None:
        try:
            import jax

            on_tpu = jax.devices()[0].platform == "tpu"
        except Exception:
            on_tpu = False
        codec_buckets = TPU_CODEC_BUCKETS if on_tpu else CPU_CODEC_BUCKETS
    c0 = compile_count()
    report: Dict[str, object] = {
        "kinds": list(kinds),
        "codec_buckets": list(codec_buckets),
        "row_buckets": list(row_buckets),
        "warmed": {},
        "errors": {},
    }
    steps = {
        "overlap": lambda: _warm_overlap(row_buckets),
        "keys": lambda: _warm_keys(row_buckets),
        "codec": lambda: _warm_codec(codec_buckets, conf),
    }
    with span("serve.warmup"):
        for kind in kinds:
            try:
                report["warmed"][kind] = steps[kind]()
            except Exception as e:  # noqa: BLE001 - startup must survive
                report["errors"][kind] = f"{type(e).__name__}: {e}"
                METRICS.count("serve.warmup_errors", 1)
    report["compiles"] = compile_count() - c0
    METRICS.count("serve.warmup_runs", 1)
    return report
