"""Tail-latency exemplars: the p99 request, individually reconstructable.

The daemon's latency histograms (``serve.op.<op>.ms``) summarize the
population; the request that *made* the p99 — lost a lane-batcher slot,
waited out the admission queue, then hit an OOM tier-down — left no
individually reconstructable trail before this module.  The Dapper-style
fix has two tiers of cost:

- **always on, O(1) per seam**: every traced request's
  :class:`~hadoop_bam_tpu.utils.tracing.RequestContext` accumulates hop
  annotations (queue wait, batch wait, decode, window reads, executor
  attempts, tier decisions, deadline expiry), and at completion the
  :class:`TailSampler` folds them into a compact summary — no ring
  scan, no allocation beyond the hop list;
- **on breach only**: a request over the latency threshold, or ending in
  ``SHED``/``RETRY_AFTER``/``DEADLINE_EXCEEDED``/error, or that tiered
  down under OOM, gets its *full* event set copied out of the tracer
  ring (``args["trace"]`` is the join key) into the bounded
  :class:`ExemplarStore` before the ring evicts it — optionally spilled
  as one JSON file per exemplar to ``--exemplar-dir`` so post-mortems
  survive the daemon.

Exemplars are stamped ``incomplete: true`` when any event category they
contain lost events to ring overflow (the tracer's per-category drop
ledger) — ``tools/request_report.py`` must never render a partial
waterfall as complete.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Dict, List, Optional

from ..utils.tracing import METRICS, TRACER, RequestContext

DEFAULT_THRESHOLD_MS = 1000.0
DEFAULT_MAX_EXEMPLARS = 64

#: Outcome codes that always earn an exemplar regardless of latency:
#: the request classes whose post-mortem question is "why this one?".
TRIGGER_OUTCOMES = frozenset(
    ("SHED", "RETRY_AFTER", "DEADLINE_EXCEEDED", "ERROR", "JOB_LOST")
)

#: Hop-name prefixes that mark a degradation the sampler triggers on
#: even when the request finished in budget (a tier-down answered fast
#: *this* time; the exemplar is the evidence trail for why it happened).
TIERDOWN_HOP_PREFIXES = ("oom.", "tier.")


def request_summary(
    rctx: RequestContext,
    outcome: str,
    duration_ms: float,
    op: Optional[str] = None,
    extra: Optional[dict] = None,
) -> dict:
    """The compact per-request record: identity, outcome, duration, and
    the waterfall-relevant aggregates (queue wait, batch wait, decode,
    tier decisions) reduced from the hop annotations.  This is what the
    access log writes per line and what ``exemplars`` lists."""
    hops = list(rctx.hops)
    agg: Dict[str, float] = {}
    tiers: List[str] = []
    for h in hops:
        name = h["hop"]
        if "ms" in h:
            agg[name] = agg.get(name, 0.0) + h["ms"]
        if name.startswith(TIERDOWN_HOP_PREFIXES):
            tiers.append(name)
    s = {
        "trace_id": rctx.trace_id,
        "span_id": rctx.span_id,
        "parent_id": rctx.parent_id,
        "op": op or rctx.op,
        "outcome": outcome,
        "t_wall": rctx.t0_wall,
        "duration_ms": round(float(duration_ms), 3),
        "queue_wait_ms": round(agg.get("queue.wait", 0.0), 3),
        "batch_wait_ms": round(agg.get("batch.wait", 0.0), 3),
        "decode_ms": round(agg.get("batch.decode", 0.0), 3),
        "tier_decisions": tiers,
        "shed": outcome in ("SHED", "RETRY_AFTER"),
        "deadline_exceeded": outcome == "DEADLINE_EXCEEDED",
        "oom": any(t.startswith("oom.") for t in tiers),
        "hops": hops,
        "hops_dropped": rctx.hops_dropped,
    }
    if rctx.baggage:
        s["baggage"] = dict(rctx.baggage)
    if extra:
        s.update(extra)
    return s


def access_record(summary: dict) -> dict:
    """The JSONL access-log line: the summary minus the per-hop detail
    (one structured line per completed request; joins with the exemplar
    store on ``trace_id``)."""
    return {k: v for k, v in summary.items() if k != "hops"}


def build_exemplar(
    summary: dict, events: List[dict],
    dropped_by_category: Optional[Dict[str, int]] = None,
) -> dict:
    """An exemplar: summary + the request's full ring events + the
    completeness verdict.  ``incomplete`` is true when any category
    present in (or plausibly missing from) the tree lost ring events —
    with zero surviving events and *any* drops, completeness is
    unknowable, so the stamp stays honest and pessimistic."""
    dropped = dropped_by_category or {}
    cats = {e.get("cat", "") for e in events}
    incomplete = any(dropped.get(c, 0) for c in cats)
    if not events and any(dropped.values()):
        incomplete = True
    return {
        "summary": summary,
        "events": events,
        "categories": sorted(cats),
        "dropped_by_category": {k: v for k, v in dropped.items() if v},
        "incomplete": incomplete,
    }


class ExemplarStore:
    """Bounded per-daemon store of full request traces, keyed by trace
    id; oldest evicted beyond ``max_exemplars``.  With ``spill_dir``
    set, each exemplar is also written as ``<dir>/<trace_id>.json`` at
    admission — the on-disk copy outlives both the bound and the
    daemon."""

    def __init__(
        self,
        max_exemplars: int = DEFAULT_MAX_EXEMPLARS,
        spill_dir: Optional[str] = None,
    ) -> None:
        self.max_exemplars = max(1, int(max_exemplars))
        self.spill_dir = spill_dir
        self._lock = threading.Lock()
        self._by_id: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)

    def add(self, exemplar: dict) -> None:
        tid = exemplar["summary"]["trace_id"]
        with self._lock:
            self._by_id[tid] = exemplar
            self._by_id.move_to_end(tid)
            while len(self._by_id) > self.max_exemplars:
                self._by_id.popitem(last=False)
                METRICS.count("serve.trace.exemplars_evicted", 1)
            n = len(self._by_id)
        METRICS.count("serve.trace.exemplars", 1)
        METRICS.set_gauge("serve.trace.exemplar_count", n)
        if self.spill_dir:
            try:
                path = os.path.join(self.spill_dir, f"{tid}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(exemplar, f, sort_keys=True)
                os.replace(tmp, path)
            except OSError:
                METRICS.count("serve.trace.spill_errors", 1)

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            return self._by_id.get(trace_id)

    def summaries(self) -> List[dict]:
        """Newest-last list of exemplar summaries (the ``exemplars``
        serve op's listing; full trees fetched per trace id)."""
        with self._lock:
            return [
                {**access_record(ex["summary"]),
                 "incomplete": ex["incomplete"],
                 "n_events": len(ex["events"])}  # listing stays compact
                for ex in self._by_id.values()
            ]

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_id)


class TailSampler:
    """The always-on summary path + the breach trigger.

    ``observe`` is called once per completed request with its summary:
    it counts the request, and when the request breached — latency over
    ``threshold_ms``, a trigger outcome, or a tier-down hop — copies the
    request's full event set out of the (armed) tracer ring into the
    store.  ``threshold_ms <= 0`` disables the latency trigger (outcome
    and tier-down triggers stay live: a shed request is exemplar-worthy
    at any speed).
    """

    def __init__(
        self,
        store: ExemplarStore,
        threshold_ms: float = DEFAULT_THRESHOLD_MS,
        per_op_threshold_ms: Optional[Dict[str, float]] = None,
    ) -> None:
        self.store = store
        self.threshold_ms = float(threshold_ms)
        self.per_op_threshold_ms = dict(per_op_threshold_ms or {})

    def _threshold(self, op: str) -> float:
        return self.per_op_threshold_ms.get(op, self.threshold_ms)

    def would_sample(
        self, op: str, outcome: str, duration_ms: float, hops
    ) -> bool:
        """The trigger decision from the raw completion facts, without a
        built summary — the server's fast path skips the whole summary
        construction for the (vast majority of) requests that neither
        sample nor have an access log to feed.  Must stay equivalent to
        :meth:`should_sample`; tests/test_request_tracing.py pins the
        equivalence."""
        if outcome in TRIGGER_OUTCOMES:
            return True
        for h in hops:
            if h["hop"].startswith(TIERDOWN_HOP_PREFIXES):
                return True
        thr = self._threshold(op)
        return thr > 0 and duration_ms > thr

    def should_sample(self, summary: dict) -> Optional[str]:
        """The trigger that fired (None = no exemplar)."""
        if summary["outcome"] in TRIGGER_OUTCOMES:
            return f"outcome:{summary['outcome']}"
        if summary["tier_decisions"]:
            return f"tierdown:{summary['tier_decisions'][0]}"
        thr = self._threshold(summary["op"])
        if thr > 0 and summary["duration_ms"] > thr:
            return f"latency:{summary['duration_ms']:.1f}ms>{thr:.0f}ms"
        return None

    def observe(self, summary: dict) -> Optional[dict]:
        """One completed request; returns the exemplar if one was taken."""
        METRICS.count("serve.trace.requests", 1)
        trigger = self.should_sample(summary)
        if trigger is None:
            return None
        events: List[dict] = []
        dropped: Dict[str, int] = {}
        if TRACER.armed:
            events = TRACER.chrome_events_for_trace(summary["trace_id"])
            _, dropped = TRACER.drops_snapshot()
        ex = build_exemplar(dict(summary, trigger=trigger), events, dropped)
        self.store.add(ex)
        return ex
