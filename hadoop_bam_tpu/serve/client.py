"""Thin stdlib client for the resident daemon (serve/server.py framing).

One connection per request: the daemon's protocol is strictly
request/reply, so a persistent connection would only add failure modes
(half-closed sockets across daemon drains).  Every method raises
:class:`ServeError` on an ``ok: false`` reply — callers never have to
inspect protocol envelopes.

Failure policy (PR 7): transport-level failures — connection refused or
reset, a dropped connection before the reply, a stalled read past the
socket timeout — raise :class:`ServeConnectionError`, and *idempotent*
requests (ping/view/flagstat/job/stats) retry them a bounded number of
times with exponential backoff before giving up.  ``sort`` submissions
are never auto-retried (a resubmit is a second job).  :meth:`wait` polls
with jittered exponential backoff (0.05 s → ``poll_max``) instead of the
old fixed 0.05 s spin, and rides out a bounded streak of retryable
polling errors rather than dying on the first daemon hiccup.
"""

from __future__ import annotations

import base64
import random
import socket
import time
from typing import Optional

from ..utils.deadline import Deadline
from ..utils.tracing import RequestContext, current_request
from .admission import DEADLINE_EXCEEDED, JOB_LOST, RETRY_AFTER, SHED
from .server import recv_msg, send_msg


class ServeError(RuntimeError):
    """The daemon replied ok=false (the error string is the message).
    ``code`` carries the protocol error code when the reply had one."""

    code: Optional[str] = None


class ServeConnectionError(ServeError, ConnectionError):
    """A transport-level failure (refused/reset/dropped/stalled) — the
    retryable class; the daemon may be fine and merely mid-drain.  Also a
    ``ConnectionError`` so pre-existing callers catching ``OSError`` for
    connection trouble keep working."""


class ServeShedError(ServeError):
    """The daemon refused to admit the request (codes ``SHED`` /
    ``RETRY_AFTER``).  ``retry_after_ms`` is the server-computed backoff
    hint; idempotent requests honor it automatically."""

    def __init__(self, message: str, code: str = SHED, retry_after_ms: int = 50):
        super().__init__(message)
        self.code = code
        self.retry_after_ms = int(retry_after_ms)


class DeadlineExceededError(ServeError):
    """The request's end-to-end deadline expired (server- or client-side
    detected).  Never auto-retried — the budget is spent."""

    code = DEADLINE_EXCEEDED


class JobLostError(ServeError):
    """The daemon does not know this job id (code ``JOB_LOST``): it
    restarted and the journal could not account for the job, or the id
    never existed.  Terminal — ``wait`` raises it instead of polling an
    id that can never resolve."""

    code = JOB_LOST


#: code → typed exception; tests assert this map covers every code the
#: server can emit (``admission.ERROR_CODES``), so new codes cannot
#: silently degrade to the untyped ServeError.
_CODE_ERRORS = {
    SHED: ServeShedError,
    RETRY_AFTER: ServeShedError,
    DEADLINE_EXCEEDED: DeadlineExceededError,
    JOB_LOST: JobLostError,
}


def error_from_reply(reply: dict) -> ServeError:
    """The typed exception for an ``ok: false`` reply (the client half of
    the error-code round trip)."""
    msg = reply.get("error", "unknown daemon error")
    code = reply.get("code")
    cls = _CODE_ERRORS.get(code)
    if cls is ServeShedError:
        return ServeShedError(
            msg, code=code, retry_after_ms=reply.get("retry_after_ms", 50)
        )
    if cls is not None:
        return cls(msg)
    return ServeError(msg)


#: Exceptions worth retrying at the transport layer.  ``socket.timeout``
#: and the ``Connection*`` family are OSError subclasses, but transient
#: non-OSError paths (json of a half frame) surface as ServeConnectionError.
_RETRYABLE = (ServeConnectionError, socket.timeout, ConnectionError, OSError)


class ServeClient:
    def __init__(
        self,
        socket_path: Optional[str] = None,
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        timeout: float = 300.0,
        retries: int = 2,
        retry_backoff: float = 0.05,
    ):
        if socket_path is None and port is None:
            from .server import default_socket_path

            socket_path = default_socket_path()
        self.socket_path = socket_path
        self.host = host
        self.port = port
        self.timeout = timeout
        self.retries = retries
        self.retry_backoff = retry_backoff
        #: Trace id of the most recent request this client originated —
        #: the handle a caller joins against the daemon's exemplar
        #: store, access log, and ``tools/request_report.py``.
        self.last_trace_id: Optional[str] = None

    def _request_once(self, obj: dict) -> dict:
        if self.socket_path is not None:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            addr = self.socket_path
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            addr = (self.host, self.port)
        sock.settimeout(self.timeout)
        try:
            sock.connect(addr)
            send_msg(sock, obj)
            reply = recv_msg(sock)
        finally:
            sock.close()
        if reply is None:
            raise ServeConnectionError(
                "daemon closed the connection without a reply"
            )
        if not reply.get("ok"):
            raise error_from_reply(reply)
        return reply

    def _request(
        self,
        obj: dict,
        idempotent: bool = False,
        deadline: Optional[Deadline] = None,
    ) -> dict:
        """One request; idempotent ones retry transport failures with
        exponential backoff (``retries`` attempts beyond the first) and
        shed replies by the server's ``retry_after_ms`` hint.

        ``DEADLINE_EXCEEDED`` and ``JOB_LOST`` replies are never retried
        (terminal by definition).  With a ``deadline``, each attempt
        sends the *remaining* budget as ``deadline_ms`` and the retry
        loop itself stops — with :class:`DeadlineExceededError` — once
        the budget is spent, so a client deadline bounds the whole
        exchange, retries included.

        Every request carries a ``trace`` field: the client *originates*
        the 128-bit trace id (continuing any ambient
        :func:`~hadoop_bam_tpu.utils.tracing.request_scope` as a child
        span), the daemon continues it, and retries reuse it — one
        logical request is one trace whatever the transport did.  The
        id is kept in :attr:`last_trace_id`.

        Connection-reset/refused on an idempotent op — the signature of
        a daemon restart or a fleet hand-off — is retried with
        *jittered* backoff (±50%, so a fleet of clients bounced off the
        same dying daemon does not re-stampede it in lockstep), and the
        retry is a first-class ``client.retry`` hop on the request's
        trace: the waterfall names the transport failure and the pause
        instead of showing an unexplained gap.
        """
        ambient = current_request()
        rctx = (
            ambient.child(op=obj.get("op", ""))
            if ambient is not None
            else RequestContext.new(op=obj.get("op", ""))
        )
        obj["trace"] = rctx.to_wire()  # callers pass fresh dicts
        self.last_trace_id = rctx.trace_id
        attempts = (self.retries + 1) if idempotent else 1
        last: Optional[Exception] = None
        for attempt in range(attempts):
            if deadline is not None:
                rem = deadline.remaining_ms()
                if rem <= 0:
                    raise DeadlineExceededError(
                        "client deadline expired "
                        + ("before the request" if attempt == 0
                           else "between retries")
                    )
                obj = {**obj, "deadline_ms": rem}
            pause = self.retry_backoff * (2 ** attempt) * random.uniform(
                0.5, 1.5
            )
            try:
                return self._request_once(obj)
            except ServeShedError as e:
                if not idempotent:
                    raise  # a shed sort must stay the caller's decision
                last = e
                pause = max(pause, e.retry_after_ms / 1e3)
            except ServeError as e:
                if not isinstance(e, ServeConnectionError):
                    raise  # a real daemon reply: never retry
                last = e
            except _RETRYABLE as e:
                last = e
            if attempt + 1 < attempts:
                rctx.annotate(
                    "client.retry",
                    attempt=attempt + 1,
                    error=type(last).__name__,
                    pause_ms=pause * 1e3,
                )
                time.sleep(pause)
        assert last is not None
        raise (
            last
            if isinstance(last, ServeError)
            else ServeConnectionError(f"{type(last).__name__}: {last}")
        )

    # -- ops ----------------------------------------------------------------

    @staticmethod
    def _deadline(deadline_ms: Optional[float]) -> Optional[Deadline]:
        return None if deadline_ms is None else Deadline.after_ms(deadline_ms)

    def ping(self) -> dict:
        return self._request({"op": "ping"}, idempotent=True)

    def view(
        self,
        path: str,
        region: str,
        level: int = 6,
        deadline_ms: Optional[float] = None,
    ) -> bytes:
        """The region's records as a complete small BAM (bytes).
        ``deadline_ms`` is the end-to-end budget: the daemon cancels the
        work at its next seam once it expires (``DeadlineExceededError``)
        instead of finishing an answer nobody will read."""
        r = self._request(
            {"op": "view", "path": path, "region": region, "level": level},
            idempotent=True,
            deadline=self._deadline(deadline_ms),
        )
        return base64.b64decode(r["data_b64"])

    def flagstat(
        self, path: str, deadline_ms: Optional[float] = None
    ) -> dict:
        return self._request(
            {"op": "flagstat", "path": path},
            idempotent=True,
            deadline=self._deadline(deadline_ms),
        )["counts"]

    def variants(
        self,
        path: str,
        region: str,
        deadline_ms: Optional[float] = None,
    ) -> bytes:
        """The region's variant records as a complete small BCF (bytes),
        same reply contract as :meth:`view` for the variant plane."""
        r = self._request(
            {"op": "variants", "path": path, "region": region},
            idempotent=True,
            deadline=self._deadline(deadline_ms),
        )
        return base64.b64decode(r["data_b64"])

    def depth(
        self,
        path: str,
        region: str,
        bin_size: int = 1 << 12,
        per_base: bool = False,
        deadline_ms: Optional[float] = None,
    ) -> dict:
        """Pileup depth summary for an alignment region (dict: binned
        depth vector, max/mean, covered bases; ``per_base`` adds the
        exact vector under the server's span cap)."""
        r = self._request(
            {
                "op": "depth",
                "path": path,
                "region": region,
                "bin_size": bin_size,
                "per_base": per_base,
            },
            idempotent=True,
            deadline=self._deadline(deadline_ms),
        )
        return r["depth"]

    def sort(
        self, bam, output: str, deadline_ms: Optional[float] = None, **kwargs
    ) -> str:
        """Submit a sort; returns the job id (poll with :meth:`job` or
        block with :meth:`wait`).  Deliberately not auto-retried — a
        resubmitted request is a *second* job.  ``deadline_ms`` bounds
        the whole *job* server-side (the pipeline checks it down to the
        part-write attempt loop)."""
        req = {"op": "sort", "bam": bam, "output": output}
        req.update(kwargs)
        return self._request(req, deadline=self._deadline(deadline_ms))[
            "job"
        ]

    def ingest(
        self,
        fastq,
        output: str,
        r2: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        **kwargs,
    ) -> str:
        """Submit a FASTQ → collated-uBAM ingest job; returns the job id
        (poll with :meth:`job` or block with :meth:`wait`).  ``fastq``
        is the R1 (or sole) input path, or a [r1, r2] list; same job
        lifecycle as :meth:`sort` — not auto-retried, journaled, resumed
        from ``part_dir`` checkpoints on daemon restart."""
        paths = list(fastq) if isinstance(fastq, (list, tuple)) else [fastq]
        if r2 is not None:
            paths.append(r2)
        req = {"op": "ingest", "fastq": paths, "output": output}
        req.update(kwargs)
        return self._request(req, deadline=self._deadline(deadline_ms))[
            "job"
        ]

    def job(self, job_id: str) -> dict:
        return self._request({"op": "job", "id": job_id}, idempotent=True)

    def wait(
        self,
        job_id: str,
        timeout: float = 600.0,
        poll_s: float = 0.05,
        poll_max: float = 1.0,
        max_poll_errors: int = 5,
        deadline_ms: Optional[float] = None,
    ) -> dict:
        """Poll a submitted job to completion; raises on job failure.

        Polling backs off exponentially from ``poll_s`` to ``poll_max``
        with ±20% jitter (a fleet of waiters must not stampede the
        daemon in lockstep), and a streak of up to ``max_poll_errors``
        retryable transport errors — reset connections, stalled reads —
        is ridden out with the same backoff instead of aborting a job
        that is still running server-side.

        Two loss bounds (the old loop could poll a dead id forever at
        1 Hz): a ``JOB_LOST`` reply — or a journal-replayed ``lost``
        status — raises the typed :class:`JobLostError` immediately, and
        ``deadline_ms`` (the client's own end-to-end budget) caps the
        polling wall clock with :class:`DeadlineExceededError` on top of
        ``timeout``'s plain :class:`TimeoutError`.
        """
        client_dl = self._deadline(deadline_ms)
        deadline = time.monotonic() + timeout
        delay = poll_s
        errors_in_a_row = 0
        while True:
            try:
                st = self.job(job_id)
                errors_in_a_row = 0
            except JobLostError:
                raise  # terminal: the daemon does not know this job
            except _RETRYABLE as e:
                errors_in_a_row += 1
                if errors_in_a_row > max_poll_errors:
                    raise ServeConnectionError(
                        f"job {job_id}: {errors_in_a_row} consecutive "
                        f"polling failures (last: {type(e).__name__}: {e})"
                    ) from e
                st = None
            if st is not None:
                if st["status"] == "done":
                    return st
                if st["status"] == "lost":
                    raise JobLostError(
                        st.get("error", f"job {job_id} lost by the daemon")
                    )
                if st["status"] == "failed":
                    raise error_from_reply(
                        {"code": st.get("code"),
                         "error": st.get("error", "job failed")}
                    )
            if client_dl is not None and client_dl.expired:
                raise DeadlineExceededError(
                    f"job {job_id} not done within the client deadline "
                    f"({deadline_ms:.0f} ms)"
                )
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"job {job_id} not done after {timeout}s"
                )
            time.sleep(
                min(delay, max(deadline - time.monotonic(), 0.0))
                * random.uniform(0.8, 1.2)
            )
            delay = min(delay * 1.6, poll_max)

    def stats(self) -> dict:
        return self._request({"op": "stats"}, idempotent=True)

    def exemplars(self, trace_id: Optional[str] = None):
        """The daemon's tail-latency exemplars: without ``trace_id``,
        the compact listing (newest last); with one, the full exemplar —
        summary + the request's trace events + the completeness verdict
        (``incomplete: true`` when ring overflow ate part of the tree).
        """
        req = {"op": "exemplars"}
        if trace_id is not None:
            req["trace_id"] = trace_id
            return self._request(req, idempotent=True)["exemplar"]
        return self._request(req, idempotent=True)["exemplars"]

    def adopt(self, journal: str, source: Optional[str] = None) -> dict:
        """Direct this daemon to adopt a dead peer's journal: replay it,
        resume what the checkpoints can reproduce byte-identically under
        fresh local job ids, report the rest lost.  Returns the reply
        with ``adopted`` ({peer job id → local job id}) and ``lost``.
        Deliberately NOT idempotent-retried: a re-sent adopt would
        double-submit the resumable jobs (the fleet router, the normal
        caller, sends it exactly once per death)."""
        req = {"op": "adopt", "journal": journal}
        if source is not None:
            req["source"] = source
        return self._request(req)

    def warmth(
        self,
        path: str,
        export: bool = False,
        windows: Optional[list] = None,
        level: int = 1,
    ) -> dict:
        """The daemon's warm arena windows for ``path``: list (default),
        export as PR 15 compressed members (``export=True``), or install
        shipped ``windows`` into the local arena.  Listing/export are
        idempotent reads; an import is applied once."""
        req = {"op": "warmth", "path": path}
        if windows is not None:
            req["windows"] = windows
            return self._request(req)
        if export:
            req["export"] = True
            req["level"] = level
        return self._request(req, idempotent=True)

    def fleet(self) -> dict:
        """The front router's fleet view (ring ownership shares, member
        liveness, hand-off history).  Only the router answers this op;
        a plain daemon replies unknown-op."""
        return self._request({"op": "fleet"}, idempotent=True)

    def metrics(self) -> str:
        """The daemon's metrics in Prometheus text exposition format
        (cumulative counters/histograms + live gauges) — what a scraping
        sidecar would relay."""
        return self._request({"op": "metrics"}, idempotent=True)["text"]

    def shutdown(self) -> dict:
        """Graceful drain: the daemon finishes in-flight jobs, replies,
        then exits its accept loop."""
        return self._request({"op": "shutdown"})
