"""Thin stdlib client for the resident daemon (serve/server.py framing).

One connection per request: the daemon's protocol is strictly
request/reply, so a persistent connection would only add failure modes
(half-closed sockets across daemon drains).  Every method raises
:class:`ServeError` on an ``ok: false`` reply — callers never have to
inspect protocol envelopes.
"""

from __future__ import annotations

import base64
import socket
import time
from typing import Optional

from .server import recv_msg, send_msg


class ServeError(RuntimeError):
    """The daemon replied ok=false (the error string is the message)."""


class ServeClient:
    def __init__(
        self,
        socket_path: Optional[str] = None,
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        timeout: float = 300.0,
    ):
        if socket_path is None and port is None:
            from .server import default_socket_path

            socket_path = default_socket_path()
        self.socket_path = socket_path
        self.host = host
        self.port = port
        self.timeout = timeout

    def _request(self, obj: dict) -> dict:
        if self.socket_path is not None:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            addr = self.socket_path
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            addr = (self.host, self.port)
        sock.settimeout(self.timeout)
        try:
            sock.connect(addr)
            send_msg(sock, obj)
            reply = recv_msg(sock)
        finally:
            sock.close()
        if reply is None:
            raise ServeError("daemon closed the connection without a reply")
        if not reply.get("ok"):
            raise ServeError(reply.get("error", "unknown daemon error"))
        return reply

    # -- ops ----------------------------------------------------------------

    def ping(self) -> dict:
        return self._request({"op": "ping"})

    def view(self, path: str, region: str, level: int = 6) -> bytes:
        """The region's records as a complete small BAM (bytes)."""
        r = self._request(
            {"op": "view", "path": path, "region": region, "level": level}
        )
        return base64.b64decode(r["data_b64"])

    def flagstat(self, path: str) -> dict:
        return self._request({"op": "flagstat", "path": path})["counts"]

    def sort(self, bam, output: str, **kwargs) -> str:
        """Submit a sort; returns the job id (poll with :meth:`job` or
        block with :meth:`wait`)."""
        req = {"op": "sort", "bam": bam, "output": output}
        req.update(kwargs)
        return self._request(req)["job"]

    def job(self, job_id: str) -> dict:
        return self._request({"op": "job", "id": job_id})

    def wait(
        self, job_id: str, timeout: float = 600.0, poll_s: float = 0.05
    ) -> dict:
        """Poll a submitted job to completion; raises on job failure."""
        deadline = time.monotonic() + timeout
        while True:
            st = self.job(job_id)
            if st["status"] == "done":
                return st
            if st["status"] == "failed":
                raise ServeError(st.get("error", "job failed"))
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"job {job_id} still {st['status']} after {timeout}s"
                )
            time.sleep(poll_s)

    def stats(self) -> dict:
        return self._request({"op": "stats"})

    def shutdown(self) -> dict:
        """Graceful drain: the daemon finishes in-flight jobs, replies,
        then exits its accept loop."""
        return self._request({"op": "shutdown"})
