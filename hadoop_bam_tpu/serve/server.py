"""The resident daemon: a long-lived process that owns the TPU.

Transport is deliberately stdlib-only: a UNIX-domain socket (or a
127.0.0.1 TCP port) carrying length-prefixed JSON — 4 bytes big-endian
length, then a UTF-8 JSON object — one request per connection.  Binary
payloads (the ``view`` response BAM) ride base64 in the JSON; at the
"tiny responses, high QPS" design point the 4/3 expansion is noise next
to skipping a cold start.

Request ops:

- ``ping``                         → liveness + endpoint info
- ``view``  {path, region, level}  → base64 BAM of overlapping records
- ``flagstat`` {path}              → flag census counters
- ``sort``  {bam, output, ...}     → submit; returns a job id (the job
  runs through ``pipeline.sort_bam``, whose part writes already ride
  ``parallel.executor.ElasticExecutor`` — retries + atomic restarts)
- ``job``   {id}                   → job status/stats
- ``stats``                        → daemon-lifetime metrics delta +
  per-op latency histograms (p50/p95/p99) + arena/cache/queue gauges
- ``metrics``                      → Prometheus text exposition format
  (counters/histograms + live gauges, ready for a scraper)
- ``shutdown``                     → graceful drain: stop admitting,
  finish in-flight jobs, reply, exit the accept loop

Warm state (kernel jit caches via serve/warmup.py, header/index cache,
HBM residency arena, the cross-request lane batcher) lives in one
:class:`~hadoop_bam_tpu.serve.endpoints.ServeContext` for the daemon's
lifetime — the whole point of being resident.

Overload resilience (PR 10): every data-plane op passes the bounded
admission layer (serve/admission.py) — overload gets a *typed* refusal
(``code: SHED | RETRY_AFTER`` with a server-computed ``retry_after_ms``)
instead of unbounded queueing; a request's ``deadline_ms`` becomes a
:class:`~hadoop_bam_tpu.utils.deadline.Deadline` checked at every seam
down to the executor attempt loop (``code: DEADLINE_EXCEEDED``); device
``RESOURCE_EXHAUSTED`` degrades (arena evict → retry → host tier) rather
than killing the daemon; and with a journal configured
(serve/journal.py) job submissions/transitions survive a daemon crash —
a restart reports accurate terminal states, resumes interrupted sorts
byte-identically through the PR 7 checkpoints, and answers unknown ids
with ``code: JOB_LOST``.  SIGTERM/SIGINT drain like the ``shutdown`` op.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import socket
import struct
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from .. import faults
from ..conf import (
    Configuration,
    FLEET_DIR,
    FLEET_HEARTBEAT_MS,
    FLEET_NAME,
    SERVE_ACCESS_LOG,
    SERVE_ACCESS_LOG_BYTES,
    SERVE_ADMISSION_TOKENS,
    SERVE_EXEMPLAR_DIR,
    SERVE_EXEMPLAR_THRESHOLD_MS,
    SERVE_EXEMPLARS_MAX,
    SERVE_FLIGHTREC,
    SERVE_FLIGHTREC_BYTES,
    SERVE_FLIGHTREC_CADENCE_MS,
    SERVE_JOURNAL,
    SERVE_MAX_INFLIGHT,
    SERVE_MAX_QUEUE,
    SERVE_MAX_QUEUE_MS,
    SERVE_PORT,
    SERVE_REQUEST_TRACING,
    SERVE_SLO,
    SERVE_SLO_WINDOWS,
    SERVE_SOCKET,
    SERVE_WARMUP,
    TRACE_EVENTS,
)
from ..utils.deadline import Deadline, DeadlineExceeded, deadline_scope
from ..utils.tracing import (
    DEFAULT_TRACE_EVENTS,
    METRICS,
    TRACER,
    RequestContext,
    delta,
    prometheus_text,
    request_scope,
    snapshot,
    transfers_report,
)
from . import exemplars as exemplars_mod
from . import fleet as fleet_mod
from . import flightrec as flightrec_mod
from . import journal as journal_mod
from . import slo as slo_mod
from .admission import (
    DEADLINE_EXCEEDED,
    DEFAULT_MAX_QUEUE,
    DEFAULT_MAX_QUEUE_MS,
    DEFAULT_TOKENS,
    JOB_LOST,
    AdmissionController,
    ShedError,
)
from .endpoints import (
    ServeContext,
    depth_stat,
    flagstat,
    variants_blob,
    view_blob,
)

_LEN = struct.Struct(">I")
MAX_MESSAGE = 1 << 30
DEFAULT_MAX_INFLIGHT = 2

#: Every op the dispatcher understands.  New dispatch arms must land
#: here — the request-tracing lint (tests/test_request_tracing.py)
#: cross-checks this tuple against the ``if op == "…"`` literals in
#: ``_dispatch``, so an op cannot be added without being registered
#: (and thereby running under the dispatch RequestContext).
KNOWN_OPS = (
    "ping", "view", "flagstat", "variants", "depth", "sort", "ingest",
    "job", "stats", "metrics", "exemplars", "adopt", "warmth", "shutdown",
)

#: Data-plane ops whose completions feed the tail sampler and the access
#: log.  Control-plane ops (ping/stats/…) run under a RequestContext too
#: but record no summaries — a stats scrape per second must not flood
#: the per-request artifacts.
TRACED_OPS = ("view", "flagstat", "variants", "depth", "sort", "ingest")


def default_socket_path() -> str:
    """Per-user default UDS path (localhost TCP is the opt-in)."""
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"hbam-serve-{uid}.sock")


def send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_msg(sock: socket.socket) -> Optional[dict]:
    """One length-prefixed JSON message, or None on clean EOF."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > MAX_MESSAGE:
        raise ValueError(f"message of {n} bytes exceeds cap {MAX_MESSAGE}")
    body = _recv_exact(sock, n)
    if body is None:
        raise ConnectionError("truncated message")
    return json.loads(body.decode("utf-8"))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if not buf:
                return None  # clean EOF between messages
            raise ConnectionError("truncated message")
        buf.extend(chunk)
    return bytes(buf)


class BamDaemon:
    """Accept loop + request dispatch + bounded job pool + drain."""

    def __init__(
        self,
        conf: Optional[Configuration] = None,
        socket_path: Optional[str] = None,
        port: Optional[int] = None,
        host: str = "127.0.0.1",
        max_inflight: Optional[int] = None,
        warmup: Optional[bool] = None,
        warmup_kwargs: Optional[dict] = None,
        journal_path: Optional[str] = None,
        flightrec_path: Optional[str] = None,
    ):
        self.conf = conf or Configuration()
        faults.arm_from_conf(self.conf)  # drills via hadoopbam.faults.plan
        self.socket_path = socket_path or self.conf.get(SERVE_SOCKET)
        self.port = (
            port
            if port is not None
            else (self.conf.get_int(SERVE_PORT, 0) or None)
        )
        self.host = host
        if self.socket_path is None and self.port is None:
            self.socket_path = default_socket_path()
        self.max_inflight = max_inflight or self.conf.get_int(
            SERVE_MAX_INFLIGHT, DEFAULT_MAX_INFLIGHT
        )
        self.warmup = (
            warmup
            if warmup is not None
            else self.conf.get_boolean(SERVE_WARMUP, True)
        )
        self.warmup_kwargs = warmup_kwargs or {}
        self.warmup_report: Optional[dict] = None
        self.ctx = ServeContext.from_conf(self.conf)
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._handlers: List[threading.Thread] = []
        self._jobs: Dict[str, dict] = {}
        self._jobs_lock = threading.Lock()
        self._job_seq = 0
        self._job_pool = ThreadPoolExecutor(
            max_workers=self.max_inflight,
            thread_name_prefix="hbam-serve-job",
        )
        # Admission control: the bounded front door for data-plane ops.
        self.admission = AdmissionController(
            tokens=self.conf.get_int(SERVE_ADMISSION_TOKENS, DEFAULT_TOKENS),
            max_queue=self.conf.get_int(SERVE_MAX_QUEUE, DEFAULT_MAX_QUEUE),
            max_queue_ms=self.conf.get_int(
                SERVE_MAX_QUEUE_MS, DEFAULT_MAX_QUEUE_MS
            ),
        )
        # Crash-safe job journal (None = jobs die with the process, the
        # pre-PR-10 behavior; every journal touch below is one branch).
        self.journal_path = journal_path or self.conf.get(SERVE_JOURNAL)
        self._journal = (
            journal_mod.JobJournal(self.journal_path)
            if self.journal_path
            else None
        )
        # Flight recorder: periodic gauge/counter/ledger snapshots to a
        # bounded on-disk ring — after a kill -9, the replay explains
        # what the daemon was doing in its final seconds (the journal
        # already explains what it *owed*).  Unset = no recorder.
        self.flightrec_path = flightrec_path or self.conf.get(SERVE_FLIGHTREC)
        self._flightrec = (
            flightrec_mod.FlightRecorder(
                self.flightrec_path,
                cadence_s=self.conf.get_int(
                    SERVE_FLIGHTREC_CADENCE_MS,
                    flightrec_mod.DEFAULT_CADENCE_MS,
                ) / 1e3,
                max_bytes=self.conf.get_int(
                    SERVE_FLIGHTREC_BYTES, flightrec_mod.DEFAULT_RING_BYTES
                ),
                source=self._flight_snapshot,
            )
            if self.flightrec_path
            else None
        )
        # Request-scoped tracing plane (PR 12): every request runs under
        # a RequestContext (client-originated trace id, or minted at
        # dispatch); the tail sampler copies breaching requests' full
        # event sets out of the tracer ring into the bounded exemplar
        # store; the SLO monitor judges the op histograms against the
        # declared objectives; the access log writes one line per
        # completed data-plane request.
        self.request_tracing = self.conf.get_boolean(
            SERVE_REQUEST_TRACING, True
        )
        self._owns_tracer = False
        self.exemplars = exemplars_mod.ExemplarStore(
            max_exemplars=self.conf.get_int(
                SERVE_EXEMPLARS_MAX, exemplars_mod.DEFAULT_MAX_EXEMPLARS
            ),
            spill_dir=self.conf.get(SERVE_EXEMPLAR_DIR),
        )
        self.sampler = exemplars_mod.TailSampler(
            self.exemplars,
            threshold_ms=float(
                self.conf.get_int(
                    SERVE_EXEMPLAR_THRESHOLD_MS,
                    int(exemplars_mod.DEFAULT_THRESHOLD_MS),
                )
            ),
            # Sort jobs are minutes-long by design: only their failures
            # are exemplar-worthy, never their (expected) duration.
            per_op_threshold_ms={"sort.job": 0.0},
        )
        self.slo = slo_mod.SloMonitor.from_conf(self.conf)
        access_log_path = self.conf.get(SERVE_ACCESS_LOG)
        self._access_log = (
            flightrec_mod.AccessLog(
                access_log_path,
                max_bytes=self.conf.get_int(
                    SERVE_ACCESS_LOG_BYTES,
                    flightrec_mod.DEFAULT_ACCESS_LOG_BYTES,
                ),
            )
            if access_log_path
            else None
        )
        # Fleet membership (PR 18): with hadoopbam.fleet.dir set, the
        # daemon publishes an atomic member record (name, endpoint,
        # journal path, flight-recorder base) in the shared fleet
        # directory and refreshes it on a heartbeat cadence; the front
        # router (serve/router.py) builds its consistent-hash ring from
        # these records and reads a gone-stale one as a death signal.
        self.fleet_dir = self.conf.get(FLEET_DIR)
        self.fleet_name = (
            self.conf.get(FLEET_NAME) or f"daemon-{os.getpid()}"
        )
        self._heartbeater: Optional[fleet_mod.Heartbeater] = None
        self._drain_requested = threading.Event()
        self._started_snapshot = snapshot()

    # -- lifecycle ----------------------------------------------------------

    @property
    def endpoint(self) -> dict:
        if self.socket_path is not None:
            return {"socket": self.socket_path}
        return {"host": self.host, "port": self.port}

    def start(self) -> None:
        """Bind the listener and run the startup warm-up (idempotent);
        with a journal configured, replay it first so recovered jobs are
        answerable from the first accepted connection."""
        if self._listener is not None:
            return
        if self.request_tracing and not TRACER.armed:
            # The tracing plane needs the ring live so exemplars have
            # events to copy out; the daemon owns (and disarms on
            # shutdown) what it armed — a CLI --trace in the same
            # process keeps its own ring.
            TRACER.start(
                capacity=self.conf.get_int(
                    TRACE_EVENTS, DEFAULT_TRACE_EVENTS
                )
            )
            self._owns_tracer = True
        if self._journal is not None:
            self._recover_journal()
        if self.warmup and self.warmup_report is None:
            from .warmup import warm_kernels

            self.warmup_report = warm_kernels(
                self.conf, **self.warmup_kwargs
            )
        if self.socket_path is not None:
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)
            lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            lst.bind(self.socket_path)
        else:
            lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lst.bind((self.host, self.port or 0))
            self.port = lst.getsockname()[1]
        lst.listen(64)
        lst.settimeout(0.1)
        self._listener = lst
        if self._flightrec is not None:
            self._flightrec.start()
        if self.fleet_dir:
            # Heartbeat only after the endpoint is final (a TCP daemon
            # learns its port at bind): the first record the router sees
            # is already routable.
            self._heartbeater = fleet_mod.Heartbeater(
                self.fleet_dir,
                self._fleet_member_record,
                period_s=self.conf.get_int(
                    FLEET_HEARTBEAT_MS, fleet_mod.DEFAULT_HEARTBEAT_MS
                ) / 1e3,
            )
            self._heartbeater.start()
        METRICS.count("serve.daemon_starts", 1)

    def _fleet_member_record(self) -> dict:
        """The heartbeat payload: everything a router (or post-mortem
        tool) needs to route to, or recover from, this daemon."""
        return {
            "name": self.fleet_name,
            "endpoint": self.endpoint,
            "journal": self.journal_path,
            "flightrec": self.flightrec_path,
            "pid": os.getpid(),
            "draining": self._draining.is_set(),
        }

    def _recover_journal(self) -> None:
        """Replay the journal: restore terminal states, resume what the
        PR 7 checkpoints can reproduce byte-identically, mark the rest
        lost.  Never raises — recovery failure degrades to an empty job
        table, not a daemon that won't start."""
        try:
            jobs = journal_mod.replay(self.journal_path)
        except ValueError:
            METRICS.count("serve.journal.corrupt", 1)
            return
        plan = journal_mod.recovery_plan(jobs)
        seq = 0
        with self._jobs_lock:
            for jid, job in jobs.items():
                # Ids look like job-0042; keep numbering past them so a
                # resumed daemon never reuses a journaled id.
                try:
                    seq = max(seq, int(jid.rsplit("-", 1)[-1]))
                except ValueError:
                    pass
                entry = {
                    "status": job["status"],
                    "output": (job.get("req") or {}).get("output"),
                }
                for k in ("stats", "error"):
                    if k in job:
                        entry[k] = job[k]
                action = plan.get(jid)
                if action == "lost":
                    entry["status"] = "lost"
                    entry["error"] = (
                        "job interrupted by a daemon crash and not "
                        "resumable (no part_dir checkpoint, or the "
                        "input files changed)"
                    )
                    METRICS.count("serve.journal.lost", 1)
                elif action == "resume":
                    entry["status"] = "queued"
                else:
                    METRICS.count("serve.journal.replayed", 1)
                self._jobs[jid] = entry
            self._job_seq = max(self._job_seq, seq)
        for jid, action in sorted(plan.items()):
            if action != "resume":
                continue
            METRICS.count("serve.journal.resumed", 1)
            if self._journal is not None:
                self._journal.state(jid, "resumed")
            self._job_pool.submit(
                self._run_job, jid, dict(jobs[jid]["req"])
            )

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain, exactly like the ``shutdown``
        op (finish in-flight jobs, then exit the accept loop).  A no-op
        off the main thread (Python restricts signal handling there) —
        the CLI calls this; embedded/test daemons use :meth:`stop`."""

        def _handler(signum, frame):
            METRICS.count("serve.signal_drains", 1)
            self._drain_requested.set()

        try:
            signal.signal(signal.SIGTERM, _handler)
            signal.signal(signal.SIGINT, _handler)
        except ValueError:
            pass  # not the main thread

    def serve_forever(self, ready: Optional[threading.Event] = None) -> None:
        """Blocking accept loop until a ``shutdown`` request (or
        :meth:`stop`).  ``ready`` is set once requests can connect —
        the hook tests and the CLI's readiness print use."""
        self.start()
        if ready is not None:
            ready.set()
        try:
            while not self._stop.is_set():
                if self._drain_requested.is_set():
                    # Signal-initiated drain: same semantics as the
                    # shutdown op, minus a reply socket.
                    self._drain()
                    break
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                t = threading.Thread(
                    target=self._handle, args=(conn,), daemon=True
                )
                t.start()
                self._handlers.append(t)
                self._handlers = [h for h in self._handlers if h.is_alive()]
        finally:
            self._shutdown_cleanup()

    def stop(self) -> None:
        """Out-of-band stop (signal handlers); requests should prefer the
        ``shutdown`` op, which drains jobs before stopping."""
        self._stop.set()

    def _shutdown_cleanup(self) -> None:
        if self._heartbeater is not None:
            # The final beat carries the current draining flag: a
            # drained daemon's last record says so, and the router
            # treats its silence as a planned exit (the flight
            # recorder's final snapshot is the authoritative evidence).
            self._heartbeater.stop()
            self._heartbeater = None
        for h in list(self._handlers):
            h.join(timeout=5.0)
        self._job_pool.shutdown(wait=True)
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        if self.socket_path and os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        if self._flightrec is not None:
            # Finalize the ring (idempotent — a drain already wrote the
            # final snapshot; a kill never reaches here, which is the
            # point: no final record = unclean death).
            self._flightrec.stop(final=True)
        if self._journal is not None:
            self._journal.close()
        if self._access_log is not None:
            self._access_log.close()
        if self._owns_tracer:
            TRACER.stop()
            self._owns_tracer = False
        self.ctx.close()

    # -- request handling ---------------------------------------------------

    def _handle(self, conn: socket.socket) -> None:
        import time as _time

        stop_after = False
        try:
            with conn:
                req = recv_msg(conn)
                if req is None:
                    return
                op = req.get("op")
                # The request's identity: continue the client's trace
                # (Dapper propagation — the wire carries trace_id/
                # span_id/baggage) or originate one at dispatch.  None
                # when the plane is off: every seam below is then one
                # is-None branch, the fault-seam disarmed contract.
                rctx = None
                if self.request_tracing:
                    rctx = RequestContext.from_wire(
                        req.get("trace"), op=op
                    ) or RequestContext.new(op=op)
                t0 = _time.perf_counter()
                with request_scope(rctx):
                    try:
                        reply, stop_after = self._dispatch(req)
                    except ShedError as e:
                        # Typed load shedding: the client gets the code
                        # AND the server-computed backoff hint —
                        # overload is an answer, not a timeout.
                        reply = {
                            "ok": False,
                            "code": e.code,
                            "error": str(e),
                            "retry_after_ms": e.retry_after_ms,
                        }
                    except DeadlineExceeded as e:
                        reply = {
                            "ok": False,
                            "code": DEADLINE_EXCEEDED,
                            "error": str(e),
                            "seam": e.seam,
                        }
                    except Exception as e:  # noqa: BLE001 - reply, don't die
                        METRICS.count("serve.request_errors", 1)
                        reply = {
                            "ok": False,
                            "error": f"{type(e).__name__}: {e}",
                        }
                    # Per-op latency histogram (log2 ms buckets →
                    # p50/p95/p99 in the stats/metrics ops without
                    # unbounded memory) + per-op error counter (the SLO
                    # monitor's availability numerator rides on these).
                    METRICS.observe(
                        f"serve.op.{op}.ms",
                        (_time.perf_counter() - t0) * 1e3,
                    )
                    if not reply.get("ok"):
                        METRICS.count(f"serve.op.{op}.errors", 1)
                    dropped_reply = False
                    if faults.ACTIVE is not None:
                        # The serve-socket fault seam: dropped
                        # connections and stalled replies, injected
                        # between dispatch and send so the client's
                        # retry/backoff path is what's proven (the
                        # request itself already executed — exactly the
                        # ambiguity a real connection drop leaves
                        # behind).
                        act = faults.ACTIVE.serve_action(op)
                        if act is not None and act["action"] == "drop":
                            dropped_reply = True
                        elif act is not None and act["action"] == "stall":
                            ts = _time.perf_counter()
                            _time.sleep(act["ms"] / 1e3)
                            if rctx is not None:
                                # The injected stall is a hop like any
                                # other: the waterfall must name it as
                                # the blocking reason, not leave a gap.
                                rctx.annotate(
                                    "reply.stall",
                                    ms=(
                                        _time.perf_counter() - ts
                                    ) * 1e3,
                                    injected=True,
                                )
                    if rctx is not None:
                        reply.setdefault("trace_id", rctx.trace_id)
                        self._finish_request(
                            rctx, op, reply, dropped_reply
                        )
                    if dropped_reply:
                        return  # close without replying
                send_msg(conn, reply)
        except Exception:
            METRICS.count("serve.connection_errors", 1)
        finally:
            if stop_after:
                self._stop.set()

    def _finish_request(
        self,
        rctx: RequestContext,
        op: Optional[str],
        reply: dict,
        dropped_reply: bool = False,
    ) -> None:
        """The always-on completion path for data-plane requests: fold
        the hop annotations into a compact summary, feed the tail
        sampler (exemplar copy-out happens here, before the ring can
        evict the events), and write the access-log line."""
        if op not in TRACED_OPS:
            return
        outcome = (
            "OK" if reply.get("ok") else (reply.get("code") or "ERROR")
        )
        duration_ms = rctx.elapsed_ms()
        if self._access_log is None and not self.sampler.would_sample(
            op, outcome, duration_ms, rctx.hops
        ):
            # Fast path for the healthy majority: count the request,
            # build nothing (no access log to feed, nothing to sample).
            METRICS.count("serve.trace.requests", 1)
            return
        extra = {"dropped_reply": True} if dropped_reply else None
        summary = exemplars_mod.request_summary(
            rctx, outcome, duration_ms, op=op, extra=extra
        )
        self.sampler.observe(summary)
        if self._access_log is not None:
            self._access_log.log(exemplars_mod.access_record(summary))

    def _dispatch(self, req: dict) -> Tuple[dict, bool]:
        op = req.get("op")
        METRICS.count(f"serve.op.{op}", 1)
        # The end-to-end deadline, if the client sent one: checked here
        # (dispatch seam) and carried through admission, the endpoint
        # window loops, the lane batcher, and the executor attempt loop.
        deadline = Deadline.from_request(req)
        if deadline is not None:
            deadline.check("dispatch")
        if op == "ping":
            return (
                {
                    "ok": True,
                    "pid": os.getpid(),
                    "endpoint": self.endpoint,
                    "draining": self._draining.is_set(),
                },
                False,
            )
        if op == "view":
            with self.admission.acquire(op, deadline=deadline), \
                    deadline_scope(deadline):
                blob = view_blob(
                    self.ctx,
                    req["path"],
                    req["region"],
                    level=int(req.get("level", 6)),
                    deadline=deadline,
                )
            return (
                {
                    "ok": True,
                    "data_b64": base64.b64encode(blob).decode("ascii"),
                },
                False,
            )
        if op == "flagstat":
            with self.admission.acquire(op, deadline=deadline), \
                    deadline_scope(deadline):
                counts = flagstat(self.ctx, req["path"], deadline=deadline)
            return ({"ok": True, "counts": counts}, False)
        if op == "variants":
            # The BCF region query: same admission + deadline + reply
            # shape as view (a small complete file, base64 over the
            # framed socket), backed by the variant-plane endpoint.
            with self.admission.acquire(op, deadline=deadline), \
                    deadline_scope(deadline):
                blob = variants_blob(
                    self.ctx,
                    req["path"],
                    req["region"],
                    deadline=deadline,
                )
            return (
                {
                    "ok": True,
                    "data_b64": base64.b64encode(blob).decode("ascii"),
                },
                False,
            )
        if op == "depth":
            with self.admission.acquire(op, deadline=deadline), \
                    deadline_scope(deadline):
                stat = depth_stat(
                    self.ctx,
                    req["path"],
                    req["region"],
                    bin_size=int(req.get("bin_size", 1 << 12)),
                    per_base=bool(req.get("per_base", False)),
                    deadline=deadline,
                )
            return ({"ok": True, "depth": stat}, False)
        if op == "sort":
            if self._draining.is_set():
                return ({"ok": False, "error": "daemon is draining"}, False)
            # The job holds its admission tokens for its whole lifetime
            # (released in _run_job), so queued+running jobs weigh on
            # the same budget concurrent views contend for.
            ticket = self.admission.acquire(op, deadline=deadline)
            try:
                jid = self._submit_job(req, ticket, deadline)
            except BaseException:
                ticket.release()
                raise
            return ({"ok": True, "job": jid}, False)
        if op == "ingest":
            # FASTQ → collated-uBAM job: same lifecycle as sort (job id,
            # whole-lifetime admission ticket, journal durable-before-
            # pool, crash resume via part_dir) — the write-heavy op the
            # fleet routes alongside the sort traffic.
            if self._draining.is_set():
                return ({"ok": False, "error": "daemon is draining"}, False)
            ticket = self.admission.acquire(op, deadline=deadline)
            try:
                jid = self._submit_job(req, ticket, deadline)
            except BaseException:
                ticket.release()
                raise
            return ({"ok": True, "job": jid}, False)
        if op == "job":
            with self._jobs_lock:
                job = self._jobs.get(req.get("id"))
            if job is None:
                # Typed: a restarted daemon without (or beyond) journal
                # coverage must tell waiters the job is gone, not leave
                # them polling an id that can never resolve.
                return (
                    {
                        "ok": False,
                        "code": JOB_LOST,
                        "error": f"unknown job id {req.get('id')!r}",
                    },
                    False,
                )
            return ({"ok": True, **job}, False)
        if op == "stats":
            return ({"ok": True, **self._stats()}, False)
        if op == "exemplars":
            # The tail sampler's export surface: the listing (compact
            # summaries, newest last), or one full exemplar — summary +
            # the request's ring events + the completeness verdict — by
            # trace id.  Control plane: never gated, so post-mortems
            # work under overload.
            tid = req.get("trace_id")
            if tid:
                ex = self.exemplars.get(tid)
                if ex is None:
                    return (
                        {
                            "ok": False,
                            "error": f"no exemplar for trace {tid!r} "
                            "(not sampled, or evicted from the store)",
                        },
                        False,
                    )
                return ({"ok": True, "exemplar": ex}, False)
            return (
                {"ok": True, "exemplars": self.exemplars.summaries()},
                False,
            )
        if op == "metrics":
            # Prometheus text exposition: cumulative process counters +
            # full histogram buckets (Prometheus counters are cumulative
            # by convention; scrapers rate() them) plus the live gauges.
            return (
                {
                    "ok": True,
                    "content_type": "text/plain; version=0.0.4",
                    "text": prometheus_text(
                        snapshot(), gauges=self._gauges()
                    ),
                },
                False,
            )
        if op == "adopt":
            # Fleet hand-off (control plane — a death must be recoverable
            # even while this daemon sheds data-plane load): replay a
            # dead peer's journal and resume what the checkpoints can
            # reproduce byte-identically, under fresh local job ids.
            return (self._adopt(req), False)
        if op == "warmth":
            # Arena warmth as a first-class surface: list, export as
            # PR 15 compressed members, or install a peer's shipped
            # windows (planned fleet hand-offs move warmth, not just
            # jobs).
            return (self._warmth(req), False)
        if op == "shutdown":
            return (self._drain(), True)
        return ({"ok": False, "error": f"unknown op {op!r}"}, False)

    # -- fleet hand-off -----------------------------------------------------

    def _adopt(self, req: dict) -> dict:
        """Adopt a dead peer's journal (the router's recovery action).

        Replays the peer journal, plans recovery exactly as a restart of
        the peer would (:func:`~hadoop_bam_tpu.serve.journal.recovery_plan`
        — inputs identity must still match and the request must carry a
        persistent ``part_dir``), then resubmits each resumable job
        under a *fresh local* job id, journaled locally durable-before-
        submit so a crash of the adopter is itself recoverable.  Returns
        ``{"adopted": {peer jid: local jid}, "lost": [...]}``."""
        jpath = req.get("journal")
        if not jpath:
            return {"ok": False, "error": "adopt needs a journal path"}
        try:
            jobs = journal_mod.replay(jpath)
        except (ValueError, OSError) as e:
            METRICS.count("serve.adopt.journal_errors", 1)
            return {
                "ok": False,
                "error": f"peer journal {jpath!r} unreadable: {e}",
            }
        plan = journal_mod.recovery_plan(jobs)
        adopted: Dict[str, str] = {}
        lost: List[str] = []
        for peer_jid, action in sorted(plan.items()):
            if action != "resume":
                lost.append(peer_jid)
                METRICS.count("serve.adopt.lost", 1)
                continue
            peer_req = dict(jobs[peer_jid]["req"])
            with self._jobs_lock:
                self._job_seq += 1
                jid = f"job-{self._job_seq:04d}"
                self._jobs[jid] = {
                    "status": "queued",
                    "output": peer_req.get("output"),
                    "adopted_from": {
                        "job": peer_jid,
                        "source": req.get("source"),
                    },
                }
            if self._journal is not None:
                # Durable locally before the pool sees it — adoption
                # re-homes the job's crash-safety, not just its work.
                self._journal.submit(
                    jid, peer_req, jobs[peer_jid].get("inputs")
                )
                self._journal.state(jid, "adopted", source=req.get("source"))
            self._job_pool.submit(self._run_job, jid, peer_req)
            adopted[peer_jid] = jid
            METRICS.count("serve.adopt.resumed", 1)
        METRICS.count("serve.adoptions", 1)
        return {
            "ok": True,
            "adopted": adopted,
            "lost": lost,
            "jobs_seen": len(jobs),
        }

    def _warmth(self, req: dict) -> dict:
        """The arena-warmth surface behind the ``warmth`` op: list this
        daemon's warm windows for a path, export them as compressed
        members, or install windows a peer shipped."""
        path = req.get("path")
        if not path:
            return {"ok": False, "error": "warmth needs a path"}
        if req.get("windows") is not None:
            installed = fleet_mod.unpack_windows(
                self.ctx.arena, path, req["windows"]
            )
            return {"ok": True, "installed": installed}
        keys = fleet_mod._arena_keys_for(self.ctx.arena, path)
        if not req.get("export"):
            return {
                "ok": True,
                "windows": [
                    {"kind": k[0], "span": [int(k[2]), int(k[3])]}
                    for k in keys
                ],
            }
        return {
            "ok": True,
            "windows": fleet_mod.pack_windows(
                self.ctx.arena, path, level=int(req.get("level", 1))
            ),
        }

    # -- sort / ingest jobs -------------------------------------------------

    @staticmethod
    def _job_kind(req: dict) -> str:
        """A job request's kind, from its payload rather than ``op`` —
        journal replays and peer adoptions carry the req dict without
        the op key, and must resume as what they were."""
        return "ingest" if "fastq" in req else "sort"

    @staticmethod
    def _job_inputs(req: dict) -> List[str]:
        paths = req.get("fastq") if "fastq" in req else req.get("bam")
        if isinstance(paths, str):
            paths = [paths]
        return list(paths or [])

    def _submit_job(
        self, req: dict, ticket=None, deadline: Optional[Deadline] = None
    ) -> str:
        # The job continues the submission's trace on the pool thread as
        # a child span (thread-locals do not follow a submit): every
        # pipeline/executor event the job emits carries the same trace
        # id the client originated.
        from ..utils.tracing import current_request

        rctx = current_request()
        kind = self._job_kind(req)
        job_ctx = rctx.child(op=f"{kind}.job") if rctx is not None else None
        with self._jobs_lock:
            self._job_seq += 1
            jid = f"job-{self._job_seq:04d}"
            self._jobs[jid] = {
                "status": "queued",
                "output": req.get("output"),
            }
            if job_ctx is not None:
                self._jobs[jid]["trace_id"] = job_ctx.trace_id
        if self._journal is not None:
            # Durable before the pool sees it: a crash between this
            # append and the submit leaves a journaled job the restart
            # resumes (or reports lost) — never one nobody remembers.
            self._journal.submit(
                jid,
                {k: v for k, v in req.items() if k != "op"},
                journal_mod.input_identity(self._job_inputs(req)),
            )
        self._job_pool.submit(
            self._run_job, jid, dict(req), ticket, deadline, job_ctx
        )
        METRICS.count("serve.jobs_submitted", 1)
        return jid

    def _journal_state(self, jid: str, status: str, **extra) -> None:
        if self._journal is not None:
            try:
                self._journal.state(jid, status, **extra)
            except OSError:
                METRICS.count("serve.journal.append_errors", 1)
        from ..utils.tracing import current_request

        rctx = current_request()
        if rctx is not None:
            # Journal transitions are request hops: the waterfall of a
            # crashed-then-resumed job shows its state machine inline.
            rctx.annotate("journal.state", job=jid, status=status)

    def _run_job(
        self,
        jid: str,
        req: dict,
        ticket=None,
        deadline: Optional[Deadline] = None,
        rctx: Optional[RequestContext] = None,
    ) -> None:
        kind = self._job_kind(req)
        with self._jobs_lock:
            self._jobs[jid]["status"] = "running"
        outcome = "OK"
        with request_scope(rctx):
            self._journal_state(jid, "running")
            try:
                if kind == "ingest":
                    from ..ingest import ingest_fastq

                    stats = ingest_fastq(
                        self._job_inputs(req),
                        req["output"],
                        conf=self.conf,
                        level=int(req.get("level", 6)),
                        memory_budget=req.get("memory_budget"),
                        part_dir=req.get("part_dir"),
                        errors=req.get("errors"),
                        deadline=deadline,
                        resource_cache=self.ctx.cache,
                    )
                    stats_d = {
                        "n_records": stats.n_records,
                        "n_pairs": stats.n_pairs,
                        "n_members": stats.n_members,
                        "out_bytes": stats.out_bytes,
                    }
                else:
                    from ..pipeline import sort_bam

                    stats = sort_bam(
                        self._job_inputs(req),
                        req["output"],
                        conf=self.conf,
                        level=int(req.get("level", 6)),
                        memory_budget=req.get("memory_budget"),
                        part_dir=req.get("part_dir"),
                        write_splitting_bai=bool(
                            req.get("write_splitting_bai")
                        ),
                        mark_duplicates=bool(req.get("mark_duplicates")),
                        sort_order=req.get("sort_order"),
                        resource_cache=self.ctx.cache,
                        deadline=deadline,
                    )
                    stats_d = {
                        "n_records": stats.n_records,
                        "n_splits": stats.n_splits,
                        "backend": stats.backend,
                        "n_duplicates": stats.n_duplicates,
                    }
                with self._jobs_lock:
                    self._jobs[jid].update(status="done", stats=stats_d)
                self._journal_state(jid, "done", stats=stats_d)
            except DeadlineExceeded as e:
                outcome = DEADLINE_EXCEEDED
                METRICS.count("serve.jobs_failed", 1)
                with self._jobs_lock:
                    self._jobs[jid].update(
                        status="failed", code=DEADLINE_EXCEEDED,
                        error=str(e),
                    )
                self._journal_state(jid, "failed", error=str(e))
            except Exception as e:  # noqa: BLE001 - job status carries it
                outcome = "ERROR"
                METRICS.count("serve.jobs_failed", 1)
                err = f"{type(e).__name__}: {e}"
                with self._jobs_lock:
                    self._jobs[jid].update(status="failed", error=err)
                self._journal_state(jid, "failed", error=err)
            finally:
                if ticket is not None:
                    ticket.release()
                if rctx is not None:
                    # The job's own completion record: same trace id as
                    # the submission, op "<kind>.job", so a failed or
                    # slow job earns its exemplar even though the
                    # submission request replied fast.
                    summary = exemplars_mod.request_summary(
                        rctx, outcome, rctx.elapsed_ms(),
                        op=f"{kind}.job", extra={"job": jid},
                    )
                    self.sampler.observe(summary)
                    if self._access_log is not None:
                        self._access_log.log(
                            exemplars_mod.access_record(summary)
                        )

    # -- stats / drain ------------------------------------------------------

    def _gauges(self) -> Dict[str, float]:
        """Point-in-time gauges: arena/cache occupancy, batcher queue
        depth, job-pool pressure — the daemon's live resource state next
        to the cumulative counters."""
        arena = self.ctx.arena.stats()
        cache = self.ctx.cache.stats()
        with self._jobs_lock:
            statuses = [j["status"] for j in self._jobs.values()]
        # First-class registry gauges ride along (HBM ledger levels, the
        # arena's own set_gauge() values): subsystems publish once, every
        # surface — stats, metrics op, flight recorder — sees them.
        g = METRICS.gauges()
        g.update({
            "serve.arena.used_bytes": arena["used_bytes"],
            "serve.arena.budget_bytes": arena["budget_bytes"],
            "serve.arena.entries": arena["entries"],
            "serve.arena.device_resident": arena["device_resident"],
            "serve.cache.used_bytes": cache["used_bytes"],
            "serve.cache.budget_bytes": cache["budget_bytes"],
            "serve.cache.entries": cache["entries"],
            "serve.jobs.queued": sum(
                1 for s in statuses if s == "queued"
            ),
            "serve.jobs.running": sum(
                1 for s in statuses if s == "running"
            ),
            "serve.jobs.max_inflight": self.max_inflight,
            "serve.draining": int(self._draining.is_set()),
        })
        g.update(self.admission.gauges())
        if self.ctx.batcher is not None:
            g["serve.batch.queue_depth"] = self.ctx.batcher.queue_depth()
        g["serve.trace.exemplar_count"] = len(self.exemplars)
        return g

    def _flight_snapshot(self) -> dict:
        """The flight recorder's per-tick source: live gauges + the
        degradation-class counters (sheds, OOM, journal, HBM leaks)."""
        counters = METRICS.report()["counters"]
        rec = {
            "gauges": self._gauges(),
            "counters": {
                k: v
                for k, v in counters.items()
                if k.startswith(flightrec_mod.SNAPSHOT_COUNTER_PREFIXES)
            },
        }
        try:
            # SLO state rides every snapshot: a post-mortem replay shows
            # which objectives were burning in the final seconds.
            rec["slo"] = self.slo.brief()
        except Exception:  # noqa: BLE001 - the recorder never kills
            METRICS.count("serve.flightrec.source_errors", 1)
        return rec

    def _stats(self) -> dict:
        # Snapshot/delta exclusively — never reset(): the daemon-lifetime
        # delta keeps the process-global registry untouched, so any
        # concurrent request doing its own per-request delta accounting
        # stays correct (MetricsRegistry.reset's documented hazard).
        report = delta(self._started_snapshot)
        # Histograms carry only count/sum through a delta; the percentile
        # summaries are cumulative-distribution properties, so surface the
        # live ones (per-op p50/p95/p99 latency, observed daemon-side).
        report["histograms"] = snapshot()["histograms"]
        report["transfers"] = transfers_report(report["counters"])
        with self._jobs_lock:
            jobs = {k: dict(v) for k, v in self._jobs.items()}
        return {
            "metrics": report,
            "gauges": self._gauges(),
            "cache": self.ctx.cache.stats(),
            "arena": self.ctx.arena.stats(),
            "jobs": jobs,
            "warmup": self.warmup_report,
            "draining": self._draining.is_set(),
            # The SLO judgment: current burn rates per objective over
            # the fast/slow windows, window compliance, the worst op,
            # and the alert set — evaluated here, so every stats scrape
            # is also an SLO sample point.
            "slo": self.slo.evaluate(),
        }

    def _drain(self) -> dict:
        """Graceful shutdown: refuse new jobs, finish the in-flight ones,
        report what was drained.  The caller gets the reply before the
        accept loop exits (the stop flag is set by the handler after the
        reply is on the wire)."""
        self._draining.set()
        self._job_pool.shutdown(wait=True)
        if self._flightrec is not None:
            # The drain IS the clean-death marker: the final snapshot
            # lands before the reply, so a ring whose last record is not
            # final means the daemon died, not drained.
            self._flightrec.stop(final=True)
        with self._jobs_lock:
            statuses = [j["status"] for j in self._jobs.values()]
        METRICS.count("serve.drains", 1)
        return {
            "ok": True,
            "drained": True,
            "jobs_total": len(statuses),
            "jobs_done": sum(1 for s in statuses if s == "done"),
            "jobs_failed": sum(1 for s in statuses if s == "failed"),
        }
