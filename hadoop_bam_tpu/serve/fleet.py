"""Fleet plumbing: the ring, membership, death forensics, warmth packing.

One daemon owns one TPU; the millions-of-users story needs N of them
behind a front router (serve/router.py) without giving up anything the
single daemon earned — warm caches, crash-safe jobs, explainable deaths.
This module is the shared substrate both sides stand on:

- :class:`HashRing` — consistent hashing over the existing
  ``(path, size, mtime_ns)`` cache identity (:func:`file_key`), with
  virtual nodes so ownership spreads evenly and the loss of one member
  moves only that member's ranges.  Hashing is ``blake2b``, never
  Python's salted ``hash()`` — every process in the fleet must agree on
  ownership byte-for-byte.
- **membership** — each daemon publishes one atomic JSON record in a
  shared fleet directory (:func:`write_member` / :func:`read_members`)
  and refreshes it on a heartbeat cadence (:class:`Heartbeater`).  The
  record carries everything a post-mortem needs: endpoint, journal
  path, flight-recorder base, pid.
- :func:`classify_death` — the router's adopt/no-adopt evidence,
  built on the PR 11 flight-recorder contract: a ring whose last
  record is ``"final": true`` is a clean drain (nothing to adopt —
  the daemon finished its jobs before exiting); records without a
  final (including a torn final line, which replay drops) are an
  unclean death; no ring at all is an unknown.  Unclean and unknown
  both adopt — the PR 10 journal resume path is idempotent and
  identity-checked, so adopting a clean corpse's journal would merely
  find nothing to do, but skipping a real corpse loses jobs.
- **warmth packing** (:func:`pack_windows` / :func:`unpack_windows`) —
  a member's hot decoded arena windows shipped as PR 15 compressed
  BGZF members, so a planned hand-off (member join, graceful drain)
  moves cache warmth instead of re-paying cold reads.  The receiver
  re-decodes through the same host chain walk + SoA gather the read
  path uses, so an imported window answers requests byte-identically
  to a locally-read one.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.tracing import METRICS
from . import flightrec as flightrec_mod

DEFAULT_VNODES = 64
DEFAULT_HEARTBEAT_MS = 500
#: A member whose record is older than this is presumed dead (the
#: router then consults the flight recorder before adopting).
DEFAULT_HEARTBEAT_TIMEOUT_MS = 3000

#: Death verdicts, in decreasing order of certainty.
CLEAN = "clean"
UNCLEAN = "unclean"
UNKNOWN = "unknown"


def stable_hash(key: str) -> int:
    """64-bit position on the ring.  ``blake2b`` (stdlib, unsalted):
    every fleet process — daemons, router, report tools — must compute
    identical ownership, which Python's per-process ``hash()`` salt
    forbids."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


def file_key(path: str) -> str:
    """The routing key: the serve-cache ``(path, size, mtime_ns)`` file
    identity, flattened.  A rewritten file is a *different* key — its
    warmth deliberately lands on (possibly) a different owner, because
    the old owner's arena windows are stale for it anyway.  An unstatable
    path degrades to the path alone (the request will fail downstream
    with a real error; routing just has to be deterministic)."""
    try:
        st = os.stat(path)
        return f"{path}|{st.st_size}|{st.st_mtime_ns}"
    except OSError:
        return path


class HashRing:
    """Consistent-hash ring with virtual nodes (thread-safe).

    ``vnodes`` points per member; ownership of a key is the first point
    clockwise from the key's hash.  Removing a member hands each of its
    ranges to the next surviving point — no other key moves, which is
    the whole reason the fleet can lose a daemon without a global cache
    cold-start."""

    def __init__(self, members: Tuple[str, ...] = (), vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._lock = threading.Lock()
        self._points: List[int] = []
        self._owners: List[str] = []
        self._members: set = set()
        for m in members:
            self.add(m)

    def __len__(self) -> int:
        return len(self._members)

    @property
    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def add(self, name: str) -> None:
        with self._lock:
            if name in self._members:
                return
            self._members.add(name)
            for v in range(self.vnodes):
                h = stable_hash(f"{name}#{v}")
                i = bisect.bisect_left(self._points, h)
                self._points.insert(i, h)
                self._owners.insert(i, name)

    def remove(self, name: str) -> None:
        with self._lock:
            if name not in self._members:
                return
            self._members.discard(name)
            keep = [
                (p, o)
                for p, o in zip(self._points, self._owners)
                if o != name
            ]
            self._points = [p for p, _ in keep]
            self._owners = [o for _, o in keep]

    def owner(self, key: str) -> Optional[str]:
        """The member owning ``key``, or None on an empty ring."""
        with self._lock:
            if not self._points:
                return None
            i = bisect.bisect_right(self._points, stable_hash(key))
            return self._owners[i % len(self._owners)]

    def owners(self, key: str, n: int = 2) -> List[str]:
        """Preference list: the owner, then the next ``n - 1`` distinct
        members clockwise — the router's retry/adoption order."""
        with self._lock:
            if not self._points:
                return []
            out: List[str] = []
            i = bisect.bisect_right(self._points, stable_hash(key))
            for k in range(len(self._owners)):
                o = self._owners[(i + k) % len(self._owners)]
                if o not in out:
                    out.append(o)
                    if len(out) >= n:
                        break
            return out

    def successor(self, name: str) -> Optional[str]:
        """The member that inherits ``name``'s primary range when it
        dies: the first distinct owner clockwise from ``name``'s first
        vnode.  The adoption target — deterministic, so every router
        (and the report tool) names the same adopter."""
        with self._lock:
            if name not in self._members or len(self._members) < 2:
                return None
            h = stable_hash(f"{name}#0")
            i = bisect.bisect_right(self._points, h)
            for k in range(len(self._owners)):
                o = self._owners[(i + k) % len(self._owners)]
                if o != name:
                    return o
            return None

    def shares(self) -> Dict[str, float]:
        """Fraction of the hash space each member owns (the report
        tool's balance column)."""
        with self._lock:
            if not self._points:
                return {}
            total = 1 << 64
            out: Dict[str, float] = {m: 0.0 for m in self._members}
            for i, p in enumerate(self._points):
                prev = self._points[i - 1] if i else self._points[-1] - total
                out[self._owners[i]] += (p - prev) / total
            return out


# -- membership -------------------------------------------------------------


def member_path(fleet_dir: str, name: str) -> str:
    return os.path.join(fleet_dir, f"{name}.json")


def write_member(fleet_dir: str, rec: dict) -> None:
    """Publish one member record atomically (tmp + rename — a reader
    never sees a torn record, the spill-manifest stance)."""
    os.makedirs(fleet_dir, exist_ok=True)
    path = member_path(fleet_dir, rec["name"])
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rec, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def remove_member(fleet_dir: str, name: str) -> None:
    try:
        os.unlink(member_path(fleet_dir, name))
    except OSError:
        pass


def read_members(fleet_dir: str) -> Dict[str, dict]:
    """Every parseable member record in the fleet directory.  A torn or
    foreign file is skipped (membership reads must never fail the
    router), counted as ``fleet.members.unreadable``."""
    out: Dict[str, dict] = {}
    try:
        names = sorted(os.listdir(fleet_dir))
    except OSError:
        return out
    for fn in names:
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(fleet_dir, fn), "r", encoding="utf-8") as f:
                rec = json.load(f)
            if isinstance(rec, dict) and rec.get("name"):
                out[rec["name"]] = rec
        except (OSError, ValueError):
            METRICS.count("fleet.members.unreadable", 1)
    return out


def heartbeat_age_s(rec: dict, now: Optional[float] = None) -> float:
    """Seconds since the member last heartbeat (inf for a garbled
    record — an unreadable heartbeat is a missed one)."""
    now = time.time() if now is None else now
    try:
        return max(0.0, now - float(rec["t_wall"]))
    except (KeyError, TypeError, ValueError):
        return float("inf")


class Heartbeater:
    """The daemon's membership pulse: re-publish the member record every
    ``period_s`` until stopped.  ``source`` returns the current record
    (the daemon closes over its live endpoint/draining state); the final
    write on stop carries whatever the source then says — a draining
    daemon's last heartbeat says ``draining: true``, which the router
    reads as a planned exit, not a death."""

    def __init__(
        self, fleet_dir: str, source: Callable[[], dict],
        period_s: float = DEFAULT_HEARTBEAT_MS / 1e3,
    ):
        self.fleet_dir = fleet_dir
        self.period = max(0.02, float(period_s))
        self._source = source
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0

    def beat(self) -> None:
        rec = dict(self._source() or {})
        rec["t_wall"] = time.time()
        rec["seq"] = self._seq
        self._seq += 1
        write_member(self.fleet_dir, rec)
        METRICS.count("fleet.heartbeats", 1)

    def start(self) -> None:
        if self._thread is not None:
            return
        self.beat()  # registered before the first request can route here
        self._thread = threading.Thread(
            target=self._run, name="hbam-fleet-heartbeat", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self.beat()
            except Exception:  # noqa: BLE001 - the pulse never kills
                METRICS.count("fleet.heartbeat_errors", 1)

    def stop(self, unregister: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            if unregister:
                remove_member(self.fleet_dir, (self._source() or {}).get("name", ""))
            else:
                self.beat()  # one final record (drain state included)
        except Exception:  # noqa: BLE001
            METRICS.count("fleet.heartbeat_errors", 1)


# -- death forensics --------------------------------------------------------


def classify_death(flightrec_base: Optional[str]) -> dict:
    """The flight-recorder verdict on a silent member, as the router
    consumes it: ``{"verdict": clean|unclean|unknown, ...}``.

    - **clean** — the ring's last surviving record is ``final: true``:
      the daemon drained (finished its jobs) before exiting.  No adopt.
    - **unclean** — records exist but the last one is not final (a
      SIGKILL: the periodic snapshots stop mid-stream; a torn final
      line is dropped by replay and lands here too).  Adopt.
    - **unknown** — no ring was configured, or neither segment exists
      (or nothing in them parses).  Adopt: absence of evidence of a
      clean drain must not strand journaled jobs.
    """
    if not flightrec_base:
        return {"verdict": UNKNOWN, "reason": "no flight recorder configured",
                "snapshots": 0, "torn": 0}
    seg0, seg1 = flightrec_mod.segment_paths(flightrec_base)
    if not (os.path.exists(seg0) or os.path.exists(seg1)):
        return {"verdict": UNKNOWN, "reason": "flight-recorder ring missing",
                "snapshots": 0, "torn": 0}
    snaps, torn = flightrec_mod.load_ring(flightrec_base)
    if not snaps:
        return {
            "verdict": UNCLEAN, "snapshots": 0, "torn": torn,
            "reason": "ring exists but holds no parseable snapshot "
                      "(died before/while writing the baseline)",
        }
    last = snaps[-1]
    if last.get("final"):
        return {
            "verdict": CLEAN, "snapshots": len(snaps), "torn": torn,
            "reason": f"final snapshot present (seq {last.get('seq')})",
        }
    return {
        "verdict": UNCLEAN, "snapshots": len(snaps), "torn": torn,
        "reason": (
            f"{len(snaps)} snapshots, none final"
            + (f" ({torn} torn line(s) dropped)" if torn else "")
        ),
    }


def should_adopt(verdict: str) -> bool:
    """Adopt on anything but a proven clean drain (see
    :func:`classify_death` — the resume path is identity-checked and
    idempotent, so over-adopting is cheap and under-adopting loses
    jobs)."""
    return verdict != CLEAN


# -- warmth packing ---------------------------------------------------------

#: Arena key kinds a fleet migration understands, with the SoA field
#: set each was decoded under (must match serve/endpoints.py).
_KIND_FIELDS = {
    "view": (
        "refid", "pos", "flag", "rec_off", "rec_len", "l_read_name",
        "n_cigar_op",
    ),
    "flagstat": ("flag", "rec_off", "rec_len"),
}


def _arena_keys_for(arena, path: str) -> List[tuple]:
    """The arena keys holding windows of ``path`` (any identity vintage):
    ``(kind, (path, size, mtime_ns), a, b)`` tuples as the endpoints
    build them."""
    out = []
    for key in arena.keys():
        if (
            isinstance(key, tuple) and len(key) == 4
            and key[0] in _KIND_FIELDS
            and isinstance(key[1], tuple) and len(key[1]) == 3
            and key[1][0] == path
        ):
            out.append(key)
    return out


def pack_windows(arena, path: str, level: int = 1, max_windows: int = 64) -> List[dict]:
    """Export ``path``'s warm decoded windows as PR 15 compressed
    members: each window's records are gathered into one dense
    (block_size word + body) stream (``gather_record_array`` — dense so
    the receiver can re-walk it from offset 0) and deflated into
    ≤64 KiB BGZF members, the same wire format the mesh shuffle ships.
    Only windows whose identity still matches the file on disk ship —
    stale warmth must not out-live its file twice."""
    import base64

    from .. import native
    from ..io.bam import gather_record_array
    from .cache import file_identity

    try:
        ident = file_identity(path)
    except OSError:
        return []
    windows: List[dict] = []
    for key in _arena_keys_for(arena, path):
        if key[1] != ident:
            continue  # stale vintage: not worth shipping
        batch = arena.get(key)
        if batch is None or getattr(batch, "data", None) is None:
            continue
        try:
            payload = gather_record_array(batch)
        except Exception:  # noqa: BLE001 - unshippable window: skip, count
            METRICS.count("fleet.migrate.export_errors", 1)
            continue
        if len(payload) == 0:
            continue
        blob = native.deflate_blocks(payload, level=level)
        windows.append({
            "kind": key[0],
            "span": [int(key[2]), int(key[3])],
            "n_records": int(batch.n_records),
            "nbytes": int(len(payload)),
            "members_b64": base64.b64encode(blob).decode("ascii"),
        })
        METRICS.count("fleet.migrate.windows", 1)
        METRICS.count("fleet.migrate.bytes", len(blob))
        if len(windows) >= max_windows:
            break
    return windows


def unpack_windows(arena, path: str, windows: List[dict]) -> int:
    """Install shipped windows into the local arena: inflate the BGZF
    members, re-walk the record chain, re-gather the SoA columns — the
    same decode the read path performs, so an imported window serves
    requests byte-identically to a locally-read one.  Returns how many
    windows were installed (a window whose identity no longer matches
    the file on disk, or whose payload will not parse, is dropped and
    counted, never fatal)."""
    import base64

    import numpy as np

    from ..io.bam import RecordBatch
    from ..spec import bam as bam_spec
    from ..spec import bgzf as bgzf_spec
    from .cache import file_identity

    try:
        ident = file_identity(path)
    except OSError:
        METRICS.count("fleet.migrate.stale_drop", len(windows))
        return 0
    installed = 0
    for w in windows:
        kind = w.get("kind")
        fields = _KIND_FIELDS.get(kind)
        span = w.get("span") or [0, 0]
        if fields is None:
            METRICS.count("fleet.migrate.import_errors", 1)
            continue
        try:
            blob = base64.b64decode(w["members_b64"])
            payload = np.frombuffer(
                bgzf_spec.decompress_all(blob), dtype=np.uint8
            )
            offsets = bam_spec.record_offsets(payload)
            soa = bam_spec.soa_decode(payload, offsets, fields=fields)
            batch = RecordBatch(
                soa=soa, data=payload, keys=np.empty(0, np.int64)
            )
            if w.get("n_records") not in (None, batch.n_records):
                raise ValueError(
                    f"window re-decode mismatch: {batch.n_records} records "
                    f"!= shipped {w.get('n_records')}"
                )
            key = (kind, ident, int(span[0]), int(span[1]))
            arena.hold(key, batch)
            installed += 1
            METRICS.count("fleet.migrate.imported", 1)
        except Exception:  # noqa: BLE001 - a bad window is dropped, counted
            METRICS.count("fleet.migrate.import_errors", 1)
    return installed
