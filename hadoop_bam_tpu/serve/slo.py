"""SLO monitor: declared objectives, sliding windows, burn-rate alerts.

The daemon has had per-op latency histograms since PR 8 and typed
overload refusals since PR 10, but no *judgment*: nothing said "the
view endpoint is currently violating the latency objective it is
supposed to hold".  This module is that judgment, in the standard SRE
shape:

- **objectives** are declared per op (``hadoopbam.serve.slo`` grammar
  below): a latency objective ("fraction of view requests under 100 ms
  ≥ 99%") or an availability objective ("fraction of sort requests not
  erroring ≥ 99%");
- evaluation rides the **existing histograms** — ``serve.op.<op>.ms``
  buckets give the under-threshold count cumulatively, the per-op
  error counters give availability — so the monitor adds no per-request
  cost at all: it samples the cumulative registry and diffs;
- **multi-window burn rates**: for each objective, the error-budget
  burn over a fast and a slow sliding window (defaults 60 s / 600 s,
  ``hadoopbam.serve.slo-windows``).  ``burn = bad_fraction /
  (1 - target)`` — burn 1.0 spends the budget exactly at the objective
  boundary; an alert fires only when *both* windows burn over their
  thresholds (fast-only = a blip, slow-only = stale history; both = a
  real, still-burning breach — the Google SRE multiwindow rule);
- surfaced in the ``stats`` op's ``slo`` block, the flight recorder's
  snapshots (post-mortem replay shows SLO state at death), and the
  Prometheus text (first-class ``slo.*`` gauges).

Objective grammar (semicolon-separated, whitespace ignored)::

    view:latency=100          # 99% (default target) of views < 100 ms
    view:latency=100@0.999    # 99.9% of views < 100 ms
    sort:availability=0.99    # 99% of sorts end without error

Latency thresholds land on the histogram's log2 bucket boundaries (the
smallest power of two ≥ the threshold) — a documented ≤2x coarsening,
the same fidelity contract the histograms themselves carry.
"""

from __future__ import annotations

import collections
import time
from typing import Deque, Dict, List, Optional, Tuple

from ..utils.tracing import METRICS, MetricsRegistry

DEFAULT_TARGET = 0.99
DEFAULT_FAST_S = 60.0
DEFAULT_SLOW_S = 600.0
#: Multiwindow burn thresholds (Google SRE workbook's 1h/5m page pair
#: rescaled to our two windows): the fast window must burn hard AND the
#: slow window must confirm it is not a blip.
DEFAULT_FAST_BURN = 10.0
DEFAULT_SLOW_BURN = 2.0

#: Default objectives when ``hadoopbam.serve.slo`` is unset: lenient
#: enough that a healthy daemon is compliant, present enough that the
#: SLO surface is never empty.
DEFAULT_OBJECTIVES = (
    "view:latency=250;view:availability=0.999;"
    "flagstat:availability=0.999;sort:availability=0.99;"
    "variants:latency=250;variants:availability=0.999;"
    "depth:availability=0.999"
)


class SloObjective:
    """One declared objective: ``op`` + kind (latency|availability) +
    target fraction (+ threshold_ms for latency)."""

    __slots__ = ("op", "kind", "target", "threshold_ms")

    def __init__(
        self,
        op: str,
        kind: str,
        target: float = DEFAULT_TARGET,
        threshold_ms: Optional[float] = None,
    ) -> None:
        if kind not in ("latency", "availability"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if not (0.0 < target < 1.0):
            raise ValueError(f"SLO target must be in (0, 1), got {target}")
        if kind == "latency" and not threshold_ms:
            raise ValueError("latency objective needs a threshold")
        self.op = op
        self.kind = kind
        self.target = float(target)
        self.threshold_ms = (
            float(threshold_ms) if threshold_ms is not None else None
        )

    @property
    def name(self) -> str:
        if self.kind == "latency":
            return f"{self.op}:latency<{self.threshold_ms:g}ms"
        return f"{self.op}:availability"

    def as_dict(self) -> dict:
        d = {"op": self.op, "kind": self.kind, "target": self.target}
        if self.threshold_ms is not None:
            d["threshold_ms"] = self.threshold_ms
        return d


def parse_objectives(spec: str) -> List[SloObjective]:
    """Parse the conf grammar; raises ValueError with the offending
    clause named (a garbled SLO declaration must fail loudly at daemon
    start, not silently monitor nothing)."""
    out: List[SloObjective] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        try:
            op, rest = clause.split(":", 1)
            kind, value = rest.split("=", 1)
            kind = kind.strip()
            target = DEFAULT_TARGET
            if "@" in value:
                value, tgt = value.split("@", 1)
                target = float(tgt)
            if kind == "latency":
                out.append(
                    SloObjective(
                        op.strip(), "latency", target,
                        threshold_ms=float(value),
                    )
                )
            elif kind == "availability":
                out.append(
                    SloObjective(op.strip(), "availability", float(value))
                )
            else:
                raise ValueError(f"unknown kind {kind!r}")
        except (ValueError, IndexError) as e:
            raise ValueError(
                f"bad SLO clause {clause!r}: {e}"
            ) from None
    return out


def _good_total(
    obj: SloObjective, registry: MetricsRegistry
) -> Tuple[float, float]:
    """Cumulative ``(good, total)`` for one objective, read from the
    live registry — the monotone series the sliding windows diff."""
    h = registry.histogram(f"serve.op.{obj.op}.ms")
    total = float(h.n) if h is not None else 0.0
    if obj.kind == "latency":
        if h is None:
            return 0.0, 0.0
        good = 0.0
        for i, c in enumerate(h.counts):
            if h.bucket_upper(i) <= obj.threshold_ms:
                good += c
        return good, total
    errors = float(
        registry.report()["counters"].get(f"serve.op.{obj.op}.errors", 0)
    )
    return max(0.0, total - errors), total


class SloMonitor:
    """Sliding-window compliance + burn rates over cumulative samples.

    Sampling is lazy: every :meth:`evaluate` (the ``stats`` op, the
    flight-recorder tick) appends one cumulative sample per objective
    and diffs against the sample nearest the window start — no thread,
    no timer, bounded memory (samples older than the slow window are
    dropped).  ``now`` is injectable for the synthetic-window unit
    tests.
    """

    def __init__(
        self,
        objectives: List[SloObjective],
        fast_s: float = DEFAULT_FAST_S,
        slow_s: float = DEFAULT_SLOW_S,
        fast_burn: float = DEFAULT_FAST_BURN,
        slow_burn: float = DEFAULT_SLOW_BURN,
        registry: Optional[MetricsRegistry] = None,
    ) -> None:
        self.objectives = list(objectives)
        self.fast_s = float(fast_s)
        self.slow_s = max(float(slow_s), self.fast_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.registry = registry or METRICS
        # Per-objective deque of (t, good, total) cumulative samples.
        self._samples: Dict[str, Deque[Tuple[float, float, float]]] = {
            o.name: collections.deque() for o in self.objectives
        }
        self._alerting: Dict[str, bool] = {}

    @classmethod
    def from_conf(cls, conf) -> "SloMonitor":
        from ..conf import SERVE_SLO, SERVE_SLO_WINDOWS

        spec = conf.get(SERVE_SLO) or DEFAULT_OBJECTIVES
        fast, slow = DEFAULT_FAST_S, DEFAULT_SLOW_S
        win = conf.get(SERVE_SLO_WINDOWS)
        if win:
            try:
                parts = [float(w) for w in win.split(",")]
                fast, slow = parts[0], parts[-1]
            except (ValueError, IndexError):
                raise ValueError(
                    f"bad {SERVE_SLO_WINDOWS} value {win!r} "
                    "(expected 'fast_s,slow_s')"
                ) from None
        return cls(parse_objectives(spec), fast_s=fast, slow_s=slow)

    # -- windows ------------------------------------------------------------

    def _sample(self, now: float) -> None:
        for o in self.objectives:
            good, total = _good_total(o, self.registry)
            dq = self._samples[o.name]
            dq.append((now, good, total))
            # Keep one sample beyond the slow window so the window diff
            # always has an anchor at-or-before its start.
            while len(dq) > 2 and dq[1][0] <= now - self.slow_s:
                dq.popleft()

    def _window(
        self, name: str, window_s: float, now: float
    ) -> Tuple[float, float]:
        """``(good, total)`` deltas over the trailing window."""
        dq = self._samples[name]
        if not dq:
            return 0.0, 0.0
        newest = dq[-1]
        cutoff = now - window_s
        anchor = dq[0]
        for s in dq:
            if s[0] <= cutoff:
                anchor = s
            else:
                break
        return newest[1] - anchor[1], newest[2] - anchor[2]

    @staticmethod
    def _burn(good: float, total: float, target: float) -> float:
        if total <= 0:
            return 0.0
        bad_frac = 1.0 - good / total
        return bad_frac / max(1e-9, 1.0 - target)

    # -- evaluation ---------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> dict:
        """Sample + judge every objective; the ``stats`` op's ``slo``
        block.  Publishes ``slo.*`` burn gauges and counts alert
        *transitions* (``serve.slo.alerts``) so a sustained breach is
        one alert, not one per scrape."""
        now = time.monotonic() if now is None else now
        self._sample(now)
        objectives = []
        worst = None
        alerting: List[str] = []
        for o in self.objectives:
            fg, ft = self._window(o.name, self.fast_s, now)
            sg, st = self._window(o.name, self.slow_s, now)
            fb = self._burn(fg, ft, o.target)
            sb = self._burn(sg, st, o.target)
            is_alerting = fb >= self.fast_burn and sb >= self.slow_burn
            compliant = fb <= 1.0
            rec = {
                **o.as_dict(),
                "name": o.name,
                "windows": {
                    "fast": {
                        "seconds": self.fast_s, "total": ft,
                        "bad": round(ft - fg, 3), "burn": round(fb, 4),
                        "compliant": compliant,
                    },
                    "slow": {
                        "seconds": self.slow_s, "total": st,
                        "bad": round(st - sg, 3), "burn": round(sb, 4),
                        "compliant": sb <= 1.0,
                    },
                },
                "alerting": is_alerting,
            }
            objectives.append(rec)
            gkey = f"slo.{o.op}.{o.kind}"
            METRICS.set_gauge(f"{gkey}.burn_fast", round(fb, 4))
            METRICS.set_gauge(f"{gkey}.burn_slow", round(sb, 4))
            METRICS.set_gauge(f"{gkey}.alerting", float(is_alerting))
            if is_alerting:
                alerting.append(o.name)
                if not self._alerting.get(o.name):
                    METRICS.count("serve.slo.alerts", 1)
                    METRICS.count(f"serve.slo.alerts.{o.op}", 1)
            self._alerting[o.name] = is_alerting
            if worst is None or fb > worst["burn_fast"]:
                worst = {
                    "name": o.name, "op": o.op,
                    "burn_fast": round(fb, 4), "burn_slow": round(sb, 4),
                }
        return {
            "objectives": objectives,
            "alerting": alerting,
            "compliant": not alerting and all(
                ob["windows"]["fast"]["compliant"] for ob in objectives
            ),
            "worst": worst,
        }

    def brief(self, now: Optional[float] = None) -> dict:
        """The flight recorder's per-tick SLO line: burn rates and the
        alert set only (full windows ride the stats op)."""
        ev = self.evaluate(now)
        return {
            "alerting": ev["alerting"],
            "compliant": ev["compliant"],
            "burns": {
                o["name"]: o["windows"]["fast"]["burn"]
                for o in ev["objectives"]
            },
        }


def fold_slo(blocks: List[dict]) -> dict:
    """Fold per-member ``evaluate()`` blocks into one fleet-wide SLO
    judgment (the router's ``stats`` op).

    An objective's error budget is a property of the *service*, not of
    any one daemon, so the fold sums each window's request and bad
    counts across members (same objective name → same window lengths,
    since every member parses the same conf grammar) and recomputes the
    burn from the summed fractions: ``burn = (Σbad/Σtotal) / (1-target)``
    — a member serving 1% of the traffic cannot dominate the fleet burn,
    and one fully-burning hot member shows up exactly in proportion to
    its share.  ``alerting`` is the union (any member's confirmed
    multiwindow breach is a fleet breach: the affected keys route only
    to it); ``compliant`` requires the folded fast burn ≤ 1 for every
    objective and no member alerting."""
    folded: Dict[str, dict] = {}
    alerting: List[str] = []
    for block in blocks:
        if not block:
            continue
        for name in block.get("alerting") or []:
            if name not in alerting:
                alerting.append(name)
        for o in block.get("objectives", []):
            f = folded.get(o["name"])
            if f is None:
                f = {
                    k: o[k]
                    for k in ("name", "op", "kind", "target", "threshold_ms")
                    if k in o
                }
                f["windows"] = {
                    w: {
                        "seconds": o["windows"][w]["seconds"],
                        "total": 0.0,
                        "bad": 0.0,
                    }
                    for w in ("fast", "slow")
                }
                f["members"] = 0
                folded[o["name"]] = f
            f["members"] += 1
            for w in ("fast", "slow"):
                f["windows"][w]["total"] += o["windows"][w]["total"]
                f["windows"][w]["bad"] += o["windows"][w]["bad"]
    objectives = []
    worst = None
    for f in folded.values():
        for w in ("fast", "slow"):
            win = f["windows"][w]
            good = win["total"] - win["bad"]
            burn = SloMonitor._burn(good, win["total"], f["target"])
            win["burn"] = round(burn, 4)
            win["compliant"] = burn <= 1.0
            win["bad"] = round(win["bad"], 3)
        f["alerting"] = f["name"] in alerting
        objectives.append(f)
        fb = f["windows"]["fast"]["burn"]
        if worst is None or fb > worst["burn_fast"]:
            worst = {
                "name": f["name"], "op": f["op"],
                "burn_fast": fb,
                "burn_slow": f["windows"]["slow"]["burn"],
            }
    return {
        "objectives": objectives,
        "alerting": alerting,
        "compliant": not alerting and all(
            o["windows"]["fast"]["compliant"] for o in objectives
        ),
        "worst": worst,
        "members": len([b for b in blocks if b]),
    }


def format_slo_block(slo: dict) -> str:
    """Human rendering of the ``stats`` op's ``slo`` block (the CLI
    ``stats`` subcommand and post-mortem replays share it)."""
    if not slo:
        return "slo: (no monitor)"
    lines = [
        "slo: " + (
            "COMPLIANT" if slo.get("compliant")
            else "ALERTING: " + ", ".join(slo.get("alerting") or ["?"])
        )
    ]
    for o in slo.get("objectives", []):
        w = o["windows"]
        lines.append(
            f"  {o['name']:<28} target {o['target']:.3%}  "
            f"burn fast {w['fast']['burn']:>7.2f} "
            f"({w['fast']['total']:.0f} reqs, {w['fast']['bad']:.0f} bad)"
            f"  slow {w['slow']['burn']:>7.2f}"
            + ("  ALERT" if o["alerting"] else "")
        )
    if slo.get("worst"):
        lines.append(
            f"  worst: {slo['worst']['name']} "
            f"(burn {slo['worst']['burn_fast']:.2f})"
        )
    return "\n".join(lines)
