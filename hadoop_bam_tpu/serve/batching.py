"""Cross-request lane batching: many small requests, one 128-lane launch.

The lockstep-lane codec kernels decode up to 128 BGZF members per launch,
but the batch pipeline only ever shows them one file's members at a time —
a daemon answering many concurrent small ``view`` requests would otherwise
pay one launch (and one h2d round trip) per request for a handful of
members each.  :class:`LaneBatcher` is the admission queue that fixes the
mismatch: requests submit their member-decompress work and block; a worker
holds the first arrival for a short batch window, drains everything that
accumulated (up to the 128-lane capacity), concatenates the members into
one synthetic back-to-back stream — BGZF members are self-contained, so
members from *different files* coexist in one launch — and runs a single
decode, then scatters each request's slice back.

The decode function is pluggable: the default resolves the same tier
chain as the split readers (``ops.flate.inflate_blocks_device`` when the
lanes tier is enabled, native zlib otherwise), so coalescing works — and
is counted — identically on a host-only deployment.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from .. import faults
from ..utils.deadline import Deadline, DeadlineExceeded
from ..utils.tracing import METRICS, TRACER, current_request

#: Lane capacity of one lockstep codec launch (ops/pallas/inflate_lanes.py).
MAX_LANES = 128


def default_decode_fn(conf=None, stream=None) -> Callable:
    """The daemon's decode tier resolution, once per batcher.

    With a ``stream`` (a
    :class:`~hadoop_bam_tpu.device_stream.DeviceStream`, the daemon's
    own), the batcher is a stream *client*: every coalesced launch rides
    :meth:`~hadoop_bam_tpu.device_stream.DeviceStream.decode_members` —
    the same tier seam the split readers use, with device errors
    propagated so the serve OOM ladder (evict → retry → tier-down) stays
    in charge a layer up.  Without one, the legacy resolution: the
    device lanes wrapper when the inflate-lanes gate fires (conf key /
    env / local-latency auto rule), else the native host codec."""
    if stream is not None:

        def decode(raw, co, cs, us):
            return stream.decode_members(raw, co, cs, us)

        return decode
    from ..ops import flate

    if flate.lanes_tier_enabled(conf):

        def decode(raw, co, cs, us):
            out, offs = flate.inflate_blocks_device(raw, co, cs, us)
            return out, offs

        return decode
    from .. import native

    def decode(raw, co, cs, us):
        return native.inflate_blocks(raw, co, cs, us)

    return decode


class _Pending:
    __slots__ = (
        "raw", "co", "cs", "us", "out", "offs", "err", "done", "deadline",
        "rctx", "t_submit", "t_launch", "coalesced",
    )

    def __init__(self, raw, co, cs, us, deadline=None):
        self.raw = raw
        self.co = co
        self.cs = cs
        self.us = us
        self.out = None
        self.offs = None
        self.err: Optional[BaseException] = None
        self.done = threading.Event()
        self.deadline: Optional[Deadline] = deadline
        # Request attribution: captured at submit (the worker thread has
        # no ambient scope), so the wait/decode hops land on the right
        # request even though the launch is shared.
        self.rctx = current_request()
        self.t_submit = time.perf_counter()
        self.t_launch: Optional[float] = None
        self.coalesced = 1

    @property
    def n_members(self) -> int:
        return len(self.co)


class LaneBatcher:
    """Admission queue coalescing member inflates into shared launches.

    ``window_s`` is the coalescing window: the first submission of a batch
    waits at most this long for company before launching (0 → every
    submission launches alone — correct, just uncoalesced).  Counters:
    ``serve.batch.launches`` / ``.members`` / ``.requests`` /
    ``.coalesced_requests`` (requests that shared their launch with at
    least one other).
    """

    def __init__(
        self,
        window_s: float = 0.002,
        decode_fn: Optional[Callable] = None,
        max_lanes: int = MAX_LANES,
        conf=None,
        stream=None,
    ):
        self.window_s = max(0.0, float(window_s))
        self.max_lanes = max(1, int(max_lanes))
        self._decode = decode_fn or default_decode_fn(conf, stream=stream)
        self._lock = threading.Lock()
        self._queue: List[_Pending] = []
        self._wake = threading.Event()
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="hbam-lane-batcher", daemon=True
        )
        self._worker.start()

    # -- request side -------------------------------------------------------

    def submit(
        self,
        raw,
        coffsets: np.ndarray,
        csizes: np.ndarray,
        usizes: np.ndarray,
        deadline: Optional[Deadline] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Blockingly decode one request's members; same contract as
        ``native.inflate_blocks``: ``(out, out_offsets)`` with member i's
        payload at ``out[out_offsets[i]:out_offsets[i+1]]``.

        ``deadline`` (the request's end-to-end budget) is checked at
        admission and again when the worker drains the queue: a request
        whose deadline expired while waiting for a launch window is
        failed with ``DeadlineExceeded`` and never occupies a lane —
        expired work must not burn a shared launch."""
        if self._closed:
            raise RuntimeError("LaneBatcher is closed")
        if deadline is not None:
            deadline.check("batcher")
        raw_a = (
            raw
            if isinstance(raw, np.ndarray)
            else np.frombuffer(raw, dtype=np.uint8)
        )
        p = _Pending(
            raw_a,
            np.asarray(coffsets, dtype=np.int64),
            np.asarray(csizes, dtype=np.int32),
            np.asarray(usizes, dtype=np.int32),
            deadline=deadline,
        )
        with self._lock:
            self._queue.append(p)
        self._wake.set()
        p.done.wait()
        if p.rctx is not None:
            # Two hops, split at the launch instant: "batch.wait" is
            # time lost to the coalescing window and lane contention,
            # "batch.decode" the shared kernel itself — the waterfall's
            # batch-wait vs kernel attribution.  An expired-in-queue
            # request (t_launch None) spent its whole stay waiting.
            t_end = time.perf_counter()
            t_launch = p.t_launch if p.t_launch is not None else t_end
            p.rctx.annotate(
                "batch.wait",
                ms=(t_launch - p.t_submit) * 1e3,
                members=p.n_members,
                coalesced=p.coalesced,
            )
            if p.t_launch is not None:
                p.rctx.annotate(
                    "batch.decode", ms=(t_end - t_launch) * 1e3
                )
        if p.err is not None:
            raise p.err
        return p.out, p.offs

    def queue_depth(self) -> int:
        """Requests currently waiting for a launch (the daemon's
        ``serve.batch.queue_depth`` gauge — sustained nonzero means the
        window/lane capacity is the bottleneck, not the kernels)."""
        with self._lock:
            return len(self._queue)

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        self._worker.join(timeout=5.0)

    # -- worker side --------------------------------------------------------

    def _run(self) -> None:
        while True:
            self._wake.wait()
            if self._closed and not self._queue:
                return
            # Batch window: let concurrent requests pile onto the first
            # arrival before launching.
            if self.window_s:
                time.sleep(self.window_s)
            expired: List[_Pending] = []
            with self._lock:
                if not self._queue:
                    self._wake.clear()
                    continue
                batch: List[_Pending] = []
                lanes = 0
                while self._queue:
                    nxt = self._queue[0]
                    if (
                        nxt.deadline is not None
                        and nxt.deadline.expired
                    ):
                        # Dead on arrival at the launch: fail it out of
                        # band, never spend a lane on it.
                        expired.append(self._queue.pop(0))
                        continue
                    if batch and lanes + nxt.n_members > self.max_lanes:
                        break  # next launch takes it (capacity packing)
                    batch.append(self._queue.pop(0))
                    lanes += nxt.n_members
                if not self._queue:
                    self._wake.clear()
            for p in expired:
                try:
                    p.deadline.check("batcher")
                except DeadlineExceeded as e:
                    p.err = e
                p.done.set()
            if batch:
                self._launch(batch)

    def _launch(self, batch: List[_Pending]) -> None:
        t0 = time.perf_counter()
        for p in batch:
            p.t_launch = t0
            p.coalesced = len(batch)
        try:
            if faults.ACTIVE is not None and faults.ACTIVE.arena_oom(
                "lane_batcher"
            ):
                # The deterministic device-OOM drill: surfaces to every
                # waiter exactly like a real RESOURCE_EXHAUSTED from the
                # decode launch would.
                raise faults.InjectedResourceExhausted("lane_batcher")
            # One synthetic stream: each member's compressed bytes are
            # self-contained, so back-to-back concatenation is a valid
            # input for any of the decode tiers.
            parts: List[np.ndarray] = []
            co_l: List[int] = []
            cs_l: List[int] = []
            us_l: List[int] = []
            pos = 0
            for p in batch:
                for k in range(p.n_members):
                    c0 = int(p.co[k])
                    cs = int(p.cs[k])
                    parts.append(p.raw[c0 : c0 + cs])
                    co_l.append(pos)
                    cs_l.append(cs)
                    us_l.append(int(p.us[k]))
                    pos += cs
            cat = (
                np.concatenate(parts)
                if parts
                else np.empty(0, dtype=np.uint8)
            )
            out, offs = self._decode(
                cat,
                np.asarray(co_l, dtype=np.int64),
                np.asarray(cs_l, dtype=np.int32),
                np.asarray(us_l, dtype=np.int32),
            )
            METRICS.count("serve.batch.launches", 1)
            METRICS.count("serve.batch.members", len(co_l))
            METRICS.count("serve.batch.requests", len(batch))
            if len(batch) > 1:
                METRICS.count(
                    "serve.batch.coalesced_requests", len(batch)
                )
            if TRACER.armed:
                # One stage event per shared launch, carrying EVERY
                # rider's trace id: a request's causal tree includes the
                # launch it shared even though the worker thread has no
                # single ambient context.
                traces = sorted(
                    {p.rctx.trace_id for p in batch if p.rctx is not None}
                )
                TRACER.emit(
                    "serve.batch.launch", "stage", t0,
                    time.perf_counter(),
                    {
                        "members": len(co_l),
                        "requests": len(batch),
                        "traces": traces,
                    },
                    merge_ctx=False,
                )
            # Scatter each request's contiguous member run back out.
            m0 = 0
            for p in batch:
                m1 = m0 + p.n_members
                lo, hi = int(offs[m0]), int(offs[m1])
                p.out = out[lo:hi]
                p.offs = np.asarray(offs[m0 : m1 + 1], dtype=np.int64) - lo
                m0 = m1
        except BaseException as e:  # noqa: BLE001 - delivered to waiters
            for p in batch:
                p.err = e
        finally:
            for p in batch:
                p.done.set()
