"""The fleet front router: one address, N daemons, no lost warmth or jobs.

A single :class:`~hadoop_bam_tpu.serve.server.BamDaemon` owns one
accelerator; the millions-of-users north star needs N of them looking
like *one service*.  The router is that facade, deliberately on the same
stdlib transport the daemon speaks (UDS / 127.0.0.1 TCP, length-prefixed
JSON, one request per connection), so every existing
:class:`~hadoop_bam_tpu.serve.client.ServeClient` — CLI, bench, tests —
points at a router exactly as it would at a daemon:

- **placement** — data-plane ops route by consistent hash of the file's
  ``(path, size, mtime_ns)`` cache identity (:func:`fleet.file_key` on a
  :class:`fleet.HashRing`), so one file's header/index/arena warmth
  accumulates on exactly one daemon instead of being diluted N ways; a
  rewritten file hashes elsewhere *by construction*, because its
  identity changed.
- **federated admission** — the :class:`fleet ledger
  <hadoop_bam_tpu.serve.admission.FleetLedger>` gates at the front
  door: a fleet-wide token pool plus a per-file cap, so one hot file
  saturates its owner at a bounded rate while every other file stays
  servable.  The router never queues — members own the only bounded
  queues — so overload replies stay immediate and typed.
- **membership & recovery** — a monitor thread watches the shared fleet
  directory daemons heartbeat into.  A stale heartbeat triggers the
  flight-recorder forensics (:func:`fleet.classify_death`): a confirmed
  clean drain just leaves the ring; an unclean death (or no evidence)
  additionally makes the ring successor **adopt the corpse's journal**
  over the daemon ``adopt`` op — the PR 10 resume path re-runs every
  resumable job byte-identically under the adopter, and the router
  re-aliases the dead member's namespaced job ids so waiting clients'
  ``job``/``wait`` polls follow the work to its new home.  Optionally
  (``hadoopbam.fleet.migrate-warmth``) a *planned* leave ships the
  leaving member's warm arena windows to the new ring owners as PR 15
  compressed members.
- **observability** — the router continues each request's trace across
  its hop (``router.route`` / ``router.retry`` annotations on the same
  trace id the client originated), folds per-member SLO blocks into a
  fleet judgment (:func:`slo.fold_slo`) in ``stats``, and answers a
  router-only ``fleet`` op with the ring, member liveness, and hand-off
  history — ``tools/fleet_report.py`` renders it.

Job ids crossing the router are namespaced ``<member>:<local id>``, so
a client can hold one opaque id while the fleet moves the job under it.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..conf import (
    Configuration,
    FLEET_DIR,
    FLEET_FILE_TOKENS,
    FLEET_HEARTBEAT_TIMEOUT_MS,
    FLEET_MIGRATE_WARMTH,
    FLEET_PORT,
    FLEET_SOCKET,
    FLEET_TOKENS,
    FLEET_VNODES,
    SERVE_REQUEST_TRACING,
)
from ..utils.tracing import (
    METRICS,
    RequestContext,
    prometheus_text,
    request_scope,
    snapshot,
)
from . import fleet as fleet_mod
from . import slo as slo_mod
from .admission import JOB_LOST, FleetLedger, ShedError
from .client import ServeClient, ServeConnectionError, ServeError
from .server import KNOWN_OPS, recv_msg, send_msg

DEFAULT_FLEET_TOKENS = 32
DEFAULT_FILE_TOKENS = 8
#: Ops the router forwards to a file's ring owner.
ROUTED_OPS = ("view", "flagstat", "sort", "warmth")
#: How many recently-routed paths per member the router remembers for
#: optional warmth migration on a planned leave.
_RECENT_PATHS = 32


def default_router_socket_path() -> str:
    import tempfile

    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"hbam-fleet-{uid}.sock")


class FleetRouter:
    """Accept loop + ring routing + death monitor (stdlib-only)."""

    def __init__(
        self,
        conf: Optional[Configuration] = None,
        fleet_dir: Optional[str] = None,
        socket_path: Optional[str] = None,
        port: Optional[int] = None,
        host: str = "127.0.0.1",
        heartbeat_timeout_ms: Optional[float] = None,
        member_timeout: float = 300.0,
    ):
        self.conf = conf or Configuration()
        self.fleet_dir = fleet_dir or self.conf.get(FLEET_DIR)
        if not self.fleet_dir:
            raise ValueError(
                f"the fleet router needs a fleet directory ({FLEET_DIR})"
            )
        self.socket_path = socket_path or self.conf.get(FLEET_SOCKET)
        self.port = (
            port
            if port is not None
            else (self.conf.get_int(FLEET_PORT, 0) or None)
        )
        self.host = host
        if self.socket_path is None and self.port is None:
            self.socket_path = default_router_socket_path()
        self.heartbeat_timeout_ms = float(
            heartbeat_timeout_ms
            if heartbeat_timeout_ms is not None
            else self.conf.get_int(
                FLEET_HEARTBEAT_TIMEOUT_MS,
                fleet_mod.DEFAULT_HEARTBEAT_TIMEOUT_MS,
            )
        )
        self.member_timeout = member_timeout
        self.migrate_warmth = self.conf.get_boolean(FLEET_MIGRATE_WARMTH, False)
        self.request_tracing = self.conf.get_boolean(
            SERVE_REQUEST_TRACING, True
        )
        self.ring = fleet_mod.HashRing(
            vnodes=self.conf.get_int(FLEET_VNODES, fleet_mod.DEFAULT_VNODES)
        )
        self.ledger = FleetLedger(
            tokens=self.conf.get_int(FLEET_TOKENS, DEFAULT_FLEET_TOKENS),
            file_tokens=self.conf.get_int(
                FLEET_FILE_TOKENS, DEFAULT_FILE_TOKENS
            ),
        )
        self._lock = threading.Lock()
        #: name → latest member record (ring members only).
        self._members: Dict[str, dict] = {}
        #: name → death record (verdict, adoption outcome, timestamps).
        self._dead: Dict[str, dict] = {}
        #: router job id → router job id (dead member's id → its new
        #: home after adoption; chased transitively on ``job`` polls).
        self._job_alias: Dict[str, str] = {}
        #: hand-off history, oldest first (the ``fleet`` op + report).
        self._handoffs: List[dict] = []
        #: member → recently routed paths (warmth-migration candidates).
        self._recent_paths: Dict[str, List[str]] = {}
        self._clients: Dict[str, ServeClient] = {}
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        self._handlers: List[threading.Thread] = []
        self._started_snapshot = snapshot()

    # -- membership ---------------------------------------------------------

    @property
    def endpoint(self) -> dict:
        if self.socket_path is not None:
            return {"socket": self.socket_path}
        return {"host": self.host, "port": self.port}

    def _client_for(self, name: str) -> Optional[ServeClient]:
        """A (cached) client for a member, from its published endpoint.
        Router-side retries are explicit (the successor hop), so the
        member client itself never retries."""
        with self._lock:
            rec = self._members.get(name) or self._dead.get(name, {}).get(
                "record"
            )
            c = self._clients.get(name)
            if c is not None:
                return c
            ep = (rec or {}).get("endpoint") or {}
            if not ep:
                return None
            c = ServeClient(
                socket_path=ep.get("socket"),
                host=ep.get("host", "127.0.0.1"),
                port=ep.get("port"),
                timeout=self.member_timeout,
                retries=0,
            )
            self._clients[name] = c
            return c

    def scan_members(self) -> None:
        """One membership pass: admit new heartbeats, refresh known
        ones, classify the silent.  The monitor thread loops this; tests
        and the in-process smoke call it directly for determinism."""
        recs = fleet_mod.read_members(self.fleet_dir)
        now = time.time()
        timeout_s = self.heartbeat_timeout_ms / 1e3
        with self._lock:
            for name, rec in recs.items():
                fresh = fleet_mod.heartbeat_age_s(rec, now) <= timeout_s
                if name in self._dead:
                    if fresh:
                        # A restarted daemon re-publishing under its old
                        # name rejoins as a new member (its journal was
                        # already adopted; it starts empty-handed).
                        self._dead.pop(name, None)
                        self._clients.pop(name, None)
                    else:
                        continue
                if name not in self._members:
                    if not fresh or rec.get("draining"):
                        continue
                    self._members[name] = rec
                    self.ring.add(name)
                    METRICS.count("fleet.member_joins", 1)
                else:
                    if self._members[name].get("endpoint") != rec.get(
                        "endpoint"
                    ):
                        self._clients.pop(name, None)
                    self._members[name] = rec
        # Outside the lock: leaves and deaths talk to member sockets.
        for name in list(self._members):
            rec = recs.get(name)
            if rec is None:
                self._leave(name, reason="unregistered")
            elif rec.get("draining"):
                self._leave(name, reason="draining")
            elif fleet_mod.heartbeat_age_s(rec, now) > timeout_s:
                self._on_death(name, rec)

    def _leave(self, name: str, reason: str) -> None:
        """A planned exit: drop the member from the ring; with warmth
        migration on, ship its recently-routed paths' warm windows to
        their new ring owners first (the member is draining, not dead —
        its control plane still answers)."""
        with self._lock:
            rec = self._members.get(name)
            if rec is None:
                return
            paths = list(self._recent_paths.get(name, ()))
        if self.migrate_warmth and reason == "draining":
            self._migrate_warmth_from(name, paths)
        with self._lock:
            self._members.pop(name, None)
            self.ring.remove(name)
            self._clients.pop(name, None)
            self._recent_paths.pop(name, None)
            self._handoffs.append({
                "t_wall": time.time(), "member": name, "kind": "leave",
                "reason": reason,
            })
        METRICS.count("fleet.member_leaves", 1)

    def _migrate_warmth_from(self, name: str, paths: List[str]) -> None:
        src = self._client_for(name)
        if src is None:
            return
        for path in paths:
            with self._lock:
                # Ownership after the leave: remove is idempotent, and
                # computing on a copy keeps the live ring serving.
                probe = fleet_mod.HashRing(
                    tuple(m for m in self.ring.members if m != name),
                    vnodes=self.ring.vnodes,
                )
            dst_name = probe.owner(fleet_mod.file_key(path))
            if dst_name is None or dst_name == name:
                continue
            dst = self._client_for(dst_name)
            if dst is None:
                continue
            try:
                windows = src.warmth(path, export=True).get("windows", [])
                if windows:
                    dst.warmth(path, windows=windows)
                    METRICS.count("fleet.migrations", 1)
            except (ServeError, OSError):
                METRICS.count("fleet.migration_errors", 1)

    def _on_death(self, name: str, rec: dict) -> None:
        """A missed heartbeat: forensics, ring surgery, adoption."""
        forensics = fleet_mod.classify_death(rec.get("flightrec"))
        adopt = fleet_mod.should_adopt(forensics["verdict"])
        with self._lock:
            if name not in self._members:
                return  # a concurrent scan already buried this member
            adopter = self.ring.successor(name)
            self._members.pop(name, None)
            self.ring.remove(name)
            self._clients.pop(name, None)
            self._recent_paths.pop(name, None)
            dead = {
                "record": rec,
                "t_detected": time.time(),
                "forensics": forensics,
                "adopter": adopter if adopt else None,
            }
            self._dead[name] = dead
        METRICS.count("fleet.deaths", 1)
        METRICS.count(f"fleet.deaths.{forensics['verdict']}", 1)
        handoff = {
            "t_wall": time.time(), "member": name, "kind": "death",
            "verdict": forensics["verdict"],
            "reason": forensics.get("reason"),
            "adopter": adopter if adopt else None,
        }
        if adopt and adopter and rec.get("journal"):
            client = self._client_for(adopter)
            try:
                r = (
                    client.adopt(rec["journal"], source=name)
                    if client is not None
                    else {}
                )
                adopted = r.get("adopted", {})
                with self._lock:
                    for old, new in adopted.items():
                        self._job_alias[f"{name}:{old}"] = f"{adopter}:{new}"
                handoff["adopted"] = adopted
                handoff["lost"] = r.get("lost", [])
                dead["adopted"] = adopted
                METRICS.count("fleet.adoptions", 1)
                METRICS.count("fleet.jobs_adopted", len(adopted))
            except (ServeError, OSError) as e:
                handoff["adopt_error"] = f"{type(e).__name__}: {e}"
                METRICS.count("fleet.adoption_errors", 1)
        with self._lock:
            self._handoffs.append(handoff)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._listener is not None:
            return
        self.scan_members()
        if self.socket_path is not None:
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)
            lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            lst.bind(self.socket_path)
        else:
            lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lst.bind((self.host, self.port or 0))
            self.port = lst.getsockname()[1]
        lst.listen(64)
        lst.settimeout(0.1)
        self._listener = lst
        self._monitor_thread = threading.Thread(
            target=self._monitor, name="hbam-fleet-monitor", daemon=True
        )
        self._monitor_thread.start()
        METRICS.count("fleet.router_starts", 1)

    def _monitor(self) -> None:
        # Scan a few times per timeout so detection latency is a
        # fraction of the timeout, not a multiple.
        period = min(1.0, max(0.05, self.heartbeat_timeout_ms / 1e3 / 4))
        while not self._stop.wait(period):
            try:
                self.scan_members()
            except Exception:  # noqa: BLE001 - the monitor never dies
                METRICS.count("fleet.monitor_errors", 1)

    def serve_forever(self, ready: Optional[threading.Event] = None) -> None:
        self.start()
        if ready is not None:
            ready.set()
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                t = threading.Thread(
                    target=self._handle, args=(conn,), daemon=True
                )
                t.start()
                self._handlers.append(t)
                self._handlers = [h for h in self._handlers if h.is_alive()]
        finally:
            self._shutdown_cleanup()

    def stop(self) -> None:
        self._stop.set()

    def _shutdown_cleanup(self) -> None:
        for h in list(self._handlers):
            h.join(timeout=5.0)
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
            self._monitor_thread = None
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        if self.socket_path and os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass

    # -- request handling ---------------------------------------------------

    def _handle(self, conn: socket.socket) -> None:
        stop_after = False
        try:
            with conn:
                req = recv_msg(conn)
                if req is None:
                    return
                op = req.get("op")
                rctx = None
                if self.request_tracing:
                    rctx = RequestContext.from_wire(
                        req.get("trace"), op=op
                    ) or RequestContext.new(op=op)
                with request_scope(rctx):
                    try:
                        reply, stop_after = self._dispatch(req, rctx)
                    except ShedError as e:
                        reply = {
                            "ok": False, "code": e.code, "error": str(e),
                            "retry_after_ms": e.retry_after_ms,
                        }
                    except ServeError as e:
                        reply = {"ok": False, "error": str(e)}
                        if e.code is not None:
                            reply["code"] = e.code
                        if getattr(e, "retry_after_ms", None) is not None:
                            reply["retry_after_ms"] = e.retry_after_ms
                    except Exception as e:  # noqa: BLE001 - reply, don't die
                        METRICS.count("fleet.router.request_errors", 1)
                        reply = {
                            "ok": False,
                            "error": f"{type(e).__name__}: {e}",
                        }
                if rctx is not None:
                    reply.setdefault("trace_id", rctx.trace_id)
                send_msg(conn, reply)
        except Exception:
            METRICS.count("fleet.router.connection_errors", 1)
        finally:
            if stop_after:
                self._stop.set()

    def _routing_path(self, req: dict) -> Optional[str]:
        op = req.get("op")
        if op in ("sort", "ingest"):
            paths = req.get("bam") if op == "sort" else req.get("fastq")
            if isinstance(paths, str):
                return paths
            return paths[0] if paths else None
        return req.get("path")

    def _forward(
        self, name: str, req: dict, rctx: Optional[RequestContext]
    ) -> dict:
        """One member exchange under this request's trace: the member
        client runs inside the router's request scope, so its wire
        ``trace`` is a child span of the same trace id the origin client
        minted — ``router.route`` is a hop, not a new trace."""
        client = self._client_for(name)
        if client is None:
            raise ServeConnectionError(f"no endpoint for member {name!r}")
        fwd = {k: v for k, v in req.items() if k != "trace"}
        with request_scope(rctx):
            return client._request(fwd, idempotent=False)

    def _route_data(
        self, req: dict, rctx: Optional[RequestContext]
    ) -> dict:
        """Route a data-plane op to its ring owner; on a transport
        failure retry exactly once against the ring successor (the
        member most likely to adopt the owner's range) with a
        ``router.retry`` hop."""
        op = req.get("op")
        path = self._routing_path(req)
        if path is None:
            raise ServeError(f"op {op!r} carries no routable path")
        key = fleet_mod.file_key(path)
        owners = self.ring.owners(key, n=2)
        if not owners:
            raise ServeConnectionError("fleet has no live members")
        release = self.ledger.acquire(op, key)
        try:
            member = owners[0]
            with self._lock:
                recent = self._recent_paths.setdefault(member, [])
                if path in recent:
                    recent.remove(path)
                recent.append(path)
                del recent[:-_RECENT_PATHS]
            if rctx is not None:
                rctx.annotate("router.route", member=member, op=op)
            METRICS.count("fleet.router.routed", 1)
            try:
                reply = self._forward(member, req, rctx)
            except (ServeConnectionError, ConnectionError, OSError) as e:
                self._maybe_eager_death(member, e, rctx)
                if len(owners) < 2 or op in ("sort", "ingest"):
                    # A job submit is never blind-retried (a resubmit
                    # is a second job) — the death monitor's adoption
                    # path owns its recovery instead.
                    raise
                retry_to = owners[1]
                if rctx is not None:
                    rctx.annotate(
                        "router.retry",
                        member=retry_to,
                        error=type(e).__name__,
                    )
                METRICS.count("fleet.router.retries", 1)
                member = retry_to
                reply = self._forward(member, req, rctx)
            if op in ("sort", "ingest") and "job" in reply:
                reply["job"] = f"{member}:{reply['job']}"
            reply.setdefault("member", member)
            return reply
        finally:
            release()

    def _maybe_eager_death(self, member: str, err: BaseException,
                           rctx: Optional[RequestContext]) -> None:
        """Eager death detection: a *connection-refused* from a member
        whose heartbeat is still fresh means the process died between
        heartbeats (refused is active OS evidence the listener is gone —
        unlike a timeout, which may just be load).  Classify and bury it
        immediately instead of waiting out the heartbeat floor, so the
        successor retry below already routes against the repaired
        ring."""
        refused = isinstance(err, ConnectionRefusedError) or (
            "refused" in str(err).lower()
        )
        if not refused:
            return
        with self._lock:
            rec = self._members.get(member)
        if rec is None:
            return
        fresh = fleet_mod.heartbeat_age_s(rec, time.time()) <= (
            self.heartbeat_timeout_ms / 1e3
        )
        if not fresh:
            return  # the ordinary monitor pass owns stale members
        METRICS.count("fleet.eager_refused", 1)
        if rctx is not None:
            rctx.annotate("router.eager_death", member=member)
        self._on_death(member, rec)

    def _job_status(self, req: dict) -> dict:
        rid = req.get("id") or ""
        with self._lock:
            seen = set()
            while rid in self._job_alias and rid not in seen:
                seen.add(rid)
                rid = self._job_alias[rid]
        member, _, local = rid.partition(":")
        if not local:
            return {
                "ok": False, "code": JOB_LOST,
                "error": f"job id {req.get('id')!r} is not a fleet id "
                "(expected member:job-nnnn)",
            }
        with self._lock:
            known = member in self._members
        if not known:
            return {
                "ok": False, "code": JOB_LOST,
                "error": f"job {rid!r}: member {member!r} is gone and no "
                "adoption re-homed the job",
            }
        reply = self._forward(member, {"op": "job", "id": local}, None)
        reply["id"] = rid
        reply.setdefault("member", member)
        return reply

    def _fan_out(self, req: dict) -> Dict[str, dict]:
        """The control-plane fan-out (stats/metrics/exemplars): every
        member queried, per-member transport failures recorded as error
        entries rather than failing the fleet answer."""
        with self._lock:
            names = sorted(self._members)
        out: Dict[str, dict] = {}
        for name in names:
            try:
                out[name] = self._forward(name, dict(req), None)
            except (ServeError, OSError) as e:
                out[name] = {
                    "ok": False, "error": f"{type(e).__name__}: {e}"
                }
        return out

    def fleet_view(self) -> dict:
        """The ``fleet`` op payload: ring, members, deaths, hand-offs."""
        now = time.time()
        with self._lock:
            members = {
                name: {
                    "endpoint": rec.get("endpoint"),
                    "pid": rec.get("pid"),
                    "journal": rec.get("journal"),
                    "flightrec": rec.get("flightrec"),
                    "heartbeat_age_ms": round(
                        fleet_mod.heartbeat_age_s(rec, now) * 1e3, 1
                    ),
                    "draining": bool(rec.get("draining")),
                }
                for name, rec in self._members.items()
            }
            dead = {
                name: {
                    k: v for k, v in d.items() if k != "record"
                }
                for name, d in self._dead.items()
            }
            handoffs = list(self._handoffs)
            aliases = dict(self._job_alias)
        return {
            "ok": True,
            "router": {"endpoint": self.endpoint, "pid": os.getpid()},
            "fleet_dir": self.fleet_dir,
            "members": members,
            "ring": {
                "vnodes": self.ring.vnodes,
                "shares": {
                    m: round(s, 4) for m, s in self.ring.shares().items()
                },
            },
            "dead": dead,
            "handoffs": handoffs,
            "job_aliases": aliases,
            "admission": self.ledger.gauges(),
            "heartbeat_timeout_ms": self.heartbeat_timeout_ms,
        }

    def _dispatch(
        self, req: dict, rctx: Optional[RequestContext]
    ) -> Tuple[dict, bool]:
        op = req.get("op")
        METRICS.count(f"fleet.router.op.{op}", 1)
        if op == "ping":
            with self._lock:
                n = len(self._members)
            return (
                {
                    "ok": True, "pid": os.getpid(), "router": True,
                    "endpoint": self.endpoint, "members": n,
                },
                False,
            )
        if op == "fleet":
            return (self.fleet_view(), False)
        if op in ROUTED_OPS:
            return (self._route_data(req, rctx), False)
        if op == "adopt":
            # Manual hand-off: the operator names the adopter.
            member = req.get("member")
            if not member:
                return (
                    {"ok": False,
                     "error": "router adopt needs a member name"},
                    False,
                )
            return (self._forward(member, req, rctx), False)
        if op == "job":
            return (self._job_status(req), False)
        if op == "stats":
            per_member = self._fan_out({"op": "stats"})
            fold = slo_mod.fold_slo([
                r.get("slo") for r in per_member.values() if r.get("ok")
            ])
            return (
                {
                    "ok": True,
                    "router": self.fleet_view(),
                    "members": per_member,
                    "slo": fold,
                },
                False,
            )
        if op == "metrics":
            texts = [
                f"# fleet member: {name}\n{r.get('text', '')}"
                for name, r in sorted(self._fan_out({"op": "metrics"}).items())
                if r.get("ok")
            ]
            texts.append(
                "# fleet router\n" + prometheus_text(snapshot())
            )
            return (
                {
                    "ok": True,
                    "content_type": "text/plain; version=0.0.4",
                    "text": "\n".join(texts),
                },
                False,
            )
        if op == "exemplars":
            tid = req.get("trace_id")
            if tid:
                for name, r in self._fan_out(dict(req)).items():
                    if r.get("ok"):
                        ex = r["exemplar"]
                        ex.setdefault("member", name)
                        return ({"ok": True, "exemplar": ex}, False)
                return (
                    {"ok": False,
                     "error": f"no member holds an exemplar for {tid!r}"},
                    False,
                )
            merged = []
            for name, r in sorted(self._fan_out({"op": "exemplars"}).items()):
                for ex in r.get("exemplars", []) if r.get("ok") else []:
                    merged.append({**ex, "member": name})
            return ({"ok": True, "exemplars": merged}, False)
        if op == "shutdown":
            # Stops the *router* only: members keep serving their own
            # sockets (drain them individually, or kill the fleet dir).
            return ({"ok": True, "drained": True, "router": True}, True)
        return (
            {
                "ok": False,
                "error": f"unknown op {op!r} (router knows "
                f"{sorted(set(KNOWN_OPS) | {'fleet'})})",
            },
            False,
        )
